# Reference: the root Makefile (test: ginkgo -r; battletest: race+coverage).
# Python analog: pytest suite, native kernel build, benchmarks.

.PHONY: test battletest bench bench-shapes bench-control bench-pipeline bench-consolidate bench-marshal bench-gang bench-filter bench-policy bench-affinity bench-global bench-topology bench-carve-journal bench-replay bench-replay-smoke bench-history bench-regress replay-smoke metrics-lint native dryrun lint chart chaos-soak chaos-crash chaos-overload clean help

help: ## Show targets
	@grep -E '^[a-z-]+:.*##' $(MAKEFILE_LIST) | awk -F ':.*## ' '{printf "  %-12s %s\n", $$1, $$2}'

test: ## Run the test suite (CPU mesh, fail-fast)
	python -m pytest tests/ -x -q

battletest: ## Randomized order + full run (the reference's battletest analog)
	python -m pytest tests/ -q -p no:cacheprovider

bench: ## Run the 5-config benchmark on the available accelerator
	python bench.py

bench-shapes: ## Shape-cardinality + type-SPMD configs only (compaction regime)
	python bench.py --only config_6 config_8

bench-control: ## Control-plane config only (columnar filter regime, filter_ms breakdown)
	python bench.py --only config_7

DEVICES ?= 2  # virtual host devices for bench-pipeline (--xla_force_host_platform_device_count)

bench-pipeline: ## Pipeline A/B at DEVICES virtual devices (DEVICES=N); prints verdict line on stderr
	python bench.py --only config_7 --devices $(DEVICES) \
		| python tools/pipeline_verdict.py

bench-consolidate: ## Batched what-if consolidation window (config_5), diurnal trace leg when TRACE_replay.json exists (bench-replay); prints verdict line on stderr
	python bench.py --only config_5 --trace TRACE_replay.json \
		| python tools/consolidate_verdict.py

bench-marshal: ## Steady-state window replay, cold vs delta marshal+encode A/B (config_10); prints verdict line on stderr
	python bench.py --only config_10 \
		| python tools/marshal_verdict.py

bench-gang: ## Batched gang co-pack window, one device solve vs per-gang host loop (config_11); prints verdict line on stderr
	python bench.py --only config_11 \
		| python tools/gang_verdict.py

bench-filter: ## Device-resident fused feasibility, bit-plane window filter vs host columnar A/B (config_12); prints verdict line on stderr
	python bench.py --only config_12 \
		| python tools/filter_verdict.py

bench-policy: ## Device-vectorized policy scoring vs per-cell host loop + spot repack frontier (config_13); prints verdict line on stderr
	python bench.py --only config_13 \
		| python tools/policy_verdict.py

bench-affinity: ## Soft-affinity scoring: co-location steering A/B + fused soft-row kernel vs host loop (config_18); prints verdict line on stderr
	python bench.py --only config_18 \
		| python tools/affinity_verdict.py

bench-global: ## Whole-window global solve vs per-schedule FFD fleet cost A/B (config_14); prints verdict line on stderr
	python bench.py --only config_14 \
		| python tools/global_verdict.py

bench-topology: ## Torus-grid slice carving: fragmentation harvest, carve kernel vs scalar loop, priced preemption (config_16); prints verdict line on stderr
	python bench.py --only config_16 \
		| python tools/topology_verdict.py

bench-carve-journal: ## Durable carve ledger: journal tax (gate <=1% of loop wall) + cold ledger-recovery wall + machine cleanliness (config_17)
	python bench.py --only config_17

bench-replay: ## Million-pod replay across 4 shards + 100k-object store A/B (config_9); verdict + SLO verdict + traceview table on stderr
	python bench.py --only config_9 \
		| python tools/replay_verdict.py \
		| python tools/slo_verdict.py \
		| python tools/traceview.py --bench

bench-replay-smoke: ## bench-replay at 10k pods / 2 shards (KARPENTER_REPLAY_SMOKE=1); same verdict + SLO verdict + traceview chain
	KARPENTER_REPLAY_SMOKE=1 python bench.py --only config_9 \
		| python tools/replay_verdict.py \
		| python tools/slo_verdict.py \
		| python tools/traceview.py --bench

replay-smoke: ## 10k-pod 2-shard replay smoke (<60s) with chaos + pressure active
	JAX_PLATFORMS=cpu python -m pytest tests/test_replay.py -q -s -m slow

metrics-lint: ## Every registered metric must carry help text and appear in the docs metric tables
	python tools/metrics_lint.py

bench-history: ## Render the BENCH_r*.json trajectory as one table
	python tools/bench_history.py

bench-regress: ## CI gate: latest BENCH round vs best prior per tracked series; exit 1 on regression
	python tools/bench_regress.py

native: ## Build the C++ FFD kernel explicitly (normally built lazily)
	g++ -O3 -std=c++17 -shared -fPIC \
		-o karpenter_tpu/native/_libktffd.so karpenter_tpu/native/ffd.cc

lint: ## ruff + mypy quality gate (the golangci/gocyclo analog, SURVEY §5.2)
	@if command -v ruff >/dev/null 2>&1; then \
		ruff check karpenter_tpu tests bench.py __graft_entry__.py; \
	else \
		echo "lint: ruff not installed in this environment; skipping (CI runs it)"; \
	fi
	@if command -v mypy >/dev/null 2>&1; then \
		mypy karpenter_tpu/solver karpenter_tpu/ops karpenter_tpu/api; \
	else \
		echo "lint: mypy not installed in this environment; skipping (CI runs it)"; \
	fi

chart: ## Render the Helm chart with the in-repo renderer (no helm needed)
	python -m karpenter_tpu.utils.helmlite charts/karpenter-tpu

dryrun: ## Compile-check the sharded multi-chip step on an 8-device CPU mesh
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
		python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

soak: ## Extended differential soak: 500 fuzz cases + repeated chaos/races
	KARPENTER_FUZZ_CASES=500 python -m pytest tests/test_fuzz_parity.py -q
	python -m pytest tests/test_chaos.py tests/test_races.py -q --count=5 \
		2>/dev/null || for i in 1 2 3 4 5; do \
		python -m pytest tests/test_chaos.py tests/test_races.py -q; done

chaos-soak: ## Seeded fault-injection soak (slow); prints seed, replay via KARPENTER_CHAOS_SEED=<n>
	JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q -s -m slow

chaos-crash: ## Crash-restart soak: every journal kill point (incl. carve/preempt, ledger compared bit-for-bit) x seeds {1,7,42} (slow)
	JAX_PLATFORMS=cpu python -m pytest tests/test_crash_recovery.py -q -s -m slow

chaos-overload: ## Brownout soak: 50k-pod flood + pressure faults (slow) after the fast seeded smoke
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_chaos.py::TestOverloadSoak::test_overload_smoke_brownout_and_recovery \
		tests/test_pressure.py -q -s
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_chaos.py::TestOverloadSoak::test_overload_soak_50k_flood \
		-q -s -m slow

cardinality-diff: ## One-off full-size 50k×25k-shape differential (hours)
	python tools/full_cardinality_diff.py

clean: ## Remove build artifacts
	rm -f karpenter_tpu/native/_libktffd.so
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
