"""Benchmark: the north-star config from BASELINE.json.

Packs 50k mixed pending pods against a 400-type catalog and reports p99
end-to-end solve latency (host marshal + encode + device pack + decode).
Target (BASELINE.md): < 200 ms p99 on TPU v5e-4, node count within ±1 of
the reference Go FFD packer — we assert EXACT node parity against the host
oracle, which implements the Go packer's semantics verbatim.

Prints exactly one JSON line:
  {"metric": ..., "value": p99_ms, "unit": "ms", "vs_baseline": 200/p99_ms}
vs_baseline > 1.0 means beating the engineered 200 ms target (the reference
publishes no benchmark numbers — BASELINE.md).
"""

from __future__ import annotations

import json
import sys
import time

N_PODS = 50_000
N_TYPES = 400
ITERS = 9
TARGET_MS = 200.0


def build_workload():
    from karpenter_tpu.api.core import Container, Pod, PodSpec, ResourceRequirements
    from karpenter_tpu.cloudprovider.fake.provider import make_instance_type
    from karpenter_tpu.controllers.provisioning import universe_constraints

    # 400-type synthetic EC2-like catalog: cpu × memory-ratio grid
    catalog = []
    i = 0
    cpus = [1, 2, 4, 8, 16, 24, 32, 48, 64, 96]
    ratios = [2, 4, 8]
    while len(catalog) < N_TYPES:
        cpu = cpus[i % len(cpus)]
        ratio = ratios[(i // len(cpus)) % len(ratios)]
        catalog.append(make_instance_type(
            name=f"syn-{cpu}x{ratio}-{i}",
            cpu=str(cpu), memory=f"{cpu * ratio}Gi",
            pods=str(min(110, cpu * 15)),
        ))
        i += 1
    constraints = universe_constraints(catalog)

    # 50k mixed pods across 32 recurring request shapes
    shapes = []
    for c in (100, 250, 500, 750, 1000, 1500, 2000, 4000):
        for m in (128, 512, 1024, 4096):
            shapes.append((c, m))
    pods = [
        Pod(spec=PodSpec(containers=[Container(resources=ResourceRequirements.make(
            requests={"cpu": f"{c}m", "memory": f"{m}Mi"}))]))
        for i in range(N_PODS)
        for c, m in (shapes[i % len(shapes)],)
    ]
    return constraints, pods, catalog


def main():
    from karpenter_tpu.solver.adapter import build_packables, pod_vector
    from karpenter_tpu.models.ffd import solve_ffd_device, solve_ffd_numpy

    constraints, pods, catalog = build_workload()
    packables, _ = build_packables(catalog, constraints, pods, [])
    vecs = [pod_vector(p) for p in pods]
    ids = list(range(len(pods)))

    # warm-up (compile)
    device = solve_ffd_device(vecs, ids, packables)
    assert device is not None, "bench workload must be device-encodable"

    # exact-parity check vs the shape-level host oracle (Go packer semantics;
    # itself differentially tested against the per-pod oracle in tests/)
    host = solve_ffd_numpy(vecs, ids, packables)
    assert device.node_count == host.node_count, (
        f"node-count mismatch: device={device.node_count} host={host.node_count}")

    times = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        r = solve_ffd_device(vecs, ids, packables)
        times.append(time.perf_counter() - t0)
    times.sort()
    p99 = times[min(len(times) - 1, int(len(times) * 0.99))] * 1000.0
    print(json.dumps({
        "metric": "p99_solve_latency_ms_50k_pods_x_400_types",
        "value": round(p99, 3),
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / p99, 3),
        "extra": {
            "median_ms": round(times[len(times) // 2] * 1000.0, 3),
            "pods_per_sec": round(N_PODS / (times[len(times) // 2] or 1e-9)),
            "node_count": device.node_count,
            "node_parity_vs_go_ffd_oracle": "exact",
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
