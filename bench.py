"""Benchmark: the five BASELINE.json configs.

Headline (the one JSON line): p99 latency of the PUBLIC ``solve()`` path for
config 4 — Pod objects in → node set out: marshal (cached vector gather —
vectors are computed once per pod at watch/codec ingest, solver/adapter.py),
packables (memoized per catalog/constraints), encode, device pack, decode,
materialize. The one-time ingest marshal cost for all 50k pods is reported
separately (``ingest_marshal_ms``) — in production it is paid per watch
event, off the solve path. Target (BASELINE.md): < 200 ms p99 on TPU v5e-4,
node count within ±1 of the reference Go FFD packer — we assert EXACT node
parity against the C++ per-pod oracle (native/ffd.cc), which implements the
Go packer's semantics verbatim and is itself differentially tested against
the Python per-pod oracle and both device kernels.

Prints exactly one JSON line:
  {"metric": ..., "value": p99_ms, "unit": "ms", "vs_baseline": 200/p99_ms,
   "extra": {... all five configs, backend, degraded flag ...}}
vs_baseline > 1.0 means beating the engineered 200 ms target (the reference
publishes no benchmark numbers — BASELINE.md).

Failure posture (the bench mirrors the solver's rings, SURVEY.md §5.3).
The top-level process is a SUPERVISOR that never imports jax: it probes the
TPU backend in a subprocess with timeout+retries (utils/backend.py), then
runs the actual bench in a child it can kill:
  1. probe ok → TPU child (mode=direct). A child that hangs mid-run (the
     tunnel died after a good probe) is killed at its deadline;
  2. probe failed or TPU child failed → CPU child (mode=direct-cpu) which
     hard-deregisters the accelerator plugin (force_cpu — JAX_PLATFORMS
     alone is ignored by the axon plugin) and reports "degraded": true;
  3. inside a child, each non-headline config runs under try/except — one
     config's failure is recorded in its slot, the others still report;
  4. the JSON line is ALWAYS emitted, worst case with "degraded": true and
     an "error" note. rc=0 unless even the emit fails.

Configs (BASELINE.md table):
  1. 100 pods, cpu/mem only, 10 types, 1 AZ (smoke)
  2. 5k pods, nodeSelector + taints/tolerations, 400-type catalog
  3. 20k pods, 3-zone topology spread (3 per-zone schedules, batch-solved)
  4. 50k mixed pods, spot+OD, cost-minimizing           ← headline
  5. consolidation: re-pack 2k running nodes → minimal set

Statistics: ≥100 timed iterations per config (time-budgeted — slow
degraded paths cap at BUDGET_S and report the honest iteration count);
p50/p90/p99 all reported, p99 by rank on the sorted sample.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

TARGET_MS = 200.0
ITERS = 100           # target timed iterations per config
BUDGET_S = 90.0       # wall-clock cap per config's timing loop
_MODE_ENV = "KARPENTER_BENCH_MODE"        # unset=supervisor | direct | direct-cpu
_DEVICES_ENV = "KARPENTER_BENCH_DEVICES"  # --devices N, inherited by children
TPU_CHILD_DEADLINE_S = 1800.0
CPU_CHILD_DEADLINE_S = 1500.0


def _apply_devices_env():
    """Honor ``--devices N`` (the _DEVICES_ENV var) in a child: force the
    host platform to expose N virtual devices via XLA_FLAGS. Must run
    before jax is imported; if some import beat us to it (direct mode
    invoked by hand in an already-warm interpreter), re-exec so the flag
    takes. On a real TPU backend the flag is inert (it only affects the
    host platform), so it is safe to set unconditionally."""
    raw = os.environ.get(_DEVICES_ENV, "").strip()
    if not raw:
        return
    try:
        n = int(raw)
    except ValueError:
        return
    if n < 1:
        return
    want = f"--xla_force_host_platform_device_count={n}"
    flags = os.environ.get("XLA_FLAGS", "")
    if want not in flags.split():
        flags = " ".join(
            [f for f in flags.split()
             if not f.startswith("--xla_force_host_platform_device_count=")]
            + [want])
        os.environ["XLA_FLAGS"] = flags
        if "jax" in sys.modules:  # too late for this process: re-exec
            os.execv(sys.executable, [sys.executable] + sys.argv)


def _q(times_sorted, frac):
    return times_sorted[min(len(times_sorted) - 1,
                            int(len(times_sorted) * frac))] * 1000.0


def _stats(times):
    ts = sorted(times)
    return {
        "iters": len(ts),
        "p50_ms": round(_q(ts, 0.50), 3),
        "p90_ms": round(_q(ts, 0.90), 3),
        "p99_ms": round(_q(ts, 0.99), 3),
        "mean_ms": round(sum(ts) / len(ts) * 1000.0, 3),
    }


def run_timed(fn, max_iters=ITERS, budget_s=BUDGET_S):
    """Time fn() up to max_iters times within a wall-clock budget (≥3 always)."""
    times = []
    t_start = time.monotonic()
    for i in range(max_iters):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
        if i >= 2 and time.monotonic() - t_start > budget_s:
            break
    return times


def make_catalog(n_types, zones=3, price_base=0.05, spot_rate=None):
    from karpenter_tpu.cloudprovider.fake.provider import make_instance_type
    from karpenter_tpu.cloudprovider.spi import Offering

    catalog = []
    cpus = [1, 2, 4, 8, 16, 24, 32, 48, 64, 96]
    ratios = [2, 4, 8]
    i = 0
    while len(catalog) < n_types:
        cpu = cpus[i % len(cpus)]
        ratio = ratios[(i // len(cpus)) % len(ratios)]
        offerings = [
            Offering(ct, f"bench-zone-{z + 1}",
                     interruption_rate=(spot_rate(i, z) if spot_rate
                                        and ct == "spot" else 0.0))
            for z in range(zones) for ct in ("on-demand", "spot")
        ]
        catalog.append(make_instance_type(
            name=f"syn-{cpu}x{ratio}-{i}",
            cpu=str(cpu), memory=f"{cpu * ratio}Gi",
            pods=str(min(110, cpu * 15)),
            offerings=offerings,
            price=price_base * cpu * (1 + 0.1 * (ratio // 4)),
        ))
        i += 1
    return catalog


def make_pods(n, shapes):
    from karpenter_tpu.api.core import Container, Pod, PodSpec, ResourceRequirements

    return [
        Pod(spec=PodSpec(containers=[Container(resources=ResourceRequirements.make(
            requests={"cpu": f"{c}m", "memory": f"{m}Mi"}))]))
        for i in range(n)
        for c, m in (shapes[i % len(shapes)],)
    ]


MIXED_SHAPES = [
    (c, m)
    for c in (100, 250, 500, 750, 1000, 1500, 2000, 4000)
    for m in (128, 512, 1024, 4096)
]


def oracle_node_count(constraints, pods, catalog, daemons=()):
    """Per-POD Go-semantics node count from the C++ oracle
    (native/ffd.cc kt_ffd_pack_per_pod — packer.go:109-141 transcribed, no
    fast-forward, one record per node) — every config's forward solve
    asserts parity against this. Falls back through the executor rings if
    the native toolchain is unavailable."""
    from karpenter_tpu.models.ffd import solve_ffd_numpy
    from karpenter_tpu.solver.adapter import build_packables_cached, pod_vectors
    from karpenter_tpu.solver.native_ffd import solve_ffd_per_pod_native

    packables, _ = build_packables_cached(catalog, constraints, pods, daemons)
    vecs, ids = pod_vectors(pods), list(range(len(pods)))
    result = solve_ffd_per_pod_native(vecs, ids, packables)
    label = "exact (per-pod C++ oracle)"
    if result is None:  # no C++ toolchain: shape-level numpy mirror instead
        result = solve_ffd_numpy(vecs, ids, packables)
        label = "exact (shape-level numpy fallback — no C++ toolchain)"
    return result.node_count, label


def config_1_smoke():
    """The production solve() path: 100 pods route to the native C++ kernel
    (below device_min_pods a device round-trip costs more than it saves)."""
    from karpenter_tpu.controllers.provisioning import universe_constraints
    from karpenter_tpu.solver import host_ffd
    from karpenter_tpu.solver.adapter import build_packables, pod_vector
    from karpenter_tpu.solver.solve import solve

    catalog = make_catalog(10, zones=1)
    pods = make_pods(100, [(500, 512), (1000, 1024)])
    constraints = universe_constraints(catalog)
    result = solve(constraints, pods, catalog)  # warm-up
    packables, _ = build_packables(catalog, constraints, pods, [])
    oracle = host_ffd.pack([pod_vector(p) for p in pods],
                           list(range(len(pods))), packables)
    assert result.node_count == oracle.node_count
    times = run_timed(lambda: solve(constraints, pods, catalog))
    st = _stats(times)
    return {"pods": 100, **st,
            "node_count": result.node_count,
            "pods_per_sec": round(100 / (st["p50_ms"] / 1000.0 or 1e-9)),
            "node_parity_vs_per_pod_go_oracle": "exact (python per-pod oracle)"}


def config_2_constrained():
    """5k pods with nodeSelector + tolerations through the public solve()
    path: constraint tightening + viability filtering + cost-aware option
    ordering all included."""
    from karpenter_tpu.api import wellknown
    from karpenter_tpu.api.constraints import Taints
    from karpenter_tpu.api.core import Taint, Toleration
    from karpenter_tpu.controllers.provisioning import universe_constraints
    from karpenter_tpu.solver.solve import solve

    catalog = make_catalog(400)
    constraints = universe_constraints(catalog)
    constraints.taints = Taints([Taint(key="bench", value="true", effect="NoSchedule")])
    pods = make_pods(5_000, MIXED_SHAPES)
    for p in pods:
        p.spec.node_selector = {wellknown.LABEL_TOPOLOGY_ZONE: "bench-zone-1"}
        p.spec.tolerations = [Toleration(key="bench", operator="Equal",
                                         value="true", effect="NoSchedule")]
    tightened = constraints.tighten(pods[0])
    tightened.taints = constraints.taints
    result = solve(tightened, pods, catalog)  # warm-up
    assert not result.unschedulable
    oracle, oracle_label = oracle_node_count(tightened, pods, catalog)
    assert result.node_count == oracle, (
        f"node-count mismatch: solve={result.node_count} per-pod-oracle={oracle}")
    times = run_timed(lambda: solve(tightened, pods, catalog))
    st = _stats(times)
    return {"pods": 5_000, **st,
            "node_count": result.node_count,
            "node_parity_vs_per_pod_go_oracle": oracle_label,
            "pods_per_sec": round(5_000 / (st["p50_ms"] / 1000.0 or 1e-9))}


def config_3_topology():
    """20k pods spread over 3 zones → 3 per-zone schedules solved through
    the PUBLIC solve_batch() — marshal + encode + ONE sharded device call
    (vmap within a chip, shard_map across the mesh, one flattened fetch) +
    decode/materialize, exactly what the provisioning worker runs
    (controllers/provisioning.py:127). Per-zone node parity asserted against
    the per-pod C++ oracle."""
    from karpenter_tpu.controllers.provisioning import universe_constraints
    from karpenter_tpu.solver.batch_solve import Problem, solve_batch

    catalog = make_catalog(100)
    constraints = universe_constraints(catalog)
    pods = make_pods(20_000, MIXED_SHAPES)
    # topology-spread: each zone domain receives len(pods)/3 (topology.go:112-140)
    problems = [
        Problem(constraints=constraints, pods=pods[z::3], instance_types=catalog)
        for z in range(3)
    ]

    results = solve_batch(problems)  # warm-up (compile)
    node_count = 0
    for prob, res in zip(problems, results):
        assert not res.unschedulable
        oracle, oracle_label = oracle_node_count(constraints, prob.pods, catalog)
        assert res.node_count == oracle, (
            f"node-count mismatch: solve={res.node_count} per-pod-oracle={oracle}")
        node_count += res.node_count

    times = run_timed(lambda: solve_batch(problems))
    st = _stats(times)
    return {"pods": 20_000, "zones": 3, **st, "node_count": node_count,
            "node_parity_vs_per_pod_go_oracle": f"{oracle_label} — each zone",
            "timed_path": "public solve_batch(): 3 schedules, one device call",
            "pods_per_sec": round(20_000 / (st["p50_ms"] / 1000.0 or 1e-9))}


def _kernel_breakdown(pods, catalog):
    """Isolate kernel cost from transport: run each device kernel with ALL
    outputs reduced to one scalar on device, so a solve costs exactly one
    tiny fetch. The spread over the measured raw RTT is the kernel's own
    device time (the tunnel RTT dominates everything end-to-end)."""
    import functools

    import numpy as np

    import jax
    import jax.numpy as jnp

    from karpenter_tpu.controllers.provisioning import universe_constraints
    from karpenter_tpu.models.ffd import device_args
    from karpenter_tpu.ops.encode import encode
    from karpenter_tpu.ops.pack import pack_chunk
    from karpenter_tpu.ops.pack_pallas import (
        check_counts_within_div_cap, pack_chunk_pallas,
    )
    from karpenter_tpu.solver.adapter import build_packables, pod_vector

    constraints = universe_constraints(catalog)
    packables, _ = build_packables(catalog, constraints, pods, [])
    enc = encode([pod_vector(p) for p in pods], list(range(len(pods))), packables)
    # counts is still concrete: enforce the pallas DIV_CAP precondition
    # before timing anything (a clamped kernel would bench garbage)
    check_counts_within_div_cap(enc.counts)
    args = tuple(jax.device_put(device_args(enc)))

    @functools.partial(jax.jit, static_argnames=("which",))
    def csum(*a, which):
        fn = pack_chunk if which == "xla" else pack_chunk_pallas
        return sum(jnp.sum(o.astype(jnp.int32)) for o in fn(*a, num_iters=64))

    f = jax.jit(lambda x: x + 1)
    tiny = jax.device_put(np.zeros(4, np.int32))
    np.asarray(f(tiny))
    # Mosaic only compiles on real TPU; interpret-mode timings would be
    # meaningless, so the pallas row is TPU-only
    kernels = (None, "xla", "pallas") if jax.default_backend() == "tpu" else (
        None, "xla")
    out = {}
    for which in kernels:
        run = (lambda: np.asarray(f(tiny))) if which is None else (
            lambda: np.asarray(csum(*args, which=which)))
        run()
        ts = run_timed(run, max_iters=25, budget_s=20.0)
        out["raw_rtt_ms" if which is None else f"{which}_single_fetch_ms"] = (
            round(sorted(ts)[len(ts) // 2] * 1000.0, 2))
    return out


def config_4_headline():
    """THE production path: Pod objects in → node set out through the public
    solve() — cached-marshal gather + memoized packables + encode + device
    pack + decode + materialize all inside the timed region. The one-time
    ingest marshal (watch/codec primes each pod's vector) is measured and
    reported separately."""
    from karpenter_tpu.controllers.provisioning import universe_constraints
    from karpenter_tpu.solver.adapter import pod_vectors
    from karpenter_tpu.solver.solve import solve

    catalog = make_catalog(400)
    pods = make_pods(50_000, MIXED_SHAPES)
    constraints = universe_constraints(catalog)

    t0 = time.perf_counter()
    pod_vectors(pods)  # ingest-time marshal (codec does this per watch event)
    ingest_marshal_ms = round((time.perf_counter() - t0) * 1000.0, 1)

    result = solve(constraints, pods, catalog)  # warm-up (compile)
    oracle, oracle_label = oracle_node_count(constraints, pods, catalog)
    assert result.node_count == oracle, (
        f"node-count mismatch: solve={result.node_count} per-pod-oracle={oracle}")
    assert not result.unschedulable

    times = run_timed(lambda: solve(constraints, pods, catalog))
    st = _stats(times)
    return times, {"pods": 50_000, "types": 400, **st,
                   "node_count": result.node_count,
                   "pods_per_sec": round(50_000 / (st["p50_ms"] / 1000.0 or 1e-9)),
                   "node_parity_vs_per_pod_go_oracle": oracle_label,
                   "timed_path": "public solve(): Pod objects in, node set out",
                   "ingest_marshal_ms_50k_cold": ingest_marshal_ms,
                   "kernel_breakdown": _kernel_breakdown(pods, catalog)}


def config_5_consolidation():
    """Re-pack 2k fragmented running nodes into the minimal set
    (models/consolidate.repack_plan on the device kernel)."""
    from karpenter_tpu.api import wellknown
    from karpenter_tpu.api.core import Node, NodeSpec, NodeStatus, ObjectMeta
    from karpenter_tpu.controllers.provisioning import universe_constraints
    from karpenter_tpu.models.consolidate import repack_plan
    from karpenter_tpu.utils.resources import parse_resource_list

    catalog = make_catalog(100)
    constraints = universe_constraints(catalog)
    big = max(catalog, key=lambda it: it.cpu.nano)
    nodes, pods_by_node = [], {}
    pods = make_pods(2_000 * 3, [(250, 256), (500, 512), (1000, 1024)])
    for i in range(2_000):
        name = f"frag-{i}"
        nodes.append(Node(
            metadata=ObjectMeta(name=name, namespace="", labels={
                wellknown.LABEL_INSTANCE_TYPE: big.name,
                wellknown.LABEL_CAPACITY_TYPE: "on-demand",
                wellknown.PROVISIONER_NAME_LABEL: "bench",
            }),
            spec=NodeSpec(),
            status=NodeStatus(allocatable=parse_resource_list({
                "cpu": str(big.cpu), "memory": str(big.memory),
                "pods": str(big.pods)})),
        ))
        batch = pods[i * 3:(i + 1) * 3]
        for j, p in enumerate(batch):
            p.metadata.name = f"pod-{i}-{j}"
        pods_by_node[name] = batch

    plan = repack_plan(nodes, pods_by_node, constraints, catalog)  # warm-up
    assert plan.saves, "fragmented fleet must consolidate"
    oracle, oracle_label = oracle_node_count(constraints, pods, catalog)
    assert plan.planned_nodes == oracle, (
        f"node-count mismatch: repack={plan.planned_nodes} per-pod-oracle={oracle}")
    times = run_timed(
        lambda: repack_plan(nodes, pods_by_node, constraints, catalog),
        budget_s=60.0)
    st = _stats(times)
    return {"running_nodes": 2_000, "pods": 6_000, **st,
            "planned_nodes": plan.planned_nodes,
            "node_parity_vs_per_pod_go_oracle": f"{oracle_label} — re-pack forward solve",
            "cost_before_per_hour": round(plan.current_cost_per_hour, 2),
            "cost_after_per_hour": round(plan.planned_cost_per_hour, 2),
            "consolidation_window": _consolidation_window_bench(),
            "trace_leg": _trace_shaped_window_bench()}


def _consolidation_window_bench():
    """Steady-state 2k-node what-if window (the bench-consolidate gate):
    W near-full candidate nodes (a DaemonSet filler pins most of each bin,
    3 movable pods ride on top), a mostly-full fleet, and a scarce tail of
    empty receivers. The host-incremental leg answers each "does node i
    drain?" with its own place_onto scan (the old one-node-per-pass cost);
    the batched leg answers the whole window with one encode + one kernel.
    Every executed drain is independently re-verified here with a fresh
    place_onto commit sequence — the zero-unverified-drains evidence the
    verdict gate reads."""
    from karpenter_tpu.api import wellknown
    from karpenter_tpu.api.core import (
        Node, NodeSpec, NodeStatus, ObjectMeta, OwnerReference,
    )
    from karpenter_tpu.controllers.provisioning import universe_constraints
    from karpenter_tpu.models.consolidate import (
        node_bin, place_onto, repack_plan, reschedulable_pods,
    )
    from karpenter_tpu.ops.whatif import encode_window
    from karpenter_tpu.solver.whatif import (
        WhatIfConfig, plan_window, solve_window,
    )
    from karpenter_tpu.utils.resources import parse_resource_list

    W, FULL, RECV = 384, 1592, 24
    catalog = make_catalog(100)
    big = max(catalog, key=lambda it: it.cpu.nano)

    def mk_node(name):
        return Node(
            metadata=ObjectMeta(name=name, namespace="", labels={
                wellknown.LABEL_INSTANCE_TYPE: big.name,
                wellknown.LABEL_CAPACITY_TYPE: "on-demand",
                wellknown.PROVISIONER_NAME_LABEL: "bench"}),
            spec=NodeSpec(),
            status=NodeStatus(allocatable=parse_resource_list({
                "cpu": str(big.cpu), "memory": str(big.memory),
                "pods": str(big.pods)})))

    ds = OwnerReference(api_version="apps/v1", kind="DaemonSet",
                        name="filler", uid="ds")
    # filler bins keep 100m free (< any movable pod); candidates keep 850m
    # so their movable load fits nowhere but the receiver tail
    fill_m = (big.cpu.nano - 100 * 10**6) // 10**6
    cand_fill_m = (big.cpu.nano - 850 * 10**6) // 10**6

    def mk_pods(prefix, shapes, owner=None):
        out = []
        for j, (c, m) in enumerate(shapes):
            p = make_pods(1, [(c, m)])[0]
            p.metadata.name = f"{prefix}-{j}"
            if owner is not None:
                p.metadata.owner_references = [owner]
            out.append(p)
        return out

    nodes, pods_by = [], {}
    for i in range(W):
        n = mk_node(f"cand-{i}")
        nodes.append(n)
        pods_by[n.metadata.name] = (
            mk_pods(f"cfill-{i}", [(cand_fill_m, 128)], owner=ds)
            + mk_pods(f"mv-{i}", [(250, 256)] * 3))
    for i in range(FULL):
        n = mk_node(f"full-{i}")
        nodes.append(n)
        pods_by[n.metadata.name] = mk_pods(
            f"fill-{i}", [(fill_m, 128)], owner=ds)
    for i in range(RECV):
        n = mk_node(f"recv-{i}")
        nodes.append(n)
        pods_by[n.metadata.name] = []

    bins = [node_bin(n, pods_by[n.metadata.name]) for n in nodes]
    cand_idx = list(range(W))
    cand_movable = [reschedulable_pods(pods_by[f"cand-{i}"])[0]
                    for i in range(W)]

    # leg 1: host-incremental — one place_onto scan per candidate
    t0 = time.perf_counter()
    host_feas = [
        place_onto(cand_movable[i], bins[:i] + bins[i + 1:]) is not None
        for i in cand_idx]
    t_inc = time.perf_counter() - t0

    # leg 2: batched what-if — one encode + one kernel for the window
    cfg = WhatIfConfig(device_min_cells=0)
    solve_window(encode_window(bins, cand_idx, cand_movable), cfg)  # warm-up
    t0 = time.perf_counter()
    enc = encode_window(bins, cand_idx, cand_movable)
    feas, _, executor = solve_window(enc, cfg)
    t_bat = time.perf_counter() - t0
    parity = [bool(f) for f in feas] == host_feas

    plan = plan_window(enc, feas, [big.price] * W, max_drains=W)
    # independent re-verification: replay the plan as place_onto commits on
    # a FRESH bin set (drained bins drop out as the replay proceeds)
    vbins = [node_bin(n, pods_by[n.metadata.name]) for n in nodes]
    drained = set()
    unverified = 0
    for action in plan.actions:
        surviving = [b for j, b in enumerate(vbins)
                     if j != action.bin and j not in drained]
        if place_onto(cand_movable[action.cand], surviving,
                      commit=True) is None:
            unverified += 1
        else:
            drained.add(action.bin)

    # leg 3: LP/ADMM relaxation re-pack of the candidate subset
    constraints = universe_constraints(catalog)
    cand_nodes = nodes[:W]
    cand_pods_by = {n.metadata.name: pods_by[n.metadata.name]
                    for n in cand_nodes}
    t0 = time.perf_counter()
    rplan = repack_plan(cand_nodes, cand_pods_by, constraints, catalog,
                        backend="relax")
    t_relax = time.perf_counter() - t0
    relax = rplan.relax

    return {
        "fleet_nodes": len(nodes), "candidates": W,
        "host_incremental_s": round(t_inc, 4),
        "host_incremental_evals_per_s": round(W / t_inc, 1),
        "batched_s": round(t_bat, 4),
        "batched_evals_per_s": round(W / t_bat, 1),
        "speedup": round(t_inc / t_bat, 1),
        "executor": executor,
        "parity": parity,
        "feasible": int(sum(host_feas)),
        "drains": len(plan.actions),
        "unverified_drains": unverified,
        "reclaimed_per_hour": round(plan.reclaimed_per_hour, 2),
        "relax": None if relax is None else {
            "seconds": round(t_relax, 3),
            "used": relax.used, "reason": relax.reason,
            "relax_cost": round(relax.relax_cost, 4)
            if relax.relax_cost != float("inf") else None,
            "ffd_cost": round(relax.ffd_cost, 4)
            if relax.ffd_cost != float("inf") else None,
            "planned_nodes": rplan.planned_nodes},
    }


def _trace_shaped_window_bench():
    """`bench.py --only config_5 --trace TRACE_replay.json`: feed a
    RECORDED diurnal load shape into the scale-down window instead of the
    synthetic steady state. The replay's trace dump (bench-replay,
    obs/trace.dump_chrome) carries one ``window-close`` event per
    provisioning window with its item count; bucketing those into K
    phases recovers the offered-load curve the replay actually ran. Each
    phase then drives one what-if window: candidate occupancy scales with
    the phase's load (peak ⇒ 3 movable pods pinned per candidate, trough
    ⇒ 1) against a fixed scarce receiver tail, so the drainable fraction
    the batched solve finds must move INVERSELY with the recorded curve —
    scale-down capacity appears exactly when the diurnal trough does.
    Per phase: host place_onto parity and an independent commit-replay
    re-verification (zero unverified drains), the same contract as the
    synthetic window. No --trace (or a missing file) skips the leg."""
    import json as _json

    from karpenter_tpu.api import wellknown
    from karpenter_tpu.api.core import (
        Node, NodeSpec, NodeStatus, ObjectMeta, OwnerReference,
    )
    from karpenter_tpu.models.consolidate import node_bin, place_onto
    from karpenter_tpu.ops.whatif import encode_window
    from karpenter_tpu.solver.whatif import (
        WhatIfConfig, plan_window, solve_window,
    )
    from karpenter_tpu.utils.resources import parse_resource_list

    path = os.environ.get("KARPENTER_BENCH_TRACE", "").strip()
    if not path:
        return {"skipped": "no --trace"}
    try:
        with open(path) as f:
            dump = _json.load(f)
    except (OSError, ValueError) as e:
        return {"skipped": f"trace unreadable: {type(e).__name__}: {e}"}
    events = [e for e in dump.get("traceEvents", [])
              if e.get("name") == "window-close" and "ts" in e]
    if len(events) < 2:
        return {"skipped": "trace has no window-close events"}

    # the recorded curve: bucket window item counts into K phases
    K = 6
    t0 = min(e["ts"] for e in events)
    t1 = max(e["ts"] for e in events)
    span = max(t1 - t0, 1e-9)
    load = [0.0] * K
    for e in events:
        k = min(K - 1, int((e["ts"] - t0) / span * K))
        load[k] += float((e.get("args") or {}).get("items", 1))
    peak = max(load) or 1.0
    weights = [round(v / peak, 4) for v in load]

    W, RECV = 128, 8
    catalog = make_catalog(100)
    big = max(catalog, key=lambda it: it.cpu.nano)
    ds = OwnerReference(api_version="apps/v1", kind="DaemonSet",
                       name="filler", uid="ds")
    cand_fill_m = (big.cpu.nano - 850 * 10**6) // 10**6

    def mk_node(name, cpu, memory, pods):
        return Node(
            metadata=ObjectMeta(name=name, namespace="", labels={
                wellknown.LABEL_INSTANCE_TYPE: big.name,
                wellknown.LABEL_CAPACITY_TYPE: "on-demand",
                wellknown.PROVISIONER_NAME_LABEL: "bench"}),
            spec=NodeSpec(),
            status=NodeStatus(allocatable=parse_resource_list({
                "cpu": cpu, "memory": memory, "pods": pods})))

    phases = []
    cfg = WhatIfConfig(device_min_cells=0)
    warm = False
    for k, w in enumerate(weights):
        # recorded load -> pinned movable occupancy: 1 (trough) .. 3 (peak)
        mv = 1 + round(2 * w)
        nodes, pods_by = [], {}
        for i in range(W):
            n = mk_node(f"tc{k}-{i}", str(big.cpu), str(big.memory),
                        str(big.pods))
            nodes.append(n)
            fill = make_pods(1, [(cand_fill_m, 128)])[0]
            fill.metadata.name = f"tcf{k}-{i}"
            fill.metadata.owner_references = [ds]
            movable = make_pods(mv, [(250, 256)])
            for j, p in enumerate(movable):
                p.metadata.name = f"tmv{k}-{i}-{j}"
            pods_by[n.metadata.name] = [fill] + movable
        for i in range(RECV):
            # scarce fixed tail: 2 cpu / 16 pods each — peak-phase load
            # cannot fully evacuate, trough-phase load can
            n = mk_node(f"tr{k}-{i}", "2", "8Gi", "16")
            nodes.append(n)
            pods_by[n.metadata.name] = []

        bins = [node_bin(n, pods_by[n.metadata.name]) for n in nodes]
        cand_idx = list(range(W))
        cand_movable = [pods_by[f"tc{k}-{i}"][1:] for i in range(W)]
        host_feas = [
            place_onto(cand_movable[i], bins[:i] + bins[i + 1:]) is not None
            for i in cand_idx]
        if not warm:
            solve_window(encode_window(bins, cand_idx, cand_movable), cfg)
            warm = True
        t_start = time.perf_counter()
        enc = encode_window(bins, cand_idx, cand_movable)
        feas, _, executor = solve_window(enc, cfg)
        t_bat = time.perf_counter() - t_start
        plan = plan_window(enc, feas, [big.price] * W, max_drains=W)
        vbins = [node_bin(n, pods_by[n.metadata.name]) for n in nodes]
        drained, unverified = set(), 0
        for action in plan.actions:
            surviving = [b for j, b in enumerate(vbins)
                         if j != action.bin and j not in drained]
            if place_onto(cand_movable[action.cand], surviving,
                          commit=True) is None:
                unverified += 1
            else:
                drained.add(action.bin)
        phases.append({
            "weight": w, "movable_per_candidate": mv,
            "drains": len(plan.actions),
            "parity": [bool(f) for f in feas] == host_feas,
            "unverified_drains": unverified,
            "batched_s": round(t_bat, 4), "executor": executor,
            "reclaimed_per_hour": round(plan.reclaimed_per_hour, 2),
        })

    trough = min(range(K), key=lambda k: weights[k])
    peak_k = max(range(K), key=lambda k: weights[k])
    return {
        "source": path, "windows": len(events), "phases": phases,
        "weights": weights,
        # the recorded shape must drive scale-down: the trough phase
        # drains at least as much as the peak phase
        "shape_consistent": phases[trough]["drains"]
                            >= phases[peak_k]["drains"],
        "drains_trough": phases[trough]["drains"],
        "drains_peak": phases[peak_k]["drains"],
    }


def config_6_high_cardinality():
    """Heterogeneous-cluster regime (round-2 gap: >4,096 distinct request
    vectors silently left the TPU path, unmeasured). Two sub-configs:

    - 8k distinct shapes / 50k pods: the DEVICE path via the 8192-shape
      bucket (block-tiled shape scan), device forced, parity vs the per-pod
      C++ oracle;
    - 25k distinct shapes / 50k pods: beyond any device bucket — the
      production solve() auto-routes to the per-pod C++ kernel (skip list +
      cpu-jump), measured through the public path.
    """
    import random

    from karpenter_tpu.api.core import (
        Container, Pod, PodSpec, ResourceRequirements,
    )
    from karpenter_tpu.controllers.provisioning import universe_constraints
    from karpenter_tpu.models.ffd import solve_ffd_device
    from karpenter_tpu.solver.adapter import build_packables_cached, pod_vectors
    from karpenter_tpu.solver.solve import solve

    def mkpods(n, distinct, seed):
        rng = random.Random(seed)
        shapes = set()
        while len(shapes) < distinct:
            shapes.add((rng.randint(50, 4000), rng.randint(64, 4096)))
        shapes = sorted(shapes)
        return [
            Pod(spec=PodSpec(containers=[Container(
                resources=ResourceRequirements.make(requests={
                    "cpu": f"{c}m", "memory": f"{m}Mi"}))]))
            for i in range(n) for c, m in (shapes[i % len(shapes)],)
        ]

    catalog = make_catalog(400)
    constraints = universe_constraints(catalog)
    out = {}

    # -- 8k shapes: device path, forced --------------------------------------
    pods = mkpods(50_000, 8_000, seed=11)
    packables, _ = build_packables_cached(catalog, constraints, pods, [])
    vecs, ids = pod_vectors(pods), list(range(len(pods)))
    # larger chunks: at high cardinality fast-forward rarely collapses, so
    # records ≈ nodes and each extra chunk is a device round trip.
    # kernel=None → default (pallas on real TPU): the 8192 bucket was
    # hardware-validated r4 (exact vs the per-pod C++ oracle at 5k/8k
    # shapes) and the fused pallas kernel runs it ~1.9 s warm (r5 blocked
    # walk + exact f32 division + pipelined fetch; the block-tiled XLA
    # scan needs ~37 s) — docs/solver.md §9 has the measured roofline
    dev = solve_ffd_device(vecs, ids, packables, chunk_iters=512)  # warm-up
    if dev is not None:
        import jax

        oracle, oracle_label = oracle_node_count(constraints, pods, catalog)
        assert dev.node_count == oracle, (
            f"high-cardinality mismatch: device={dev.node_count} oracle={oracle}")
        if jax.default_backend() == "cpu":
            # degraded path: the XLA-on-CPU scan takes minutes per call at
            # this bucket; one timed call records the honest (meaningless
            # for TPU) number without eating the child deadline
            t0 = time.perf_counter()
            solve_ffd_device(vecs, ids, packables, chunk_iters=512)
            times = [time.perf_counter() - t0]
        else:
            times = run_timed(lambda: solve_ffd_device(
                vecs, ids, packables, chunk_iters=512),
                max_iters=25, budget_s=60.0)
        st = _stats(times)
        out["device_8k_shapes"] = {
            "pods": 50_000, "distinct_shapes": 8_000, "types": 400, **st,
            "node_count": dev.node_count,
            "node_parity": oracle_label,
            "executor": "device kernel (pallas on TPU), 8192-shape bucket "
                        "(forced)"}
    else:
        out["device_8k_shapes"] = {"error": "device path declined 8k shapes"}

    # -- 25k shapes: public solve(), auto-routed to per-pod C++ --------------
    # At this cardinality solve() and the C++ oracle are the same executor,
    # so the independent check runs at a subsample the Python per-pod oracle
    # can still afford: full result-key parity at 1,500 fully-distinct
    # shapes (the same code path, different implementation).
    from karpenter_tpu.solver import host_ffd
    from karpenter_tpu.solver.native_ffd import solve_ffd_per_pod_native

    sub = mkpods(1_500, 1_500, seed=17)
    sub_packables, _ = build_packables_cached(catalog, constraints, sub, [])
    sub_vecs, sub_ids = pod_vectors(sub), list(range(len(sub)))
    want = host_ffd.pack(sub_vecs, sub_ids, sub_packables)
    got = solve_ffd_per_pod_native(sub_vecs, sub_ids, sub_packables)
    sub_parity = "unchecked (no C++ toolchain)"
    if got is not None:
        assert got.node_count == want.node_count
        assert sorted(got.unschedulable) == sorted(want.unschedulable)
        sub_parity = ("exact vs python per-pod oracle "
                      "(1.5k-distinct-shape subsample)")

    pods = mkpods(50_000, 25_000, seed=13)
    result = solve(constraints, pods, catalog)  # warm-up + route
    oracle, _ = oracle_node_count(constraints, pods, catalog)
    assert result.node_count == oracle
    times = run_timed(lambda: solve(constraints, pods, catalog),
                      max_iters=25, budget_s=60.0)
    st = _stats(times)
    out["auto_25k_shapes"] = {
        "pods": 50_000, "distinct_shapes": 25_000, "types": 400, **st,
        "node_count": result.node_count,
        "node_parity": sub_parity,
        "executor": "per-pod C++ (auto-routed: beyond device buckets)"}
    return out


def config_8_large_catalog_type_spmd():
    """The type-axis SPMD kernel at its claimed regime (VERDICT r4 #6):
    ONE 50k-pod problem over a 2,000-type catalog (the 2048 TYPE bucket).
    Single chip, two executors on the identical encoded problem:

    - the standard solo device kernel (production default routing);
    - the type-sharded kernel on a 1-device mesh (the collective pattern
      with degenerate collectives — the single-chip data point for the
      multi-chip scaling row; the 8-device CPU-mesh run lives in
      MULTICHIP_r05 with exact parity).
    """
    from karpenter_tpu.cloudprovider.fake.provider import instance_types
    from karpenter_tpu.controllers.provisioning import universe_constraints
    from karpenter_tpu.models.ffd import device_args, solve_ffd_device
    from karpenter_tpu.ops.encode import encode, pad_encoding
    from karpenter_tpu.parallel.type_sharded import (
        pack_chunk_type_sharded, type_mesh,
    )
    from karpenter_tpu.solver.adapter import build_packables_cached, pod_vectors
    from karpenter_tpu.solver.native_ffd import solve_ffd_per_pod_native

    import numpy as np

    catalog = instance_types(2_000)
    constraints = universe_constraints(catalog)
    pods = make_pods(50_000, MIXED_SHAPES)
    packables, _ = build_packables_cached(catalog, constraints, pods, [])
    vecs, ids = pod_vectors(pods), list(range(len(pods)))
    enc = encode(vecs, ids, packables)
    assert enc is not None and enc.totals.shape[0] == 2048

    # parity first (both executors vs the per-pod C++ oracle)
    dev = solve_ffd_device(vecs, ids, packables, enc=enc)
    oracle = solve_ffd_per_pod_native(vecs, ids, packables)
    parity = "unchecked (no C++ toolchain)"
    if oracle is not None and dev is not None:
        assert dev.node_count == oracle.node_count
        parity = "exact (per-pod C++ oracle)"

    out = {"pods": 50_000, "types": 2_000, "type_bucket": 2048,
           "node_count": dev.node_count if dev else None,
           "node_parity": parity}

    times = run_timed(lambda: solve_ffd_device(vecs, ids, packables, enc=enc),
                      max_iters=25, budget_s=45.0)
    out["standard_kernel"] = _stats(times)

    tmesh = type_mesh(jax_devices_first())
    L = 256
    args = device_args(pad_encoding(enc))
    buf = np.asarray(pack_chunk_type_sharded(*args, num_iters=L, mesh=tmesh))
    from karpenter_tpu.ops.pack import unpack_flat

    _, _, done, _, q, _ = unpack_flat(buf, args[0].shape[0], L)
    assert done, "type-sharded kernel did not converge in one chunk"
    if oracle is not None:
        assert int(q[q > 0].sum()) == oracle.node_count
    times = run_timed(lambda: np.asarray(pack_chunk_type_sharded(
        *args, num_iters=L, mesh=tmesh)), max_iters=25, budget_s=45.0)
    out["type_spmd_1device"] = _stats(times)
    return out


def config_9_million_pod_replay():
    """Million-pod traffic replay against the horizontally sharded control
    plane (karpenter_tpu/replay.py, docs/scale.md §3): 1M offered pods
    across 4 shard workers and 8 tenant Provisioners with chaos faults and
    the pressure ladder active, plus the 100k-object store list-by-kind
    A/B vs the naive single-dict store. Heavy (minutes) — skipped on the
    default full run; `make bench-replay` selects it via --only config_9
    and gates the result with tools/replay_verdict.py."""
    import os as _os

    from karpenter_tpu.obs import flight as _flight
    from karpenter_tpu.obs import trace as _trace
    from karpenter_tpu.replay import ReplayConfig, run_replay, store_ab

    # windows traced end-to-end (obs/trace.py): the dump feeds
    # tools/traceview.py in the bench-replay verdict chain, so the
    # overlap claim comes from spans, not wall-clock subtraction
    _trace.reset()
    was_tracing = _trace.enabled()
    _trace.enable()
    smoke = _os.environ.get("KARPENTER_REPLAY_SMOKE", "") not in ("", "0")
    # the smoke leg ALSO keeps exact latency lists so the report carries
    # the digest-vs-exact quantile parity gate (slo_verdict checks <=1%)
    cfg = ReplayConfig(
        pods_total=10_000, shards=2, tenants=2, seed=7, bound_cohort=200,
        churn_pods=200, max_depth=4_000, ticks=8, tick_sleep_s=0.1,
        burst_ticks=2, chaos=True, settle_s=60.0, flood_pool=128,
        gang_fraction=0.2, slo_exact_check=True) \
        if smoke else ReplayConfig(gang_fraction=0.2)
    try:
        ab = store_ab(objects=100_000, minority=2_000)
        report = run_replay(cfg)  # 1M / 4-shard default (smoke: 10k / 2)
    finally:
        if not was_tracing:
            _trace.disable()
    # dump BEFORE the chaos probe below: the probe resets the SLO engine,
    # and the dump's otherData.slo (traceview's digest columns) must carry
    # the MAIN leg's digests
    dump = _trace.dump_chrome(
        _os.environ.get("KARPENTER_TRACE_DUMP", "TRACE_replay.json"))
    # seeded-chaos sentinel probe: a tiny replay under the same FaultPlan
    # with a deliberately impossible objective — the burn sentinel MUST
    # trip (band/stage-tagged) and degrade readyz, where the main leg
    # above must run trip-free
    probe = run_replay(ReplayConfig(
        pods_total=1_200, shards=1, tenants=1, seed=7, bound_cohort=80,
        churn_pods=40, max_depth=600, ticks=3, tick_sleep_s=0.1,
        burst_ticks=1, chaos=True, settle_s=30.0, flood_pool=32,
        slo_objectives={"default": 0.001}))
    slo_chaos = {
        "trips": probe["slo"]["trips"],
        "burning": probe["slo"]["burning"],
        "last_trip": probe["slo"]["burn"]["last_trip"],
        "readyz_degraded": bool(probe["slo"]["burning"]),
        "probe_wall_s": probe["wall_s"],
    }
    return {
        "replay": report,
        "store_ab": ab,
        "slo_chaos": slo_chaos,
        "smoke": smoke,
        "trace_dump": dump,
        "trace": _trace.state(),
        "flight": _flight.state(),
        "nproc": _os.cpu_count(),
        "device_count": _device_count(),
        "note": "single-core host: the shard win is algorithmic (per-shard "
                "admission isolation + by-kind store index), not parallel "
                "speedup; nproc is recorded honestly above",
    }


def config_10_marshal_delta():
    """Round-10 gate: the incremental window encode (docs/solver.md §14).
    A steady-state window stream (20k pods, ~10% object churn per window)
    is marshalled + encoded through the exact production entry points
    (marshal_pods_interned → build_packables_versioned → encode) twice per
    window: DELTA (warm arena + versioned catalog cache — the round-10
    steady state) and COLD (arena, catalog cache and per-pod handles
    cleared first — the pre-round-10 cost). Each window's two encodings
    are compared bit-for-bit; the last window also solves end-to-end both
    ways (node count + bound-set parity), and a donate-leg repeat solve
    proves the steady-state ring ships zero fresh catalog transfers.
    `make bench-marshal` gates via tools/marshal_verdict.py."""
    import random as _random

    from karpenter_tpu.controllers.provisioning import universe_constraints
    from karpenter_tpu.metrics.marshal import MARSHAL_DELTA_FRACTION
    from karpenter_tpu.ops import encode as enc_mod
    from karpenter_tpu.solver import adapter
    from karpenter_tpu.solver.pipeline import get_ring
    from karpenter_tpu.solver.solve import SolverConfig, solve

    catalog = make_catalog(100)
    constraints = universe_constraints(catalog)
    n, windows, churn = 20_000, 12, 0.10
    rng = _random.Random(42)

    # deterministic window stream: each window replaces ~10% of pod
    # OBJECTS (fresh handles, same shape population — kube churn), so
    # ~90% of pods carry their arena row handle into the next window
    pop = list(make_pods(n, MIXED_SHAPES))
    streams = []
    for _ in range(windows + 1):
        k = int(n * churn)
        fresh = make_pods(k, MIXED_SHAPES)
        for j, idx in enumerate(rng.sample(range(n), k)):
            pop[idx] = fresh[j]
        streams.append(list(pop))

    def marshal_encode(win):
        vecs, required, sids = adapter.marshal_pods_interned(win)
        packables, _st, ver = adapter.build_packables_versioned(
            catalog, constraints, win, [], required=required)
        return enc_mod.encode(vecs, list(range(len(win))), packables,
                              pad=False, sids=sids, catalog_version=ver)

    def clear_all(win):
        # the pre-round-10 state: no arena rows, no per-pod handles, no
        # cached catalog tensors (the packables cache predates round 10
        # and stays warm in both legs)
        for p in win:
            p.__dict__.pop("_marshal", None)
            p.__dict__.pop("_arena_row", None)
        enc_mod.reset_marshal_arena()
        enc_mod.clear_catalog_encoding_cache()

    def enc_key(e):
        return (e.shapes.tobytes(), e.counts.tobytes(), e.totals.tobytes(),
                e.reserved0.tobytes(), e.valid.tobytes(), e.last_valid,
                e.num_shapes, e.num_types, e.shape_pods, e.scales,
                e.pods_unit)

    marshal_encode(streams[0])  # warm the arena + caches (untimed)
    cold_times, delta_times, parity = [], [], True
    for win in streams[1:]:
        t0 = time.perf_counter()
        e_delta = marshal_encode(win)       # arena warm from prior window
        delta_times.append(time.perf_counter() - t0)
        frac = MARSHAL_DELTA_FRACTION.collect().get((), None)
        clear_all(win)
        t0 = time.perf_counter()
        e_cold = marshal_encode(win)        # repopulates for next delta
        cold_times.append(time.perf_counter() - t0)
        parity = parity and enc_key(e_delta) == enc_key(e_cold)

    # end-to-end solve parity on the final window, delta vs cold
    def bound_key(win, result):
        pos = {id(p): i for i, p in enumerate(win)}
        return (result.node_count, sorted(
            (tuple(it.name for it in p.instance_type_options),
             p.node_quantity,
             sorted(tuple(sorted(pos[id(pod)] for pod in node))
                    for node in p.pods))
            for p in result.packings))

    final = streams[-1]
    k_delta = bound_key(final, solve(constraints, final, catalog))
    clear_all(final)
    k_cold = bound_key(final, solve(constraints, final, catalog))

    # steady-state device leg: an identical repeat solve through the solo
    # donate ring must allocate nothing fresh — catalog buffers answer by
    # token (reuses), only the donated counts buffer refills
    small = final[:400]
    dcfg = SolverConfig(device_min_pods=1, device_donate=True)
    solve(constraints, small, catalog, config=dcfg)  # populate the ring
    c0 = get_ring().counters()
    solve(constraints, small, catalog, config=dcfg)
    c1 = get_ring().counters()
    steady = {k: c1[k] - c0.get(k, 0) for k in c1}

    st_cold = _stats(cold_times)
    st_delta = _stats(delta_times)
    speedup = round(st_cold["p50_ms"] / (st_delta["p50_ms"] or 1e-9), 2)
    return {
        "pods": n, "windows": windows, "churn": churn,
        "cold_p50_ms": st_cold["p50_ms"], "cold_p99_ms": st_cold["p99_ms"],
        "delta_p50_ms": st_delta["p50_ms"], "delta_p99_ms": st_delta["p99_ms"],
        "speedup": speedup,
        "delta_fraction": frac,
        "encode_parity": bool(parity),
        "solve_parity": bool(k_delta == k_cold),
        "node_count": k_delta[0],
        "steady_ring": steady,
        "fresh_catalog_transfers": steady.get("allocations", -1),
        "arena": enc_mod.marshal_arena().stats(),
    }


def config_11_gang_copack():
    """Round-11 gate: batched gang co-pack (docs/solver.md §15). A
    256-gang window of all-or-nothing pod groups (2-4 heavyweight
    members each) is solved two ways over the SAME encoding:

    - leg A, the per-gang sequential host loop: ops/gang.host_gang runs
      one exact first-fit per gang over its private pool copy — G python
      solves back to back (what a host-only implementation pays);
    - leg B, one batched device solve: solver/gang.solve_gang_window
      vmaps all G sub-solves into a single kernel dispatch through the
      DeviceRing.

    Both verdicts then feed plan_gang_window, whose host re-verification
    commits every accepted gang on exact nano ints — the plans must be
    node-for-node identical (exact node parity) and every placement is
    host-verified (zero unverified placements). `make bench-gang` gates
    speedup >= 5x via tools/gang_verdict.py."""
    import numpy as _np

    from karpenter_tpu.controllers.provisioning import universe_constraints
    from karpenter_tpu.ops import feasibility
    from karpenter_tpu.ops.gang import encode_gang_window, host_gang
    from karpenter_tpu.solver import adapter
    from karpenter_tpu.solver.gang import (
        GangConfig, plan_gang_window, solve_gang_window,
    )

    G = 256
    catalog = make_catalog(100)
    constraints = universe_constraints(catalog)
    # the realistic TPU gang shape: small groups of heavyweight slice
    # workers (2-4 members, 2-6 CPU each) — the member axis stays narrow
    # while the prospective-node pool is wide
    sizes = [2, 3, 4]
    shapes = [(2000, 2048), (4000, 4096), (6000, 6144)]
    gangs = []
    all_pods = []
    for gi in range(G):
        k = sizes[gi % len(sizes)]
        members = make_pods(k, [shapes[(gi + j) % len(shapes)]
                                for j in range(k)])
        for j, p in enumerate(members):
            p.metadata.name = f"gang-{gi}-m{j}"
        all_pods.extend(members)
        gangs.append((f"gang-{gi}", members))

    packables, sorted_types = adapter.build_packables_cached(
        catalog, constraints, all_pods, ())
    type_frees = [[t - r for t, r in zip(pk.total, pk.reserved)]
                  for pk in packables]
    type_prices = [it.price for it in sorted_types]
    type_names = [it.name for it in sorted_types]
    allowed = adapter._allowed_sets(constraints)
    required = adapter._required_resources(all_pods)
    mask = feasibility.gang_feasibility_mask(
        sorted_types, [(allowed, required)])
    enc = encode_gang_window(
        [(key, pods, mask, None) for key, pods in gangs],
        type_frees, type_prices, type_names)
    assert enc.g == G, f"encode dropped gangs: {enc.g}/{G} ({enc.skipped})"
    assert enc.device_ready and enc.cells >= GangConfig().device_min_cells, \
        f"window too small for the device leg: {enc.cells} cells"

    cfg = GangConfig()
    # leg parity first: identical verdicts, then identical plans
    feas_a, slots_a = host_gang(enc)
    feas_b, slots_b, executor = solve_gang_window(enc, cfg)  # warm-up + jit
    assert executor == "device-gang", f"device leg fell back: {executor}"
    feas_parity = bool(_np.array_equal(feas_a, feas_b))
    slots_parity = bool(_np.array_equal(slots_a, slots_b))

    def plan_sig(plan):
        return [(pl.gang.index,
                 tuple((bi, tuple(pl.gang.pods.index(p) for p in ps))
                       for bi, ps in pl.node_sets))
                for pl in plan.placements]

    plan_a = plan_gang_window(enc, feas_a)
    plan_b = plan_gang_window(enc, feas_b)
    node_parity = plan_sig(plan_a) == plan_sig(plan_b)
    # the device verdict is a FILTER: every placement re-verified on host
    unverified = len(plan_b.placements) - min(plan_b.verified,
                                              len(plan_b.placements))

    host_times = run_timed(lambda: host_gang(enc), budget_s=45.0)
    device_times = run_timed(lambda: solve_gang_window(enc, cfg),
                             budget_s=20.0)
    st_host = _stats(host_times)
    st_device = _stats(device_times)
    speedup = round(st_host["p50_ms"] / (st_device["p50_ms"] or 1e-9), 2)
    return {
        "gangs": enc.g, "members": len(all_pods), "bins": enc.b,
        "padded_cells": enc.cells,
        "host_p50_ms": st_host["p50_ms"], "host_p99_ms": st_host["p99_ms"],
        "device_p50_ms": st_device["p50_ms"],
        "device_p99_ms": st_device["p99_ms"],
        "speedup": speedup,
        "executor": executor,
        "feasible_gangs": int(feas_b.sum()),
        "placed_gangs": len(plan_b.placements),
        "verdict_parity": bool(feas_parity and slots_parity),
        "node_parity": bool(node_parity),
        "unverified_placements": int(unverified),
    }


def config_12_device_filter():
    """Round-12 gate: device-resident fused feasibility (docs/solver.md
    §16). The filter stage of a 24-schedule window over a 400-type catalog
    is timed two ways, cycling 192 distinct constraint variants (more than
    the host mask cache holds — every host iteration pays the columnar
    build, the way a live control plane rotating tenants does):

    - leg A, host columnar: one catalog_feasibility_mask + packables build
      per schedule (what the pre-§16 solve path pays per window);
    - leg B, device fused: ONE bit-plane program for the whole window
      (ops/device_filter.compute_mask) + the shared universe packables
      (cached; built once per catalog) — the planes never re-cross PCIe
      (token-aware ring slots), only the tiny row stack does.

    Verdict parity is asserted per variant (device mask vs the host
    columnar mask, bit for bit), and a full 10k-pod solve_batch runs
    filter-on vs filter-off for node parity. Ring counters prove the
    steady-state residency claim: plane reuses move during the timed loop,
    fresh device allocations do not. `make bench-filter` gates >= 2x via
    tools/filter_verdict.py."""
    import numpy as _np

    from karpenter_tpu.controllers.provisioning import universe_constraints
    from karpenter_tpu.metrics.filter import (
        FILTER_DEVICE_FALLBACK_TOTAL, FILTER_PLANE_RING_REUSES_TOTAL,
    )
    from karpenter_tpu.ops import device_filter, feasibility
    from karpenter_tpu.solver import adapter
    from karpenter_tpu.solver.batch_solve import Problem, solve_batch
    from karpenter_tpu.solver.pipeline import get_ring
    from karpenter_tpu.solver.solve import SolverConfig
    from karpenter_tpu.utils import resources as res

    if not device_filter.enabled():
        return {"skipped": "KARPENTER_DEVICE_FILTER=0"}

    T, S, VARIANTS = 400, 24, 192
    catalog = make_catalog(T)
    constraints = universe_constraints(catalog)
    base = adapter._allowed_sets(constraints)
    cts = sorted(base[0])
    zones = sorted(base[1])
    names = sorted(base[2]) if base[2] else sorted(it.name for it in catalog)

    # 192 distinct (allowed, required) keys (v mod lcm(4,3,50)=300 is
    # injective below 192): rotate capacity type, drop one zone, drop a
    # rotating prefix of type names, sprinkle an ENI requirement
    pairs_ring = []
    for v in range(VARIANTS):
        allowed = (
            frozenset(cts if v % 4 else cts[:1]),
            frozenset(z for j, z in enumerate(zones) if j != v % len(zones)),
            frozenset(names[(v * 7) % 50:]),
            base[3], base[4],
        )
        required = (frozenset([res.AWS_POD_ENI]) if v % 16 == 15
                    else frozenset())
        pairs_ring.append((allowed, required))
    n_windows = VARIANTS // S
    windows = [pairs_ring[w * S:(w + 1) * S] for w in range(n_windows)]

    # verdict parity, every variant: the device bit-plane mask must equal
    # the host columnar mask bit for bit (this also warms planes/rows/jit)
    divergence = 0
    for w in windows:
        mask_d = device_filter.compute_mask(catalog, w)
        assert mask_d is not None, "catalog not device-indexable"
        for s, (allowed, required) in enumerate(w):
            mask_h = feasibility.catalog_feasibility_mask(
                catalog, allowed, required)
            divergence += int(_np.sum(mask_d[s] != mask_h))

    # full-solve node parity: 10k pods over S zone-rotated schedules,
    # fused filter on vs kill switch off
    from karpenter_tpu.api.core import NodeSelectorRequirement as _Req
    from karpenter_tpu.api import wellknown as _wk

    per = 10_000 // S
    problems = []
    for b in range(S):
        tightened = constraints.deepcopy()
        tightened.requirements = tightened.requirements.add(_Req(
            key=_wk.LABEL_TOPOLOGY_ZONE, operator="In",
            values=[f"bench-zone-{1 + b % 3}"]))
        pods = make_pods(per, MIXED_SHAPES[b % len(MIXED_SHAPES):]
                         + MIXED_SHAPES[:b % len(MIXED_SHAPES)])
        for j, p in enumerate(pods):
            p.metadata.name = f"f{b}-{j}"
        problems.append(Problem(constraints=tightened, pods=pods,
                                instance_types=catalog))
    cfg = SolverConfig(device_min_pods=1)
    fb_before = dict(FILTER_DEVICE_FALLBACK_TOTAL.collect())
    prev = os.environ.get("KARPENTER_DEVICE_FILTER")
    try:
        os.environ["KARPENTER_DEVICE_FILTER"] = "1"
        on = solve_batch(problems, cfg)
        os.environ["KARPENTER_DEVICE_FILTER"] = "0"
        off = solve_batch(problems, cfg)
    finally:
        if prev is None:
            os.environ.pop("KARPENTER_DEVICE_FILTER", None)
        else:
            os.environ["KARPENTER_DEVICE_FILTER"] = prev

    def nodes(rs):
        return [sum(p.node_quantity for p in r.packings) for r in rs]

    nodes_on, nodes_off = nodes(on), nodes(off)
    node_parity = nodes_on == nodes_off
    fb_after = dict(FILTER_DEVICE_FALLBACK_TOTAL.collect())
    fallbacks = {dict(k).get("reason", "?"): fb_after[k] - fb_before.get(k, 0)
                 for k in fb_after
                 if fb_after[k] - fb_before.get(k, 0.0) > 0}

    # the timed filter-stage A/B, cycling windows so the host caches
    # (mask cap 128 < 192 variants) keep missing while the device side
    # hits its planes/rows interning
    state_h, state_d = {"i": 0}, {"i": 0}

    def host_leg():
        w = windows[state_h["i"] % n_windows]
        state_h["i"] += 1
        for allowed, required in w:
            adapter._build_packables_from(catalog, allowed, (), required)

    def device_leg():
        w = windows[state_d["i"] % n_windows]
        state_d["i"] += 1
        assert device_filter.compute_mask(catalog, w) is not None
        adapter.build_universe_packables(catalog)

    host_leg()
    device_leg()  # warmup both once more post-solve
    ring = get_ring()
    reuses0 = FILTER_PLANE_RING_REUSES_TOTAL.collect().get((), 0.0)
    allocs0 = ring.allocations
    host_times = run_timed(host_leg, budget_s=30.0)
    device_times = run_timed(device_leg, budget_s=15.0)
    st_host = _stats(host_times)
    st_device = _stats(device_times)
    speedup = round(st_host["p50_ms"] / (st_device["p50_ms"] or 1e-9), 2)
    return {
        "pods": per * S, "types": T, "schedules_per_window": S,
        "variants": VARIANTS,
        "host_p50_ms": st_host["p50_ms"], "host_p99_ms": st_host["p99_ms"],
        "device_p50_ms": st_device["p50_ms"],
        "device_p99_ms": st_device["p99_ms"],
        "speedup": speedup,
        "verdict_divergence": int(divergence),
        "node_parity": bool(node_parity),
        "nodes": int(sum(nodes_on)),
        "plane_ring_reuses": FILTER_PLANE_RING_REUSES_TOTAL.collect().get(
            (), 0.0) - reuses0,
        "steady_allocations": ring.allocations - allocs0,
        "device_fallbacks": fallbacks,
    }


def config_13_policy_scoring():
    """Round-13 gate: device-vectorized packing-policy scoring
    (docs/solver.md §17). A 24-schedule fused window over a 400-type
    priced catalog — every spot offering carrying its own interruption
    rate — is scored two ways under the interruption-priced policy:

    - leg A, host per-cell: one policy.score() per (schedule, packable),
      a Python loop over offerings inside every call — the pre-§17 prices
      seam (batch_solve._problem_prices), and still the fallback leg;
    - leg B, device: ops/policy.score_fused_window — ONE jit scores every
      (schedule × type × capacity-type) cell of the window; the probe
      re-verification against the numpy mirror is timed INSIDE the leg,
      so the speedup is net of the filter contract's cost.

    Three correctness gates ride along: default-policy row parity
    (the device row must equal encode_prices of the host scores bit for
    bit on every member — the differential guarantee the default policy
    rides on), full-solve node parity (10k pods, device scoring on vs
    KARPENTER_POLICY_DEVICE=0, identical node counts AND launch picks),
    and a repack-cost frontier sweep asserting spot is selected exactly
    when ``rate x repack < price x (1 - spot_factor)`` — the
    interruption-priced policy's documented break-even. `make
    bench-policy` gates >= 5x at zero unverified placements via
    tools/policy_verdict.py."""
    import numpy as _np

    from karpenter_tpu.api import wellknown as _wk
    from karpenter_tpu.api.core import NodeSelectorRequirement as _Req
    from karpenter_tpu.cloudprovider.fake.provider import make_instance_type
    from karpenter_tpu.cloudprovider.spi import Offering
    from karpenter_tpu.controllers.provisioning import universe_constraints
    from karpenter_tpu.metrics.policy import (
        POLICY_FALLBACK_TOTAL, POLICY_CELLS_SCORED_TOTAL,
        POLICY_SPOT_SELECTED_TOTAL,
    )
    from karpenter_tpu.models.ffd import encode_prices
    from karpenter_tpu.ops import device_filter
    from karpenter_tpu.ops import policy as ops_policy
    from karpenter_tpu.solver import policy as policy_registry
    from karpenter_tpu.solver.adapter import marshal_pods_interned
    from karpenter_tpu.solver.batch_solve import Problem, solve_batch
    from karpenter_tpu.solver.policy import PolicyContext
    from karpenter_tpu.solver.solve import (
        SolverConfig, resolved_device_max_shapes,
    )

    if not ops_policy.enabled():
        return {"skipped": "KARPENTER_POLICY_DEVICE=0"}
    if not device_filter.enabled():
        return {"skipped": "KARPENTER_DEVICE_FILTER=0 (no fused window)"}

    T, S = 400, 24
    # per-type, per-zone spot volatility: 0.01..0.106 reclaims/h, varied
    # so the kernel's min-over-allowed-zones actually has work to do
    catalog = make_catalog(
        T, spot_rate=lambda i, z: round(0.01 + 0.004 * ((i * 7 + z) % 25), 6))
    constraints = universe_constraints(catalog)

    per = 10_000 // S
    problems = []
    for b in range(S):
        tightened = constraints.deepcopy()
        tightened.requirements = tightened.requirements.add(_Req(
            key=_wk.LABEL_TOPOLOGY_ZONE, operator="In",
            values=[f"bench-zone-{1 + b % 3}"]))
        pods = make_pods(per, MIXED_SHAPES[b % len(MIXED_SHAPES):]
                         + MIXED_SHAPES[:b % len(MIXED_SHAPES)])
        for j, p in enumerate(pods):
            p.metadata.name = f"p{b}-{j}"
        problems.append(Problem(constraints=tightened, pods=pods,
                                instance_types=catalog))

    ctx = PolicyContext(repack_cost_per_hour=2.0)
    cfg = SolverConfig(device_min_pods=1,
                       packing_policy="interruption-priced",
                       policy_context=ctx)
    priced = policy_registry.get("interruption-priced")
    cheapest = policy_registry.get("cheapest")

    # full-solve node parity: device scoring on vs the kill switch, same
    # policy — identical node counts AND identical launch picks (the
    # device verdict is a filter, never a commit)
    fb_before = dict(POLICY_FALLBACK_TOTAL.collect())
    cells0 = POLICY_CELLS_SCORED_TOTAL.collect().get((), 0.0)
    prev = os.environ.get("KARPENTER_POLICY_DEVICE")
    try:
        os.environ["KARPENTER_POLICY_DEVICE"] = "1"
        on = solve_batch(problems, cfg)
        os.environ["KARPENTER_POLICY_DEVICE"] = "0"
        off = solve_batch(problems, cfg)
    finally:
        if prev is None:
            os.environ.pop("KARPENTER_POLICY_DEVICE", None)
        else:
            os.environ["KARPENTER_POLICY_DEVICE"] = prev

    def nodes(rs):
        return [sum(p.node_quantity for p in r.packings) for r in rs]

    def picks(rs):
        return [[p.instance_type_options[0].name if p.instance_type_options
                 else None for p in r.packings] for r in rs]

    nodes_on, nodes_off = nodes(on), nodes(off)
    node_parity = nodes_on == nodes_off
    pick_parity = picks(on) == picks(off)

    # the timed scoring-stage A/B over one real fused window
    marshaled = [marshal_pods_interned(p.pods) for p in problems]
    fused = device_filter.prepare_fused(problems, marshaled, cfg,
                                        resolved_device_max_shapes(cfg))
    if fused is None:
        return {"error": "window not fused — scoring A/B needs the "
                         "bit-plane window (config_12's stage)"}
    try:
        planes = device_filter.planes_for(fused.uni_types)
        TB = planes.TB

        def host_leg():
            rows = []
            for i in fused.batch_idx:
                reqs = problems[i].constraints.requirements
                rows.append(encode_prices(
                    [priced.score(fused.uni_types[p.index], reqs,
                                  cfg.cost_config, ctx)[0]
                     for p in fused.packables], TB))
            return rows

        def device_leg():
            rows = ops_policy.score_fused_window(
                fused, priced, cfg.cost_config, ctx)
            assert rows is not None, "device scoring fell back mid-bench"
            return rows

        # default-policy differential: penalty-free algebra must make the
        # device row bit-identical to encode_prices of the host scores
        rows_cheap_d = ops_policy.score_fused_window(
            fused, cheapest, cfg.cost_config, PolicyContext())
        row_divergence = -1
        if rows_cheap_d is not None:
            row_divergence = 0
            for b, i in enumerate(fused.batch_idx):
                reqs = problems[i].constraints.requirements
                row_h = encode_prices(
                    [cheapest.score(fused.uni_types[p.index], reqs,
                                    cfg.cost_config, PolicyContext())[0]
                     for p in fused.packables], TB)
                row_divergence += int(_np.sum(rows_cheap_d[b] != row_h))

        host_leg()
        device_leg()  # warm tables + jit before the clock starts
        host_times = run_timed(host_leg, budget_s=30.0)
        device_times = run_timed(device_leg, budget_s=15.0)
    finally:
        fused.release()
    st_host = _stats(host_times)
    st_device = _stats(device_times)
    speedup = round(st_host["p50_ms"] / (st_device["p50_ms"] or 1e-9), 2)

    # frontier sweep: one type at price P with a single spot offering at
    # rate r — spot must win exactly while rate x repack < P x (1 - f)
    f = cfg.cost_config.spot_price_factor
    P, r = 1.0, 0.5
    threshold = P * (1.0 - f) / r
    mini = [make_instance_type(
        name="frontier-4x", cpu="4", memory="8Gi", pods="16", price=P,
        offerings=[Offering("on-demand", "bench-zone-1"),
                   Offering("spot", "bench-zone-1", interruption_rate=r)])]
    mini_cons = universe_constraints(mini)
    frontier = []
    for mult in (0.0, 0.25, 0.5, 0.9, 1.1, 2.0, 4.0):
        v = round(threshold * mult, 6)
        pcfg = SolverConfig(
            device_min_pods=1, packing_policy="interruption-priced",
            policy_context=PolicyContext(repack_cost_per_hour=v))
        probs = []
        for k in range(2):
            pods = make_pods(40, [(500, 512)])
            for j, p in enumerate(pods):
                p.metadata.name = f"fr{mult}-{k}-{j}"
            probs.append(Problem(constraints=mini_cons.deepcopy(),
                                 pods=pods, instance_types=mini))
        before = sum(POLICY_SPOT_SELECTED_TOTAL.collect().values())
        rs = solve_batch(probs, pcfg)
        placed = sum(sum(p.node_quantity for p in res.packings)
                     for res in rs)
        chose_spot = sum(POLICY_SPOT_SELECTED_TOTAL.collect().values()) \
            - before > 0
        frontier.append({
            "repack_cost_per_hour": v, "nodes": int(placed),
            "spot_expected": bool(r * v < P * (1.0 - f)),
            "spot_selected": bool(chose_spot),
        })
    frontier_ok = all(pt["nodes"] > 0
                      and pt["spot_expected"] == pt["spot_selected"]
                      for pt in frontier)

    fb_after = dict(POLICY_FALLBACK_TOTAL.collect())
    fallbacks = {dict(k).get("reason", "?"): fb_after[k] - fb_before.get(k, 0)
                 for k in fb_after
                 if fb_after[k] - fb_before.get(k, 0.0) > 0}
    return {
        "pods": per * S, "types": T, "schedules_per_window": S,
        "policy": "interruption-priced",
        "host_p50_ms": st_host["p50_ms"], "host_p99_ms": st_host["p99_ms"],
        "device_p50_ms": st_device["p50_ms"],
        "device_p99_ms": st_device["p99_ms"],
        "speedup": speedup,
        "row_divergence_default": row_divergence,
        "node_parity": bool(node_parity),
        "pick_parity": bool(pick_parity),
        "nodes": int(sum(nodes_on)),
        "unverified": int(fallbacks.get("score-mismatch", 0)),
        "cells_scored": POLICY_CELLS_SCORED_TOTAL.collect().get(
            (), 0.0) - cells0,
        "spot_frontier": frontier,
        "frontier_ok": bool(frontier_ok),
        "frontier_threshold": round(threshold, 6),
        "policy_fallbacks": fallbacks,
    }


def config_14_global_window():
    """Round-14 gate: the whole-window global solve (docs/solver.md §18).
    A heterogeneous 12-schedule window over a catalog whose price-per-cpu
    spreads 4x — so node-count-minimal (FFD's objective) and cost-minimal
    fleets genuinely diverge — is solved two ways:

    - leg A, per-schedule exact FFD: one host_ffd.pack per schedule over
      the full catalog — the packing every schedule falls back to;
    - leg B, the global backend: ONE joint batched proximal solve over
      all schedules (solver/global_solve.solve_window_global), support ->
      restricted exact-FFD rounding -> strict int micro-$ verdict.

    The fleet-cost delta is computed per the controller's substitution
    rule: an accepted schedule contributes its rounded plan, a declined
    one its untouched FFD plan. Gates (tools/global_verdict.py): fleet
    >= 5% cheaper (or fewer nodes) at bounded window p99 — the global
    window rides the dispatch stage CONCURRENT with the per-schedule
    batch, so the solve p99 is unchanged as long as the global leg fits
    the 200 ms window budget; exact-FFD parity on every decline (a
    single-type window where restricted rounding can never win must
    return all-None results); zero unverified placements; and a live
    kill switch."""
    from karpenter_tpu.cloudprovider.fake.provider import make_instance_type
    from karpenter_tpu.cloudprovider.spi import Offering
    from karpenter_tpu.controllers.provisioning import universe_constraints
    from karpenter_tpu.metrics.global_solve import GLOBAL_FALLBACK_TOTAL
    from karpenter_tpu.ops.global_solve import encode_window, plan_cost_micro
    from karpenter_tpu.solver import global_solve as gs
    from karpenter_tpu.solver import host_ffd
    from karpenter_tpu.solver.batch_solve import Problem
    from karpenter_tpu.solver.solve import SolverConfig

    if not gs.enabled():
        return {"skipped": "KARPENTER_GLOBAL_SOLVE=0"}

    def t(name, cpu, ratio, price):
        return make_instance_type(
            name=name, cpu=str(cpu), memory=f"{cpu * ratio}Gi",
            pods=str(min(110, cpu * 15)),
            offerings=[Offering("on-demand", f"bench-zone-{z + 1}")
                       for z in range(3)],
            price=price)

    # $/cpu: 0.05 on the small end, 0.20-0.22 on the big end — FFD's
    # max-pods-per-node choice lands on the big types, the cheap fleet
    # doesn't
    catalog = [
        t("gw-small-8", 8, 4, 0.40), t("gw-small-12", 12, 4, 0.66),
        t("gw-mid-16", 16, 4, 1.92), t("gw-mid-24", 24, 4, 3.36),
        t("gw-big-32", 32, 4, 6.40), t("gw-big-48", 48, 4, 10.56),
    ]
    constraints = universe_constraints(catalog)
    S = 12
    shapes = [(1000, 2048), (2000, 4096), (500, 1024), (4000, 8192)]
    problems = []
    for b in range(S):
        n = 10 + (b * 7) % 26
        pods = make_pods(n, [shapes[b % len(shapes)]])
        for j, p in enumerate(pods):
            p.metadata.name = f"gw{b}-{j}"
        problems.append(Problem(constraints=constraints.deepcopy(),
                                pods=pods, instance_types=catalog))

    cfg = SolverConfig(window_backend="global")
    gcfg = gs.GlobalConfig(device_min_cells=0)  # exercise the device path
    win = encode_window(problems, cfg.cost_config)

    def ffd_leg():
        out = []
        for s in win.scheds:
            out.append(host_ffd.pack(
                s.pod_vecs, s.pod_ids, s.packables,
                max_instance_types=cfg.max_instance_types)
                if s.reason is None else None)
        return out

    def global_leg():
        return gs.solve_window_global(problems, cfg, gcfg)

    fb_before = dict(GLOBAL_FALLBACK_TOTAL.collect())
    ffd_results = ffd_leg()
    plan = global_leg()  # warm: jit + ring fill before the clock starts
    ffd_times = run_timed(ffd_leg, budget_s=15.0)
    global_times = run_timed(global_leg, budget_s=30.0)
    st_ffd = _stats(ffd_times)
    st_global = _stats(global_times)

    ffd_micro = [plan_cost_micro(r, s.prices_micro) if r is not None else 0
                 for s, r in zip(win.scheds, ffd_results)]
    ffd_nodes = sum(r.node_count for r in ffd_results if r is not None)
    global_micro, global_nodes = 0, 0
    for i, (info, result) in enumerate(zip(plan.infos, plan.results)):
        if result is not None:  # controller substitution rule
            global_micro += info.relax_cost_micro
            global_nodes += result.node_count
        else:
            global_micro += ffd_micro[i]
            global_nodes += (ffd_results[i].node_count
                             if ffd_results[i] is not None else 0)
    ffd_total = sum(ffd_micro)
    saving_pct = round(100.0 * (ffd_total - global_micro)
                       / (ffd_total or 1), 2)

    # decline-parity leg: one type only — restricted rounding can never
    # beat full FFD, every schedule must decline and keep its FFD plan
    solo = [t("gw-solo-16", 16, 4, 1.0)]
    solo_cons = universe_constraints(solo)
    solo_problems = []
    for b in range(4):
        pods = make_pods(12, [shapes[b % len(shapes)]])
        for j, p in enumerate(pods):
            p.metadata.name = f"gwsolo{b}-{j}"
        solo_problems.append(Problem(constraints=solo_cons.deepcopy(),
                                     pods=pods, instance_types=solo))
    solo_plan = gs.solve_window_global(solo_problems, cfg, gcfg)
    decline_parity = (solo_plan.accepted == 0
                      and all(r is None for r in solo_plan.results)
                      and all(i.reason.startswith("fallback-")
                              for i in solo_plan.infos))

    prev = os.environ.get("KARPENTER_GLOBAL_SOLVE")
    try:
        os.environ["KARPENTER_GLOBAL_SOLVE"] = "0"
        killswitch_gate = not gs.enabled()
    finally:
        if prev is None:
            os.environ.pop("KARPENTER_GLOBAL_SOLVE", None)
        else:
            os.environ["KARPENTER_GLOBAL_SOLVE"] = prev

    fb_after = dict(GLOBAL_FALLBACK_TOTAL.collect())
    fallbacks = {dict(k).get("reason", "?"): fb_after[k] - fb_before.get(k, 0)
                 for k in fb_after
                 if fb_after[k] - fb_before.get(k, 0.0) > 0}
    p99_budget_ms = max(TARGET_MS, 5.0 * st_ffd["p99_ms"])
    return {
        "schedules": S, "pods": sum(len(p.pods) for p in problems),
        "types": len(catalog), "executor": plan.executor,
        "accepted": plan.accepted,
        "ffd_cost_per_hour": round(ffd_total / 1e6, 6),
        "global_cost_per_hour": round(global_micro / 1e6, 6),
        "saving_pct": saving_pct,
        "ffd_nodes": int(ffd_nodes), "global_nodes": int(global_nodes),
        "ffd_p50_ms": st_ffd["p50_ms"], "ffd_p99_ms": st_ffd["p99_ms"],
        "global_p50_ms": st_global["p50_ms"],
        "global_p99_ms": st_global["p99_ms"],
        "p99_budget_ms": round(p99_budget_ms, 3),
        "p99_ok": bool(st_global["p99_ms"] <= p99_budget_ms),
        "decline_parity": bool(decline_parity),
        "killswitch_gate": bool(killswitch_gate),
        "unverified": int(fallbacks.get("unverified", 0)),
        "global_fallbacks": fallbacks,
    }


def config_15_crash_recovery():
    """Crash-consistency gate (docs/robustness.md §5). Three legs:

    - journal tax: a journaled (fsync ON) replay leg — the bench-replay
      shape scaled down — with the tax read from the journal's own
      append histogram delta against the leg's wall (acceptance: <= 1%,
      the crash_recovery_clean ratchet in tools/bench_regress.py).
      A bare vs journaled ProvisionerWorker micro A/B (after an untimed
      prewarm) prices the raw per-append fsync alongside; at micro
      scale the fsync dominates the toy bind loop, so the micro numbers
      are reported for attribution, not gated.
    - recovery wall: a journal seeded with open fleet-launch intents
      over genuinely leaked fake-provider capacity, replayed by
      RecoveryController from a cold open, repeated for p50/p99 —
      the window the readyz gate holds 503 ``recovering``.
    - leak gate: after every replay the provider ledger must be empty
      and the journal must hold zero open intents (``leaks`` /
      ``open_intents_after`` feed the bench-regress ratchet)."""
    import shutil
    import tempfile
    import time as _time

    from karpenter_tpu.api import wellknown
    from karpenter_tpu.api.constraints import Constraints
    from karpenter_tpu.api.core import NodeSelectorRequirement as Req
    from karpenter_tpu.api.requirements import Requirements
    from karpenter_tpu.cloudprovider.fake.provider import (
        FakeCloudProvider, instance_types,
    )
    from karpenter_tpu.controllers.provisioning import (
        ProvisionerWorker, global_requirements,
    )
    from karpenter_tpu.controllers.recovery import RecoveryController
    from karpenter_tpu.metrics.recovery import JOURNAL_APPEND_SECONDS
    from karpenter_tpu.metrics.registry import HISTOGRAMS
    from karpenter_tpu.runtime import journal as jr
    from karpenter_tpu.runtime.journal import IntentJournal
    from karpenter_tpu.runtime.kubecore import KubeCore
    from karpenter_tpu.scheduling.batcher import Batcher
    from tests.expectations import make_provisioner, unschedulable_pod

    def _hsum(hist):
        collected = hist.collect()
        return (sum(s for _, s, _ in collected.values()),
                sum(t for _, _, t in collected.values()))

    def _constraints():
        return Constraints(
            labels={wellknown.PROVISIONER_NAME_LABEL: "crash-bench"},
            requirements=Requirements([
                Req(key=wellknown.LABEL_TOPOLOGY_ZONE, operator="In",
                    values=["test-zone-1"]),
                Req(key=wellknown.LABEL_CAPACITY_TYPE, operator="In",
                    values=["on-demand"]),
            ]))

    def _bind_leg(n_pods, journal):
        kube = KubeCore()
        provider = FakeCloudProvider(catalog=instance_types(4))
        cons = _constraints()
        prov = make_provisioner(name="crash-bench", constraints=cons)
        prov.spec.constraints.requirements = (
            prov.spec.constraints.requirements.add(
                *global_requirements(provider.get_instance_types(cons)).items))
        kube.create(prov)
        worker = ProvisionerWorker(
            prov, kube, provider,
            batcher=Batcher(idle_seconds=0.01, max_seconds=0.1),
            journal=journal)
        pods = []
        for i in range(n_pods):
            p = unschedulable_pod(requests={"cpu": "500m", "memory": "256Mi"},
                                  name=f"crash-bench-pod-{i}")
            kube.create(p)
            pods.append(p)
        bind0 = _hsum(HISTOGRAMS.histogram("bind_duration_seconds"))
        tax0 = _hsum(JOURNAL_APPEND_SECONDS)
        t0 = _time.perf_counter()
        for _ in range(25):
            unbound = [p for p in pods
                       if not kube.get("Pod", p.metadata.name).spec.node_name]
            if not unbound:
                break
            for p in unbound:
                worker.add(p, key=(p.metadata.namespace, p.metadata.name))
            worker.provision()
        wall = _time.perf_counter() - t0
        bind1 = _hsum(HISTOGRAMS.histogram("bind_duration_seconds"))
        tax1 = _hsum(JOURNAL_APPEND_SECONDS)
        bound = sum(1 for p in pods
                    if kube.get("Pod", p.metadata.name).spec.node_name)
        return {
            "wall_s": round(wall, 4),
            "bound": bound,
            "bind_s": round(bind1[0] - bind0[0], 6),
            "journal_tax_s": round(tax1[0] - tax0[0], 6),
            "journal_appends": tax1[1] - tax0[1],
        }

    n_pods = 400
    _bind_leg(64, journal=None)   # untimed prewarm: jit + import caches
    bare = _bind_leg(n_pods, journal=None)
    jdir = tempfile.mkdtemp(prefix="bench-journal-")
    try:
        with IntentJournal(jdir, fsync=True) as journal:
            journaled = _bind_leg(n_pods, journal=journal)
    finally:
        shutil.rmtree(jdir, ignore_errors=True)

    # the gated number: the journal's share of a replay-shaped run
    # (the bench-replay bind path scaled down; chaos off for stability)
    from karpenter_tpu.replay import ReplayConfig, run_replay

    jdir = tempfile.mkdtemp(prefix="bench-journal-replay-")
    try:
        tax0 = _hsum(JOURNAL_APPEND_SECONDS)
        replay = run_replay(ReplayConfig(
            pods_total=4_000, shards=1, tenants=1, seed=7,
            bound_cohort=400, churn_pods=0, max_depth=2_000, ticks=8,
            tick_sleep_s=0.6, burst_ticks=1, chaos=False, settle_s=60.0,
            flood_pool=96, journal_dir=jdir, journal_fsync=True))
        tax1 = _hsum(JOURNAL_APPEND_SECONDS)
    finally:
        shutil.rmtree(jdir, ignore_errors=True)
    replay_tax_s = tax1[0] - tax0[0]
    overhead_pct = (round(replay_tax_s / replay["wall_s"] * 100.0, 4)
                    if replay["wall_s"] else None)

    leaks_per_iter, noop_per_iter, iters = 48, 24, 16
    walls, leaks_after, opens_after, errors = [], 0, 0, 0
    rolled_back = 0
    for _ in range(iters):
        kube = KubeCore()
        provider = FakeCloudProvider(catalog=instance_types(4))
        cons = _constraints()
        itype = provider.catalog[-1]
        d = tempfile.mkdtemp(prefix="bench-recovery-")
        try:
            journal = IntentJournal(d, fsync=False)
            for _k in range(leaks_per_iter):
                nonce = jr.new_nonce()
                journal.open_intent("fleet-launch", nonce=nonce,
                                    provisioner="crash-bench")
                # bind dies before the Node write: the ledger entry is a
                # real leak attributable only through the journaled nonce
                with jr.preassigned_nonce(nonce):
                    provider.create(cons, [itype], 1,
                                    lambda node: "simulated crash")
            for _k in range(noop_per_iter):
                journal.open_intent("fleet-launch", nonce=jr.new_nonce(),
                                    provisioner="crash-bench")
            journal.close_journal()
            with IntentJournal(d, fsync=False) as journal:
                recovery = RecoveryController(kube, provider, journal)
                t0 = _time.perf_counter()
                stats = recovery.run()
                walls.append(_time.perf_counter() - t0)
                errors += stats["errors"]
                rolled_back += stats["rollback"]
                leaks_after += len(provider.list_instances())
                opens_after += len(journal.open_intents())
        finally:
            shutil.rmtree(d, ignore_errors=True)

    return {
        "bind_leg_pods": n_pods,
        "bare": bare,
        "journaled": journaled,
        "bound_equal": bare["bound"] == journaled["bound"] == n_pods,
        "journal_tax": {
            "overhead_pct": overhead_pct,
            "replay_tax_s": round(replay_tax_s, 6),
            "replay_appends": tax1[1] - tax0[1],
            "replay_wall_s": replay["wall_s"],
            "replay_bound": replay["bound"],
            "replay_completed": replay["completed"],
            "micro_appends": journaled["journal_appends"],
            "micro_tax_s": journaled["journal_tax_s"],
            "micro_bind_s": journaled["bind_s"],
            "us_per_append": (round(journaled["journal_tax_s"] * 1e6
                                    / journaled["journal_appends"], 2)
                              if journaled["journal_appends"] else None),
        },
        "recovery": {
            "iters": iters,
            "open_intents_per_iter": leaks_per_iter + noop_per_iter,
            "leaked_instances_per_iter": leaks_per_iter,
            "wall_ms": _stats(walls),
            "rolled_back": rolled_back,
            "errors": errors,
        },
        "leaks": leaks_after,
        "open_intents_after": opens_after,
    }


def config_16_topology_carve():
    """Round-16 gate: torus-grid slice carving + priced preemption
    (docs/solver.md §19). Five legs over 4x4-torus fleets:

    - fragmentation A/B (grow=False — no fresh capacity): the
      conservative shape-only baseline can only trust fully-EMPTY nodes
      (without cell geometry a fragmented torus is unusable — the
      pre-v18 planner handed slice gangs whole fresh nodes), while the
      carve-aware walk additionally harvests every node whose free
      chips form a contiguous sub-slice. Gate: >= 20% more gangs placed
      on the same saturated fleet. Scatter-fragmented nodes (free chips
      counted right, contiguity impossible) are the phantom-capacity
      trap — shape math places gangs there, the carve walk must reject
      every one (topology_carve_rejects_total);
    - commit audit: every committed carve is re-validated post hoc —
      exactly one placement-mask row, disjoint from the replayed
      occupancy plane. Gate: 0 unverified carves;
    - kernel throughput: the batched carve jit (gangs x bins x
      placements in ONE dispatch) vs the scalar host carve loop
      (ops/topology.scalar_carve — first_carve per cell) on a 64x64
      window, bit-identical verdicts required. Gate: >= 5x at p50;
    - priced preemption on a saturated pool: strictly-lower-band
      victims only, displacement accepted exactly while the summed
      what-if price stays under the beneficiary's fresh-node cost.
      Gate: >= 1 executed preemption (non-vacuous), 0 system-critical
      displacements, the overpriced victim declined fresh-cheaper;
    - kill switch: KARPENTER_TOPOLOGY_CARVE=0 reads disabled AND an
      annotation-free encode is bit-for-bit the shape-only encoding
      (no carve tensors, identical device tensors, identical plan)."""
    import numpy as np

    from karpenter_tpu.metrics.topology import (
        PREEMPTION_DECLINED_TOTAL, TOPOLOGY_CARVE_REJECTS_TOTAL,
    )
    from karpenter_tpu.ops import topology as topo
    from karpenter_tpu.ops.gang import GangBin, encode_gang_window
    from karpenter_tpu.ops.whatif import _reserve_vec
    from karpenter_tpu.solver import topology as topo_solver
    from karpenter_tpu.solver.gang import (
        PreemptCandidate, PreemptContext, plan_gang_window,
    )
    from karpenter_tpu.solver.topology import CarveConfig, solve_carve_window

    if not topo_solver.carve_enabled():
        return {"skipped": "KARPENTER_TOPOLOGY_CARVE=0"}

    GRID = (4, 4)
    CELLS = 16
    member_shape = (4000, 8192)  # one pod == one chip-equivalent
    mvec = [max(v, 1) for v in _reserve_vec(make_pods(1, [member_shape])[0])]

    def chips(n):  # free vector worth exactly n chips of members
        return [v * n for v in mvec]

    t_names, t_prices = ["tpu-carve-4x4"], [4.0]
    t_frees, t_grids = [chips(CELLS)], [GRID]

    def gangs_of(n, members, prefix, slice_dims, band="default"):
        out, slices, bands = [], [], []
        for i in range(n):
            pods = make_pods(members, [member_shape])
            for j, p in enumerate(pods):
                p.metadata.name = f"{prefix}{i}-m{j}"
            out.append(((f"bench-{prefix}", f"g{i}"), pods,
                        np.ones(1, bool), None))
            slices.append(slice_dims)
            bands.append(band)
        return out, slices, bands

    def seed(name, occ):
        occ = np.asarray(occ, bool)
        return GangBin(name=name, type_index=0,
                       free=chips(int(CELLS - occ.sum())),
                       grid=GRID, occ=occ, node_name=name)

    def plan_sig(plan):
        return sorted((pl.gang.key,
                       [(bi, [p.metadata.name for p in pods])
                        for bi, pods in pl.node_sets])
                      for pl in plan.placements)

    # --- leg 1: fragmentation A/B ------------------------------------
    # E empty nodes; C contiguous-fragmented (rows 0-1 busy, a clean 2x4
    # slab free); S scatter-fragmented (checkerboard: 8 free chips, no
    # contiguous 2x4 exists even with torus wrap)
    E, C, S = 4, 8, 8
    rows01 = np.zeros(CELLS, bool)
    rows01[:8] = True
    checker = np.array([(r + c) % 2 == 0 for r in range(4)
                        for c in range(4)])

    def fleet(kinds):
        out = []
        for i in range(E):
            if "empty" in kinds:
                out.append(seed(f"n-empty-{i}", np.zeros(CELLS, bool)))
        for i in range(C):
            if "contig" in kinds:
                out.append(seed(f"n-contig-{i}", rows01))
        for i in range(S):
            if "scatter" in kinds:
                out.append(seed(f"n-scatter-{i}", checker))
        return out

    G = 24
    gangs, slices, bands = gangs_of(G, 8, "frag", (2, 4))
    rej0 = sum(TOPOLOGY_CARVE_REJECTS_TOTAL.collect().values())
    enc_carve = encode_gang_window(
        gangs, t_frees, t_prices, t_names, slices=slices, bands=bands,
        type_grids=t_grids, seed_bins=fleet({"empty", "contig", "scatter"}),
        grow=False)
    plan_carve = plan_gang_window(enc_carve)
    carve_placed = len(plan_carve.placements)
    carve_rejects = sum(TOPOLOGY_CARVE_REJECTS_TOTAL.collect().values()) - rej0

    # commit audit: replay every committed carve cell-by-cell
    unverified = 0
    replay: dict = {}
    for pl in plan_carve.placements:
        for bi, cells in pl.carves.items():
            bn = enc_carve.bins[bi]
            base = replay.setdefault(bi, (bn.occ.copy() if bn.occ is not None
                                          else np.zeros(CELLS, bool)))
            want = np.zeros(CELLS, bool)
            want[list(cells)] = True
            masks = topo.placement_masks(bn.grid, pl.gang.slice_dims)
            row_ok = masks is not None and any(
                np.array_equal(row, want) for row in masks)
            if not row_ok or base[list(cells)].any():
                unverified += 1
            base[list(cells)] = True

    # shape-only conservative baseline: empty nodes only, no carve plumbing
    gangs_a, _, _ = gangs_of(G, 8, "frag", None)
    shape_bins = [GangBin(name=s.name, type_index=0, free=list(s.free),
                          node_name=s.name) for s in fleet({"empty"})]
    enc_shape = encode_gang_window(gangs_a, t_frees, t_prices, t_names,
                                   seed_bins=shape_bins, grow=False)
    shape_placed = len(plan_gang_window(enc_shape).placements)
    gain_pct = round(100.0 * (carve_placed - shape_placed)
                     / (shape_placed or 1), 2)

    # phantom illustration: naive shape-only over the WHOLE fleet happily
    # lands gangs on scatter bins — capacity that does not exist
    gangs_n, _, _ = gangs_of(G, 8, "frag", None)
    naive_bins = [GangBin(name=s.name, type_index=0, free=list(s.free),
                          node_name=s.name)
                  for s in fleet({"empty", "contig", "scatter"})]
    enc_naive = encode_gang_window(gangs_n, t_frees, t_prices, t_names,
                                   seed_bins=naive_bins, grow=False)
    phantom = sum(
        1 for pl in plan_gang_window(enc_naive).placements
        if any(enc_naive.bins[bi].name.startswith("n-scatter")
               for bi, _ in pl.node_sets))

    # --- leg 2: kernel vs scalar host carve loop ---------------------
    KG, KB = 64, 64
    kgangs, kslices, kbands = gangs_of(KG, 4, "kern", (2, 2))
    kseeds = []
    for j in range(KB):
        occ = np.zeros(CELLS, bool)
        occ[[(j * 7 + 3 * k) % CELLS for k in range(j % 10)]] = True
        kseeds.append(seed(f"n-kern-{j}", occ))
    enc_k = encode_gang_window(
        kgangs, t_frees, t_prices, t_names, slices=kslices, bands=kbands,
        type_grids=t_grids, seed_bins=kseeds, grow=False)
    kcfg = CarveConfig(device_min_cells=0)
    verdict_dev, kexec = solve_carve_window(enc_k, kcfg)  # warm: jit+ring
    verdict_scalar = topo.scalar_carve(enc_k)
    divergence = int((verdict_dev != verdict_scalar).sum())
    kernel_times = run_timed(lambda: solve_carve_window(enc_k, kcfg),
                             budget_s=10.0)
    scalar_times = run_timed(lambda: topo.scalar_carve(enc_k),
                             budget_s=15.0)
    st_k, st_s = _stats(kernel_times), _stats(scalar_times)
    speedup = round(st_s["p50_ms"] / max(st_k["p50_ms"], 1e-9), 2)

    # --- leg 3: priced preemption on a saturated pool ----------------
    # three saturated nodes, each half-held by a displaceable resident:
    # bin 0 low band at $0.25 (cheap — fires), bin 1 system-critical
    # (must never fire), bin 2 low band at $10 > the $4 fresh node
    # (declined fresh-cheaper, beneficiary falls through to growth)
    sat = [seed(f"p-sat-{i}", np.ones(CELLS, bool)) for i in range(3)]
    half = list(range(8))

    def victim(bi, band, cost):
        return PreemptCandidate(
            gang_key=("bench-victim", f"v{bi}"), bin_index=bi,
            node=f"p-sat-{bi}", band=band,
            pods=[("default", f"v{bi}-m{k}") for k in range(8)],
            cells=np.array(half), refund=chips(8),
            displacement_cost=cost)

    ctx = PreemptContext(candidates=[
        victim(0, "low", 0.25), victim(1, "system-critical", 0.0),
        victim(2, "low", 10.0)])
    pgangs, pslices, pbands = gangs_of(2, 8, "pre", (2, 4), band="high")
    enc_p = encode_gang_window(
        pgangs, t_frees, t_prices, t_names, slices=pslices, bands=pbands,
        type_grids=t_grids, seed_bins=sat, grow=True)
    dec0 = dict(PREEMPTION_DECLINED_TOTAL.collect())
    plan_p = plan_gang_window(enc_p, preempt=ctx)
    dec1 = PREEMPTION_DECLINED_TOTAL.collect()
    declines = {dict(k).get("reason", "?"): dec1[k] - dec0.get(k, 0.0)
                for k in dec1 if dec1[k] - dec0.get(k, 0.0) > 0}
    sc_preempts = sum(1 for _, c in plan_p.preemptions
                      if c.band == "system-critical")
    displaced = sum(len(c.pods) for _, c in plan_p.preemptions)
    fresh_fallback = sum(  # the priced-out gang must land on growth
        1 for pl in plan_p.placements
        if all(enc_p.bins[bi].node_name is None for bi, _ in pl.node_sets))

    # --- leg 4: kill switch ------------------------------------------
    prev = os.environ.get("KARPENTER_TOPOLOGY_CARVE")
    try:
        os.environ["KARPENTER_TOPOLOGY_CARVE"] = "0"
        killswitch_gate = not topo_solver.carve_enabled()
    finally:
        if prev is None:
            os.environ.pop("KARPENTER_TOPOLOGY_CARVE", None)
        else:
            os.environ["KARPENTER_TOPOLOGY_CARVE"] = prev
    ks_a, _, _ = gangs_of(6, 4, "ks", None)
    ks_b, sl_b, bd_b = gangs_of(6, 4, "ks", None)
    enc_a = encode_gang_window(ks_a, t_frees, t_prices, t_names)
    enc_b = encode_gang_window(ks_b, t_frees, t_prices, t_names,
                               slices=sl_b, bands=bd_b, type_grids=t_grids)
    tensors_equal = all(
        (x is None and y is None) or
        (x is not None and y is not None and np.array_equal(x, y))
        for x, y in ((enc_a.d_pods, enc_b.d_pods),
                     (enc_a.d_valid, enc_b.d_valid),
                     (enc_a.d_compat, enc_b.d_compat),
                     (enc_a.d_free0, enc_b.d_free0)))
    parity = (enc_b.carve is None and tensors_equal
              and plan_sig(plan_gang_window(enc_a))
              == plan_sig(plan_gang_window(enc_b)))

    return {
        "gangs": G, "seed_nodes": E + C + S, "empty_nodes": E,
        "frag_contiguous": C, "frag_scattered": S,
        "shape_only_placed": int(shape_placed),
        "carve_placed": int(carve_placed),
        "gain_pct": gain_pct,
        "phantom_gangs_naive": int(phantom),
        "carve_rejects": int(carve_rejects),
        "unverified": int(unverified),
        "kernel_gangs": KG, "kernel_bins": KB,
        "kernel_executor": kexec,
        "kernel_divergence": divergence,
        "kernel_p50_ms": st_k["p50_ms"], "kernel_p99_ms": st_k["p99_ms"],
        "scalar_p50_ms": st_s["p50_ms"], "scalar_p99_ms": st_s["p99_ms"],
        "speedup": speedup,
        "preemptions": len(plan_p.preemptions),
        "system_critical_preemptions": int(sc_preempts),
        "displaced_pods": int(displaced),
        "preempt_declines": declines,
        "preempt_fresh_fallback": int(fresh_fallback),
        "preempt_placed": len(plan_p.placements),
        "killswitch_gate": bool(killswitch_gate),
        "killswitch_parity": bool(parity),
    }


def config_17_carve_journal():
    """Round-17 gate: the durable topology ledger + preemption intent
    machine (docs/robustness.md §6). Three legs over one carve-heavy
    gang loop — launch, carve commit, priced displacement of the
    previous resident, winner carve on the freed node:

    - carve-journal tax: a replay-shaped run (journal fsync ON) whose
      gang cohort carries ``gang_slice`` labels, so every gang routes
      through the topology-carve planner and journals one durable carve
      intent per committed slice at realistic window pacing. The tax is
      the carve records' share of the journal's append histogram
      (records x mean append latency) against the run's wall.
      Gate: <= 1% (``overhead_pct`` / ``tax_gate``);
    - ledger recovery wall: the gang loop's journal (its open carve
      intents ARE the durable ledger) is replayed from cold —
      LEDGER.reset() + fresh handle + RecoveryController.run() per
      iteration — and the rebuilt occupancy must be bit-for-bit the
      pre-death snapshot every time (``recovered_bitident``).
      ``wall_ms`` p50/p99 feed the ledger_recovery_p99_ms ratchet;
    - machine cleanliness: after the loop the ONLY open intents are the
      live carves (every preempt/gang-bind pair folded) and replay
      reports zero errors — the preempt_crash_clean flag."""
    import shutil
    import tempfile
    import time as _time
    from types import SimpleNamespace

    import numpy as np

    from karpenter_tpu.api import wellknown
    from karpenter_tpu.api.constraints import Constraints
    from karpenter_tpu.api.core import NodeSelectorRequirement as Req
    from karpenter_tpu.api.requirements import Requirements
    from karpenter_tpu.cloudprovider.fake.provider import (
        FakeCloudProvider, tpu_catalog,
    )
    from karpenter_tpu.controllers.provisioning import (
        ProvisionerWorker, global_requirements,
    )
    from karpenter_tpu.controllers.recovery import RecoveryController
    from karpenter_tpu.metrics.recovery import JOURNAL_APPEND_SECONDS
    from karpenter_tpu.metrics.topology import (
        TOPOLOGY_CARVES_COMMITTED_TOTAL,
    )
    from karpenter_tpu.ops import topology as topo
    from karpenter_tpu.replay import ReplayConfig, run_replay
    from karpenter_tpu.runtime.journal import IntentJournal
    from karpenter_tpu.runtime.kubecore import KubeCore
    from karpenter_tpu.scheduling.batcher import Batcher
    from karpenter_tpu.solver.gang import PreemptCandidate
    from tests.expectations import make_provisioner, unschedulable_pod

    GRID = (4, 4)
    CELLS = 16
    G = 96       # gangs through the loop; every odd one displaces
    RECOVERY_ITERS = 12

    def _hsum(hist):
        collected = hist.collect()
        return (sum(s for _, s, _ in collected.values()),
                sum(t for _, _, t in collected.values()))

    def canon():
        out = []
        for ng in topo.LEDGER.snapshot():
            for k, r in ng.carves.items():
                out.append((ng.node, ng.type_name, tuple(ng.dims),
                            tuple(int(c) for c in sorted(r.cells)),
                            r.band, str(k),
                            tuple(sorted(f"{a}/{b}" for a, b in r.pods))))
        return sorted(out)

    cons = Constraints(
        labels={wellknown.PROVISIONER_NAME_LABEL: "carve-bench"},
        requirements=Requirements([
            Req(key=wellknown.LABEL_TOPOLOGY_ZONE, operator="In",
                values=["test-zone-1"]),
            Req(key=wellknown.LABEL_CAPACITY_TYPE, operator="In",
                values=["on-demand"]),
        ]))
    kube = KubeCore()
    provider = FakeCloudProvider(catalog=tpu_catalog())
    itype = next(t for t in provider.catalog if t.name == "tpu-v5e-4x4")
    prov = make_provisioner(name="carve-bench", constraints=cons)
    prov.spec.constraints.requirements = (
        prov.spec.constraints.requirements.add(
            *global_requirements(provider.get_instance_types(cons)).items))
    kube.create(prov)

    def prep_of(key, node=None):
        enc = SimpleNamespace(bins=[SimpleNamespace(
            type_index=0, name=f"{key}-bin", grid=GRID, node_name=node)])
        return SimpleNamespace(
            gang_enc=enc, gang_nodes=dict({0: node} if node else {}),
            gang_types=[(itype.name, itype)])

    def placement_of(key, pods, band, cells):
        gang = SimpleNamespace(
            key=key, pods=pods, band=band,
            context=SimpleNamespace(constraints=cons))
        return SimpleNamespace(gang=gang, node_sets=[(0, pods)],
                               carves={0: list(cells)})

    def rec_of(key):
        for ng in topo.LEDGER.snapshot():
            for k, r in ng.carves.items():
                if str(k) == key:
                    return ng.node, r
        return None

    # --- leg 1: carve-journal tax at replay pacing -------------------
    # the gang cohort is slice-labeled, so every gang runs the REAL
    # topology-carve planner inside the paced provisioning loop and
    # journals a durable carve intent per committed slice (fsync ON)
    topo.LEDGER.reset()
    rdir = tempfile.mkdtemp(prefix="bench-carve-replay-")
    try:
        carves0 = sum(TOPOLOGY_CARVES_COMMITTED_TOTAL.collect().values())
        rtax0 = _hsum(JOURNAL_APPEND_SECONDS)
        replay = run_replay(ReplayConfig(
            pods_total=3_000, shards=1, tenants=1, seed=7,
            bound_cohort=320, gang_fraction=0.5, gang_size=4,
            gang_slice="v5e-2x2", churn_pods=0, max_depth=2_000,
            ticks=6, tick_sleep_s=0.5, burst_ticks=1, chaos=False,
            settle_s=60.0, flood_pool=96, journal_dir=rdir,
            journal_fsync=True))
        rtax1 = _hsum(JOURNAL_APPEND_SECONDS)
    finally:
        shutil.rmtree(rdir, ignore_errors=True)
    carves = sum(TOPOLOGY_CARVES_COMMITTED_TOTAL.collect().values()) - carves0
    appends = rtax1[1] - rtax0[1]
    mean_append_s = ((rtax1[0] - rtax0[0]) / appends) if appends else 0.0
    # the tax the carve ledger ADDED: one durable record per committed
    # carve, priced at this run's measured mean append latency (the rest
    # of the append volume — fleet-launch, bind, gang-bind — predates
    # the ledger and is gated by config_15)
    carve_tax_s = carves * mean_append_s
    overhead_pct = (round(carve_tax_s / replay["wall_s"] * 100.0, 4)
                    if replay["wall_s"] else None)

    topo.LEDGER.reset()
    jdir = tempfile.mkdtemp(prefix="bench-carve-journal-")
    try:
        journal = IntentJournal(jdir, fsync=True)
        worker = ProvisionerWorker(
            prov, kube, provider,
            batcher=Batcher(idle_seconds=0.01, max_seconds=0.1),
            journal=journal)
        preemptions = launch_errors = 0
        tax0 = _hsum(JOURNAL_APPEND_SECONDS)
        t0 = _time.perf_counter()
        for i in range(G):
            key = f"cj-{i}"
            pods = []
            for j in range(2):
                p = unschedulable_pod(
                    requests={"cpu": "250m", "memory": "128Mi"},
                    name=f"{key}-m{j}")
                kube.create(p)
                pods.append(p)
            victims, node = [], None
            if i % 2 == 1:
                found = rec_of(f"cj-{i - 1}")  # displace the resident
                if found is not None:
                    node, r = found
                    victims.append(PreemptCandidate(
                        gang_key=r.gang_key, bin_index=0, node=node,
                        band=r.band, pods=list(r.pods),
                        cells=r.cells.copy(), refund=[0],
                        displacement_cost=0.1))
            prep = prep_of(key, node=node)
            placement = placement_of(
                key, pods, "high" if victims else "low",
                list(range(CELLS)))
            err = worker._launch_gang(prep, placement, victims or None)
            if err is not None:
                launch_errors += 1
                continue
            worker._commit_carves(prep, placement)
            preemptions += len(victims)
        loop_wall = _time.perf_counter() - t0
        tax1 = _hsum(JOURNAL_APPEND_SECONDS)
        loop_tax_s = tax1[0] - tax0[0]

        before = canon()
        opens = journal.open_intents()
        non_carve_open = sum(
            1 for it in opens.values() if it.kind != "carve")
        journal.close_journal()

        walls, errors = [], 0
        bitident = True
        for _ in range(RECOVERY_ITERS):
            topo.LEDGER.reset()
            with IntentJournal(jdir, fsync=False) as j2:
                recovery = RecoveryController(kube, provider, j2)
                r0 = _time.perf_counter()
                stats = recovery.run()
                walls.append(_time.perf_counter() - r0)
                errors += stats["errors"]
                bitident = bitident and canon() == before
    finally:
        topo.LEDGER.reset()
        shutil.rmtree(jdir, ignore_errors=True)

    return {
        "carve_tax": {
            "replay_wall_s": replay["wall_s"],
            "replay_bound": replay["bound"],
            "replay_completed": replay["completed"],
            "carves_committed": int(carves),
            "appends": appends,
            "mean_append_us": round(mean_append_s * 1e6, 2),
            "carve_tax_s": round(carve_tax_s, 6),
            "overhead_pct": overhead_pct,
        },
        "overhead_pct": overhead_pct,
        "tax_gate": (overhead_pct is not None and overhead_pct <= 1.0
                     and carves > 0),
        "gang_loop": {
            "gangs": G,
            "preemptions": preemptions,
            "launch_errors": launch_errors,
            "wall_s": round(loop_wall, 4),
            "journal_tax_s": round(loop_tax_s, 6),
            "journal_appends": tax1[1] - tax0[1],
        },
        "open_carves": len(opens),
        "non_carve_open_after": non_carve_open,
        "recovery": {
            "iters": RECOVERY_ITERS,
            "wall_ms": _stats(walls),
            "errors": errors,
            "recovered_bitident": bool(bitident),
        },
    }


def config_18_soft_affinity():
    """Round-16 gate: preferred (soft) pod-affinity fused into the
    window-scoring jit (docs/scheduling.md §8). A two-zone fleet carries
    24 follower cohorts, each preferring co-location with an anchor
    cohort pinned to an alternating zone; the preferred terms become
    per-schedule zone vote maps.

    Three legs:

    - co-location A/B: with soft scoring on, `ops/policy.steer_zone`
      pins every follower's launch to its anchor's zone; with
      KARPENTER_SOFT_AFFINITY=0 the launcher falls back to its
      deterministic first-allowed-zone pick, scattering the cohorts
      whose anchor sits in the other zone. Gate: co-located cohorts
      >= 2x the soft-off leg at <= 1% node-count regression (steering
      must narrow zones, never inflate the fleet);
    - kernel A/B: `score_fused_window` with per-(schedule, zone) soft
      adjustment rows vs the per-cell host loop computing the same
      exact-int algebra (micro-$ base + clamp(-w x scale), min over
      viable zones) from raw offerings. Gate: >= 5x, with the probe
      re-verification timed INSIDE the device leg;
    - the filter contract: zero score-mismatch / soft-affinity-mismatch
      fallbacks across the whole run — every soft row that reached the
      pack kernel survived the probe against the scalar oracle.
    """
    import numpy as _np

    from karpenter_tpu.api import wellknown as _wk
    from karpenter_tpu.api.core import NodeSelectorRequirement as _Req
    from karpenter_tpu.controllers.provisioning import universe_constraints
    from karpenter_tpu.metrics.policy import POLICY_FALLBACK_TOTAL
    from karpenter_tpu.models.cost import CostConfig
    from karpenter_tpu.ops import device_filter
    from karpenter_tpu.ops import policy as ops_policy
    from karpenter_tpu.solver import policy as policy_registry
    from karpenter_tpu.solver.adapter import marshal_pods_interned
    from karpenter_tpu.solver.batch_solve import Problem, solve_batch
    from karpenter_tpu.solver.policy import PolicyContext
    from karpenter_tpu.solver.solve import (
        SolverConfig, resolved_device_max_shapes,
    )

    if not ops_policy.enabled():
        return {"skipped": "KARPENTER_POLICY_DEVICE=0"}
    if not device_filter.enabled():
        return {"skipped": "KARPENTER_DEVICE_FILTER=0 (no fused window)"}

    T, S = 400, 24
    catalog = make_catalog(T, zones=2)
    constraints = universe_constraints(catalog)
    zones = [f"bench-zone-{z}" for z in (1, 2)]
    ctx = PolicyContext(soft_affinity_cost_per_weight=0.001)
    cfg = SolverConfig(device_min_pods=1)
    cheapest = policy_registry.get("cheapest")

    per = 4800 // S
    anchors, problems = [], []
    for b in range(S):
        anchor = zones[b % 2]
        anchors.append(anchor)
        pods = make_pods(per, MIXED_SHAPES[b % len(MIXED_SHAPES):]
                         + MIXED_SHAPES[:b % len(MIXED_SHAPES)])
        for j, p in enumerate(pods):
            p.metadata.name = f"p{b}-{j}"
        problems.append(Problem(
            constraints=constraints.deepcopy(), pods=pods,
            instance_types=catalog,
            soft_affinity={(_wk.LABEL_TOPOLOGY_ZONE, anchor): 100}))

    # -- co-location A/B: steered zone pick vs the soft-off default ------
    def picks(env_on):
        prev = os.environ.get("KARPENTER_SOFT_AFFINITY")
        os.environ["KARPENTER_SOFT_AFFINITY"] = "1" if env_on else "0"
        try:
            steers, resolved = [], []
            for prob in problems:
                z = ops_policy.steer_zone(
                    catalog, prob.constraints.requirements,
                    cfg.cost_config, ctx, prob.soft_affinity)
                steers.append(z)
                # the launcher's deterministic fallback: first allowed zone
                resolved.append(z if z is not None else zones[0])
            return steers, resolved
        finally:
            if prev is None:
                os.environ.pop("KARPENTER_SOFT_AFFINITY", None)
            else:
                os.environ["KARPENTER_SOFT_AFFINITY"] = prev

    steers_on, picks_on = picks(True)
    _, picks_off = picks(False)
    steered = sum(1 for z in steers_on if z is not None)
    coloc_on = sum(1 for z, a in zip(picks_on, anchors) if z == a)
    coloc_off = sum(1 for z, a in zip(picks_off, anchors) if z == a)
    coloc_gain = round(coloc_on / max(1, coloc_off), 2)

    # node-count regression: the steered (zone-pinned) window vs the
    # unpinned one — steering narrows the offering set, so the gate is
    # that the narrowed fleet packs no more than 1% extra nodes
    def pinned(zs):
        out = []
        for prob, z in zip(problems, zs):
            tight = prob.constraints.deepcopy()
            tight.requirements = tight.requirements.add(_Req(
                key=_wk.LABEL_TOPOLOGY_ZONE, operator="In", values=[z]))
            out.append(Problem(constraints=tight, pods=prob.pods,
                               instance_types=catalog))
        return out

    def total_nodes(rs):
        return sum(sum(p.node_quantity for p in r.packings) for r in rs)

    nodes_on = total_nodes(solve_batch(pinned(picks_on), cfg))
    nodes_off = total_nodes(solve_batch(
        [Problem(constraints=p.constraints, pods=p.pods,
                 instance_types=catalog) for p in problems], cfg))
    regression_pct = round(
        (nodes_on - nodes_off) / max(1, nodes_off) * 100.0, 3)

    # -- kernel A/B over one fused soft window ---------------------------
    fb_before = dict(POLICY_FALLBACK_TOTAL.collect())
    marshaled = [marshal_pods_interned(p.pods) for p in problems]
    fused = device_filter.prepare_fused(problems, marshaled, cfg,
                                        resolved_device_max_shapes(cfg))
    if fused is None:
        return {"error": "window not fused — soft scoring A/B needs the "
                         "bit-plane window (config_12's stage)"}
    try:
        imax = int(ops_policy._INT32_MAX)
        clamp = int(ops_policy._SOFT_CLAMP)
        scale = int(round(ctx.soft_affinity_cost_per_weight * 1e6))
        cost_config = cfg.cost_config or CostConfig()

        def host_leg():
            rows = []
            for i in fused.batch_idx:
                reqs = problems[i].constraints.requirements
                votes = {z: w for (k, z), w in
                         problems[i].soft_affinity.items()
                         if k == _wk.LABEL_TOPOLOGY_ZONE}
                cts = reqs.capacity_types()
                zallow = reqs.zones()
                row = []
                for p in fused.packables:
                    it = fused.uni_types[p.index]
                    best = imax
                    for ct in {o.capacity_type for o in it.offerings}:
                        if cts is not None and ct not in cts:
                            continue
                        viable = [o.zone for o in it.offerings
                                  if o.capacity_type == ct
                                  and (zallow is None or o.zone in zallow)]
                        if not viable:
                            continue
                        base = it.price * cost_config.spot_price_factor \
                            if ct == _wk.CAPACITY_TYPE_SPOT else it.price
                        cell = int(ops_policy._encode_micro(base))
                        adj = min(max(-clamp,
                                      min(-votes.get(z, 0) * scale, clamp))
                                  for z in viable)
                        best = min(best, max(0, min(cell + adj, imax)))
                    row.append(best)
                rows.append(_np.asarray(row, dtype=_np.int32))
            return rows

        def device_leg():
            rows = ops_policy.score_fused_window(
                fused, cheapest, cost_config, ctx)
            assert rows is not None, "device scoring fell back mid-bench"
            return rows

        host_rows = host_leg()
        dev_rows = device_leg()  # warm tables + jit before the clock
        divergence = sum(
            int(_np.sum(_np.asarray(d)[:len(h)] != h))
            for d, h in zip(dev_rows, host_rows))
        host_times = run_timed(host_leg, budget_s=30.0)
        device_times = run_timed(device_leg, budget_s=15.0)
    finally:
        fused.release()
    st_host = _stats(host_times)
    st_device = _stats(device_times)
    speedup = round(st_host["p50_ms"] / (st_device["p50_ms"] or 1e-9), 2)

    fb_after = dict(POLICY_FALLBACK_TOTAL.collect())
    fallbacks = {dict(k).get("reason", "?"): fb_after[k] - fb_before.get(k, 0)
                 for k in fb_after
                 if fb_after[k] - fb_before.get(k, 0.0) > 0}
    unverified = int(fallbacks.get("soft-affinity-mismatch", 0)
                     + fallbacks.get("score-mismatch", 0))
    return {
        "pods": per * S, "types": T, "schedules_per_window": S,
        "cohorts": S, "steered": int(steered),
        "coloc_on": int(coloc_on), "coloc_off": int(coloc_off),
        "coloc_gain": coloc_gain,
        "nodes_on": int(nodes_on), "nodes_off": int(nodes_off),
        "node_regression_pct": regression_pct,
        "host_p50_ms": st_host["p50_ms"], "host_p99_ms": st_host["p99_ms"],
        "device_p50_ms": st_device["p50_ms"],
        "device_p99_ms": st_device["p99_ms"],
        "speedup": speedup,
        "row_divergence": int(divergence),
        "unverified": unverified,
        "policy_fallbacks": fallbacks,
    }


def jax_devices_first():
    import jax

    return jax.devices()[:1]


_CP_CHUNK_ITEMS = 2048  # pipeline chunk unit for the config_7 A/B


def config_7_control_plane():
    """Control-plane load, pipeline A/B: the full 10k-pod stack runs TWICE
    in one call — pipelined (depth 2, solver/pipeline.py double buffering)
    and serial (depth 1) — with identical batching and chunk boundaries,
    so `nodes_created` must match exactly and the throughput ratio is
    attributable to launch/bind ↔ solve overlap alone. Adaptive depth is
    PINNED OFF for both legs (an adaptive run would collapse the depth-2
    leg under its own measurement and poison the A/B). Headline fields
    report the pipelined run; the side-by-side comparison lands in
    ``pipeline_ab`` with per-stage wall, per-device live bytes at peak,
    and the ring's allocation/refill deltas per leg. NOTE: on a 1-core
    host (this container) the overlap is GIL-bound — the honest speedup
    ceiling is ~1.0× here; the ratio is reported, not asserted."""
    # untimed prewarm at a fraction of the load: compiles the ring pjit +
    # refill jits and leaves warm ring slots, so neither timed leg pays
    # cold-compile inside its window (the legs share every jit cache —
    # whichever ran first used to eat ~2 s of XLA lowering in 'marshal')
    from karpenter_tpu.obs import slo as _slo
    from karpenter_tpu.obs import trace as _trace

    # the prewarm leg runs TRACED (it is untimed, so the span tax cannot
    # touch the A/B): its span count times the measured ns/span bounds the
    # tracing tax as a fraction of window wall — the <2% acceptance claim.
    # The SLO stamp tax is bounded the same way: record() calls during the
    # prewarm × measured ns/call (weighted chunk stamps are one call, so
    # calls — not samples — is the honest unit).
    _trace.reset()
    _trace.enable()
    _slo.reset()
    slo_was_enabled = _slo.enabled()
    _slo.enable()
    try:
        prewarm = _control_plane_run(pipeline_depth=2, n=4096)
    finally:
        if not slo_was_enabled:
            _slo.disable()
    prewarm_spans = _trace.state()["spans_buffered"]
    slo_calls = _slo.record_calls()
    _trace.reset()
    overhead = _trace.measure_overhead()
    slo_over = _slo.measure_overhead()
    on = _control_plane_run(pipeline_depth=2)
    off = _control_plane_run(pipeline_depth=1)
    sps, pps = off["pods_bound_per_sec"], on["pods_bound_per_sec"]
    tax_pct = (prewarm_spans * overhead["enabled_ns_per_span"] / 1e9
               / prewarm["wall_s"] * 100) if prewarm["wall_s"] else None
    slo_tax_pct = (slo_calls * slo_over["enabled_ns_per_record"] / 1e9
                   / prewarm["wall_s"] * 100) if prewarm["wall_s"] else None
    return {
        **on,
        "trace_overhead": {
            "disabled_ns_per_span": round(overhead["disabled_ns_per_span"], 1),
            "enabled_ns_per_span": round(overhead["enabled_ns_per_span"], 1),
            "spans_per_traced_run": prewarm_spans,
            "traced_run_wall_s": round(prewarm["wall_s"], 4),
            "est_tax_pct": round(tax_pct, 4) if tax_pct is not None else None,
        },
        "slo_overhead": {
            "disabled_ns_per_record": round(
                slo_over["disabled_ns_per_record"], 1),
            "enabled_ns_per_record": round(
                slo_over["enabled_ns_per_record"], 1),
            "record_calls_per_run": slo_calls,
            "stamped_run_wall_s": round(prewarm["wall_s"], 4),
            "est_tax_pct": (round(slo_tax_pct, 4)
                            if slo_tax_pct is not None else None),
        },
        "pipeline_ab": {
            "depth_pipelined": 2,
            "depth_serial": 1,
            "adaptive": "pinned off for both legs",
            "device_count": _device_count(),
            "chunk_items": _CP_CHUNK_ITEMS,
            "pods_bound_per_sec_pipelined": pps,
            "pods_bound_per_sec_serial": sps,
            "speedup": round(pps / sps, 3) if sps else None,
            "overlap_seconds_pipelined": on["overlap_seconds"],
            "overlap_seconds_serial": off["overlap_seconds"],
            "nodes_created_pipelined": on["nodes_created"],
            "nodes_created_serial": off["nodes_created"],
            "nodes_equal": on["nodes_created"] == off["nodes_created"],
            "executors_pipelined": on["executor_delta"],
            "executors_serial": off["executor_delta"],
            "stage_ms_pipelined": on["stage_ms"],
            "stage_ms_serial": off["stage_ms"],
            "ring_pipelined": on["ring"],
            "ring_serial": off["ring"],
            "peak_live_device_bytes": max(on["peak_live_device_bytes"],
                                          off["peak_live_device_bytes"]),
            "prewarm_wall_s": prewarm["wall_s"],
        },
    }


def _control_plane_run(pipeline_depth: int, n: int = 10_000):
    """Control-plane load: 10k unschedulable pods through the FULL stack —
    watch pump → selection (64 workers, non-blocking gate) → batcher →
    pipelined batched sharded solves → launch → bind — against the
    in-memory apiserver (kubecore). The reference's regime is 10,000
    concurrent selection reconciles (selection/controller.go:181); this
    measures the Python plane sustaining the same pod count end-to-end.

    Batching is single-window (idle 1 s, max 60 s): every pod lands in one
    window, so the pipeline's chunk boundaries — and therefore the packing
    and node counts — are identical between the depth-2 and depth-1 runs
    (the A/B's equal-nodes invariant needs deterministic windowing, which
    the old 0.3 s/5 s window race could not give).

    Reported: pods-bound/sec over the whole run, pending→bound latency
    percentiles (per pod: bind observed at poll t → latency ≈ t - create),
    a filter_ms breakdown — time spent in the columnar feasibility
    filter (ops/feasibility.py) per stage plus any scalar fallbacks — so
    control-plane wins are attributable, plus the run's overlap seconds
    and per-executor solve deltas (a pipeline-attributable fallback would
    show up here as host/native counts in the pipelined column only).
    """
    import functools
    import time as _time

    from karpenter_tpu.metrics.filter import (
        FILTER_BATCH_SECONDS, FILTER_FALLBACK_TOTAL,
    )
    from karpenter_tpu.metrics.pipeline import PIPELINE_STAGE_SECONDS

    def _filter_snapshot():
        hist = {lv: (s, total) for lv, (_, s, total)
                in FILTER_BATCH_SECONDS.collect().items()}
        return hist, dict(FILTER_FALLBACK_TOTAL.collect())

    def _stage_snapshot():
        return {lv: (s, n) for lv, (_, s, n)
                in PIPELINE_STAGE_SECONDS.collect().items()}

    def _stage_delta(before, after):
        """Per-stage wall delta: where this leg's chunks actually spent
        their time (marshal | device | launch_bind)."""
        out = {}
        for lv, (s1, n1) in after.items():
            s0, n0 = before.get(lv, (0.0, 0))
            if n1 - n0:
                out[dict(lv).get("stage", "?")] = {
                    "total_ms": round((s1 - s0) * 1000, 1),
                    "chunks": n1 - n0}
        return out

    def _filter_delta(before, after):
        hist0, fb0 = before
        hist1, fb1 = after
        out = {}
        for lv, (s1, n1) in hist1.items():
            s0, n0 = hist0.get(lv, (0.0, 0))
            stage = dict(lv).get("stage", "?")
            out[f"{stage}_total_ms"] = round((s1 - s0) * 1000, 2)
            out[f"{stage}_batches"] = n1 - n0
        fallbacks = {}
        for lv, v1 in fb1.items():
            d = v1 - fb0.get(lv, 0.0)
            if d:
                fallbacks[dict(lv).get("reason", "?")] = d
        out["fallbacks"] = fallbacks
        return out

    from karpenter_tpu.api.provisioner import Provisioner
    from karpenter_tpu.cloudprovider.fake.provider import FakeCloudProvider
    from karpenter_tpu.cloudprovider.metrics import decorate
    from karpenter_tpu.controllers.provisioning import ProvisioningController
    from karpenter_tpu.controllers.selection import SelectionController
    from karpenter_tpu.runtime.kubecore import KubeCore
    from karpenter_tpu.runtime.manager import Manager
    from karpenter_tpu.metrics.pipeline import SOLVER_OVERLAP_SECONDS_TOTAL
    from karpenter_tpu.metrics.registry import DEFAULT
    from karpenter_tpu.scheduling.batcher import Batcher
    from karpenter_tpu.solver.pipeline import PipelineConfig
    from tests.expectations import unschedulable_pod

    from karpenter_tpu.utils.workers import adaptive_workers

    def _overlap_total():
        return sum(SOLVER_OVERLAP_SECONDS_TOTAL.collect().values())

    def _executor_counts():
        return dict(DEFAULT.counter("solver_solves_total").collect())

    N = n
    catalog = make_catalog(100)
    kube = KubeCore()
    provider = decorate(FakeCloudProvider(catalog=catalog))
    # adaptive=False: the A/B legs pin their depth — letting the adaptive
    # controller re-step mid-leg would measure its policy, not the overlap
    provisioning = ProvisioningController(
        kube, provider,
        pipeline_config=PipelineConfig(depth=pipeline_depth,
                                       chunk_items=_CP_CHUNK_ITEMS,
                                       adaptive=False),
        batcher_factory=functools.partial(
            Batcher, idle_seconds=1.0, max_seconds=60.0))
    manager = Manager(kube)
    manager.register(provisioning, workers=2)
    # clamped to the host's cores (utils/workers.py): 64 GIL-bound threads
    # on a 1-core host bound 10k pods ~4x slower than the adaptive pool
    # (driver capture BENCH_r04 config_7: 128 pods/s)
    sel_workers = adaptive_workers(64)
    manager.register(SelectionController(kube, provisioning),
                     workers=sel_workers)

    prov = Provisioner()
    prov.metadata.name = "load"
    kube.create(prov)
    manager.start()
    try:
        # wait for the provisioner worker to exist before the pod flood
        deadline = _time.monotonic() + 10.0
        while "load" not in provisioning.workers:
            if _time.monotonic() > deadline:
                raise RuntimeError("provisioner worker did not start")
            _time.sleep(0.02)

        # meta-only watch for bind detection: event-driven timestamps with
        # no deep copies and no polling (the previous 50 ms no-copy scan of
        # 10k objects consumed ~20% of the single core it shares with the
        # plane under test)
        import queue as _queue

        watch_q = kube.watch("Pod", meta_only=True)

        from karpenter_tpu.parallel.mesh import device_bytes_in_use
        from karpenter_tpu.solver.pipeline import get_ring

        shapes = MIXED_SHAPES
        created_at = {}
        filter_before = _filter_snapshot()
        stage_before = _stage_snapshot()
        ring0 = get_ring().counters()
        peak_bytes, peak_per_device = 0, {}

        def _sample_device_bytes():
            nonlocal peak_bytes, peak_per_device
            per_dev = device_bytes_in_use()
            total = sum(per_dev.values())
            if total > peak_bytes:
                peak_bytes, peak_per_device = total, per_dev

        overlap0 = _overlap_total()
        exec0 = _executor_counts()
        from karpenter_tpu.api import wellknown

        t_start = _time.perf_counter()
        for i in range(N):
            c, m = shapes[i % len(shapes)]
            # alternate zones: each chunk schedules into >= 2 problems so
            # the window exercises the BATCHED sharded solve (the ring +
            # donation path under test), not the solo per-problem kernel
            pod = unschedulable_pod(
                requests={"cpu": f"{c}m", "memory": f"{m}Mi"},
                node_selector={wellknown.LABEL_TOPOLOGY_ZONE:
                               f"bench-zone-{1 + i % 2}"},
                name=f"load-{i}")
            kube.create(pod)
            created_at[pod.metadata.name] = _time.perf_counter()
        t_created = _time.perf_counter()

        bound_at = {}
        deadline = _time.monotonic() + 240.0
        polls = 0
        while len(bound_at) < N and _time.monotonic() < deadline:
            polls += 1
            if polls % 50 == 0:  # ~10 s cadence: live-buffer walks aren't free
                _sample_device_bytes()
            try:
                event = watch_q.get(timeout=0.2)
            except _queue.Empty:
                continue
            name = event.obj.metadata.name
            if (event.type == "MODIFIED" and name in created_at
                    and name not in bound_at):
                # cheap no-copy confirmation that this MODIFIED is the bind
                if kube.read("Pod", name, event.obj.metadata.namespace,
                             lambda p: bool(p.spec.node_name)):
                    bound_at[name] = _time.perf_counter()
        t_done = _time.perf_counter()
        _sample_device_bytes()  # steady-state sample: the ring is resident
        filter_after = _filter_snapshot()
        stage_after = _stage_snapshot()
        ring1 = get_ring().counters()
        kube.unwatch(watch_q)
    finally:
        manager.stop()

    bound = len(bound_at)
    lat = sorted(bound_at[n] - created_at[n] for n in bound_at)
    total_s = t_done - t_start
    executor_delta = {}
    for lv, v in _executor_counts().items():
        d = v - exec0.get(lv, 0.0)
        if d:
            executor_delta[dict(lv).get("executor", "?")] = int(d)
    out = {
        "pods": N, "bound": bound,
        "pipeline_depth": pipeline_depth,
        "overlap_seconds": round(_overlap_total() - overlap0, 3),
        "executor_delta": executor_delta,
        "create_all_s": round(t_created - t_start, 2),
        "pending_to_bound_p50_s": round(lat[len(lat) // 2], 2) if lat else None,
        "pending_to_bound_p99_s": round(lat[int(len(lat) * 0.99)], 2) if lat else None,
        "wall_s": round(total_s, 2),
        "pods_bound_per_sec": round(bound / total_s) if total_s > 0 else 0,
        "nodes_created": len(kube.list("Node")),
        "filter_ms": _filter_delta(filter_before, filter_after),
        "stage_ms": _stage_delta(stage_before, stage_after),
        "ring": {"allocations": ring1["allocations"] - ring0["allocations"],
                 "refills": ring1["refills"] - ring0["refills"],
                 "slots": ring1["slots"]},
        "peak_live_device_bytes": peak_bytes,
        "peak_live_device_bytes_per_device": {
            str(k): v for k, v in sorted(peak_per_device.items())},
        "selection_workers": sel_workers,
        "stack": f"watch → selection({sel_workers}w adaptive, non-blocking)"
                 " → batcher(single-window) → pipelined batched sharded "
                 f"solve (depth {pipeline_depth}, chunks of "
                 f"{_CP_CHUNK_ITEMS}, 2-zone spread → ring/donation path) "
                 "→ launch → bulk bind (kubecore)",
    }
    assert bound == N, f"only {bound}/{N} pods bound"
    return out


def _backend_name():
    import jax

    try:
        return jax.default_backend()
    except Exception:
        return "unknown"


def _device_count():
    try:
        import jax

        return jax.device_count()
    except Exception:
        return None


def _persist_partial(extra):
    """Per-config checkpoint: a child killed mid-run (tunnel death after a
    good probe) leaves its completed configs on disk for the supervisor to
    salvage into the final line instead of zeroing the round."""
    path = os.environ.get("KARPENTER_BENCH_PARTIAL")
    if not path:
        return
    try:
        with open(path, "w") as f:
            json.dump(extra, f)
    except OSError:
        pass


def _only_set():
    """`bench.py --only config_6 config_8` → the KARPENTER_BENCH_ONLY env
    (set in main, inherited by the supervisor's children): run only the
    named configs. None = everything (the default full line)."""
    raw = os.environ.get("KARPENTER_BENCH_ONLY", "").strip()
    if not raw:
        return None
    return {t.strip() for t in raw.replace(",", " ").split() if t.strip()}


def _selected(key: str, only) -> bool:
    # prefix match on a full name segment: `config_1` must not also select
    # config_10_marshal_delta
    return only is None or any(key == o or key.startswith(o + "_")
                               for o in only)


def run_all(degraded: bool, probe_note: str = ""):
    """Run the five configs; individual failures land in their slot, a
    headline failure propagates (main decides whether to re-exec degraded)."""
    only = _only_set()
    if _selected("config_4_50k_pods_cost_minimizing", only):
        headline_times, c4 = config_4_headline()   # headline first: fail fast
    else:
        headline_times, c4 = [], {"skipped": "not in --only"}
    extra = {"backend": _backend_name(), "degraded": degraded,
             "device_count": _device_count()}
    if probe_note:
        extra["probe"] = probe_note
    if only is not None:
        extra["only"] = sorted(only)
    extra["config_4_50k_pods_cost_minimizing"] = c4
    extra["headline_times"] = [round(t, 6) for t in sorted(headline_times)]
    _persist_partial(extra)
    for key, fn in (
        ("config_1_smoke_100_pods", config_1_smoke),
        ("config_2_5k_pods_constrained", config_2_constrained),
        ("config_3_20k_pods_3zone_topology", config_3_topology),
        ("config_5_consolidate_2k_nodes", config_5_consolidation),
        ("config_6_high_shape_cardinality", config_6_high_cardinality),
        ("config_7_control_plane_10k_pods", config_7_control_plane),
        ("config_8_large_catalog_type_spmd", config_8_large_catalog_type_spmd),
        ("config_9_million_pod_replay", config_9_million_pod_replay),
        ("config_10_marshal_delta", config_10_marshal_delta),
        ("config_11_gang_copack", config_11_gang_copack),
        ("config_12_device_filter", config_12_device_filter),
        ("config_13_policy_scoring", config_13_policy_scoring),
        ("config_14_global_window", config_14_global_window),
        ("config_15_crash_recovery", config_15_crash_recovery),
        ("config_16_topology_carve", config_16_topology_carve),
        ("config_17_carve_journal", config_17_carve_journal),
        ("config_18_soft_affinity", config_18_soft_affinity),
    ):
        if not _selected(key, only):
            continue
        if key == "config_9_million_pod_replay" and only is None:
            # minutes of wall per run: opt-in only (make bench-replay)
            extra[key] = {"skipped": "heavy: run via --only config_9 "
                                     "(make bench-replay)"}
            continue
        try:
            extra[key] = fn()
        except Exception as e:  # ring 2: one config never kills the line
            extra[key] = {"error": f"{type(e).__name__}: {e}"}
        _persist_partial(extra)
    extra.pop("headline_times", None)
    # tail-mitigation evidence: how often the hedged second fetch
    # (solver/hedge.py) fired across the whole run, and how often the
    # hedge beat the stuck first attempt
    from karpenter_tpu.solver.hedge import FETCHER

    extra["hedged_fetches"] = {"fired": FETCHER.hedges_fired,
                               "won": FETCHER.hedges_won}
    _persist_partial(extra)  # keep the salvage path's checkpoint complete
    p99 = _stats(headline_times)["p99_ms"] if headline_times else None
    return _metric_line(p99, extra)


def _metric_line(p99_ms, extra):
    """The one JSON line's shape — single point of truth for the metric
    name and vs_baseline math (used by run_all, the salvage path, and the
    fallback)."""
    return {
        "metric": "p99_solve_latency_ms_50k_pods_x_400_types",
        "value": p99_ms,
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / p99_ms, 3) if p99_ms else 0.0,
        "extra": extra,
    }


def _fallback_line(note):
    return _metric_line(None, {"degraded": True, "error": note})


def _read_partial(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _run_child(mode: str, deadline_s: float, probe_note: str,
               partial_path: str = ""):
    """Run this script in `mode`; return its JSON line (dict) or None.
    stderr passes through for debugging; stdout is parsed for the LAST
    line that decodes to the bench dict."""
    env = {**os.environ, _MODE_ENV: mode, "KARPENTER_BENCH_NOTE": probe_note}
    if partial_path:
        env["KARPENTER_BENCH_PARTIAL"] = partial_path
        try:
            os.unlink(partial_path)
        except OSError:
            pass
    # persistent XLA compilation cache: the large shape buckets (config 6)
    # compile once per bucket pair; caching them across runs keeps repeat
    # benches inside the child deadline
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/karpenter_jax_cache")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, text=True, timeout=deadline_s)
        stdout = proc.stdout
        rc = proc.returncode
    except subprocess.TimeoutExpired as e:
        # a child wedged in runtime TEARDOWN may already have printed its
        # line — salvage the captured stdout before declaring failure
        print(f"bench child mode={mode} exceeded {deadline_s:.0f}s deadline",
              file=sys.stderr)
        stdout = e.stdout if isinstance(e.stdout, str) else (
            (e.stdout or b"").decode(errors="replace"))
        rc = -1
    for raw in reversed((stdout or "").strip().splitlines()):
        try:
            line = json.loads(raw)
            if isinstance(line, dict) and "metric" in line:
                return line
        except ValueError:
            continue
    print(f"bench child mode={mode} rc={rc}: no JSON line", file=sys.stderr)
    return None


def _parse_args(argv):
    """`--only config_N ...` and `--devices N`, in either order. Both are
    carried in the environment so the supervisor's child processes (and
    their degraded re-execs) inherit the selection without re-parsing."""
    usage = ("usage: bench.py [--only config_N ...] [--devices N] "
             "[--trace TRACE.json]")
    i = 0
    while i < len(argv):
        if argv[i] == "--trace":
            if i + 1 >= len(argv) or argv[i + 1].startswith("--"):
                print(usage, file=sys.stderr)
                return False
            # env, not a global: the supervisor's children inherit it the
            # same way they inherit --only (config_5's trace leg reads it)
            os.environ["KARPENTER_BENCH_TRACE"] = argv[i + 1]
            i += 2
        elif argv[i] == "--devices":
            if i + 1 >= len(argv) or not argv[i + 1].isdigit():
                print(usage, file=sys.stderr)
                return False
            os.environ[_DEVICES_ENV] = argv[i + 1]
            i += 2
        elif argv[i] == "--only":
            names = []
            i += 1
            while i < len(argv) and not argv[i].startswith("--"):
                names.append(argv[i])
                i += 1
            if not names:
                print(usage, file=sys.stderr)
                return False
            os.environ["KARPENTER_BENCH_ONLY"] = " ".join(names)
        else:
            print(f"unknown argument {argv[i]!r}; {usage}", file=sys.stderr)
            return False
    return True


def main():
    if not _parse_args(sys.argv[1:]):
        return 2
    mode = os.environ.get(_MODE_ENV)
    note = os.environ.get("KARPENTER_BENCH_NOTE", "")
    if mode in ("direct", "direct-cpu"):
        # must precede any jax import in this child (re-execs if one won)
        _apply_devices_env()
    if mode == "direct":
        print(json.dumps(run_all(degraded=False, probe_note=note)))
        return 0
    if mode == "direct-cpu":
        from karpenter_tpu.utils.backend import force_cpu

        force_cpu()
        print(json.dumps(run_all(degraded=True, probe_note=note)))
        return 0

    # -- supervisor: never imports jax ------------------------------------
    from karpenter_tpu.utils.backend import probe_backend

    probe = probe_backend(timeout_s=120.0, retries=2)
    line = None
    if probe.ok and probe.platform not in ("cpu", ""):
        probe_note = (f"{probe.platform} up in {probe.elapsed_s:.0f}s "
                      f"({probe.attempts} attempt(s))")
        # unique per-run checkpoint path: a fixed /tmp name would let
        # concurrent bench runs clobber or cross-salvage each other
        import tempfile

        fd, partial_path = tempfile.mkstemp(
            prefix="karpenter_bench_partial_", suffix=".json")
        os.close(fd)
        line = _run_child("direct", TPU_CHILD_DEADLINE_S, probe_note,
                          partial_path=partial_path)
        if line is None:
            # the TPU child died mid-run: salvage its per-config
            # checkpoints — completed TPU configs beat a degraded rerun
            partial = _read_partial(partial_path)
            times = (partial or {}).pop("headline_times", None)
            if partial and times:
                line = _metric_line(
                    _stats(times)["p99_ms"],
                    {**partial, "partial": "TPU child died mid-run; "
                                           "completed configs salvaged"})
            else:
                line = _run_child(
                    "direct-cpu", CPU_CHILD_DEADLINE_S,
                    "device run failed mid-flight; degraded to cpu")
                if line is not None and partial:
                    line.setdefault("extra", {})[
                        "partial_tpu_results"] = partial
        try:
            os.unlink(partial_path)
        except OSError:
            pass
    else:
        note = (f"no accelerator (backend is {probe.platform}); running on cpu"
                if probe.ok else
                f"backend init failed ({probe.error}); degraded to cpu")
        line = _run_child("direct-cpu", CPU_CHILD_DEADLINE_S, note)
    if line is None:
        line = _fallback_line("both device and cpu bench children failed")
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
