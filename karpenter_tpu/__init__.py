"""karpenter_tpu: a TPU-native Kubernetes node-provisioning autoscaler.

Same capabilities as the reference Karpenter (watch unschedulable pods →
evaluate constraints → bin-pack onto instance types → launch/bind →
deprovision), with the scheduling hot loop formulated as a vectorized
assignment problem solved with JAX/XLA on TPU.

Layout:
- api/            Provisioner CRD types + constraint algebra (host reference)
- ops/            device kernels + columnar filters: encode/interning, pack,
                  compact, feasibility (interned-bitset constraint engine)
- models/         solver formulations (FFD-parity, cost-minimizing, consolidation)
- parallel/       device mesh + pods-axis sharding (shard_map)
- solver/         end-to-end solve orchestration + host oracle + C++ fallback
- scheduling/     batcher, scheduler (constraint grouping), topology
- controllers/    provisioning, selection, node, termination, counter, pvc, metrics
- cloudprovider/  SPI + fake + aws providers
- runtime/        in-memory kube API (envtest equivalent), manager, workqueue
- utils/          quantities, predicates, injectable clock
"""

__version__ = "0.1.0"
