"""Provisioner manifest codec: CRD JSON/YAML dicts ⟷ API dataclasses.

Reference: the v1alpha5 CRD schema (charts/karpenter/crds/
karpenter.sh_provisioners.yaml; mirrored at deploy/crds/) and the Go type
JSON tags in pkg/apis/provisioning/v1alpha5/{provisioner.go,constraints.go}.
Used by the admission webhook server (webhooks/server.py) and by anything
loading `kubectl`-shaped manifests.
"""

from __future__ import annotations

from typing import Any, Dict

from karpenter_tpu.api.codec_core import (
    ts_from as codec_core_ts_from, ts_to as codec_core_ts_to,
)
from karpenter_tpu.api.constraints import Constraints, KubeletConfiguration, Limits, Taints
from karpenter_tpu.api.core import NodeSelectorRequirement, ObjectMeta, Taint
from karpenter_tpu.api.provisioner import (
    Condition, Provisioner, ProvisionerSpec, ProvisionerStatus,
)
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.utils.resources import parse_resource_list

API_VERSION = "karpenter.sh/v1alpha5"
KIND = "Provisioner"


def _ts_from_lenient(s):
    """codec_core.ts_from, but a malformed timestamp in a user-supplied
    manifest must not 500 the admission webhook — decode to None instead."""
    try:
        return codec_core_ts_from(s)
    except (ValueError, TypeError, AttributeError):
        return None


def provisioner_from_manifest(manifest: Dict[str, Any]) -> Provisioner:
    """Decode a CRD-shaped dict (what the API server posts to the webhook)."""
    meta = manifest.get("metadata") or {}
    spec = manifest.get("spec") or {}
    constraints = Constraints(
        labels=dict(spec.get("labels") or {}),
        taints=Taints([
            Taint(key=t.get("key", ""), value=t.get("value", ""),
                  effect=t.get("effect", "NoSchedule"))
            for t in (spec.get("taints") or [])
        ]),
        requirements=Requirements([
            NodeSelectorRequirement(
                key=r.get("key", ""), operator=r.get("operator", "In"),
                values=list(r.get("values") or []))
            for r in (spec.get("requirements") or [])
        ]),
        kubelet_configuration=KubeletConfiguration(
            cluster_dns=list((spec.get("kubeletConfiguration") or {})
                             .get("clusterDNS") or [])),
        provider=spec.get("provider"),
    )
    limits_res = (spec.get("limits") or {}).get("resources")
    status = manifest.get("status") or {}
    status_res = status.get("resources") or {}
    return Provisioner(
        status=ProvisionerStatus(
            conditions=[
                Condition(type=c.get("type", ""),
                          status=c.get("status", "Unknown"),
                          reason=c.get("reason", ""),
                          message=c.get("message", ""),
                          last_transition_time=_ts_from_lenient(
                              c.get("lastTransitionTime")))
                for c in (status.get("conditions") or [])
            ],
            resources=parse_resource_list(
                {k: str(v) for k, v in status_res.items()}),
            last_scale_time=_ts_from_lenient(status.get("lastScaleTime")),
        ),
        metadata=ObjectMeta(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            labels=dict(meta.get("labels") or {}),
            annotations=dict(meta.get("annotations") or {}),
            uid=meta.get("uid", ""),
        ),
        spec=ProvisionerSpec(
            constraints=constraints,
            ttl_seconds_after_empty=spec.get("ttlSecondsAfterEmpty"),
            ttl_seconds_until_expired=spec.get("ttlSecondsUntilExpired"),
            limits=Limits(resources=parse_resource_list(
                {k: str(v) for k, v in limits_res.items()}) if limits_res else None),
            consolidation_enabled=bool(spec.get("consolidation", {}).get("enabled"))
            if isinstance(spec.get("consolidation"), dict) else False,
        ),
    )


def provisioner_to_manifest(p: Provisioner) -> Dict[str, Any]:
    """Encode back to the CRD shape. Inverse of provisioner_from_manifest for
    every field the CRD declares (round-trip tested)."""
    c = p.spec.constraints
    spec: Dict[str, Any] = {}
    if c.labels:
        spec["labels"] = dict(c.labels)
    if c.taints:
        spec["taints"] = [
            {"key": t.key, **({"value": t.value} if t.value else {}),
             "effect": t.effect}
            for t in c.taints
        ]
    if len(c.requirements):
        # preserve value order: the defaulting webhook diffs original vs
        # round-tripped manifests, and normalizing here would patch every
        # user manifest even when no defaults applied
        spec["requirements"] = [
            {"key": r.key, "operator": r.operator, "values": list(r.values)}
            for r in c.requirements.items
        ]
    if c.kubelet_configuration.cluster_dns:
        spec["kubeletConfiguration"] = {
            "clusterDNS": list(c.kubelet_configuration.cluster_dns)}
    if c.provider is not None:
        spec["provider"] = c.provider
    if p.spec.ttl_seconds_after_empty is not None:
        spec["ttlSecondsAfterEmpty"] = p.spec.ttl_seconds_after_empty
    if p.spec.ttl_seconds_until_expired is not None:
        spec["ttlSecondsUntilExpired"] = p.spec.ttl_seconds_until_expired
    if p.spec.limits.resources:
        spec["limits"] = {"resources": {
            k: str(q) for k, q in p.spec.limits.resources.items()}}
    if p.spec.consolidation_enabled:
        spec["consolidation"] = {"enabled": True}
    manifest: Dict[str, Any] = {
        "apiVersion": API_VERSION,
        "kind": KIND,
        "metadata": {"name": p.metadata.name},
        "spec": spec,
    }
    # status is ALWAYS emitted, empty lists/maps included: _merge's removal
    # contract is "owned fields always present, even when empty" — omitting
    # an empty status made clearing the last condition or the resources map
    # inexpressible through update/_merge (advisor r4)
    manifest["status"] = {
        "conditions": [
            {"type": c.type, "status": c.status,
             **({"reason": c.reason} if c.reason else {}),
             **({"message": c.message} if c.message else {}),
             **({"lastTransitionTime": codec_core_ts_to(
                 c.last_transition_time)}
                if c.last_transition_time is not None else {})}
            for c in p.status.conditions
        ],
        "resources": {k: str(q) for k, q in p.status.resources.items()},
    }
    if p.status.last_scale_time is not None:
        # scalar + volatile: emitted when set (reference omitempty,
        # provisioner_status.go:27) — unlike the owned list/map fields
        # above, absence means "unset", not "cleared"
        manifest["status"]["lastScaleTime"] = codec_core_ts_to(
            p.status.last_scale_time)
    meta = manifest["metadata"]
    if p.metadata.namespace and p.metadata.namespace != "default":
        meta["namespace"] = p.metadata.namespace
    if p.metadata.labels:
        meta["labels"] = dict(p.metadata.labels)
    if p.metadata.annotations:
        meta["annotations"] = dict(p.metadata.annotations)
    if p.metadata.uid:
        meta["uid"] = p.metadata.uid
    return manifest
