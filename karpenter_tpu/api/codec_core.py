"""Core-object JSON codec: Kubernetes API JSON ⟷ the framework dataclasses.

Companion to api/codec.py (which handles the Provisioner CRD). Decodes the
subset of core/v1 + apps/v1 fields the controllers actually read, and
encodes everything the controllers write — used by the real API-server
client (runtime/kubeclient.py). Unknown fields are dropped on decode.
Encoders emit OWNED fields (the ones controllers mutate: labels,
annotations, finalizers, taints, unschedulable, …) unconditionally — even
when empty — and omit unmodeled ones; the client's read-merge-write
(kubeclient._merge) then overlays exactly the owned fields onto the
server's raw JSON, so foreign/server-owned fields are never erased while
owned-field removal (e.g. stripping a finalizer) still round-trips.

Reference shapes: k8s core/v1 (Pod, Node, ConfigMap, PVC, PV), apps/v1
(DaemonSet), storage.k8s.io/v1 (StorageClass) — the kinds the reference
watches/writes (SURVEY.md §2 rows 3-12, 19).
"""

from __future__ import annotations

import calendar
import time
from typing import Any, Dict, Optional

from karpenter_tpu.api.core import (
    Affinity, ConfigMap, Container, DaemonSet, DaemonSetSpec, LabelSelector,
    Lease, LeaseSpec,
    Node, NodeAffinity, NodeCondition, NodeSelectorRequirement,
    NodeSelectorTerm, NodeSpec, NodeStatus, ObjectMeta, OwnerReference,
    PersistentVolume, PersistentVolumeClaim, PersistentVolumeClaimSpec,
    PersistentVolumeClaimVolumeSource, PersistentVolumeSpec, Pod,
    PodCondition, PodSpec, PodStatus, PodTemplateSpec,
    PreferredSchedulingTerm, ResourceRequirements, Secret, StorageClass, Taint,
    Toleration, TopologySelectorTerm, TopologySpreadConstraint, Volume,
    VolumeNodeAffinity,
)
from karpenter_tpu.utils.resources import parse_resource_list

RFC3339 = "%Y-%m-%dT%H:%M:%SZ"


def ts_from(s: Optional[str]) -> Optional[float]:
    if not s:
        return None
    return float(calendar.timegm(time.strptime(s.split(".")[0].rstrip("Z") + "Z",
                                               RFC3339)))


def ts_to(t: Optional[float]) -> Optional[str]:
    if t is None:
        return None
    return time.strftime(RFC3339, time.gmtime(t))


# -- metadata ---------------------------------------------------------------

def meta_from(m: Dict[str, Any]) -> ObjectMeta:
    return ObjectMeta(
        name=m.get("name", ""),
        namespace=m.get("namespace", "default"),
        labels=dict(m.get("labels") or {}),
        annotations=dict(m.get("annotations") or {}),
        finalizers=list(m.get("finalizers") or []),
        owner_references=[
            OwnerReference(kind=o.get("kind", ""), name=o.get("name", ""),
                           controller=bool(o.get("controller")),
                           api_version=o.get("apiVersion", ""),
                           uid=o.get("uid", ""))
            for o in (m.get("ownerReferences") or [])
        ],
        deletion_timestamp=ts_from(m.get("deletionTimestamp")),
        creation_timestamp=ts_from(m.get("creationTimestamp")),
        resource_version=int(m.get("resourceVersion") or 0),
        uid=m.get("uid", ""),
    )


def meta_to(meta: ObjectMeta, cluster_scoped: bool = False) -> Dict[str, Any]:
    # labels/annotations/finalizers are OWNED fields: always emitted (even
    # empty) so the client's read-merge-write can express their removal —
    # an omitted key would be indistinguishable from "unmodeled, preserve"
    out: Dict[str, Any] = {
        "name": meta.name,
        "labels": dict(meta.labels),
        "annotations": dict(meta.annotations),
        "finalizers": list(meta.finalizers),
    }
    if not cluster_scoped:
        out["namespace"] = meta.namespace or "default"
    if meta.owner_references:
        # apiVersion/uid round-trip verbatim from decode — the server's
        # copy is authoritative (uid is REQUIRED server-side; inventing it
        # would make every update() of an owned object invalid). The
        # kind-based apiVersion guess remains only for locally-built refs
        # (tests/fixtures) that never hit a real API server.
        out["ownerReferences"] = [
            {"kind": o.kind, "name": o.name, "controller": o.controller,
             "apiVersion": o.api_version or (
                 "apps/v1" if o.kind == "DaemonSet" else "v1"),
             **({"uid": o.uid} if o.uid else {})}
            for o in meta.owner_references
        ]
    if meta.resource_version:
        out["resourceVersion"] = str(meta.resource_version)
    if meta.uid:
        out["uid"] = meta.uid
    return out


# -- shared fragments -------------------------------------------------------

def _req_from(r: Dict[str, Any]) -> NodeSelectorRequirement:
    return NodeSelectorRequirement(key=r.get("key", ""),
                                   operator=r.get("operator", "In"),
                                   values=list(r.get("values") or []))


def _req_to(r: NodeSelectorRequirement) -> Dict[str, Any]:
    return {"key": r.key, "operator": r.operator, "values": list(r.values)}


def _term_from(t: Dict[str, Any]) -> NodeSelectorTerm:
    return NodeSelectorTerm(
        match_expressions=[_req_from(r) for r in (t.get("matchExpressions") or [])],
        match_fields=[_req_from(r) for r in (t.get("matchFields") or [])],
    )


def _term_to(t: NodeSelectorTerm) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if t.match_expressions:
        out["matchExpressions"] = [_req_to(r) for r in t.match_expressions]
    if t.match_fields:
        out["matchFields"] = [_req_to(r) for r in t.match_fields]
    return out


def _selector_from(s: Optional[Dict[str, Any]]) -> Optional[LabelSelector]:
    if s is None:
        return None
    return LabelSelector(
        match_labels=dict(s.get("matchLabels") or {}),
        match_expressions=[_req_from(r) for r in (s.get("matchExpressions") or [])],
    )


def _selector_to(s: Optional[LabelSelector]) -> Optional[Dict[str, Any]]:
    if s is None:
        return None
    out: Dict[str, Any] = {}
    if s.match_labels:
        out["matchLabels"] = dict(s.match_labels)
    if s.match_expressions:
        out["matchExpressions"] = [_req_to(r) for r in s.match_expressions]
    return out


def _affinity_from(a: Optional[Dict[str, Any]]) -> Optional[Affinity]:
    if a is None:
        return None
    na = a.get("nodeAffinity")
    node_affinity = None
    if na is not None:
        required = na.get("requiredDuringSchedulingIgnoredDuringExecution")
        node_affinity = NodeAffinity(
            required=[_term_from(t) for t in required.get("nodeSelectorTerms") or []]
            if required else None,
            preferred=[
                PreferredSchedulingTerm(weight=int(p.get("weight", 1)),
                                        preference=_term_from(p.get("preference") or {}))
                for p in (na.get("preferredDuringSchedulingIgnoredDuringExecution") or [])
            ],
        )
    # pod (anti-)affinity is decoded only far enough for validation to
    # reject it (selection/controller.go:123-174 behavior)
    from karpenter_tpu.api.core import PodAffinity, PodAffinityTerm

    def pa_from(block):
        if block is None:
            return None
        return PodAffinity(required=[
            PodAffinityTerm(topology_key=t.get("topologyKey", ""),
                            label_selector=_selector_from(t.get("labelSelector")))
            for t in (block.get("requiredDuringSchedulingIgnoredDuringExecution") or [])
        ])

    return Affinity(node_affinity=node_affinity,
                    pod_affinity=pa_from(a.get("podAffinity")),
                    pod_anti_affinity=pa_from(a.get("podAntiAffinity")))


def _affinity_to(a: Optional[Affinity]) -> Optional[Dict[str, Any]]:
    if a is None or a.node_affinity is None:
        return None
    na = a.node_affinity
    out: Dict[str, Any] = {}
    if na.required is not None:
        out["requiredDuringSchedulingIgnoredDuringExecution"] = {
            "nodeSelectorTerms": [_term_to(t) for t in na.required]}
    if na.preferred:
        out["preferredDuringSchedulingIgnoredDuringExecution"] = [
            {"weight": p.weight, "preference": _term_to(p.preference)}
            for p in na.preferred
        ]
    return {"nodeAffinity": out}


def _resources_from(r: Optional[Dict[str, Any]]) -> ResourceRequirements:
    r = r or {}
    return ResourceRequirements(
        requests=parse_resource_list({k: str(v) for k, v in (r.get("requests") or {}).items()}),
        limits=parse_resource_list({k: str(v) for k, v in (r.get("limits") or {}).items()}),
    )


def _resources_to(r: ResourceRequirements) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if r.requests:
        out["requests"] = {k: str(q) for k, q in r.requests.items()}
    if r.limits:
        out["limits"] = {k: str(q) for k, q in r.limits.items()}
    return out


def _taint_from(t: Dict[str, Any]) -> Taint:
    return Taint(key=t.get("key", ""), value=t.get("value", ""),
                 effect=t.get("effect", "NoSchedule"))


def _taint_to(t: Taint) -> Dict[str, Any]:
    out = {"key": t.key, "effect": t.effect}
    if t.value:
        out["value"] = t.value
    return out


# -- Pod --------------------------------------------------------------------

def pod_spec_from(s: Dict[str, Any]) -> PodSpec:
    return PodSpec(
        node_name=s.get("nodeName", ""),
        node_selector=dict(s.get("nodeSelector") or {}),
        containers=[
            Container(name=c.get("name", "app"), image=c.get("image", ""),
                      resources=_resources_from(c.get("resources")))
            for c in (s.get("containers") or [])
        ],
        tolerations=[
            Toleration(key=t.get("key", ""), operator=t.get("operator", "Equal"),
                       value=t.get("value", ""), effect=t.get("effect", ""))
            for t in (s.get("tolerations") or [])
        ],
        affinity=_affinity_from(s.get("affinity")),
        topology_spread_constraints=[
            TopologySpreadConstraint(
                max_skew=int(c.get("maxSkew", 1)),
                topology_key=c.get("topologyKey", ""),
                when_unsatisfiable=c.get("whenUnsatisfiable", "DoNotSchedule"),
                label_selector=_selector_from(c.get("labelSelector")))
            for c in (s.get("topologySpreadConstraints") or [])
        ],
        volumes=[
            Volume(name=v.get("name", ""),
                   persistent_volume_claim=PersistentVolumeClaimVolumeSource(
                       claim_name=v["persistentVolumeClaim"].get("claimName", ""))
                   if v.get("persistentVolumeClaim") else None)
            for v in (s.get("volumes") or [])
        ],
        priority_class_name=s.get("priorityClassName", ""),
        priority=int(s.get("priority", 0) or 0),
        preemption_policy=s.get("preemptionPolicy", "PreemptLowerPriority"),
        # 0 is a valid, explicit "delete immediately" — only None defaults
        termination_grace_period_seconds=(
            30 if s.get("terminationGracePeriodSeconds") is None
            else int(s["terminationGracePeriodSeconds"])),
    )


def pod_spec_to(s: PodSpec) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if s.node_name:
        out["nodeName"] = s.node_name
    if s.node_selector:
        out["nodeSelector"] = dict(s.node_selector)
    if s.containers:
        out["containers"] = [
            {"name": c.name, **({"image": c.image} if c.image else {}),
             "resources": _resources_to(c.resources)}
            for c in s.containers
        ]
    if s.tolerations:
        out["tolerations"] = [
            {k: v for k, v in (("key", t.key), ("operator", t.operator),
                               ("value", t.value), ("effect", t.effect)) if v}
            for t in s.tolerations
        ]
    aff = _affinity_to(s.affinity)
    if aff:
        out["affinity"] = aff
    if s.topology_spread_constraints:
        out["topologySpreadConstraints"] = [
            {"maxSkew": c.max_skew, "topologyKey": c.topology_key,
             "whenUnsatisfiable": c.when_unsatisfiable,
             **({"labelSelector": _selector_to(c.label_selector)}
                if c.label_selector else {})}
            for c in s.topology_spread_constraints
        ]
    if s.volumes:
        out["volumes"] = [
            {"name": v.name,
             **({"persistentVolumeClaim": {"claimName": v.persistent_volume_claim.claim_name}}
                if v.persistent_volume_claim else {})}
            for v in s.volumes
        ]
    if s.priority_class_name:
        out["priorityClassName"] = s.priority_class_name
    if s.priority:
        out["priority"] = s.priority
    out["terminationGracePeriodSeconds"] = s.termination_grace_period_seconds
    return out


def pod_from(obj: Dict[str, Any]) -> Pod:
    status = obj.get("status") or {}
    pod = Pod(
        metadata=meta_from(obj.get("metadata") or {}),
        spec=pod_spec_from(obj.get("spec") or {}),
        status=PodStatus(
            phase=status.get("phase", "Pending"),
            conditions=[
                PodCondition(type=c.get("type", ""), status=c.get("status", ""),
                             reason=c.get("reason", ""))
                for c in (status.get("conditions") or [])
            ],
            nominated_node_name=status.get("nominatedNodeName", ""),
        ),
    )
    # Prime the solver marshal cache at ingest: the codec touches every pod
    # exactly once per watch event, so the per-pod resource-vector extraction
    # happens here — off the solve path — and the hot loop's marshal becomes
    # a cached gather (SURVEY.md §7 "including marshal of 50k pods").
    from karpenter_tpu.solver.adapter import pod_vector

    pod_vector(pod)
    return pod


def pod_to(p: Pod) -> Dict[str, Any]:
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": meta_to(p.metadata),
        "spec": pod_spec_to(p.spec),
        "status": {
            "phase": p.status.phase,
            **({"conditions": [
                {"type": c.type, "status": c.status,
                 **({"reason": c.reason} if c.reason else {})}
                for c in p.status.conditions]} if p.status.conditions else {}),
        },
    }


# -- Node -------------------------------------------------------------------

def node_from(obj: Dict[str, Any]) -> Node:
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    return Node(
        metadata=meta_from(obj.get("metadata") or {}),
        spec=NodeSpec(
            taints=[_taint_from(t) for t in (spec.get("taints") or [])],
            unschedulable=bool(spec.get("unschedulable")),
            provider_id=spec.get("providerID", ""),
        ),
        status=NodeStatus(
            capacity=parse_resource_list(
                {k: str(v) for k, v in (status.get("capacity") or {}).items()}),
            allocatable=parse_resource_list(
                {k: str(v) for k, v in (status.get("allocatable") or {}).items()}),
            conditions=[
                NodeCondition(type=c.get("type", ""), status=c.get("status", "Unknown"),
                              reason=c.get("reason", ""),
                              last_heartbeat_time=ts_from(c.get("lastHeartbeatTime")))
                for c in (status.get("conditions") or [])
            ],
        ),
    )


def node_to(n: Node) -> Dict[str, Any]:
    status: Dict[str, Any] = {}
    if n.status.capacity:
        status["capacity"] = {k: str(q) for k, q in n.status.capacity.items()}
    if n.status.allocatable:
        status["allocatable"] = {k: str(q) for k, q in n.status.allocatable.items()}
    if n.status.conditions:
        status["conditions"] = [
            {"type": c.type, "status": c.status,
             **({"reason": c.reason} if c.reason else {}),
             **({"lastHeartbeatTime": ts_to(c.last_heartbeat_time)}
                if c.last_heartbeat_time else {})}
            for c in n.status.conditions
        ]
    # taints/unschedulable are owned (cordon + not-ready lifecycle): always
    # emitted so removal survives the read-merge-write
    spec: Dict[str, Any] = {
        "taints": [_taint_to(t) for t in n.spec.taints],
        "unschedulable": n.spec.unschedulable,
    }
    if n.spec.provider_id:
        spec["providerID"] = n.spec.provider_id
    return {"apiVersion": "v1", "kind": "Node",
            "metadata": meta_to(n.metadata, cluster_scoped=True),
            "spec": spec, "status": status}


# -- other kinds ------------------------------------------------------------

def daemonset_from(obj: Dict[str, Any]) -> DaemonSet:
    template = ((obj.get("spec") or {}).get("template") or {})
    return DaemonSet(
        metadata=meta_from(obj.get("metadata") or {}),
        spec=DaemonSetSpec(template=PodTemplateSpec(
            metadata=meta_from(template.get("metadata") or {}),
            spec=pod_spec_from(template.get("spec") or {}))),
    )


def configmap_from(obj: Dict[str, Any]) -> ConfigMap:
    return ConfigMap(metadata=meta_from(obj.get("metadata") or {}),
                     data=dict(obj.get("data") or {}))


def configmap_to(cm: ConfigMap) -> Dict[str, Any]:
    return {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": meta_to(cm.metadata), "data": dict(cm.data)}


def secret_from(obj: Dict[str, Any]) -> Secret:
    return Secret(metadata=meta_from(obj.get("metadata") or {}),
                  data=dict(obj.get("data") or {}),
                  type=obj.get("type", "Opaque"))


def secret_to(s: Secret) -> Dict[str, Any]:
    return {"apiVersion": "v1", "kind": "Secret", "type": s.type,
            "metadata": meta_to(s.metadata), "data": dict(s.data)}


def lease_from(obj: Dict[str, Any]) -> Lease:
    spec = obj.get("spec") or {}
    return Lease(
        metadata=meta_from(obj.get("metadata") or {}),
        spec=LeaseSpec(
            holder_identity=spec.get("holderIdentity", "") or "",
            lease_duration_seconds=int(spec.get("leaseDurationSeconds") or 15),
            acquire_time=ts_from(spec.get("acquireTime")),
            renew_time=ts_from(spec.get("renewTime"))),
    )


def lease_to(lease: Lease) -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        "holderIdentity": lease.spec.holder_identity,
        "leaseDurationSeconds": lease.spec.lease_duration_seconds,
    }
    if lease.spec.acquire_time is not None:
        spec["acquireTime"] = ts_to(lease.spec.acquire_time)
    if lease.spec.renew_time is not None:
        spec["renewTime"] = ts_to(lease.spec.renew_time)
    else:
        spec["renewTime"] = None  # owned: an explicit release must round-trip
    return {"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
            "metadata": meta_to(lease.metadata), "spec": spec}


def pvc_from(obj: Dict[str, Any]) -> PersistentVolumeClaim:
    spec = obj.get("spec") or {}
    return PersistentVolumeClaim(
        metadata=meta_from(obj.get("metadata") or {}),
        spec=PersistentVolumeClaimSpec(
            storage_class_name=spec.get("storageClassName"),
            volume_name=spec.get("volumeName", "")),
    )


def pvc_to(pvc: PersistentVolumeClaim) -> Dict[str, Any]:
    spec: Dict[str, Any] = {}
    if pvc.spec.storage_class_name is not None:
        spec["storageClassName"] = pvc.spec.storage_class_name
    if pvc.spec.volume_name:
        spec["volumeName"] = pvc.spec.volume_name
    return {"apiVersion": "v1", "kind": "PersistentVolumeClaim",
            "metadata": meta_to(pvc.metadata), "spec": spec}


def daemonset_to(ds: DaemonSet) -> Dict[str, Any]:
    return {"apiVersion": "apps/v1", "kind": "DaemonSet",
            "metadata": meta_to(ds.metadata),
            "spec": {"template": {
                "metadata": meta_to(ds.spec.template.metadata),
                "spec": pod_spec_to(ds.spec.template.spec)}}}


def pv_to(pv: PersistentVolume) -> Dict[str, Any]:
    spec: Dict[str, Any] = {}
    if pv.spec.node_affinity is not None and pv.spec.node_affinity.required:
        spec["nodeAffinity"] = {"required": {"nodeSelectorTerms": [
            _term_to(t) for t in pv.spec.node_affinity.required]}}
    return {"apiVersion": "v1", "kind": "PersistentVolume",
            "metadata": meta_to(pv.metadata, cluster_scoped=True), "spec": spec}


def storageclass_to(sc: StorageClass) -> Dict[str, Any]:
    return {"apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
            "metadata": meta_to(sc.metadata, cluster_scoped=True),
            "allowedTopologies": [
                {"matchLabelExpressions": [
                    {"key": e.key, "values": list(e.values)}
                    for e in t.match_label_expressions]}
                for t in sc.allowed_topologies]}


def pv_from(obj: Dict[str, Any]) -> PersistentVolume:
    spec = obj.get("spec") or {}
    na = spec.get("nodeAffinity")
    return PersistentVolume(
        metadata=meta_from(obj.get("metadata") or {}),
        spec=PersistentVolumeSpec(node_affinity=VolumeNodeAffinity(
            required=[_term_from(t) for t in
                      (na.get("required") or {}).get("nodeSelectorTerms") or []])
            if na else None),
    )


def storageclass_from(obj: Dict[str, Any]) -> StorageClass:
    return StorageClass(
        metadata=meta_from(obj.get("metadata") or {}),
        allowed_topologies=[
            TopologySelectorTerm(match_label_expressions=[
                NodeSelectorRequirement(key=e.get("key", ""), operator="In",
                                        values=list(e.get("values") or []))
                for e in (t.get("matchLabelExpressions") or [])
            ])
            for t in (obj.get("allowedTopologies") or [])
        ],
    )


# -- dispatch ---------------------------------------------------------------

DECODERS = {
    "Secret": secret_from,
    "Lease": lease_from,
    "Pod": pod_from,
    "Node": node_from,
    "DaemonSet": daemonset_from,
    "ConfigMap": configmap_from,
    "PersistentVolumeClaim": pvc_from,
    "PersistentVolume": pv_from,
    "StorageClass": storageclass_from,
}

ENCODERS = {
    "Secret": secret_to,
    "Lease": lease_to,
    "Pod": pod_to,
    "Node": node_to,
    "ConfigMap": configmap_to,
    "PersistentVolumeClaim": pvc_to,
    "DaemonSet": daemonset_to,
    "PersistentVolume": pv_to,
    "StorageClass": storageclass_to,
}


def decode(kind: str, obj: Dict[str, Any]):
    out = DECODERS[kind](obj)
    if kind == "Node":
        # cluster-scoped; the framework's store convention is namespace ""
        out.metadata.namespace = ""
    return out


def encode_obj(obj) -> Dict[str, Any]:
    return ENCODERS[obj.kind](obj)
