"""Constraints, Taints and Limits — the Provisioner's scheduling algebra.

Reference: pkg/apis/provisioning/v1alpha5/{constraints.go,taints.go,limits.go}.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from karpenter_tpu.api.core import Pod, Taint
from karpenter_tpu.api.requirements import Requirements, pod_requirements
from karpenter_tpu.utils.resources import ResourceList


class SchedulingError(Exception):
    """Pod requirements incompatible with constraints."""


class Taints(list):
    """Decorated list of Taint (taints.go:24-78)."""

    def with_pod(self, pod: Pod) -> "Taints":
        """Generate per-node taints matching pod tolerations (taints.go:27-53).
        Only Equal tolerations generate taints; empty effect taints both
        NoSchedule and NoExecute."""
        ts = Taints(self)
        for toleration in pod.spec.tolerations:
            if toleration.operator != "Equal":
                continue
            if toleration.effect:
                generated = [Taint(key=toleration.key, value=toleration.value, effect=toleration.effect)]
            else:
                generated = [
                    Taint(key=toleration.key, value=toleration.value, effect="NoSchedule"),
                    Taint(key=toleration.key, value=toleration.value, effect="NoExecute"),
                ]
            for taint in generated:
                if not ts.has(taint):
                    ts.append(taint)
        return ts

    def has(self, taint: Taint) -> bool:
        """True if a taint with the same key+effect exists (taints.go:56-63)."""
        return any(t.key == taint.key and t.effect == taint.effect for t in self)

    def tolerates(self, pod: Pod) -> List[str]:
        """Errors for every taint the pod does not tolerate (taints.go:66-78).
        Empty list means tolerated."""
        errs = []
        for taint in self:
            if not any(t.tolerates_taint(taint) for t in pod.spec.tolerations):
                errs.append(f"did not tolerate {taint.key}={taint.value}:{taint.effect}")
        return errs


@dataclass
class Limits:
    """Resource ceilings per Provisioner (limits.go:23-41)."""

    resources: Optional[ResourceList] = None

    def exceeded_by(self, usage: ResourceList) -> Optional[str]:
        if not self.resources:
            return None
        for name, used in usage.items():
            limit = self.resources.get(name)
            if limit is not None and used.cmp(limit) >= 0:
                return f"{name} resource usage of {used} exceeds limit of {limit}"
        return None


@dataclass
class KubeletConfiguration:
    cluster_dns: List[str] = field(default_factory=list)


@dataclass
class Constraints:
    """Node constraints applied by a Provisioner (constraints.go:24-43)."""

    labels: Dict[str, str] = field(default_factory=dict)
    taints: Taints = field(default_factory=Taints)
    requirements: Requirements = field(default_factory=Requirements)
    kubelet_configuration: KubeletConfiguration = field(default_factory=KubeletConfiguration)
    # Cloud-provider vendor block (spec.provider RawExtension equivalent):
    # opaque to the core, round-tripped by the provider's codec.
    provider: Optional[Dict[str, Any]] = None

    def validate_pod(self, pod: Pod) -> Optional[str]:
        """Error if pod requirements are unmet (constraints.go:46-66)."""
        errs = self.taints.tolerates(pod)
        if errs:
            return errs[0]
        podreqs = pod_requirements(pod)
        for key in podreqs.keys():
            own = self.requirements.requirement(key)
            if own is None or len(own) == 0:
                return f"invalid nodeSelector {key!r}, {sorted(podreqs.requirement(key) or [])} not in {sorted(own or [])}"
        combined = self.requirements.add(*podreqs.items)
        for key in podreqs.keys():
            if len(combined.requirement(key) or ()) == 0:
                return f"invalid nodeSelector {key!r}, {sorted(podreqs.requirement(key) or [])} not in {sorted(self.requirements.requirement(key) or [])}"
        return None

    def tighten(self, pod: Pod) -> "Constraints":
        """Constraints ∧ pod requirements, consolidated, well-known-only
        (constraints.go:68-76)."""
        return Constraints(
            labels=self.labels,
            taints=self.taints,
            requirements=self.requirements.add(*pod_requirements(pod).items).consolidate().well_known(),
            kubelet_configuration=self.kubelet_configuration,
            provider=self.provider,
        )

    def deepcopy(self) -> "Constraints":
        return copy.deepcopy(self)
