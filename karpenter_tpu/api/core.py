"""Minimal Kubernetes core object model.

The reference builds on k8s.io/api types. This framework keeps a small,
typed, deep-copyable object model with exactly the fields Karpenter's logic
reads/writes: metadata (labels/annotations/finalizers/deletionTimestamp),
PodSpec scheduling fields, NodeSpec taints, statuses, and the storage trio
(PVC/PV/StorageClass). Everything is a dataclass; the in-memory API server
(karpenter_tpu/runtime/kubecore.py) gives them watch/patch/optimistic-
concurrency semantics.
"""

from __future__ import annotations

import copy
import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karpenter_tpu.utils.resources import ResourceList, parse_resource_list


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    finalizers: List[str] = field(default_factory=list)
    owner_references: List["OwnerReference"] = field(default_factory=list)
    deletion_timestamp: Optional[float] = None
    creation_timestamp: Optional[float] = None
    resource_version: int = 0
    uid: str = ""


@dataclass
class OwnerReference:
    kind: str = ""
    name: str = ""
    controller: bool = False
    # server-assigned identity, round-tripped verbatim: a real API server
    # REQUIRES uid on ownerReferences, so an update that re-sends refs with
    # a fabricated uid is rejected (or corrupts GC linkage)
    api_version: str = ""
    uid: str = ""


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" matches all effects

    def tolerates_taint(self, taint: "Taint") -> bool:
        """k8s core/v1 Toleration.ToleratesTaint semantics."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == "Exists":
            # k8s: Exists tolerations must not carry a value
            return self.value == ""
        if self.operator in ("", "Equal"):
            return self.value == taint.value
        return False


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass
class NodeSelectorRequirement:
    key: str = ""
    operator: str = "In"  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: List[str] = field(default_factory=list)


@dataclass
class NodeSelectorTerm:
    match_expressions: List[NodeSelectorRequirement] = field(default_factory=list)
    match_fields: List[NodeSelectorRequirement] = field(default_factory=list)


@dataclass
class PreferredSchedulingTerm:
    weight: int = 1
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass
class NodeAffinity:
    required: Optional[List[NodeSelectorTerm]] = None  # RequiredDuringScheduling terms
    preferred: List[PreferredSchedulingTerm] = field(default_factory=list)


@dataclass
class PodAffinityTerm:
    topology_key: str = ""
    label_selector: Optional["LabelSelector"] = None


@dataclass
class WeightedPodAffinityTerm:
    """PreferredDuringSchedulingIgnoredDuringExecution entry: a soft
    (anti-)affinity term scored with ``weight`` (kube range 1-100)."""

    weight: int = 1
    term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass
class PodAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAffinity] = None


@dataclass
class LabelSelector:
    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[NodeSelectorRequirement] = field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for expr in self.match_expressions:
            val = labels.get(expr.key)
            if expr.operator == "In":
                if val not in expr.values:
                    return False
            elif expr.operator == "NotIn":
                if val in expr.values:
                    return False
            elif expr.operator == "Exists":
                if expr.key not in labels:
                    return False
            elif expr.operator == "DoesNotExist":
                if expr.key in labels:
                    return False
        return True


@dataclass
class TopologySpreadConstraint:
    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = "DoNotSchedule"
    label_selector: Optional[LabelSelector] = None


@dataclass
class ResourceRequirements:
    requests: ResourceList = field(default_factory=dict)
    limits: ResourceList = field(default_factory=dict)

    @staticmethod
    def make(requests=None, limits=None) -> "ResourceRequirements":
        return ResourceRequirements(
            requests=parse_resource_list(requests), limits=parse_resource_list(limits)
        )


@dataclass
class Container:
    name: str = "app"
    image: str = ""
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)


@dataclass
class PersistentVolumeClaimVolumeSource:
    claim_name: str = ""


@dataclass
class Volume:
    name: str = ""
    persistent_volume_claim: Optional[PersistentVolumeClaimVolumeSource] = None


@dataclass
class PodSpec:
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    containers: List[Container] = field(default_factory=list)
    tolerations: List[Toleration] = field(default_factory=list)
    affinity: Optional[Affinity] = None
    topology_spread_constraints: List[TopologySpreadConstraint] = field(default_factory=list)
    volumes: List[Volume] = field(default_factory=list)
    priority_class_name: str = ""
    priority: int = 0  # resolved priority value (admission stamps it from the class)
    preemption_policy: str = "PreemptLowerPriority"
    termination_grace_period_seconds: int = 30


@dataclass
class PodCondition:
    type: str = ""
    status: str = ""  # True | False | Unknown
    reason: str = ""


@dataclass
class PodStatus:
    phase: str = "Pending"
    conditions: List[PodCondition] = field(default_factory=list)
    nominated_node_name: str = ""


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)
    kind: str = "Pod"


@dataclass
class NodeSpec:
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False
    provider_id: str = ""


@dataclass
class NodeCondition:
    type: str = ""
    status: str = "Unknown"
    reason: str = ""
    last_heartbeat_time: Optional[float] = None


@dataclass
class NodeStatus:
    capacity: ResourceList = field(default_factory=dict)
    allocatable: ResourceList = field(default_factory=dict)
    conditions: List[NodeCondition] = field(default_factory=list)


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)
    kind: str = "Node"


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


@dataclass
class DaemonSetSpec:
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class DaemonSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DaemonSetSpec = field(default_factory=DaemonSetSpec)
    kind: str = "DaemonSet"


@dataclass
class LeaseSpec:
    holder_identity: str = ""
    lease_duration_seconds: int = 15
    acquire_time: Optional[float] = None
    renew_time: Optional[float] = None


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease — leader election's backing object."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LeaseSpec = field(default_factory=LeaseSpec)
    kind: str = "Lease"


@dataclass
class ConfigMap:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)
    kind: str = "ConfigMap"


@dataclass
class Secret:
    """v1 Secret; ``data`` values are base64-encoded strings (wire form).
    Backs the webhook serving certificate (cmd/webhook/main.go:49,57 —
    knative's certificates controller persists its CA + serving pair the
    same way)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)
    type: str = "Opaque"
    kind: str = "Secret"


@dataclass
class PersistentVolumeClaimSpec:
    storage_class_name: Optional[str] = None
    volume_name: str = ""


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeClaimSpec = field(default_factory=PersistentVolumeClaimSpec)
    kind: str = "PersistentVolumeClaim"


@dataclass
class VolumeNodeAffinity:
    required: Optional[List[NodeSelectorTerm]] = None


@dataclass
class PersistentVolumeSpec:
    node_affinity: Optional[VolumeNodeAffinity] = None


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeSpec = field(default_factory=PersistentVolumeSpec)
    kind: str = "PersistentVolume"


@dataclass
class TopologySelectorTerm:
    match_label_expressions: List[NodeSelectorRequirement] = field(default_factory=list)


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    allowed_topologies: List[TopologySelectorTerm] = field(default_factory=list)
    kind: str = "StorageClass"


@dataclass
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    # IntOrString, like the real API: an integer count or a percentage
    # string ("50%") resolved against the PDB's expectedPods at eviction
    # time (runtime/kubecore.py evict_pod). Setting both is the same
    # misconfiguration it is upstream and 500s the eviction.
    min_available: Optional[object] = None  # int | "N%"
    max_unavailable: Optional[object] = None  # int | "N%"
    kind: str = "PodDisruptionBudget"


def deepcopy_obj(obj):
    return copy.deepcopy(obj)


def is_dataclass_obj(obj) -> bool:
    return dataclasses.is_dataclass(obj) and not isinstance(obj, type)
