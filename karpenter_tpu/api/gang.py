"""Gang (pod-group) label contract: parsing + slice-shape compatibility.

A *gang* is a set of pods that must bind all-or-nothing (Tesserae's atomic
multi-pod DL jobs, ROADMAP item 1). Membership is declared with labels:

    karpenter.sh/pod-group:       <name>     group identity (per namespace)
    karpenter.sh/pod-group-size:  <int>      full membership count (>= 1)
    karpenter.sh/pod-group-slice: v5e-4x4    optional TPU slice shape

The slice shape constrains *which offerings may host the gang*: an instance
type is slice-compatible when it advertises a TPU topology
(``InstanceType.tpu_topology``) of the same accelerator family whose grid
contains the requested grid (every sorted dimension >=, e.g. a v5e-4x8 host
can carve a v5e-4x4 slice, a v5e-2x2 host cannot). Compatibility is pure
shape algebra here; the columnar mask over a whole catalog lives in
:func:`karpenter_tpu.ops.feasibility.gang_feasibility_mask`.

Malformed declarations (unparseable size, bad slice syntax) do NOT silently
demote the pod to a singleton — that would break the all-or-nothing promise
for its siblings. They parse to a :class:`GangSpec` with ``error`` set and
the scheduler refuses the pod with ``reason=gang``.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from itertools import product
from typing import Iterator, Optional, Sequence, Tuple

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.core import Pod

# "v5e-4x4", "v4-2x2x4": family token, then an 'x'-separated integer grid
_SLICE_RE = re.compile(r"^([a-z][a-z0-9]*)-(\d+(?:x\d+)*)$")

# gangs larger than this are refused at parse time (a window could never
# hold them and the batcher would sit on the partial group until TTL)
MAX_GANG_SIZE = 4096


@dataclass(frozen=True)
class SliceShape:
    """A TPU slice topology: accelerator family + dimension grid."""

    family: str          # "v5e", "v4", ...
    dims: Tuple[int, ...]  # ("4x4" → (4, 4)); never empty

    @property
    def chips(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    @property
    def grid(self) -> Tuple[int, ...]:
        """The chip grid this shape spans — the torus the carving engine
        (ops/topology.py) models. An alias of ``dims`` with the physical
        reading made explicit: axis i has ``dims[i]`` chips and its ICI
        links wrap (TPU pods close every axis into a ring)."""
        return self.dims

    def coords(self) -> Iterator[Tuple[int, ...]]:
        """Every chip coordinate of the grid in row-major order — the cell
        enumeration the occupancy bit-planes flatten over."""
        return product(*(range(d) for d in self.dims))

    def flat_index(self, coord: Sequence[int]) -> int:
        """Row-major flat cell index of one chip coordinate (the inverse of
        the ``coords()`` enumeration order)."""
        idx = 0
        for c, d in zip(coord, self.dims):
            idx = idx * d + (c % d)
        return idx

    def __str__(self) -> str:
        return f"{self.family}-" + "x".join(str(d) for d in self.dims)


def parse_slice_shape(text: str) -> Optional[SliceShape]:
    """``"v5e-4x4"`` → SliceShape; None for anything malformed (empty,
    missing grid, zero dimension)."""
    m = _SLICE_RE.match(text.strip())
    if not m:
        return None
    dims = tuple(int(d) for d in m.group(2).split("x"))
    if not dims or any(d <= 0 for d in dims):
        return None
    return SliceShape(family=m.group(1), dims=dims)


def slice_fits(host: Optional[SliceShape], requested: SliceShape) -> bool:
    """True when a host topology can carve the requested slice: same family
    and the requested grid fits inside the host grid. Grids compare sorted
    descending, the shorter one padded with 1s — a (4,4) request fits a
    (4,4,2) host; orientation does not matter for containment here."""
    if host is None or host.family != requested.family:
        return False
    h = sorted(host.dims, reverse=True)
    r = sorted(requested.dims, reverse=True)
    n = max(len(h), len(r))
    h += [1] * (n - len(h))
    r += [1] * (n - len(r))
    return all(rd <= hd for rd, hd in zip(r, h))


def instance_slice_shape(it) -> Optional[SliceShape]:
    """The TPU topology an instance type advertises, parsed once and cached
    on the instance (same idiom as the marshal/feasibility tokens). Empty
    ``tpu_topology`` → None: the type hosts no slice-shaped gangs."""
    cached = it.__dict__.get("_slice_shape", False)
    if cached is not False:
        return cached
    topo = getattr(it, "tpu_topology", "") or ""
    shape = parse_slice_shape(topo) if topo else None
    it.__dict__["_slice_shape"] = shape
    return shape


@dataclass(frozen=True)
class GangSpec:
    """Parsed gang membership of one pod. ``key`` identifies the gang
    (namespace-scoped); equal keys must agree on size/slice — the scheduler
    folds the full spec into the group key, so a disagreeing member lands
    in its own (forever-incomplete) group rather than corrupting the gang."""

    namespace: str
    name: str
    size: int
    slice_: Optional[SliceShape] = None
    error: Optional[str] = None

    @property
    def key(self) -> Tuple[str, str]:
        return (self.namespace, self.name)

    @property
    def group_part(self) -> tuple:
        """The structural tail appended to the scheduler group key."""
        return ("gang", self.namespace, self.name, self.size,
                str(self.slice_) if self.slice_ else "")


def gang_of(pod: Pod) -> Optional[GangSpec]:
    """The pod's gang declaration, or None for a plain pod. Cached on the
    pod (labels are immutable through the scheduling path). A malformed
    declaration returns a spec with ``error`` set, never None."""
    cached = pod.__dict__.get("_gang_spec", False)
    if cached is not False:
        return cached
    spec = _parse_gang(pod)
    pod.__dict__["_gang_spec"] = spec
    return spec


def _parse_gang(pod: Pod) -> Optional[GangSpec]:
    labels = pod.metadata.labels or {}
    name = labels.get(wellknown.POD_GROUP_LABEL)
    if name is None:
        return None
    ns = pod.metadata.namespace
    raw_size = labels.get(wellknown.POD_GROUP_SIZE_LABEL, "")
    try:
        size = int(raw_size)
    except (TypeError, ValueError):
        return GangSpec(ns, name, 0,
                        error=f"invalid {wellknown.POD_GROUP_SIZE_LABEL}="
                              f"{raw_size!r} (want an integer)")
    if size < 1 or size > MAX_GANG_SIZE:
        return GangSpec(ns, name, 0,
                        error=f"gang size {size} out of range "
                              f"[1, {MAX_GANG_SIZE}]")
    slice_ = None
    raw_slice = labels.get(wellknown.POD_GROUP_SLICE_LABEL)
    if raw_slice:
        slice_ = parse_slice_shape(raw_slice)
        if slice_ is None:
            return GangSpec(ns, name, size,
                            error=f"invalid {wellknown.POD_GROUP_SLICE_LABEL}="
                                  f"{raw_slice!r} (want e.g. 'v5e-4x4')")
    return GangSpec(ns, name, size, slice_)
