"""The Provisioner custom resource.

Reference: pkg/apis/provisioning/v1alpha5/{provisioner.go,provisioner_status.go}.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from karpenter_tpu.api.constraints import Constraints, Limits
from karpenter_tpu.api.core import ObjectMeta
from karpenter_tpu.utils.resources import ResourceList


@dataclass
class ProvisionerSpec:
    constraints: Constraints = field(default_factory=Constraints)
    # Seconds after a node is empty (only daemonset/static pods) before it is
    # deleted; None disables emptiness deprovisioning (provisioner.go:36-41).
    ttl_seconds_after_empty: Optional[int] = None
    # Seconds after creation before a node is expired and recycled; None
    # disables expiry (provisioner.go:43-50).
    ttl_seconds_until_expired: Optional[int] = None
    limits: Limits = field(default_factory=Limits)
    # Actively drain under-utilized nodes whose pods fit elsewhere (a
    # capability beyond the reference, which only reaps empty nodes —
    # models/consolidate.py). Off by default: it evicts running pods.
    consolidation_enabled: bool = False


@dataclass
class Condition:
    """Status condition (provisioner_status.go:25-36; the reference keeps a
    living `Active` condition set via register.go:51-54)."""

    type: str = ""
    status: str = "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: Optional[float] = None


def set_condition(conditions: List[Condition], type: str, status: str,
                  reason: str = "", message: str = "",
                  now: Optional[float] = None) -> bool:
    """Upsert a condition in place; returns True when anything (other than
    the transition timestamp) changed — callers skip the status write when
    nothing did, so a condition refresh can't create a watch-event loop."""
    for c in conditions:
        if c.type == type:
            if (c.status, c.reason, c.message) == (status, reason, message):
                return False
            if c.status != status:
                c.last_transition_time = now
            c.status, c.reason, c.message = status, reason, message
            return True
    conditions.append(Condition(type=type, status=status, reason=reason,
                                message=message, last_transition_time=now))
    return True


def get_condition(conditions: List[Condition], type: str) -> Optional[Condition]:
    for c in conditions:
        if c.type == type:
            return c
    return None


@dataclass
class ProvisionerStatus:
    last_scale_time: Optional[float] = None
    conditions: List[Condition] = field(default_factory=list)
    # Aggregated capacity of this provisioner's nodes, maintained by the
    # counter controller and consumed by the limits check.
    resources: ResourceList = field(default_factory=dict)


@dataclass
class Provisioner:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ProvisionerSpec = field(default_factory=ProvisionerSpec)
    status: ProvisionerStatus = field(default_factory=ProvisionerStatus)
    kind: str = "Provisioner"
