"""Requirements: the node-selector constraint algebra.

Host reference implementation of the set semantics in
pkg/apis/provisioning/v1alpha5/requirements.go. A requirement list evaluates,
per key, to ``(∩ of all In sets) ∖ (∪ of all NotIn sets)``; ``None`` means
"unconstrained". The vectorized (interned bitset) twin of this algebra is
karpenter_tpu/ops/feasibility.py, property-tested against this module in
tests/test_feasibility.py; this module is the oracle, and any semantic
change here must be mirrored there (docs/scheduling.md specifies the
encoding and its quirk-preservation obligations).
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Iterable, List, Optional

from karpenter_tpu.api.core import NodeSelectorRequirement, Pod
from karpenter_tpu.api import wellknown

IN = "In"
NOT_IN = "NotIn"


class Requirements:
    """Decorated list of NodeSelectorRequirements (requirements.go:73-74)."""

    __slots__ = ("items",)

    def __init__(self, items: Optional[Iterable[NodeSelectorRequirement]] = None):
        self.items: List[NodeSelectorRequirement] = list(items or [])

    # -- construction -------------------------------------------------------
    def add(self, *reqs: NodeSelectorRequirement) -> "Requirements":
        """Append normalized requirements, returning a new list
        (requirements.go:96-98)."""
        return Requirements(self.items + Requirements(reqs).normalize().items)

    def normalize(self) -> "Requirements":
        """Translate aliased label keys to well-known ones
        (requirements.go:101-111)."""
        out = []
        for r in self.items:
            key = wellknown.NORMALIZED_LABELS.get(r.key, r.key)
            out.append(NodeSelectorRequirement(key=key, operator=r.operator, values=list(r.values)))
        return Requirements(out)

    def consolidate(self) -> "Requirements":
        """Collapse to one In requirement per key (requirements.go:119-128).
        A NotIn with no In collapses to [] permanently — quirk preserved."""
        out = Requirements()
        for key in self.keys():
            out = out.add(NodeSelectorRequirement(
                key=key, operator=IN, values=sorted(self.requirement(key) or set())))
        return out

    def well_known(self) -> "Requirements":
        """Keep only well-known keys (requirements.go:157-164)."""
        out = Requirements()
        for r in self.items:
            if r.key in wellknown.WELL_KNOWN_LABELS:
                out = out.add(r)
        return out

    # -- evaluation ---------------------------------------------------------
    def keys(self) -> List[str]:
        seen = []
        for r in self.items:
            if r.key not in seen:
                seen.append(r.key)
        return seen

    def requirement(self, key: str) -> Optional[FrozenSet[str]]:
        """Allowed values for key: (∩ In) ∖ (∪ NotIn); None if unconstrained
        (requirements.go:176-195)."""
        result: Optional[set] = None
        for r in self.items:
            if r.key == key and r.operator == IN:
                vals = set(r.values)
                result = vals if result is None else (result & vals)
        for r in self.items:
            if r.key == key and r.operator == NOT_IN:
                # Go quirk: nil.Difference(x) returns a non-nil empty set, so
                # a NotIn with no In collapses to "nothing allowed", not
                # "unconstrained" (requirements.go:189-194).
                result = (result or set()) - set(r.values)
        return frozenset(result) if result is not None else None

    # -- well-known accessors (requirements.go:76-94) -----------------------
    def zones(self) -> Optional[FrozenSet[str]]:
        return self.requirement(wellknown.LABEL_TOPOLOGY_ZONE)

    def instance_types(self) -> Optional[FrozenSet[str]]:
        return self.requirement(wellknown.LABEL_INSTANCE_TYPE)

    def architectures(self) -> Optional[FrozenSet[str]]:
        return self.requirement(wellknown.LABEL_ARCH)

    def operating_systems(self) -> Optional[FrozenSet[str]]:
        return self.requirement(wellknown.LABEL_OS)

    def capacity_types(self) -> Optional[FrozenSet[str]]:
        return self.requirement(wellknown.LABEL_CAPACITY_TYPE)

    def __iter__(self):
        return iter(self.items)

    def __len__(self):
        return len(self.items)

    def __repr__(self):
        return f"Requirements({[(r.key, r.operator, r.values) for r in self.items]})"


def has_value(s: Optional[FrozenSet[str]], value: str) -> bool:
    """Membership against a possibly-unconstrained (None) requirement set.

    Go's sets.String.Has(nil) is false; callers in the reference always
    materialize the full universe before querying, so None here means
    "no constraint" only at sites that treat it so explicitly. We keep the
    strict Go behavior: None → False.
    """
    return s is not None and value in s


def label_requirements(labels: Dict[str, str]) -> Requirements:
    """Labels as In requirements (requirements.go:130-135)."""
    r = Requirements()
    for key, value in labels.items():
        r = r.add(NodeSelectorRequirement(key=key, operator=IN, values=[value]))
    return r


def pod_requirements(pod: Pod) -> Requirements:
    """Extract scheduling requirements from a pod (requirements.go:137-155):
    nodeSelector + heaviest preferred term + first required term."""
    r = Requirements()
    for key, value in pod.spec.node_selector.items():
        r = r.add(NodeSelectorRequirement(key=key, operator=IN, values=[value]))
    affinity = pod.spec.affinity
    if affinity is None or affinity.node_affinity is None:
        return r
    na = affinity.node_affinity
    if na.preferred:
        heaviest = max(na.preferred, key=lambda t: t.weight)
        r = r.add(*heaviest.preference.match_expressions)
    if na.required:
        r = r.add(*na.required[0].match_expressions)
    return r
