"""Well-known labels, annotations and domains.

Reference: pkg/apis/provisioning/v1alpha5/{requirements.go:24-71,register.go:43-47}.
"""

from __future__ import annotations

# k8s node labels
LABEL_TOPOLOGY_ZONE = "topology.kubernetes.io/zone"
LABEL_INSTANCE_TYPE = "node.kubernetes.io/instance-type"
LABEL_ARCH = "kubernetes.io/arch"
LABEL_OS = "kubernetes.io/os"
LABEL_HOSTNAME = "kubernetes.io/hostname"

# legacy/beta aliases
LABEL_FAILURE_DOMAIN_BETA_ZONE = "failure-domain.beta.kubernetes.io/zone"
LABEL_BETA_ARCH = "beta.kubernetes.io/arch"
LABEL_BETA_OS = "beta.kubernetes.io/os"
LABEL_BETA_INSTANCE_TYPE = "beta.kubernetes.io/instance-type"

ARCHITECTURE_AMD64 = "amd64"
ARCHITECTURE_ARM64 = "arm64"
OPERATING_SYSTEM_LINUX = "linux"

# karpenter domain (register.go:43-47)
KARPENTER_DOMAIN = "karpenter.sh"
PROVISIONER_NAME_LABEL = KARPENTER_DOMAIN + "/provisioner-name"
NOT_READY_TAINT_KEY = KARPENTER_DOMAIN + "/not-ready"
DO_NOT_EVICT_ANNOTATION = KARPENTER_DOMAIN + "/do-not-evict"
EMPTINESS_TIMESTAMP_ANNOTATION = KARPENTER_DOMAIN + "/emptiness-timestamp"
TERMINATION_FINALIZER = KARPENTER_DOMAIN + "/termination"
LABEL_CAPACITY_TYPE = KARPENTER_DOMAIN + "/capacity-type"
# provider tag stamped atomically at launch (before any Node exists) so a
# leaked instance is attributable to the exact launch that leaked it —
# the GC controller logs it when terminating orphans
LAUNCH_NONCE_TAG = KARPENTER_DOMAIN + "/launch-nonce"
# operator-defined placement domain: a topology key for pod-(anti-)affinity
# whose vocabulary comes from the provisioner's own requirements
# (scheduling/affinity.py) — well-known so tighten() keeps its pin
LABEL_NODE_GROUP = KARPENTER_DOMAIN + "/node-group"

CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_ON_DEMAND = "on-demand"

# Gang scheduling labels (ROADMAP item 1 / Tesserae): pods carrying the same
# pod-group value (within one namespace) bind all-or-nothing. pod-group-size
# declares the full membership count — the batcher holds the group until
# that many members are queued (or a TTL expires). pod-group-slice
# optionally names a TPU slice shape ("v5e-4x4"): only instance types whose
# topology contains that shape may host the group (api/gang.py).
POD_GROUP_LABEL = KARPENTER_DOMAIN + "/pod-group"
POD_GROUP_SIZE_LABEL = KARPENTER_DOMAIN + "/pod-group-size"
POD_GROUP_SLICE_LABEL = KARPENTER_DOMAIN + "/pod-group-slice"

WELL_KNOWN_LABELS = frozenset({
    LABEL_TOPOLOGY_ZONE,
    LABEL_INSTANCE_TYPE,
    LABEL_ARCH,
    LABEL_OS,
    LABEL_CAPACITY_TYPE,
    LABEL_HOSTNAME,  # used internally for hostname topology spread
    LABEL_NODE_GROUP,  # topology-keyed affinity domain (affinity.py)
})

# NormalizedLabels (requirements.go:65-70): aliased concepts → well-known
NORMALIZED_LABELS = {
    LABEL_FAILURE_DOMAIN_BETA_ZONE: LABEL_TOPOLOGY_ZONE,
    LABEL_BETA_ARCH: LABEL_ARCH,
    LABEL_BETA_OS: LABEL_OS,
    LABEL_BETA_INSTANCE_TYPE: LABEL_INSTANCE_TYPE,
}

# Restricted label machinery (requirements.go:29-50)
RESTRICTED_LABELS = frozenset({EMPTINESS_TIMESTAMP_ANNOTATION, LABEL_HOSTNAME})
ALLOWED_LABEL_DOMAINS = frozenset({"kops.k8s.io"})
RESTRICTED_LABEL_DOMAINS = frozenset({"kubernetes.io", "k8s.io", KARPENTER_DOMAIN})
