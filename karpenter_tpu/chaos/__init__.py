"""Deterministic fault injection for crash-safety testing.

The :mod:`karpenter_tpu.chaos.inject` module holds a seeded
:class:`~karpenter_tpu.chaos.inject.FaultPlan` plus thin shims for the three
trust boundaries the control plane crosses — the kube apiserver
(:class:`~karpenter_tpu.chaos.inject.ChaosKube`), the cloud SDK
(:class:`~karpenter_tpu.chaos.inject.ChaosEC2`), and the device solver (a
hook inside the solver watchdog). Production code only ever touches the
module through :func:`~karpenter_tpu.chaos.inject.active_fault`, which is a
single ``None`` check when no plan is installed.
"""
