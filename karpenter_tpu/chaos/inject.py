"""Seeded, deterministic fault injection across the control plane's
trust boundaries.

A :class:`FaultPlan` compiles a list of :class:`FaultSpec` triples
(boundary × op × fault kind, with a trigger count) into per-stream fire
maps: for every call stream — a ``(boundary, op)`` pair such as
``("provider", "create")`` or ``("kube", "patch")`` — the plan draws the
call indices at which each fault fires from ``random.Random(seed)``.

The determinism contract: **the decision for the N-th call of a stream is
a pure function of (seed, specs)**. Concurrent controllers may interleave
differently from run to run, which permutes *which concrete operation*
lands on index N, but the sequence of fault decisions per stream — and
therefore the number and kind of injected faults — is reproducible from
the seed alone. That is what lets a chaos soak print one integer and be
re-run bit-for-bit.

Boundaries and the fault kinds their shims understand:

========== ============== ==========================================
boundary   op             kinds
========== ============== ==========================================
kube       create/update/ ``conflict`` (409 before the write lands),
           patch/delete/  ``timeout`` (generic ApiError — request
           bind_pods/     lost before the server applied it),
           evict_pod      ``slow-apiserver`` (the request succeeds
                          but only after a synthetic latency stall —
                          the brownout soak's degraded-apiserver mode)
kube       watch          ``drop`` (a Pod MODIFIED event vanishes;
                          ADDED/DELETED and non-Pod kinds are never
                          dropped — see :class:`_DroppingWatch`)
provider   create         ``ice`` (launch refused), ``crash-before-
                          bind`` (capacity launched, controller dies
                          before the Node write — the GC leak case),
                          ``spot-interruption`` (the oldest running
                          spot instance is reclaimed through the
                          capacity ledger concurrently with this
                          launch, which itself proceeds — ghost Node
                          for GC, pods repack)
provider   reclaim        ``spot-interruption`` again, drawn once per
                          tick by the replay harness's own plan
                          (replay.py --spot-fraction) rather than by a
                          provider shim — fires → oldest spot instance
                          reclaimed mid-run
ec2        create_fleet   ``ice``, ``throttle``, ``partial`` (one
                          unit ICEs, the rest launch),
                          ``crash-before-bind`` (fleet launched,
                          response lost)
device     solve          ``watchdog-trip`` (forced solver timeout →
                          breaker opens → host-FFD fallback)
pressure   depth          ``queue-flood`` (the monitor's intake-depth
                          sample is inflated by max_depth/2 — a
                          synthetic 50%-of-bound flood, no real
                          queue entries allocated)
pressure   rss            ``memory-pressure`` (the RSS sample is
                          inflated by 87% of the watermark —
                          deterministically lands in the L2 band
                          without allocating memory)
journal    <transition>   ``crash-point`` (deterministic simulated
                          process death at a named write-ahead-journal
                          transition — ``pre:<kind>:<phase>`` fires
                          before the record is durable,
                          ``<kind>:<phase>`` after; raises
                          :class:`SimulatedCrash`, which derives from
                          BaseException so no ``except Exception``
                          recovery path can accidentally survive it —
                          see runtime/journal.py KILL_POINTS)
========== ============== ==========================================

The ``pressure`` boundary is consumed by
:class:`karpenter_tpu.pressure.monitor.PressureMonitor` — one
``decide()`` per monitor evaluation, so ``count`` bounds how many
evaluations see the inflated sample.

Production call sites consult :func:`active_fault`; with no plan
installed that is one global read and a ``None`` return.
"""

from __future__ import annotations

import logging
import queue
import random
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("karpenter.chaos")


@dataclass(frozen=True)
class FaultSpec:
    """``count`` triggers of ``kind`` on the ``(boundary, op)`` stream."""

    boundary: str
    op: str
    kind: str
    count: int = 1


@dataclass(frozen=True)
class FiredFault:
    """One injection that actually happened (for post-soak assertions)."""

    boundary: str
    op: str
    index: int
    kind: str


class FaultPlan:
    """Compiled fault schedule; thread-safe; install with :func:`install`.

    ``window`` bounds how deep into each stream faults may land: fire
    indices are sampled from ``range(window)``, so a stream that receives
    at least ``window`` calls is guaranteed to absorb every planned fault.
    Keep it small relative to the soak's call volume (default 32) or tail
    faults may never fire.
    """

    def __init__(self, seed: int, specs: List[FaultSpec], window: int = 32):
        self.seed = seed
        self.specs = list(specs)
        self.window = window
        self._lock = threading.Lock()
        self._calls: Dict[Tuple[str, str], int] = {}
        self._fired: List[FiredFault] = []
        # compile: one shared RNG, specs consumed in list order, collisions
        # within a stream avoided by sampling from the remaining indices —
        # all deterministic given (seed, specs, window)
        rng = random.Random(seed)
        self._fire: Dict[Tuple[str, str], Dict[int, str]] = {}
        free: Dict[Tuple[str, str], List[int]] = {}
        for spec in self.specs:
            if spec.count < 1:
                continue
            stream = (spec.boundary, spec.op)
            pool = free.setdefault(stream, list(range(window)))
            if spec.count > len(pool):
                raise ValueError(
                    f"stream {stream}: {spec.count} triggers do not fit in "
                    f"the remaining window ({len(pool)} of {window} free)")
            picked = rng.sample(pool, spec.count)
            for idx in picked:
                pool.remove(idx)
                self._fire.setdefault(stream, {})[idx] = spec.kind

    # -- decision -----------------------------------------------------------
    def decide(self, boundary: str, op: str) -> Optional[str]:
        """Advance the ``(boundary, op)`` counter and return the fault kind
        planned for this index, if any."""
        stream = (boundary, op)
        with self._lock:
            idx = self._calls.get(stream, 0)
            self._calls[stream] = idx + 1
            kind = self._fire.get(stream, {}).get(idx)
            if kind is not None:
                self._fired.append(FiredFault(boundary, op, idx, kind))
        if kind is not None:
            log.info("chaos: injecting %s at %s/%s call #%d",
                     kind, boundary, op, idx)
            from karpenter_tpu.obs import flight

            flight.trip("chaos-fault", kind=kind, boundary=boundary,
                        op=op, index=idx, seed=self.seed)
        return kind

    # -- introspection (for soak assertions) --------------------------------
    def fired(self) -> List[FiredFault]:
        with self._lock:
            return list(self._fired)

    def fired_counts(self) -> Dict[Tuple[str, str, str], int]:
        counts: Dict[Tuple[str, str, str], int] = {}
        for f in self.fired():
            key = (f.boundary, f.op, f.kind)
            counts[key] = counts.get(key, 0) + 1
        return counts

    def calls(self, boundary: str, op: str) -> int:
        with self._lock:
            return self._calls.get((boundary, op), 0)

    def pending(self) -> int:
        """Planned triggers that have not fired yet (streams too short)."""
        planned = sum(len(m) for m in self._fire.values())
        with self._lock:
            return planned - len(self._fired)


# ---------------------------------------------------------------------------
# Global hook — the only thing production code touches
# ---------------------------------------------------------------------------

_PLAN: Optional[FaultPlan] = None


def install(plan: FaultPlan) -> None:
    global _PLAN
    _PLAN = plan


def uninstall() -> None:
    global _PLAN
    _PLAN = None


def installed() -> Optional[FaultPlan]:
    return _PLAN


def active_fault(boundary: str, op: str) -> Optional[str]:
    """Consult the installed plan; no plan → no fault, one global read."""
    plan = _PLAN
    if plan is None:
        return None
    return plan.decide(boundary, op)


class SimulatedCrash(BaseException):
    """Deterministic simulated process death at a journal kill point.

    Derives from BaseException — NOT Exception — so the control plane's
    broad ``except Exception`` error-handling (launch error aggregation,
    reconcile loops, unwind paths) cannot accidentally survive it: like
    a real SIGKILL, nothing between the kill point and the soak harness
    gets to clean up.
    """

    def __init__(self, point: str):
        super().__init__(f"simulated crash at journal kill point {point!r}")
        self.point = point


def crash_point(name: str) -> None:
    """Named kill point on the ``journal`` boundary; the write-ahead
    journal fires one per transition edge (see runtime/journal.py
    KILL_POINTS). With no plan installed this is one global read."""
    if active_fault("journal", name) == "crash-point":
        raise SimulatedCrash(name)


# ---------------------------------------------------------------------------
# Kube boundary shim
# ---------------------------------------------------------------------------


class _DroppingWatch:
    """Queue proxy that consults the plan per Pod MODIFIED event and may
    swallow it.

    Only Pod MODIFIED is ever droppable: the selection controller re-
    verifies every in-flight pod on a 5 s requeue, so a lost pod update is
    recovered by level-triggered reconciliation. A dropped ADDED would lose
    a pod forever (KubeCore has no re-list), and a dropped Node MODIFIED
    could swallow a deletionTimestamp and wedge termination — neither is a
    fault this codebase claims to survive, so the injector refuses to
    create it.
    """

    def __init__(self, inner: "queue.Queue"):
        self._inner = inner

    def get(self, block: bool = True, timeout: Optional[float] = None):
        while True:
            event = self._inner.get(block=block, timeout=timeout)
            obj = event.obj
            if (event.type == "MODIFIED"
                    and getattr(obj, "kind", "") == "Pod"
                    and active_fault("kube", "watch") == "drop"):
                continue
            return event

    def put(self, item, block: bool = True, timeout: Optional[float] = None):
        self._inner.put(item, block=block, timeout=timeout)

    def qsize(self) -> int:
        return self._inner.qsize()

    def empty(self) -> bool:
        return self._inner.empty()


class ChaosKube:
    """KubeCore wrapper injecting apiserver-shaped failures on the write
    path. Reads (get/scan/read/list) pass through untouched — the faults
    modeled are lost/rejected writes and dropped watch events, which is
    what an optimistic-concurrency control plane actually has to survive.

    Injection happens BEFORE delegation: the request dies on the wire, the
    server never applied it. That is the harder failure for callers (a
    post-apply error would leave the write visible on the next read).
    """

    _FAULTED_OPS = ("create", "update", "patch", "delete",
                    "bind_pods", "evict_pod")

    def __init__(self, inner):
        self._inner = inner

    #: synthetic apiserver latency for ``slow-apiserver`` (seconds) — long
    #: enough to register against the soak's wall clock, short enough that
    #: a handful of stalls don't dominate it
    SLOW_APISERVER_STALL_S = 0.25

    def _maybe_raise(self, op: str) -> None:
        from karpenter_tpu.runtime.kubecore import ApiError, Conflict

        kind = active_fault("kube", op)
        if kind == "conflict":
            raise Conflict(f"injected conflict on {op}")
        if kind == "timeout":
            raise ApiError(f"injected timeout on {op}")
        if kind == "slow-apiserver":
            # the write SUCCEEDS, just late — models a degraded (not dead)
            # apiserver; the caller's only symptom is latency
            import time as _time

            _time.sleep(self.SLOW_APISERVER_STALL_S)

    def create(self, obj):
        self._maybe_raise("create")
        return self._inner.create(obj)

    def update(self, obj):
        self._maybe_raise("update")
        return self._inner.update(obj)

    def patch(self, kind, name, namespace, fn):
        self._maybe_raise("patch")
        return self._inner.patch(kind, name, namespace, fn)

    def delete(self, kind, name, namespace="default", precondition_rv=None):
        self._maybe_raise("delete")
        return self._inner.delete(kind, name, namespace,
                                  precondition_rv=precondition_rv)

    def bind_pods(self, pods, node_name):
        self._maybe_raise("bind_pods")
        return self._inner.bind_pods(pods, node_name)

    def evict_pod(self, name, namespace="default"):
        self._maybe_raise("evict_pod")
        return self._inner.evict_pod(name, namespace)

    def watch(self, kind=None, meta_only=False):
        return _DroppingWatch(self._inner.watch(kind, meta_only=meta_only))

    def unwatch(self, q):
        self._inner.unwatch(q._inner if isinstance(q, _DroppingWatch) else q)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return getattr(self._inner, item)


# ---------------------------------------------------------------------------
# EC2 boundary shim
# ---------------------------------------------------------------------------


class ChaosEC2:
    """EC2API wrapper injecting CreateFleet failure modes against the fake
    (or any) EC2 implementation. Every other API passes through.

    - ``ice``: the whole fleet is refused — the inner call never happens,
      every override reports InsufficientInstanceCapacity, and the
      provider's offering cache gets poisoned for all of them.
    - ``throttle``: RequestLimitExceeded before the inner call — transient,
      retried by the Retryer on the real client and surfaced as a launch
      error on the fake.
    - ``partial``: one unit of target capacity ICEs (first override), the
      rest launch for real — the partial-fulfillment path end to end.
    - ``crash-before-bind``: the inner CreateFleet RUNS — capacity exists
      provider-side, tagged and attributable — then the response is lost.
      The caller sees a failed launch; the instances are leaked until the
      GC controller reaps them. This is the crash window the launch-nonce
      tag exists for.
    """

    def __init__(self, inner):
        self._inner = inner

    def create_fleet(self, request):
        from karpenter_tpu.cloudprovider.aws import sdk

        kind = active_fault("ec2", "create_fleet")
        if kind == "throttle":
            raise sdk.EC2Error("RequestLimitExceeded",
                               "injected CreateFleet throttle")
        if kind == "ice":
            return self._full_ice(request)
        if kind == "partial":
            first = next(
                (o for c in request.launch_template_configs
                 for o in c.overrides), None)
            if first is not None and request.total_target_capacity > 1:
                import copy

                shrunk = copy.deepcopy(request)
                shrunk.total_target_capacity -= 1
                response = self._inner.create_fleet(shrunk)
                response.errors.append(sdk.CreateFleetError(
                    error_code=sdk.INSUFFICIENT_CAPACITY_ERROR_CODE,
                    error_message="injected partial ICE",
                    instance_type=first.instance_type,
                    availability_zone=first.availability_zone))
                return response
            # single-unit fleet: a partial IS a full ICE
            return self._full_ice(request)
        if kind == "crash-before-bind":
            self._inner.create_fleet(request)
            raise sdk.EC2Error(
                "RequestTimeout",
                "injected connection loss after CreateFleet launched")
        return self._inner.create_fleet(request)

    @staticmethod
    def _full_ice(request):
        from karpenter_tpu.cloudprovider.aws import sdk

        errors = [
            sdk.CreateFleetError(
                error_code=sdk.INSUFFICIENT_CAPACITY_ERROR_CODE,
                error_message="injected full ICE",
                instance_type=o.instance_type,
                availability_zone=o.availability_zone)
            for c in request.launch_template_configs for o in c.overrides
        ]
        return sdk.CreateFleetResponse(instance_ids=[], errors=errors)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return getattr(self._inner, item)
