"""AWS cloud provider plane.

Reference: pkg/cloudprovider/aws/. Importing this package registers the
"aws" provider in the SPI registry.
"""

from karpenter_tpu.cloudprovider.aws.provider import AWSCloudProvider  # noqa: F401
from karpenter_tpu.cloudprovider.aws.vendor import AWSProvider  # noqa: F401
