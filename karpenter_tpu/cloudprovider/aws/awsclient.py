"""Real AWS EC2/SSM clients on stdlib HTTP — no boto3.

The reference builds an AWS session with a retryer and IMDS-discovered
region (/root/reference/pkg/cloudprovider/aws/cloudprovider.go:68-103) and
talks to EC2 (query protocol, XML responses) and SSM (JSON protocol).
This module provides the same capabilities hand-rolled, in the same
discipline as runtime/kubeclient.py:

- SigV4 signing (sigv4.py, tested against AWS's published examples);
- credential chain: env → shared credentials file → IMDSv2 instance role,
  with expiry-aware refresh for role credentials;
- region discovery: env → IMDSv2 (placement/region);
- a retryer with exponential backoff and full jitter on throttling/5xx/
  connection errors (cloudprovider.go:83-94's client-side rate limiting
  analog is in instancetypes/instance providers; this is the wire retry);
- ``Ec2Client``/``SsmClient`` implementing the EC2API/SSMAPI seam from
  sdk.py — so the entire provider stack and its tests are transport-
  agnostic, and the fake (fake/ec2api.py) remains drop-in.
"""

from __future__ import annotations

import base64
import calendar
import configparser
import http.client
import json
import logging
import os
import random
import time
import urllib.parse
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from karpenter_tpu.cloudprovider.aws import sdk, sigv4

log = logging.getLogger("karpenter.aws.client")

EC2_API_VERSION = "2016-11-15"
IMDS_ENDPOINT = "http://169.254.169.254"
IMDS_TOKEN_TTL = "21600"

RETRYABLE_CODES = {
    "Throttling", "ThrottlingException", "RequestLimitExceeded",
    "RequestThrottled", "RequestThrottledException", "TooManyRequestsException",
    "ServiceUnavailable", "InternalError", "InternalFailure", "EC2ThrottledException",
}


class AwsApiError(sdk.EC2Error):
    """Wire-level AWS error: carries HTTP status + AWS error code."""

    def __init__(self, code: str, message: str = "", status: int = 0):
        super().__init__(code, message)
        self.status = status


# ---------------------------------------------------------------------------
# Credentials
# ---------------------------------------------------------------------------


@dataclass
class Credentials:
    access_key: str
    secret_key: str
    session_token: Optional[str] = None
    expiration: Optional[float] = None    # epoch seconds; None = static

    def expired(self, now: Optional[float] = None, margin: float = 300.0) -> bool:
        if self.expiration is None:
            return False
        return (now if now is not None else time.time()) > self.expiration - margin


def credentials_from_env(env: Optional[Dict[str, str]] = None) -> Optional[Credentials]:
    env = os.environ if env is None else env
    ak, sk = env.get("AWS_ACCESS_KEY_ID"), env.get("AWS_SECRET_ACCESS_KEY")
    if ak and sk:
        return Credentials(ak, sk, env.get("AWS_SESSION_TOKEN") or None)
    return None


def credentials_from_shared_file(
    path: Optional[str] = None, profile: Optional[str] = None,
) -> Optional[Credentials]:
    path = path or os.environ.get(
        "AWS_SHARED_CREDENTIALS_FILE",
        os.path.expanduser("~/.aws/credentials"))
    profile = profile or os.environ.get("AWS_PROFILE", "default")
    if not os.path.exists(path):
        return None
    cp = configparser.ConfigParser()
    try:
        cp.read(path)
        sec = cp[profile]
        return Credentials(sec["aws_access_key_id"], sec["aws_secret_access_key"],
                           sec.get("aws_session_token") or None)
    except (KeyError, configparser.Error):
        return None


class Imds:
    """IMDSv2: session-token metadata access (the reference resolves its
    region through exactly this service, cloudprovider.go:96-103)."""

    def __init__(self, endpoint: Optional[str] = None, timeout: float = 2.0):
        # AWS_EC2_METADATA_SERVICE_ENDPOINT is the standard SDK override
        endpoint = endpoint or os.environ.get(
            "AWS_EC2_METADATA_SERVICE_ENDPOINT") or IMDS_ENDPOINT
        split = urllib.parse.urlsplit(endpoint)
        self._host = split.hostname or endpoint
        self._port = split.port or 80
        self.timeout = timeout
        self._token: Optional[str] = None
        self._token_expiry = 0.0

    def _req(self, method: str, path: str,
             headers: Optional[Dict[str, str]] = None) -> str:
        conn = http.client.HTTPConnection(self._host, self._port,
                                          timeout=self.timeout)
        try:
            conn.request(method, path, headers=headers or {})
            resp = conn.getresponse()
            body = resp.read().decode()
            if resp.status >= 300:
                raise AwsApiError("IMDSError", f"{method} {path}: {resp.status}",
                                  resp.status)
            return body
        finally:
            conn.close()

    def token(self) -> str:
        # the session token lives IMDS_TOKEN_TTL (6 h) — cache it; IMDS is
        # rate-limited per instance, so two round trips per read would be a
        # throttle hazard on the credential-refresh path
        now = time.monotonic()
        if self._token is None or now >= self._token_expiry:
            self._token = self._req("PUT", "/latest/api/token", {
                "x-aws-ec2-metadata-token-ttl-seconds": IMDS_TOKEN_TTL})
            self._token_expiry = now + float(IMDS_TOKEN_TTL) - 60.0
        return self._token

    def get(self, path: str) -> str:
        return self._req("GET", path, {"x-aws-ec2-metadata-token": self.token()})

    def region(self) -> str:
        return self.get("/latest/meta-data/placement/region").strip()

    def role_credentials(self) -> Credentials:
        role = self.get("/latest/meta-data/iam/security-credentials/").strip()
        role = role.splitlines()[0]
        doc = json.loads(self.get(
            f"/latest/meta-data/iam/security-credentials/{role}"))
        exp = None
        if doc.get("Expiration"):
            try:
                # Expiration is UTC ("...Z") — timegm, NOT mktime (which
                # would skew the epoch by the host's UTC offset and keep
                # serving dead credentials for hours)
                exp = float(calendar.timegm(time.strptime(
                    doc["Expiration"].rstrip("Z"), "%Y-%m-%dT%H:%M:%S")))
            except ValueError:
                exp = None
        return Credentials(doc["AccessKeyId"], doc["SecretAccessKey"],
                           doc.get("Token"), expiration=exp)


def resolve_region(imds: Optional[Imds] = None) -> str:
    region = os.environ.get("AWS_REGION") or os.environ.get("AWS_DEFAULT_REGION")
    if region:
        return region
    return (imds or Imds()).region()


class CredentialProvider:
    """Chain resolver with caching + expiry-aware refresh."""

    def __init__(self, imds: Optional[Imds] = None):
        self.imds = imds
        self._cached: Optional[Credentials] = None

    def get(self) -> Credentials:
        if self._cached is not None and not self._cached.expired():
            return self._cached
        creds = credentials_from_env() or credentials_from_shared_file()
        if creds is None:
            creds = (self.imds or Imds()).role_credentials()
        self._cached = creds
        return creds


# ---------------------------------------------------------------------------
# Retry + transport
# ---------------------------------------------------------------------------


class Retryer:
    """Exponential backoff with full jitter (the AWS-recommended policy;
    the reference's session uses client.DefaultRetryer)."""

    def __init__(self, max_attempts: int = 5, base_s: float = 0.2,
                 cap_s: float = 5.0,
                 sleep: Callable[[float], None] = time.sleep,
                 rand: Callable[[], float] = random.random):
        self.max_attempts = max_attempts
        self.base_s = base_s
        self.cap_s = cap_s
        self.sleep = sleep
        self.rand = rand

    def retryable(self, err: Exception) -> bool:
        if isinstance(err, AwsApiError):
            return (err.status in (429, 500, 502, 503, 504)
                    or err.code in RETRYABLE_CODES)
        return isinstance(err, (OSError, http.client.HTTPException))

    def run(self, fn: Callable[[], object]):
        last: Optional[Exception] = None
        for attempt in range(self.max_attempts):
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — filtered by retryable()
                if not self.retryable(e):
                    raise
                last = e
                if attempt < self.max_attempts - 1:
                    delay = self.rand() * min(self.cap_s,
                                              self.base_s * (2 ** attempt))
                    log.debug("aws retry %d/%d after %.2fs: %s",
                              attempt + 1, self.max_attempts, delay, e)
                    self.sleep(delay)
        raise last  # type: ignore[misc]


class AwsHttp:
    """One signed POST per call against a single AWS service endpoint."""

    def __init__(
        self,
        service: str,
        region: str,
        credentials: CredentialProvider,
        endpoint: Optional[str] = None,     # override for tests/VPC endpoints
        retryer: Optional[Retryer] = None,
        timeout: float = 30.0,
        now: Callable[[], float] = time.time,
    ):
        self.service = service
        self.region = region
        self.credentials = credentials
        self.retryer = retryer or Retryer()
        self.timeout = timeout
        self.now = now
        url = endpoint or f"https://{service}.{region}.amazonaws.com"
        split = urllib.parse.urlsplit(url)
        self._https = split.scheme == "https"
        self._host = split.hostname or url
        self._port = split.port or (443 if self._https else 80)
        # Host header must include a non-default port (stub servers)
        default = (443 if self._https else 80)
        self._host_header = (self._host if split.port in (None, default)
                             else f"{self._host}:{split.port}")

    def _conn(self):
        if self._https:
            return http.client.HTTPSConnection(self._host, self._port,
                                               timeout=self.timeout)
        return http.client.HTTPConnection(self._host, self._port,
                                          timeout=self.timeout)

    def _post(self, body: bytes, content_type: str,
              extra_headers: Dict[str, str],
              parse_error: Callable[[int, bytes], AwsApiError]) -> bytes:
        def attempt() -> bytes:
            creds = self.credentials.get()
            amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime(self.now()))
            headers = sigv4.sign(
                method="POST", host=self._host_header, path="/",
                query_params={}, headers={"content-type": content_type,
                                          **extra_headers},
                payload=body, access_key=creds.access_key,
                secret_key=creds.secret_key, region=self.region,
                service=self.service, amz_date=amz_date,
                session_token=creds.session_token)
            conn = self._conn()
            try:
                conn.request("POST", "/", body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                if resp.status >= 300:
                    raise parse_error(resp.status, data)
                return data
            finally:
                conn.close()

        return self.retryer.run(attempt)  # type: ignore[return-value]


# ---------------------------------------------------------------------------
# EC2 (query protocol, XML)
# ---------------------------------------------------------------------------


def flatten_params(params: Dict[str, object]) -> Dict[str, str]:
    """AWS query-protocol flattening: lists → Key.N, dicts → Key.Sub."""
    out: Dict[str, str] = {}

    def walk(prefix: str, v: object):
        if isinstance(v, dict):
            for k, sub in v.items():
                walk(f"{prefix}.{k}" if prefix else str(k), sub)
        elif isinstance(v, (list, tuple)):
            for i, sub in enumerate(v, start=1):
                walk(f"{prefix}.{i}", sub)
        elif isinstance(v, bool):
            out[prefix] = "true" if v else "false"
        elif v is not None:
            out[prefix] = str(v)

    walk("", dict(params))
    return out


def _strip_ns(root: ET.Element) -> ET.Element:
    for el in root.iter():
        if "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
    return root


def _text(el: Optional[ET.Element], default: str = "") -> str:
    return el.text.strip() if el is not None and el.text else default


def _int(el: Optional[ET.Element], default: int = 0) -> int:
    try:
        return int(_text(el))
    except ValueError:
        return default


def parse_ec2_error(status: int, body: bytes) -> AwsApiError:
    """<Response><Errors><Error><Code>…</Code><Message>…</Message>…"""
    try:
        root = _strip_ns(ET.fromstring(body.decode()))
        err = root.find(".//Error")
        if err is not None:
            return AwsApiError(_text(err.find("Code"), "UnknownError"),
                               _text(err.find("Message")), status)
    except ET.ParseError:
        pass
    return AwsApiError("UnknownError", body[:200].decode(errors="replace"),
                       status)


def _launch_unix(iso: str) -> float:
    """EC2 launchTime (ISO8601 UTC, optional fractional seconds) → unix
    seconds; 0.0 when absent/unparseable (reads as infinitely old, which
    errs toward GC eligibility only after the grace window anyway)."""
    if not iso:
        return 0.0
    import calendar

    base = iso.split(".")[0].rstrip("Z")
    try:
        return float(calendar.timegm(time.strptime(base, "%Y-%m-%dT%H:%M:%S")))
    except ValueError:
        return 0.0


def _tagset(el: Optional[ET.Element]) -> Dict[str, str]:
    tags = {}
    if el is not None:
        for item in el.findall("item"):
            tags[_text(item.find("key"))] = _text(item.find("value"))
    return tags


class Ec2Client(sdk.EC2API):
    """EC2API over the wire. Pagination is followed to exhaustion; tag
    filters use the same '*'-means-tag-key-wildcard convention as the
    provider (aws/subnets.go:63-76)."""

    def __init__(self, http_client: AwsHttp):
        self.http = http_client

    # -- plumbing ---------------------------------------------------------
    def _call(self, action: str, params: Dict[str, object]) -> ET.Element:
        form = {"Action": action, "Version": EC2_API_VERSION,
                **flatten_params(params)}
        body = urllib.parse.urlencode(sorted(form.items())).encode()
        data = self.http._post(
            body, "application/x-www-form-urlencoded; charset=utf-8", {},
            parse_ec2_error)
        return _strip_ns(ET.fromstring(data.decode()))

    def _paged(self, action: str, params: Dict[str, object]):
        token = None
        while True:
            p = dict(params)
            if token:
                p["NextToken"] = token
            root = self._call(action, p)
            yield root
            token = _text(root.find("nextToken")) or None
            if not token:
                return

    @staticmethod
    def _tag_filters(tag_filters: Dict[str, str]) -> List[Dict[str, object]]:
        filters: List[Dict[str, object]] = []
        for key, value in tag_filters.items():
            if value == "*":
                filters.append({"Name": "tag-key", "Value": [key]})
            else:
                filters.append({"Name": f"tag:{key}",
                                "Value": value.split(",")})
        return filters

    # -- operations -------------------------------------------------------
    def describe_instance_types(self) -> List[sdk.InstanceTypeInfo]:
        out: List[sdk.InstanceTypeInfo] = []
        for root in self._paged("DescribeInstanceTypes", {"MaxResults": 100}):
            for item in root.findall(".//instanceTypeSet/item"):
                gpus = [sdk.GPUInfo(
                    manufacturer=_text(g.find("manufacturer")),
                    count=_int(g.find("count")))
                    for g in item.findall("gpuInfo/gpus/item")]
                accels = sum(
                    _int(a.find("count"))
                    for a in item.findall("inferenceAcceleratorInfo/accelerators/item"))
                net = item.find("networkInfo")
                out.append(sdk.InstanceTypeInfo(
                    instance_type=_text(item.find("instanceType")),
                    supported_architectures=[
                        _text(a) for a in item.findall(
                            "processorInfo/supportedArchitectures/item")],
                    supported_usage_classes=[
                        _text(u) for u in item.findall("supportedUsageClasses/item")],
                    supported_virtualization_types=[
                        _text(v) for v in item.findall(
                            "supportedVirtualizationTypes/item")],
                    vcpus=_int(item.find("vCpuInfo/defaultVCpus")),
                    memory_mib=_int(item.find("memoryInfo/sizeInMiB")),
                    gpus=gpus,
                    inference_accelerator_count=accels,
                    maximum_network_interfaces=_int(
                        net.find("maximumNetworkInterfaces") if net is not None else None),
                    ipv4_addresses_per_interface=_int(
                        net.find("ipv4AddressesPerInterface") if net is not None else None),
                    bare_metal=_text(item.find("bareMetal")) == "true",
                    fpga=item.find("fpgaInfo") is not None,
                ))
        return out

    def describe_instance_type_offerings(self) -> List[sdk.InstanceTypeOffering]:
        out = []
        for root in self._paged("DescribeInstanceTypeOfferings",
                                {"LocationType": "availability-zone"}):
            for item in root.findall(".//instanceTypeOfferingSet/item"):
                out.append(sdk.InstanceTypeOffering(
                    instance_type=_text(item.find("instanceType")),
                    location=_text(item.find("location"))))
        return out

    def describe_subnets(self, tag_filters: Dict[str, str]) -> List[sdk.Subnet]:
        params = {"Filter": self._tag_filters(tag_filters)}
        out = []
        for root in self._paged("DescribeSubnets", params):
            for item in root.findall(".//subnetSet/item"):
                out.append(sdk.Subnet(
                    subnet_id=_text(item.find("subnetId")),
                    availability_zone=_text(item.find("availabilityZone")),
                    tags=_tagset(item.find("tagSet"))))
        return out

    def describe_security_groups(
            self, tag_filters: Dict[str, str]) -> List[sdk.SecurityGroup]:
        params = {"Filter": self._tag_filters(tag_filters)}
        out = []
        for root in self._paged("DescribeSecurityGroups", params):
            for item in root.findall(".//securityGroupInfo/item"):
                out.append(sdk.SecurityGroup(
                    group_id=_text(item.find("groupId")),
                    group_name=_text(item.find("groupName")),
                    tags=_tagset(item.find("tagSet"))))
        return out

    def describe_launch_templates(self, names: List[str]) -> List[sdk.LaunchTemplate]:
        try:
            root = self._call("DescribeLaunchTemplates",
                              {"LaunchTemplateName": list(names)})
        except AwsApiError as e:
            if "NotFound" in e.code:
                return []
            raise
        return [
            sdk.LaunchTemplate(
                launch_template_name=_text(item.find("launchTemplateName")),
                launch_template_id=_text(item.find("launchTemplateId")))
            for item in root.findall(".//launchTemplates/item")
        ]

    def create_launch_template(self, template: sdk.LaunchTemplate) -> sdk.LaunchTemplate:
        data: Dict[str, object] = {
            "ImageId": template.image_id,
            "UserData": base64.b64encode(template.user_data.encode()).decode(),
            "SecurityGroupId": list(template.security_group_ids),
        }
        if template.instance_profile:
            data["IamInstanceProfile"] = {"Name": template.instance_profile}
        if template.metadata_options:
            data["MetadataOptions"] = dict(template.metadata_options)
        params: Dict[str, object] = {
            "LaunchTemplateName": template.launch_template_name,
            "LaunchTemplateData": data,
        }
        if template.tags:
            params["TagSpecification"] = [{
                "ResourceType": "launch-template",
                "Tag": [{"Key": k, "Value": v} for k, v in template.tags.items()],
            }]
        root = self._call("CreateLaunchTemplate", params)
        lt = root.find(".//launchTemplate")
        template.launch_template_id = _text(
            lt.find("launchTemplateId") if lt is not None else None)
        return template

    def create_fleet(self, request: sdk.CreateFleetRequest) -> sdk.CreateFleetResponse:
        configs: List[Dict[str, object]] = []
        for cfg in request.launch_template_configs:
            overrides = []
            for o in cfg.overrides:
                ov: Dict[str, object] = {"InstanceType": o.instance_type,
                                         "SubnetId": o.subnet_id}
                if o.availability_zone:
                    ov["AvailabilityZone"] = o.availability_zone
                if o.priority is not None:
                    ov["Priority"] = o.priority
                overrides.append(ov)
            configs.append({
                "LaunchTemplateSpecification": {
                    "LaunchTemplateName": cfg.launch_template_name,
                    "Version": cfg.version,
                },
                "Overrides": overrides,
            })
        params: Dict[str, object] = {
            "Type": request.fleet_type,
            "LaunchTemplateConfigs": configs,
            "TargetCapacitySpecification": {
                "TotalTargetCapacity": request.total_target_capacity,
                "DefaultTargetCapacityType": request.default_target_capacity_type,
            },
            # the reference launches spot with capacity-optimized-prioritized
            # so Priority on overrides is honored (aws/instance.go:122-131)
            "OnDemandOptions": {"AllocationStrategy": "lowest-price"},
            "SpotOptions": {
                "AllocationStrategy": request.allocation_strategy
                or "capacity-optimized-prioritized"},
        }
        if request.tags:
            params["TagSpecification"] = [{
                "ResourceType": "instance",
                "Tag": [{"Key": k, "Value": v} for k, v in request.tags.items()],
            }]
        root = self._call("CreateFleet", params)
        ids = [
            _text(i) for i in root.findall(".//fleetInstanceSet/item/instanceIds/item")
        ]
        errors = []
        for err in root.findall(".//errorSet/item"):
            ov = err.find("launchTemplateAndOverrides/overrides")
            errors.append(sdk.CreateFleetError(
                error_code=_text(err.find("errorCode")),
                error_message=_text(err.find("errorMessage")),
                instance_type=_text(ov.find("instanceType") if ov is not None else None),
                availability_zone=_text(
                    ov.find("availabilityZone") if ov is not None else None)))
        return sdk.CreateFleetResponse(instance_ids=ids, errors=errors)

    def describe_instances(self, instance_ids: List[str]) -> List[sdk.Instance]:
        out = []
        for root in self._paged("DescribeInstances",
                                {"InstanceId": list(instance_ids)}):
            out.extend(self._parse_instances(root))
        return out

    def describe_instances_by_tags(
            self, tag_filters: Dict[str, str]) -> List[sdk.Instance]:
        params = {"Filter": self._tag_filters(tag_filters),
                  "MaxResults": 1000}
        out = []
        for root in self._paged("DescribeInstances", params):
            out.extend(self._parse_instances(root))
        return out

    @staticmethod
    def _parse_instances(root: ET.Element) -> List[sdk.Instance]:
        out = []
        for item in root.findall(".//reservationSet/item/instancesSet/item"):
            out.append(sdk.Instance(
                instance_id=_text(item.find("instanceId")),
                instance_type=_text(item.find("instanceType")),
                availability_zone=_text(item.find("placement/availabilityZone")),
                private_dns_name=_text(item.find("privateDnsName")),
                image_id=_text(item.find("imageId")),
                architecture=_text(item.find("architecture"), "x86_64"),
                spot_instance_request_id=_text(
                    item.find("spotInstanceRequestId")) or None,
                tags=_tagset(item.find("tagSet")),
                launch_time=_launch_unix(_text(item.find("launchTime"))),
                state=_text(item.find("instanceState/name"), "running")))
        return out

    def terminate_instances(self, instance_ids: List[str]) -> None:
        self._call("TerminateInstances", {"InstanceId": list(instance_ids)})


# ---------------------------------------------------------------------------
# SSM (JSON protocol)
# ---------------------------------------------------------------------------


def parse_ssm_error(status: int, body: bytes) -> AwsApiError:
    try:
        doc = json.loads(body.decode())
        code = (doc.get("__type") or "UnknownError").split("#")[-1]
        return AwsApiError(code, doc.get("message") or doc.get("Message", ""),
                           status)
    except ValueError:
        return AwsApiError("UnknownError",
                           body[:200].decode(errors="replace"), status)


class SsmClient(sdk.SSMAPI):
    """GetParameter — resolves EKS-optimized AMI ids (aws/ami.go:40-100)."""

    def __init__(self, http_client: AwsHttp):
        self.http = http_client

    def get_parameter(self, name: str) -> str:
        body = json.dumps({"Name": name}).encode()
        data = self.http._post(
            body, "application/x-amz-json-1.1",
            {"x-amz-target": "AmazonSSM.GetParameter"}, parse_ssm_error)
        doc = json.loads(data.decode())
        return str((doc.get("Parameter") or {}).get("Value", ""))


# ---------------------------------------------------------------------------
# Construction
# ---------------------------------------------------------------------------


def default_clients(
    region: Optional[str] = None,
    ec2_endpoint: Optional[str] = None,
    ssm_endpoint: Optional[str] = None,
):
    """Build (Ec2Client, SsmClient) from the ambient environment — the
    counterpart of the reference's session construction
    (cloudprovider.go:68-103): region from env or IMDS, credential chain,
    shared retryer policy."""
    imds = Imds()
    region = region or resolve_region(imds)
    creds = CredentialProvider(imds)
    ec2 = Ec2Client(AwsHttp("ec2", region, creds, endpoint=ec2_endpoint))
    ssm = SsmClient(AwsHttp("ssm", region, creds, endpoint=ssm_endpoint))
    return ec2, ssm
