"""Tag-selector discovery providers: subnets, security groups, AMIs.

Reference: pkg/cloudprovider/aws/{subnets.go,securitygroups.go,ami.go}. All
three follow the same shape — selector → cached Describe/GetParameter — so
they live in one module here.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List

from karpenter_tpu.api import wellknown
from karpenter_tpu.cloudprovider.aws import sdk
from karpenter_tpu.cloudprovider.aws.vendor import AWSProvider
from karpenter_tpu.cloudprovider.spi import InstanceType
from karpenter_tpu.utils.cache import TTLCache

log = logging.getLogger("karpenter.aws.discovery")

CACHE_TTL = 60.0  # aws/cloudprovider.go:47-55


def _selector_key(selector: Dict[str, str]) -> str:
    return "|".join(f"{k}={v}" for k, v in sorted(selector.items()))


class SubnetProvider:
    """Subnets by tag selector, 60-s cached (subnets.go:37-76)."""

    def __init__(self, ec2api: sdk.EC2API):
        self.ec2api = ec2api
        self._cache = TTLCache(CACHE_TTL)

    def get(self, provider: AWSProvider) -> List[sdk.Subnet]:
        key = _selector_key(provider.subnet_selector)
        cached = self._cache.get(key)
        if cached is not None:
            return cached
        subnets = self.ec2api.describe_subnets(provider.subnet_selector)
        if not subnets:
            raise ValueError(
                f"no subnets matched selector {provider.subnet_selector}")
        self._cache.set(key, subnets)
        log.debug("Discovered subnets: %s",
                  [f"{s.subnet_id} ({s.availability_zone})" for s in subnets])
        return subnets


class SecurityGroupProvider:
    """Security group IDs by tag selector, 60-s cached
    (securitygroups.go:40-76)."""

    def __init__(self, ec2api: sdk.EC2API):
        self.ec2api = ec2api
        self._cache = TTLCache(CACHE_TTL)

    def get(self, provider: AWSProvider) -> List[str]:
        key = _selector_key(provider.security_group_selector)
        cached = self._cache.get(key)
        if cached is None:
            cached = self.ec2api.describe_security_groups(
                provider.security_group_selector)
            self._cache.set(key, cached)
            log.debug("Discovered security groups: %s",
                      [g.group_id for g in cached])
        if not cached:
            raise ValueError("no security groups exist given constraints")
        return [g.group_id for g in cached]


class AMIProvider:
    """EKS-optimized AMI lookup via SSM, keyed by instance-type class
    (ami.go:40-106).

    ``kube_version`` is a callable so the kube discovery round-trip stays
    behind the same cache as the reference's clientSet.Discovery() call.
    """

    def __init__(self, ssm: sdk.SSMAPI, kube_version: Callable[[], str]):
        self.ssm = ssm
        self.kube_version = kube_version
        self._cache = TTLCache(CACHE_TTL)

    def get(self, instance_types: List[InstanceType]) -> Dict[str, List[InstanceType]]:
        """AMI id → instance types sharing it (ami.go:48-70)."""
        version = self._kube_server_version()
        queries: Dict[str, List[InstanceType]] = {}
        for it in instance_types:
            queries.setdefault(self._ssm_query(it, version), []).append(it)
        ami_ids: Dict[str, List[InstanceType]] = {}
        for query, its in queries.items():
            ami_ids.setdefault(self._ami_id(query), []).extend(its)
        return ami_ids

    def _ami_id(self, query: str) -> str:
        cached = self._cache.get(query)
        if cached is not None:
            return cached
        ami = self.ssm.get_parameter(query)
        self._cache.set(query, ami)
        log.debug("Discovered ami %s for query %s", ami, query)
        return ami

    @staticmethod
    def _ssm_query(instance_type: InstanceType, version: str) -> str:
        """GPU/Neuron → -gpu image; arm64 → -arm64 image (ami.go:87-95)."""
        suffix = ""
        if not instance_type.nvidia_gpus.is_zero() or not instance_type.aws_neurons.is_zero():
            suffix = "-gpu"
        elif instance_type.architecture == wellknown.ARCHITECTURE_ARM64:
            suffix = "-arm64"
        return (f"/aws/service/eks/optimized-ami/{version}/"
                f"amazon-linux-2{suffix}/recommended/image_id")

    def _kube_server_version(self) -> str:
        cached = self._cache.get("kubernetesVersion")
        if cached is not None:
            return cached
        version = self.kube_version().rstrip("+")
        self._cache.set("kubernetesVersion", version)
        log.debug("Discovered kubernetes version %s", version)
        return version
