"""Programmable fake AWS SDK surface (reference: pkg/cloudprovider/aws/fake/)."""

from karpenter_tpu.cloudprovider.aws.fake.ec2api import (  # noqa: F401
    CapacityPool,
    EC2Behavior,
    FakeEC2API,
    default_instance_type_infos,
    default_security_groups,
    default_subnets,
)
from karpenter_tpu.cloudprovider.aws.fake.ssmapi import FakeSSMAPI  # noqa: F401
