"""Programmable fake EC2 API.

Reference: pkg/cloudprovider/aws/fake/ec2api.go — canned Describe outputs,
call-capture lists, InsufficientCapacityPools to simulate ICE on CreateFleet,
and Reset() between tests. The AWS provider suite keeps the real provider
code and fakes only this surface.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karpenter_tpu.cloudprovider.aws import sdk
from karpenter_tpu.utils import clock

_counter = itertools.count(1)


@dataclass(frozen=True)
class CapacityPool:
    """An (instance type, zone, capacity type) pool to fail with ICE
    (ec2api.go:54, CapacityPool)."""

    instance_type: str
    zone: str
    capacity_type: str


def default_instance_type_infos() -> List[sdk.InstanceTypeInfo]:
    """Hardcoded catalog mirroring the reference fake's families
    (ec2api.go:214-388): burstable/standard/GPU/ARM/inferentia, plus a
    bare-metal and an FPGA type that the provider filter must drop."""
    return [
        sdk.InstanceTypeInfo(
            instance_type="t3.large", vcpus=2, memory_mib=8 * 1024,
            maximum_network_interfaces=3, ipv4_addresses_per_interface=12,
            price_per_hour=0.0832),
        sdk.InstanceTypeInfo(
            instance_type="m5.large", vcpus=2, memory_mib=8 * 1024,
            maximum_network_interfaces=3, ipv4_addresses_per_interface=30,
            pod_eni_trunking_compatible=True, pod_eni_branch_interfaces=9,
            price_per_hour=0.096),
        sdk.InstanceTypeInfo(
            instance_type="m5.xlarge", vcpus=4, memory_mib=16 * 1024,
            maximum_network_interfaces=4, ipv4_addresses_per_interface=60,
            pod_eni_trunking_compatible=True, pod_eni_branch_interfaces=18,
            price_per_hour=0.192),
        sdk.InstanceTypeInfo(
            instance_type="p3.8xlarge", vcpus=32, memory_mib=249856,
            gpus=[sdk.GPUInfo(manufacturer="NVIDIA", count=4)],
            maximum_network_interfaces=4, ipv4_addresses_per_interface=60,
            price_per_hour=12.24),
        sdk.InstanceTypeInfo(
            instance_type="c6g.large", vcpus=2, memory_mib=2 * 1024,
            supported_architectures=["arm64"],
            maximum_network_interfaces=4, ipv4_addresses_per_interface=60,
            price_per_hour=0.068),
        sdk.InstanceTypeInfo(
            instance_type="inf1.2xlarge", vcpus=8, memory_mib=16384,
            inference_accelerator_count=1,
            maximum_network_interfaces=4, ipv4_addresses_per_interface=60,
            price_per_hour=0.362),
        sdk.InstanceTypeInfo(
            instance_type="inf1.6xlarge", vcpus=24, memory_mib=49152,
            inference_accelerator_count=4,
            maximum_network_interfaces=8, ipv4_addresses_per_interface=30,
            price_per_hour=1.18),
        # dropped by the filter:
        sdk.InstanceTypeInfo(
            instance_type="m5.metal", vcpus=96, memory_mib=384 * 1024,
            bare_metal=True,
            maximum_network_interfaces=15, ipv4_addresses_per_interface=50),
        sdk.InstanceTypeInfo(
            instance_type="f1.2xlarge", vcpus=8, memory_mib=122 * 1024,
            fpga=True,
            maximum_network_interfaces=4, ipv4_addresses_per_interface=15),
        sdk.InstanceTypeInfo(  # non-allowlisted family
            instance_type="x1.16xlarge", vcpus=64, memory_mib=999424,
            maximum_network_interfaces=8, ipv4_addresses_per_interface=30),
    ]


DEFAULT_ZONES = ["test-zone-1a", "test-zone-1b", "test-zone-1c"]


def default_subnets() -> List[sdk.Subnet]:
    return [
        sdk.Subnet(subnet_id="test-subnet-1", availability_zone="test-zone-1a",
                   tags={"Name": "test-subnet-1"}),
        sdk.Subnet(subnet_id="test-subnet-2", availability_zone="test-zone-1b",
                   tags={"Name": "test-subnet-2"}),
        sdk.Subnet(subnet_id="test-subnet-3", availability_zone="test-zone-1c",
                   tags={"Name": "test-subnet-3", "TestTag": ""}),
    ]


def default_security_groups() -> List[sdk.SecurityGroup]:
    return [
        sdk.SecurityGroup(group_id="test-security-group-1", tags={"Name": "test-security-group-1"}),
        sdk.SecurityGroup(group_id="test-security-group-2", tags={"Name": "test-security-group-2"}),
        sdk.SecurityGroup(group_id="test-security-group-3",
                          tags={"Name": "test-security-group-3", "TestTag": ""}),
    ]


@dataclass
class EC2Behavior:
    """Canned outputs; None falls through to defaults (ec2api.go:42-56)."""

    describe_instance_types_output: Optional[List[sdk.InstanceTypeInfo]] = None
    describe_instance_type_offerings_output: Optional[List[sdk.InstanceTypeOffering]] = None
    describe_subnets_output: Optional[List[sdk.Subnet]] = None
    describe_security_groups_output: Optional[List[sdk.SecurityGroup]] = None
    describe_instances_output: Optional[List[sdk.Instance]] = None
    insufficient_capacity_pools: List[CapacityPool] = field(default_factory=list)
    create_fleet_error: Optional[Exception] = None


class FakeEC2API(sdk.EC2API):
    def __init__(self, behavior: Optional[EC2Behavior] = None):
        self.behavior = behavior or EC2Behavior()
        self.calls: Dict[str, List[object]] = {}
        self._launch_templates: Dict[str, sdk.LaunchTemplate] = {}
        self._instances: Dict[str, sdk.Instance] = {}
        self.terminated: List[str] = []

    def reset(self) -> None:
        """Clear state between tests (ec2api.go:67-75)."""
        self.behavior = EC2Behavior()
        self.calls.clear()
        self._launch_templates.clear()
        self._instances.clear()
        self.terminated.clear()

    def _record(self, method: str, payload) -> None:
        self.calls.setdefault(method, []).append(payload)

    # -- describes -----------------------------------------------------------
    def describe_instance_types(self) -> List[sdk.InstanceTypeInfo]:
        self._record("describe_instance_types", None)
        if self.behavior.describe_instance_types_output is not None:
            return list(self.behavior.describe_instance_types_output)
        return default_instance_type_infos()

    def describe_instance_type_offerings(self) -> List[sdk.InstanceTypeOffering]:
        self._record("describe_instance_type_offerings", None)
        if self.behavior.describe_instance_type_offerings_output is not None:
            return list(self.behavior.describe_instance_type_offerings_output)
        infos = (self.behavior.describe_instance_types_output
                 if self.behavior.describe_instance_types_output is not None
                 else default_instance_type_infos())
        return [
            sdk.InstanceTypeOffering(instance_type=info.instance_type, location=zone)
            for info in infos
            for zone in DEFAULT_ZONES
        ]

    def describe_subnets(self, tag_filters: Dict[str, str]) -> List[sdk.Subnet]:
        self._record("describe_subnets", dict(tag_filters))
        subnets = (self.behavior.describe_subnets_output
                   if self.behavior.describe_subnets_output is not None
                   else default_subnets())
        return [s for s in subnets if _matches(s.tags, tag_filters)]

    def describe_security_groups(self, tag_filters: Dict[str, str]) -> List[sdk.SecurityGroup]:
        self._record("describe_security_groups", dict(tag_filters))
        groups = (self.behavior.describe_security_groups_output
                  if self.behavior.describe_security_groups_output is not None
                  else default_security_groups())
        return [g for g in groups if _matches(g.tags, tag_filters)]

    # -- launch templates ----------------------------------------------------
    def describe_launch_templates(self, names: List[str]) -> List[sdk.LaunchTemplate]:
        self._record("describe_launch_templates", list(names))
        return [self._launch_templates[n] for n in names if n in self._launch_templates]

    def create_launch_template(self, template: sdk.LaunchTemplate) -> sdk.LaunchTemplate:
        self._record("create_launch_template", template)
        template.launch_template_id = f"lt-{next(_counter):08d}"
        self._launch_templates[template.launch_template_name] = template
        return template

    # -- fleet (ec2api.go:77-137) -------------------------------------------
    def create_fleet(self, request: sdk.CreateFleetRequest) -> sdk.CreateFleetResponse:
        self._record("create_fleet", request)
        if self.behavior.create_fleet_error is not None:
            raise self.behavior.create_fleet_error
        if not request.launch_template_configs:
            raise sdk.EC2Error("MissingParameter", "missing launch template configs")
        for config in request.launch_template_configs:
            if not config.launch_template_name:
                raise sdk.EC2Error("MissingParameter", "missing launch template name")

        capacity_type = request.default_target_capacity_type
        response = sdk.CreateFleetResponse()
        iced: set = set()
        # fulfill each unit of capacity from the first non-ICE'd override,
        # honoring spot priority when present
        overrides = [
            o for config in request.launch_template_configs
            for o in sorted(config.overrides,
                            key=lambda o: o.priority if o.priority is not None else 0.0)
        ]
        for _ in range(request.total_target_capacity):
            launched = False
            for override in overrides:
                pool = CapacityPool(
                    override.instance_type, override.availability_zone, capacity_type)
                if pool in self.behavior.insufficient_capacity_pools:
                    iced.add(pool)
                    continue
                instance = sdk.Instance(
                    instance_id=f"i-{next(_counter):016x}",
                    instance_type=override.instance_type,
                    availability_zone=override.availability_zone,
                    private_dns_name=f"ip-192-168-1-{next(_counter)}.ec2.internal",
                    spot_instance_request_id=(
                        f"sir-{next(_counter):06d}"
                        if capacity_type == "spot" else None),
                    # fleet TagSpecifications land on the instances (real
                    # CreateFleet semantics) — the GC enumeration keys off
                    # these; launch time reads the injectable clock so
                    # grace-window tests can time-travel
                    tags=dict(request.tags),
                    launch_time=clock.now(),
                )
                self._instances[instance.instance_id] = instance
                response.instance_ids.append(instance.instance_id)
                launched = True
                break
            if not launched:
                break
        for pool in sorted(iced, key=lambda p: (p.instance_type, p.zone)):
            response.errors.append(sdk.CreateFleetError(
                error_code=sdk.INSUFFICIENT_CAPACITY_ERROR_CODE,
                error_message="there is no capacity available",
                instance_type=pool.instance_type,
                availability_zone=pool.zone,
            ))
        return response

    # -- instances -----------------------------------------------------------
    def describe_instances(self, instance_ids: List[str]) -> List[sdk.Instance]:
        self._record("describe_instances", list(instance_ids))
        if self.behavior.describe_instances_output is not None:
            return list(self.behavior.describe_instances_output)
        return [self._instances[i] for i in instance_ids if i in self._instances]

    def describe_instances_by_tags(
            self, tag_filters: Dict[str, str]) -> List[sdk.Instance]:
        self._record("describe_instances_by_tags", dict(tag_filters))
        return [i for i in self._instances.values()
                if _matches(i.tags, tag_filters)]

    def terminate_instances(self, instance_ids: List[str]) -> None:
        self._record("terminate_instances", list(instance_ids))
        for instance_id in instance_ids:
            if instance_id not in self._instances:
                raise sdk.EC2Error(
                    "InvalidInstanceID.NotFound", f"{instance_id} does not exist")
            del self._instances[instance_id]
            self.terminated.append(instance_id)


def _matches(tags: Dict[str, str], tag_filters: Dict[str, str]) -> bool:
    """Tag selector semantics: "*" (and the ""→wildcard convention from
    subnets.go:63-67) match on key presence; otherwise exact value."""
    for key, value in tag_filters.items():
        if key not in tags:
            return False
        if value not in ("*", "") and tags[key] != value:
            return False
    return True
