"""Fake SSM API returning deterministic AMI ids per parameter path.

Reference: pkg/cloudprovider/aws/fake/ssmapi.go.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List

from karpenter_tpu.cloudprovider.aws import sdk


class FakeSSMAPI(sdk.SSMAPI):
    def __init__(self):
        self.calls: List[str] = []
        self.parameters: Dict[str, str] = {}

    def get_parameter(self, name: str) -> str:
        self.calls.append(name)
        if name in self.parameters:
            return self.parameters[name]
        # stable fake AMI id derived from the query, so distinct queries
        # (gpu/arm64 suffixes) yield distinct AMIs
        digest = hashlib.sha256(name.encode()).hexdigest()[:17]
        return f"ami-{digest}"

    def reset(self) -> None:
        self.calls.clear()
        self.parameters.clear()
