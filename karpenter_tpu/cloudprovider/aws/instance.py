"""Instance provider: EC2 Fleet launches and terminations.

Reference: pkg/cloudprovider/aws/instance.go. Launches capacity via
CreateFleet type=instant with launch-template configs whose overrides are the
cross-product of (instance type × subnet-in-zone), spot-prioritized; feeds
insufficient-capacity errors back into the offering cache; converts described
instances into Node objects.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Sequence

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.constraints import Constraints
from karpenter_tpu.api.core import Node, NodeSpec, NodeStatus, ObjectMeta
from karpenter_tpu.cloudprovider.aws import sdk
from karpenter_tpu.cloudprovider.aws.discovery import SubnetProvider
from karpenter_tpu.cloudprovider.aws.instancetypes import InstanceTypeProvider
from karpenter_tpu.cloudprovider.aws.launchtemplate import LaunchTemplateProvider
from karpenter_tpu.cloudprovider.aws.vendor import (
    AWSProvider,
    CAPACITY_TYPE_ON_DEMAND,
    CAPACITY_TYPE_SPOT,
    merge_tags,
)
from karpenter_tpu.cloudprovider.spi import InstanceType

log = logging.getLogger("karpenter.aws.instance")

NODE_NAME_CONVENTION_IP_NAME = "ip-name"
NODE_NAME_CONVENTION_RESOURCE_NAME = "resource-name"


class InstanceProvider:
    def __init__(
        self,
        ec2api: sdk.EC2API,
        instance_type_provider: InstanceTypeProvider,
        subnet_provider: SubnetProvider,
        launch_template_provider: LaunchTemplateProvider,
        cluster_name: str,
        node_name_convention: str = NODE_NAME_CONVENTION_IP_NAME,
        describe_retry_delay: float = 1.0,
        fleet_limiter=None,
    ):
        from karpenter_tpu.utils.ratelimit import TokenBucket

        self.ec2api = ec2api
        self.instance_type_provider = instance_type_provider
        self.subnet_provider = subnet_provider
        self.launch_template_provider = launch_template_provider
        self.cluster_name = cluster_name
        self.node_name_convention = node_name_convention
        self.describe_retry_delay = describe_retry_delay
        # CreateFleet budget 2 QPS / 100 burst (cloudprovider.go:41-46)
        self.fleet_limiter = fleet_limiter or TokenBucket(2, 100)

    # -- create (instance.go:51-90) -----------------------------------------
    def create(
        self,
        constraints: Constraints,
        provider: AWSProvider,
        instance_types: Sequence[InstanceType],
        quantity: int,
        provisioner_name: str = "default",
    ) -> List[Node]:
        """instance_types must arrive sorted by priority for spot (the packer
        emits them smallest-first, which is what the spot
        capacity-optimized-prioritized strategy wants)."""
        ids = self._launch_instances(
            constraints, provider, instance_types, quantity, provisioner_name)
        instances = self._get_instances_with_retry(ids)
        nodes = []
        for instance in instances:
            log.info(
                "Launched instance: %s, hostname: %s, type: %s, zone: %s, capacityType: %s",
                instance.instance_id, instance.private_dns_name,
                instance.instance_type, instance.availability_zone,
                _capacity_type_of(instance))
            node = self._instance_to_node(instance, instance_types)
            if node is None:
                log.error("creating Node from an EC2 Instance: unrecognized "
                          "instance type %s", instance.instance_type)
                continue
            nodes.append(node)
        if not nodes:
            raise RuntimeError("zero nodes were created")
        return nodes

    def terminate(self, node: Node) -> None:
        """Terminate by providerID; NotFound is success (instance.go:92-106)."""
        self.terminate_by_id(get_instance_id(node))

    def terminate_by_id(self, instance_id: str) -> None:
        """Terminate raw capacity with no Node to parse — the GC orphan
        path. NotFound is success (already gone)."""
        try:
            self.ec2api.terminate_instances([instance_id])
        except sdk.EC2Error as e:
            if not e.is_not_found:
                raise

    # -- enumerate (upstream instance garbage collection) --------------------
    LIVE_STATES = ("pending", "running")

    def list_cluster_instances(self) -> List[sdk.Instance]:
        """All live instances this cluster's launches created, enumerated by
        the cluster ownership tag (DescribeInstances by tag filter, paged by
        the client, retried by the shared Retryer). Terminated/shutting-down
        instances are dropped here: they linger in DescribeInstances for up
        to an hour and must not read as leaked capacity."""
        described = self.ec2api.describe_instances_by_tags(
            {f"kubernetes.io/cluster/{self.cluster_name}": "owned"})
        return [i for i in described if i.state in self.LIVE_STATES]

    # -- launch (instance.go:108-149) ---------------------------------------
    def _launch_instances(
        self,
        constraints: Constraints,
        provider: AWSProvider,
        instance_types: Sequence[InstanceType],
        quantity: int,
        provisioner_name: str,
    ) -> List[str]:
        capacity_type = self._get_capacity_type(constraints, instance_types)
        configs = self._launch_template_configs(
            constraints, provider, instance_types, capacity_type)
        # the nonce tag rides the CreateFleet TagSpecification, so it is on
        # the instances from birth — a crash anywhere after this call
        # leaves capacity that list_instances() can enumerate and attribute.
        # A journaled launch pre-stamps the nonce (runtime/journal.py) so
        # the write-ahead record and the cloud tags agree across a restart.
        import uuid

        from karpenter_tpu.runtime import journal

        nonce = journal.current_preassigned_nonce() or uuid.uuid4().hex
        request = sdk.CreateFleetRequest(
            launch_template_configs=configs,
            total_target_capacity=quantity,
            default_target_capacity_type=capacity_type,
            allocation_strategy=(
                "capacity-optimized-prioritized"
                if capacity_type == CAPACITY_TYPE_SPOT else "lowest-price"),
            tags=merge_tags(
                provisioner_name, provider.tags,
                {f"kubernetes.io/cluster/{self.cluster_name}": "owned",
                 wellknown.LAUNCH_NONCE_TAG: nonce}),
        )
        self.fleet_limiter.acquire()
        response = self.ec2api.create_fleet(request)
        self._update_unavailable_offerings(response.errors, capacity_type)
        if not response.instance_ids:
            raise RuntimeError("with fleet error(s), " + "; ".join(sorted({
                f"{e.error_code}: {e.error_message}" for e in response.errors})))
        if len(response.instance_ids) != quantity:
            log.error(
                "Failed to launch %d EC2 instances out of the %d EC2 instances requested",
                quantity - len(response.instance_ids), quantity)
        return list(response.instance_ids)

    def _launch_template_configs(
        self,
        constraints: Constraints,
        provider: AWSProvider,
        instance_types: Sequence[InstanceType],
        capacity_type: str,
    ) -> List[sdk.FleetLaunchTemplateConfig]:
        subnets = self.subnet_provider.get(provider)
        launch_templates = self.launch_template_provider.get(
            constraints, provider, list(instance_types),
            {wellknown.LABEL_CAPACITY_TYPE: capacity_type})
        configs = []
        for name, its in launch_templates.items():
            overrides = self._overrides(
                its, subnets, constraints.requirements.zones() or frozenset(),
                capacity_type)
            if overrides:
                configs.append(sdk.FleetLaunchTemplateConfig(
                    launch_template_name=name, overrides=overrides))
        if not configs:
            raise RuntimeError(
                "no capacity offerings are currently available given the constraints")
        return configs

    @staticmethod
    def _overrides(
        instance_types: Sequence[InstanceType],
        subnets: Sequence[sdk.Subnet],
        zones: frozenset,
        capacity_type: str,
    ) -> List[sdk.FleetOverride]:
        """Cross product of instance type × first-subnet-in-zone, constrained
        by zones/offerings; spot priority = catalog index, so the
        smallest-first ordering biases capacity-optimized-prioritized away
        from excessively large types (instance.go:183-216)."""
        overrides = []
        for i, it in enumerate(instance_types):
            for offering in it.offerings:
                if offering.capacity_type != capacity_type:
                    continue
                if offering.zone not in zones:
                    continue
                for subnet in subnets:
                    if subnet.availability_zone != offering.zone:
                        continue
                    overrides.append(sdk.FleetOverride(
                        instance_type=it.name,
                        subnet_id=subnet.subnet_id,
                        availability_zone=subnet.availability_zone,
                        priority=float(i) if capacity_type == CAPACITY_TYPE_SPOT else None,
                    ))
                    break  # Fleet can't span subnets from the same AZ
        return overrides

    # -- describe (instance.go:218-243) -------------------------------------
    def _get_instances_with_retry(self, ids: List[str]) -> List[sdk.Instance]:
        """3 × 1 s retry: EC2 is eventually consistent after CreateFleet."""
        last_error: Optional[Exception] = None
        for attempt in range(3):
            if attempt:
                time.sleep(self.describe_retry_delay)
            try:
                instances = self._get_instances(ids)
            except Exception as e:  # noqa: BLE001 — retried, re-raised below
                last_error = e
                continue
            return instances
        if last_error is not None:
            raise last_error
        return []

    def _get_instances(self, ids: List[str]) -> List[sdk.Instance]:
        described = self.ec2api.describe_instances(ids)
        if len(described) != len(ids):
            raise RuntimeError(
                f"expected {len(ids)} instance(s), but got {len(described)}")
        if self.node_name_convention == NODE_NAME_CONVENTION_RESOURCE_NAME:
            return described
        with_dns = [i for i in described if i.private_dns_name]
        if len(with_dns) != len(described):
            raise RuntimeError("instance(s) missing PrivateDnsName")
        return with_dns

    def _instance_to_node(
        self, instance: sdk.Instance, instance_types: Sequence[InstanceType],
    ) -> Optional[Node]:
        """EC2 instance → Node object with zone/type/capacity labels and
        providerID (instance.go:245-285)."""
        for it in instance_types:
            if it.name != instance.instance_type:
                continue
            if self.node_name_convention == NODE_NAME_CONVENTION_RESOURCE_NAME:
                node_name = instance.instance_id
            else:
                node_name = instance.private_dns_name.lower()
            resources = {
                "pods": it.pods, "cpu": it.cpu, "memory": it.memory}
            return Node(
                metadata=ObjectMeta(
                    name=node_name,
                    namespace="",
                    labels={
                        wellknown.LABEL_TOPOLOGY_ZONE: instance.availability_zone,
                        wellknown.LABEL_INSTANCE_TYPE: instance.instance_type,
                        wellknown.LABEL_CAPACITY_TYPE: _capacity_type_of(instance),
                    },
                ),
                spec=NodeSpec(provider_id=(
                    f"aws:///{instance.availability_zone}/{instance.instance_id}")),
                status=NodeStatus(capacity=dict(resources), allocatable=dict(resources)),
            )
        return None

    def _update_unavailable_offerings(
        self, errors: List[sdk.CreateFleetError], capacity_type: str) -> None:
        """ICE errors poison the offering cache (instance.go:287-293)."""
        for err in errors:
            if err.error_code == sdk.INSUFFICIENT_CAPACITY_ERROR_CODE:
                self.instance_type_provider.cache_unavailable(
                    err.instance_type, err.availability_zone, capacity_type)

    @staticmethod
    def _get_capacity_type(
        constraints: Constraints, instance_types: Sequence[InstanceType]) -> str:
        """Spot iff the constraints allow spot AND a spot offering exists in
        an allowed zone; else on-demand (instance.go:296-309)."""
        capacity_types = constraints.requirements.capacity_types() or frozenset()
        zones = constraints.requirements.zones() or frozenset()
        if CAPACITY_TYPE_SPOT in capacity_types:
            for it in instance_types:
                for offering in it.offerings:
                    if offering.zone in zones and offering.capacity_type == CAPACITY_TYPE_SPOT:
                        return CAPACITY_TYPE_SPOT
        return CAPACITY_TYPE_ON_DEMAND


def get_instance_id(node: Node) -> str:
    """Parse the instance id out of aws:///<zone>/<id> (instance.go:331-337)."""
    parts = node.spec.provider_id.split("/")
    if len(parts) < 5:
        raise ValueError(f"parsing instance id {node.spec.provider_id}")
    return parts[4]


def _capacity_type_of(instance: sdk.Instance) -> str:
    return (CAPACITY_TYPE_SPOT if instance.spot_instance_request_id
            else CAPACITY_TYPE_ON_DEMAND)
