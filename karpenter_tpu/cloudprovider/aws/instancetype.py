"""EC2 instance-type adaptation: raw DescribeInstanceTypes data → SPI
InstanceType.

Reference: pkg/cloudprovider/aws/instancetype.go. All the capacity math the
Go adapter does lazily per accessor is materialized once here into the dense
value type the solver encodes into capacity tensors — the TPU hot path never
re-derives it.
"""

from __future__ import annotations

from typing import List, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.cloudprovider.aws import sdk
from karpenter_tpu.cloudprovider.aws.vendor import AWS_TO_KUBE_ARCHITECTURES
from karpenter_tpu.cloudprovider.spi import InstanceType, Offering
from karpenter_tpu.utils.resources import Quantity

# EC2 VM consumes <7.5% of machine memory (instancetype.go:32)
EC2_VM_AVAILABLE_MEMORY_FACTOR = 0.925

# kube-reserved CPU percentage ladder (instancetype.go:143-152, from
# bottlerocket's kubernetes settings)
_CPU_OVERHEAD_LADDER = (
    (0, 1000, 0.06),
    (1000, 2000, 0.01),
    (2000, 4000, 0.005),
    (4000, 1 << 31, 0.0025),
)


def eni_limited_pods(info: sdk.InstanceTypeInfo) -> int:
    """max ENIs × (IPv4 addresses per ENI − 1) + 2 (instancetype.go:166-169)."""
    return info.maximum_network_interfaces * (info.ipv4_addresses_per_interface - 1) + 2


def memory_mib(info: sdk.InstanceTypeInfo) -> int:
    """Memory discounted by the VM overhead factor (instancetype.go:65-71)."""
    return int(info.memory_mib * EC2_VM_AVAILABLE_MEMORY_FACTOR)


def architecture(info: sdk.InstanceTypeInfo) -> str:
    """First recognized architecture (instancetype.go:53-60)."""
    for arch in info.supported_architectures:
        if arch in AWS_TO_KUBE_ARCHITECTURES:
            return AWS_TO_KUBE_ARCHITECTURES[arch]
    return str(info.supported_architectures)  # unrecognized; kept for errors


def gpu_count(info: sdk.InstanceTypeInfo, manufacturer: str) -> int:
    """Sum GPU counts gated on the FIRST entry's manufacturer — the
    reference checks Gpus[0].Manufacturer inside the loop
    (instancetype.go:92-116); quirk preserved for parity."""
    if not info.gpus:
        return 0
    if info.gpus[0].manufacturer != manufacturer:
        return 0
    return sum(g.count for g in info.gpus)


def overhead_cpu_milli(vcpus: int) -> int:
    """system-reserved 100m + kube-reserved ladder (instancetype.go:127-161)."""
    cpu_milli = vcpus * 1000
    total = 100  # system-reserved
    for start, end, percentage in _CPU_OVERHEAD_LADDER:
        if cpu_milli >= start:
            r = float(min(cpu_milli, end) - start)
            total += int(r * percentage)
    return total


def overhead_memory_mib(info: sdk.InstanceTypeInfo) -> int:
    """kube-reserved (11 Mi/pod + 255) + system-reserved 100 + eviction
    threshold 100 (instancetype.go:134-139)."""
    return (11 * eni_limited_pods(info) + 255) + 100 + 100


def pods(info: sdk.InstanceTypeInfo, max_pods: Optional[int]) -> int:
    """Configured cap if the ENI-limited density option is off, else the ENI
    formula (instancetype.go:73-78)."""
    if max_pods is not None:
        return max_pods
    return eni_limited_pods(info)


def adapt(
    info: sdk.InstanceTypeInfo,
    offerings: List[Offering],
    max_pods: Optional[int] = None,
) -> InstanceType:
    """Materialize the SPI value type from raw EC2 data."""
    pod_eni = info.pod_eni_branch_interfaces if info.pod_eni_trunking_compatible else 0
    return InstanceType(
        name=info.instance_type,
        offerings=list(offerings),
        architecture=architecture(info),
        operating_systems=frozenset({wellknown.OPERATING_SYSTEM_LINUX}),
        cpu=Quantity.parse(str(info.vcpus)),
        memory=Quantity.parse(f"{memory_mib(info)}Mi"),
        pods=Quantity.parse(str(pods(info, max_pods))),
        nvidia_gpus=Quantity.parse(str(gpu_count(info, "NVIDIA"))),
        amd_gpus=Quantity.parse(str(gpu_count(info, "AMD"))),
        aws_neurons=Quantity.parse(str(info.inference_accelerator_count)),
        aws_pod_eni=Quantity.parse(str(pod_eni)),
        overhead={
            "cpu": Quantity.parse(f"{overhead_cpu_milli(info.vcpus)}m"),
            "memory": Quantity.parse(f"{overhead_memory_mib(info)}Mi"),
        },
        price=info.price_per_hour,
    )
