"""Instance-type catalog provider: discovery, caching, offering synthesis.

Reference: pkg/cloudprovider/aws/instancetypes.go. The catalog it produces is
the static side of the solver's input — adapt()-ed types feed straight into
the capacity/price tensors built by karpenter_tpu/solver/adapter.py, so this
provider is the boundary where eventually-consistent cloud state becomes
immutable arrays for the TPU pack kernel.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Set

from karpenter_tpu.cloudprovider.aws import sdk
from karpenter_tpu.cloudprovider.aws.discovery import SubnetProvider
from karpenter_tpu.cloudprovider.aws.instancetype import adapt
from karpenter_tpu.cloudprovider.aws.vendor import AWSProvider
from karpenter_tpu.cloudprovider.spi import InstanceType, Offering
from karpenter_tpu.utils.cache import TTLCache

log = logging.getLogger("karpenter.aws.instancetypes")

INSTANCE_TYPES_AND_ZONES_CACHE_TTL = 5 * 60.0  # instancetypes.go:38
INSUFFICIENT_CAPACITY_ERROR_CACHE_TTL = 45.0   # instancetypes.go:39

# Prefix allowlist of useful-for-Kubernetes families (instancetypes.go:163-172)
_FAMILY_PREFIXES = (
    "m", "c", "r", "a",  # standard
    "i3",                # storage-optimized
    "t3", "t4",          # burstable
    "p", "inf", "g",     # accelerators
)


def _unavailable_key(capacity_type: str, instance_type: str, zone: str) -> str:
    """<capacityType>:<instanceType>:<zone> (instancetypes.go:198-200)."""
    return f"{capacity_type}:{instance_type}:{zone}"


class InstanceTypeProvider:
    """Catalog + offerings with the 5-min discovery cache and the 45-s
    insufficient-capacity avoidance cache (instancetypes.go:43-60)."""

    def __init__(self, ec2api: sdk.EC2API, subnet_provider: SubnetProvider,
                 eni_limited_pod_density: bool = True):
        self.ec2api = ec2api
        self.subnet_provider = subnet_provider
        self.eni_limited_pod_density = eni_limited_pod_density
        # values cached BEFORE subtracting unavailable offerings, so ICE
        # expiry restores an offering without re-discovery
        self._cache = TTLCache(INSTANCE_TYPES_AND_ZONES_CACHE_TTL)
        self._unavailable = TTLCache(INSUFFICIENT_CAPACITY_ERROR_CACHE_TTL)
        # Interned adapted objects: the same (discovery generation, name,
        # offerings) must yield the SAME InstanceType object call over call,
        # so the solver's identity-keyed packables memo (solver/adapter.py)
        # hits between catalog refreshes. An ICE poisoning or discovery
        # refresh changes the key → fresh object → the memo recomputes,
        # never stale.
        self._interned: Dict[tuple, InstanceType] = {}
        self._types_generation = 0

    def get(self, provider: AWSProvider) -> List[InstanceType]:
        """All viable instance types for the provider's subnets
        (instancetypes.go:63-95). Requirements are NOT applied here — the
        solver's feasibility mask handles them."""
        infos = self._get_instance_types()
        subnet_zones = {s.availability_zone for s in self.subnet_provider.get(provider)}
        type_zones = self._get_instance_type_zones()
        result = []
        interned: Dict[tuple, InstanceType] = {}
        max_pods = None if self.eni_limited_pod_density else 110
        for info in infos.values():
            offerings = self._create_offerings(
                info, subnet_zones, type_zones.get(info.instance_type, set()))
            if not offerings:
                continue
            key = (self._types_generation, info.instance_type,
                   tuple(offerings), max_pods)
            it = self._interned.get(key)
            if it is None:
                it = adapt(info, offerings, max_pods=max_pods)
            interned[key] = it
            result.append(it)
        # keep only live keys: expired infos/offering sets age out with them
        self._interned = interned
        return result

    def _create_offerings(self, info: sdk.InstanceTypeInfo, subnet_zones: Set[str],
                          available_zones: Set[str]) -> List[Offering]:
        """zones ∩ subnets × usage classes, minus recently-ICE'd offerings
        (instancetypes.go:97-109)."""
        offerings = []
        for zone in sorted(subnet_zones & available_zones):
            for capacity_type in sorted(set(info.supported_usage_classes)):
                if self._unavailable.get(
                        _unavailable_key(capacity_type, info.instance_type, zone)) is None:
                    offerings.append(Offering(capacity_type=capacity_type, zone=zone))
        return offerings

    def _get_instance_type_zones(self) -> Dict[str, Set[str]]:
        cached = self._cache.get("zones")
        if cached is not None:
            return cached
        zones: Dict[str, Set[str]] = {}
        for offering in self.ec2api.describe_instance_type_offerings():
            zones.setdefault(offering.instance_type, set()).add(offering.location)
        log.debug("Discovered EC2 instance types zonal offerings")
        self._cache.set("zones", zones)
        return zones

    def _get_instance_types(self) -> Dict[str, sdk.InstanceTypeInfo]:
        cached = self._cache.get("types")
        if cached is not None:
            return cached
        types = {
            info.instance_type: info
            for info in self.ec2api.describe_instance_types()
            if self._filter(info)
        }
        log.debug("Discovered %d EC2 instance types", len(types))
        self._cache.set("types", types)
        self._types_generation += 1  # fresh infos → fresh interned objects
        return types

    @staticmethod
    def _filter(info: sdk.InstanceTypeInfo) -> bool:
        """HVM, non-FPGA, non-metal, allowlisted family
        (instancetypes.go:139-176)."""
        if info.fpga or info.bare_metal:
            return False
        if "hvm" not in info.supported_virtualization_types:
            return False
        return info.instance_type.startswith(_FAMILY_PREFIXES)

    def cache_unavailable(self, instance_type: str, zone: str, capacity_type: str) -> None:
        """Poison an offering for 45 s after an insufficient-capacity error;
        repeat errors extend the window (instancetypes.go:180-196)."""
        log.debug(
            "%s for offering { instanceType: %s, zone: %s, capacityType: %s }, "
            "avoiding for %ss", sdk.INSUFFICIENT_CAPACITY_ERROR_CODE,
            instance_type, zone, capacity_type, INSUFFICIENT_CAPACITY_ERROR_CACHE_TTL)
        self._unavailable.set(
            _unavailable_key(capacity_type, instance_type, zone), True)
