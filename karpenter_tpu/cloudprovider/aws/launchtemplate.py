"""Launch template provider: hash-named ensure-or-create + EKS bootstrap
userData generation.

Reference: pkg/cloudprovider/aws/launchtemplate.go. The template name is a
stable hash of everything that affects the booted node, so equivalent
constraints converge on one EC2 LaunchTemplate (launchtemplate.go:64-85);
userData is built deterministically (sorted labels/taints) for the same
reason (launchtemplate.go:229-246).
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import threading
from typing import Callable, Dict, List, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.constraints import Constraints
from karpenter_tpu.cloudprovider.aws import sdk
from karpenter_tpu.cloudprovider.aws.discovery import AMIProvider, SecurityGroupProvider
from karpenter_tpu.cloudprovider.aws.vendor import AWSProvider
from karpenter_tpu.cloudprovider.spi import InstanceType
from karpenter_tpu.utils.cache import TTLCache

log = logging.getLogger("karpenter.aws.launchtemplate")

LAUNCH_TEMPLATE_NAME_FORMAT = "Karpenter-{cluster}-{hash}"


def needs_docker(instance_types: List[InstanceType]) -> bool:
    """GPU/Neuron instances can't use containerd directly
    (launchtemplate.go:163-172)."""
    return any(
        not it.aws_neurons.is_zero() or not it.nvidia_gpus.is_zero()
        for it in instance_types)


def launch_template_name(options: Dict[str, object]) -> str:
    """Deterministic name from the hashed option struct
    (launchtemplate.go:64-70)."""
    digest = hashlib.sha256(
        json.dumps(options, sort_keys=True, default=str).encode()).hexdigest()[:16]
    return LAUNCH_TEMPLATE_NAME_FORMAT.format(
        cluster=options["ClusterName"], hash=digest)


class LaunchTemplateProvider:
    def __init__(
        self,
        ec2api: sdk.EC2API,
        ami_provider: AMIProvider,
        security_group_provider: SecurityGroupProvider,
        cluster_name: str,
        cluster_endpoint: str,
        ca_bundle: Optional[Callable[[], Optional[str]]] = None,
        eni_limited_pod_density: bool = True,
    ):
        self.ec2api = ec2api
        self.ami_provider = ami_provider
        self.security_group_provider = security_group_provider
        self.cluster_name = cluster_name
        self.cluster_endpoint = cluster_endpoint
        self.ca_bundle = ca_bundle or (lambda: None)
        self.eni_limited_pod_density = eni_limited_pod_density
        self._cache = TTLCache(60.0)
        self._lock = threading.Lock()

    def get(
        self,
        constraints: Constraints,
        provider: AWSProvider,
        instance_types: List[InstanceType],
        additional_labels: Dict[str, str],
    ) -> Dict[str, List[InstanceType]]:
        """launch template name → instance types using it
        (launchtemplate.go:88-126). AMI may differ per architecture/
        accelerator, hence the grouping."""
        if provider.launch_template is not None:
            return {provider.launch_template: list(instance_types)}
        security_group_ids = self.security_group_provider.get(provider)
        launch_templates: Dict[str, List[InstanceType]] = {}
        for ami_id, its in self.ami_provider.get(instance_types).items():
            user_data = self._user_data(constraints, its, additional_labels)
            template = self._ensure(
                {
                    "UserData": user_data,
                    "ClusterName": self.cluster_name,
                    "InstanceProfile": provider.instance_profile,
                    "AMIID": ami_id,
                    "SecurityGroupsIds": sorted(security_group_ids),
                    "Tags": dict(sorted(provider.tags.items())),
                    "MetadataOptions": provider.get_metadata_options(),
                })
            launch_templates[template.launch_template_name] = its
        return launch_templates

    def _ensure(self, options: Dict[str, object]) -> sdk.LaunchTemplate:
        """Cache → Describe → Create, single-flighted (launchtemplate.go:128-160)."""
        with self._lock:
            name = launch_template_name(options)
            cached = self._cache.get(name)
            if cached is not None:
                return cached
            existing = self.ec2api.describe_launch_templates([name])
            if existing:
                log.debug("Discovered launch template %s", name)
                template = existing[0]
            else:
                template = self.ec2api.create_launch_template(sdk.LaunchTemplate(
                    launch_template_name=name,
                    user_data=str(options["UserData"]),
                    image_id=str(options["AMIID"]),
                    instance_profile=str(options["InstanceProfile"]),
                    security_group_ids=list(options["SecurityGroupsIds"]),
                    metadata_options=dict(options["MetadataOptions"]),
                    tags=dict(options["Tags"]),
                ))
                log.debug("Created launch template, %s", name)
            self._cache.set(name, template)
            return template

    # -- userData (launchtemplate.go:229-296) -------------------------------
    def _user_data(
        self,
        constraints: Constraints,
        instance_types: List[InstanceType],
        additional_labels: Dict[str, str],
    ) -> str:
        container_runtime = "" if needs_docker(instance_types) else " --container-runtime containerd"
        lines = [
            "#!/bin/bash -xe",
            "exec > >(tee /var/log/user-data.log|logger -t user-data -s 2>/dev/console) 2>&1",
            f"/etc/eks/bootstrap.sh '{self.cluster_name}'{container_runtime} \\",
            f"    --apiserver-endpoint '{self.cluster_endpoint}'",
        ]
        ca = self.ca_bundle()
        if ca is not None:
            lines[-1] += " \\"
            lines.append(f"    --b64-cluster-ca '{ca}'")

        kubelet_extra = " ".join(filter(None, [
            self._node_label_args({**additional_labels, **constraints.labels}),
            self._node_taint_args(constraints),
        ]))
        if not self.eni_limited_pod_density:
            lines[-1] += " \\"
            lines.append("    --use-max-pods=false")
            kubelet_extra = (kubelet_extra + " --max-pods=110").strip()
        if kubelet_extra:
            lines[-1] += " \\"
            lines.append(f"    --kubelet-extra-args '{kubelet_extra}'")
        if constraints.kubelet_configuration.cluster_dns:
            lines[-1] += " \\"
            lines.append(
                f"    --dns-cluster-ip '{constraints.kubelet_configuration.cluster_dns[0]}'")
        return base64.b64encode("\n".join(lines).encode()).decode()

    @staticmethod
    def _node_label_args(labels: Dict[str, str]) -> str:
        """Sorted --node-labels, skipping allowed-domain labels the kubelet
        may not self-apply (launchtemplate.go:298-313)."""
        items = [
            f"{k}={v}" for k, v in sorted(labels.items())
            if k not in wellknown.ALLOWED_LABEL_DOMAINS
        ]
        return f"--node-labels={','.join(items)}" if items else ""

    @staticmethod
    def _node_taint_args(constraints: Constraints) -> str:
        """Sorted --register-with-taints (launchtemplate.go:315-332)."""
        if not constraints.taints:
            return ""
        sorted_taints = sorted(
            constraints.taints, key=lambda t: (t.key, t.value, t.effect))
        return "--register-with-taints=" + ",".join(
            f"{t.key}={t.value}:{t.effect}" for t in sorted_taints)
