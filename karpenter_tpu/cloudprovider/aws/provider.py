"""The AWS CloudProvider: SPI implementation wiring the sub-providers.

Reference: pkg/cloudprovider/aws/cloudprovider.go. Construction takes the
EC2/SSM seam (sdk.EC2API/sdk.SSMAPI) so tests keep the real provider logic
and fake only the AWS surface, exactly like the reference suite.
"""

from __future__ import annotations

import logging
from typing import Callable, List, Optional, Sequence

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.constraints import Constraints
from karpenter_tpu.api.core import Node
from karpenter_tpu.cloudprovider import spi
from karpenter_tpu.cloudprovider.aws import sdk
from karpenter_tpu.cloudprovider.aws.discovery import (
    AMIProvider,
    SecurityGroupProvider,
    SubnetProvider,
)
from karpenter_tpu.cloudprovider.aws.instance import InstanceProvider
from karpenter_tpu.cloudprovider.aws.instancetypes import InstanceTypeProvider
from karpenter_tpu.cloudprovider.aws.launchtemplate import LaunchTemplateProvider
from karpenter_tpu.cloudprovider.aws.vendor import AWSProvider, default_constraints
from karpenter_tpu.cloudprovider.spi import BindCallback, CloudProvider, InstanceType

log = logging.getLogger("karpenter.aws")

# EC2 CreateFleet budget (cloudprovider.go:41-46) — enforced by the caller's
# workqueue in the reference; recorded here for the control plane's limiter.
CREATE_FLEET_QPS = 2
CREATE_FLEET_BURST = 100

# The EBS CSI zone label aliases the standard zone label
# (cloudprovider.go:58-60); registered at import so Requirements.normalize
# folds it in.
wellknown.NORMALIZED_LABELS.setdefault(
    "topology.ebs.csi.aws.com/zone", wellknown.LABEL_TOPOLOGY_ZONE)


class AWSCloudProvider(CloudProvider):
    def __init__(
        self,
        ec2api: sdk.EC2API,
        ssmapi: sdk.SSMAPI,
        cluster_name: str,
        cluster_endpoint: str,
        kube_version: Callable[[], str] = lambda: "1.21",
        ca_bundle: Optional[Callable[[], Optional[str]]] = None,
        eni_limited_pod_density: bool = True,
        node_name_convention: str = "ip-name",
        describe_retry_delay: float = 1.0,
    ):
        self.subnet_provider = SubnetProvider(ec2api)
        self.instance_type_provider = InstanceTypeProvider(
            ec2api, self.subnet_provider,
            eni_limited_pod_density=eni_limited_pod_density)
        self.launch_template_provider = LaunchTemplateProvider(
            ec2api,
            AMIProvider(ssmapi, kube_version),
            SecurityGroupProvider(ec2api),
            cluster_name=cluster_name,
            cluster_endpoint=cluster_endpoint,
            ca_bundle=ca_bundle,
            eni_limited_pod_density=eni_limited_pod_density,
        )
        self.instance_provider = InstanceProvider(
            ec2api,
            self.instance_type_provider,
            self.subnet_provider,
            self.launch_template_provider,
            cluster_name=cluster_name,
            node_name_convention=node_name_convention,
            describe_retry_delay=describe_retry_delay,
        )

    # -- SPI (cloudprovider.go:113-152) -------------------------------------
    def create(
        self,
        constraints: Constraints,
        instance_types: Sequence[InstanceType],
        quantity: int,
        bind: BindCallback,
    ) -> List[Optional[str]]:
        provider = AWSProvider.deserialize(constraints)
        provisioner_name = constraints.labels.get(
            wellknown.PROVISIONER_NAME_LABEL, "default")
        try:
            nodes = self.instance_provider.create(
                constraints, provider, instance_types, quantity,
                provisioner_name=provisioner_name)
        except Exception as e:  # noqa: BLE001 — surfaced per SPI contract
            return [f"launching instances, {e}"] * quantity
        errs = [bind(node) for node in nodes]
        # partial fulfillment: unlaunched capacity reported as errors
        errs.extend(["instance not launched"] * (quantity - len(nodes)))
        return errs

    def delete(self, node: Node) -> Optional[str]:
        try:
            self.instance_provider.terminate(node)
        except Exception as e:  # noqa: BLE001
            return f"terminating instance {node.metadata.name}, {e}"
        return None

    def list_instances(self) -> List[spi.CapacityRecord]:
        """Provider-side capacity enumeration for the GC controller:
        DescribeInstances by the cluster ownership tag, converted to
        CapacityRecords carrying the attribution tags stamped at launch."""
        records = []
        for inst in self.instance_provider.list_cluster_instances():
            records.append(spi.CapacityRecord(
                instance_id=inst.instance_id,
                provisioner_name=inst.tags.get(
                    wellknown.PROVISIONER_NAME_LABEL, ""),
                launch_nonce=inst.tags.get(wellknown.LAUNCH_NONCE_TAG, ""),
                created_unix=inst.launch_time,
                zone=inst.availability_zone,
                instance_type=inst.instance_type,
            ))
        return records

    def delete_instance(self, instance_id: str) -> Optional[str]:
        try:
            self.instance_provider.terminate_by_id(instance_id)
        except Exception as e:  # noqa: BLE001
            return f"terminating instance {instance_id}, {e}"
        return None

    def get_instance_types(self, constraints: Constraints) -> List[InstanceType]:
        """Full viable catalog; Requirements filtering happens in the solver's
        feasibility mask, not here (cloudprovider.go:133-140)."""
        provider = AWSProvider.deserialize(constraints)
        return self.instance_type_provider.get(provider)

    def default(self, constraints: Constraints) -> None:
        """Webhook defaulting hook (cloudprovider.go:154-161): arch amd64 +
        capacity-type on-demand, plus an empty provider block if missing so
        deserialize() holds its invariant."""
        if constraints.provider is None:
            constraints.provider = {}
        default_constraints(constraints)

    def validate(self, constraints: Constraints) -> Optional[str]:
        try:
            provider = AWSProvider.deserialize(constraints)
        except ValueError as e:
            return str(e)
        errs = provider.validate()
        return "; ".join(errs) if errs else None

    def name(self) -> str:
        return "aws"


spi.register("aws", AWSCloudProvider)
