"""EC2/SSM API surface — the seam the AWS provider is tested at.

The reference programs against ``ec2iface.EC2API``/``ssmiface.SSMAPI`` and
fakes exactly that surface in tests (pkg/cloudprovider/aws/fake/ec2api.go).
We keep the same seam: typed request/response shapes (plain dataclasses
instead of aws-sdk-go pointer soup), an abstract client, a programmable fake
(karpenter_tpu/cloudprovider/aws/fake), and a boto3 adapter that is only
imported when boto3 is actually present (it is not baked into this image).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, List, Optional

INSUFFICIENT_CAPACITY_ERROR_CODE = "InsufficientInstanceCapacity"


class EC2Error(Exception):
    """An EC2 API error with a machine-readable code."""

    def __init__(self, code: str, message: str = ""):
        super().__init__(f"{code}: {message}" if message else code)
        self.code = code

    @property
    def is_not_found(self) -> bool:
        return self.code.endswith(".NotFound")


# ---------------------------------------------------------------------------
# Shapes (the subset of ec2.* structs the provider reads)
# ---------------------------------------------------------------------------


@dataclass
class GPUInfo:
    manufacturer: str = ""
    count: int = 0


@dataclass
class InstanceTypeInfo:
    """ec2.InstanceTypeInfo subset consumed by the adapter
    (aws/instancetype.go)."""

    instance_type: str = ""
    supported_architectures: List[str] = field(default_factory=lambda: ["x86_64"])
    supported_usage_classes: List[str] = field(default_factory=lambda: ["on-demand", "spot"])
    supported_virtualization_types: List[str] = field(default_factory=lambda: ["hvm"])
    vcpus: int = 0
    memory_mib: int = 0
    gpus: List[GPUInfo] = field(default_factory=list)
    inference_accelerator_count: int = 0
    maximum_network_interfaces: int = 0
    ipv4_addresses_per_interface: int = 0
    bare_metal: bool = False
    fpga: bool = False
    # vpc-resource-controller trunking data (aws/instancetype.go:82-89)
    pod_eni_trunking_compatible: bool = False
    pod_eni_branch_interfaces: int = 0
    # extension for the cost-minimizing solver objective: on-demand $/h
    price_per_hour: float = 0.0


@dataclass
class InstanceTypeOffering:
    instance_type: str = ""
    location: str = ""  # availability zone


@dataclass
class Subnet:
    subnet_id: str = ""
    availability_zone: str = ""
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class SecurityGroup:
    group_id: str = ""
    group_name: str = ""
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class LaunchTemplate:
    launch_template_name: str = ""
    launch_template_id: str = ""
    user_data: str = ""
    image_id: str = ""
    instance_profile: str = ""
    security_group_ids: List[str] = field(default_factory=list)
    metadata_options: Dict[str, object] = field(default_factory=dict)
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class FleetOverride:
    """ec2.FleetLaunchTemplateOverridesRequest subset (aws/instance.go:185-205)."""

    instance_type: str = ""
    subnet_id: str = ""
    availability_zone: str = ""
    priority: Optional[float] = None


@dataclass
class FleetLaunchTemplateConfig:
    launch_template_name: str = ""
    version: str = "$Default"
    overrides: List[FleetOverride] = field(default_factory=list)


@dataclass
class CreateFleetRequest:
    launch_template_configs: List[FleetLaunchTemplateConfig] = field(default_factory=list)
    total_target_capacity: int = 0
    default_target_capacity_type: str = "on-demand"
    fleet_type: str = "instant"
    allocation_strategy: str = ""
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class CreateFleetError:
    error_code: str = ""
    error_message: str = ""
    # override that failed — zone kept redundantly so ICE errors are
    # attributable without extra lookups (aws/instance.go:196-199)
    instance_type: str = ""
    availability_zone: str = ""


@dataclass
class CreateFleetResponse:
    instance_ids: List[str] = field(default_factory=list)
    errors: List[CreateFleetError] = field(default_factory=list)


@dataclass
class Instance:
    instance_id: str = ""
    instance_type: str = ""
    availability_zone: str = ""
    private_dns_name: str = ""
    image_id: str = ""
    architecture: str = "x86_64"
    spot_instance_request_id: Optional[str] = None
    # garbage-collection fields: the tags CreateFleet stamped at launch
    # (provisioner name + launch nonce), the launch time the grace window
    # is measured against, and the lifecycle state (terminated instances
    # still appear in DescribeInstances for ~an hour and must not read as
    # live capacity)
    tags: Dict[str, str] = field(default_factory=dict)
    launch_time: float = 0.0
    state: str = "running"  # pending | running | shutting-down | terminated


# ---------------------------------------------------------------------------
# Client interfaces
# ---------------------------------------------------------------------------


class EC2API(abc.ABC):
    """The EC2 operations Karpenter performs (ec2iface subset)."""

    @abc.abstractmethod
    def describe_instance_types(self) -> List[InstanceTypeInfo]:
        ...

    @abc.abstractmethod
    def describe_instance_type_offerings(self) -> List[InstanceTypeOffering]:
        ...

    @abc.abstractmethod
    def describe_subnets(self, tag_filters: Dict[str, str]) -> List[Subnet]:
        """``filters[key] == "*"`` means tag-key wildcard (aws/subnets.go:63-76)."""

    @abc.abstractmethod
    def describe_security_groups(self, tag_filters: Dict[str, str]) -> List[SecurityGroup]:
        ...

    @abc.abstractmethod
    def describe_launch_templates(self, names: List[str]) -> List[LaunchTemplate]:
        ...

    @abc.abstractmethod
    def create_launch_template(self, template: LaunchTemplate) -> LaunchTemplate:
        ...

    @abc.abstractmethod
    def create_fleet(self, request: CreateFleetRequest) -> CreateFleetResponse:
        ...

    @abc.abstractmethod
    def describe_instances(self, instance_ids: List[str]) -> List[Instance]:
        ...

    @abc.abstractmethod
    def describe_instances_by_tags(
            self, tag_filters: Dict[str, str]) -> List[Instance]:
        """DescribeInstances with tag filters instead of ids — the
        garbage-collection enumeration path (upstream's ListByTags). Same
        '*'-means-tag-key-wildcard convention as describe_subnets. Paged to
        exhaustion by implementations; includes non-running instances (the
        caller filters by state)."""

    @abc.abstractmethod
    def terminate_instances(self, instance_ids: List[str]) -> None:
        ...


class SSMAPI(abc.ABC):
    @abc.abstractmethod
    def get_parameter(self, name: str) -> str:
        ...


def default_clients(region: Optional[str] = None):
    """Construct the real AWS clients (no SDK dependency): hand-rolled
    SigV4 + IMDSv2 + retryer on stdlib HTTP — see awsclient.py. Region
    resolves env → IMDS exactly like the reference's session
    (aws/cloudprovider.go:68-103)."""
    from karpenter_tpu.cloudprovider.aws import awsclient

    return awsclient.default_clients(region=region)


# historical name from when this was a boto3 import gate
boto3_clients = default_clients
