"""AWS Signature Version 4 request signing — pure functions, no I/O.

The reference reaches AWS through aws-sdk-go, which signs every request
with SigV4 (session construction at
/root/reference/pkg/cloudprovider/aws/cloudprovider.go:68-103). No AWS SDK
exists in this image, so the signing algorithm is implemented directly and
unit-tested against the worked examples AWS publishes in the SigV4
developer documentation (tests/test_aws_sigv4.py).

Algorithm (docs.aws.amazon.com "Signature Version 4 signing process"):
  1. canonical request  = METHOD \n URI \n query \n canonical headers \n
                          signed header names \n hex(sha256(payload))
  2. string to sign     = AWS4-HMAC-SHA256 \n timestamp \n scope \n
                          hex(sha256(canonical request))
  3. signing key        = HMAC-chain(AWS4+secret, date, region, service,
                          "aws4_request")
  4. signature          = hex(HMAC(signing key, string to sign))
"""

from __future__ import annotations

import hashlib
import hmac
import urllib.parse
from typing import Dict, Optional, Tuple

ALGORITHM = "AWS4-HMAC-SHA256"


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def derive_signing_key(secret_key: str, date: str, region: str,
                       service: str) -> bytes:
    """kSigning = HMAC(HMAC(HMAC(HMAC("AWS4"+secret, date), region),
    service), "aws4_request"). `date` is YYYYMMDD."""
    k_date = _hmac(("AWS4" + secret_key).encode(), date)
    k_region = _hmac(k_date, region)
    k_service = _hmac(k_region, service)
    return _hmac(k_service, "aws4_request")


def canonical_query(params: Dict[str, str]) -> str:
    """URI-encode each pair (RFC 3986, space as %20) and sort by key."""
    pairs = sorted(
        (urllib.parse.quote(k, safe="-_.~"), urllib.parse.quote(v, safe="-_.~"))
        for k, v in params.items()
    )
    return "&".join(f"{k}={v}" for k, v in pairs)


def canonical_request(
    method: str,
    path: str,
    query: str,
    headers: Dict[str, str],
    payload_hash: str,
) -> Tuple[str, str]:
    """Returns (canonical_request, signed_headers). Header names are
    lowercased and sorted; values trimmed of surrounding whitespace."""
    items = sorted((k.lower().strip(), v.strip()) for k, v in headers.items())
    canon_headers = "".join(f"{k}:{v}\n" for k, v in items)
    signed = ";".join(k for k, _ in items)
    req = "\n".join([
        method.upper(), path or "/", query, canon_headers, signed, payload_hash,
    ])
    return req, signed


def string_to_sign(amz_date: str, scope: str, canon_request: str) -> str:
    return "\n".join([
        ALGORITHM, amz_date, scope, sha256_hex(canon_request.encode()),
    ])


def sign(
    method: str,
    host: str,
    path: str,
    query_params: Dict[str, str],
    headers: Dict[str, str],
    payload: bytes,
    access_key: str,
    secret_key: str,
    region: str,
    service: str,
    amz_date: str,                      # YYYYMMDDTHHMMSSZ
    session_token: Optional[str] = None,
) -> Dict[str, str]:
    """Sign a request; returns the full header dict to send (input headers
    plus host, x-amz-date, optional x-amz-security-token, authorization)."""
    date = amz_date[:8]
    all_headers = {**headers, "host": host, "x-amz-date": amz_date}
    if session_token:
        all_headers["x-amz-security-token"] = session_token
    payload_hash = sha256_hex(payload)
    query = canonical_query(query_params)
    canon, signed = canonical_request(method, path, query, all_headers,
                                      payload_hash)
    scope = f"{date}/{region}/{service}/aws4_request"
    sts = string_to_sign(amz_date, scope, canon)
    key = derive_signing_key(secret_key, date, region, service)
    signature = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    all_headers["authorization"] = (
        f"{ALGORITHM} Credential={access_key}/{scope}, "
        f"SignedHeaders={signed}, Signature={signature}")
    return all_headers
