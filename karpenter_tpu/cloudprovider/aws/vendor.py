"""AWS vendor extension block — the ``spec.provider`` payload.

Reference: pkg/cloudprovider/aws/apis/v1alpha1/{provider.go,provider_defaults.go,
provider_validation.go,tags.go}. The core treats ``Constraints.provider`` as an
opaque dict; this module is the codec + defaulting + validation for the AWS
shape of it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.constraints import Constraints
from karpenter_tpu.api.core import NodeSelectorRequirement

CAPACITY_TYPE_SPOT = wellknown.CAPACITY_TYPE_SPOT
CAPACITY_TYPE_ON_DEMAND = wellknown.CAPACITY_TYPE_ON_DEMAND

# ec2.LaunchTemplateHttpTokensState* / metadata defaults (provider.go:25-32)
DEFAULT_METADATA_OPTIONS = {
    "httpEndpoint": "enabled",
    "httpProtocolIPv6": "disabled",
    "httpPutResponseHopLimit": 2,
    "httpTokens": "required",
}
_METADATA_ENUMS = {
    "httpEndpoint": {"enabled", "disabled"},
    "httpProtocolIPv6": {"enabled", "disabled"},
    "httpTokens": {"optional", "required"},
}

AWS_TO_KUBE_ARCHITECTURES = {
    "x86_64": wellknown.ARCHITECTURE_AMD64,
    "arm64": wellknown.ARCHITECTURE_ARM64,
}


@dataclass
class AWSProvider:
    """The AWS block inside spec.provider (provider.go:42-121)."""

    instance_profile: str = ""
    launch_template: Optional[str] = None
    subnet_selector: Dict[str, str] = field(default_factory=dict)
    security_group_selector: Dict[str, str] = field(default_factory=dict)
    tags: Dict[str, str] = field(default_factory=dict)
    metadata_options: Optional[Dict[str, object]] = None

    # -- codec (provider.go:123-148) ---------------------------------------
    @classmethod
    def deserialize(cls, constraints: Constraints) -> "AWSProvider":
        if constraints.provider is None:
            raise ValueError(
                "invariant violated: spec.provider is not defined. "
                "Is the defaulting webhook installed?")
        p = constraints.provider
        return cls(
            instance_profile=p.get("instanceProfile", ""),
            launch_template=p.get("launchTemplate"),
            subnet_selector=dict(p.get("subnetSelector") or {}),
            security_group_selector=dict(p.get("securityGroupSelector") or {}),
            tags=dict(p.get("tags") or {}),
            metadata_options=p.get("metadataOptions"),
        )

    def serialize(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "instanceProfile": self.instance_profile,
            "subnetSelector": dict(self.subnet_selector),
            "securityGroupSelector": dict(self.security_group_selector),
            "tags": dict(self.tags),
        }
        if self.launch_template is not None:
            out["launchTemplate"] = self.launch_template
        if self.metadata_options is not None:
            out["metadataOptions"] = dict(self.metadata_options)
        return out

    def get_metadata_options(self) -> Dict[str, object]:
        """Effective IMDS options (provider.go:150-160)."""
        if self.metadata_options is None:
            return dict(DEFAULT_METADATA_OPTIONS)
        return dict(self.metadata_options)

    # -- validation (provider_validation.go) --------------------------------
    def validate(self) -> List[str]:
        errs: List[str] = []
        if not self.instance_profile:
            errs.append("provider.instanceProfile: missing field")
        if not self.subnet_selector:
            errs.append("provider.subnetSelector: missing field")
        for key, value in self.subnet_selector.items():
            if key == "" or value == "":
                errs.append(f"provider.subnetSelector[{key!r}]: invalid empty value")
        if not self.security_group_selector:
            errs.append("provider.securityGroupSelector: missing field")
        for key, value in self.security_group_selector.items():
            if key == "" or value == "":
                errs.append(f"provider.securityGroupSelector[{key!r}]: invalid empty value")
        for key in self.tags:
            if key == "":
                errs.append("provider.tags: empty tag keys aren't supported")
        errs.extend(self._validate_metadata_options())
        return errs

    def _validate_metadata_options(self) -> List[str]:
        if self.metadata_options is None:
            return []
        errs = []
        for fld, allowed in _METADATA_ENUMS.items():
            v = self.metadata_options.get(fld)
            if v is not None and v not in allowed:
                errs.append(
                    f"provider.metadataOptions.{fld}: invalid value {v!r} "
                    f"(expected one of {sorted(allowed)})")
        hops = self.metadata_options.get("httpPutResponseHopLimit")
        if hops is not None and not (1 <= int(hops) <= 64):
            errs.append(
                f"provider.metadataOptions.httpPutResponseHopLimit: {hops} "
                "out of bounds [1, 64]")
        return errs


def default_constraints(constraints: Constraints) -> None:
    """Defaulting hook: architecture amd64 + capacity type on-demand unless
    already labeled/required (provider_defaults.go:26-57). Mutates in place,
    matching webhook defaulting semantics."""
    for key, default_value in (
        (wellknown.LABEL_ARCH, wellknown.ARCHITECTURE_AMD64),
        (wellknown.LABEL_CAPACITY_TYPE, CAPACITY_TYPE_ON_DEMAND),
    ):
        if key in constraints.labels:
            continue
        if key in constraints.requirements.keys():
            continue
        constraints.requirements = constraints.requirements.add(
            NodeSelectorRequirement(key=key, operator="In", values=[default_value]))


def merge_tags(provisioner_name: str, *custom: Dict[str, str]) -> Dict[str, str]:
    """Union custom tags with the discovery tags Karpenter always applies
    (tags.go:28-37); later maps win, Karpenter's own keys last."""
    merged: Dict[str, str] = {}
    for m in custom:
        merged.update(m or {})
    merged[wellknown.PROVISIONER_NAME_LABEL] = provisioner_name
    merged["Name"] = f"{wellknown.PROVISIONER_NAME_LABEL}/{provisioner_name}"
    return merged
