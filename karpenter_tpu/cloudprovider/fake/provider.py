"""Fake cloud provider: in-memory capacity substrate for tests.

Reference: pkg/cloudprovider/fake/{cloudprovider.go,instancetype.go}. Nodes
are fabricated as API objects honoring zone/capacity-type requirements; the
synthetic catalog generator matches the reference fixture exactly (i-th type
= (i+1) vCPU, 2(i+1) Gi, 10(i+1) pods) so benchmark workloads are comparable.
"""

from __future__ import annotations

import itertools
import threading
import uuid
from typing import Dict, List, Optional, Sequence

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.constraints import Constraints
from karpenter_tpu.api.core import Node, NodeCondition, NodeSpec, NodeStatus, ObjectMeta
from karpenter_tpu.chaos import inject
from karpenter_tpu.cloudprovider import spi
from karpenter_tpu.cloudprovider.spi import (
    CapacityRecord, CloudProvider, InstanceType, Offering,
)
from karpenter_tpu.runtime import journal
from karpenter_tpu.utils import clock
from karpenter_tpu.utils.resources import Quantity, parse_resource_list

_DEFAULT_OFFERINGS = [
    Offering("spot", "test-zone-1"),
    Offering("spot", "test-zone-2"),
    Offering("on-demand", "test-zone-1"),
    Offering("on-demand", "test-zone-2"),
    Offering("on-demand", "test-zone-3"),
]

_name_counter = itertools.count()


def make_instance_type(
    name: str,
    offerings: Optional[List[Offering]] = None,
    architecture: str = "amd64",
    operating_systems: frozenset = frozenset({"linux", "windows", "darwin"}),
    cpu: str = "4",
    memory: str = "4Gi",
    pods: str = "5",
    nvidia_gpus: str = "0",
    amd_gpus: str = "0",
    aws_neurons: str = "0",
    aws_pod_eni: str = "0",
    price: float = 0.0,
    tpu_topology: str = "",
) -> InstanceType:
    """fake.NewInstanceType defaults (instancetype.go:27-52)."""
    return InstanceType(
        name=name,
        offerings=list(offerings) if offerings else list(_DEFAULT_OFFERINGS),
        architecture=architecture,
        operating_systems=operating_systems,
        cpu=Quantity.parse(cpu),
        memory=Quantity.parse(memory),
        pods=Quantity.parse(pods),
        nvidia_gpus=Quantity.parse(nvidia_gpus),
        amd_gpus=Quantity.parse(amd_gpus),
        aws_neurons=Quantity.parse(aws_neurons),
        aws_pod_eni=Quantity.parse(aws_pod_eni),
        overhead=parse_resource_list({"cpu": "100m", "memory": "10Mi"}),
        price=price,
        tpu_topology=tpu_topology,
    )


def instance_types(total: int) -> List[InstanceType]:
    """Synthetic incrementing catalog (instancetype.go:73-84): i-th type =
    (i+1) vCPU, 2(i+1) Gi, 10(i+1) pods."""
    return [
        make_instance_type(
            name=f"fake-it-{i}",
            cpu=str(i + 1),
            memory=f"{(i + 1) * 2}Gi",
            pods=str((i + 1) * 10),
        )
        for i in range(total)
    ]


def tpu_catalog() -> List[InstanceType]:
    """Multi-host TPU catalog for slice-carve tests and benches: two
    2-D torus hosts (v5e 4x4 and 4x8 chip grids, priced per size), one
    REAL 3-D torus host (v4-style 2x2x4 — 16 chips on a genuine
    x·y·z grid, so the 3-D carve encoding runs end-to-end rather than
    only in oracle tests), plus a plain CPU type so non-slice pods never
    land on TPU capacity by accident."""
    return [
        make_instance_type("tpu-v5e-4x4", cpu="32", memory="64Gi",
                           pods="32", price=4.0, tpu_topology="v5e-4x4"),
        make_instance_type("tpu-v5e-4x8", cpu="64", memory="128Gi",
                           pods="64", price=8.0, tpu_topology="v5e-4x8"),
        make_instance_type("tpu-v4-2x2x4", cpu="64", memory="128Gi",
                           pods="64", price=6.0, tpu_topology="v4-2x2x4"),
        make_instance_type("cpu-standard", cpu="16", memory="64Gi",
                           pods="64", price=1.0),
    ]


def default_catalog() -> List[InstanceType]:
    """The 7-type default catalog (fake/cloudprovider.go:85-115)."""
    return [
        make_instance_type("default-instance-type"),
        make_instance_type("pod-eni-instance-type", aws_pod_eni="1"),
        make_instance_type("small-instance-type", cpu="2", memory="2Gi"),
        make_instance_type("nvidia-gpu-instance-type", nvidia_gpus="2"),
        make_instance_type("amd-gpu-instance-type", amd_gpus="2"),
        make_instance_type("aws-neuron-instance-type", aws_neurons="2"),
        make_instance_type("arm-instance-type", architecture="arm64"),
    ]


class FakeCloudProvider(CloudProvider):
    """In-memory provider fabricating Node objects (fake/cloudprovider.go:37-79)."""

    def __init__(self, catalog: Optional[Sequence[InstanceType]] = None,
                 nodes_become_ready: bool = True):
        self.catalog = list(catalog) if catalog is not None else None
        self.nodes_become_ready = nodes_become_ready
        self.created: List[Node] = []
        self.deleted: List[str] = []
        # fault injection: zero-capacity (name, zone, capacity_type) triples,
        # analog of the AWS fake's InsufficientCapacityPools
        self.insufficient_capacity: set = set()
        # provider-side capacity ledger: instance id (= node name) → record.
        # Registered BEFORE bind runs, exactly like the AWS path's
        # CreateFleet tags, so a crash between launch and node create
        # leaves an enumerable, attributable orphan for the GC controller.
        self._capacity: Dict[str, CapacityRecord] = {}
        self._lock = threading.Lock()

    def create(self, constraints, instance_types_, quantity, bind):
        errs: List[Optional[str]] = []
        provisioner_name = constraints.labels.get(
            wellknown.PROVISIONER_NAME_LABEL, "default")
        # one nonce per create call, shared by every unit it launches —
        # the same semantics as the AWS path's per-CreateFleet launch-nonce
        # tag. When the caller journaled the launch, its pre-stamped nonce
        # is used so crashed launches stay attributable across restart.
        launch_nonce = journal.current_preassigned_nonce() or uuid.uuid4().hex
        for _ in range(quantity):
            n = next(_name_counter)
            name = f"fake-node-{n}"
            instance = instance_types_[0]
            zone = capacity_type = ""
            cts = constraints.requirements.capacity_types() or frozenset()
            zones = constraints.requirements.zones() or frozenset()
            for o in instance.offerings:
                if o.capacity_type in cts and o.zone in zones:
                    zone, capacity_type = o.zone, o.capacity_type
                    break
            # one fault draw per unit of capacity: ICE prevents the launch,
            # crash-before-bind leaks it (see below), spot-interruption
            # reclaims running spot capacity out-of-band
            fault = inject.active_fault("provider", "create")
            if fault == "spot-interruption":
                # an interruption lands concurrently with provisioning: the
                # oldest spot instance vanishes from the ledger (its Node
                # survives as a ghost for GC; its pods must repack) while
                # THIS launch proceeds normally — the fault is about the
                # fleet already running, not the unit being created
                self.reclaim_spot(1)
            if ((instance.name, zone, capacity_type) in self.insufficient_capacity
                    or fault == "ice"):
                errs.append(f"insufficient capacity for {instance.name} in {zone}")
                continue
            # capacity exists from this point on — the ledger entry is the
            # fake analog of a launched EC2 instance
            with self._lock:
                self._capacity[name] = CapacityRecord(
                    instance_id=name,
                    provisioner_name=provisioner_name,
                    launch_nonce=launch_nonce,
                    created_unix=clock.now(),
                    zone=zone,
                    instance_type=instance.name,
                    capacity_type=capacity_type,
                )
            if fault == "crash-before-bind":
                # controller dies between the launch and the node write:
                # the instance above is now leaked until GC reaps it
                errs.append(f"injected crash before bind of {name}")
                continue
            node = Node(
                metadata=ObjectMeta(
                    name=name,
                    namespace="",
                    labels={
                        wellknown.LABEL_TOPOLOGY_ZONE: zone,
                        wellknown.LABEL_INSTANCE_TYPE: instance.name,
                        wellknown.LABEL_CAPACITY_TYPE: capacity_type,
                    },
                ),
                spec=NodeSpec(provider_id=f"fake:///{name}/{zone}"),
                status=NodeStatus(
                    capacity=parse_resource_list({
                        "pods": str(instance.pods), "cpu": str(instance.cpu),
                        "memory": str(instance.memory)}),
                    allocatable=parse_resource_list({
                        "pods": str(instance.pods), "cpu": str(instance.cpu),
                        "memory": str(instance.memory)}),
                    # fake capacity "boots" instantly: the Ready condition the
                    # kubelet would eventually report is present from birth,
                    # so the liveness reaper (node/liveness.go) doesn't churn
                    # nodes in a kubelet-less control plane. Tests that need
                    # a not-yet-joined node overwrite status explicitly.
                    conditions=(
                        [NodeCondition(type="Ready", status="True",
                                       reason="KubeletReady")]
                        if self.nodes_become_ready else []),
                ),
            )
            with self._lock:
                self.created.append(node)
            errs.append(bind(node))
        return errs

    def delete(self, node: Node) -> Optional[str]:
        with self._lock:
            self.deleted.append(node.metadata.name)
            # fake providerID is fake:///<instance-id>/<zone>; the instance
            # id doubles as the node name
            parts = (node.spec.provider_id or "").split("/")
            instance_id = parts[3] if len(parts) > 3 else node.metadata.name
            self._capacity.pop(instance_id, None)
        return None

    def list_instances(self) -> List[CapacityRecord]:
        with self._lock:
            return list(self._capacity.values())

    def delete_instance(self, instance_id: str) -> Optional[str]:
        with self._lock:
            if self._capacity.pop(instance_id, None) is not None:
                self.deleted.append(instance_id)
        return None  # not-found is success: the capacity is gone either way

    def reclaim_spot(self, limit: int = 1) -> List[str]:
        """Out-of-band termination of up to ``limit`` spot instances — the
        fake analog of an EC2 spot interruption. The ledger entry vanishes
        (exactly what DescribeInstances would stop returning) while any Node
        object survives as a ghost for GC to reap; pods on it must repack.
        Returns the reclaimed instance ids, oldest launches first so soaks
        are deterministic under a fixed creation order."""
        with self._lock:
            spot = sorted(
                (r for r in self._capacity.values()
                 if r.capacity_type == wellknown.CAPACITY_TYPE_SPOT),
                key=lambda r: (r.created_unix, r.instance_id))
            victims = [r.instance_id for r in spot[:max(0, limit)]]
            for iid in victims:
                self._capacity.pop(iid, None)
                self.deleted.append(iid)
        return victims

    def get_instance_types(self, constraints: Constraints) -> List[InstanceType]:
        if self.catalog is not None:
            return list(self.catalog)
        return default_catalog()

    def name(self) -> str:
        return "fake"


spi.register("fake", FakeCloudProvider)
