"""CloudProvider metrics decorator.

Reference: pkg/cloudprovider/metrics/cloudprovider.go:65-92 — every SPI
method is wrapped in a ``cloudprovider_duration_seconds{method, provider}``
histogram, installed unconditionally at cmd/controller/main.go:76-77 so
provider latency (CreateFleet, DescribeInstanceTypes, admission hooks) is
always visible at /metrics. The decorator is transparent: it satisfies the
same CloudProvider contract and forwards everything, timing included
failures (the Go defer-timer records on panic too).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from karpenter_tpu.api.constraints import Constraints
from karpenter_tpu.api.core import Node
from karpenter_tpu.cloudprovider.spi import (
    BindCallback, CloudProvider, InstanceType,
)
from karpenter_tpu.metrics.registry import HISTOGRAMS

METRIC = "cloudprovider_duration_seconds"


class MeteredCloudProvider(CloudProvider):
    """Wraps any provider so all five SPI methods emit duration histograms
    (metrics/cloudprovider.go:65-92: Create/Delete/GetInstanceTypes/
    Default/Validate)."""

    def __init__(self, inner: CloudProvider):
        self._inner = inner
        self._provider = inner.name()

    def _timer(self, method: str):
        return HISTOGRAMS.time(METRIC, method=method, provider=self._provider)

    def create(
        self,
        constraints: Constraints,
        instance_types: Sequence[InstanceType],
        quantity: int,
        bind: BindCallback,
    ) -> List[Optional[str]]:
        with self._timer("Create"):
            return self._inner.create(constraints, instance_types, quantity, bind)

    def delete(self, node: Node) -> Optional[str]:
        with self._timer("Delete"):
            return self._inner.delete(node)

    def list_instances(self):
        # GC enumeration latency matters operationally (a paged
        # DescribeInstances sweep across a big cluster) — metered like the
        # rest of the SPI surface
        with self._timer("ListInstances"):
            return self._inner.list_instances()

    def delete_instance(self, instance_id: str) -> Optional[str]:
        with self._timer("DeleteInstance"):
            return self._inner.delete_instance(instance_id)

    def get_instance_types(self, constraints: Constraints) -> List[InstanceType]:
        with self._timer("GetInstanceTypes"):
            return self._inner.get_instance_types(constraints)

    def default(self, constraints: Constraints) -> None:
        with self._timer("Default"):
            return self._inner.default(constraints)

    def validate(self, constraints: Constraints) -> Optional[str]:
        with self._timer("Validate"):
            return self._inner.validate(constraints)

    def name(self) -> str:
        return self._inner.name()

    def __getattr__(self, item):
        # provider-specific extras (fake fault injection, AWS sub-providers)
        # pass through untimed — only the SPI surface is metered. Dunder/
        # private lookups raise instead of dereferencing _inner: during
        # unpickle/deepcopy __getattr__ runs before __dict__ is restored and
        # a _inner dereference would recurse forever.
        if item.startswith("_"):
            raise AttributeError(item)
        return getattr(self._inner, item)


def decorate(provider: CloudProvider) -> MeteredCloudProvider:
    """Idempotent wrap (a double-decorated provider would double-count)."""
    if isinstance(provider, MeteredCloudProvider):
        return provider
    return MeteredCloudProvider(provider)
