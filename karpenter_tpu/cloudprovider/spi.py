"""CloudProvider SPI — the plugin boundary.

Preserves the reference's provider contract (pkg/cloudprovider/types.go:29-76)
so provider implementations are interchangeable: Create is callback-based to
let providers batch node launches; GetInstanceTypes returns the live catalog
filtered by constraints; Default/Validate hook into admission.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from karpenter_tpu.api.constraints import Constraints
from karpenter_tpu.api.core import Node
from karpenter_tpu.utils.resources import Quantity, ResourceList


@dataclass(frozen=True)
class Offering:
    """A (capacity type, zone) pair an instance type is available in
    (types.go:73-76).

    ``interruption_rate`` is the provider's expected reclaims/hour for this
    offering (0 for on-demand; spot offerings carry the published pool
    volatility). It is advisory pricing input for the interruption-priced
    scoring policy (solver/policy.py) — feasibility never consults it."""

    capacity_type: str  # "spot" | "on-demand"
    zone: str
    interruption_rate: float = 0.0


@dataclass(frozen=True)
class CapacityRecord:
    """Provider-side view of one unit of live capacity, as enumerated by
    :meth:`CloudProvider.list_instances`.

    This is the raw material of crash recovery: the garbage-collection
    controller (controllers/gc.py) cross-references these records against
    Node objects to find capacity the control plane paid for but lost track
    of (a crash between Create and the node write, a bind failure) and
    Nodes whose backing capacity was terminated out-of-band.

    ``instance_id`` must appear verbatim as a path segment of the
    providerID the provider stamps on Nodes it creates (aws:///<zone>/<id>,
    fake:///<id>/<zone>) — that containment is the ownership test GC uses.
    ``launch_nonce`` is stamped as a provider tag at launch time, BEFORE
    any node object exists, so an orphan is attributable to the launch
    that leaked it."""

    instance_id: str
    provisioner_name: str = ""
    launch_nonce: str = ""
    created_unix: float = 0.0
    zone: str = ""
    instance_type: str = ""
    # capacity type the launch drew from ("spot" | "on-demand"); lets the
    # spot-interruption chaos boundary and reclaim tooling target spot
    # capacity without consulting Node labels (which may not exist yet).
    capacity_type: str = ""


@dataclass
class InstanceType:
    """Concrete instance type description (types.go:55-69).

    The reference models this as an interface over provider data; here it is
    a value type every provider materializes. ``price`` is an extension used
    by the cost-minimizing solver model (absent in the reference, which
    delegates price decisions to EC2 Fleet).
    """

    name: str
    offerings: List[Offering] = field(default_factory=list)
    architecture: str = "amd64"
    operating_systems: frozenset = frozenset({"linux"})
    cpu: Quantity = field(default_factory=lambda: Quantity(0))
    memory: Quantity = field(default_factory=lambda: Quantity(0))
    pods: Quantity = field(default_factory=lambda: Quantity(0))
    nvidia_gpus: Quantity = field(default_factory=lambda: Quantity(0))
    amd_gpus: Quantity = field(default_factory=lambda: Quantity(0))
    aws_neurons: Quantity = field(default_factory=lambda: Quantity(0))
    aws_pod_eni: Quantity = field(default_factory=lambda: Quantity(0))
    overhead: ResourceList = field(default_factory=dict)
    price: float = 0.0
    # TPU slice topology this type advertises ("v5e-4x4"; "" = none). Gangs
    # carrying a pod-group-slice label only land on types whose topology
    # contains the requested shape (api/gang.py, ops/feasibility.py).
    tpu_topology: str = ""

    def grid_dims(self) -> Optional[Tuple[int, ...]]:
        """Chip-grid dimensions of the advertised TPU topology — the
        per-type torus the carving engine (ops/topology.py) models
        occupancy over — or None when the type hosts no slices. Parsed
        once and cached on the instance, same idiom as
        api/gang.instance_slice_shape."""
        cached = self.__dict__.get("_grid_dims", False)
        if cached is not False:
            return cached
        from karpenter_tpu.api.gang import instance_slice_shape
        shape = instance_slice_shape(self)
        dims = shape.dims if shape is not None else None
        self.__dict__["_grid_dims"] = dims
        return dims


BindCallback = Callable[[Node], Optional[str]]


class CloudProvider(abc.ABC):
    """Provider contract (types.go:29-46)."""

    @abc.abstractmethod
    def create(
        self,
        constraints: Constraints,
        instance_types: Sequence[InstanceType],
        quantity: int,
        bind: BindCallback,
    ) -> List[Optional[str]]:
        """Launch ``quantity`` nodes drawn from ``instance_types`` and invoke
        ``bind`` for each created node. Returns per-node errors (None=ok)."""

    @abc.abstractmethod
    def delete(self, node: Node) -> Optional[str]:
        """Terminate the capacity backing ``node``."""

    @abc.abstractmethod
    def get_instance_types(self, constraints: Constraints) -> List[InstanceType]:
        """The catalog viable for these constraints (cached by providers)."""

    def list_instances(self) -> List[CapacityRecord]:
        """Enumerate the provider-side capacity this control plane launched
        (upstream Karpenter's DescribeInstances-by-tag garbage-collection
        input). The default returns nothing, which degrades the GC
        controller to a no-op for providers that cannot enumerate — it must
        NEVER be implemented by returning a partial view, because records
        missing here read as out-of-band terminations and get their Nodes
        reaped."""
        return []

    def delete_instance(self, instance_id: str) -> Optional[str]:
        """Terminate capacity by provider instance id — for orphans that
        never got a Node object, where :meth:`delete` has nothing to work
        from. NotFound-equivalent outcomes are success (the capacity is
        gone either way). None means terminated."""
        return f"provider {self.name()} cannot terminate by instance id"

    def default(self, constraints: Constraints) -> None:
        """Defaulting webhook hook (registry/register.go:25-31)."""

    def validate(self, constraints: Constraints) -> Optional[str]:
        """Validation webhook hook; None means valid."""

    @abc.abstractmethod
    def name(self) -> str:
        ...


# ---------------------------------------------------------------------------
# Registry: runtime provider selection. The reference selects at compile time
# via build tags (registry/aws.go); a Python framework selects by name with
# the fake provider as the default fallback (registry/fake.go).
# ---------------------------------------------------------------------------

_REGISTRY = {}


def register(name: str, factory) -> None:
    _REGISTRY[name] = factory


def resolve(name: str, **kwargs) -> CloudProvider:
    if name not in _REGISTRY:
        raise KeyError(f"unknown cloud provider {name!r}; registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


def registered() -> List[str]:
    return sorted(_REGISTRY)
