"""Process options: flags + environment.

Reference: pkg/utils/options/options.go:33-76. Flags fall back to
KARPENTER_-prefixed environment variables; validation mirrors the
reference's required-field and port checks.
"""

from __future__ import annotations

import argparse
import os
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class Options:
    cluster_name: str = ""
    cluster_endpoint: str = ""
    metrics_port: int = 8080
    health_probe_port: int = 8081
    webhook_port: int = 8443
    kube_client_qps: int = 200
    kube_client_burst: int = 300
    cloud_provider: str = "fake"
    # the controller's own namespace: where config-logging lives and where
    # the election Lease is written. Defaults from the POD_NAMESPACE
    # downward-API env (deploy/controller.yaml) so the deployed namespace
    # ("karpenter") wins over the dev default.
    namespace: str = field(
        default_factory=lambda: os.environ.get("POD_NAMESPACE", "default"))
    # API backend: "in-cluster" (real API server via the service account,
    # runtime/kubeclient.py) or "memory" (runtime/kubecore.py — dev/tests)
    kube_backend: str = "memory"
    # single-writer guard across replicas (cmd/controller/main.go:80-81)
    leader_elect: bool = False
    # batching (batcher.go:23-28 defaults; max_items raised — see batcher.py)
    batch_idle_seconds: float = 1.0
    batch_max_seconds: float = 10.0
    batch_max_items: int = 50_000
    # horizontal control-plane shards (docs/scale.md §1): N long-lived
    # intake/provisioning workers, provisioners assigned by crc32(name)%N;
    # 0 = one worker per Provisioner CR (the reference's shape)
    provisioning_shards: int = 0
    # solver
    solver_use_device: bool = True
    # pipelined hot loop (solver/pipeline.py): dispatched-but-unfetched
    # solve chunks in flight (1 = serial; collapses to 1 at pressure L1+)
    pipeline_depth: int = 2
    # L0 chunk size the pipeline overlaps over; applied at every depth so
    # serial and pipelined runs see identical chunk boundaries; 0 disables
    pipeline_chunk_items: int = 4096
    # step the depth 1↔3 from measured per-window overlap instead of
    # pinning the flag (solver/pipeline.py _AdaptiveDepth); pipeline-depth
    # becomes the starting point
    pipeline_adaptive: bool = True
    # device ring + buffer donation (solver/pipeline.py DeviceRing):
    # steady-state chunks refill device-resident buffers in place instead
    # of allocating; off restores fresh device_puts per chunk
    solver_donate: bool = True
    # pre-compile the (shape × type) bucket ladder at boot (solver/warmup.py)
    solver_warmup: bool = False
    # packing policy (solver/policy.py registry): cheapest |
    # interruption-priced | throughput-per-dollar. The default preserves
    # today's cheapest-feasible ordering/tiebreak bit-for-bit.
    packing_policy: str = "cheapest"
    # pins the interruption-priced policy's repack price ($/h) instead of the
    # per-chunk what-if estimate; 0 = let the what-if engine price each chunk.
    # Also the consolidation keep-cost premium on spot nodes (rate x this).
    policy_repack_cost: float = 0.0
    # provisioning-window packing backend (solver/global_solve.py): ffd |
    # global. "global" solves the whole window jointly as one batched
    # ADMM relaxation with FFD as the exact rounding oracle and the
    # bit-for-bit fallback; pressure L1+ and gang schedules keep FFD, and
    # KARPENTER_GLOBAL_SOLVE=0 kills the global path regardless. Default
    # since PR 18 (docs/solver.md §18): the relaxation only replaces FFD
    # plans it strictly beats in exact micro-$, so the flip is cost-
    # monotone; --window-backend=ffd restores the previous behavior.
    window_backend: str = "global"
    # JAX persistent compilation cache dir ("" disables): restarts re-load
    # compiled programs instead of re-lowering them
    solver_compile_cache_dir: str = ""
    # capacity garbage collection (controllers/gc.py): sweep cadence and the
    # both-directions grace window; 0 interval disables the controller
    gc_interval_seconds: float = 120.0
    gc_grace_seconds: float = 600.0
    # brownout / pressure ladder (karpenter_tpu/pressure/,
    # docs/robustness.md §4)
    pressure_enabled: bool = True
    pressure_max_depth: int = 100_000       # batcher hard depth bound
    pressure_rss_watermark_mb: int = 4096   # L3 RSS watermark; 0 disables
    pressure_dwell_seconds: float = 5.0     # hysteresis dwell per rung
    pressure_split_items: int = 4096        # L1+ max pods per solve chunk
    pressure_aging_seconds: float = 60.0    # one band promotion per step
    # observability (karpenter_tpu/obs/, docs/observability.md): span tracer
    # off by default — enabled it costs ~µs/span, disabled it is a no-op
    trace_enabled: bool = False
    # write a Chrome-trace-event dump here on shutdown ("" disables)
    trace_dump: str = ""
    # wrap device-solve spans in jax.profiler.TraceAnnotation so an XLA
    # profile capture (KARPENTER_PROFILE_PORT) correlates to window spans
    trace_jax: bool = False
    # flight recorder dump directory ("" keeps the ring in memory only)
    flight_dir: str = ""
    # write-ahead intent journal directory (runtime/journal.py,
    # docs/robustness.md §5); "" disables journaling AND startup recovery
    journal_dir: str = ""
    # fsync every journal append (crash-safe); disable only for benches
    # where the journal's durability is not under test
    journal_fsync: bool = True
    # per-pod SLO engine (obs/slo.py, docs/observability.md §7): mergeable
    # latency digests per (band × stage) + burn-rate sentinel; ~µs/pod
    # enabled, a no-op branch disabled
    slo_enabled: bool = True
    # objective overrides, "band=seconds[:target]" comma-separated — e.g.
    # "default=30,high=20:0.995"; "" keeps the built-in defaults
    # (system-critical 30s, high 45s, default 60s, all at 0.99)
    slo_objectives: str = ""
    # burn-rate windows and thresholds (multi-window multi-burn alerting:
    # burning iff fast-window burn >= fast AND slow-window burn >= slow)
    slo_fast_window_seconds: float = 60.0
    slo_slow_window_seconds: float = 1800.0
    slo_fast_burn: float = 6.0
    slo_slow_burn: float = 1.0
    # AWS provider (options.go:45-49)
    aws_node_name_convention: str = "ip-name"  # ip-name | resource-name
    aws_eni_limited_pod_density: bool = True

    def validate(self) -> List[str]:
        errs = []
        if not self.cluster_name:
            errs.append("cluster-name is required")
        if not self.cluster_endpoint:
            errs.append("cluster-endpoint is required")
        for name, port in (("metrics-port", self.metrics_port),
                           ("health-probe-port", self.health_probe_port),
                           ("webhook-port", self.webhook_port)):
            if not (0 < port < 65536):
                errs.append(f"{name} out of range: {port}")
        if self.kube_backend not in ("memory", "in-cluster"):
            errs.append(f"kube-backend invalid: {self.kube_backend}")
        if self.gc_interval_seconds < 0 or self.gc_grace_seconds < 0:
            errs.append("gc-interval-seconds/gc-grace-seconds must be >= 0")
        if self.pressure_max_depth < 1:
            errs.append(
                f"pressure-max-depth must be >= 1: {self.pressure_max_depth}")
        if self.pressure_rss_watermark_mb < 0:
            errs.append("pressure-rss-watermark-mb must be >= 0")
        if self.pressure_dwell_seconds < 0:
            errs.append("pressure-dwell-seconds must be >= 0")
        if self.pressure_split_items < 1:
            errs.append(
                f"pressure-split-items must be >= 1: {self.pressure_split_items}")
        if self.pressure_aging_seconds < 0:
            errs.append("pressure-aging-seconds must be >= 0")
        if self.provisioning_shards < 0:
            errs.append("provisioning-shards must be >= 0 (0 = one worker "
                        f"per provisioner): {self.provisioning_shards}")
        if self.pipeline_depth < 1:
            errs.append(f"pipeline-depth must be >= 1: {self.pipeline_depth}")
        if self.pipeline_chunk_items < 0:
            errs.append("pipeline-chunk-items must be >= 0 (0 disables "
                        f"chunking): {self.pipeline_chunk_items}")
        if self.slo_fast_window_seconds <= 0 or self.slo_slow_window_seconds <= 0:
            errs.append("slo-fast/slow-window-seconds must be > 0")
        if self.slo_fast_burn <= 0 or self.slo_slow_burn <= 0:
            errs.append("slo-fast/slow-burn must be > 0")
        if self.slo_objectives:
            try:
                self.parse_slo_objectives()
            except ValueError as e:
                errs.append(f"slo-objectives invalid: {e}")
        from karpenter_tpu.solver import policy as packing_policies

        if self.packing_policy not in packing_policies.available():
            errs.append(f"packing-policy invalid: {self.packing_policy} "
                        f"(available: {packing_policies.available()})")
        if self.policy_repack_cost < 0:
            errs.append(
                f"policy-repack-cost invalid: {self.policy_repack_cost}")
        if self.window_backend not in ("ffd", "global"):
            errs.append(f"window-backend invalid: {self.window_backend} "
                        "(available: ffd | global)")
        if self.aws_node_name_convention not in ("ip-name", "resource-name"):
            errs.append(
                f"aws-node-name-convention invalid: {self.aws_node_name_convention}")
        return errs

    def parse_slo_objectives(self) -> dict:
        """Parse ``slo_objectives`` ("band=seconds[:target]", comma-sep)
        into ``{band: (threshold_s, target)}``. Raises ValueError on a
        malformed entry (surfaced by validate())."""
        out = {}
        for entry in self.slo_objectives.split(","):
            entry = entry.strip()
            if not entry:
                continue
            if "=" not in entry:
                raise ValueError(f"expected band=seconds[:target]: {entry!r}")
            band, _, rest = entry.partition("=")
            threshold, _, target = rest.partition(":")
            threshold_s = float(threshold)
            target_f = float(target) if target else 0.99
            if threshold_s <= 0:
                raise ValueError(f"threshold must be > 0: {entry!r}")
            if not (0.0 < target_f < 1.0):
                raise ValueError(f"target must be in (0, 1): {entry!r}")
            out[band.strip()] = (threshold_s, target_f)
        return out


def _env(name: str, default):
    v = os.environ.get(f"KARPENTER_{name.upper().replace('-', '_')}")
    if v is None:
        return default
    if isinstance(default, bool):
        return v.lower() in ("1", "true", "yes")
    if isinstance(default, int):
        return int(v)
    if isinstance(default, float):
        return float(v)
    return v


def parse(argv: Optional[List[str]] = None) -> Options:
    defaults = Options()
    p = argparse.ArgumentParser("karpenter-tpu")
    p.add_argument("--cluster-name", default=_env("cluster-name", defaults.cluster_name))
    p.add_argument("--cluster-endpoint",
                   default=_env("cluster-endpoint", defaults.cluster_endpoint))
    p.add_argument("--metrics-port", type=int,
                   default=_env("metrics-port", defaults.metrics_port))
    p.add_argument("--health-probe-port", type=int,
                   default=_env("health-probe-port", defaults.health_probe_port))
    p.add_argument("--webhook-port", type=int,
                   default=_env("webhook-port", defaults.webhook_port))
    p.add_argument("--kube-client-qps", type=int,
                   default=_env("kube-client-qps", defaults.kube_client_qps))
    p.add_argument("--kube-client-burst", type=int,
                   default=_env("kube-client-burst", defaults.kube_client_burst))
    p.add_argument("--cloud-provider",
                   default=_env("cloud-provider", defaults.cloud_provider))
    p.add_argument("--namespace",
                   default=_env("namespace", defaults.namespace))
    p.add_argument("--kube-backend", choices=["memory", "in-cluster"],
                   default=_env("kube-backend", defaults.kube_backend))
    p.add_argument("--leader-elect", action=argparse.BooleanOptionalAction,
                   default=_env("leader-elect", defaults.leader_elect))
    p.add_argument("--batch-idle-seconds", type=float,
                   default=_env("batch-idle-seconds", defaults.batch_idle_seconds))
    p.add_argument("--batch-max-seconds", type=float,
                   default=_env("batch-max-seconds", defaults.batch_max_seconds))
    p.add_argument("--batch-max-items", type=int,
                   default=_env("batch-max-items", defaults.batch_max_items))
    p.add_argument("--provisioning-shards", type=int,
                   default=_env("provisioning-shards",
                                defaults.provisioning_shards),
                   help="horizontal control-plane shards: N long-lived "
                        "intake/provisioning workers keyed by provisioner "
                        "hash (0 = one worker per Provisioner CR)")
    p.add_argument("--solver-use-device", action=argparse.BooleanOptionalAction,
                   default=_env("solver-use-device", defaults.solver_use_device))
    p.add_argument("--pipeline-depth", type=int,
                   default=_env("pipeline-depth", defaults.pipeline_depth),
                   help="provisioning pipeline depth: solve chunks in "
                        "flight (1=serial; collapses to 1 at pressure L1+)")
    p.add_argument("--pipeline-chunk-items", type=int,
                   default=_env("pipeline-chunk-items",
                                defaults.pipeline_chunk_items),
                   help="max pods per pipelined solve chunk at L0 "
                        "(0 disables chunking)")
    p.add_argument("--pipeline-adaptive",
                   action=argparse.BooleanOptionalAction,
                   default=_env("pipeline-adaptive",
                                defaults.pipeline_adaptive),
                   help="adapt pipeline depth 1-3 to measured overlap "
                        "(pipeline-depth is the starting point)")
    p.add_argument("--solver-donate", action=argparse.BooleanOptionalAction,
                   default=_env("solver-donate", defaults.solver_donate),
                   help="device buffer ring + donation: steady-state solve "
                        "chunks reuse device memory in place")
    p.add_argument("--solver-warmup", action=argparse.BooleanOptionalAction,
                   default=_env("solver-warmup", defaults.solver_warmup),
                   help="pre-compile the solver bucket ladder at boot on a "
                        "background thread (solver/warmup.py)")
    p.add_argument("--packing-policy",
                   default=_env("packing-policy", defaults.packing_policy),
                   help="packing-policy scoring (solver/policy.py): "
                        "cheapest (default, preserves cheapest-feasible "
                        "exactly) | interruption-priced (spot taxed by "
                        "reclaim-rate x what-if repack cost) | "
                        "throughput-per-dollar (heterogeneous accelerator "
                        "catalogs)")
    p.add_argument("--policy-repack-cost", type=float,
                   default=_env("policy-repack-cost",
                                defaults.policy_repack_cost),
                   help="pin the interruption-priced policy's repack price "
                        "($/h); 0 lets the what-if engine price each chunk")
    p.add_argument("--window-backend", choices=["ffd", "global"],
                   default=_env("window-backend", defaults.window_backend),
                   help="provisioning-window packing backend: global "
                        "(whole-window ADMM relaxation with FFD as the "
                        "exact rounding oracle and bit-for-bit fallback; "
                        "the default — L1+ pressure and gang schedules "
                        "keep ffd) | ffd (per-schedule greedy batch, the "
                        "pre-v18 default)")
    p.add_argument("--solver-compile-cache-dir",
                   default=_env("solver-compile-cache-dir",
                                defaults.solver_compile_cache_dir),
                   help="JAX persistent compilation cache directory "
                        "(empty disables)")
    p.add_argument("--gc-interval-seconds", type=float,
                   default=_env("gc-interval-seconds", defaults.gc_interval_seconds))
    p.add_argument("--gc-grace-seconds", type=float,
                   default=_env("gc-grace-seconds", defaults.gc_grace_seconds))
    p.add_argument("--pressure-enabled", action=argparse.BooleanOptionalAction,
                   default=_env("pressure-enabled", defaults.pressure_enabled),
                   help="brownout ladder: pressure-aware admission/shedding")
    p.add_argument("--pressure-max-depth", type=int,
                   default=_env("pressure-max-depth",
                                defaults.pressure_max_depth),
                   help="hard bound on pods awaiting a batch window")
    p.add_argument("--pressure-rss-watermark-mb", type=int,
                   default=_env("pressure-rss-watermark-mb",
                                defaults.pressure_rss_watermark_mb),
                   help="process RSS watermark (MiB) for L2/L3; 0 disables")
    p.add_argument("--pressure-dwell-seconds", type=float,
                   default=_env("pressure-dwell-seconds",
                                defaults.pressure_dwell_seconds),
                   help="seconds below a rung before the ladder steps down")
    p.add_argument("--pressure-split-items", type=int,
                   default=_env("pressure-split-items",
                                defaults.pressure_split_items),
                   help="max pods per solve chunk when splitting at L1+")
    p.add_argument("--pressure-aging-seconds", type=float,
                   default=_env("pressure-aging-seconds",
                                defaults.pressure_aging_seconds),
                   help="queued/shed pods gain one priority band per step")
    p.add_argument("--trace-enabled", action=argparse.BooleanOptionalAction,
                   default=_env("trace-enabled", defaults.trace_enabled),
                   help="span tracer (obs/trace.py): per-window spans with "
                        "stage children; disabled mode is a no-op")
    p.add_argument("--trace-dump",
                   default=_env("trace-dump", defaults.trace_dump),
                   help="write a Chrome-trace-event JSON dump here on "
                        "shutdown (empty disables)")
    p.add_argument("--trace-jax", action=argparse.BooleanOptionalAction,
                   default=_env("trace-jax", defaults.trace_jax),
                   help="annotate device-solve spans into the XLA profiler "
                        "timeline (jax.profiler.TraceAnnotation)")
    p.add_argument("--flight-dir",
                   default=_env("flight-dir", defaults.flight_dir),
                   help="flight recorder dump directory for watchdog/"
                        "breaker/pressure-L3/chaos trips (empty = in-memory "
                        "ring only)")
    p.add_argument("--journal-dir",
                   default=_env("journal-dir", defaults.journal_dir),
                   help="write-ahead intent journal directory; every multi-"
                        "step mutation (launch/bind/gang/drain/delete) is "
                        "journaled there and replayed by startup recovery "
                        "(empty disables journaling and recovery)")
    p.add_argument("--journal-fsync", action=argparse.BooleanOptionalAction,
                   default=_env("journal-fsync", defaults.journal_fsync),
                   help="fsync every journal append (crash durability); "
                        "--no-journal-fsync trades that for speed in "
                        "benches")
    p.add_argument("--slo-enabled", action=argparse.BooleanOptionalAction,
                   default=_env("slo-enabled", defaults.slo_enabled),
                   help="per-pod SLO engine (obs/slo.py): latency digests "
                        "per band/stage + burn-rate sentinel")
    p.add_argument("--slo-objectives",
                   default=_env("slo-objectives", defaults.slo_objectives),
                   help="objective overrides, band=seconds[:target] comma-"
                        "separated (empty keeps built-in defaults)")
    p.add_argument("--slo-fast-window-seconds", type=float,
                   default=_env("slo-fast-window-seconds",
                                defaults.slo_fast_window_seconds),
                   help="fast burn-rate window")
    p.add_argument("--slo-slow-window-seconds", type=float,
                   default=_env("slo-slow-window-seconds",
                                defaults.slo_slow_window_seconds),
                   help="slow burn-rate window")
    p.add_argument("--slo-fast-burn", type=float,
                   default=_env("slo-fast-burn", defaults.slo_fast_burn),
                   help="fast-window burn-rate trip threshold")
    p.add_argument("--slo-slow-burn", type=float,
                   default=_env("slo-slow-burn", defaults.slo_slow_burn),
                   help="slow-window burn-rate trip threshold")
    p.add_argument("--aws-node-name-convention",
                   choices=["ip-name", "resource-name"],
                   default=_env("aws-node-name-convention",
                                defaults.aws_node_name_convention))
    p.add_argument("--aws-eni-limited-pod-density",
                   action=argparse.BooleanOptionalAction,
                   default=_env("aws-eni-limited-pod-density",
                                defaults.aws_eni_limited_pod_density))
    ns = p.parse_args(argv)
    return Options(**{k.replace("-", "_"): v for k, v in vars(ns).items()})
