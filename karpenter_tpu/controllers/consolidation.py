"""Consolidation controller: drain under-utilized nodes one safe step at a
time.

A deprovisioning capability beyond the reference (which only deletes empty
nodes, node/emptiness.go). Per Provisioner with ``consolidationEnabled``:
find a ready node whose reschedulable pods provably fit in the surviving
nodes' free capacity (models/consolidate.py), delete it, and let the
existing machinery do the rest — the termination finalizer cordons/drains
(termination/terminate.go flow), evicted pods go pending, selection routes
them, and they land on the surviving capacity or trigger a cheaper launch.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.core import Node, Pod
from karpenter_tpu.models.consolidate import removable_nodes
from karpenter_tpu.runtime.kubecore import KubeCore, NotFound
from karpenter_tpu.utils import node as nodeutil

log = logging.getLogger("karpenter.consolidation")


class ConsolidationController:
    """Watches Provisioners; one consolidation action per reconcile."""

    REQUEUE_SECONDS = 30.0

    def __init__(self, kube: KubeCore, max_actions_per_pass: int = 1):
        self.kube = kube
        self.max_actions_per_pass = max_actions_per_pass

    def kind(self) -> str:
        return "Provisioner"

    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        try:
            provisioner = self.kube.get("Provisioner", name, namespace)
        except NotFound:
            return None
        if not provisioner.spec.consolidation_enabled:
            return None
        if provisioner.metadata.deletion_timestamp is not None:
            return None

        candidates: List[Node] = []
        pods_by_node: Dict[str, List[Pod]] = {}
        for node in self.kube.list("Node"):
            if node.metadata.labels.get(wellknown.PROVISIONER_NAME_LABEL) != name:
                continue
            # only consolidate settled capacity: ready, not being deleted
            if node.metadata.deletion_timestamp is not None:
                continue
            if not nodeutil.is_ready(node):
                continue
            candidates.append(node)
            pods_by_node[node.metadata.name] = self.kube.pods_on_node(
                node.metadata.name)

        for node in removable_nodes(
                candidates, pods_by_node, max_actions=self.max_actions_per_pass):
            log.info("consolidating node %s (%d pods fit on surviving capacity)",
                     node.metadata.name, len(pods_by_node[node.metadata.name]))
            try:
                self.kube.delete("Node", node.metadata.name, node.metadata.namespace)
            except NotFound:
                pass
        return self.REQUEUE_SECONDS
