"""Consolidation controller: one batched what-if solve per window.

A deprovisioning capability beyond the reference (which only deletes empty
nodes, node/emptiness.go). Per Provisioner with ``consolidationEnabled``,
each reconcile runs ONE window:

1. Gather settled capacity (ready, not deleting) into bins and filter the
   candidates that may actually drain: a ``karpenter.sh/do-not-evict`` pod
   pins its node, and a node whose movable pods would breach a
   PodDisruptionBudget's headroom (or whose PDBs are misconfigured — >1
   selecting a pod, or both minAvailable and maxUnavailable set — which
   the eviction subresource 500s) never enters the batch.
2. Encode "cluster minus node i" for every candidate i as one tensor
   program (ops/whatif.py) and solve the whole window in a single batched
   device call (solver/whatif.py) riding the DeviceRing + watchdog — N
   candidate evaluations for one device round trip.
3. Score feasible drains in $/h (models/cost.py via fleet_prices) and
   execute the cheapest feasible multi-node plan, each drain re-verified
   exactly on host before its delete (zero unverified drains). Deletion
   rides the existing termination finalizer flow — cordon/drain, evicted
   pods go pending, selection routes them onto surviving capacity.

Nodes whose instance type has left the catalog price at $0 but REMAIN
candidates (the old path silently skipped them, so they were never
consolidated); they are logged once per window with a counter.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.core import Node, Pod
from karpenter_tpu.metrics.consolidation import (
    CONSOLIDATION_CANDIDATES_TOTAL, CONSOLIDATION_DRAINS_TOTAL,
    CONSOLIDATION_FILTERED_TOTAL, CONSOLIDATION_RECLAIMED_TOTAL,
    CONSOLIDATION_SOLVE_SECONDS, CONSOLIDATION_UNKNOWN_TYPE_TOTAL,
    CONSOLIDATION_WINDOW_CANDIDATES, CONSOLIDATION_WINDOW_RECLAIMED)
from karpenter_tpu.models.consolidate import (
    fleet_prices, node_bin, reschedulable_pods)
from karpenter_tpu.models.cost import CostConfig
from karpenter_tpu.metrics.policy import SOFT_AFFINITY_BLOCKED_DRAINS_TOTAL
from karpenter_tpu.obs import trace as obtrace
from karpenter_tpu.ops.whatif import encode_window, soft_affinity_loss
from karpenter_tpu.runtime.kubecore import KubeCore, NotFound
from karpenter_tpu.solver.whatif import (
    WhatIfConfig, dispatch_window, plan_window)
from karpenter_tpu.utils import node as nodeutil

log = logging.getLogger("karpenter.consolidation")


class _PdbHeadroom:
    """Read-only mirror of the eviction subresource's PDB math
    (runtime/kubecore.py evict_pod), evaluated once per window: per-PDB
    (healthy, desired) over the namespace's pods, so candidate filtering
    costs one pass instead of one dry-run eviction per pod."""

    def __init__(self, kube: KubeCore):
        self.kube = kube
        self._by_ns: Dict[str, list] = {}

    def _pdbs(self, namespace: str) -> list:
        cached = self._by_ns.get(namespace)
        if cached is not None:
            return cached
        from karpenter_tpu.runtime.kubecore import _scaled_int_or_percent

        entries = []
        pods = self.kube.list("Pod", namespace=namespace)
        for pdb in self.kube.list("PodDisruptionBudget", namespace=namespace):
            if pdb.selector is None:
                continue
            expected = healthy = 0
            for p in pods:
                if not pdb.selector.matches(p.metadata.labels):
                    continue
                expected += 1
                if getattr(p.spec, "node_name", None) \
                        and p.metadata.deletion_timestamp is None:
                    healthy += 1
            both = pdb.min_available is not None \
                and pdb.max_unavailable is not None
            desired: Optional[int] = None
            if not both:
                try:
                    if pdb.min_available is not None:
                        desired = _scaled_int_or_percent(
                            pdb.min_available, expected, pdb.metadata.name)
                    elif pdb.max_unavailable is not None:
                        desired = expected - _scaled_int_or_percent(
                            pdb.max_unavailable, expected, pdb.metadata.name)
                except Exception:
                    both = True  # malformed IntOrString → conservative block
            entries.append((pdb, desired, healthy, both))
        self._by_ns[namespace] = entries
        return entries

    def blocks_drain(self, movable: Sequence[Pod]) -> bool:
        """Would draining ALL these pods at once breach any PDB? Mirrors
        evict_pod: >1 matching PDB or both fields set blocks outright;
        else the node's total healthy loss per PDB must fit its headroom
        (healthy − desired)."""
        loss: Dict[int, int] = {}
        by_id: Dict[int, tuple] = {}
        for pod in movable:
            matched = []
            for entry in self._pdbs(pod.metadata.namespace):
                if entry[0].selector.matches(pod.metadata.labels):
                    matched.append(entry)
            if not matched:
                continue
            if len(matched) > 1:
                return True  # eviction would 500: misconfigured
            pdb, desired, healthy, both = matched[0]
            if both or desired is None and (
                    pdb.min_available is not None
                    or pdb.max_unavailable is not None):
                return True
            if desired is None:
                continue  # selector-only PDB: no budget expressed
            if getattr(pod.spec, "node_name", None) \
                    and pod.metadata.deletion_timestamp is None:
                key = id(pdb)
                by_id[key] = matched[0]
                loss[key] = loss.get(key, 0) + 1
        for key, n in loss.items():
            _, desired, healthy, _ = by_id[key]
            if healthy - n < desired:
                return True
        return False


class ConsolidationController:
    """Watches Provisioners; one batched what-if window per reconcile."""

    REQUEUE_SECONDS = 30.0

    def __init__(self, kube: KubeCore, provider=None,
                 max_actions_per_pass: int = 8,
                 window_size: int = 512,
                 whatif_config: Optional[WhatIfConfig] = None,
                 cost_config: CostConfig = CostConfig(),
                 repack_cost_per_hour: float = 0.0,
                 soft_affinity_cost_per_weight: float = 0.001,
                 journal=None):
        self.kube = kube
        self.provider = provider
        self.journal = journal
        self.max_actions_per_pass = max_actions_per_pass
        self.window_size = window_size
        self.whatif_config = whatif_config or WhatIfConfig()
        self.cost_config = cost_config
        # interruption-priced handoff: spot nodes' keep-cost carries their
        # reclaim tax, so savings rank risk as well as discount
        self.repack_cost_per_hour = repack_cost_per_hour
        # a drain that scatters a preferred co-located set pays the
        # scheduler's soft-affinity price back out of its savings
        self.soft_affinity_cost_per_weight = soft_affinity_cost_per_weight

    def kind(self) -> str:
        return "Provisioner"

    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        try:
            provisioner = self.kube.get("Provisioner", name, namespace)
        except NotFound:
            return None
        if not provisioner.spec.consolidation_enabled:
            return None
        if provisioner.metadata.deletion_timestamp is not None:
            return None
        wid = obtrace.new_window_id()
        with obtrace.window_span("consolidate", window_id=wid,
                                 provisioner=name):
            return self._window(provisioner, name, wid)

    def _window(self, provisioner, name: str, wid: str) -> Optional[float]:
        """One consolidation window (the traced reconcile body)."""
        t_gather = time.perf_counter()
        fleet: List[Node] = []
        pods_by_node: Dict[str, List[Pod]] = {}
        for node in self.kube.list("Node"):
            if node.metadata.labels.get(wellknown.PROVISIONER_NAME_LABEL) != name:
                continue
            # only consolidate settled capacity: ready, not being deleted
            if node.metadata.deletion_timestamp is not None:
                continue
            if not nodeutil.is_ready(node):
                continue
            fleet.append(node)
            pods_by_node[node.metadata.name] = self.kube.pods_on_node(
                node.metadata.name)

        catalog = self.provider.get_instance_types(
            provisioner.spec.constraints) if self.provider is not None else []
        prices, unknown = fleet_prices(
            fleet, catalog, self.cost_config,
            repack_cost_per_hour=self.repack_cost_per_hour)
        if unknown and catalog:
            # once per window, not per node — the counter carries cardinality
            CONSOLIDATION_UNKNOWN_TYPE_TOTAL.inc(len(unknown))
            log.warning(
                "consolidation window: %d node(s) have instance types absent "
                "from the catalog (e.g. %s=%r on %s); priced at $0/h but "
                "still consolidation candidates", len(unknown),
                wellknown.LABEL_INSTANCE_TYPE,
                unknown[0].metadata.labels.get(wellknown.LABEL_INSTANCE_TYPE),
                unknown[0].metadata.name)

        # every settled node is a receiver bin; only filtered nodes drain
        bins = [node_bin(n, pods_by_node[n.metadata.name]) for n in fleet]
        pdb = _PdbHeadroom(self.kube)
        cand_idx: List[int] = []
        cand_movable: List[List[Pod]] = []
        savings: List[float] = []
        # the incremental removable_nodes pass's receiver set (drainable or
        # empty unpinned nodes, fewest movable pods first) — plan_window's
        # at-least-as-cheap-as-incremental emulation leg scans exactly it
        inc_targets: List[Tuple[int, int]] = []
        for i, node in enumerate(fleet):
            movable, ok = reschedulable_pods(pods_by_node[node.metadata.name])
            if not ok:
                CONSOLIDATION_FILTERED_TOTAL.inc(reason="do-not-evict")
                continue
            inc_targets.append((len(movable), i))
            if not movable:
                continue  # empty nodes are the emptiness controller's job
            if pdb.blocks_drain(movable):
                CONSOLIDATION_FILTERED_TOTAL.inc(reason="pdb")
                continue
            if len(cand_idx) >= self.window_size:
                break
            price = prices.get(node.metadata.name, 0.0)
            loss = soft_affinity_loss(node, movable, fleet, pods_by_node,
                                      self.soft_affinity_cost_per_weight)
            if loss > 0.0 and loss >= price:
                # scattering the co-located set costs more than the node
                CONSOLIDATION_FILTERED_TOTAL.inc(reason="soft-affinity")
                SOFT_AFFINITY_BLOCKED_DRAINS_TOTAL.inc()
                continue
            cand_idx.append(i)
            cand_movable.append(movable)
            savings.append(price - loss)

        CONSOLIDATION_WINDOW_CANDIDATES.set(float(len(cand_idx)))
        obtrace.add_span("gather", t_gather, time.perf_counter(),
                         fleet=len(fleet), candidates=len(cand_idx))
        if len(cand_idx) == 0 or len(bins) < 2:
            CONSOLIDATION_WINDOW_RECLAIMED.set(0.0)
            return self.REQUEUE_SECONDS

        t0 = time.perf_counter()
        with obtrace.span("encode", candidates=len(cand_idx),
                          bins=len(bins)):
            enc = encode_window(bins, cand_idx, cand_movable)
        feasible, _, executor = dispatch_window(enc, self.whatif_config).fetch()
        solve_s = time.perf_counter() - t0
        CONSOLIDATION_SOLVE_SECONDS.observe(solve_s)
        CONSOLIDATION_CANDIDATES_TOTAL.inc(float(len(cand_idx)))

        with obtrace.span("plan"):
            plan = plan_window(enc, feasible, savings,
                               max_drains=self.max_actions_per_pass,
                               incremental_targets=[i for _, i
                                                    in sorted(inc_targets)])
        CONSOLIDATION_WINDOW_RECLAIMED.set(plan.reclaimed_per_hour)
        if plan.actions:
            log.info(
                "consolidation window: %d candidates → %d feasible → "
                "%d drains reclaiming $%.4f/h (%s, %.3fs) window_id=%s",
                plan.evaluated, plan.feasible, len(plan.actions),
                plan.reclaimed_per_hour, executor, solve_s, wid)
        for action in plan.actions:
            node = fleet[action.bin]
            log.info("consolidating node %s (%d pods fit on surviving "
                     "capacity; reclaims $%.4f/h) window_id=%s",
                     node.metadata.name,
                     len(enc.cand_pods[action.cand]), action.saving, wid)
            self._drain_node(node, action.saving)
        return self.REQUEUE_SECONDS

    def _drain_node(self, node: Node, saving: float) -> bool:
        """Execute one planned drain, journaled as a ``drain`` intent
        (open → deleting → closed) so a crash between the decision and
        the delete is re-driven by restart recovery instead of silently
        keeping the node."""
        journal = self.journal
        iid = None
        if journal is not None:
            iid = journal.open_intent(
                "drain", node=node.metadata.name,
                namespace=node.metadata.namespace, saving=saving)
        try:
            self.kube.delete("Node", node.metadata.name,
                             node.metadata.namespace)
        except NotFound:
            if iid is not None:
                journal.close(iid, outcome="gone")
            return False
        if iid is not None:
            journal.advance(iid, "deleting")
            journal.close(iid)
        CONSOLIDATION_DRAINS_TOTAL.inc()
        CONSOLIDATION_RECLAIMED_TOTAL.inc(saving)
        return True
