"""Counter controller: aggregate node capacity into Provisioner status.

Reference: pkg/controllers/counter/controller.go:51-87. The result feeds the
limits check in the provisioning worker (provisioner.go:139-144).
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.core import LabelSelector
from karpenter_tpu.runtime.kubecore import KubeCore, NotFound
from karpenter_tpu.utils.resources import Quantity


class CounterController:
    def __init__(self, kube: KubeCore):
        self.kube = kube

    def kind(self) -> str:
        return "Provisioner"

    def mappings(self):
        """Node events map to their provisioner (counter/controller.go:90-112)."""
        def node_to_provisioner(node):
            name = node.metadata.labels.get(wellknown.PROVISIONER_NAME_LABEL)
            return [(name, "default")] if name else []

        return [("Node", node_to_provisioner)]

    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        try:
            self.kube.get("Provisioner", name, namespace)
        except NotFound:
            return None
        nodes = self.kube.list(
            "Node",
            label_selector=LabelSelector(
                match_labels={wellknown.PROVISIONER_NAME_LABEL: name}))
        cpu, memory = Quantity(0), Quantity(0)
        for node in nodes:
            cpu = cpu.add(node.status.capacity.get("cpu", Quantity(0)))
            memory = memory.add(node.status.capacity.get("memory", Quantity(0)))

        def apply(p):
            p.status.resources = {"cpu": cpu, "memory": memory}
        try:
            self.kube.patch("Provisioner", name, namespace, apply)
        except NotFound:
            pass
        return None
