"""Capacity garbage collection: reap what crashed provisioning left behind.

Upstream analog: sigs.k8s.io/karpenter's instance garbage-collection
controller (pkg/controllers/nodeclaim/garbagecollection). This codebase has
no NodeClaim intermediary, so the crash window is wider: a controller that
dies between ``CloudProvider.create`` launching capacity and the Node write
landing leaks a running instance no Kubernetes object remembers. The
launch-nonce/provisioner tags stamped at CreateFleet time (before any Node
exists) make such capacity enumerable and attributable; this controller
closes the loop by cross-referencing ``list_instances()`` against Nodes in
BOTH directions:

- **Orphaned instance** — provider-side capacity older than the grace
  window whose instance id backs no Node: terminated via
  ``delete_instance``. The grace window covers the legitimate launch→bind
  latency (an instance seconds old is probably mid-bind, not leaked).

- **Ghost node** — a Node carrying this provider's providerID, older than
  the grace window, whose backing instance the provider no longer reports:
  deleted through the normal finalizer flow, so drain/evict/provider.delete
  all run (and provider deletion of already-gone capacity is NotFound →
  success by SPI contract).

Ownership test: a record backs a Node iff the instance id appears verbatim
as a path segment of the Node's providerID (``aws:///<zone>/<id>``,
``fake:///<id>/<zone>`` — segment containment sidesteps the per-provider
ordering). Only Nodes whose providerID starts with ``<provider>://`` are
considered at all; nodes from other provisioners/providers are invisible.

Fail-safe bias: if ``list_instances()`` raises, the sweep is skipped
entirely — an empty-looking provider must never read as "every node is a
ghost". Per-item delete failures are logged and retried next interval.

The controller is time-driven (``kind() -> None`` + one seeded key) and
self-perpetuates by returning its interval from ``reconcile``.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Tuple

from karpenter_tpu.cloudprovider.spi import CloudProvider
from karpenter_tpu.metrics.registry import DEFAULT
from karpenter_tpu.runtime.kubecore import KubeCore, NotFound
from karpenter_tpu.utils import clock

log = logging.getLogger("karpenter.gc")

DEFAULT_INTERVAL_SECONDS = 120.0
# must comfortably exceed launch→bind latency (CreateFleet + 3×1 s describe
# retry + node create); upstream uses 10 min for the same reason
DEFAULT_GRACE_SECONDS = 600.0

_TERMINATED = DEFAULT.counter(
    "gc_instances_terminated_total",
    "Leaked provider instances terminated by the capacity GC")
_REMOVED = DEFAULT.counter(
    "gc_nodes_removed_total",
    "Ghost nodes (backing instance gone) deleted by the capacity GC")


class GarbageCollection:
    """Periodic two-way sweep of provider capacity vs Node objects."""

    def __init__(
        self,
        kube: KubeCore,
        cloud_provider: CloudProvider,
        interval_seconds: float = DEFAULT_INTERVAL_SECONDS,
        grace_seconds: float = DEFAULT_GRACE_SECONDS,
        journal=None,
    ):
        self.kube = kube
        self.cloud_provider = cloud_provider
        self.interval_seconds = interval_seconds
        self.grace_seconds = grace_seconds
        # ownership handoff with restart recovery: capacity whose launch
        # nonce is covered by an open journaled fleet-launch intent belongs
        # to recovery (which rolls it forward or terminates it exactly
        # once); GC must never race it — see controllers/recovery.py
        self.journal = journal

    # -- manager wiring ------------------------------------------------------
    def kind(self) -> Optional[str]:
        return None  # time-driven: no watch, one seeded key + self-requeue

    def seeds(self) -> List[Tuple[str, str]]:
        return [("capacity-gc", "")]

    # -- sweep ---------------------------------------------------------------
    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        try:
            records = self.cloud_provider.list_instances()
        except Exception:  # noqa: BLE001 — skip the sweep, never guess
            log.exception("listing provider instances failed; skipping sweep")
            return self.interval_seconds

        # one no-copy pass over Nodes: (name, providerID segments, age gate)
        prefix = f"{self.cloud_provider.name()}://"
        cutoff = clock.now() - self.grace_seconds

        def extract(n):
            pid = getattr(n.spec, "provider_id", "") or ""
            if not pid.startswith(prefix):
                return None
            return (n.metadata.name,
                    n.metadata.namespace,
                    frozenset(s for s in pid.split("/") if s),
                    (n.metadata.creation_timestamp or clock.now()) < cutoff,
                    n.metadata.deletion_timestamp is not None)
        nodes = [t for t in self.kube.scan("Node", extract) if t is not None]

        backed = set()
        for _, _, segments, _, _ in nodes:
            backed |= segments

        # direction 1: instances with no Node → terminate after grace
        covered = (self.journal.covered_nonces()
                   if self.journal is not None else frozenset())
        live_ids = set()
        for record in records:
            if not record.instance_id:
                continue  # malformed: never act on an empty id
            live_ids.add(record.instance_id)
            if record.instance_id in backed:
                continue
            if record.launch_nonce and record.launch_nonce in covered:
                # journal-owned: an open fleet-launch intent covers this
                # nonce, so recovery is (or will be) resolving it — acting
                # here would double-terminate or kill a roll-forward
                log.debug("instance %s owned by open journal intent "
                          "(nonce=%s); skipping", record.instance_id,
                          record.launch_nonce)
                continue
            if record.created_unix <= 0.0:
                # unknown launch time: fail-safe — age cannot be proven
                log.debug("instance %s has no launch time; skipping",
                          record.instance_id)
                continue
            if record.created_unix > cutoff:
                continue  # younger than grace: probably mid-bind
            err = self.cloud_provider.delete_instance(record.instance_id)
            if err is not None:
                log.error("terminating leaked instance %s: %s",
                          record.instance_id, err)
                continue
            _TERMINATED.inc(provisioner=record.provisioner_name or "unknown")
            log.info(
                "terminated leaked instance %s (provisioner=%s nonce=%s "
                "age=%.0fs type=%s zone=%s)",
                record.instance_id, record.provisioner_name,
                record.launch_nonce, clock.now() - record.created_unix,
                record.instance_type, record.zone)

        # direction 2: Nodes whose instance is gone → delete after grace.
        # Routed through kube.delete so the termination finalizer runs the
        # full drain path; provider deletion of absent capacity is NotFound
        # → success, so the finalizer always clears.
        for node_name, node_ns, segments, old_enough, deleting in nodes:
            if deleting or not old_enough:
                continue
            if segments & live_ids:
                continue
            try:
                self.kube.delete("Node", node_name, node_ns)
            except NotFound:
                continue  # already gone: someone else won the race
            except Exception:  # noqa: BLE001 — retried next sweep
                log.exception("deleting ghost node %s failed", node_name)
                continue
            _REMOVED.inc()
            log.info("deleting ghost node %s (backing instance gone)",
                     node_name)

        return self.interval_seconds
