"""Live log-level reload from the ``config-logging`` ConfigMap.

Reference: cmd/controller/main.go:105-117 — the logging context is built
from the ``config-logging`` ConfigMap and the level is live-reloaded on
ConfigMap change (knative's UpdateLevelFromConfigMap); cmd/webhook/main.go
:84-92 validates the same map. Data format follows knative's:

- ``zap-logger-config``: JSON whose ``level`` field sets the root
  ``karpenter`` logger ("debug" | "info" | "warn" | "error");
- ``loglevel.<component>``: per-component override, applied to
  ``karpenter.<component>`` (e.g. ``loglevel.solver: debug``).
"""

from __future__ import annotations

import json
import logging
from typing import Optional

from karpenter_tpu.runtime.kubecore import KubeCore, NotFound

log = logging.getLogger("karpenter.logging-config")

CONFIG_MAP_NAME = "config-logging"

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


def _zap_level(raw: str):
    """Parse zap-logger-config JSON → (level or None, error or None)."""
    try:
        cfg = json.loads(raw)
    except ValueError as e:
        return None, f"zap-logger-config: invalid JSON: {e}"
    if not isinstance(cfg, dict):
        return None, "zap-logger-config: must be a JSON object"
    level = cfg.get("level")
    if level is not None and level not in _LEVELS:
        return None, f"zap-logger-config: unknown level {level!r}"
    return level, None


def validate_config(data: dict) -> Optional[str]:
    """Webhook-side validation of the map (cmd/webhook/main.go:84-92)."""
    raw = data.get("zap-logger-config")
    if raw is not None:
        _, err = _zap_level(raw)
        if err is not None:
            return err
    for key, value in data.items():
        if key.startswith("loglevel.") and value not in _LEVELS:
            return f"{key}: unknown level {value!r}"
    return None


class LoggingConfigController:
    """Applies the config on every ConfigMap reconcile."""

    def __init__(self, kube: KubeCore, namespace: str = "default",
                 root_logger: str = "karpenter"):
        self.kube = kube
        self.namespace = namespace
        self.root_logger = root_logger

    def kind(self) -> str:
        return "ConfigMap"

    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        # only the controller's own namespace may configure logging — any
        # tenant could otherwise create a config-logging map and flip levels
        if name != CONFIG_MAP_NAME or namespace != self.namespace:
            return None
        try:
            cm = self.kube.get("ConfigMap", name, namespace)
        except NotFound:
            return None
        err = validate_config(cm.data)
        if err is not None:
            log.error("ignoring %s: %s", CONFIG_MAP_NAME, err)
            return None
        raw = cm.data.get("zap-logger-config")
        if raw:
            level, _ = _zap_level(raw)
            if level:
                logging.getLogger(self.root_logger).setLevel(_LEVELS[level])
                log.info("root log level set to %s", level)
        for key, value in cm.data.items():
            if key.startswith("loglevel."):
                component = key[len("loglevel."):]
                logging.getLogger(f"{self.root_logger}.{component}").setLevel(
                    _LEVELS[value])
                log.info("%s log level set to %s", component, value)
        return None
