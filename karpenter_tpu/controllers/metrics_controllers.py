"""Metrics controllers: node resource gauges and pod state.

Reference: pkg/controllers/metrics/{node,pod}/controller.go. Node: six gauge
families (allocatable, total_pod_requests/limits, total_daemon_requests/
limits, system_overhead) labeled by resource/node/provisioner/zone/arch/
capacity-type/instance-type/phase, recomputed per reconcile with
stale-series cleanup. Pod: the karpenter_pods_state gauge.
"""

from __future__ import annotations

from typing import Dict, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.metrics import registry
from karpenter_tpu.runtime.kubecore import KubeCore, NotFound
from karpenter_tpu.utils import pod as podutil
from karpenter_tpu.utils.resources import (
    Quantity, limits_for_pods, requests_for_pods,
)

_GAUGES = {
    "allocatable": "nodes_allocatable",
    "pod_requests": "nodes_total_pod_requests",
    "pod_limits": "nodes_total_pod_limits",
    "daemon_requests": "nodes_total_daemon_requests",
    "daemon_limits": "nodes_total_daemon_limits",
    "overhead": "nodes_system_overhead",
}


def _node_labels(node) -> Dict[str, str]:
    labels = node.metadata.labels
    return {
        "node_name": node.metadata.name,
        "provisioner": labels.get(wellknown.PROVISIONER_NAME_LABEL, ""),
        "zone": labels.get(wellknown.LABEL_TOPOLOGY_ZONE, ""),
        "arch": labels.get(wellknown.LABEL_ARCH, ""),
        "capacity_type": labels.get(wellknown.LABEL_CAPACITY_TYPE, ""),
        "instance_type": labels.get(wellknown.LABEL_INSTANCE_TYPE, ""),
        "phase": "Ready" if any(
            c.type == "Ready" and c.status == "True"
            for c in node.status.conditions) else "NotReady",
    }


def _as_float(q: Quantity, resource_name: str) -> float:
    if resource_name == "cpu":
        return q.milli_value() / 1000.0
    return float(q.value())


class NodeMetricsController:
    """metrics/node/controller.go:144-302."""

    def __init__(self, kube: KubeCore, reg: Optional[registry.Registry] = None):
        self.kube = kube
        self.registry = reg or registry.DEFAULT

    def kind(self) -> str:
        return "Node"

    def mappings(self):
        """Pod events map to their node (metrics/node watches pods)."""
        def pod_to_node(pod):
            return [(pod.spec.node_name, "")] if pod.spec.node_name else []

        return [("Pod", pod_to_node)]

    def reconcile(self, name: str, namespace: str = "") -> Optional[float]:
        gauges = {k: self.registry.gauge(v) for k, v in _GAUGES.items()}
        try:
            node = self.kube.get("Node", name, namespace)
        except NotFound:
            for g in gauges.values():
                g.delete_matching(node_name=name)
            return None

        labels = _node_labels(node)
        for g in gauges.values():
            g.delete_matching(node_name=name)

        pods = self.kube.pods_on_node(name)
        daemons = [p for p in pods if podutil.is_owned_by_daemonset(p)]
        series = {
            "allocatable": node.status.allocatable,
            "pod_requests": requests_for_pods(*pods),
            "pod_limits": limits_for_pods(*pods),
            "daemon_requests": requests_for_pods(*daemons),
            "daemon_limits": limits_for_pods(*daemons),
            "overhead": _overhead(node),
        }
        for kind, resource_list in series.items():
            for resource_name, q in resource_list.items():
                gauges[kind].set(_as_float(q, resource_name),
                                 resource_type=resource_name, **labels)
        return None


def _overhead(node):
    """capacity - allocatable (system/kubelet reservation)."""
    out = {}
    for name, cap in node.status.capacity.items():
        alloc = node.status.allocatable.get(name, Quantity(0))
        out[name] = cap.sub(alloc)
    return out


class PodMetricsController:
    """metrics/pod/controller.go: karpenter_pods_state gauge."""

    def __init__(self, kube: KubeCore, reg: Optional[registry.Registry] = None):
        self.kube = kube
        self.registry = reg or registry.DEFAULT

    def kind(self) -> str:
        return "Pod"

    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        gauge = self.registry.gauge("pods_state")
        try:
            pod = self.kube.get("Pod", name, namespace)
        except NotFound:
            gauge.delete_matching(name=name, namespace=namespace)
            return None
        gauge.delete_matching(name=name, namespace=namespace)
        node_labels: Dict[str, str] = {}
        if pod.spec.node_name:
            try:
                node = self.kube.get("Node", pod.spec.node_name, "")
                node_labels = node.metadata.labels
            except NotFound:
                pass
        gauge.set(1.0,
                  name=name, namespace=namespace, node=pod.spec.node_name,
                  provisioner=node_labels.get(wellknown.PROVISIONER_NAME_LABEL, ""),
                  zone=node_labels.get(wellknown.LABEL_TOPOLOGY_ZONE, ""),
                  arch=node_labels.get(wellknown.LABEL_ARCH, ""),
                  capacity_type=node_labels.get(wellknown.LABEL_CAPACITY_TYPE, ""),
                  instance_type=node_labels.get(wellknown.LABEL_INSTANCE_TYPE, ""),
                  phase=pod.status.phase)
        return None
