"""Node lifecycle controller: readiness, liveness, expiration, emptiness,
finalizer.

Reference: pkg/controllers/node/ (orchestrator + 5 sub-reconcilers). The
orchestrator deep-copies the node, runs every sub-reconciler in sequence,
patches once if anything changed, and requeues at the minimum of the
sub-results (utils/result.Min).
"""

from __future__ import annotations

import logging
from typing import List, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.core import Node, Pod
from karpenter_tpu.api.provisioner import Provisioner
from karpenter_tpu.runtime.kubecore import KubeCore, NotFound
from karpenter_tpu.utils import clock
from karpenter_tpu.utils import node as nodeutil
from karpenter_tpu.utils import pod as podutil

log = logging.getLogger("karpenter.node")

LIVENESS_TIMEOUT_SECONDS = 15 * 60  # liveness.go LivenessTimeout


class Readiness:
    """Remove the not-ready taint once Ready (readiness.go)."""

    def reconcile(self, provisioner: Provisioner, n: Node, kube: KubeCore) -> Optional[float]:
        if not nodeutil.is_ready(n):
            return None
        n.spec.taints = [t for t in n.spec.taints
                         if t.key != wellknown.NOT_READY_TAINT_KEY]
        return None


class Liveness:
    """Delete nodes whose kubelet never reported within the timeout
    (liveness.go:224-250) — the runaway-scaling reaper."""

    def reconcile(self, provisioner: Provisioner, n: Node, kube: KubeCore) -> Optional[float]:
        created = n.metadata.creation_timestamp or clock.now()
        since_creation = clock.now() - created
        if since_creation < LIVENESS_TIMEOUT_SECONDS:
            return LIVENESS_TIMEOUT_SECONDS - since_creation
        condition = nodeutil.get_condition(n, "Ready")
        # "" = never set; NodeStatusNeverUpdated = kcm marked it unreachable
        if condition.reason not in ("", "NodeStatusNeverUpdated"):
            return None
        log.info("triggering termination for node %s that failed to join",
                 n.metadata.name)
        kube.delete("Node", n.metadata.name, n.metadata.namespace)
        return None


class Expiration:
    """Delete nodes older than ttlSecondsUntilExpired (expiration.go)."""

    def reconcile(self, provisioner: Provisioner, n: Node, kube: KubeCore) -> Optional[float]:
        ttl = provisioner.spec.ttl_seconds_until_expired
        if ttl is None:
            return None
        expiration_time = (n.metadata.creation_timestamp or 0) + ttl
        if clock.now() > expiration_time:
            log.info("triggering termination for expired node %s after %ss",
                     n.metadata.name, ttl)
            kube.delete("Node", n.metadata.name, n.metadata.namespace)
            return None
        return expiration_time - clock.now()


class Emptiness:
    """Stamp/clear the emptiness timestamp; delete after the TTL
    (emptiness.go:38-99)."""

    def reconcile(self, provisioner: Provisioner, n: Node, kube: KubeCore) -> Optional[float]:
        ttl = provisioner.spec.ttl_seconds_after_empty
        if ttl is None:
            return None
        if not nodeutil.is_ready(n):
            return None
        empty = self._is_empty(kube, n)
        stamp = n.metadata.annotations.get(wellknown.EMPTINESS_TIMESTAMP_ANNOTATION)
        if not empty:
            if stamp is not None:
                del n.metadata.annotations[wellknown.EMPTINESS_TIMESTAMP_ANNOTATION]
                log.info("removed emptiness TTL from node %s", n.metadata.name)
            return None
        if stamp is None:
            n.metadata.annotations[wellknown.EMPTINESS_TIMESTAMP_ANNOTATION] = (
                repr(clock.now()))
            log.info("added TTL to empty node %s", n.metadata.name)
            return float(ttl)
        try:
            emptiness_time = float(stamp)
        except ValueError:
            log.error("unparseable emptiness timestamp %r", stamp)
            return None
        if clock.now() > emptiness_time + ttl:
            log.info("triggering termination after %ss for empty node %s",
                     ttl, n.metadata.name)
            kube.delete("Node", n.metadata.name, n.metadata.namespace)
        return None

    def _is_empty(self, kube: KubeCore, n: Node) -> bool:
        """Only terminal/daemonset/static pods remain (emptiness.go:84-99)."""
        for p in kube.pods_on_node(n.metadata.name):
            if podutil.is_terminal(p):
                continue
            if not podutil.is_owned_by_daemonset(p) and not podutil.is_owned_by_node(p):
                return False
        return True


class Finalizer:
    """Re-add the termination finalizer on self-registered nodes
    (finalizer.go:178-193)."""

    def reconcile(self, provisioner: Provisioner, n: Node, kube: KubeCore) -> Optional[float]:
        if n.metadata.deletion_timestamp is not None:
            return None
        if wellknown.TERMINATION_FINALIZER not in n.metadata.finalizers:
            n.metadata.finalizers.append(wellknown.TERMINATION_FINALIZER)
        return None


class NodeController:
    """Orchestrator (node/controller.go:63-118)."""

    def __init__(self, kube: KubeCore):
        self.kube = kube
        self.readiness = Readiness()
        self.liveness = Liveness()
        self.expiration = Expiration()
        self.emptiness = Emptiness()
        self.finalizer = Finalizer()

    def kind(self) -> str:
        return "Node"

    def mappings(self):
        """Extra watches (node/controller.go:125-149): pod events map to
        their node; provisioner events map to all its nodes."""
        def pod_to_node(pod):
            return [(pod.spec.node_name, "")] if getattr(pod.spec, "node_name", "") else []

        def provisioner_to_nodes(p):
            from karpenter_tpu.api.core import LabelSelector
            nodes = self.kube.list("Node", label_selector=LabelSelector(
                match_labels={wellknown.PROVISIONER_NAME_LABEL: p.metadata.name}))
            return [(n.metadata.name, "") for n in nodes]

        return [("Pod", pod_to_node), ("Provisioner", provisioner_to_nodes)]

    def reconcile(self, name: str, namespace: str = "") -> Optional[float]:
        try:
            stored = self.kube.get("Node", name, namespace)
        except NotFound:
            return None
        provisioner_name = stored.metadata.labels.get(wellknown.PROVISIONER_NAME_LABEL)
        if provisioner_name is None:
            return None
        if stored.metadata.deletion_timestamp is not None:
            return None
        try:
            provisioner = self.kube.get("Provisioner", provisioner_name)
        except NotFound:
            return None

        node = _copy_node(stored)
        requeues: List[float] = []
        for sub in (self.readiness, self.liveness, self.expiration,
                    self.emptiness, self.finalizer):
            requeue = sub.reconcile(provisioner, node, self.kube)
            if requeue is not None:
                requeues.append(requeue)
        if _node_changed(node, stored):
            try:
                def apply(live: Node):
                    live.spec.taints = node.spec.taints
                    live.metadata.annotations = node.metadata.annotations
                    live.metadata.finalizers = node.metadata.finalizers
                self.kube.patch("Node", name, namespace, apply)
            except NotFound:
                return None
        return min(requeues) if requeues else None


def _copy_node(n: Node) -> Node:
    import copy

    return copy.deepcopy(n)


def _node_changed(a: Node, b: Node) -> bool:
    return (
        [(t.key, t.value, t.effect) for t in a.spec.taints]
        != [(t.key, t.value, t.effect) for t in b.spec.taints]
        or a.metadata.annotations != b.metadata.annotations
        or a.metadata.finalizers != b.metadata.finalizers
    )
