"""Provisioning controller + sharded Provisioner workers.

Reference: pkg/controllers/provisioning/{controller.go,provisioner.go}.
- The controller reconciles Provisioner CRs into in-memory workers (one
  thread each, the Go goroutine analog), refreshes global requirements from
  the live instance-type catalog, and restarts workers on spec change.
- The worker owns the hot loop: batch → filter → schedule → TPU solve →
  launch → bind.

Sharding model (docs/scale.md §1): the per-Provisioner machinery —
scheduler, solve pipeline, launch/bind path — is factored into
:class:`ProvisionerEngine`. A :class:`ProvisionerWorker` is one intake
shard: one thread, one bounded priority batcher, hosting one or more
engines. Two deployment shapes share the code:

- **Legacy (shards=0, the default):** one worker per Provisioner CR,
  exactly the reference's model — every existing call site and test keeps
  its shape (``worker.provisioner``, ``worker.add(pod)``, ``worker._bind``).
- **Sharded (shards=N):** the controller runs N long-lived shard workers
  and assigns each Provisioner's engine to ``crc32(name) % N``. Intake,
  window assembly, and the solve pipeline parallelize per shard while the
  pressure ladder (process-wide monitor), leader election, and kube-client
  rate limits stay global — sharding multiplies throughput, not the blast
  radius of overload.

Batched items carry their engine routing as ``(provisioner_name, pod)``
tuples so one shard window can serve many tenants; the window's priority
order is preserved within each engine group (a system-critical pod still
solves in its engine's first chunk).
"""

from __future__ import annotations

import logging
import threading
import time
import zlib
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.constraints import Constraints
from karpenter_tpu.api.core import Node, NodeSelectorRequirement as Req, Pod, Taint
from karpenter_tpu.api.gang import gang_of
from karpenter_tpu.api.provisioner import Provisioner, set_condition
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.cloudprovider.spi import CloudProvider, InstanceType
from karpenter_tpu import pressure
from karpenter_tpu.metrics.gang import (
    GANG_WINDOWS_TOTAL, GANGS_PLACED_TOTAL, GANGS_UNPLACEABLE_TOTAL,
)
from karpenter_tpu.metrics.policy import SOFT_AFFINITY_STEERED_TOTAL
from karpenter_tpu.metrics.pressure import WINDOW_SPLITS_TOTAL
from karpenter_tpu.metrics.registry import HISTOGRAMS
from karpenter_tpu.obs import slo
from karpenter_tpu.obs import trace as obtrace
from karpenter_tpu.runtime import journal as jr
from karpenter_tpu.runtime.kubecore import (
    AlreadyExists, ApiError, KubeCore, NotFound,
)
from karpenter_tpu.scheduling.batcher import Batcher
from karpenter_tpu.scheduling.scheduler import Scheduler
from karpenter_tpu.metrics.topology import (
    PREEMPTION_DISPLACED_PODS_TOTAL, PREEMPTIONS_TOTAL,
    TOPOLOGY_CARVE_WINDOWS_TOTAL, TOPOLOGY_CARVES_COMMITTED_TOTAL,
)
from karpenter_tpu.scheduling.preempt_budget import PreemptionBudget
from karpenter_tpu.ops import topology as topo_ops
from karpenter_tpu.ops.gang import GangBin, GangEncoding, encode_gang_window
from karpenter_tpu.pressure.bands import RANK
from karpenter_tpu.solver import global_solve
from karpenter_tpu.solver import topology as topo_solver
from karpenter_tpu.solver.batch_solve import Problem, dispatch_batch
from karpenter_tpu.solver.gang import (
    GangConfig, GangPlacement, PreemptCandidate, PreemptContext,
    dispatch_gang_window, plan_gang_window,
)
from karpenter_tpu.solver.pipeline import PipelineConfig, SolvePipeline
from karpenter_tpu.solver.solve import SolveResult, SolverConfig
from karpenter_tpu.utils import node as nodeutil
from karpenter_tpu.utils import pod as podutil

log = logging.getLogger("karpenter.provisioning")


class _NoChange(Exception):
    """Raised inside a patch fn to abort a no-op status write (kubecore.patch
    applies fn under the store lock; an exception leaves the store untouched,
    so no MODIFIED event fires and condition refreshes cannot self-loop)."""


def shard_of(name: str, shards: int) -> int:
    """Stable provisioner→shard assignment: crc32 of the CR name. Stable
    across processes and restarts so shard-labeled metrics stay comparable
    between runs."""
    return zlib.crc32(name.encode()) % shards


def global_requirements(instance_types: List[InstanceType]) -> Requirements:
    """Inject supported zones/types/arch/OS/capacity-types as requirements
    (controller.go:141-162): the 'universe' that makes unconstrained keys
    concrete before they reach the solver."""
    zones, names, archs, oss, cts = set(), set(), set(), set(), set()
    for it in instance_types:
        names.add(it.name)
        archs.add(it.architecture)
        oss |= set(it.operating_systems)
        for o in it.offerings:
            zones.add(o.zone)
            cts.add(o.capacity_type)
    return Requirements().add(
        Req(key=wellknown.LABEL_TOPOLOGY_ZONE, operator="In", values=sorted(zones)),
        Req(key=wellknown.LABEL_INSTANCE_TYPE, operator="In", values=sorted(names)),
        Req(key=wellknown.LABEL_ARCH, operator="In", values=sorted(archs)),
        Req(key=wellknown.LABEL_OS, operator="In", values=sorted(oss)),
        Req(key=wellknown.LABEL_CAPACITY_TYPE, operator="In", values=sorted(cts)),
    )


@dataclass
class _ChunkPrep:
    """Host-marshalled state of one window chunk, handed stage-to-stage
    through the pipeline (schedule → dispatch → launch/bind)."""

    schedules: list
    problems: List[Problem]
    # the chunk's raw pod list, kept for per-pod SLO stamping in
    # _observe_chunk (the schedules lists re-group pods per constraint set,
    # losing the window-meta alignment)
    pods: list = field(default_factory=list)
    dispatch_s: float = field(default=0.0)
    # gang co-pack half of the chunk: one batched device solve for every
    # complete pod group the scheduler grouped out of this chunk
    gang_enc: Optional[GangEncoding] = None
    gang_types: list = field(default_factory=list)  # type idx → (schedule, it)
    gang_handle: Optional[object] = None
    gang_nodes: Dict[int, str] = field(default_factory=dict)  # bin → node
    # whole-window global solve (solver/global_solve.py): the in-flight
    # handle when window_backend="global" dispatched this chunk jointly;
    # fetch substitutes only strictly-cheaper host-verified plans, so a
    # None (or a declined schedule) keeps the FFD result bit-for-bit
    global_handle: Optional[object] = None
    # chunk-scoped SolverConfig override: the interruption-priced policy's
    # what-if repack context is priced per chunk (None → worker config)
    solver_config: Optional[SolverConfig] = None


class ProvisionerEngine:
    """Per-Provisioner solve machinery, independent of intake: scheduler +
    ONE long-lived SolvePipeline (the adaptive-depth state machine learns
    across provisioning windows and its device rings stay warm between
    them, solver/pipeline.py). A shard worker hosts one engine per tenant
    Provisioner; in the legacy one-worker-per-Provisioner shape it hosts
    exactly one."""

    def __init__(self, provisioner: Provisioner, kube: KubeCore,
                 pipeline_config: Optional[PipelineConfig] = None,
                 shard: str = ""):
        self.provisioner = provisioner
        self.pipeline_config = pipeline_config or PipelineConfig()
        self.pipeline = SolvePipeline(self.pipeline_config, shard=shard)
        self.scheduler = Scheduler(kube)
        self.shard = shard


class ProvisionerWorker:
    """One intake shard: a thread + bounded priority batcher hosting the
    engine(s) of the Provisioner(s) assigned to it (provisioner.go:41-76 —
    one CR per worker in the reference; here N CRs share a shard when the
    controller runs with shards>0)."""

    def __init__(
        self,
        provisioner: Optional[Provisioner],
        kube: KubeCore,
        cloud_provider: CloudProvider,
        solver_config: Optional[SolverConfig] = None,
        batcher: Optional[Batcher] = None,
        pipeline_config: Optional[PipelineConfig] = None,
        shard: str = "",
        journal: Optional["jr.IntentJournal"] = None,
    ):
        self.kube = kube
        self.cloud_provider = cloud_provider
        self.journal = journal
        self.solver_config = solver_config or SolverConfig()
        self.gang_config = GangConfig()
        self.preempt_budget = PreemptionBudget()
        self.batcher = batcher or Batcher()
        self.pipeline_config = pipeline_config or PipelineConfig()
        self.shard = shard
        if shard:
            self.batcher.shard = shard  # per-shard intake metric labels
        # engine map is copy-on-write (REPLACED under _engines_lock, never
        # mutated) so the hot loop and selection's targets() iterate a
        # snapshot without taking the lock
        self._engines: Dict[str, ProvisionerEngine] = {}
        self._engines_lock = threading.Lock()
        # the engine a provision pass is currently serving; the chunk-stage
        # callbacks (and the monkeypatchable _bind) resolve through this so
        # their signatures stay engine-free. Only the worker thread writes
        # it during a pass; direct test calls see the default engine.
        self._current: Optional[ProvisionerEngine] = None
        # the id of the window this worker is serving: the trace id of the
        # window span AND the window_id= key on every window-scoped log
        # line (present even with tracing disabled, so logs always join)
        self._window_id: str = "-"
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if provisioner is not None:
            self.attach(provisioner)

    # -- engine management ----------------------------------------------------
    def attach(self, provisioner: Provisioner) -> None:
        """Add (or replace, on spec change) the engine for a Provisioner."""
        eng = ProvisionerEngine(provisioner, self.kube,
                                pipeline_config=self.pipeline_config,
                                shard=self.shard)
        with self._engines_lock:
            engines = dict(self._engines)
            engines[provisioner.metadata.name] = eng
            self._engines = engines

    def detach(self, name: str) -> None:
        with self._engines_lock:
            if name in self._engines:
                engines = dict(self._engines)
                del engines[name]
                self._engines = engines

    def engines(self) -> List[ProvisionerEngine]:
        """Snapshot of hosted engines in attach order."""
        return list(self._engines.values())

    def _default_engine(self) -> Optional[ProvisionerEngine]:
        for eng in self._engines.values():
            return eng
        return None

    def _engine(self) -> ProvisionerEngine:
        eng = self._current or self._default_engine()
        if eng is None:
            raise RuntimeError("worker has no attached provisioner engine")
        return eng

    @property
    def provisioner(self) -> Provisioner:
        """The Provisioner a direct caller means: the engine currently
        being served, else the first attached one (the legacy single-
        provisioner worker's CR)."""
        return self._engine().provisioner

    @property
    def pipeline(self) -> SolvePipeline:
        return self._engine().pipeline

    @property
    def scheduler(self) -> Scheduler:
        return self._engine().scheduler

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        name = (f"provisioner-shard-{self.shard}" if self.shard
                else f"provisioner-{self.provisioner.metadata.name}")
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.batcher.stop()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.provision()
            except Exception:
                log.exception("provisioning failed")

    # -- API for the selection controller -----------------------------------
    def add(self, pod: Pod, key=None,
            provisioner: Optional[str] = None) -> Optional[threading.Event]:
        """Enqueue a pod; returns the gate to block on (provisioner.go:80-82)
        or None when brownout admission shed the pod (it re-enters via the
        selection requeue once pressure falls). ``key`` (namespace, name)
        enables :meth:`pending` de-duplication. ``provisioner`` routes the
        pod to that engine's group within the shard window; None means the
        default (first attached) engine — the legacy single-tenant call."""
        band, priority = pressure.classify(pod)
        gspec = gang_of(pod)
        gang = (gspec.key, gspec.size) \
            if gspec is not None and not gspec.error else None
        return self.batcher.add((provisioner, pod), key=key, band=band,
                                priority=priority, gang=gang)

    def pending(self, key) -> bool:
        """True while a pod with this (namespace, name) key awaits a batch
        window — the selection requeue loop skips re-adding it."""
        return self.batcher.contains(key)

    # -- the hot loop (provisioner.go:84-120) --------------------------------
    def provision(self) -> Optional[SolveResult]:
        t_wait0 = time.perf_counter()
        items, window = self.batcher.wait()
        t_wait1 = time.perf_counter()
        try:
            if not items or self._stop.is_set():
                return None
            # window marks: the batcher leaves per-pod (band, intake_s)
            # aligned index-for-index with items; keyed by pod identity they
            # follow the window across chunking/regrouping, and use_marks
            # makes them reachable from every pipeline stage callback (and,
            # via the BatchHandle capture, from the fetch side too)
            meta = self.batcher.last_window_meta
            self.batcher.last_window_meta = None
            marks = None
            if meta is not None and len(meta) == len(items):
                marks = slo.WindowMarks(
                    t_close=t_wait1,
                    meta={id(it[1]): m for it, m in zip(items, meta)})
            wid = self._window_id = obtrace.new_window_id()
            shard = self.shard or "0"
            monitor = self.batcher._monitor()
            with slo.use_marks(marks), \
                 obtrace.window_span("provision", window_id=wid,
                                     shard=shard,
                                     pressure_level=int(monitor.level()),
                                     pods=len(items)):
                # the intake wait predates the window span; record it
                # retroactively as its first child
                obtrace.add_span("intake", t_wait0, t_wait1,
                                 shard=shard, window_s=round(window, 4))
                log.info("batched %d pods in %.2fs window_id=%s shard=%s",
                         len(items), window, wid, shard)
                # dedupe within the batch: the non-blocking selection path
                # can requeue a still-pending pod into the same window
                # (selection.py concurrency note); packing it twice would
                # double-count it. Then group by engine, PRESERVING the
                # window's priority order within each group (dict insertion
                # order) — a critical pod still lands in its engine's first
                # chunk.
                seen = set()
                groups: Dict[Optional[str], List[Pod]] = {}
                for item in items:
                    pname, p = item
                    key = (p.metadata.namespace, p.metadata.name)
                    if key in seen:
                        continue
                    seen.add(key)
                    groups.setdefault(pname, []).append(p)
                last_result = None
                for pname, pods in groups.items():
                    eng = (self._engines.get(pname) if pname is not None
                           else self._default_engine())
                    if eng is None:
                        # provisioner deleted while its pods sat in the
                        # window: the pods stay Pending and the selection
                        # requeue re-routes them to a surviving provisioner
                        log.info("dropping %d pod(s) for detached "
                                 "provisioner %s window_id=%s shard=%s",
                                 len(pods), pname, wid, shard)
                        continue
                    result = self._provision_group(eng, pods)
                    if result is not None:
                        last_result = result
                return last_result
        finally:
            self.batcher.flush()

    def _provision_group(self, eng: ProvisionerEngine,
                         pods: List[Pod]) -> Optional[SolveResult]:
        """Run one engine's share of the window through its pipeline."""
        pods = [p for p in pods if self._is_provisionable(p)]
        # L1+ batch-split: the batcher returns windows in priority
        # order, so chunking preserves it — critical pods solve and
        # bind in the FIRST chunk while the tail is still queued, and
        # each chunk bounds solve p99 under pressure
        monitor = self.batcher._monitor()
        split = monitor.config.split_items
        if int(monitor.level()) >= 1 and 0 < split < len(pods):
            chunks = [pods[i:i + split]
                      for i in range(0, len(pods), split)]
            if self.shard:
                WINDOW_SPLITS_TOTAL.inc(amount=float(len(chunks) - 1),
                                        shard=self.shard)
            else:
                WINDOW_SPLITS_TOTAL.inc(amount=float(len(chunks) - 1))
            log.info("pressure L%d: split %d-pod window into %d "
                     "chunks of <=%d window_id=%s shard=%s",
                     int(monitor.level()), len(pods), len(chunks), split,
                     self._window_id, self.shard or "0")
        else:
            # L0: bound chunks to the pipeline's unit size so depth>1
            # has work to overlap. The SAME boundaries apply at depth 1
            # — chunking is governed by chunk_items, depth only by the
            # pipeline — so serial and pipelined runs stay node-for-node
            # identical (the A/B bench and differential suite rely on it)
            ci = eng.pipeline_config.chunk_items
            if 0 < ci < len(pods):
                chunks = [pods[i:i + ci]
                          for i in range(0, len(pods), ci)]
            else:
                chunks = [pods]
        # the pipeline consumes FIFO, so the first chunk still launches
        # and binds as soon as its solve lands (first-chunk-binds-early)
        # while the next chunk's solve is already in flight; at L1+ the
        # effective depth collapses to 1 and this degenerates to the
        # serial chunk loop
        eng.pipeline.set_monitor(monitor)
        self._current = eng
        try:
            results = eng.pipeline.run(
                chunks, prepare=self._prepare_chunk,
                dispatch=self._dispatch_chunk,
                consume=self._complete_chunk,
                on_chunk=self._observe_chunk)
        finally:
            self._current = None
            # tag the window span with the pipeline's measured overlap so
            # traceview's overlap column comes from the same ledger as
            # solver_overlap_seconds_total
            cur = obtrace.current_context()
            lw = eng.pipeline.last_window
            if cur is not None and lw:
                cur.tag(wall_s=round(lw.get("wall_s", 0.0), 6),
                        overlap_s=round(lw.get("overlap_s", 0.0), 6),
                        depth=lw.get("depth"))
        last_result = None
        for result in results:
            if result is not None:
                last_result = result
        return last_result

    # -- pipeline stages (one schedule → solve → launch pass per chunk) ------
    def _prepare_chunk(self, pods: List[Pod]) -> _ChunkPrep:
        """Host marshal stage: schedule the chunk and build its packing
        problems. Catalog/daemon I/O stays OUTSIDE the binpacking histogram
        so that measures the solver alone."""
        eng = self._engine()
        with HISTOGRAMS.time("scheduling_duration_seconds",
                             provisioner=eng.provisioner.metadata.name):
            with obtrace.span("feasibility",
                              provisioner=eng.provisioner.metadata.name,
                              pods=len(pods)):
                schedules = eng.scheduler.solve(eng.provisioner, pods)
            # gang schedules peel off into the co-pack window; the rest
            # keep the reference's per-schedule packing problems
            gang_scheds = [s for s in schedules if s.gang is not None]
            schedules = [s for s in schedules if s.gang is None]
            problems = [
                Problem(
                    constraints=s.constraints,
                    pods=s.pods,
                    instance_types=self.cloud_provider.get_instance_types(
                        s.constraints),
                    daemons=self._get_daemons(s.constraints),
                    soft_affinity=s.soft_affinity)
                for s in schedules
            ]
        prep = _ChunkPrep(schedules=schedules, problems=problems, pods=pods)
        if gang_scheds:
            prep.gang_enc, prep.gang_types = self._encode_gangs(gang_scheds)
            # seed bins ARE real nodes: pre-binding their bin→node names
            # makes _launch_gang bind onto them without creating anything
            for bi, bn in enumerate(prep.gang_enc.bins):
                if bn.node_name:
                    prep.gang_nodes[bi] = bn.node_name
        prep.solver_config = self._chunk_solver_config(prep)
        return prep

    def _chunk_solver_config(self, prep: _ChunkPrep) -> Optional[SolverConfig]:
        """What-if pricing handoff: when the interruption-priced policy is
        active and the operator left repack_cost_per_hour unpinned (0), price
        this chunk's spot-loss cost through solver/policy.whatif_repack_cost
        — ~0 when the chunk's pods would refit on the fleet's existing free
        capacity (losing a spot node is then nearly free, so spot's discount
        wins), else the cheapest on-demand replacement $/h (spot must now
        beat its reclaim tax). Returns a chunk-scoped SolverConfig carrying
        the priced PolicyContext, or None to use the worker config as-is."""
        cfg = self.solver_config
        if cfg.packing_policy != "interruption-priced":
            return None
        if cfg.policy_context.repack_cost_per_hour > 0.0:
            return None  # operator-pinned: respect the explicit price
        if not prep.problems:
            return None
        from karpenter_tpu.models.consolidate import free_capacity_vector
        from karpenter_tpu.solver.adapter import pod_vector
        from karpenter_tpu.solver.policy import (
            PolicyContext, whatif_repack_cost,
        )
        free_vecs = []
        for node in self.kube.list("Node"):
            if node.metadata.deletion_timestamp is not None:
                continue
            if not nodeutil.is_ready(node):
                continue
            free_vecs.append(free_capacity_vector(
                node, self.kube.pods_on_node(node.metadata.name)))
        # price the dearest schedule group of the chunk: conservative —
        # spot is only chosen when even the worst-case repack is cheap
        repack = 0.0
        for problem in prep.problems:
            repack = max(repack, whatif_repack_cost(
                [pod_vector(p) for p in problem.pods], free_vecs,
                problem.instance_types,
                problem.constraints.requirements,
                cfg.cost_config))
        return replace(cfg, policy_context=PolicyContext(
            repack_cost_per_hour=repack,
            throughput=cfg.policy_context.throughput))

    def _encode_gangs(self, gang_scheds):
        """Marshal every gang schedule of the chunk into ONE window
        encoding. The window type axis is the concatenation of each
        schedule's validated+sorted catalog segment, so a gang's
        group-feasibility column (ops/feasibility.gang_feasibility_mask)
        is zero outside its own segment — prospective nodes only ever
        carry one schedule's labels/taints, exactly like the scalar
        launch path."""
        from karpenter_tpu.ops import feasibility
        from karpenter_tpu.solver import adapter

        type_frees: list = []
        type_prices: list = []
        type_names: list = []
        type_ctx: list = []
        segments = []
        for s in gang_scheds:
            catalog = self.cloud_provider.get_instance_types(s.constraints)
            daemons = self._get_daemons(s.constraints)
            packables, sorted_types = adapter.build_packables_cached(
                catalog, s.constraints, s.pods, daemons)
            allowed = adapter._allowed_sets(s.constraints)
            required = adapter._required_resources(s.pods)
            seg_mask = feasibility.gang_feasibility_mask(
                sorted_types, [(allowed, required)], s.gang.slice_)
            base = len(type_frees)
            for pk, it in zip(packables, sorted_types):
                type_frees.append(
                    [t - r for t, r in zip(pk.total, pk.reserved)])
                type_prices.append(it.price)
                type_names.append(it.name)
                type_ctx.append((s, it))
            segments.append((s, base, seg_mask))
        n = len(type_frees)
        gangs = []
        slice_dims: list = []
        gang_bands: list = []
        for s, base, seg_mask in segments:
            mask = np.zeros(n, bool)
            mask[base:base + len(seg_mask)] = seg_mask
            gangs.append((s.gang.key, s.pods, mask, s))
            slice_dims.append(s.gang.slice_.dims
                              if s.gang.slice_ is not None else None)
            # the gang's band is its highest-priority member's: one
            # critical member makes the whole group preemption-proof
            gang_bands.append(min(
                (pressure.classify(p)[0] for p in s.pods),
                key=lambda b: RANK.get(b, RANK["default"]),
                default="default"))
        if (topo_solver.carve_enabled()
                and any(d is not None for d in slice_dims)):
            # carve mode: annotate the window with slice grids, bands,
            # per-type torus dims, and the ledger's partially-carved real
            # nodes as seed bins. With the kill switch off, NONE of these
            # reach the encoder and the window is bit-for-bit shape-only.
            type_grids = [it.grid_dims() for _s, it in type_ctx]
            enc = encode_gang_window(
                gangs, type_frees, type_prices, type_names,
                slices=slice_dims, bands=gang_bands,
                type_grids=type_grids,
                seed_bins=self._gang_seed_bins(type_ctx))
        else:
            enc = encode_gang_window(gangs, type_frees, type_prices,
                                     type_names)
        return enc, type_ctx

    def _gang_seed_bins(self, type_ctx) -> List[GangBin]:
        """Re-offer the occupancy ledger's partially-carved Ready nodes to
        the gang window as seed bins. A node matches by (instance type
        name, constraints signature) against the window's own type axis,
        so a seed only ever hosts gangs whose labels/taints the node
        already carries — the same isolation the segment masks give fresh
        bins. Free capacity is the node's LIVE residual (allocatable minus
        running pods), so shape math and carve cells stay consistent."""
        dropped = topo_ops.LEDGER.prune(
            [n.metadata.name for n in self.kube.list("Node")])
        if self.journal is not None:
            # a pruned node's carves are gone for good — fold their
            # durable intents so compaction can drop the records
            for rec in dropped:
                if rec.intent_id:
                    self.journal.close(rec.intent_id, outcome="node-pruned")
        snap = topo_ops.LEDGER.snapshot()
        if not snap:
            return []
        from karpenter_tpu.models.consolidate import free_capacity_vector
        index_of: Dict[Tuple[str, tuple], int] = {}
        sig_of: Dict[int, tuple] = {}
        for ti, (s, it) in enumerate(type_ctx):
            sig = sig_of.get(id(s))
            if sig is None:
                sig = topo_ops.constraints_sig(s.constraints.labels,
                                               s.constraints.taints)
                sig_of[id(s)] = sig
            index_of.setdefault((it.name, sig), ti)
        seeds: List[GangBin] = []
        for ng in snap:
            ti = index_of.get((ng.type_name, ng.labels_sig))
            if ti is None:
                continue
            try:
                node = self.kube.get("Node", ng.node, "")
            except NotFound:
                continue
            if (node.metadata.deletion_timestamp is not None
                    or not nodeutil.is_ready(node)):
                continue
            free = free_capacity_vector(
                node, self.kube.pods_on_node(ng.node))
            seeds.append(GangBin(
                name=ng.node, type_index=ti,
                free=[max(f, 0) for f in free],
                grid=ng.dims, occ=ng.occ.copy(), node_name=ng.node))
        return seeds

    def _dispatch_chunk(self, prep: _ChunkPrep):
        """ALL the chunk's schedules pack in one batched device call (one
        tunnel round trip total, vmap/shard_map over the batch axis) instead
        of the reference's sequential per-schedule loop
        (provisioner.go:109-120). Async: returns the in-flight BatchHandle
        for the pipeline to fetch; fallbacks resolve at fetch time."""
        t0 = time.perf_counter()
        cfg = prep.solver_config or self.solver_config
        handle = dispatch_batch(prep.problems, config=cfg)
        if (cfg.window_backend == "global" and prep.problems
                and global_solve.enabled()
                and int(self.batcher._monitor().level()) < 1):
            # whole-window joint solve rides the same dispatch stage; at
            # pressure L1+ the window collapses to the FFD backend (chunked
            # solves must stay p99-bounded), and gang schedules never enter
            # (they peeled off into their dedicated co-pack window above)
            prep.global_handle = global_solve.dispatch_global_window(
                prep.problems, solver_config=cfg)
        if prep.gang_enc is not None and prep.gang_enc.g > 0:
            # same round trip: the gang window rides the dispatch stage
            # alongside the per-schedule batch, fetch resolves both
            prep.gang_handle = dispatch_gang_window(prep.gang_enc,
                                                    self.gang_config)
        prep.dispatch_s = time.perf_counter() - t0
        return handle

    def _complete_chunk(self, prep: _ChunkPrep,
                        results: List[SolveResult]) -> Optional[SolveResult]:
        """Launch/bind stage: runs while the NEXT chunk's solve is in
        flight (depth permitting)."""
        last_result = None
        global_results: Optional[list] = None
        if prep.global_handle is not None:
            try:
                plan = prep.global_handle.fetch()
                global_results = plan.results
                if plan.accepted:
                    log.info("global window solve: %d/%d schedule(s) "
                             "strictly cheaper (executor=%s) window_id=%s "
                             "shard=%s", plan.accepted, len(plan.results),
                             plan.executor, self._window_id,
                             self.shard or "0")
            except Exception:
                # verdict-is-a-filter: any global-solve failure keeps the
                # FFD backend's results untouched
                log.exception("global window fetch failed; keeping FFD "
                              "plans window_id=%s", self._window_id)
        for idx, (schedule, result) in enumerate(
                zip(prep.schedules, results)):
            if global_results is not None and idx < len(global_results) \
                    and global_results[idx] is not None:
                result = global_results[idx]
            last_result = result
            for packing in result.packings:
                err = self._launch(self._steer(schedule, packing), packing)
                if err is not None:
                    log.error("could not launch node: %s", err)
        if prep.gang_enc is not None:
            self._complete_gangs(prep)
        return last_result

    # -- gang co-pack (all-or-nothing pod groups) ----------------------------
    def _complete_gangs(self, prep: _ChunkPrep) -> None:
        """Fetch the window's batched gang solve, re-verify every accepted
        gang on exact host ints, and bind atomically. Unplaceable gangs
        stay Pending — the selection requeue's jittered backoff re-enters
        them on the next pass."""
        enc = prep.gang_enc
        GANG_WINDOWS_TOTAL.inc()
        if enc.carve is not None:
            TOPOLOGY_CARVE_WINDOWS_TOTAL.inc()
        for key, reason in enc.skipped:
            GANGS_UNPLACEABLE_TOTAL.inc(reason="no-type")
            log.info("gang %s unplaceable: %s window_id=%s shard=%s",
                     key, reason, self._window_id, self.shard or "0")
        feasible = None
        if prep.gang_handle is not None:
            feasible, _, executor = prep.gang_handle.fetch()
            log.info("gang window solved: %d gang(s) executor=%s "
                     "window_id=%s shard=%s", enc.g, executor,
                     self._window_id, self.shard or "0")
        preempt = None
        if enc.carve is not None:
            preempt = self._build_preempt_context(prep)
        plan = plan_gang_window(enc, feasible, preempt)
        for e, reason in plan.unplaced:
            GANGS_UNPLACEABLE_TOTAL.inc(reason=reason)
            log.info("gang %s unplaceable: %s window_id=%s shard=%s",
                     e.key, reason, self._window_id, self.shard or "0")
        pre_of: Dict[int, List[PreemptCandidate]] = {}
        for e, cand in plan.preemptions:
            pre_of.setdefault(e.index, []).append(cand)
        for placement in plan.placements:
            # victims ride into _launch_gang: they unbind only after every
            # beneficiary node exists (so a failed fleet launch displaces
            # nothing) but before bind_pods lands (the carve cells and
            # resource refund the planner charged for must be real by then)
            err = self._launch_gang(prep, placement,
                                    pre_of.pop(placement.gang.index, []))
            if err is None:
                GANGS_PLACED_TOTAL.inc()
            else:
                GANGS_UNPLACEABLE_TOTAL.inc(reason="bind-failed")
                log.error("gang %s bind failed (unwound): %s window_id=%s "
                          "shard=%s", placement.gang.key, err,
                          self._window_id, self.shard or "0")

    def _build_preempt_context(self, prep: _ChunkPrep
                               ) -> Optional[PreemptContext]:
        """Price every displaceable resident of the window's seed bins.
        System-critical residents are never offered; everyone else is
        priced through solver/policy.whatif_repack_cost — ~0 when the
        victim's members refit on the fleet's existing free capacity,
        else the cheapest replacement node's $/h — so the planner preempts
        exactly when displacement is cheaper than a fresh node."""
        enc = prep.gang_enc
        seeds = [(bi, bn) for bi, bn in enumerate(enc.bins)
                 if bn.node_name]
        if not seeds:
            return None
        self.preempt_budget.tick()
        from karpenter_tpu.models.consolidate import (
            NANO, free_capacity_vector)
        from karpenter_tpu.solver.adapter import pod_vector
        from karpenter_tpu.solver.host_ffd import R_PODS
        from karpenter_tpu.solver.policy import whatif_repack_cost
        by_node = {ng.node: ng for ng in topo_ops.LEDGER.snapshot()}
        free_vecs: Optional[list] = None
        cands: List[PreemptCandidate] = []
        for bi, bn in seeds:
            ng = by_node.get(bn.node_name)
            if ng is None:
                continue
            sched, _it = prep.gang_types[bn.type_index]
            seg_types = [it for s2, it in prep.gang_types if s2 is sched]
            for rec in ng.carves.values():
                if rec.band == "system-critical":
                    continue
                vecs, live = [], []
                refund = [0] * len(bn.free)
                for pns, pname in rec.pods:
                    try:
                        p = self.kube.get("Pod", pname, pns)
                    except NotFound:
                        continue
                    v = pod_vector(p)
                    vecs.append(v)
                    refund = [a + b for a, b in zip(refund, v)]
                    refund[R_PODS] += NANO  # the pod slot comes back too
                    live.append((pns, pname))
                if free_vecs is None:
                    free_vecs = []
                    for node in self.kube.list("Node"):
                        if node.metadata.deletion_timestamp is not None:
                            continue
                        if not nodeutil.is_ready(node):
                            continue
                        free_vecs.append(free_capacity_vector(
                            node,
                            self.kube.pods_on_node(node.metadata.name)))
                cost = (whatif_repack_cost(
                    vecs, free_vecs, seg_types,
                    sched.constraints.requirements,
                    self.solver_config.cost_config) if vecs else 0.0)
                cands.append(PreemptCandidate(
                    gang_key=rec.gang_key, bin_index=bi, node=ng.node,
                    band=rec.band, pods=live, cells=rec.cells.copy(),
                    refund=refund, displacement_cost=cost))
        # anti-thrash gate: cooldown + per-band token filtering happens
        # BEFORE the planner prices anything, so a budget-capped window
        # falls back to fresh nodes instead of oscillating residents
        cands = self.preempt_budget.admit(cands)
        return PreemptContext(cands) if cands else None

    def _execute_preemption(self, cand: PreemptCandidate,
                            beneficiary=None) -> Optional[str]:
        """Displace one resident gang: unbind its members, release its
        ledger carves, and requeue the whole group atomically through the
        band-aware batcher (shed-proof — the members were running). The
        requeued items route to the default engine; a multi-engine shard's
        selection requeue re-offers any that miss their window.

        The whole displacement is bracketed by a durable ``preempt``
        intent: the victim list is on disk BEFORE the first unbind, and
        the phase advances to ``victims-unbound`` only after the requeue
        and the carve release both landed. A crash at any instant is
        therefore replayable — still phase ``open`` with every member
        bound means nothing happened (no-op); anything else rolls
        forward through RecoveryController._resolve_preempt (victims
        re-admitted, carve cells released). Returns the intent id so
        _launch_gang can advance it to ``beneficiary-bound`` once the
        winner's members land."""
        journal = self.journal
        piid = None
        if journal is not None:
            piid = journal.open_intent(
                "preempt", gang=str(cand.gang_key), node=cand.node,
                band=cand.band,
                pods=[f"{pns}/{pname}" for pns, pname in cand.pods],
                beneficiary=str(beneficiary) if beneficiary else "")

        def clear(obj):
            if getattr(obj.spec, "node_name", ""):
                obj.spec.node_name = ""
            else:
                raise _NoChange

        entries = []
        for pns, pname in cand.pods:
            try:
                self.kube.patch("Pod", pname, pns, clear)
            except (_NoChange, NotFound):
                pass
            try:
                p = self.kube.get("Pod", pname, pns)
            except NotFound:
                continue
            band, priority = pressure.classify(p)
            gspec = gang_of(p)
            gang = ((gspec.key, gspec.size)
                    if gspec is not None and not gspec.error else None)
            entries.append(((None, p), (pns, pname), band, priority, gang))
        if entries:
            self.batcher.requeue_displaced(entries)
        for _node, rec in topo_ops.LEDGER.pop_gang(cand.gang_key):
            if journal is not None and rec.intent_id:
                # fold the victim's durable carve: compaction may now
                # drop both halves of the pair
                journal.close(rec.intent_id, outcome="preempted")
        self.preempt_budget.charge(cand.gang_key, cand.band)
        if piid is not None:
            journal.advance(piid, "victims-unbound")
        PREEMPTIONS_TOTAL.inc(band=cand.band)
        if entries:
            PREEMPTION_DISPLACED_PODS_TOTAL.inc(amount=float(len(entries)))
        log.info("preempted gang %s on %s: band=%s %d pod(s) requeued "
                 "displacement=$%.4f/h window_id=%s shard=%s",
                 cand.gang_key, cand.node, cand.band, len(entries),
                 cand.displacement_cost, self._window_id, self.shard or "0")
        return piid

    def _carve_payload(self, prep: _ChunkPrep,
                       placement: GangPlacement) -> List[dict]:
        """JSON-ready carve records for a placement, one per carved bin —
        the exact data a ``carve`` intent carries. Built BEFORE the
        gang-bind record advances to ``bound`` so the payload rides that
        append: the bind close and the carve commits are then covered by
        one durable record, and a crash between them no longer loses the
        carve (RecoveryController._resolve_gang_bind re-commits from it)."""
        if not getattr(placement, "carves", None):
            return []
        enc = prep.gang_enc
        schedule = placement.gang.context
        sig = topo_ops.constraints_sig(schedule.constraints.labels,
                                       schedule.constraints.taints)
        members = {bi: [(p.metadata.namespace, p.metadata.name)
                        for p in pods]
                   for bi, pods in placement.node_sets}
        payload: List[dict] = []
        for bi, cells in placement.carves.items():
            node = prep.gang_nodes.get(bi)
            bn = enc.bins[bi]
            if node is None or bn.grid is None:
                continue
            _s, itype = prep.gang_types[bn.type_index]
            payload.append(dict(
                gang=str(placement.gang.key), node=node,
                grid=[int(d) for d in bn.grid], type=itype.name,
                sig=sig, cells=[int(c) for c in cells],
                band=placement.gang.band,
                pods=[f"{ns}/{nm}" for ns, nm in members.get(bi, [])]))
        return payload

    def _commit_carves(self, prep: _ChunkPrep, placement: GangPlacement,
                       carves: Optional[List[dict]] = None) -> None:
        """Record a bound slice gang's carve cells in the occupancy
        ledger so later windows seed its nodes' residual grids back into
        the pool (and can price this gang as a preemption victim).

        Each commit is durably journaled as a long-lived ``carve``
        intent BEFORE the in-memory ledger mutates: the open intent IS
        the durable form of the carve, so a restart rebuilds this exact
        record (RecoveryController._resolve_carve) instead of seeing the
        fragmented node as empty and double-carving it. ``carves`` is
        the pre-built payload when the caller already journaled it onto
        the gang-bind ``bound`` append (so a crash BEFORE these opens is
        equally covered); None builds it here."""
        journal = self.journal
        if carves is None:
            carves = self._carve_payload(prep, placement)
        live: Dict[Tuple[str, str], str] = {}
        if journal is not None and carves:
            # idempotent at the journal layer too: a re-drive (or the
            # gang-bind path having already committed) reuses the live
            # carve intent instead of leaking a duplicate open one
            live = {(str(c.data.get("gang") or ""),
                     str(c.data.get("node") or "")): c.id
                    for c in journal.open_of_kind("carve")}
        for rec in carves:
            cid = ""
            if journal is not None:
                cid = (live.get((rec["gang"], rec["node"]))
                       or journal.open_intent("carve", **rec))
            topo_ops.LEDGER.commit(
                rec["node"], tuple(rec["grid"]), rec["type"], rec["sig"],
                placement.gang.key, rec["cells"], rec["band"],
                [tuple(str(p).partition("/")[::2]) for p in rec["pods"]],
                intent_id=cid)
            TOPOLOGY_CARVES_COMMITTED_TOTAL.inc()

    def _launch_gang(self, prep: _ChunkPrep,
                     placement: GangPlacement,
                     victims: Optional[List[PreemptCandidate]] = None
                     ) -> Optional[str]:
        """Atomic gang launch: every member binds or none stays bound.
        Two phases — create all node objects first, then bind members —
        so a mid-fleet launch failure costs zero binds; a mid-bind
        failure unwinds the bound members and hands the created nodes to
        the termination finalizer. ``victims`` (this gang's planned
        preemptions) displace between the phases: only once every node
        exists, so a limits refusal or a failed fleet launch evicts
        nothing, yet before any member binds onto the freed capacity."""
        schedule = placement.gang.context
        constraints = schedule.constraints
        provisioner = self._engine().provisioner
        try:
            latest = self.kube.get("Provisioner", provisioner.metadata.name)
        except NotFound:
            return "provisioner deleted"
        err = provisioner.spec.limits.exceeded_by(latest.status.resources)
        if err is not None:
            return err
        enc = prep.gang_enc
        journal = self.journal
        iid = None
        if journal is not None:
            # member set + created-node set are journaled as they grow,
            # so a crash at ANY instant — mid phase 1, mid bind, mid
            # unwind — leaves the exact rollback list on disk
            iid = journal.open_intent(
                "gang-bind", gang=str(placement.gang.key),
                members=[f"{p.metadata.namespace}/{p.metadata.name}"
                         for p in placement.gang.pods])
        # phase 1: every node object exists before any member binds
        created: List[str] = []
        nonces: List[str] = []
        node_of: Dict[int, str] = {}
        for bin_index, _pods in placement.node_sets:
            name = prep.gang_nodes.get(bin_index)
            if name is None:
                _, itype = prep.gang_types[enc.bins[bin_index].type_index]
                if iid is not None:
                    # each gang node's launch nonce is durable BEFORE the
                    # provider create: a crash between the instance launch
                    # and the Node write (or the created-set note below)
                    # leaves capacity recovery attributes by nonce rather
                    # than an uncovered leak
                    nonce = jr.new_nonce()
                    nonces.append(nonce)
                    journal.note(iid, nonces=list(nonces))
                    with jr.preassigned_nonce(nonce):
                        name = self._create_gang_node(constraints, itype)
                else:
                    name = self._create_gang_node(constraints, itype)
                if name is None:
                    self._unwind_gang_journaled(iid, prep, placement,
                                                node_of, created)
                    return (f"could not launch node for bin "
                            f"{enc.bins[bin_index].name}")
                prep.gang_nodes[bin_index] = name
                created.append(name)
                if iid is not None:
                    journal.note(iid, created=list(created))
            node_of[bin_index] = name
        if iid is not None:
            journal.advance(iid, "nodes-created",
                            nodes=sorted(set(node_of.values())),
                            created=list(created))
        preempt_iids: List[str] = []
        for cand in victims or ():
            piid = self._execute_preemption(
                cand, beneficiary=placement.gang.key)
            if piid is not None:
                preempt_iids.append(piid)
        # phase 2: bind members node-set by node-set
        for bin_index, pods in placement.node_sets:
            name = node_of[bin_index]
            try:
                errs = self.kube.bind_pods(pods, name)
            except ApiError as e:
                errs = [str(e)] * len(pods)
            errs = [e for e in errs
                    if "already bound" not in e and "already exists" not in e]
            if errs:
                self._unwind_gang_journaled(iid, prep, placement,
                                            node_of, created)
                if journal is not None:
                    # victims were already unbound + requeued in-process;
                    # the displacement stands even though the winner
                    # unwound, so the intents fold at victims-unbound
                    for piid in preempt_iids:
                        journal.close(piid, outcome="beneficiary-unwound")
                return f"binding to {name}: " + "; ".join(errs)
        # the carve payload rides the ``bound`` append: one durable record
        # covers both the bind close and the carve commits, so a crash
        # between them re-commits the carves from the gang-bind intent
        # instead of losing them (the PR 19 one-append durability gap)
        carves = self._carve_payload(prep, placement)
        if iid is not None:
            journal.advance(iid, "bound", carves=carves)
        self._commit_carves(prep, placement, carves)
        if iid is not None:
            for piid in preempt_iids:
                journal.advance(piid, "beneficiary-bound")
                journal.close(piid)
            journal.close(iid)
        log.info("gang %s bound: %d pod(s) across %d node(s) window_id=%s "
                 "shard=%s", placement.gang.key, len(placement.gang.pods),
                 len(placement.node_sets), self._window_id,
                 self.shard or "0")
        return None

    def _unwind_gang_journaled(self, iid: Optional[str], prep: _ChunkPrep,
                               placement: GangPlacement,
                               node_of: Dict[int, str],
                               created: List[str]) -> None:
        """Journal-bracketed unwind: ``unwinding`` is durable before the
        first rollback write and ``unwound`` after the last, so recovery
        can resume (phase unwinding) or skip (unwound) a crashed one."""
        journal = self.journal
        if journal is not None and iid is not None:
            journal.advance(iid, "unwinding",
                            nodes=sorted(set(node_of.values())),
                            created=list(created))
        self._unwind_gang(prep, placement, node_of, created)
        if journal is not None and iid is not None:
            journal.advance(iid, "unwound")
            journal.close(iid, outcome="unwound")

    def _create_gang_node(self, constraints: Constraints,
                          itype) -> Optional[str]:
        """Launch ONE node of ``itype`` and create its Node object
        (finalizer + not-ready taint) without binding anything."""
        names: List[str] = []

        def bind(node: Node) -> Optional[str]:
            node.metadata.labels.update(constraints.labels)
            node.spec.taints.extend(constraints.taints)
            err = self._bind(node, [])
            if err is None:
                names.append(node.metadata.name)
            return err

        errs = self.cloud_provider.create(constraints, [itype], 1, bind)
        errs = [e for e in errs if e]
        if errs:
            log.error("gang node launch failed: %s", "; ".join(errs))
        return names[0] if names else None

    def _unwind_gang(self, prep: _ChunkPrep, placement: GangPlacement,
                     node_of: Dict[int, str], created: List[str]) -> None:
        """Roll a partially-bound gang back to nothing: unbind every
        member that landed on one of this gang's nodes, then delete the
        nodes created for it — the termination finalizer walks them
        through cordon/drain/instance teardown like any other node."""
        names = set(node_of.values())

        def clear(obj):
            if getattr(obj.spec, "node_name", "") in names:
                obj.spec.node_name = ""
            else:
                raise _NoChange

        for pod in placement.gang.pods:
            try:
                self.kube.patch("Pod", pod.metadata.name,
                                pod.metadata.namespace, clear)
            except (_NoChange, NotFound):
                pass
        gone = set(created)
        for bi in [b for b, n in prep.gang_nodes.items() if n in gone]:
            del prep.gang_nodes[bi]  # a later gang must not bind here
        for name in created:
            try:
                self.kube.delete("Node", name, "")
            except (NotFound, ApiError):
                pass

    def _observe_chunk(self, prep: _ChunkPrep, stats: dict) -> None:
        # binpacking = solver wall the hot loop actually paid (dispatch +
        # blocked fetch); device time hidden behind launch/bind is the
        # pipeline's win and lands in solver_overlap_seconds_total instead
        HISTOGRAMS.histogram("binpacking_duration_seconds").observe(
            prep.dispatch_s + stats.get("device_s", 0.0),
            provisioner=self._engine().provisioner.metadata.name)
        if slo.enabled():
            self._stamp_chunk_slo(prep, stats)

    def _stamp_chunk_slo(self, prep: _ChunkPrep, stats: dict) -> None:
        """Fold the chunk into the SLO digests, reusing the pipeline's own
        stage boundaries (stats t_dispatch/t_fetch/t_done, perf_counter)
        against the window marks' close timestamp — no re-timing, no clock
        mixing (intake_s is pre-computed by the batcher on its own clock).
        Stage durations are shared chunk-wide, so they fold via one O(1)
        weighted record per band; only e2e (intake varies per pod) is
        per-pod."""
        marks = slo.current_marks()
        t_dispatch = stats.get("t_dispatch")
        t_fetch = stats.get("t_fetch")
        t_done = stats.get("t_done")
        if marks is None or not prep.pods or t_dispatch is None \
                or t_fetch is None or t_done is None:
            return
        schedule_s = max(0.0, t_dispatch - marks.t_close)
        solve_s = max(0.0, t_fetch - t_dispatch)
        bind_s = max(0.0, t_done - t_fetch)
        tail_s = max(0.0, t_done - marks.t_close)
        band_counts: Dict[str, int] = {}
        for p in prep.pods:
            m = marks.meta.get(id(p))
            if m is None:
                continue
            band, intake_s = m
            band_counts[band] = band_counts.get(band, 0) + 1
            slo.record(band, "e2e", intake_s + tail_s)
        for band, cnt in band_counts.items():
            slo.record(band, "schedule", schedule_s, count=cnt)
            slo.record(band, "solve", solve_s, count=cnt)
            slo.record(band, "bind", bind_s, count=cnt)

    def _is_provisionable(self, candidate: Pod) -> bool:
        """Fresh read per pod to avoid duplicate binds (provisioner.go:
        126-135). Uses the no-copy cache read: the Go analog reads the
        informer cache, and deep-copying every batched pod costs seconds
        at the 10k-pod regime for a one-field check."""
        try:
            return not self.kube.read(
                "Pod", candidate.metadata.name, candidate.metadata.namespace,
                podutil.is_scheduled)
        except NotFound:
            return False

    def _get_daemons(self, constraints: Constraints) -> List[Pod]:
        """Daemonset pods that would schedule on these nodes (packer.go:148-162)."""
        daemons = []
        for ds in self.kube.list("DaemonSet"):
            pod = Pod(spec=ds.spec.template.spec)
            if constraints.validate_pod(pod) is None:
                daemons.append(pod)
        return daemons

    def _steer(self, schedule, packing) -> Constraints:
        """Soft-affinity zone steering: the scoring kernel priced this
        schedule's row at its best-case zone (ops/policy.py soft term); the
        fleet launch would otherwise pick lowest-price among ALL allowed
        zones and could scatter the cohort. steer_zone re-derives the
        winning zone on host in the same exact int micro-$ fixed point and
        the launch narrows to it — a copy, never the cached schedule
        constraints. No votes / kill switch off / already pinned → the
        original constraints object, bit-for-bit the pre-soft launch."""
        soft = getattr(schedule, "soft_affinity", None)
        if not soft:
            return schedule.constraints
        from karpenter_tpu.ops import policy as ops_policy

        cfg = self.solver_config
        zone = ops_policy.steer_zone(
            packing.instance_type_options, schedule.constraints.requirements,
            cfg.cost_config, cfg.policy_context, soft)
        if zone is None:
            return schedule.constraints
        steered = schedule.constraints.deepcopy()
        steered.requirements.items.append(Req(
            key=wellknown.LABEL_TOPOLOGY_ZONE, operator="In", values=[zone]))
        SOFT_AFFINITY_STEERED_TOTAL.inc()
        return steered

    def _launch(self, constraints: Constraints, packing) -> Optional[str]:
        """Limits check + CloudProvider.Create with bind callback
        (provisioner.go:137-157)."""
        provisioner = self._engine().provisioner
        try:
            latest = self.kube.get("Provisioner", provisioner.metadata.name)
        except NotFound:
            return "provisioner deleted"
        err = provisioner.spec.limits.exceeded_by(latest.status.resources)
        if err is not None:
            return err
        pods_per_node = list(packing.pods)

        def bind(node: Node) -> Optional[str]:
            node.metadata.labels.update(constraints.labels)
            node.spec.taints.extend(constraints.taints)
            return self._bind(node, pods_per_node.pop(0) if pods_per_node else [])

        journal = self.journal
        if journal is None:
            errs = self.cloud_provider.create(
                constraints, packing.instance_type_options,
                packing.node_quantity, bind)
            errs = [e for e in errs if e]
            return "; ".join(errs) if errs else None
        # journaled fleet launch: the launch nonce is drawn and durable
        # BEFORE the provider create, and pre-stamped onto the capacity it
        # launches — a crash anywhere inside leaves instances that restart
        # recovery attributes by nonce instead of waiting out GC's grace
        nonce = jr.new_nonce()
        iid = journal.open_intent(
            "fleet-launch", nonce=nonce,
            provisioner=provisioner.metadata.name,
            quantity=int(packing.node_quantity))
        with jr.preassigned_nonce(nonce):
            errs = self.cloud_provider.create(
                constraints, packing.instance_type_options,
                packing.node_quantity, bind)
        journal.advance(iid, "launched")
        errs = [e for e in errs if e]
        journal.close(iid, outcome="error" if errs else "done")
        return "; ".join(errs) if errs else None

    def _bind(self, node: Node, pods: List[Pod]) -> Optional[str]:
        """Create the node object (finalizer + not-ready taint) and bind pods
        (provisioner.go:159-198)."""
        provisioner = self._engine().provisioner
        t_bind = time.perf_counter()
        try:
            return self._bind_traced(node, pods, provisioner)
        finally:
            # the window trace id rides as the exemplar, joining this
            # histogram's tail back to one concrete window trace
            HISTOGRAMS.histogram("bind_duration_seconds").observe(
                time.perf_counter() - t_bind,
                exemplar=obtrace.current_trace_id(),
                provisioner=provisioner.metadata.name)

    def _bind_traced(self, node: Node, pods: List[Pod],
                     provisioner: Provisioner) -> Optional[str]:
        with obtrace.span("bind", node=node.metadata.name, pods=len(pods)):
            node.metadata.namespace = ""
            node.metadata.finalizers.append(wellknown.TERMINATION_FINALIZER)
            node.metadata.labels.setdefault(
                wellknown.PROVISIONER_NAME_LABEL, provisioner.metadata.name)
            # prevent the kube scheduler racing our binds (provisioner.go:164-176)
            node.spec.taints.append(Taint(key=wellknown.NOT_READY_TAINT_KEY,
                                          effect="NoSchedule"))
            journal = self.journal
            iid = None
            if journal is not None:
                iid = journal.open_intent(
                    "bind", node=node.metadata.name,
                    provider_id=node.spec.provider_id,
                    pods=[f"{p.metadata.namespace}/{p.metadata.name}"
                          for p in pods])
            try:
                self.kube.create(node)
            except AlreadyExists:
                pass  # self-registered first — idempotent (provisioner.go:177-186)
            except ApiError as e:
                # no Node object: the pods stay pending and re-enter the
                # next batch; the launched capacity (if any) is the GC
                # controller's problem, not silently orphaned state
                if iid is not None:
                    journal.close(iid, outcome="error")
                return f"creating node object {node.metadata.name}: {e}"
            if iid is not None:
                journal.advance(iid, "node-created")
            # one locked pass for the node's whole pod set (provisioner.go
            # binds sequentially; per-pod lock round-trips dominated the
            # 10k-pod flood on a contended host)
            try:
                errs = self.kube.bind_pods(pods, node.metadata.name)
            except ApiError as e:
                errs = [str(e)] * len(pods)
            # an already-bound pod is success, not failure: informer-cache
            # lag over the wire can re-batch a pod whose earlier bind
            # landed, and treating that as an error would relaunch capacity
            # for it every window until the cache catches up
            errs = [e for e in errs
                    if "already bound" not in e and "already exists" not in e]
            for e in errs:
                log.error("failed to bind to %s: %s", node.metadata.name, e)
            log.info("bound %d pod(s) to node %s window_id=%s shard=%s",
                     len(pods) - len(errs), node.metadata.name,
                     self._window_id, self.shard or "0")
            # propagate instead of swallowing: the joined error surfaces
            # through CloudProvider.create → _launch → the provision loop's
            # error log, and the unbound pods remain provisionable so the
            # selection requeue / next batch retries them
            if errs:
                if iid is not None:
                    journal.close(iid, outcome="error")
                return (f"binding {len(errs)} pod(s) to "
                        f"{node.metadata.name}: " + "; ".join(errs))
            if iid is not None:
                journal.advance(iid, "bound")
                journal.close(iid)
            return None


class ProvisioningController:
    """Reconciles Provisioner CRs into workers (controller.go:44-128).

    ``shards=0`` (default): one worker per Provisioner, the reference's
    shape. ``shards=N``: N long-lived shard workers; each Provisioner's
    engine attaches to shard ``crc32(name) % N`` (docs/scale.md §1)."""

    REQUEUE_SECONDS = 5 * 60  # catch zone/type drift (controller.go:82-83)

    def __init__(self, kube: KubeCore, cloud_provider: CloudProvider,
                 solver_config: Optional[SolverConfig] = None,
                 batcher_factory: Optional[Callable[[], Batcher]] = None,
                 pipeline_config: Optional[PipelineConfig] = None,
                 shards: int = 0,
                 journal: Optional["jr.IntentJournal"] = None):
        self.kube = kube
        self.cloud_provider = cloud_provider
        self.journal = journal
        self.solver_config = solver_config
        self.pipeline_config = pipeline_config
        self.batcher_factory = batcher_factory or Batcher
        self.shards = int(shards or 0)
        # legacy: provisioner name → its worker; sharded: "shard-i" → worker
        self.workers: Dict[str, ProvisionerWorker] = {}
        self._hashes: Dict[str, tuple] = {}
        self._lock = threading.Lock()

    def kind(self) -> str:
        return "Provisioner"

    def targets(self) -> List[Tuple[Provisioner, ProvisionerWorker]]:
        """Routing snapshot for the selection controller: every hosted
        (provisioner, worker) pair across all workers, in worker-creation
        then engine-attach order (deterministic — selection's first-match
        semantics depend on a stable iteration order). Works identically
        for both deployment shapes; legacy workers host exactly one
        engine, so this reduces to the old per-worker iteration."""
        with self._lock:
            workers = list(self.workers.values())
        out = []
        for w in workers:
            for eng in w.engines():
                out.append((eng.provisioner, w))
        return out

    def _shard_worker(self, name: str) -> ProvisionerWorker:
        """Get-or-create the shard worker hosting ``name``'s engine.
        Caller holds self._lock."""
        sid = shard_of(name, self.shards)
        wname = f"shard-{sid}"
        worker = self.workers.get(wname)
        if worker is None:
            worker = ProvisionerWorker(
                None, self.kube, self.cloud_provider,
                solver_config=self.solver_config,
                batcher=self.batcher_factory(),
                pipeline_config=self.pipeline_config,
                shard=str(sid),
                journal=self.journal)
            worker.start()
            self.workers[wname] = worker
        return worker

    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        try:
            provisioner = self.kube.get("Provisioner", name, namespace)
        except NotFound:
            with self._lock:
                self._hashes.pop(name, None)
                if self.shards > 0:
                    # the shard worker outlives any one tenant: detach the
                    # engine, keep the shard serving its other provisioners
                    w = self.workers.get(f"shard-{shard_of(name, self.shards)}")
                    if w is not None:
                        w.detach(name)
                    return None
                worker = self.workers.pop(name, None)
            if worker:
                worker.stop()
            return None
        if provisioner.metadata.deletion_timestamp is not None:
            return None

        # refresh global requirements from the live catalog
        catalog = self.cloud_provider.get_instance_types(provisioner.spec.constraints)
        provisioner.spec.constraints.requirements = (
            provisioner.spec.constraints.requirements.add(
                *global_requirements(catalog).items))

        key = _spec_hash(provisioner)
        with self._lock:
            if self._hashes.get(name) != key:
                if self.shards > 0:
                    # attach replaces the engine in place; the shard worker,
                    # its thread, and its batcher (queued pods included)
                    # survive the spec change
                    self._shard_worker(name).attach(provisioner)
                else:
                    old = self.workers.get(name)
                    if old:
                        old.stop()
                    worker = ProvisionerWorker(
                        provisioner, self.kube, self.cloud_provider,
                        solver_config=self.solver_config,
                        batcher=self.batcher_factory(),
                        pipeline_config=self.pipeline_config,
                        journal=self.journal)
                    worker.start()
                    self.workers[name] = worker
                self._hashes[name] = key
        # conditions refresh EVERY reconcile, including the unchanged-spec
        # steady state: solver health moves between spec changes, and a
        # breaker trip must surface on the 5-min requeue, not only on
        # worker restart
        self._update_conditions(name, namespace)
        return float(self.REQUEUE_SECONDS)

    def _update_conditions(self, name: str, namespace: str) -> None:
        """Maintain the living status conditions (provisioner_status.go:38-49,
        register.go:51-54 wire an `Active` condition set; this framework adds
        SolverHealthy: which executor ring answered last and whether the
        device circuit breaker is open). The status write is skipped when
        nothing changed, so the refresh cannot loop on its own watch event."""
        import time as _time

        from karpenter_tpu.solver.solve import solver_health

        health = solver_health()
        executor = health["last_executor"]
        breaker = health["breaker_open"]
        if breaker:
            solver = ("False", "DeviceCircuitOpen",
                      "device transport watchdog tripped; host executors "
                      "answering (docs/TROUBLESHOOTING.md)")
        else:
            # executor name only — no volatile fields (latency, timestamps):
            # the condition must compare EQUAL between real state changes,
            # or every reconcile writes status and the MODIFIED event fans
            # out through the node controller's provisioner→nodes mapping
            # (solve latency lives in the binpacking histogram instead)
            solver = ("True", "ExecutorRingsNominal",
                      f"last solve: executor={executor}" if executor
                      else "no solves yet")

        def apply(p):
            now = _time.time()
            changed = set_condition(
                p.status.conditions, "Active", "True", "WorkerRunning",
                "provisioner worker running", now=now)
            changed |= set_condition(
                p.status.conditions, "SolverHealthy", *solver, now=now)
            if not changed:
                raise _NoChange

        try:
            self.kube.patch("Provisioner", name, namespace, apply)
        except (_NoChange, NotFound):
            pass

    def stop_all(self) -> None:
        """Stop every worker thread (called by Manager.stop)."""
        with self._lock:
            workers = list(self.workers.values())
            self.workers.clear()
            self._hashes.clear()
        for w in workers:
            w.stop()


def universe_constraints(catalog: List[InstanceType]) -> Constraints:
    """Constraints admitting everything the catalog offers — the same
    universe injection the controller performs (controller.go:141-162).
    Shared by tests/bench so fixtures can't drift from the production path."""
    return Constraints(requirements=global_requirements(catalog))


def _spec_hash(p: Provisioner) -> tuple:
    c = p.spec.constraints
    return (
        tuple(sorted((r.key, r.operator, tuple(sorted(r.values)))
                     for r in c.requirements.items)),
        tuple(sorted((t.key, t.value, t.effect) for t in c.taints)),
        tuple(sorted(c.labels.items())),
        p.spec.ttl_seconds_after_empty,
        p.spec.ttl_seconds_until_expired,
    )
