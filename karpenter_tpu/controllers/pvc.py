"""PVC controller: stamp selected-node so volumes provision in-zone.

Reference: pkg/controllers/persistentvolumeclaim/controller.go:63-94.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.runtime.kubecore import KubeCore, NotFound
from karpenter_tpu.utils import pod as podutil

SELECTED_NODE_ANNOTATION = "volume.kubernetes.io/selected-node"


class PVCController:
    def __init__(self, kube: KubeCore):
        self.kube = kube

    def kind(self) -> str:
        return "PersistentVolumeClaim"

    def mappings(self):
        """Pod events map to their PVCs (pvc controller Watches(Pod))."""
        def pod_to_pvcs(pod):
            return [
                (v.persistent_volume_claim.claim_name, pod.metadata.namespace)
                for v in pod.spec.volumes
                if v.persistent_volume_claim is not None
            ]

        return [("Pod", pod_to_pvcs)]

    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        try:
            pvc = self.kube.get("PersistentVolumeClaim", name, namespace)
        except NotFound:
            return None
        pod = self._pod_for_pvc(pvc)
        if pod is None:
            return None
        if pvc.metadata.annotations.get(SELECTED_NODE_ANNOTATION) == pod.spec.node_name:
            return None
        if not self._is_bindable(pod):
            return None

        def apply(live):
            live.metadata.annotations[SELECTED_NODE_ANNOTATION] = pod.spec.node_name
        self.kube.patch("PersistentVolumeClaim", name, namespace, apply)
        return None

    def _pod_for_pvc(self, pvc):
        for pod in self.kube.list("Pod", namespace=pvc.metadata.namespace):
            for volume in pod.spec.volumes:
                if (volume.persistent_volume_claim is not None
                        and volume.persistent_volume_claim.claim_name
                        == pvc.metadata.name):
                    return pod
        return None

    @staticmethod
    def _is_bindable(pod) -> bool:
        return podutil.is_scheduled(pod) and not podutil.is_terminal(pod)
