"""Startup recovery: replay the write-ahead intent journal before the
control plane serves.

Runs once, BEFORE the Manager starts any other controller (main.py calls
``run()`` between ``serve_observability`` and ``manager.start()``;
readyz answers 503 ``recovering`` until it completes). Every intent the
crashed process left open (runtime/journal.py) is re-derived against the
two sources of truth that survive a crash — kubecore objects and
``CloudProvider.list_instances()`` — and resolved one of three ways:

- **forward**: the mutation visibly succeeded past the point of no
  return — finish it (bind the member pods whose node exists, strip the
  finalizer whose instance is already gone, re-issue the drain delete).
- **rollback**: it did not — undo it exactly once (terminate the
  nonce-attributed instances no Node ever backed, unwind the partially
  created/bound gang). Every rollback trips the flight recorder
  (``recovery-rollback``) so a restart that lost work leaves a dump.
- **noop**: live state already converged (nothing launched, node
  already gone) — just close the intent.

Replay/rollback rules per kind (docs/robustness.md §5):

fleet-launch  any open phase → every ``list_instances()`` record carrying
              the journaled nonce either backs a Node (forward: leave it,
              the bind intent owns the rest) or does not (rollback:
              ``delete_instance``). The GC controller skips journal-
              covered nonces, so this is the only terminator.
bind          node exists → roll forward: bind the journaled member pods
              that are still unbound, close. Node absent → noop (the
              fleet-launch intent owns the capacity).
gang-bind     phase ``bound`` → forward-close. ``unwound`` → close. Any
              other phase (including mid-``unwinding``) → re-run the full
              unwind idempotently: clear members bound to gang nodes,
              tear down every journaled created node (instance delete +
              finalizer strip + object delete), and delete any instance
              carrying one of the gang's journaled launch nonces that no
              Node ever backed.
drain         node exists without a deletionTimestamp → re-issue the
              delete (forward); else noop.
node-delete   node gone but its instance still listed → finish the
              instance delete (forward). Node present at phase
              ``instance-deleted`` → strip the finalizer (forward);
              at ``open`` → noop, the termination controller re-drives.
carve         replayed FIRST (before every other kind): node exists →
              re-commit the record into the occupancy ledger and leave
              the intent OPEN (an open carve IS the durable ledger
              entry); node gone → close (noop). Idempotent re-commit.
preempt       phase ``beneficiary-bound`` → pure close (forward).
              Phase ``open`` with every journaled member still bound to
              the journaled node → close, nothing happened (noop).
              Otherwise roll forward once: finish the unbind, pop the
              victim's rebuilt carve (closing its carve intents), and
              re-admit live unbound victims via the batcher hook.

After all intents resolve the journal is compacted, ``recovering()``
flips false, and readyz goes 200. The controller also satisfies the
Manager protocol (time-driven, no-op reconcile) so it can be registered
for visibility, but correctness only needs the explicit ``run()``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from karpenter_tpu import pressure
from karpenter_tpu.api import wellknown
from karpenter_tpu.api.gang import gang_of
from karpenter_tpu.cloudprovider.spi import CloudProvider
from karpenter_tpu.metrics.recovery import (
    LEDGER_RECOVERED_CARVES_TOTAL, LEDGER_RECOVERY_SECONDS,
    RECOVERY_INTENTS_TOTAL, RECOVERY_SECONDS)
from karpenter_tpu.obs import flight
from karpenter_tpu.ops import topology as topo_ops
from karpenter_tpu.runtime.journal import Intent, IntentJournal
from karpenter_tpu.runtime.kubecore import ApiError, KubeCore, NotFound

log = logging.getLogger("karpenter.recovery")


class _NoChange(Exception):
    pass


class RecoveryController:
    """One-shot journal replay; ``recovering()`` gates readyz."""

    def __init__(self, kube: KubeCore, cloud_provider: CloudProvider,
                 journal: IntentJournal, requeue_displaced=None):
        self.kube = kube
        self.cloud_provider = cloud_provider
        self.journal = journal
        # optional batcher hook (Batcher.requeue_displaced-shaped): when
        # set, preempt roll-forward re-admits the victims directly; when
        # None (main.py — no batcher exists yet at recovery time) the
        # unbound victims are Pending and the selection controller
        # re-enters them on its first pass
        self.requeue_displaced = requeue_displaced
        self._done = threading.Event()
        self.stats: Dict[str, int] = {"forward": 0, "rollback": 0,
                                      "noop": 0, "errors": 0}

    # -- readiness gate ------------------------------------------------------
    def recovering(self) -> bool:
        return not self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    # -- manager protocol (visibility only) ----------------------------------
    def kind(self) -> Optional[str]:
        return None

    def seeds(self) -> List[Tuple[str, str]]:
        return []

    def reconcile(self, name: str, namespace: str = "") -> Optional[float]:
        return None

    # -- the replay ----------------------------------------------------------
    def run(self) -> Dict[str, int]:
        t0 = time.perf_counter()
        open_intents = self.journal.open_intents()
        try:
            records = self.cloud_provider.list_instances()
        except Exception:  # noqa: BLE001 — same fail-safe bias as GC
            log.exception("list_instances failed during recovery; capacity-"
                          "side rollback skipped this startup")
            records = []
        # carve intents replay FIRST so the occupancy ledger is whole
        # before any other rule consults or releases it (a preempt
        # roll-forward pops the victim's rebuilt carve; a gang-bind
        # unwind's node teardown drops the node's carves), then preempts,
        # then everything else in append order
        order = {"carve": 0, "preempt": 1}
        ledger_s = 0.0
        saw_carve = False
        try:
            for intent in sorted(open_intents.values(),
                                 key=lambda i: (order.get(i.kind, 2), i.id)):
                t_int = time.perf_counter()
                try:
                    action = self._resolve(intent, records)
                except Exception:  # noqa: BLE001 — one bad intent must not
                    # wedge startup; it stays open for the next restart
                    log.exception("resolving %s intent %s failed",
                                  intent.kind, intent.id)
                    self.stats["errors"] += 1
                    continue
                finally:
                    if intent.kind == "carve":
                        saw_carve = True
                        ledger_s += time.perf_counter() - t_int
                self.stats[action] += 1
                RECOVERY_INTENTS_TOTAL.inc(kind=intent.kind, action=action)
                log.info("recovered %s intent %s (phase=%s): %s",
                         intent.kind, intent.id, intent.phase, action)
            self.journal.compact()
        finally:
            if saw_carve:
                LEDGER_RECOVERY_SECONDS.observe(ledger_s)
            RECOVERY_SECONDS.observe(time.perf_counter() - t0)
            self._done.set()
        if self.stats["rollback"]:
            flight.trip("recovery-rollback",
                        rollbacks=self.stats["rollback"],
                        forward=self.stats["forward"],
                        noop=self.stats["noop"])
        log.info("recovery complete in %.3fs: %s",
                 time.perf_counter() - t0, self.stats)
        return dict(self.stats)

    def _resolve(self, intent: Intent, records) -> str:
        handler = {
            "fleet-launch": self._resolve_fleet_launch,
            "bind": self._resolve_bind,
            "gang-bind": self._resolve_gang_bind,
            "drain": self._resolve_drain,
            "node-delete": self._resolve_node_delete,
            "carve": self._resolve_carve,
            "preempt": self._resolve_preempt,
        }.get(intent.kind)
        if handler is None:
            self.journal.close(intent.id, outcome="unknown-kind")
            return "noop"
        return handler(intent, records)

    # -- per-kind rules ------------------------------------------------------
    def _backed_ids(self) -> set:
        """Every instance id appearing as a providerID path segment of
        some Node (the GC controller's ownership test)."""
        def extract(n):
            pid = getattr(n.spec, "provider_id", "") or ""
            return frozenset(s for s in pid.split("/") if s)
        backed: set = set()
        for segments in self.kube.scan("Node", extract):
            backed |= segments
        return backed

    def _node_by_instance(self) -> Dict[str, str]:
        """instance id (providerID path segment) → Node name."""
        def extract(n):
            pid = getattr(n.spec, "provider_id", "") or ""
            return (n.metadata.name,
                    frozenset(s for s in pid.split("/") if s))
        out: Dict[str, str] = {}
        for name, segments in self.kube.scan("Node", extract):
            for seg in segments:
                out[seg] = name
        return out

    def _resolve_fleet_launch(self, intent: Intent, records) -> str:
        nonce = intent.data.get("nonce")
        if not nonce:
            self.journal.close(intent.id, outcome="no-nonce")
            return "noop"
        mine = [r for r in records if r.launch_nonce == nonce]
        if not mine:
            # crash before (or instead of) the provider launch: nothing to
            # undo — the pods are still pending and re-provision normally
            self.journal.close(intent.id, outcome="nothing-launched")
            return "noop"
        backed = self._backed_ids()
        rolled_back = 0
        for r in mine:
            if r.instance_id in backed:
                continue  # a Node landed: the launch made it, keep it
            err = self.cloud_provider.delete_instance(r.instance_id)
            if err is not None:
                raise RuntimeError(
                    f"terminating orphan {r.instance_id}: {err}")
            rolled_back += 1
            log.info("recovery terminated orphan instance %s (nonce=%s)",
                     r.instance_id, nonce)
        self.journal.close(
            intent.id,
            outcome="rolled-back" if rolled_back else "converged")
        return "rollback" if rolled_back else "forward"

    def _resolve_bind(self, intent: Intent, records) -> str:
        node_name = str(intent.data.get("node") or "")
        if not node_name:
            self.journal.close(intent.id, outcome="no-node")
            return "noop"
        try:
            self.kube.get("Node", node_name, "")
        except NotFound:
            # node never landed; the capacity (if launched) is the fleet-
            # launch intent's to resolve
            self.journal.close(intent.id, outcome="node-missing")
            return "noop"
        # roll forward: bind the journaled members that are still unbound
        pending = []
        for ref in intent.data.get("pods") or []:
            ns, _, name = str(ref).partition("/")
            try:
                pod = self.kube.get("Pod", name, ns)
            except NotFound:
                continue
            if not getattr(pod.spec, "node_name", ""):
                pending.append(pod)
        if pending:
            try:
                errs = self.kube.bind_pods(pending, node_name)
            except ApiError as e:
                errs = [str(e)]
            errs = [e for e in errs
                    if e and "already bound" not in e
                    and "already exists" not in e]
            if errs:
                raise RuntimeError(
                    f"re-binding to {node_name}: " + "; ".join(errs))
        self.journal.close(intent.id, outcome="bound")
        return "forward" if pending else "noop"

    def _resolve_gang_bind(self, intent: Intent, records) -> str:
        if intent.phase == "bound":
            # the bind landed; the crash may have beaten the carve-intent
            # opens that follow it. The ``bound`` append carries the full
            # carve payload, so re-commit any carve that has no open
            # carve intent of its own yet (dedupe by (gang, node) — a
            # crash AFTER the opens must not double-journal the carve)
            self._recommit_carves(intent)
            self.journal.close(intent.id, outcome="bound")
            return "forward"
        if intent.phase == "unwound":
            self.journal.close(intent.id, outcome="unwound")
            return "noop"
        # every other phase — open (mid phase 1), nodes-created (mid
        # bind), unwinding (mid rollback) — resolves by the same
        # idempotent full unwind: a gang is atomic or absent
        created = [str(n) for n in intent.data.get("created") or []]
        nodes = set(str(n) for n in intent.data.get("nodes") or [])
        nodes.update(created)
        members = [str(m) for m in intent.data.get("members") or []]
        did = 0
        # the gang's launch nonces are durable BEFORE each provider
        # create, so a crash landing between the instance launch and the
        # created-set note still resolves: any instance carrying one of
        # them is this gang's — tear down its Node if one landed, delete
        # the bare instance if not
        nonces = {str(n) for n in intent.data.get("nonces") or []}
        if nonces:
            gang_records = [r for r in records if r.launch_nonce in nonces]
            if gang_records:
                by_instance = self._node_by_instance()
                for r in gang_records:
                    name = by_instance.get(r.instance_id)
                    if name is not None:
                        nodes.add(name)
                        if name not in created:
                            created.append(name)
                    else:
                        err = self.cloud_provider.delete_instance(
                            r.instance_id)
                        if err is not None:
                            raise RuntimeError(
                                f"deleting gang instance "
                                f"{r.instance_id}: {err}")
                        did += 1
                        log.info("recovery deleted unbacked gang "
                                 "instance %s", r.instance_id)
        for ref in members:
            ns, _, name = ref.partition("/")
            def clear(obj):
                if getattr(obj.spec, "node_name", "") in nodes:
                    obj.spec.node_name = ""
                else:
                    raise _NoChange
            try:
                self.kube.patch("Pod", name, ns, clear)
                did += 1
            except (_NoChange, NotFound):
                pass
        for name in created:
            if self._teardown_node(name):
                did += 1
        self.journal.close(intent.id, outcome="unwound")
        return "rollback" if did else "noop"

    def _recommit_carves(self, intent: Intent) -> None:
        """Re-open the carve intents a crashed gang bind journaled only
        inside its ``bound`` record. Idempotent: carves whose own intent
        already exists (the crash hit after the opens) are skipped, and
        ledger commits overwrite, so replaying twice yields the same
        state. Carves on nodes that did not survive are dropped — their
        cells are not capacity anymore."""
        carves = intent.data.get("carves") or []
        if not carves:
            return
        live = {(str(c.data.get("gang") or ""), str(c.data.get("node") or ""))
                for c in self.journal.open_of_kind("carve")}
        for rec in carves:
            if not isinstance(rec, dict):
                continue
            gang = str(rec.get("gang") or "")
            node = str(rec.get("node") or "")
            if not node or (gang, node) in live:
                continue
            try:
                self.kube.get("Node", node, "")
            except NotFound:
                continue
            dims = tuple(int(d) for d in rec.get("grid") or [])
            cells = [int(c) for c in rec.get("cells") or []]
            if not dims or not cells:
                continue
            sig = topo_ops.sig_from_json(rec.get("sig") or ((), ()))
            pods = []
            for ref in rec.get("pods") or []:
                ns, _, pname = str(ref).partition("/")
                pods.append((ns, pname))
            cid = self.journal.open_intent(
                "carve", gang=gang, node=node, grid=list(dims),
                type=str(rec.get("type") or ""), sig=sig, cells=cells,
                band=str(rec.get("band") or "default"),
                pods=[f"{ns}/{nm}" for ns, nm in pods])
            topo_ops.LEDGER.commit(
                node, dims, str(rec.get("type") or ""), sig, gang, cells,
                str(rec.get("band") or "default"), pods, intent_id=cid)
            LEDGER_RECOVERED_CARVES_TOTAL.inc()

    def _resolve_carve(self, intent: Intent, records) -> str:
        """Rebuild one occupancy-ledger entry from its durable carve
        intent. Carve intents are LONG-LIVED: open = the carve is live,
        so this handler re-commits the record and leaves the intent
        OPEN — compaction keeps it, and it closes only when the gang
        releases, is preempted, or its node is pruned/torn down.
        Re-commit is idempotent (ledger overwrite semantics), so a
        double replay yields the identical ledger."""
        node = str(intent.data.get("node") or "")
        if not node:
            self.journal.close(intent.id, outcome="no-node")
            return "noop"
        try:
            self.kube.get("Node", node, "")
        except NotFound:
            # the carved node did not survive the crash: the cells are
            # not capacity anymore, fold the intent
            self.journal.close(intent.id, outcome="node-gone")
            return "noop"
        dims = tuple(int(d) for d in intent.data.get("grid") or [])
        cells = [int(c) for c in intent.data.get("cells") or []]
        if not dims or not cells:
            self.journal.close(intent.id, outcome="malformed")
            return "noop"
        sig = topo_ops.sig_from_json(intent.data.get("sig") or ((), ()))
        pods = []
        for ref in intent.data.get("pods") or []:
            ns, _, pname = str(ref).partition("/")
            pods.append((ns, pname))
        topo_ops.LEDGER.commit(
            node, dims, str(intent.data.get("type") or ""), sig,
            str(intent.data.get("gang") or ""), cells,
            str(intent.data.get("band") or "default"), pods,
            intent_id=intent.id)
        LEDGER_RECOVERED_CARVES_TOTAL.inc()
        return "forward"

    def _resolve_preempt(self, intent: Intent, records) -> str:
        """Replay one crashed displacement (docs/robustness.md §6):

        - phase ``beneficiary-bound`` — the displacement fully happened
          and the winner's members landed; the crash hit mid-close, so
          replay is a pure close (forward).
        - phase ``open`` with EVERY journaled member still bound to the
          journaled node — the crash beat the first unbind; nothing
          happened, the victims keep running (noop).
        - anything else (phase ``victims-unbound``, or ``open`` with a
          partial unbind) — roll the displacement forward exactly once:
          finish unbinding, release the victim's rebuilt carve cells
          (closing their carve intents), and re-admit every live
          unbound victim through the batcher hook.
        """
        gang = str(intent.data.get("gang") or "")
        node = str(intent.data.get("node") or "")
        if intent.phase == "beneficiary-bound":
            self.journal.close(intent.id, outcome="bound")
            return "forward"
        live = []
        bound_here = 0
        for ref in intent.data.get("pods") or []:
            ns, _, pname = str(ref).partition("/")
            try:
                pod = self.kube.get("Pod", pname, ns)
            except NotFound:
                continue
            live.append((ns, pname))
            if getattr(pod.spec, "node_name", "") == node:
                bound_here += 1
        if intent.phase == "open" and live and bound_here == len(live):
            # crash before the first unbind: the displacement never
            # started and the planner will re-price it (or not) fresh
            self.journal.close(intent.id, outcome="not-started")
            return "noop"

        def clear(obj):
            if getattr(obj.spec, "node_name", "") == node:
                obj.spec.node_name = ""
            else:
                raise _NoChange

        for ns, pname in live:
            try:
                self.kube.patch("Pod", pname, ns, clear)
            except (_NoChange, NotFound):
                pass
        for _n, rec in topo_ops.LEDGER.pop_gang(gang):
            if rec.intent_id:
                self.journal.close(rec.intent_id, outcome="preempted")
        if self.requeue_displaced is not None and live:
            entries = []
            for ns, pname in live:
                try:
                    p = self.kube.get("Pod", pname, ns)
                except NotFound:
                    continue
                if getattr(p.spec, "node_name", ""):
                    continue  # already re-bound elsewhere; not displaced
                band, priority = pressure.classify(p)
                gspec = gang_of(p)
                g = ((gspec.key, gspec.size)
                     if gspec is not None and not gspec.error else None)
                entries.append(((None, p), (ns, pname), band, priority, g))
            if entries:
                self.requeue_displaced(entries)
        self.journal.close(intent.id, outcome="victims-readmitted")
        return "forward"

    def _teardown_node(self, name: str) -> bool:
        """Direct teardown — instance delete, finalizer strip, object
        delete — because the termination controller is not running yet.
        Idempotent: every step tolerates already-done."""
        try:
            node = self.kube.get("Node", name, "")
        except NotFound:
            return False
        err = self.cloud_provider.delete(node)
        if err is not None and "not found" not in str(err).lower():
            raise RuntimeError(f"deleting instance of {name}: {err}")

        def strip(live):
            if wellknown.TERMINATION_FINALIZER in live.metadata.finalizers:
                live.metadata.finalizers = [
                    f for f in live.metadata.finalizers
                    if f != wellknown.TERMINATION_FINALIZER]
            else:
                raise _NoChange
        try:
            self.kube.patch("Node", name, "", strip)
        except (_NoChange, NotFound):
            pass
        try:
            self.kube.delete("Node", name, "")
        except (NotFound, ApiError):
            pass
        # the node's carves (rebuilt by the carve-first replay above) go
        # with it — release the cells and fold their durable intents
        for rec in topo_ops.LEDGER.pop_node(name):
            if rec.intent_id:
                self.journal.close(rec.intent_id, outcome="node-torn-down")
        log.info("recovery tore down gang node %s", name)
        return True

    def _resolve_drain(self, intent: Intent, records) -> str:
        name = str(intent.data.get("node") or "")
        ns = str(intent.data.get("namespace") or "")
        try:
            node = self.kube.get("Node", name, ns)
        except NotFound:
            self.journal.close(intent.id, outcome="gone")
            return "noop"
        if node.metadata.deletion_timestamp is not None:
            # the delete landed; termination finishes it
            self.journal.close(intent.id, outcome="deleting")
            return "noop"
        # the drain was decided (and journaled) but the delete never
        # landed: re-issue it so the consolidation plan is not lost
        try:
            self.kube.delete("Node", name, ns)
        except NotFound:
            pass
        self.journal.close(intent.id, outcome="re-drained")
        return "forward"

    def _resolve_node_delete(self, intent: Intent, records) -> str:
        name = str(intent.data.get("node") or "")
        provider_id = str(intent.data.get("provider_id") or "")
        segments = frozenset(s for s in provider_id.split("/") if s)
        try:
            node = self.kube.get("Node", name, "")
        except NotFound:
            node = None
        if node is not None:
            if intent.phase == "instance-deleted":
                # instance gone, finalizer strip crashed: finish it
                def strip(live):
                    if wellknown.TERMINATION_FINALIZER \
                            in live.metadata.finalizers:
                        live.metadata.finalizers = [
                            f for f in live.metadata.finalizers
                            if f != wellknown.TERMINATION_FINALIZER]
                    else:
                        raise _NoChange
                try:
                    self.kube.patch("Node", name, "", strip)
                except (_NoChange, NotFound):
                    pass
                self.journal.close(intent.id, outcome="finalizer-stripped")
                return "forward"
            # phase open with the Node still present: the termination
            # controller re-reconciles it from the deletionTimestamp
            self.journal.close(intent.id, outcome="termination-redrives")
            return "noop"
        # node object gone; make sure the instance went with it
        leftover = [r for r in records if r.instance_id in segments]
        for r in leftover:
            err = self.cloud_provider.delete_instance(r.instance_id)
            if err is not None:
                raise RuntimeError(
                    f"deleting leftover instance {r.instance_id}: {err}")
            log.info("recovery deleted leftover instance %s of node %s",
                     r.instance_id, name)
        self.journal.close(intent.id, outcome="done")
        return "forward" if leftover else "noop"
