"""Selection controller: route provisionable pods to a Provisioner worker.

Reference: pkg/controllers/selection/{controller.go,preferences.go,
volumetopology.go}. Watches all pods; filters to provisionable; validates
supported features; relaxes preferences on retries; injects volume topology;
picks the first Provisioner whose constraints validate the pod; blocks on
the batch gate so the kube side can re-verify after the provisioning pass.
"""

from __future__ import annotations

import logging
import threading
import zlib
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.core import (
    Affinity, NodeAffinity, NodeSelectorRequirement, NodeSelectorTerm, Pod,
)
from karpenter_tpu.obs import slo
from karpenter_tpu.ops import feasibility
from karpenter_tpu.pressure import classify, get_monitor
from karpenter_tpu.runtime.kubecore import KubeCore, NotFound
from karpenter_tpu.utils import clock
from karpenter_tpu.utils import pod as podutil

log = logging.getLogger("karpenter.selection")

RELAXATION_TTL_SECONDS = 5 * 60  # preferences.go ExpirationTTL

# requeue jitter spread: factor in [1-J/2, 1+J/2) — wide enough that a
# mass-shed cohort's retries smear across ~2.5 s at the 5 s base, narrow
# enough that backoff tiers (5/10/20 s) never overlap
JITTER_SPREAD = 0.5


def requeue_jitter(key) -> float:
    """Deterministic per-pod jitter factor in [0.75, 1.25): crc32 of the
    (namespace, name) key mapped onto the spread. Stateless and hash-based
    so the same pod always lands on the same offset (reproducible under
    seeded chaos) while DIFFERENT pods spread uniformly — which is what
    de-synchronizes a mass shed's retry wave. key=None → 1.0 (no jitter)."""
    if key is None:
        return 1.0
    h = zlib.crc32(f"{key[0]}/{key[1]}".encode())
    return 1.0 - JITTER_SPREAD / 2 + JITTER_SPREAD * (h / 2 ** 32)


def is_provisionable(p: Pod) -> bool:
    """controller.go:115-121."""
    return (
        not podutil.is_scheduled(p)
        and not podutil.is_preempting(p)
        and podutil.failed_to_schedule(p)
        and not podutil.is_owned_by_daemonset(p)
        and not podutil.is_owned_by_node(p)
    )


def validate(p: Pod) -> Optional[str]:
    """Supported-feature validation (controller.go:123-174)."""
    errs: List[str] = []
    if p.spec.affinity is not None:
        # required pod-(anti-)affinity is compiled into the columnar filter
        # for ANY topology key (scheduling/affinity.py: hostname gets fresh
        # domains, valued keys draw from the provisioner's vocabulary; a
        # key the provisioner doesn't carry sheds at injection, not here).
        # Preferred terms are soft votes and always pass validation.
        for side, what in ((p.spec.affinity.pod_affinity, "pod affinity"),
                           (p.spec.affinity.pod_anti_affinity,
                            "pod anti-affinity")):
            if side is None:
                continue
            for term in side.required:
                if not term.topology_key:
                    errs.append(f"{what} term without a topology key "
                                "is not supported")
        na = p.spec.affinity.node_affinity
        if na is not None:
            terms = list(na.required or [])
            terms += [t.preference for t in na.preferred]
            for term in terms:
                if term.match_fields:
                    errs.append("node selector term with matchFields is not supported")
                for r in term.match_expressions:
                    if r.operator not in ("In", "NotIn"):
                        errs.append(f"unsupported operator {r.operator}")
    for c in p.spec.topology_spread_constraints:
        if c.topology_key not in (wellknown.LABEL_HOSTNAME, wellknown.LABEL_TOPOLOGY_ZONE):
            errs.append(f"unsupported topology key {c.topology_key}")
    return "; ".join(errs) if errs else None


class Preferences:
    """Iterative preference relaxation with TTL reset (preferences.go:40-106)."""

    # full-cache sweeps are amortized: a sweep per relax() call is O(cache)
    # under the lock, which goes quadratic at the 10k-pending-pod regime
    # (every pod's 5 s requeue rebuilt a 10k-entry dict — measured as a
    # top GIL consumer on a 1-core host). Per-entry TTL stays exact via the
    # timestamp check below; the sweep only reclaims memory.
    SWEEP_INTERVAL_SECONDS = RELAXATION_TTL_SECONDS / 4

    def __init__(self):
        self._cache: Dict[str, Tuple[Optional[Affinity], float]] = {}
        self._lock = threading.Lock()
        self._next_sweep = 0.0

    def relax(self, pod: Pod) -> None:
        now = clock.now()
        uid = pod.metadata.uid or f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._lock:
            if now >= self._next_sweep:
                self._cache = {k: v for k, v in self._cache.items()
                               if now - v[1] < RELAXATION_TTL_SECONDS}
                self._next_sweep = now + self.SWEEP_INTERVAL_SECONDS
            entry = self._cache.get(uid)
            if entry is not None and now - entry[1] >= RELAXATION_TTL_SECONDS:
                entry = None  # expired between sweeps: same TTL semantics
            if entry is None:
                self._cache[uid] = (pod.spec.affinity, now)
                return
            pod.spec.affinity = entry[0]
            if self._relax(pod):
                self._cache[uid] = (pod.spec.affinity, now)

    def _relax(self, pod: Pod) -> bool:
        return (self._remove_preferred_term(pod)
                or self._remove_required_term(pod))

    def _remove_preferred_term(self, pod: Pod) -> bool:
        """Strip the heaviest preferred term (preferences.go:78-92)."""
        a = pod.spec.affinity
        if a is None or a.node_affinity is None or not a.node_affinity.preferred:
            return False
        terms = sorted(a.node_affinity.preferred, key=lambda t: -t.weight)
        a.node_affinity.preferred = terms[1:]
        log.debug("relaxed: removed preferred term weight=%s", terms[0].weight)
        return True

    def _remove_required_term(self, pod: Pod) -> bool:
        """Strip the first required OR-term, never the last
        (preferences.go:94-106)."""
        a = pod.spec.affinity
        if (a is None or a.node_affinity is None or a.node_affinity.required is None
                or len(a.node_affinity.required) <= 1):
            return False
        a.node_affinity.required = a.node_affinity.required[1:]
        log.debug("relaxed: removed required term")
        return True


class VolumeTopology:
    """PVC/PV/StorageClass topology → pod node affinity
    (volumetopology.go:37-128)."""

    def __init__(self, kube: KubeCore):
        self.kube = kube

    def inject(self, pod: Pod) -> None:
        requirements: List[NodeSelectorRequirement] = []
        for volume in pod.spec.volumes:
            requirements.extend(self._get_requirements(pod, volume))
        if not requirements:
            return
        if pod.spec.affinity is None:
            pod.spec.affinity = Affinity()
        if pod.spec.affinity.node_affinity is None:
            pod.spec.affinity.node_affinity = NodeAffinity()
        na = pod.spec.affinity.node_affinity
        if na.required is None:
            na.required = []
        if not na.required:
            na.required.append(NodeSelectorTerm())
        na.required[0].match_expressions.extend(requirements)

    def _get_requirements(self, pod: Pod, volume) -> List[NodeSelectorRequirement]:
        if volume.persistent_volume_claim is None:
            return []
        pvc = self.kube.get("PersistentVolumeClaim",
                            volume.persistent_volume_claim.claim_name,
                            pod.metadata.namespace)
        if pvc.spec.volume_name:
            return self._pv_requirements(pvc)
        if pvc.spec.storage_class_name:
            return self._storage_class_requirements(pvc)
        return []

    def _pv_requirements(self, pvc) -> List[NodeSelectorRequirement]:
        pv = self.kube.get("PersistentVolume", pvc.spec.volume_name, "default")
        if pv.spec.node_affinity is None or pv.spec.node_affinity.required is None:
            return []
        terms = pv.spec.node_affinity.required
        return list(terms[0].match_expressions) if terms else []

    def _storage_class_requirements(self, pvc) -> List[NodeSelectorRequirement]:
        sc = self.kube.get("StorageClass", pvc.spec.storage_class_name, "default")
        if not sc.allowed_topologies:
            return []
        return [
            NodeSelectorRequirement(key=r.key, operator="In", values=list(r.values))
            for r in sc.allowed_topologies[0].match_label_expressions
        ]


class SelectionController:
    """controller.go:59-111.

    Concurrency model: the reference runs 10,000 concurrent reconciles
    (controller.go:181) so every reconciler can BLOCK on the batch gate
    (controller.go:108-111) without throttling intake. Python threads don't
    scale to 10k, so the equivalent here is NON-blocking by default: the
    pod is enqueued to the batcher and the 5-second requeue performs the
    same post-batch re-verification the gate wait enabled (a still-pending
    pod re-enters; the provisioning worker dedupes within a batch and
    re-GETs provisionability, provisioner.go:126-135). With 64 workers a
    blocking gate caps intake at 64 pods per window — three orders below
    the reference's regime; non-blocking restores it. Set ``gate_timeout``
    > 0 to restore the reference's blocking behavior.
    """

    REQUEUE_SECONDS = 5.0  # re-verify scheduling after the batch

    def __init__(self, kube: KubeCore, provisioning_controller,
                 gate_timeout: float = 0.0):
        self.kube = kube
        self.provisioning = provisioning_controller
        self.preferences = Preferences()
        self.volume_topology = VolumeTopology(kube)
        self.gate_timeout = gate_timeout

    def kind(self) -> str:
        return "Pod"

    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        # no-copy provisionability probe first: in the 10k-pod flood most
        # reconciles are bind-MODIFIED events or 5 s re-verify requeues of
        # already-filtered pods, and paying a full deep-copy GET for a
        # one-predicate answer was a top CPU line on a 1-core host
        try:
            if not self.kube.read("Pod", name, namespace, is_provisionable):
                return None
        except NotFound:
            return None
        # already awaiting a batch window? Skip the relax/validate/select
        # repeat — the window's consumption clears the key, so the NEXT
        # requeue performs the full post-batch re-verification this requeue
        # exists for (see the concurrency note in the class docstring)
        key = (namespace, name)
        # list() snapshot: the workers dict is mutated under the provisioning
        # controller's lock; iterating it live can see a resize mid-scan
        if any(w.pending(key)
               for w in list(self.provisioning.workers.values())):
            return self._requeue_seconds(key)
        try:
            pod = self.kube.get("Pod", name, namespace)
        except NotFound:
            return None
        if not is_provisionable(pod):
            return None
        err = validate(pod)
        if err is not None:
            log.debug("ignoring pod %s: %s", name, err)
            return None
        err = self._select_provisioner(pod)
        if err is not None:
            log.debug("could not schedule pod %s: %s", name, err)
        return self._requeue_seconds((namespace, name))

    def _requeue_seconds(self, key=None) -> float:
        """Pressure-aware requeue backoff: at L2+ the shed population's
        5 s retry storm is itself intake load, so back off (the pods are
        Pending either way — a slower retry only delays re-admission, it
        never loses a pod).

        The backoff is jittered per pod (±25%, deterministic in the pod
        key): an L2/L3 mass shed stamps thousands of pods with the SAME
        requeue delay, and without jitter they all re-enter intake on one
        tick — the retry wave itself re-spikes queue depth and re-trips
        the ladder (thundering herd). Hash-based rather than random so a
        given pod's retry cadence is reproducible under seeded chaos."""
        level = int(get_monitor().level())
        if level >= 3:
            base = self.REQUEUE_SECONDS * 4
        elif level >= 2:
            base = self.REQUEUE_SECONDS * 2
        else:
            base = self.REQUEUE_SECONDS
        return base * requeue_jitter(key)

    def _select_provisioner(self, pod: Pod) -> Optional[str]:
        """controller.go:84-111: relax → volume topology → first matching
        provisioner → block on its batch gate."""
        self.preferences.relax(pod)
        try:
            self.volume_topology.inject(pod)
        except NotFound as e:
            return f"getting volume topology requirements: {e}"
        # targets() snapshots every (provisioner, worker) routing pair in
        # deterministic order — in the sharded deployment one worker hosts
        # several provisioners, so routing iterates provisioners, not
        # workers, and hands the chosen provisioner's name to add() so the
        # shard window groups the pod under the right engine
        targets = self.provisioning.targets()
        if not targets:
            return None
        errs = []
        chosen = chosen_worker = None
        for provisioner, worker in targets:
            # columnar: the compiled bitset engine is cached on the
            # long-lived constraints object, so the 10k-reconcile flood pays
            # a memoized signature lookup per (provisioner, pod shape)
            # instead of the full scalar requirement walk per reconcile
            err = feasibility.validate_pod_fast(
                provisioner.spec.constraints, pod)
            if err is None:
                chosen, chosen_worker = provisioner, worker
                break
            errs.append(f"tried provisioner/{provisioner.metadata.name}: {err}")
        if chosen is None:
            return f"matched 0/{len(errs)} provisioners: " + "; ".join(errs)
        gate = chosen_worker.add(
            pod, key=(pod.metadata.namespace, pod.metadata.name),
            provisioner=chosen.metadata.name)
        if gate is None:
            # shed at admission (pressure level or depth bound) — already
            # counted by the batcher; the requeue retries once pressure
            # falls, so a shed is a delay, never a loss. It still burns the
            # band's error budget: a shed pod produces no latency sample,
            # which would otherwise leave the burn sentinel blind to
            # exactly the overload it exists to catch.
            slo.note_shed(classify(pod)[0])
            return (f"shed at intake by provisioner/"
                    f"{chosen.metadata.name} (pressure)")
        if self.gate_timeout > 0:
            gate.wait(timeout=self.gate_timeout)
        return None
