"""Termination controller: finalizer-driven graceful node teardown.

Reference: pkg/controllers/termination/ (design: designs/termination.md).
Deleted node with the karpenter termination finalizer → cordon → drain
(respect do-not-evict; skip unschedulable-tolerating, stuck-terminating and
static pods; evict non-critical before system-critical — the reference's
terminate.go:evict() has its critical/nonCritical variables inverted, we
implement the documented intent) → CloudProvider.Delete → strip finalizer.

The EvictionQueue is a single background worker with exponential backoff
(100 ms → 10 s) and a dedupe set (eviction.go:25-115).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Set, Tuple

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.core import Node, Pod
from karpenter_tpu.cloudprovider.spi import CloudProvider
from karpenter_tpu.runtime.kubecore import (
    Conflict, InternalError, KubeCore, NotFound, TooManyRequests,
)
from karpenter_tpu.utils import clock
from karpenter_tpu.utils import pod as podutil

log = logging.getLogger("karpenter.termination")

EVICTION_BASE_DELAY = 0.1   # eviction.go:31-35
EVICTION_MAX_DELAY = 10.0

SYSTEM_CRITICAL = ("system-cluster-critical", "system-node-critical")


def is_stuck_terminating(pod: Pod) -> bool:
    """terminate.go IsStuckTerminating: deletion grace period elapsed but the
    pod object persists (partitioned kubelet)."""
    if pod.metadata.deletion_timestamp is None:
        return False
    return clock.now() > pod.metadata.deletion_timestamp


class EvictionQueue:
    """Rate-limited eviction worker (eviction.go:39-115). PDB-style
    rejections (the fake layer may raise Conflict) requeue with backoff."""

    def __init__(self, kube: KubeCore):
        self.kube = kube
        self._set: Set[Tuple[str, str]] = set()
        self._failures: dict = {}
        self._cv = threading.Condition()
        self._items: List[Tuple[float, Tuple[str, str]]] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name="eviction-queue",
                                        daemon=True)
        self._thread.start()

    def add(self, pods: List[Pod]) -> None:
        with self._cv:
            for p in pods:
                nn = (p.metadata.namespace, p.metadata.name)
                if nn not in self._set:
                    self._set.add(nn)
                    self._items.append((time.monotonic(), nn))
            self._cv.notify()

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()

    def _run(self) -> None:
        while not self._stop.is_set():
            with self._cv:
                now = time.monotonic()
                ready = [i for i, (t, _) in enumerate(self._items) if t <= now]
                if not ready:
                    delay = min((t - now for t, _ in self._items), default=0.2)
                    self._cv.wait(timeout=max(0.01, min(delay, 0.2)))
                    continue
                t, nn = self._items.pop(ready[0])
            if self._evict(nn):
                with self._cv:
                    self._set.discard(nn)
                    self._failures.pop(nn, None)
            else:
                with self._cv:
                    n = self._failures.get(nn, 0) + 1
                    self._failures[nn] = n
                    backoff = min(EVICTION_BASE_DELAY * (2 ** n), EVICTION_MAX_DELAY)
                    self._items.append((time.monotonic() + backoff, nn))

    def _evict(self, nn: Tuple[str, str]) -> bool:
        """eviction.go:91-110: 404 → done; PDB rejection → retry. The 500
        vs 429 distinction is preserved (eviction.go:94-101): 500 means the
        PDB CONFIGURATION is broken (more than one budget selects the pod)
        — an operator problem worth a distinct message — while 429 means a
        healthy budget is simply holding the line. Both requeue with
        backoff."""
        namespace, name = nn
        try:
            self.kube.evict_pod(name, namespace)
            log.debug("evicted pod %s/%s", namespace, name)
            return True
        except NotFound:
            return True
        except InternalError:  # 500: PDB misconfiguration
            log.debug("failed to evict %s/%s due to PDB misconfiguration "
                      "(multiple budgets select it)", namespace, name)
            return False
        except TooManyRequests:  # 429: PDB violation
            log.debug("failed to evict %s/%s due to PDB violation",
                      namespace, name)
            return False
        except Conflict:  # fake layers may still signal PDB via Conflict
            log.debug("eviction of %s/%s rejected (PDB)", namespace, name)
            return False
        except Exception:
            log.exception("evicting %s/%s", namespace, name)
            return False


class Terminator:
    """terminate.go."""

    def __init__(self, kube: KubeCore, cloud_provider: CloudProvider,
                 eviction_queue: Optional[EvictionQueue] = None,
                 journal=None):
        self.kube = kube
        self.cloud_provider = cloud_provider
        self.journal = journal
        self.eviction_queue = eviction_queue or EvictionQueue(kube)

    def cordon(self, node: Node) -> None:
        if node.spec.unschedulable:
            return
        def apply(live: Node):
            live.spec.unschedulable = True
        self.kube.patch("Node", node.metadata.name, node.metadata.namespace, apply)
        log.info("cordoned node %s", node.metadata.name)

    def drain(self, node: Node) -> bool:
        """Returns True when fully drained (terminate.go drain)."""
        pods = self.kube.pods_on_node(node.metadata.name)
        for p in pods:
            if p.metadata.annotations.get(wellknown.DO_NOT_EVICT_ANNOTATION) == "true":
                log.debug("unable to drain %s: pod %s has do-not-evict",
                          node.metadata.name, p.metadata.name)
                return False
        evictable = self._get_evictable_pods(pods)
        if not evictable:
            return True
        self._evict(evictable)
        return False

    def terminate(self, node: Node) -> None:
        """CloudProvider.Delete then strip the finalizer (terminate.go).
        Journaled as a ``node-delete`` intent: a crash between the
        instance delete and the finalizer strip leaves a Node object whose
        instance is gone — recovery re-drives exactly this method."""
        journal = self.journal
        iid = None
        if journal is not None:
            iid = journal.open_intent(
                "node-delete", node=node.metadata.name,
                provider_id=node.spec.provider_id)
        err = self.cloud_provider.delete(node)
        if err is not None:
            if iid is not None:
                journal.close(iid, outcome="error")
            raise RuntimeError(f"terminating cloudprovider instance: {err}")
        if iid is not None:
            journal.advance(iid, "instance-deleted")
        def apply(live: Node):
            live.metadata.finalizers = [
                f for f in live.metadata.finalizers
                if f != wellknown.TERMINATION_FINALIZER]
        try:
            self.kube.patch("Node", node.metadata.name, node.metadata.namespace, apply)
        except NotFound:
            if iid is not None:
                journal.close(iid)
            self._release_carves(node.metadata.name)
            return
        if iid is not None:
            journal.close(iid)
        self._release_carves(node.metadata.name)
        log.info("deleted node %s", node.metadata.name)

    def _release_carves(self, name: str) -> None:
        """A terminated node's occupancy-ledger carves die with it —
        otherwise the next gang window would keep offering the dead
        node's residual grid as a seed bin. Folding the durable carve
        intents here also lets journal compaction drop the records."""
        from karpenter_tpu.ops import topology as topo_ops
        for rec in topo_ops.LEDGER.pop_node(name):
            if self.journal is not None and rec.intent_id:
                self.journal.close(rec.intent_id, outcome="node-terminated")

    def _get_evictable_pods(self, pods: List[Pod]) -> List[Pod]:
        evictable = []
        for p in pods:
            if podutil.tolerates_unschedulable_taint(p):
                continue  # will reschedule onto the cordoned node anyway
            if is_stuck_terminating(p):
                continue
            if podutil.is_owned_by_node(p):
                continue  # static mirror pods
            evictable.append(p)
        return evictable

    def _evict(self, pods: List[Pod]) -> None:
        """Non-critical first; critical only once non-critical are gone."""
        pending = [p for p in pods if p.metadata.deletion_timestamp is None]
        non_critical = [p for p in pending
                        if p.spec.priority_class_name not in SYSTEM_CRITICAL]
        critical = [p for p in pending
                    if p.spec.priority_class_name in SYSTEM_CRITICAL]
        if non_critical:
            self.eviction_queue.add(non_critical)
        else:
            self.eviction_queue.add(critical)


class TerminationController:
    """controller.go:62-98."""

    def __init__(self, kube: KubeCore, cloud_provider: CloudProvider,
                 journal=None):
        self.kube = kube
        self.terminator = Terminator(kube, cloud_provider, journal=journal)

    def kind(self) -> str:
        return "Node"

    def reconcile(self, name: str, namespace: str = "") -> Optional[float]:
        try:
            node = self.kube.get("Node", name, namespace)
        except NotFound:
            return None
        if (node.metadata.deletion_timestamp is None
                or wellknown.TERMINATION_FINALIZER not in node.metadata.finalizers):
            return None
        self.terminator.cordon(node)
        if not self.terminator.drain(node):
            return 1.0  # requeue until drained
        self.terminator.terminate(node)
        return None

    def stop_all(self) -> None:
        self.terminator.eviction_queue.stop()
