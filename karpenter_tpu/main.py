"""Controller-plane entrypoint.

Reference: cmd/controller/main.go — builds the cloud provider via the
registry, wires the eight controllers into the manager, and serves
/metrics, /healthz and /readyz. Run as ``python -m karpenter_tpu.main``.
"""

from __future__ import annotations

import logging
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from karpenter_tpu.cloudprovider import spi
from karpenter_tpu.cloudprovider.fake import provider as _fake  # noqa: F401 — registers "fake"
from karpenter_tpu.config.options import Options, parse
from karpenter_tpu.controllers.consolidation import ConsolidationController
from karpenter_tpu.controllers.counter import CounterController
from karpenter_tpu.controllers.gc import GarbageCollection
from karpenter_tpu.controllers.logging_config import LoggingConfigController
from karpenter_tpu.controllers.metrics_controllers import (
    NodeMetricsController, PodMetricsController,
)
from karpenter_tpu.controllers.node import NodeController
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.pvc import PVCController
from karpenter_tpu.controllers.selection import SelectionController
from karpenter_tpu.controllers.termination import TerminationController
from karpenter_tpu import pressure
from karpenter_tpu.metrics import registry
from karpenter_tpu.runtime.kubecore import KubeCore
from karpenter_tpu.runtime.manager import Manager
from karpenter_tpu.scheduling.batcher import Batcher
from karpenter_tpu.solver.solve import SolverConfig
from karpenter_tpu.utils.workers import adaptive_workers

log = logging.getLogger("karpenter")


def build_cloud_provider(options: Options):
    """Resolve the provider from the registry and wrap it in the metrics
    decorator so all SPI calls emit cloudprovider_duration_seconds — the
    reference installs this unconditionally (cmd/controller/main.go:76-77,
    metrics/cloudprovider.go:65-92). The AWS provider needs its SDK clients
    constructed first."""
    from karpenter_tpu.cloudprovider.metrics import decorate

    if options.cloud_provider == "aws":
        import karpenter_tpu.cloudprovider.aws  # noqa: F401 — registers "aws"
        from karpenter_tpu.cloudprovider.aws import sdk as aws_sdk

        ec2api, ssmapi = aws_sdk.default_clients()
        return decorate(spi.resolve(
            "aws", ec2api=ec2api, ssmapi=ssmapi,
            cluster_name=options.cluster_name,
            cluster_endpoint=options.cluster_endpoint,
            eni_limited_pod_density=options.aws_eni_limited_pod_density,
            node_name_convention=options.aws_node_name_convention))
    return decorate(spi.resolve(options.cloud_provider))


def build_manager(kube: KubeCore, options: Options) -> Manager:
    """Register the controllers: the reference's eight
    (cmd/controller/main.go:89-98) plus consolidation."""
    cloud_provider = build_cloud_provider(options)
    # brownout ladder: install the process-wide pressure monitor before any
    # batcher exists so every admission decision sees the configured ladder
    pressure.configure(pressure.PressureConfig(
        enabled=options.pressure_enabled,
        max_depth=options.pressure_max_depth,
        rss_watermark_bytes=options.pressure_rss_watermark_mb * 1024 ** 2,
        dwell_seconds=options.pressure_dwell_seconds,
        split_items=options.pressure_split_items,
        aging_step_seconds=options.pressure_aging_seconds))
    # pipelined hot loop (solver/pipeline.py): chunk N solves on device
    # while chunk N-1 binds and chunk N+1 marshals; compile warmup +
    # persistent cache keep the first window off the 20-40 s cold compile
    from karpenter_tpu.solver import warmup as solver_warmup
    from karpenter_tpu.solver.pipeline import PipelineConfig

    solver_warmup.configure_compilation_cache(options.solver_compile_cache_dir)
    from karpenter_tpu.solver.policy import PolicyContext
    solver_config = SolverConfig(use_device=options.solver_use_device,
                                 device_donate=options.solver_donate,
                                 packing_policy=options.packing_policy,
                                 window_backend=options.window_backend,
                                 policy_context=PolicyContext(
                                     repack_cost_per_hour=options.policy_repack_cost))
    if options.solver_warmup:
        solver_warmup.start_warmup(solver_config,
                                   include_ring=options.solver_donate)
    # crash consistency (docs/robustness.md §5): the write-ahead intent
    # journal + startup recovery are built before any controller so every
    # multi-step mutation is journaled from the first window; main() runs
    # recovery.run() before manager.start() and readyz answers 503
    # "recovering" until the replay completes
    journal = None
    recovery = None
    if options.journal_dir:
        from karpenter_tpu.controllers.recovery import RecoveryController
        from karpenter_tpu.runtime.journal import IntentJournal

        journal = IntentJournal(options.journal_dir,
                                fsync=options.journal_fsync)
        recovery = RecoveryController(kube, cloud_provider, journal)
    provisioning = ProvisioningController(
        kube, cloud_provider,
        journal=journal,
        solver_config=solver_config,
        pipeline_config=PipelineConfig(
            depth=options.pipeline_depth,
            chunk_items=options.pipeline_chunk_items,
            adaptive=options.pipeline_adaptive),
        batcher_factory=lambda: Batcher(
            idle_seconds=options.batch_idle_seconds,
            max_seconds=options.batch_max_seconds,
            max_items=options.batch_max_items,
            max_depth=options.pressure_max_depth),
        # horizontal shards (docs/scale.md §1): N long-lived intake/solve
        # workers with provisioners hashed across them; 0 keeps the
        # reference's one-worker-per-Provisioner shape
        shards=options.provisioning_shards)
    manager = Manager(kube)
    manager.register(provisioning)
    # worker pools are clamped to the host's cores (utils/workers.py): the
    # reference's 10k-concurrent-goroutine regime maps to a few GIL-bound
    # threads per core here, not a thread per in-flight reconcile
    manager.register(SelectionController(kube, provisioning),
                     workers=adaptive_workers(64))
    manager.register(NodeController(kube), workers=adaptive_workers(10))
    manager.register(TerminationController(kube, cloud_provider,
                                           journal=journal),
                     workers=adaptive_workers(10))
    manager.register(CounterController(kube))
    if options.gc_interval_seconds > 0:
        manager.register(GarbageCollection(
            kube, cloud_provider,
            interval_seconds=options.gc_interval_seconds,
            grace_seconds=options.gc_grace_seconds,
            journal=journal))
    manager.register(ConsolidationController(
        kube, provider=cloud_provider,
        journal=journal,
        # spot keep-cost premium (models/consolidate.fleet_prices): only the
        # interruption-priced policy charges reclaim risk into the ranking
        repack_cost_per_hour=(
            options.policy_repack_cost
            if options.packing_policy == "interruption-priced" else 0.0)))
    manager.register(PVCController(kube))
    manager.register(NodeMetricsController(kube))
    manager.register(PodMetricsController(kube))
    # live log-level reload from config-logging (cmd/controller/main.go:105-117);
    # watch the controller's own namespace (POD_NAMESPACE / --namespace), not
    # a hardcoded one — the deployed map lives in "karpenter"
    manager.register(LoggingConfigController(kube, namespace=options.namespace))
    # attached (not positional) so build_manager's signature stays stable
    # for every existing caller; main() getattr's them back
    manager.journal = journal
    manager.recovery = recovery
    return manager


def debug_vars() -> dict:
    """The /debug/vars payload: one JSON snapshot of every internal ledger
    an operator would otherwise need a debugger for — metric series (with
    histogram exemplar trace ids), pressure signals, solver breaker state,
    device-ring counters, tracer and flight-recorder state."""
    import json  # noqa: F401 — callers json.dumps this; keep deps obvious

    from karpenter_tpu.obs import flight, slo, trace
    from karpenter_tpu.solver import pipeline as _pipeline
    from karpenter_tpu.solver.solve import solver_health

    ring = _pipeline._RING  # peek: never allocate device memory from a GET
    return {
        "metrics": registry.DEFAULT.snapshot(),
        "pressure": pressure.get_monitor().signals(),
        "solver": solver_health(),
        "ring": ring.counters() if ring is not None else None,
        "trace": trace.state(),
        "flight": flight.state(),
        "slo": slo.state(),
    }


class _Handler(BaseHTTPRequestHandler):
    manager: Optional[Manager] = None
    recovery = None  # RecoveryController when --journal-dir is set

    def do_GET(self):
        if self.path == "/metrics":
            body = registry.DEFAULT.expose().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
        elif self.path == "/debug/vars":
            import json

            body = json.dumps(debug_vars(), indent=2, default=str).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif self.path in ("/healthz", "/readyz"):
            ok = self.manager is None or self.manager.healthz()
            level = int(pressure.get_monitor().level())
            suffix = ""
            if self.path == "/readyz":
                if self.recovery is not None and self.recovery.recovering():
                    # journal replay in progress: open intents from the
                    # previous process are still being rolled forward or
                    # back — serving windows now could double-act on them
                    ok = False
                    suffix = " recovering"
                if level >= 3:
                    # L3 = system-critical only: stop advertising readiness
                    # so load balancers drain non-critical traffic off this
                    # replica (liveness stays green — a restart would only
                    # make it worse)
                    ok = False
                from karpenter_tpu.obs import slo

                burning = slo.burning()
                if burning:
                    # sustained SLO burn degrades readiness the same way:
                    # the replica is falling behind its latency objectives
                    # even if the pressure ladder hasn't caught up yet
                    ok = False
                    suffix += f" slo-burn={','.join(burning)}"
            body = (f"{'ok' if ok else 'unhealthy'} "
                    f"level=L{level}{suffix}").encode()
            self.send_response(200 if ok else 503)
            self.send_header("Content-Type", "text/plain")
        else:
            body = b"not found"
            self.send_response(404)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # quiet
        pass


def serve_observability(manager: Manager, port: int) -> ThreadingHTTPServer:
    handler = type("Handler", (_Handler,),
                   {"manager": manager,
                    "recovery": getattr(manager, "recovery", None)})
    server = ThreadingHTTPServer(("0.0.0.0", port), handler)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="observability").start()
    return server


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s")
    options = parse(argv)
    errs = options.validate()
    if errs:
        for e in errs:
            log.error("invalid options: %s", e)
        return 1
    if options.kube_backend == "in-cluster":
        from karpenter_tpu.runtime.kubeclient import KubeApiClient

        kube = KubeApiClient.in_cluster(qps=options.kube_client_qps,
                                        burst=options.kube_client_burst)
    else:
        kube = KubeCore()
    # observability wiring before any controller runs: the tracer and
    # flight recorder must see the first window (docs/observability.md)
    from karpenter_tpu.obs import flight, slo, trace

    if options.trace_enabled:
        trace.enable(jax_annotations=options.trace_jax)
    if options.flight_dir:
        flight.configure(dir=options.flight_dir)
    objectives = None
    if options.slo_objectives:
        objectives = {
            band: slo.Objective(threshold_s=t, target=tgt)
            for band, (t, tgt) in options.parse_slo_objectives().items()}
    slo.configure(enabled=options.slo_enabled,
                  objectives=objectives,
                  fast_window_s=options.slo_fast_window_seconds,
                  slow_window_s=options.slo_slow_window_seconds,
                  fast_burn=options.slo_fast_burn,
                  slow_burn=options.slo_slow_burn)
    manager = build_manager(kube, options)
    server = serve_observability(manager, options.metrics_port)
    # opt-in XLA device tracing (KARPENTER_PROFILE_PORT, SURVEY.md §5.1);
    # a debug knob must never crash-loop the controller
    from karpenter_tpu.utils.profiling import start_server as start_profiler

    try:
        start_profiler()
    except Exception as e:  # noqa: BLE001
        log.warning("profiler server not started: %s", e)

    elector = None
    stopping = threading.Event()
    terminated = threading.Event()
    # Kubernetes stops pods with SIGTERM; without a handler the process dies
    # before elector.stop() releases the Lease, stranding it for the full
    # lease duration on every rollout
    import signal

    def _on_sigterm(signum, frame):
        terminated.set()
        stopping.set()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:  # not the main thread (tests) — skip
        pass
    if options.leader_elect:
        # single-writer guard (cmd/controller/main.go:80-81): campaign
        # before starting controllers; losing the lease means exit — the
        # orchestrator restarts the replica, which re-campaigns
        import socket
        import uuid

        from karpenter_tpu.runtime.leaderelection import LeaderElector

        elector = LeaderElector(
            kube, identity=f"{socket.gethostname()}-{uuid.uuid4().hex[:6]}",
            namespace=options.namespace,
            on_stopped_leading=stopping.set)
        elector.start()
        log.info("campaigning for leadership")
        # interrupt=stopping: a SIGTERM while standing by must break the
        # campaign wait, not park until kubelet SIGKILLs the replica
        elector.wait_for_leadership(interrupt=stopping)
    try:
        if not stopping.is_set():
            # replay the intent journal BEFORE any controller runs: open
            # intents from a crashed predecessor are rolled forward or
            # back against live state while readyz answers 503 recovering
            recovery = getattr(manager, "recovery", None)
            if recovery is not None:
                stats = recovery.run()
                log.info("journal recovery: %s", stats)
            manager.start()
            log.info("karpenter-tpu started (cluster=%s, metrics=:%d)",
                     options.cluster_name, options.metrics_port)
            stopping.wait()
    except KeyboardInterrupt:
        pass
    finally:
        manager.stop()
        if elector is not None:
            elector.stop()
        server.shutdown()
        if options.trace_dump:
            try:
                trace.dump_chrome(options.trace_dump)
                log.info("trace dump written to %s", options.trace_dump)
            except Exception as e:  # noqa: BLE001 — debug knob, never fatal
                log.warning("trace dump failed: %s", e)
    # SIGTERM (rollout) is a clean exit; stopping WITHOUT a signal means
    # lost leadership → nonzero so the orchestrator restarts this replica
    # and it re-campaigns
    return 1 if stopping.is_set() and not terminated.is_set() else 0


if __name__ == "__main__":
    sys.exit(main())
