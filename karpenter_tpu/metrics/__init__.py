from karpenter_tpu.metrics import core  # noqa: F401  (attaches help text)
