"""Metrics for the batched what-if consolidation engine.

Per-window series on the process registry (``karpenter_`` prefix via
registry.expose()):

- ``karpenter_consolidation_window_candidates``        gauge — candidates
  that entered the last what-if batch (post-filter window size)
- ``karpenter_consolidation_candidates_evaluated_total`` counter — drains
  evaluated by the batched solve, cumulative (one window adds N at once —
  the "evaluations per reconcile" the engine exists to multiply)
- ``karpenter_consolidation_candidates_filtered_total``  counter,
  ``reason`` label — candidates excluded BEFORE the batch:
  ``do-not-evict`` (an annotated pod pins the node), ``pdb`` (draining
  would breach a PodDisruptionBudget's headroom, or the pod's PDBs are
  misconfigured — >1 match / both fields set — which eviction would 500)
- ``karpenter_consolidation_drains_executed_total``    counter — node
  deletions the engine actually issued (every one host-verified)
- ``karpenter_consolidation_reclaimed_dollars_total``  counter — $/h
  reclaimed, summed over executed drains (0-priced when the catalog
  can't price the node)
- ``karpenter_consolidation_window_reclaimed_per_hour`` gauge — $/h
  reclaimed by the LAST window's plan
- ``karpenter_consolidation_whatif_solve_seconds``     histogram —
  dispatch+fetch wall time of the batched what-if solve
- ``karpenter_consolidation_relax_used_total``         counter — repacks
  where the relaxation's rounded plan beat exact FFD and was used
- ``karpenter_consolidation_relax_fallback_total``     counter,
  ``reason`` label — relaxation attempts that fell back to the exact FFD
  plan (``infeasible``, ``costlier``, ``unpriced``, ``unencodable``,
  ``no-support``, ``jax-error``, ...): the zero-unverified-drains
  contract made visible
- ``karpenter_consolidation_unknown_instance_type_total`` counter — nodes
  whose instance-type label is absent from the current catalog (priced at
  $0 and still consolidatable; logged once per window, not per node)
"""

from __future__ import annotations

from karpenter_tpu.metrics.registry import DEFAULT

CONSOLIDATION_WINDOW_CANDIDATES = DEFAULT.gauge(
    "consolidation_window_candidates",
    "Candidate drains in the last batched what-if window (post-filter)")

CONSOLIDATION_CANDIDATES_TOTAL = DEFAULT.counter(
    "consolidation_candidates_evaluated_total",
    "Candidate drains evaluated by the batched what-if solve, cumulative")

CONSOLIDATION_FILTERED_TOTAL = DEFAULT.counter(
    "consolidation_candidates_filtered_total",
    "Candidates excluded before the what-if batch, by reason "
    "(do-not-evict | pdb)")

CONSOLIDATION_DRAINS_TOTAL = DEFAULT.counter(
    "consolidation_drains_executed_total",
    "Node drains executed by the consolidation engine (host-verified)")

CONSOLIDATION_RECLAIMED_TOTAL = DEFAULT.counter(
    "consolidation_reclaimed_dollars_total",
    "Cumulative $/h reclaimed by executed drains")

CONSOLIDATION_WINDOW_RECLAIMED = DEFAULT.gauge(
    "consolidation_window_reclaimed_per_hour",
    "$/h reclaimed by the last consolidation window's plan")

CONSOLIDATION_SOLVE_SECONDS = DEFAULT.histogram(
    "consolidation_whatif_solve_seconds",
    "Wall seconds of the batched what-if solve (dispatch + fetch)")

CONSOLIDATION_RELAX_USED = DEFAULT.counter(
    "consolidation_relax_used_total",
    "Global repacks where the relaxation's rounded plan was used")

CONSOLIDATION_RELAX_FALLBACKS = DEFAULT.counter(
    "consolidation_relax_fallback_total",
    "Relaxation attempts that fell back to the exact FFD plan, by reason")

CONSOLIDATION_UNKNOWN_TYPE_TOTAL = DEFAULT.counter(
    "consolidation_unknown_instance_type_total",
    "Nodes whose instance-type label is absent from the catalog "
    "(priced $0, still consolidatable; logged once per window)")
