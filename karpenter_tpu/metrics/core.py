"""Help text for metrics whose call sites create them lazily.

Several hot paths create series through ``HISTOGRAMS.time(name, ...)``
or ``registry.gauge(name)`` with no help string (the reference's
constants.go carried the help separately). The registry attaches help
order-independently (`Registry._get_or_create` upgrades an empty help),
so pre-registering here — imported via ``karpenter_tpu.metrics`` — is
enough for ``expose()`` to render ``# HELP`` for every series and for
``tools/metrics_lint.py`` to pass.

Any NEW lazily-created metric must be added here (and to the docs table
in docs/observability.md) or metrics-lint fails the build.
"""

from __future__ import annotations

from karpenter_tpu.metrics.registry import DEFAULT, Registry

GAUGE_HELP = {
    "nodes_allocatable": "Node allocatable capacity by resource type.",
    "nodes_total_pod_requests":
        "Sum of resource requests of non-daemon pods on the node.",
    "nodes_total_pod_limits":
        "Sum of resource limits of non-daemon pods on the node.",
    "nodes_total_daemon_requests":
        "Sum of resource requests of daemonset pods on the node.",
    "nodes_total_daemon_limits":
        "Sum of resource limits of daemonset pods on the node.",
    "nodes_system_overhead":
        "Node capacity minus allocatable (system/kubelet reservation).",
    "pods_state":
        "One series per known pod with its placement labels and phase.",
}

HISTOGRAM_HELP = {
    "scheduling_duration_seconds":
        "Wall time of one scheduler feasibility pass per provisioner.",
    "binpacking_duration_seconds":
        "Wall time of the bin-packing solve per provisioner.",
    "bind_duration_seconds":
        "Wall time from node create to all chunk pods bound.",
    "cloudprovider_duration_seconds":
        "Latency of cloud-provider API methods by method/provider.",
}


def register(reg: Registry = DEFAULT) -> None:
    for name, help_ in GAUGE_HELP.items():
        reg.gauge(name, help_)
    for name, help_ in HISTOGRAM_HELP.items():
        reg.histogram(name, help_)


register()
