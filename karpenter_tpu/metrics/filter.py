"""Metrics for the columnar constraint filter (ops/feasibility.py).

Three series, all on the process-wide registry (exposed with the
``karpenter_`` prefix by registry.expose()):

- ``karpenter_filter_batch_seconds``   histogram, ``stage`` label
  ("schedule" = one scheduler window, "catalog" = one catalog mask build)
- ``karpenter_filter_fallback_total``  counter, ``reason`` label — every
  time the engine hands a decision back to the scalar path
- ``karpenter_filter_intern_table_size`` gauge — live values in the
  global key→value intern table (drops to 0 on a generation reset)
"""

from __future__ import annotations

from karpenter_tpu.metrics.registry import DEFAULT

FILTER_BATCH_SECONDS = DEFAULT.histogram(
    "filter_batch_seconds",
    "Columnar feasibility filter time per batch (stage=schedule|catalog)")
FILTER_FALLBACK_TOTAL = DEFAULT.counter(
    "filter_fallback_total",
    "Scalar-path fallbacks taken by the feasibility engine, by reason")
FILTER_INTERN_TABLE_SIZE = DEFAULT.gauge(
    "filter_intern_table_size",
    "Interned label values held by the feasibility engine's vocab table")
