"""Metrics for the columnar constraint filter (ops/feasibility.py).

Three series, all on the process-wide registry (exposed with the
``karpenter_`` prefix by registry.expose()):

- ``karpenter_filter_batch_seconds``   histogram, ``stage`` label
  ("schedule" = one scheduler window, "catalog" = one catalog mask build)
- ``karpenter_filter_fallback_total``  counter, ``reason`` label — every
  time the engine hands a decision back to the scalar path
- ``karpenter_filter_intern_table_size`` gauge — live values in the
  global key→value intern table (drops to 0 on a generation reset)
"""

from __future__ import annotations

from karpenter_tpu.metrics.registry import DEFAULT

FILTER_BATCH_SECONDS = DEFAULT.histogram(
    "filter_batch_seconds",
    "Columnar feasibility filter time per batch (stage=schedule|catalog)")
FILTER_FALLBACK_TOTAL = DEFAULT.counter(
    "filter_fallback_total",
    "Scalar-path fallbacks taken by the feasibility engine, by reason")
FILTER_INTERN_TABLE_SIZE = DEFAULT.gauge(
    "filter_intern_table_size",
    "Interned label values held by the feasibility engine's vocab table")

# -- device-resident fused filter (ops/device_filter.py, round 12) ----------
FILTER_DEVICE_SECONDS = DEFAULT.histogram(
    "filter_device_seconds",
    "Device-resident fused feasibility filter time "
    "(stage=dispatch|verify|gang)")
FILTER_DEVICE_FALLBACK_TOTAL = DEFAULT.counter(
    "filter_device_fallback_total",
    "Device-filter retreats to the host columnar / scalar path, by reason")
FILTER_PLANE_RING_REUSES_TOTAL = DEFAULT.counter(
    "filter_plane_ring_reuses_total",
    "Catalog bit-plane ring fills skipped because the slot already held "
    "this catalog's planes (content-token match: zero transfer)")
