"""Metrics for gang (all-or-nothing pod group) scheduling.

Per-window series on the process registry (``karpenter_`` prefix via
registry.expose()):

- ``karpenter_gang_windows_total``       counter — gang co-pack windows
  solved (one batched device/host solve per window)
- ``karpenter_gangs_placed_total``       counter — gangs whose members ALL
  bound (atomic bind committed; the only success state a gang has)
- ``karpenter_gangs_unplaceable_total``  counter, ``reason`` label — gangs
  that did not place: ``expired`` (partial group aged past the batcher
  hold TTL and was shed back to the band-aware requeue), ``oversize``
  (declared size exceeds the window item cap), ``infeasible`` (no
  offering passes the group feasibility column / device filter),
  ``capacity`` (host re-verification found earlier gangs consumed the
  window's pool), ``no-type`` (encode found no instance type that can
  host the members), ``bind-failed`` (mid-bind failure; members unwound
  through the termination finalizer and requeued)
- ``karpenter_gang_hold_seconds``        histogram — how long a gang waited
  in the batcher between its first member arriving and the window that
  carried the complete group
"""

from __future__ import annotations

from karpenter_tpu.metrics.registry import DEFAULT

GANG_WINDOWS_TOTAL = DEFAULT.counter(
    "gang_windows_total",
    "Gang co-pack windows solved (one batched solve per window)")

GANGS_PLACED_TOTAL = DEFAULT.counter(
    "gangs_placed_total",
    "Gangs whose members all bound atomically")

GANGS_UNPLACEABLE_TOTAL = DEFAULT.counter(
    "gangs_unplaceable_total",
    "Gangs that did not place, by reason (expired | oversize | infeasible "
    "| capacity | no-type | bind-failed)")

GANG_HOLD_SECONDS = DEFAULT.histogram(
    "gang_hold_seconds",
    "Batcher hold time from a gang's first member to its complete window")
