"""Metrics for the whole-window global-solve backend.

Per-window series on the process registry (``karpenter_`` prefix via
registry.expose()):

- ``karpenter_global_windows_total``   counter — provisioning windows
  dispatched through the global (ADMM relaxation) backend
- ``karpenter_global_used_total``      counter — schedules whose rounded
  relaxation plan was strictly cheaper, fully feasible, host-verified,
  and USED in place of the FFD backend's plan
- ``karpenter_global_fallback_total``  counter, ``reason`` label —
  schedules that kept the FFD backend's plan bit-for-bit (``empty``,
  ``unpriced``, ``unencodable``, ``no-support``, ``infeasible``,
  ``costlier``, ``unverified``, ``error``, ``window-cap``): the
  zero-unverified-placements contract made visible
- ``karpenter_global_widened_accept_total`` counter — no-support
  schedules recovered by the single widened-support rounding retry
  (accepted through the same exact cheaper/verify gates)
- ``karpenter_global_iterations``      gauge — projected-gradient
  iterations configured for the last dispatched window
- ``karpenter_global_support_threshold`` gauge — the adaptive absolute
  support threshold currently in force (EWMA acceptance-rate driven,
  between the widened 0.05 floor and the strict 0.4 ceiling)
- ``karpenter_global_solve_seconds``   histogram — dispatch+fetch wall
  seconds of the batched global solve (rounding + verification included)
"""

from __future__ import annotations

from karpenter_tpu.metrics.registry import DEFAULT

GLOBAL_WINDOWS_TOTAL = DEFAULT.counter(
    "global_windows_total",
    "Provisioning windows dispatched through the global (ADMM relaxation) "
    "backend")

GLOBAL_USED_TOTAL = DEFAULT.counter(
    "global_used_total",
    "Schedules whose rounded relaxation plan was strictly cheaper, "
    "host-verified, and used in place of the FFD plan")

GLOBAL_FALLBACK_TOTAL = DEFAULT.counter(
    "global_fallback_total",
    "Schedules that kept the FFD backend's plan bit-for-bit, by reason")

GLOBAL_WIDENED_ACCEPT_TOTAL = DEFAULT.counter(
    "global_widened_accept_total",
    "No-support schedules recovered by the widened-support rounding retry")

GLOBAL_SUPPORT_THRESHOLD = DEFAULT.gauge(
    "global_support_threshold",
    "Adaptive absolute support threshold in force (EWMA acceptance-rate "
    "interpolation between the widened floor and the strict ceiling)")

GLOBAL_ITERATIONS = DEFAULT.gauge(
    "global_iterations",
    "Projected-gradient iterations configured for the last global window")

GLOBAL_SOLVE_SECONDS = DEFAULT.histogram(
    "global_solve_seconds",
    "Wall seconds of the batched global solve (dispatch + fetch, "
    "rounding and host verification included)")
