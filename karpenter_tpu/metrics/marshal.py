"""Metrics for the incremental window encoding (ops/encode.py arena +
solver/adapter.py marshal).

Five series, all on the process-wide registry (exposed with the
``karpenter_`` prefix by registry.expose()):

- ``karpenter_marshal_row_cache_hits_total``      counter — window pods
  served straight from the delta-marshal arena (a cached row gather; no
  Python encode)
- ``karpenter_marshal_row_cache_misses_total``    counter — window pods
  that paid the Python marshal + arena row assignment (new or churned
  signatures)
- ``karpenter_marshal_row_cache_evictions_total`` counter — arena rows
  invalidated by a generation reset (intern-table rebind, vocab rebind,
  or capacity rollover)
- ``karpenter_marshal_delta_fraction``            gauge — miss fraction of
  the most recent marshal window (0 = fully incremental steady state,
  1 = cold rebuild)
- ``karpenter_catalog_encoding_rebuilds_total``   counter — catalog device
  tensor (totals/reserved0/valid) rebuilds; flat while the (catalog token,
  constraints fingerprint, scales) key repeats window after window
"""

from __future__ import annotations

from karpenter_tpu.metrics.registry import DEFAULT

MARSHAL_ROW_CACHE_HITS_TOTAL = DEFAULT.counter(
    "marshal_row_cache_hits_total",
    "Window pods served from the delta-marshal row arena without a "
    "Python encode")
MARSHAL_ROW_CACHE_MISSES_TOTAL = DEFAULT.counter(
    "marshal_row_cache_misses_total",
    "Window pods that paid the Python marshal and an arena row "
    "assignment (new or churned signatures)")
MARSHAL_ROW_CACHE_EVICTIONS_TOTAL = DEFAULT.counter(
    "marshal_row_cache_evictions_total",
    "Arena rows invalidated by a generation reset (intern rebind, "
    "vocab rebind, capacity rollover)")
MARSHAL_DELTA_FRACTION = DEFAULT.gauge(
    "marshal_delta_fraction",
    "Miss fraction of the most recent marshal window "
    "(0=fully incremental, 1=cold rebuild)")
CATALOG_ENCODING_REBUILDS_TOTAL = DEFAULT.counter(
    "catalog_encoding_rebuilds_total",
    "Catalog device tensor rebuilds by the encoding cache; flat while "
    "the (catalog token, constraints fingerprint, scales) key repeats")
