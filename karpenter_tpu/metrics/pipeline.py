"""Metrics for the pipelined provisioning hot loop (solver/pipeline.py).

Four series, all on the process-wide registry (exposed with the
``karpenter_`` prefix by registry.expose()):

- ``karpenter_pipeline_depth``                 gauge — effective pipeline
  depth of the most recent provisioning window (configured depth at L0;
  collapses to 1 at pressure L1+, so a sustained 1 here under a depth-2
  config is the ladder speaking, not a bug)
- ``karpenter_pipeline_stage_seconds``         histogram, ``stage`` label —
  per-chunk wall time by stage: ``marshal`` (schedule + problem build +
  encode + async dispatch), ``device`` (blocking fetch/materialize of the
  in-flight batch), ``launch_bind`` (cloud create + node object + binds)
- ``karpenter_solver_overlap_seconds_total``   counter — cumulative seconds
  each dispatched batch spent in flight before its fetch began, i.e. device
  time hidden behind host launch/bind + marshal work. This is an upper
  bound on wall time saved versus the serial sum (the device may finish
  early inside the span); in serial mode (depth 1) it is ~0 by construction
  because every fetch immediately follows its dispatch.
- ``karpenter_pipeline_dispatch_wait_seconds`` histogram — per-chunk wait
  between dispatch completing and the fetch starting (queueing delay a
  handle experiences inside the pipeline's bounded window)

Round-8 additions (device ring + adaptive depth):

- ``karpenter_solver_device_bytes_in_use``     gauge — live device memory
  summed over the mesh, from ``device.memory_stats()`` where the backend
  implements it, else the client's live-buffer sizes (parallel/mesh.py
  device_bytes_in_use). Best-effort: 0 where neither source exists.
- ``karpenter_pipeline_ring_allocations_total`` counter — fresh device
  buffer allocations made by the ring (slot creation, bucket change,
  compaction re-bucket). FLAT in steady state — the zero-allocation
  acceptance gate reads this.
- ``karpenter_pipeline_ring_refills_total``    counter — in-place
  donation-aliased refills of existing ring buffers (the steady-state
  path: same device memory, new chunk data).

Round-10 addition (persistent device catalog):

- ``karpenter_pipeline_ring_reuses_total``     counter — fills skipped
  entirely because the slot already holds the SAME content (token match:
  the versioned catalog encoding or identical bytes). Zero host→device
  transfer — the steady-state catalog path.

``pipeline_depth`` now reports the ADAPTIVE effective depth: the
per-window overlap measurement steps it 1↔2↔3 (solver/pipeline.py
_AdaptiveDepth), and pressure L1+ still collapses it to 1.
"""

from __future__ import annotations

from karpenter_tpu.metrics.registry import DEFAULT

PIPELINE_DEPTH = DEFAULT.gauge(
    "pipeline_depth",
    "Effective provisioning pipeline depth of the last window "
    "(1=serial; collapses to 1 at pressure L1+)")
PIPELINE_STAGE_SECONDS = DEFAULT.histogram(
    "pipeline_stage_seconds",
    "Per-chunk pipeline stage wall time "
    "(stage=marshal|device|launch_bind)")
SOLVER_OVERLAP_SECONDS_TOTAL = DEFAULT.counter(
    "solver_overlap_seconds_total",
    "Seconds dispatched batches spent in flight while the host did other "
    "pipeline work (upper bound on wall saved vs the serial sum)")
PIPELINE_DISPATCH_WAIT_SECONDS = DEFAULT.histogram(
    "pipeline_dispatch_wait_seconds",
    "Seconds between a chunk's async dispatch completing and its fetch "
    "starting inside the pipeline window")
SOLVER_DEVICE_BYTES_IN_USE = DEFAULT.gauge(
    "solver_device_bytes_in_use",
    "Live device memory across the solver mesh in bytes "
    "(memory_stats where available, else live-buffer sizes; best-effort)")
PIPELINE_RING_ALLOCATIONS_TOTAL = DEFAULT.counter(
    "pipeline_ring_allocations_total",
    "Fresh device buffer allocations by the solver ring (slot creation / "
    "bucket change); flat in steady state")
PIPELINE_RING_REFILLS_TOTAL = DEFAULT.counter(
    "pipeline_ring_refills_total",
    "In-place donation-aliased refills of existing ring buffers "
    "(steady-state chunk intake: zero fresh device allocation)")
PIPELINE_RING_REUSES_TOTAL = DEFAULT.counter(
    "pipeline_ring_reuses_total",
    "Ring fills skipped because the slot already holds the same content "
    "(catalog token match: zero host-to-device transfer)")
