"""Metrics for the pipelined provisioning hot loop (solver/pipeline.py).

Four series, all on the process-wide registry (exposed with the
``karpenter_`` prefix by registry.expose()):

- ``karpenter_pipeline_depth``                 gauge — effective pipeline
  depth of the most recent provisioning window (configured depth at L0;
  collapses to 1 at pressure L1+, so a sustained 1 here under a depth-2
  config is the ladder speaking, not a bug)
- ``karpenter_pipeline_stage_seconds``         histogram, ``stage`` label —
  per-chunk wall time by stage: ``marshal`` (schedule + problem build +
  encode + async dispatch), ``device`` (blocking fetch/materialize of the
  in-flight batch), ``launch_bind`` (cloud create + node object + binds)
- ``karpenter_solver_overlap_seconds_total``   counter — cumulative seconds
  each dispatched batch spent in flight before its fetch began, i.e. device
  time hidden behind host launch/bind + marshal work. This is an upper
  bound on wall time saved versus the serial sum (the device may finish
  early inside the span); in serial mode (depth 1) it is ~0 by construction
  because every fetch immediately follows its dispatch.
- ``karpenter_pipeline_dispatch_wait_seconds`` histogram — per-chunk wait
  between dispatch completing and the fetch starting (queueing delay a
  handle experiences inside the pipeline's bounded window)
"""

from __future__ import annotations

from karpenter_tpu.metrics.registry import DEFAULT

PIPELINE_DEPTH = DEFAULT.gauge(
    "pipeline_depth",
    "Effective provisioning pipeline depth of the last window "
    "(1=serial; collapses to 1 at pressure L1+)")
PIPELINE_STAGE_SECONDS = DEFAULT.histogram(
    "pipeline_stage_seconds",
    "Per-chunk pipeline stage wall time "
    "(stage=marshal|device|launch_bind)")
SOLVER_OVERLAP_SECONDS_TOTAL = DEFAULT.counter(
    "solver_overlap_seconds_total",
    "Seconds dispatched batches spent in flight while the host did other "
    "pipeline work (upper bound on wall saved vs the serial sum)")
PIPELINE_DISPATCH_WAIT_SECONDS = DEFAULT.histogram(
    "pipeline_dispatch_wait_seconds",
    "Seconds between a chunk's async dispatch completing and its fetch "
    "starting inside the pipeline window")
