"""Metrics for the device-vectorized scoring policies (ops/policy.py,
solver/policy.py).

Four series, all on the process-wide registry (exposed with the
``karpenter_`` prefix by registry.expose()):

- ``karpenter_policy_score_seconds``    histogram, ``stage`` label
  ("device" = one batched window scoring dispatch, "host" = one scalar
  per-cell scoring pass, "verify" = the probe re-verification)
- ``karpenter_policy_fallback_total``   counter, ``reason`` label — every
  time a device score is discarded for the scalar oracle's answer
- ``karpenter_policy_cells_scored_total`` counter — (schedule × type ×
  offering) cells scored on device, the work the host loop no longer does
- ``karpenter_policy_spot_selected_total`` counter, ``policy`` label —
  placements whose winning offering was spot (the frontier's observable)
"""

from __future__ import annotations

from karpenter_tpu.metrics.registry import DEFAULT

POLICY_SCORE_SECONDS = DEFAULT.histogram(
    "policy_score_seconds",
    "Packing-policy scoring time per window (stage=device|host|verify)")
POLICY_FALLBACK_TOTAL = DEFAULT.counter(
    "policy_fallback_total",
    "Device policy scores discarded for the scalar oracle's answer, by reason")
POLICY_CELLS_SCORED_TOTAL = DEFAULT.counter(
    "policy_cells_scored_total",
    "Feasible (schedule x type x offering) cells scored on device")
POLICY_SPOT_SELECTED_TOTAL = DEFAULT.counter(
    "policy_spot_selected_total",
    "Placements whose winning offering was spot, by policy")

# Preferred (soft) affinity series — karpenter_soft_affinity_* —
# the weighted score terms fused into the same scoring jit
# (docs/scheduling.md §8, docs/observability.md)
SOFT_AFFINITY_TERMS_TOTAL = DEFAULT.counter(
    "soft_affinity_terms_total",
    "Preferred pod-(anti-)affinity terms that produced soft votes")
SOFT_AFFINITY_STEERED_TOTAL = DEFAULT.counter(
    "soft_affinity_steered_total",
    "Launches whose zone choice was narrowed by preferred-affinity votes")
SOFT_AFFINITY_BLOCKED_DRAINS_TOTAL = DEFAULT.counter(
    "soft_affinity_blocked_drains_total",
    "Consolidation drains skipped because the soft-affinity loss "
    "exceeded the price savings")
