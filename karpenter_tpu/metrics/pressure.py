"""Metrics for the brownout subsystem (karpenter_tpu/pressure/).

Five series, all on the process-wide registry (exposed with the
``karpenter_`` prefix by registry.expose()):

- ``karpenter_pressure_level``             gauge — the current ladder rung
  (0=L0 normal … 3=L3 system-critical-only)
- ``karpenter_pods_shed_total``            counter, ``reason`` ×
  ``priority_band`` labels — every admission the intake refused
  (reason: pressure-l2 | pressure-l3 | depth-bound | displaced)
- ``karpenter_intake_queue_depth``         gauge — items awaiting a batch
  window, summed across all provisioner batchers
- ``karpenter_window_splits_total``        counter — oversized windows the
  provisioning loop split at L1+ to bound solve p99
- ``karpenter_kube_client_throttle_seconds`` histogram — time requests
  spent blocked in the kube client's TokenBucket (saturation of the
  200 QPS budget feeds the pressure monitor's throttle signal)
"""

from __future__ import annotations

from karpenter_tpu.metrics.registry import DEFAULT

PRESSURE_LEVEL = DEFAULT.gauge(
    "pressure_level",
    "Brownout ladder rung (0=normal, 1=window-shrink, 2=shed low bands, "
    "3=system-critical only)")
PODS_SHED_TOTAL = DEFAULT.counter(
    "pods_shed_total",
    "Pods refused at intake admission, by reason and priority band")
INTAKE_QUEUE_DEPTH = DEFAULT.gauge(
    "intake_queue_depth",
    "Pods awaiting a batch window across all provisioner batchers")
WINDOW_SPLITS_TOTAL = DEFAULT.counter(
    "window_splits_total",
    "Provisioning windows split into bounded solve chunks at L1+")
KUBE_CLIENT_THROTTLE_SECONDS = DEFAULT.histogram(
    "kube_client_throttle_seconds",
    "Seconds kube API requests waited in the client-side token bucket")
