"""Metrics for the crash-consistency layer: the write-ahead intent
journal (runtime/journal.py), the startup recovery controller
(controllers/recovery.py), and the watch relist-and-reconcile path
(runtime/kubeclient.py).

Series on the process registry (``karpenter_`` prefix via
registry.expose()):

- ``karpenter_journal_records_total``       counter, ``kind`` label —
  intent records appended to the write-ahead journal, by intent kind
- ``karpenter_journal_bytes_total``         counter — bytes appended to
  journal segments (CRC frame + payload + newline)
- ``karpenter_journal_append_seconds``      histogram — wall seconds of
  one durable append (serialize + write + fsync), the bind-path tax
- ``karpenter_journal_open_intents``        gauge — intents currently
  open (not yet closed) in the journal's live index
- ``karpenter_journal_segments``            gauge — journal segment
  files on disk
- ``karpenter_journal_compactions_total``   counter — segment
  compactions (closed intents dropped, segments rewritten)
- ``karpenter_journal_torn_records_total``  counter — records discarded
  on replay (torn tail or CRC mismatch)
- ``karpenter_recovery_intents_total``      counter, ``kind``/``action``
  labels — open intents resolved by startup recovery: action is
  ``forward`` (rolled forward), ``rollback`` (unwound/terminated), or
  ``noop`` (already converged)
- ``karpenter_recovery_seconds``            histogram — wall seconds of
  one full journal replay (readyz stays 503 ``recovering`` meanwhile)
- ``karpenter_ledger_recovery_seconds``     histogram — wall seconds
  spent rebuilding the topology occupancy ledger from open carve
  intents during one journal replay (a slice of recovery_seconds)
- ``karpenter_ledger_recovered_carves_total``  counter — carve records
  re-committed into the occupancy ledger by startup recovery
- ``karpenter_watch_relist_total``          counter, ``kind``/``reason``
  labels — full relist-and-reconcile passes a watch performed after a
  gap (``expired`` = resourceVersion too old / 410, ``reconnect`` =
  stream ended or errored)
"""

from __future__ import annotations

from karpenter_tpu.metrics.registry import DEFAULT

JOURNAL_RECORDS_TOTAL = DEFAULT.counter(
    "journal_records_total",
    "Intent records appended to the write-ahead journal, by intent kind")

JOURNAL_BYTES_TOTAL = DEFAULT.counter(
    "journal_bytes_total",
    "Bytes appended to write-ahead journal segments")

JOURNAL_APPEND_SECONDS = DEFAULT.histogram(
    "journal_append_seconds",
    "Wall seconds of one durable journal append (serialize+write+fsync)")

JOURNAL_OPEN_INTENTS = DEFAULT.gauge(
    "journal_open_intents",
    "Intents currently open in the journal's live index")

JOURNAL_SEGMENTS = DEFAULT.gauge(
    "journal_segments",
    "Write-ahead journal segment files on disk")

JOURNAL_COMPACTIONS_TOTAL = DEFAULT.counter(
    "journal_compactions_total",
    "Journal segment compactions (closed intents dropped)")

JOURNAL_TORN_RECORDS_TOTAL = DEFAULT.counter(
    "journal_torn_records_total",
    "Journal records discarded on replay (torn tail or CRC mismatch)")

RECOVERY_INTENTS_TOTAL = DEFAULT.counter(
    "recovery_intents_total",
    "Open intents resolved by startup recovery, by kind and action "
    "(forward | rollback | noop)")

RECOVERY_SECONDS = DEFAULT.histogram(
    "recovery_seconds",
    "Wall seconds of one full journal replay at startup")

LEDGER_RECOVERY_SECONDS = DEFAULT.histogram(
    "ledger_recovery_seconds",
    "Wall seconds rebuilding the occupancy ledger from open carve "
    "intents during startup recovery")

LEDGER_RECOVERED_CARVES_TOTAL = DEFAULT.counter(
    "ledger_recovered_carves_total",
    "Carve records re-committed into the occupancy ledger by recovery")

WATCH_RELIST_TOTAL = DEFAULT.counter(
    "watch_relist_total",
    "Full relist-and-reconcile passes performed by a watch after a gap, "
    "by kind and reason (expired | reconnect)")
