"""Prometheus-style metrics registry (self-contained).

Reference: pkg/metrics/constants.go (namespace "karpenter", duration buckets
5 ms … 60 s, Measure defer-timer) and the gauge/histogram inventory in
SURVEY.md rows 18/20 and §5.1. Exposition follows the Prometheus text
format so any scraper can consume /metrics.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

NAMESPACE = "karpenter"

# constants.go:33-38
DURATION_BUCKETS = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60]

LabelValues = Tuple[Tuple[str, str], ...]


def _lv(labels: Dict[str, str]) -> LabelValues:
    return tuple(sorted(labels.items()))


class Gauge:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: Dict[LabelValues, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_lv(labels)] = value

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            self._values[_lv(labels)] = self._values.get(_lv(labels), 0.0) + amount

    def delete(self, **labels) -> None:
        with self._lock:
            self._values.pop(_lv(labels), None)

    def delete_matching(self, **labels) -> None:
        """Drop every series whose labels include the given subset — the
        stale-series cleanup used by the node metrics controller
        (metrics/node/controller.go:196-208)."""
        subset = set(labels.items())
        with self._lock:
            self._values = {
                lv: v for lv, v in self._values.items() if not subset <= set(lv)
            }

    def collect(self) -> Dict[LabelValues, float]:
        with self._lock:
            return dict(self._values)


class Counter(Gauge):
    pass


class Histogram:
    def __init__(self, name: str, help_: str = "", buckets: Optional[List[float]] = None):
        self.name = name
        self.help = help_
        self.buckets = list(buckets or DURATION_BUCKETS)
        self._counts: Dict[LabelValues, List[int]] = {}
        self._sums: Dict[LabelValues, float] = {}
        self._totals: Dict[LabelValues, int] = {}
        # exemplar per series: the trace id of one recent observation so a
        # histogram quantile can be joined back to a concrete window trace
        # (surfaced via /debug/vars, never in the Prometheus text format)
        self._exemplars: Dict[LabelValues, Dict[str, object]] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, exemplar: Optional[str] = None,
                **labels) -> None:
        lv = _lv(labels)
        with self._lock:
            counts = self._counts.setdefault(lv, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[lv] = self._sums.get(lv, 0.0) + value
            self._totals[lv] = self._totals.get(lv, 0) + 1
            if exemplar is not None:
                self._exemplars[lv] = {"trace_id": exemplar, "value": value}

    def collect_exemplars(self) -> Dict[LabelValues, Dict[str, object]]:
        with self._lock:
            return dict(self._exemplars)

    @contextmanager
    def time(self, **labels):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0, **labels)

    def collect(self):
        with self._lock:
            return {lv: (list(c), self._sums[lv], self._totals[lv])
                    for lv, c in self._counts.items()}


class Registry:
    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help_), help_)

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help_), help_)

    def histogram(self, name: str, help_: str = "",
                  buckets: Optional[List[float]] = None) -> Histogram:
        return self._get_or_create(
            name, lambda: Histogram(name, help_, buckets), help_)

    def _get_or_create(self, name: str, factory, help_: str = ""):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = self._metrics[name] = factory()
            elif help_ and not metric.help:
                # help attachment is order-independent: whichever call
                # site carries the help text wins, whenever it runs
                metric.help = help_
            return metric

    @contextmanager
    def time(self, name: str, **labels):
        with self.histogram(name).time(**labels):
            yield

    def expose(self) -> str:
        """Prometheus text exposition format."""
        lines: List[str] = []
        with self._lock:
            metrics = dict(self._metrics)
        for name, metric in sorted(metrics.items()):
            full = f"{NAMESPACE}_{name}"
            if metric.help:
                lines.append(f"# HELP {full} {metric.help}")
            if isinstance(metric, Histogram):
                lines.append(f"# TYPE {full} histogram")
                for lv, (counts, sum_, total) in metric.collect().items():
                    base = _fmt_labels(lv)
                    cum = 0
                    for b, c in zip(metric.buckets, counts):
                        cum = c
                        lines.append(f'{full}_bucket{{{_join(base, ("le", str(b)))}}} {cum}')
                    lines.append(f'{full}_bucket{{{_join(base, ("le", "+Inf"))}}} {total}')
                    lines.append(f"{full}_sum{{{_fmt(base)}}} {sum_}")
                    lines.append(f"{full}_count{{{_fmt(base)}}} {total}")
            else:
                kind = "counter" if isinstance(metric, Counter) else "gauge"
                lines.append(f"# TYPE {full} {kind}")
                for lv, v in metric.collect().items():
                    lines.append(f"{full}{{{_fmt(lv)}}} {v}")
        return "\n".join(lines) + "\n"

    def registered(self) -> Dict[str, object]:
        """Name -> metric object view (tools/metrics_lint.py)."""
        with self._lock:
            return dict(self._metrics)

    def snapshot(self) -> Dict[str, dict]:
        """JSON-serializable dump of every registered series — the
        /debug/vars payload. Histograms report count/sum per series plus
        the stored exemplar trace id when one was attached."""
        out: Dict[str, dict] = {}
        with self._lock:
            metrics = dict(self._metrics)
        for name, metric in sorted(metrics.items()):
            if isinstance(metric, Histogram):
                series = {}
                exemplars = metric.collect_exemplars()
                for lv, (_, sum_, total) in metric.collect().items():
                    entry: Dict[str, object] = {"count": total, "sum": sum_}
                    ex = exemplars.get(lv)
                    if ex is not None:
                        entry["exemplar"] = ex
                    series[_fmt(lv)] = entry
                out[name] = {"type": "histogram", "help": metric.help,
                             "series": series}
            else:
                kind = "counter" if isinstance(metric, Counter) else "gauge"
                out[name] = {"type": kind, "help": metric.help,
                             "series": {_fmt(lv): v
                                        for lv, v in metric.collect().items()}}
        return out


def _fmt_labels(lv: LabelValues) -> List[Tuple[str, str]]:
    return list(lv)


def _fmt(pairs) -> str:
    return ",".join(f'{k}="{v}"' for k, v in pairs)


def _join(pairs, extra) -> str:
    return _fmt(list(pairs) + [extra])


# Process-wide default registry (the controller-runtime registry analog).
DEFAULT = Registry()
HISTOGRAMS = DEFAULT
