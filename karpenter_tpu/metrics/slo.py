"""Metrics for the per-pod SLO engine (karpenter_tpu/obs/slo.py).

Nine series, all on the process-wide registry (exposed with the
``karpenter_`` prefix by registry.expose()):

- ``karpenter_slo_stage_latency_p50_seconds`` gauge, ``band`` × ``stage``
  labels — digest p50 per lifecycle stage (intake/schedule/solve/bind/e2e)
- ``karpenter_slo_stage_latency_p99_seconds`` gauge, same labels — digest
  p99 per lifecycle stage
- ``karpenter_slo_samples``          gauge, ``band`` × ``stage`` labels —
  samples folded into each digest cell since the last reset
- ``karpenter_slo_objective_seconds`` gauge, ``band`` label — configured
  latency objective threshold per band
- ``karpenter_slo_burn_rate``        gauge, ``band`` × ``window``
  (fast|slow) labels — breach fraction over the window divided by the
  error budget (1 − target)
- ``karpenter_slo_burning_bands``    gauge — bands currently past both
  burn thresholds (readyz degrades while this is nonzero)
- ``karpenter_slo_burn_trips_total`` gauge — slo-burn flight-recorder
  trips since the last reset
- ``karpenter_slo_breaches_total``   counter, ``band`` × ``stage``
  labels — samples (and intake sheds) past the band's objective
- ``karpenter_slo_breach_latency_seconds`` histogram, ``band`` label —
  breaching samples only, exemplared with the sample window's trace id
"""

from __future__ import annotations

from karpenter_tpu.metrics.registry import DEFAULT

SLO_STAGE_P50 = DEFAULT.gauge(
    "slo_stage_latency_p50_seconds",
    "Digest p50 latency per priority band and lifecycle stage")
SLO_STAGE_P99 = DEFAULT.gauge(
    "slo_stage_latency_p99_seconds",
    "Digest p99 latency per priority band and lifecycle stage")
SLO_SAMPLES = DEFAULT.gauge(
    "slo_samples",
    "Samples folded into each (band, stage) digest cell since reset")
SLO_OBJECTIVE = DEFAULT.gauge(
    "slo_objective_seconds",
    "Configured per-band latency objective threshold")
SLO_BURN_RATE = DEFAULT.gauge(
    "slo_burn_rate",
    "Error-budget burn rate per band over the fast/slow window")
SLO_BURNING_BANDS = DEFAULT.gauge(
    "slo_burning_bands",
    "Bands currently past both burn-rate thresholds (degrades readyz)")
SLO_BURN_TRIPS = DEFAULT.gauge(
    "slo_burn_trips_total",
    "slo-burn flight-recorder trips since the last reset")
SLO_BREACHES = DEFAULT.counter(
    "slo_breaches_total",
    "Latency samples and intake sheds past the band's objective")
SLO_BREACH_LATENCY = DEFAULT.histogram(
    "slo_breach_latency_seconds",
    "Latency of objective-breaching samples, exemplared with the sample "
    "window's trace id")
