"""Metrics for torus-grid slice carving and priced gang preemption.

Series on the process registry (``karpenter_`` prefix via
registry.expose()):

- ``karpenter_topology_carve_windows_total``  counter — gang windows that
  carried carve tensors (at least one slice-shaped gang with carving on)
- ``karpenter_topology_carves_committed_total``  counter — contiguous
  sub-grid carves committed to the occupancy ledger (one per gang × node)
- ``karpenter_topology_carve_rejects_total``  counter — host cell-by-cell
  verification rejected a bin whose *resources* fit but whose free chips
  form no contiguous sub-grid — each one is phantom capacity the shape-only
  gate would have handed to a gang
- ``karpenter_topology_ledger_nodes``  gauge — real nodes currently
  carrying committed carves in the process occupancy ledger
- ``karpenter_preemptions_total``  counter, ``band`` label — gangs
  displaced by a higher-priority gang, by the VICTIM's pressure band
  (``system-critical`` never appears here by construction)
- ``karpenter_preemption_declined_total``  counter, ``reason`` label —
  preemption attempts that did not fire: ``fresh-cheaper`` (the what-if
  displacement price met or exceeded a fresh node for the beneficiary),
  ``no-victim`` (no strictly-lower-band resident to displace),
  ``unplaceable`` (displacement alone still left the beneficiary without
  a carve; evictions rolled back), ``budget`` (the anti-thrash
  preemption budget had no token for the victim's band, or the victim
  gang is still in its post-displacement cooldown)
- ``karpenter_preemption_displaced_pods_total``  counter — member pods
  unbound and requeued through the band-aware batcher by preemptions
- ``karpenter_preemption_budget_tokens``  gauge, ``band`` label —
  displacement tokens currently available in the per-band token bucket
- ``karpenter_preemption_budget_declines_total``  counter, ``reason``
  label — candidates filtered by the budget: ``tokens`` (band bucket
  empty) or ``cooldown`` (victim gang displaced within the last N
  gang windows)
- ``karpenter_preemption_budget_cooldowns``  gauge — victim gangs
  currently inside their post-displacement cooldown window

Carve self-heal rides the existing ``karpenter_filter_fallback_total``
counter with ``reason="carve-mismatch"`` (metrics/filter.py).
"""

from __future__ import annotations

from karpenter_tpu.metrics.registry import DEFAULT

TOPOLOGY_CARVE_WINDOWS_TOTAL = DEFAULT.counter(
    "topology_carve_windows_total",
    "Gang windows solved with carve tensors (slice gangs, carving on)")

TOPOLOGY_CARVES_COMMITTED_TOTAL = DEFAULT.counter(
    "topology_carves_committed_total",
    "Contiguous sub-grid carves committed to the occupancy ledger")

TOPOLOGY_CARVE_REJECTS_TOTAL = DEFAULT.counter(
    "topology_carve_rejects_total",
    "Bins rejected by cell-by-cell carve verification after resources fit "
    "(phantom capacity the shape-only gate would have admitted)")

TOPOLOGY_LEDGER_NODES = DEFAULT.gauge(
    "topology_ledger_nodes",
    "Real nodes currently carrying committed carves in the ledger")

PREEMPTIONS_TOTAL = DEFAULT.counter(
    "preemptions_total",
    "Gangs displaced by a higher-priority gang, by victim band")

PREEMPTION_DECLINED_TOTAL = DEFAULT.counter(
    "preemption_declined_total",
    "Preemption attempts declined, by reason (fresh-cheaper | no-victim "
    "| unplaceable | budget)")

PREEMPTION_DISPLACED_PODS_TOTAL = DEFAULT.counter(
    "preemption_displaced_pods_total",
    "Member pods unbound and requeued by gang preemptions")

PREEMPTION_BUDGET_TOKENS = DEFAULT.gauge(
    "preemption_budget_tokens",
    "Displacement tokens available in the per-band preemption budget")

PREEMPTION_BUDGET_DECLINES_TOTAL = DEFAULT.counter(
    "preemption_budget_declines_total",
    "Preemption candidates filtered by the anti-thrash budget, by reason "
    "(tokens | cooldown)")

PREEMPTION_BUDGET_COOLDOWNS = DEFAULT.gauge(
    "preemption_budget_cooldowns",
    "Victim gangs currently inside their post-displacement cooldown")
