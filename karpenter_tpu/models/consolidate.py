"""Consolidation: re-pack running capacity into a smaller/cheaper node set.

A capability beyond the reference, which only deprovisions *empty* nodes
(node/emptiness.go): here under-utilized nodes are actively drained once
their pods provably fit elsewhere. Two granularities:

- ``repack_plan``: whole-fleet minimal-set re-pack — all reschedulable pods
  re-solved against the catalog with the same TPU FFD kernel the forward
  path uses, scored in $/h (BASELINE config 5).
- ``removable_nodes``: the incremental form the controller executes — nodes
  whose pods fit into the *free* capacity of the surviving nodes, found by
  first-fit-decreasing into fixed bins. Eviction then rides the existing
  termination finalizer flow and displaced pods re-enter provisioning.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.constraints import Constraints, Taints
from karpenter_tpu.api.core import Node, Pod
from karpenter_tpu.api.requirements import pod_requirements
from karpenter_tpu.cloudprovider.spi import InstanceType
from karpenter_tpu.models.cost import CostConfig, node_price, plan_cost
from karpenter_tpu.solver.adapter import pod_vector, resource_list_vector
from karpenter_tpu.solver.host_ffd import NUM_RESOURCES, R_PODS
from karpenter_tpu.solver.solve import SolveResult, SolverConfig, solve
from karpenter_tpu.utils import pod as podutil

NANO = 10**9


def node_instance_type(node: Node, catalog: Sequence[InstanceType]) -> Optional[InstanceType]:
    """Resolve a running node back to its catalog entry via the
    instance-type label stamped at launch (instance.go:245-285)."""
    name = node.metadata.labels.get(wellknown.LABEL_INSTANCE_TYPE)
    for it in catalog:
        if it.name == name:
            return it
    return None


def spot_interruption_rate(it: InstanceType, zone: str) -> float:
    """Published reclaims/hour of this type's spot offering in ``zone``
    (the rate stamped on Offering.interruption_rate by the provider); a
    node whose zone label is stale falls back to the type's lowest spot
    rate — under-charging, never over-charging, the reclaim premium."""
    exact = None
    best = None
    for o in it.offerings:
        if o.capacity_type != wellknown.CAPACITY_TYPE_SPOT:
            continue
        if o.zone == zone:
            exact = o.interruption_rate
        if best is None or o.interruption_rate < best:
            best = o.interruption_rate
    if exact is not None:
        return exact
    return best if best is not None else 0.0


def fleet_prices(
    nodes: Sequence[Node],
    catalog: Sequence[InstanceType],
    cost_config: CostConfig = CostConfig(),
    repack_cost_per_hour: float = 0.0,
) -> Tuple[Dict[str, float], List[Node]]:
    """$/h per node name at its actual capacity type, plus the nodes whose
    instance-type label is absent from the catalog (stale label, or the
    type left the offering set). Unknown nodes price at $0 — they stay
    consolidatable (draining them reclaims SOMETHING; skipping them, the
    old callers' behavior, meant they were never consolidated and never
    priced). Callers log the unknowns once per window with the
    consolidation_unknown_instance_type_total counter.

    With ``repack_cost_per_hour`` > 0 (the interruption-priced policy's
    what-if handoff, solver/policy.py), a spot node's keep-cost includes
    its expected reclaim tax — ``interruption_rate × repack_cost`` — so
    the consolidation ranking sees the spot discount AND the reclaim risk:
    draining a volatile spot node 'reclaims' its risk premium too, and a
    cheap-but-risky node stops outranking a slightly pricier stable one."""
    by_name = {it.name: it for it in catalog}
    prices: Dict[str, float] = {}
    unknown: List[Node] = []
    for node in nodes:
        it = by_name.get(node.metadata.labels.get(wellknown.LABEL_INSTANCE_TYPE))
        if it is None:
            prices[node.metadata.name] = 0.0
            unknown.append(node)
            continue
        capacity_type = node.metadata.labels.get(
            wellknown.LABEL_CAPACITY_TYPE, wellknown.CAPACITY_TYPE_ON_DEMAND)
        price = node_price(it, capacity_type, cost_config)
        if repack_cost_per_hour > 0.0 and \
                capacity_type == wellknown.CAPACITY_TYPE_SPOT:
            zone = node.metadata.labels.get(wellknown.LABEL_TOPOLOGY_ZONE, "")
            price += spot_interruption_rate(it, zone) * repack_cost_per_hour
        prices[node.metadata.name] = price
    return prices, unknown


def current_cost(
    nodes: Sequence[Node],
    catalog: Sequence[InstanceType],
    cost_config: CostConfig = CostConfig(),
) -> float:
    """$/h of the running fleet, priced at each node's actual capacity type.
    Nodes the catalog can't price contribute $0 (see fleet_prices)."""
    prices, _ = fleet_prices(nodes, catalog, cost_config)
    return sum(prices.values())


def reschedulable_pods(pods: Sequence[Pod]) -> Tuple[List[Pod], bool]:
    """(pods to re-pack, node is a candidate). Daemonset/static pods stay
    with the node; a do-not-evict annotation pins the whole node
    (termination/terminate.go do-not-evict check)."""
    movable: List[Pod] = []
    for p in pods:
        if p.metadata.annotations.get(wellknown.DO_NOT_EVICT_ANNOTATION) == "true":
            return [], False
        if podutil.is_owned_by_daemonset(p) or podutil.is_owned_by_node(p):
            continue
        movable.append(p)
    return movable, True


@dataclass
class ConsolidationPlan:
    """A whole-fleet re-pack proposal."""

    nodes_to_remove: List[Node]
    replacement: SolveResult
    current_nodes: int
    current_cost_per_hour: float
    planned_cost_per_hour: float
    relax: Optional[object] = None  # solver.relax.RelaxInfo when backend="relax"

    @property
    def planned_nodes(self) -> int:
        return self.replacement.node_count

    @property
    def saves(self) -> bool:
        if self.replacement.unschedulable:
            return False  # never trade running pods for savings
        if self.planned_nodes < self.current_nodes:
            return True
        return self.planned_cost_per_hour < self.current_cost_per_hour - 1e-9


@dataclass
class Fleet:
    """One provisioner's consolidation scope: its running nodes, their pods,
    and the constraints/catalog its replacement capacity must come from."""

    nodes: Sequence[Node]
    pods_by_node: Dict[str, List[Pod]]
    constraints: Constraints
    catalog: Sequence[InstanceType]
    daemons: Sequence[Pod] = ()


def repack_plan(
    nodes: Sequence[Node],
    pods_by_node: Dict[str, List[Pod]],
    constraints: Constraints,
    catalog: Sequence[InstanceType],
    daemons: Sequence[Pod] = (),
    solver_config: Optional[SolverConfig] = None,
    cost_config: CostConfig = CostConfig(),
    backend: str = "ffd",
) -> ConsolidationPlan:
    """Minimal-set re-pack of every candidate node's reschedulable pods —
    one solve on the same device kernel as provisioning.

    ``backend="relax"`` routes the replacement solve through the LP/ADMM
    relaxation (solver/relax.py): its rounded plan is used only when
    strictly cheaper AND fully feasible, else the exact FFD plan — the
    returned plan is always exact-FFD-verified either way."""
    return repack_plan_multi(
        [Fleet(nodes, pods_by_node, constraints, catalog, daemons)],
        solver_config=solver_config, cost_config=cost_config,
        backend=backend)[0]


def repack_plan_multi(
    fleets: Sequence[Fleet],
    solver_config: Optional[SolverConfig] = None,
    cost_config: CostConfig = CostConfig(),
    backend: str = "ffd",
) -> List[ConsolidationPlan]:
    """Whole-fleet re-packs for MANY provisioners in one batched device
    call: the per-fleet forward solves ride solver/batch_solve.solve_batch
    (vmap within a chip, shard_map over the mesh batch axis, one flattened
    fetch) — consolidation scales across the mesh exactly like the
    provisioning hot loop (controllers/provisioning.py:127)."""
    from karpenter_tpu.solver.batch_solve import Problem, solve_batch

    prepared = []
    for fleet in fleets:
        candidates: List[Node] = []
        movable: List[Pod] = []
        for node in fleet.nodes:
            pods, ok = reschedulable_pods(
                fleet.pods_by_node.get(node.metadata.name, []))
            if not ok:
                continue
            candidates.append(node)
            movable.extend(pods)
        prepared.append((fleet, candidates, movable))

    relax_infos: List[Optional[object]] = [None] * len(prepared)
    if backend == "relax":
        from karpenter_tpu.solver.relax import relax_solve

        replacements = []
        for idx, (fleet, _, movable) in enumerate(prepared):
            replacement, info = relax_solve(
                fleet.constraints, movable, fleet.catalog,
                daemons=fleet.daemons, config=solver_config,
                cost_config=cost_config)
            replacements.append(replacement)
            relax_infos[idx] = info
    elif len(prepared) == 1:  # solo fleet: no batch machinery
        fleet, candidates, movable = prepared[0]
        replacements = [solve(fleet.constraints, movable, fleet.catalog,
                              daemons=fleet.daemons, config=solver_config)]
    else:
        replacements = solve_batch(
            [Problem(constraints=fleet.constraints, pods=movable,
                     instance_types=fleet.catalog, daemons=fleet.daemons)
             for fleet, _, movable in prepared],
            config=solver_config)

    return [
        ConsolidationPlan(
            nodes_to_remove=candidates,
            replacement=replacement,
            current_nodes=len(candidates),
            current_cost_per_hour=current_cost(
                candidates, fleet.catalog, cost_config),
            planned_cost_per_hour=plan_cost(
                replacement.packings, fleet.constraints.requirements,
                cost_config),
            relax=info,
        )
        for (fleet, candidates, _), replacement, info
        in zip(prepared, replacements, relax_infos)
    ]


# ---------------------------------------------------------------------------
# Incremental consolidation: fit one node's pods into surviving free space.
# ---------------------------------------------------------------------------


def free_capacity_vector(node: Node, pods: Sequence[Pod]) -> List[int]:
    """allocatable − Σ pod requests, in solver nano-units. The "pods"
    allocatable lands on R_PODS via the well-known resource mapping; each
    running pod additionally consumes one slot there."""
    free = list(resource_list_vector(node.status.allocatable))
    for p in pods:
        v = pod_vector(p)
        for r in range(NUM_RESOURCES):
            free[r] -= v[r]
        free[R_PODS] -= NANO  # one pod slot each
    return free


@dataclass
class _Bin:
    """A surviving node's free capacity + the scheduling surface a moved pod
    must clear (labels for selector/affinity, taints for toleration)."""

    name: str
    free: List[int]
    labels: Dict[str, str]
    taints: Taints


def _bin_for(node: Node, pods: Sequence[Pod]) -> _Bin:
    return _Bin(
        name=node.metadata.name,
        free=free_capacity_vector(node, pods),
        labels=node.metadata.labels,
        taints=Taints(node.spec.taints),
    )


def node_bin(node: Node, pods: Sequence[Pod]) -> _Bin:
    """Public form of _bin_for: the what-if window encoder
    (ops/whatif.encode_window) consumes these as its bin set."""
    return _bin_for(node, pods)


def _compatible(pod: Pod, b: _Bin) -> bool:
    """Would the kube scheduler place this pod on this node? nodeSelector/
    affinity requirements against node labels + taint toleration — the
    checks the resource-only fit can't see. A NotIn-only requirement
    evaluates to the empty set (the Go quirk, requirements.go:189-194),
    which is conservatively incompatible everywhere."""
    reqs = pod_requirements(pod)
    for key in reqs.keys():
        allowed = reqs.requirement(key)
        if allowed is None:
            continue
        if b.labels.get(key) not in allowed:
            return False
    return not b.taints.tolerates(pod)


def place_onto(
    pods: Sequence[Pod],
    bins: Sequence[_Bin],
    commit: bool = False,
) -> Optional[List[str]]:
    """First-fit-decreasing into FIXED bins, honoring scheduling
    compatibility: bin names each pod landed on, or None if any pod cannot
    be placed. With ``commit``, the placement is charged against the bins'
    free vectors (used exactly once per removal so the feasibility check
    and the accounting can never diverge). No new nodes — that is
    repack_plan's job."""
    trial = [list(b.free) for b in bins]
    placed_names: List[str] = []
    ordered = sorted(((pod_vector(p), p) for p in pods),
                     key=lambda t: (-t[0][0], -t[0][1]))
    for vec, pod in ordered:
        placed = None
        for i, b in enumerate(bins):
            f = trial[i]
            if not all(f[r] >= vec[r] for r in range(NUM_RESOURCES)):
                continue
            if f[R_PODS] < NANO:
                continue
            if not _compatible(pod, b):
                continue
            for r in range(NUM_RESOURCES):
                f[r] -= vec[r]
            f[R_PODS] -= NANO
            placed = i
            break
        if placed is None:
            return None
        placed_names.append(bins[placed].name)
    if commit:
        for i, b in enumerate(bins):
            b.free[:] = trial[i]
    return placed_names


def fits_on_existing(pod_vecs: Sequence[Sequence[int]],
                     free_vecs: Sequence[List[int]]) -> bool:
    """Resource-only convenience form of place_onto (no labels/taints) for
    callers that already hold raw vectors."""
    bins = [_Bin(name=str(i), free=list(f), labels={}, taints=Taints())
            for i, f in enumerate(free_vecs)]
    trial = [list(b.free) for b in bins]
    for v in sorted(pod_vecs, key=lambda v: (-v[0], -v[1])):
        placed = False
        for f in trial:
            if all(f[r] >= v[r] for r in range(NUM_RESOURCES)) and f[R_PODS] >= NANO:
                for r in range(NUM_RESOURCES):
                    f[r] -= v[r]
                f[R_PODS] -= NANO
                placed = True
                break
        if not placed:
            return False
    return True


def removable_nodes(
    nodes: Sequence[Node],
    pods_by_node: Dict[str, List[Pod]],
    max_actions: int = 1,
) -> List[Node]:
    """Nodes (least-loaded first) whose reschedulable pods all fit — by
    resources AND scheduling constraints — on the other candidates' free
    capacity. Conservative, one safe step at a time: at most ``max_actions``
    per pass, and a node that RECEIVED another removal's pods this pass is
    never itself removed (its free vector now backs that placement)."""
    infos = []
    for node in nodes:
        if node.metadata.deletion_timestamp is not None:
            continue
        pods = pods_by_node.get(node.metadata.name, [])
        movable, ok = reschedulable_pods(pods)
        if not ok:
            continue
        infos.append((node, pods, movable))

    # least pods first: cheapest to move
    infos.sort(key=lambda t: len(t[2]))
    bins = {n.metadata.name: _bin_for(n, pods) for n, pods, _ in infos}
    removed: List[Node] = []
    removed_names: set = set()
    receivers: set = set()
    for node, _, movable in infos:
        if len(removed) >= max_actions:
            break
        name = node.metadata.name
        if not movable:
            continue  # empty nodes are the emptiness controller's job
        if name in receivers:
            continue  # its capacity already backs an earlier removal
        targets = [b for other, b in bins.items()
                   if other != name and other not in removed_names]
        landed = place_onto(movable, targets, commit=True)
        if landed is not None:
            removed.append(node)
            removed_names.add(name)
            receivers.update(landed)
    return removed
