"""Cost model: price-aware selection over pack results.

A capability beyond the reference: the Go packer optimizes node count only
and delegates price to EC2 Fleet's allocation strategy (instance.go:134-139).
Here prices live on the catalog (InstanceType.price = on-demand $/h;
spot offers a discounted rate), so the solver can both (a) order each
node's instance-type options cheapest-first — feeding Fleet's lowest-price /
capacity-optimized-prioritized strategies the right priority order — and
(b) score whole packing plans in $, which is what consolidation compares.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.cloudprovider.spi import InstanceType

# Long-run average discount of spot vs on-demand. AWS publishes "up to 90%";
# fleets typically realize ~60-70%. Configurable per solve.
DEFAULT_SPOT_PRICE_FACTOR = 0.35


@dataclass(frozen=True)
class CostConfig:
    spot_price_factor: float = DEFAULT_SPOT_PRICE_FACTOR


def effective_price(
    it: InstanceType,
    requirements: Requirements,
    config: CostConfig = CostConfig(),
) -> Tuple[float, Optional[str]]:
    """Cheapest viable (price, capacity_type) for this instance type under
    the constraints' capacity-type/zone requirements. Unpriced catalogs
    (price=0) collapse to 0 everywhere, making cost ordering a no-op."""
    capacity_types = requirements.capacity_types()
    zones = requirements.zones()
    best: Tuple[float, Optional[str]] = (float("inf"), None)
    for offering in it.offerings:
        if capacity_types is not None and offering.capacity_type not in capacity_types:
            continue
        if zones is not None and offering.zone not in zones:
            continue
        price = it.price
        if offering.capacity_type == wellknown.CAPACITY_TYPE_SPOT:
            price *= config.spot_price_factor
        if price < best[0]:
            best = (price, offering.capacity_type)
    if best[1] is None:
        return (float("inf"), None)
    return best


def order_options_by_price(
    options: Sequence[InstanceType],
    requirements: Requirements,
    config: CostConfig = CostConfig(),
) -> list:
    """Stable cheapest-first ordering of a node's instance-type options.

    The FFD packer emits options smallest-first (capacity order); for launch
    we want price order, with capacity order as the tiebreak — stable sort
    keeps it."""
    return sorted(options, key=lambda it: effective_price(it, requirements, config)[0])


def node_price(
    it: InstanceType,
    capacity_type: str,
    config: CostConfig = CostConfig(),
) -> float:
    """$/h of one node of this type at this capacity type."""
    if capacity_type == wellknown.CAPACITY_TYPE_SPOT:
        return it.price * config.spot_price_factor
    return it.price


def plan_cost(
    packings,  # Sequence[solver.solve.Packing]
    requirements: Requirements,
    config: CostConfig = CostConfig(),
) -> float:
    """$/h of a pack plan, charging each node its cheapest viable option —
    the price Fleet's lowest-price strategy converges to."""
    total = 0.0
    for packing in packings:
        price, _ = min(
            (effective_price(it, requirements, config) for it in packing.instance_type_options),
            key=lambda t: t[0])
        total += price * packing.node_quantity
    return total
