"""Device-FFD model: orchestrates encode → pack_chunk loop → decode.

One of the framework's solver "model families": exact parity with the
reference Go packer (the others: cost-minimizing pack, consolidation).
Produces the same HostSolveResult structure as the host oracle so callers
and tests are representation-agnostic.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from karpenter_tpu.ops.encode import EncodedProblem, encode
from karpenter_tpu.solver.host_ffd import (
    HostPacking, HostSolveResult, MAX_INSTANCE_TYPES, Packable, Vec,
    instance_options,
)

DEFAULT_CHUNK_ITERS = 64
MAX_CHUNKS = 4096  # hard safety valve; each iteration provably makes progress
_INT32_MAX = 2**31 - 1
# above this many record-buffer elements (L x S) the chunk loop switches to
# the pipelined device-resident-carry path: the fetch is bandwidth-bound
# over the tunnel (~45 MB/s measured) and overlaps the next chunk's compute
_PIPELINE_ELEMS = 1 << 20


def device_args(enc: EncodedProblem):
    """THE kernel argument tuple (shapes, counts, dropped, totals,
    reserved0, valid, last_valid, pods_unit) — single source of truth for
    the pack_chunk/pack_chunk_pallas ABI, shared with bench.py."""
    return (
        enc.shapes, enc.counts, np.zeros_like(enc.counts), enc.totals,
        enc.reserved0, enc.valid,
        np.asarray(enc.last_valid, np.int32),
        np.asarray(enc.pods_unit, np.int32),
    )


def encode_prices(prices, padded_t: int) -> np.ndarray:
    """Effective $/h per packable → (T_padded,) int32 micro-$ for the
    kernel's cost tie-break. Only the ORDERING matters on device; inf
    (no viable offering) and the padding both map to int32 max so they
    never win a tie."""
    out = np.full((padded_t,), _INT32_MAX, np.int32)
    for i, p in enumerate(prices):
        if p != float("inf"):
            out[i] = min(int(p * 1e6), _INT32_MAX)
    return out


def default_kernel() -> str:
    """Pallas on real TPU (fused VMEM state, blocked shape walk, early
    exit — ~20× the XLA scan at the 8192-shape bucket, r5 capture); the
    XLA kernel elsewhere — pallas interpret mode on CPU is debug-speed
    only. Both are record-for-record parity tested
    (tests/test_pack_pallas.py).

    Backend-init failure (dead TPU tunnel, missing runtime) answers "xla":
    the caller's device_put will then raise into the fallback rings in
    solver/solve.py instead of this probe killing the whole solve."""
    import jax

    try:
        backend = jax.default_backend()
    except Exception:
        return "xla"
    return "pallas" if backend == "tpu" else "xla"


def solve_ffd_device(
    pod_vecs: Sequence[Vec],
    pod_ids: Sequence[int],
    packables: Sequence[Packable],
    max_instance_types: int = MAX_INSTANCE_TYPES,
    chunk_iters: int = DEFAULT_CHUNK_ITERS,
    kernel: Optional[str] = None,   # "xla"|"pallas"|"type-spmd"|None=auto
    prices: Optional[Sequence[float]] = None,  # per-packable effective $/h
    cost_tiebreak: bool = False,
    max_shapes: Optional[int] = None,  # decline above this cardinality
    enc: Optional[EncodedProblem] = None,  # precomputed (possibly unpadded)
    pallas_max_shapes: int = 8192,  # pallas-validated bucket ceiling
    hedge: bool = True,  # tail-mitigating second fetch (solver/hedge.py)
    compact: bool = True,  # active-shape compaction at chunk boundaries
    donate: bool = False,  # solo DeviceRing: refill/reuse device buffers
) -> Optional[HostSolveResult]:
    """Solve on device; None when the problem is not device-encodable
    (caller falls back to the host oracle). Pods may arrive unsorted; the
    same descending total order as the host oracle is applied here.

    ``cost_tiebreak`` picks the cheapest max-pods type per node (capacity
    order on price ties); implemented in-kernel by all three device
    executors (XLA scan, pallas, type-spmd) with identical semantics —
    differentially enforced by tests/test_cost_model.py.

    ``max_shapes``: return None above this distinct-shape count so the
    caller's native ring answers instead (SolverConfig.device_max_shapes —
    at high cardinality the chunked record fetches cost a round trip each).

    ``enc``: a precomputed encoding (padded or exact-size) so the solve
    path pays the O(pods) dedupe + GCD scaling once across all rings.

    ``compact``: gather the alive (counts > 0) shapes into a dense prefix
    at every chunk boundary and re-bucket to the next power-of-two shape
    bucket (ops/compact.py), so a solve that starts at the 8192+ bucket
    runs its later chunks on the small-S kernel. Provably a no-op for the
    packing result (docs/solver.md, "shape compaction & re-bucketing");
    disable only to compare against the straight-line chunk loop.

    ``donate``: route the problem tensors through the process DeviceRing
    (solver/pipeline.py) — the batched path's contract extended to solo
    solves. Steady-state windows REFILL the previous solve's device
    buffers in place (donation-aliased DUS: a stale read of the consumed
    buffer raises, never returns garbage), and buffers whose content
    token matches — the catalog tensors via the encoder's versioned
    catalog token, shapes via a byte digest — skip the host→device
    transfer entirely. The solo kernels don't donate their inputs, so
    hedged duplicate dispatches stay safe; a loser reading a buffer the
    next chunk's refill consumed raises into the hedger, which swallows
    loser errors by contract."""
    import jax

    from karpenter_tpu.ops.encode import pad_encoding
    from karpenter_tpu.ops.pack import pack_chunk_flat, unpack_flat

    if not packables:
        return HostSolveResult(packings=[], unschedulable=list(pod_ids))

    if enc is None:
        enc = encode(pod_vecs, pod_ids, packables, pad=False)
    if enc is None:
        return None
    if max_shapes is not None and enc.num_shapes > max_shapes:
        return None
    enc = pad_encoding(enc)
    if enc is None:
        return None

    if kernel is None:
        kernel = default_kernel()
    if kernel not in ("xla", "pallas", "type-spmd"):
        raise ValueError(f"unknown device kernel {kernel!r}: "
                         "expected None, 'xla', 'pallas' or 'type-spmd'")
    if kernel == "pallas" and enc.num_shapes > pallas_max_shapes:
        # the fused VMEM kernel is routed only to its hardware-validated
        # buckets (SolverConfig.pallas_max_shapes); the block-tiled XLA
        # scan is the executor built for anything above
        kernel = "xla"
    if kernel == "pallas":
        from karpenter_tpu.ops.pack_pallas import DIV_CAP

        if int(enc.counts.max(initial=0)) >= DIV_CAP - 4:
            # the pallas kernel's exact float32 division is valid while
            # per-shape pod counts stay below DIV_CAP; the batcher guards
            # batches at 100k pods so this is unreachable in production —
            # routed to the XLA scan if it ever happens
            kernel = "xla"
    use_cost = cost_tiebreak and prices is not None
    prices_dev = None
    if use_cost:
        prices_dev = jax.device_put(
            encode_prices(prices, enc.totals.shape[0]))
    if kernel == "type-spmd":
        # ONE problem across the whole mesh, instance-type axis sharded,
        # per-node decisions via in-solve collectives (parallel/
        # type_sharded.py). Bit-identical to the single-device kernels;
        # wins when the catalog is large and the batch axis can't fill
        # the mesh. Falls back to the XLA scan when the padded type
        # bucket doesn't divide across the mesh.
        from karpenter_tpu.parallel.type_sharded import (
            pack_chunk_type_sharded, type_mesh,
        )

        tmesh = type_mesh()
        if enc.totals.shape[0] % tmesh.devices.size == 0:
            import functools

            _chunk = functools.partial(
                pack_chunk_type_sharded, mesh=tmesh,
                prices=prices_dev, cost_tiebreak=use_cost)
        else:
            kernel = "xla"
    if kernel == "pallas":
        import functools

        from karpenter_tpu.ops.pack_pallas import pack_chunk_pallas_flat

        # off-TPU (tests, dev laptops) Mosaic can't compile — interpret
        _chunk = functools.partial(
            pack_chunk_pallas_flat,
            interpret=jax.default_backend() != "tpu",
            prices=prices_dev, cost_tiebreak=use_cost)
    elif kernel == "xla":
        import functools

        _chunk = functools.partial(pack_chunk_flat, prices=prices_dev,
                                   cost_tiebreak=use_cost)

    S, L = enc.shapes.shape[0], chunk_iters
    T_pad = enc.totals.shape[0]
    args = device_args(enc)
    ring = slot = _ring_sh = None
    if donate and kernel in ("xla", "pallas"):
        # type-spmd stays off-ring: its tensors live under a mesh sharding
        # the single-device refill pjit can't alias
        try:
            from jax.sharding import SingleDeviceSharding

            from karpenter_tpu.solver.pipeline import DeviceRing, get_ring

            _ring_sh = SingleDeviceSharding(jax.devices()[0])
            _names = ("shapes", "counts", "dropped", "totals", "reserved0",
                      "valid", "last_valid", "pods_unit")
            ring = get_ring()
            slot = ring.acquire(DeviceRing.signature(
                {f"solo_{n}": a for n, a in zip(_names, args)}))
        except Exception:
            ring = slot = None
    if slot is not None:
        import hashlib

        cat = enc.catalog_token
        tok = (lambda field: ("solo", field, cat)) if cat is not None \
            else (lambda field: None)
        shapes_tok = ("bytes", hashlib.blake2b(
            np.ascontiguousarray(args[0]).tobytes(), digest_size=16).digest())
        fill = lambda name, arr, token=None: ring.fill(  # noqa: E731
            slot, name, arr, _ring_sh, token=token)
        shapes_d = fill("solo_shapes", args[0], shapes_tok)
        counts_d = fill("solo_counts", args[1])
        # the device dropped buffer is an INPUT (solo kernels don't mutate
        # it) and is zeros at every chunk start — always token-reusable
        dropped_d = fill("solo_dropped", args[2], ("zeros", args[2].shape))
        totals = fill("solo_totals", args[3], tok("totals"))
        reserved0 = fill("solo_reserved0", args[4], tok("reserved0"))
        valid = fill("solo_valid", args[5], tok("valid"))
        last_valid = fill("solo_last_valid", args[6], tok("last_valid"))
        pods_unit = fill("solo_pods_unit", args[7], tok("pods_unit"))
    else:
        # one host→device transfer for the whole problem (tunnel-latency
        # bound)
        (shapes_d, counts_d, dropped_d, totals, reserved0, valid,
         last_valid, pods_unit) = jax.device_put(args)

    try:
        # the fast-forward bound depends only on (shapes, totals, reserved0,
        # valid) — all chunk-invariant — so it is computed ONCE per solve and
        # passed into every chunk (sliced through compactions below); the
        # type-spmd kernel computes its own sharded bound per chunk instead
        # (one local reduce + pmax, no replicated extra input)
        takes_maxfit = kernel in ("xla", "pallas")
        maxfit_d = None
        maxfit_full = np.zeros(S, np.int32)
        if takes_maxfit:
            from karpenter_tpu.ops.pack import compute_maxfit

            maxfit_d = jax.jit(compute_maxfit)(shapes_d, totals, reserved0,
                                               valid)
            maxfit_full = np.asarray(maxfit_d)

        def fetch_chunk(shapes_now, counts_now, dropped_now, maxfit_now,
                        S_now):
            # the per-chunk dispatch+fetch, optionally hedged: tunnel jitter
            # puts occasional >200 ms spikes on an otherwise ~72 ms RTT-bound
            # leg; the hedger re-issues the (deterministic) chunk when a fetch
            # overruns its own recent wall time and takes whichever lands
            # first
            hedge_key = (kernel, S_now, T_pad, chunk_iters, use_cost)

            def dispatch():
                kw = {"maxfit": maxfit_now} if takes_maxfit else {}
                return np.asarray(_chunk(
                    shapes_now, counts_now, dropped_now, totals, reserved0,
                    valid, last_valid, pods_unit, num_iters=chunk_iters,
                    **kw))

            if not hedge:
                return dispatch()
            from karpenter_tpu.solver.hedge import FETCHER

            return FETCHER.fetch(hedge_key, dispatch)

        records = []  # (chosen, qty, packed-vec | sparse [(shape, n), ...])
        if not compact and S * L >= _PIPELINE_ELEMS:
            # High-cardinality regime with compaction disabled: the (L, S)
            # record buffer is megabytes and the tunnel moves ~45 MB/s, so
            # the fetch — not the kernel — bounds the wall time. Pipeline:
            # keep the counts/dropped carry DEVICE-RESIDENT (sliced from the
            # flat buffer, no host round-trip between chunks), speculatively
            # dispatch chunk n+1, and overlap its compute with chunk n's
            # async copy-out. A speculatively dispatched chunk after `done`
            # is a no-op (the kernel's while loop exits immediately) and is
            # never fetched. With compaction ON (the default) this path is
            # skipped: shrinking S at each boundary cuts both the kernel and
            # the fetch for every later chunk, which beats overlapping
            # full-size ones. Hedging does not apply here — these fetches
            # are bandwidth-bound, not jitter-bound (solver/hedge.py
            # MAX_HEDGEABLE_WALL_S).
            kw = {"maxfit": maxfit_d} if takes_maxfit else {}
            buf = _chunk(shapes_d, counts_d, dropped_d, totals, reserved0,
                         valid, last_valid, pods_unit,
                         num_iters=chunk_iters, **kw)
            dropped_h = None
            for _ in range(MAX_CHUNKS):
                try:
                    buf.copy_to_host_async()
                except Exception:
                    pass  # fetch below still works, just unoverlapped
                next_buf = _chunk(
                    shapes_d, buf[:S], buf[S:2 * S], totals, reserved0,
                    valid, last_valid, pods_unit, num_iters=chunk_iters,
                    **kw)
                counts_h, dropped_h, done, chosen_h, q_h, packed_h = \
                    unpack_flat(np.asarray(buf), S, L)
                for i in range(L):
                    if q_h[i] > 0:
                        records.append(
                            (int(chosen_h[i]), int(q_h[i]), packed_h[i]))
                if done:
                    break
                buf = next_buf
            else:
                return None  # did not converge — impossible by construction
            return _decode(enc, records, dropped_h, packables,
                           max_instance_types)

        # Chunk loop with active-shape compaction at the boundaries
        # (ops/compact.py): FFD consumes shapes in descending order, so the
        # alive set shrinks front-to-back; once it fits a smaller
        # power-of-two bucket, the remaining chunks run the small-S kernel.
        # ``perm`` maps compacted rows back to original shape indices;
        # ``dropped`` is passed to the kernel as zeros each chunk and the
        # per-chunk delta is scattered into the original index space
        # host-side.
        from karpenter_tpu.ops.compact import (
            compact_alive, scatter_dropped, sparse_record,
        )

        shapes_full = np.asarray(enc.shapes)
        dropped_full = np.zeros(S, np.int64)
        perm = None
        S_cur = S
        for _ in range(MAX_CHUNKS):
            # one device→host fetch per chunk; typical solves need one chunk
            counts_h, dropped_h, done, chosen_h, q_h, packed_h = unpack_flat(
                fetch_chunk(shapes_d, counts_d, dropped_d, maxfit_d, S_cur),
                S_cur, L)
            for i in range(L):
                if q_h[i] > 0:
                    rec = (packed_h[i] if perm is None
                           else sparse_record(packed_h[i], perm))
                    records.append((int(chosen_h[i]), int(q_h[i]), rec))
            scatter_dropped(dropped_full, dropped_h, perm)
            if done:
                break
            c = (compact_alive(counts_h, perm, shapes_full, maxfit_full)
                 if compact else None)
            if c is not None:
                perm, S_cur = c.perm, c.num_shapes
                if slot is not None:
                    # re-bucket: smaller arrays — fill() sees the mismatch
                    # and makes COUNTED fresh allocations (compaction is an
                    # event, not the steady state the zero-alloc gate
                    # measures); maxfit joins the same ledger
                    shapes_d = ring.fill(slot, "solo_shapes", c.shapes,
                                         _ring_sh)
                    counts_d = ring.fill(slot, "solo_counts", c.counts,
                                         _ring_sh)
                    dropped_d = ring.fill(slot, "solo_dropped",
                                          np.zeros(S_cur, np.int32),
                                          _ring_sh,
                                          token=("zeros", (S_cur,)))
                    if takes_maxfit:
                        maxfit_d = jax.device_put(c.maxfit)
                        ring.note_allocation(1)
                    else:
                        maxfit_d = None
                else:
                    shapes_d, counts_d, dropped_d = jax.device_put(
                        (c.shapes, c.counts, np.zeros(S_cur, np.int32)))
                    maxfit_d = (jax.device_put(c.maxfit) if takes_maxfit
                                else None)
            elif slot is not None:
                # non-compact resume: the counts row refills the previous
                # chunk's buffer in place (donating DUS — a stale read of
                # the consumed buffer raises); the zeros row token-matches
                # and ships nothing
                counts_d = ring.fill(slot, "solo_counts", counts_h,
                                     _ring_sh)
                dropped_d = ring.fill(slot, "solo_dropped",
                                      np.zeros_like(counts_h), _ring_sh,
                                      token=("zeros", counts_h.shape))
            else:
                counts_d, dropped_d = jax.device_put(
                    (counts_h, np.zeros_like(counts_h)))
        else:
            return None  # did not converge — impossible by construction

        return _decode(enc, records, dropped_full, packables,
                       max_instance_types)
    finally:
        if slot is not None:
            ring.release(slot)


def solve_ffd_numpy(
    pod_vecs: Sequence[Vec],
    pod_ids: Sequence[int],
    packables: Sequence[Packable],
    max_instance_types: int = MAX_INSTANCE_TYPES,
    prices: Optional[Sequence[float]] = None,
    cost_tiebreak: bool = False,
) -> Optional[HostSolveResult]:
    """Numpy mirror of the device kernel (ops/pack.py), shape-level greedy
    with the same fast-forward. Fast enough for 50k-pod parity checks where
    the naive per-pod oracle (host_ffd.pack) is O(pods × types × nodes).
    Differential tests pin: host_ffd.pack ≡ solve_ffd_numpy ≡ device."""
    from karpenter_tpu.solver.host_ffd import R_PODS as _R_PODS

    if not packables:
        return HostSolveResult(packings=[], unschedulable=list(pod_ids))
    enc = encode(pod_vecs, pod_ids, packables)
    if enc is None:
        return None

    S, T = enc.num_shapes, enc.num_types
    shapes = enc.shapes[:S].astype(np.int64)
    counts = enc.counts[:S].astype(np.int64).copy()
    totals = enc.totals[:T].astype(np.int64)
    reserved0 = enc.reserved0[:T].astype(np.int64)
    pods_one = np.zeros(shapes.shape[1], np.int64)
    pods_one[_R_PODS] = enc.pods_unit

    avail0 = totals - reserved0
    # unrolled over R so peak memory stays (S, T), never (S, T, R) — the
    # dense intermediate is ~0.5 GB at the 8192-shape bucket
    kfit0 = np.full((S, T), _INT32_MAX, np.int64)
    with np.errstate(divide="ignore"):
        for r in range(shapes.shape[1]):
            col = shapes[:, r][:, None]
            kr_r = np.where(col > 0, avail0[None, :, r] // np.maximum(col, 1),
                            _INT32_MAX)
            np.minimum(kfit0, kr_r, out=kfit0)
    maxfit = kfit0.max(axis=1)  # (S,)

    dropped = np.zeros(S, np.int64)
    records = []
    while counts.any():
        has = counts > 0
        largest = int(np.argmax(has))
        smallest = S - 1 - int(np.argmax(has[::-1]))
        smallest_fits = np.maximum(shapes[smallest] - pods_one, 0)

        reserved = reserved0.copy()
        stopped = np.zeros(T, bool)
        npacked = np.zeros(T, np.int64)
        k_all = np.zeros((S, T), np.int64)
        for s in range(S):
            if counts[s] == 0:
                continue
            active = ~stopped
            avail = totals - reserved
            kr = np.where(shapes[s][None, :] > 0,
                          avail // np.maximum(shapes[s][None, :], 1), _INT32_MAX)
            k = np.clip(kr.min(axis=1), 0, counts[s]) * active
            failure = active & (k < counts[s])
            reserved = reserved + k[:, None] * shapes[s][None, :]
            full = np.any((totals > 0) & (reserved + smallest_fits[None, :] >= totals), axis=1)
            npacked = npacked + k
            stopped |= failure & (full | (npacked == 0))
            k_all[s] = k

        max_pods = int(npacked[T - 1])
        if max_pods == 0:
            dropped[largest] += counts[largest]
            counts[largest] = 0
            continue
        tie = npacked == max_pods
        if cost_tiebreak and prices is not None:
            p_arr = encode_prices(prices, T).astype(np.int64)
            best_price = p_arr[tie].min()
            chosen = int(np.argmax(tie & (p_arr == best_price)))
        else:
            chosen = int(np.argmax(tie))
        packedv = k_all[:, chosen]
        # fast-forward validity (see ops/pack.py + docs/solver.md): every
        # packed shape must stay STRICTLY above maxfit through all repeats
        terms = np.where(packedv > 0,
                         (counts - maxfit - 1) // np.maximum(packedv, 1),
                         _INT32_MAX)
        q = int(max(1, 1 + terms.min()))
        counts = counts - q * packedv
        records.append((chosen, q, packedv))
    return _decode(enc, records, dropped, packables, max_instance_types)


def _decode(
    enc: EncodedProblem,
    records,
    dropped: np.ndarray,
    packables: Sequence[Packable],
    max_instance_types: int,
    options_fn=None,
) -> HostSolveResult:
    """Materialize packings: map per-shape counts back to pod ids and dedupe
    by instance-option set (the hash dedupe in packer.go:130-139).

    ``options_fn`` (same signature as :func:`instance_options`) lets the
    device-filter fused path substitute its feasibility-aware option walk
    over the universe type axis (ops/device_filter.py); it may raise to
    reject the decode — the caller self-heals to the host path."""
    queues = [list(p) for p in enc.shape_pods]
    heads = [0] * len(queues)
    packings: List[HostPacking] = []
    by_options = {}
    for chosen, qty, packedv in records:
        options = (options_fn or instance_options)(
            packables, chosen, max_instance_types)
        key = tuple(options)
        # iterate only the shapes this record touches: at high cardinality
        # (tens of thousands of shapes) a per-record full-S Python loop
        # would dominate the whole solve. Records carry either a dense
        # per-shape vector or an already-sparse [(shape, count), ...] list
        # (the native per-pod kernel's ABI).
        if isinstance(packedv, list):
            touched = packedv
        else:
            arr = np.asarray(packedv[:enc.num_shapes])
            touched = [(int(s), int(arr[s])) for s in np.flatnonzero(arr)]
        for _ in range(qty):
            node_pods: List[int] = []
            for s, n in touched:
                node_pods.extend(queues[s][heads[s]:heads[s] + n])
                heads[s] += n
            if key in by_options:
                main = by_options[key]
                main.node_quantity += 1
                main.pod_ids.append(node_pods)
            else:
                p = HostPacking(pod_ids=[node_pods], instance_type_indices=options)
                by_options[key] = p
                packings.append(p)
    unschedulable: List[int] = []
    for s in range(enc.num_shapes):
        n = int(dropped[s])
        if n:
            unschedulable.extend(queues[s][heads[s]:heads[s] + n])
            heads[s] += n
    return HostSolveResult(packings=packings, unschedulable=unschedulable)
