"""Native (C++) solver components: build-on-demand ctypes bridge.

The C++ kernel (ffd.cc) is one of three interchangeable executors over the
encoded problem — see the header comment there. It is compiled lazily with
the system toolchain into this package directory and loaded via ctypes (no
build step at install time, no binding framework); environments without a
C++ compiler transparently fall back to the Python/numpy executors.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger("karpenter.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "ffd.cc")
_LIB = os.path.join(_DIR, "_libktffd.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _compile() -> bool:
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", _LIB, _SRC]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warning("native build unavailable: %s", e)
        return False
    if proc.returncode != 0:
        log.warning("native build failed:\n%s", proc.stderr)
        return False
    return True


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.kt_ffd_pack.restype = ctypes.c_int64
    lib.kt_ffd_pack.argtypes = [
        i64p, i64p, i64p, i64p,                      # shapes, counts, totals, reserved0
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # S, T, R
        ctypes.c_int64, ctypes.c_int64,              # pods_unit, r_pods
        i64p, i64p, i64p, i64p,                      # out chosen/qty/packed/dropped
        ctypes.c_int64,                              # max_records
        i64p, ctypes.c_int64,                        # prices (nullable), cost_tiebreak
    ]
    lib.kt_ffd_pack_per_pod.restype = ctypes.c_int64
    lib.kt_ffd_pack_per_pod.argtypes = [
        i64p, i64p, i64p, i64p,                      # shapes, counts, totals, reserved0
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # S, T, R
        ctypes.c_int64, ctypes.c_int64,              # pods_unit, r_pods
        i64p, i64p, i64p, i64p, i64p,                # chosen/offsets/pair_shape/pair_count/dropped
        ctypes.c_int64, ctypes.c_int64,              # max_records, max_pairs
    ]
    return lib


def load() -> Optional[ctypes.CDLL]:
    """The compiled kernel, building it on first use; None when no toolchain
    is available (callers fall back to the Python executors)."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if not os.path.exists(_LIB) or (
                os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            if not _compile():
                _build_failed = True
                return None
        try:
            _lib = _bind(ctypes.CDLL(_LIB))
        except OSError as e:
            log.warning("native library load failed: %s", e)
            _build_failed = True
            return None
        return _lib


def available() -> bool:
    return load() is not None
