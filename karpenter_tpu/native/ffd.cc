// Native host-side FFD pack kernel.
//
// The framework's solver boundary has three interchangeable executors over
// the same encoded problem (karpenter_tpu/ops/encode.py):
//   1. the TPU kernel (ops/pack.py)          — the production hot path
//   2. this C++ kernel                        — fast host fallback
//   3. the per-pod Python oracle (host_ffd)   — Go-parity reference
// All three are differentially tested to the node count. The algorithm is
// the shape-level greedy with fast-forward: semantics of the reference Go
// packer's packWithLargestPod loop (packer.go:114-141,167-198) lifted from
// per-pod to per-shape, identical to ops/pack.py / models/ffd.solve_ffd_numpy.
//
// Inputs arrive pre-scaled (encode()'s GCD scaling keeps every value within
// int32), so int64 arithmetic here cannot overflow: k*shape <= 2^31 * 2^31.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {
constexpr int64_t kInf = INT64_C(2147483647);  // matches _INT32_MAX fast-forward
}

extern "C" {

// Packs counts[s] pods of shapes[s] onto instances of types totals[t].
// Returns the number of (chosen, qty, packed[s]) records written, or -1 if
// max_records was too small. All matrices are row-major.
//
//   shapes    (S, R)  per-shape reserve vector (pods dim includes the +1)
//   counts    (S,)    pods per shape; CONSUMED (copied internally)
//   totals    (T, R)  instance capacity, ascending packable order
//   reserved0 (T, R)  overhead + daemons already reserved
//   pods_unit         one pod in device units on the pods dimension
//   r_pods            index of the pods dimension
//
// Outputs:
//   out_chosen  (max_records,)     instance-type index per record
//   out_qty     (max_records,)     identical nodes for this record
//   out_packed  (max_records, S)   pods-per-shape on each such node
//   out_dropped (S,)               unpackable pods per shape
//   prices    (T,) effective micro-$/h per type, or nullptr; with
//             cost_tiebreak != 0 the cheapest max-pods type wins the tie
//             (capacity order on price ties) — beyond-reference cost mode.
int64_t kt_ffd_pack(
    const int64_t* shapes, const int64_t* counts_in,
    const int64_t* totals, const int64_t* reserved0,
    int64_t S, int64_t T, int64_t R, int64_t pods_unit, int64_t r_pods,
    int64_t* out_chosen, int64_t* out_qty, int64_t* out_packed,
    int64_t* out_dropped, int64_t max_records,
    const int64_t* prices, int64_t cost_tiebreak) {
  std::vector<int64_t> counts(counts_in, counts_in + S);
  std::vector<int64_t> dropped(S, 0);

  // maxfit[s]: most pods of shape s any EMPTY instance fits — the
  // fast-forward validity bound (docs/solver.md).
  std::vector<int64_t> maxfit(S, 0);
  for (int64_t s = 0; s < S; ++s) {
    int64_t best = 0;
    for (int64_t t = 0; t < T; ++t) {
      int64_t k = kInf;
      for (int64_t r = 0; r < R; ++r) {
        const int64_t need = shapes[s * R + r];
        if (need > 0) {
          const int64_t avail = totals[t * R + r] - reserved0[t * R + r];
          const int64_t kr = avail >= 0 ? avail / need : 0;
          if (kr < k) k = kr;
        }
      }
      if (k > best) best = k;
    }
    maxfit[s] = best;
  }


  std::vector<int64_t> reserved(T * R);
  std::vector<char> stopped(T);
  std::vector<int64_t> npacked(T);
  std::vector<int64_t> k_all(S * T);
  std::vector<int64_t> smallest_fits(R);

  int64_t n_records = 0;
  for (;;) {
    int64_t largest = -1, smallest = -1;
    for (int64_t s = 0; s < S; ++s) {
      if (counts[s] > 0) {
        if (largest < 0) largest = s;
        smallest = s;
      }
    }
    if (largest < 0) break;

    for (int64_t r = 0; r < R; ++r) {
      int64_t v = shapes[smallest * R + r];
      if (r == r_pods) v -= pods_unit;
      smallest_fits[r] = v > 0 ? v : 0;
    }

    std::memcpy(reserved.data(), reserved0, sizeof(int64_t) * T * R);
    std::fill(stopped.begin(), stopped.end(), 0);
    std::fill(npacked.begin(), npacked.end(), 0);
    std::fill(k_all.begin(), k_all.end(), 0);

    // One pass largest→smallest shape; per type, pack as many as fit. A type
    // "stops" at its first failure once it is full-for-the-smallest-shape or
    // still empty — the early-exit upper bound of packer.go:167-198.
    for (int64_t s = 0; s < S; ++s) {
      if (counts[s] == 0) continue;
      for (int64_t t = 0; t < T; ++t) {
        if (stopped[t]) continue;
        int64_t k = kInf;
        for (int64_t r = 0; r < R; ++r) {
          const int64_t need = shapes[s * R + r];
          if (need > 0) {
            const int64_t avail = totals[t * R + r] - reserved[t * R + r];
            const int64_t kr = avail >= 0 ? avail / need : 0;
            if (kr < k) k = kr;
          }
        }
        if (k > counts[s]) k = counts[s];
        if (k < 0) k = 0;
        const bool failure = k < counts[s];
        for (int64_t r = 0; r < R; ++r) reserved[t * R + r] += k * shapes[s * R + r];
        bool full = false;
        for (int64_t r = 0; r < R; ++r) {
          if (totals[t * R + r] > 0 &&
              reserved[t * R + r] + smallest_fits[r] >= totals[t * R + r]) {
            full = true;
            break;
          }
        }
        npacked[t] += k;
        if (failure && (full || npacked[t] == 0)) stopped[t] = 1;
        k_all[s * T + t] = k;
      }
    }

    const int64_t max_pods = npacked[T - 1];
    if (max_pods == 0) {
      dropped[largest] += counts[largest];
      counts[largest] = 0;
      continue;
    }
    int64_t chosen = 0;
    while (npacked[chosen] != max_pods) ++chosen;
    if (cost_tiebreak && prices != nullptr) {
      for (int64_t t = chosen + 1; t < T; ++t) {
        if (npacked[t] == max_pods && prices[t] < prices[chosen]) chosen = t;
      }
    }

    // fast-forward: emit q identical nodes at once. Validity (ops/pack.py,
    // proof in docs/solver.md): every packed shape must stay STRICTLY
    // above maxfit through all repeated rounds — that keeps every type's
    // clip inactive (so all simulated fills and the tie-break repeat) and
    // every failure flag strict, which is what arms the is_full_for early
    // exit. The final round where equality would be reached runs live.
    int64_t min_terms = kInf;
    for (int64_t s = 0; s < S; ++s) {
      const int64_t kv = k_all[s * T + chosen];
      if (kv > 0) {
        const int64_t diff = counts[s] - maxfit[s] - 1;
        // floor division to match numpy
        int64_t q = diff / kv;
        if (diff % kv != 0 && ((diff < 0) != (kv < 0))) --q;
        if (q < min_terms) min_terms = q;
      }
    }
    int64_t q = 1 + min_terms;
    if (q < 1) q = 1;
    if (n_records >= max_records) return -1;
    out_chosen[n_records] = chosen;
    out_qty[n_records] = q;
    for (int64_t s = 0; s < S; ++s) {
      const int64_t kv = k_all[s * T + chosen];
      out_packed[n_records * S + s] = kv;
      counts[s] -= q * kv;
    }
    ++n_records;
  }

  std::memcpy(out_dropped, dropped.data(), sizeof(int64_t) * S);
  return n_records;
}

// Per-POD Go-semantics oracle: a direct transcription of the reference
// packer's loop (packer.go:109-141 pack, packer.go:167-198
// packWithLargestPod, packable.go:111-130 pack_one) — NOT the shape-level
// greedy above. It exists so benchmark parity at 50k pods is asserted
// against genuinely per-pod semantics (the Python per-pod oracle,
// solver/host_ffd.py, is too slow beyond ~5k pods).
//
// Pods are implicit: the descending per-pod sort order the Go packer uses
// (packer.go:100-108, extended to the full resource vector as in
// host_ffd.pack) equals the encoded shape order expanded by counts, since
// encode() sorts shapes by the same descending key and pods of equal shape
// are interchangeable. Within one pack_one pass, after a pod of shape s
// fails to reserve, every later pod of the same shape fails identically
// (reservations only grow and is_full_for reads unchanged state), so the
// skip-and-continue quirk (packable.go:111-130) collapses to skip-to-next-
// shape without changing semantics.
//
// Outputs one record PER NODE (qty is always 1), in SPARSE form: record i
// covers pairs [out_offsets[i], out_offsets[i+1]) of
// (out_pair_shape, out_pair_count). A dense (records × S) matrix would be
// O(pods × S) at high cardinality (50k nodes × 50k shapes ≈ 20 GB); the
// pair total is instead bounded by Σ pods-per-node ≤ pods, so callers
// allocate max_pairs = pods + S and never reallocate. Returns the record
// count, or -1 if either capacity was too small.
int64_t kt_ffd_pack_per_pod(
    const int64_t* shapes, const int64_t* counts_in,
    const int64_t* totals, const int64_t* reserved0,
    int64_t S, int64_t T, int64_t R, int64_t pods_unit, int64_t r_pods,
    int64_t* out_chosen, int64_t* out_offsets,
    int64_t* out_pair_shape, int64_t* out_pair_count,
    int64_t* out_dropped, int64_t max_records, int64_t max_pairs) {
  std::vector<int64_t> counts(counts_in, counts_in + S);
  std::vector<int64_t> dropped(S, 0);
  std::vector<int64_t> reserved(R);
  std::vector<int64_t> smallest_raw(R);
  // per-pack_one (shape, pods) pairs — only touched shapes, so commit cost
  // is O(pods-per-node), independent of S
  std::vector<std::pair<int64_t, int64_t>> pairs, chosen_pairs;

  // Active-shape skip list: next[s] = first shape index >= s with
  // counts > 0 (S terminates). Consumed shapes are unlinked lazily with
  // path compression during traversal, so pack_one visits only live
  // shapes — at high cardinality (tens of thousands of distinct shapes) a
  // plain counts[s]==0 skip scan would cost O(S) per type per node and
  // dominate everything.
  std::vector<int64_t> next(S + 1);
  for (int64_t s = 0; s <= S; ++s) next[s] = s;
  auto advance = [&](int64_t s) -> int64_t {
    int64_t cur = s;
    while (cur < S && counts[cur] == 0) {
      int64_t hop = next[cur];
      cur = (hop > cur) ? hop : cur + 1;
    }
    if (cur > s) next[s] = cur;  // compress for the next traversal
    return cur;
  };

  // pack_one (packable.go:111-130) of the remaining pod list onto type t.
  // Returns pods packed; fills `pairs` with (shape, packed>0) entries.
  // smallest_raw is the LAST pod's raw requests (no implicit pods:1) for
  // the is_full_for early exit (packable.go:145-155).
  //
  // Failure-run jump: shapes are sorted descending LEXICOGRAPHICALLY with
  // CPU as the primary dimension (encode() mirrors host_ffd.pack's sort),
  // so once a pod fails and the pack continues (skip-and-continue,
  // packable.go:128-130), every following shape with cpu > free_cpu must
  // also fail its fit test — and since `reserved` is unchanged across a
  // run of consecutive failures, is_full_for is CONSTANT over the run
  // (checked once, at the run's first failure). Binary-searching past the
  // cpu-infeasible prefix therefore preserves semantics exactly while
  // cutting the wandering tail at high shape cardinality from O(S) fit
  // tests to O(log S) per free-capacity level.
  auto cpu_jump = [&](int64_t s, int64_t free_cpu) -> int64_t {
    // smallest index > s with shapes[idx][0] <= free_cpu (cpu is dim 0,
    // non-increasing); returns S when none
    int64_t lo = s + 1, hi = S;
    while (lo < hi) {
      const int64_t mid = lo + (hi - lo) / 2;
      if (shapes[mid * R + 0] > free_cpu) lo = mid + 1; else hi = mid;
    }
    return lo;
  };

  auto pack_one = [&](int64_t t) -> int64_t {
    for (int64_t r = 0; r < R; ++r) reserved[r] = reserved0[t * R + r];
    pairs.clear();
    int64_t total_packed = 0;
    for (int64_t s = advance(0); s < S;) {
      int64_t got = 0;
      bool stop = false, give_up = false, failed = false;
      for (int64_t j = 0; j < counts[s]; ++j) {
        bool fits = true;
        for (int64_t r = 0; r < R; ++r) {
          if (reserved[r] + shapes[s * R + r] > totals[t * R + r]) {
            fits = false;
            break;
          }
        }
        if (fits) {
          for (int64_t r = 0; r < R; ++r) reserved[r] += shapes[s * R + r];
          ++got;
          ++total_packed;
          continue;
        }
        // is_full_for(smallest remaining pod): >= against any nonzero total
        for (int64_t r = 0; r < R; ++r) {
          if (totals[t * R + r] != 0 &&
              reserved[r] + smallest_raw[r] >= totals[t * R + r]) {
            stop = true;  // rest unpacked (early exit)
            break;
          }
        }
        if (!stop && total_packed == 0) give_up = true;  // empty pack
        failed = true;
        break;  // this pod unpacked; later same-shape pods fail identically
      }
      if (got > 0) pairs.emplace_back(s, got);
      if (give_up) return 0;
      if (stop) return total_packed;
      if (failed) {
        // skip the cpu-infeasible run in O(log S); memory-bound failures
        // inside the jump target region still step shape by shape
        const int64_t free_cpu = totals[t * R + 0] - reserved[0];
        const int64_t tgt = cpu_jump(s, free_cpu);
        s = advance(tgt > s + 1 ? tgt : s + 1);
      } else {
        s = advance(s + 1);
      }
    }
    return total_packed;
  };

  int64_t n_records = 0, n_pairs = 0;
  for (;;) {
    const int64_t largest = advance(0);
    if (largest >= S) break;
    int64_t smallest = largest;
    for (int64_t s = largest; s < S; s = advance(s + 1)) smallest = s;
    for (int64_t r = 0; r < R; ++r) {
      int64_t v = shapes[smallest * R + r];
      if (r == r_pods) v -= pods_unit;
      smallest_raw[r] = v;
    }

    // probe the LARGEST type for the max-pods upper bound (packer.go:170)
    const int64_t max_pods = pack_one(T - 1);
    if (max_pods == 0) {
      // drop the single largest pod (packer.go:124-128)
      dropped[largest] += 1;
      counts[largest] -= 1;
      continue;
    }
    // first (smallest) type achieving the bound wins (packer.go:174-183)
    int64_t chosen = -1;
    for (int64_t t = 0; t < T; ++t) {
      if (pack_one(t) == max_pods) {
        chosen = t;
        chosen_pairs = pairs;
        break;
      }
    }
    if (chosen < 0) {  // unreachable: T-1 achieved max_pods above
      chosen = T - 1;
      pack_one(T - 1);
      chosen_pairs = pairs;
    }

    if (n_records >= max_records) return -1;
    if (n_pairs + static_cast<int64_t>(chosen_pairs.size()) > max_pairs)
      return -1;
    out_chosen[n_records] = chosen;
    out_offsets[n_records] = n_pairs;
    for (const auto& [s, got] : chosen_pairs) {
      out_pair_shape[n_pairs] = s;
      out_pair_count[n_pairs] = got;
      ++n_pairs;
      counts[s] -= got;
    }
    ++n_records;
  }
  out_offsets[n_records] = n_pairs;

  std::memcpy(out_dropped, dropped.data(), sizeof(int64_t) * S);
  return n_records;
}

}  // extern "C"
