"""Observability layer: span tracing (`obs.trace`) + black-box flight
recorder (`obs.flight`).

Kept import-light: nothing here may import jax, controllers, or the
solver — the hot path imports *us* on every window.
"""

from karpenter_tpu.obs import flight, trace  # noqa: F401
