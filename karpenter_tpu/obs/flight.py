"""Black-box flight recorder.

A bounded in-memory ring of recent spans + trigger events that is ALWAYS
on (it costs a deque append), plus an optional dump-to-disk: when a dump
directory is configured (``configure(dir=...)`` or the
``KARPENTER_FLIGHT_DIR`` env var), each trigger writes one tagged JSON
snapshot of the ring — the last thing the system was doing when it went
wrong.

Triggers (hooked at the source, see ISSUE 9):

- ``watchdog-trip`` — any of the three `_DeviceWatchdog` trip branches
  in ``solver/solve.py`` (this is also the instant the breaker opens).
- ``pressure-l3`` — `PressureMonitor.evaluate()` rising into L3.
- ``chaos-fault`` — a seeded fault firing in ``chaos/inject.py``.
- ``slo-burn`` — the burn-rate sentinel in ``obs/slo.py`` finding a
  band's fast AND slow windows past their burn thresholds; tagged with
  the offending band, stage, burn rate, and a sample slow window's
  trace id.

Dumps are rate-limited (``min_interval_s``) because tier-1 tests trip
watchdogs and fire chaos constantly; with no directory configured the
recorder never touches the filesystem. ``slo-burn`` is limited on its
own clock: a burn storm produces exactly one dump per interval without
starving (or being starved by) a concurrent watchdog/chaos dump.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from karpenter_tpu.obs import trace

_RING_CAP = 1024
_LOCK = threading.Lock()
_EVENTS: deque = deque(maxlen=_RING_CAP)   # trigger + span records
_DUMPS: deque = deque(maxlen=32)           # paths written this process
_TRIPS: deque = deque(maxlen=256)          # trigger records only

_DIR: Optional[str] = os.environ.get("KARPENTER_FLIGHT_DIR") or None
_MIN_INTERVAL_S = 5.0
_LAST_DUMP = 0.0
_LAST_DUMP_SLO = 0.0  # independent clock for the slo-burn trigger
_TRIP_COUNT = 0


def _note_span(sp: Any) -> None:
    # sink registered with obs.trace: finished spans feed the ring when
    # tracing is enabled (the ring itself is always available)
    with _LOCK:
        _EVENTS.append({"kind": "span", "name": sp.name,
                        "trace_id": sp.trace_id, "span_id": sp.span_id,
                        "t0": sp.t0, "t1": sp.t1,
                        "tags": dict(sp.tags) if sp.tags else None})


trace.add_sink(_note_span)


def configure(dir: Optional[str] = None,
              min_interval_s: Optional[float] = None) -> None:
    global _DIR, _MIN_INTERVAL_S
    if dir is not None:
        _DIR = dir or None
    if min_interval_s is not None:
        _MIN_INTERVAL_S = float(min_interval_s)


def trip(trigger: str, **tags: Any) -> Optional[str]:
    """Record a trigger event; write a tagged JSON dump if a directory is
    configured and the rate limit allows. Returns the dump path (or
    None). The active trace id, if any, rides along automatically so the
    dump names the poisoned window."""
    global _LAST_DUMP, _LAST_DUMP_SLO, _TRIP_COUNT
    tid = trace.current_trace_id()
    if tid is not None and "trace_id" not in tags:
        tags["trace_id"] = tid
    rec = {"kind": "trigger", "trigger": trigger, "tags": tags,
           "wall": time.time(), "t": time.perf_counter()}
    with _LOCK:
        _TRIP_COUNT += 1
        _EVENTS.append(rec)
        _TRIPS.append(rec)
        if _DIR is None:
            return None
        now = time.monotonic()
        if trigger == "slo-burn":
            if now - _LAST_DUMP_SLO < _MIN_INTERVAL_S:
                return None
            _LAST_DUMP_SLO = now
        else:
            if now - _LAST_DUMP < _MIN_INTERVAL_S:
                return None
            _LAST_DUMP = now
        events = list(_EVENTS)
        seq = _TRIP_COUNT
    return _write_dump(trigger, tags, events, seq)


def _write_dump(trigger: str, tags: Dict[str, Any],
                events: List[Dict[str, Any]], seq: int) -> Optional[str]:
    assert _DIR is not None
    payload = {"trigger": trigger, "tags": tags, "wall": time.time(),
               "events": events, "spans": trace.snapshot(limit=2048),
               "tracer": trace.state()}
    name = f"flight-{seq:05d}-{trigger}.json"
    path = os.path.join(_DIR, name)
    try:
        os.makedirs(_DIR, exist_ok=True)
        with open(path, "w") as f:
            json.dump(payload, f)
    except OSError:
        return None
    with _LOCK:
        _DUMPS.append(path)
    return path


def recent(n: int = 50) -> List[Dict[str, Any]]:
    """Most recent trigger records (newest last)."""
    with _LOCK:
        return list(_TRIPS)[-n:]


def recent_dumps() -> List[str]:
    with _LOCK:
        return list(_DUMPS)


def state() -> Dict[str, Any]:
    """Cheap status block for /debug/vars."""
    with _LOCK:
        last = _TRIPS[-1] if _TRIPS else None
        return {"dir": _DIR, "ring_events": len(_EVENTS),
                "trips": _TRIP_COUNT, "dumps_written": len(_DUMPS),
                "last_trigger": (last["trigger"] if last else None),
                "min_interval_s": _MIN_INTERVAL_S}


def reset() -> None:
    """Tests: clear ring, trip history, and rate-limit state (the dump
    directory setting is left alone — pass configure() to change it)."""
    global _LAST_DUMP, _LAST_DUMP_SLO, _TRIP_COUNT
    with _LOCK:
        _EVENTS.clear()
        _TRIPS.clear()
        _DUMPS.clear()
        _LAST_DUMP = 0.0
        _LAST_DUMP_SLO = 0.0
        _TRIP_COUNT = 0
