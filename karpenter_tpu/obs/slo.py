"""Per-pod SLO engine: mergeable latency digests + burn-rate sentinel.

The replay harness proves band-differentiated p99 bind latency after the
fact, from exact per-pod lists held in replay memory. This module makes
the same answer available *continuously* and with *bounded* memory:

- :class:`Digest` — a DDSketch-style relative-error quantile sketch.
  Log-spaced buckets with ratio ``gamma = (1+alpha)/(1-alpha)`` guarantee
  every quantile estimate is within ``alpha`` relative error of the true
  sample; memory is capped at ``max_bins`` buckets (lowest buckets
  collapse first, preserving tail accuracy). Record is O(1); two digests
  merge by adding bucket counts, so sketches combine across shard
  workers and across the ``BatchHandle`` dispatch/fetch split exactly
  like span context does.
- :class:`SloEngine` — a lock-striped map of (band × stage) cells, one
  digest each. Stages follow the pod lifecycle: ``intake``
  (enqueue → window close), ``schedule`` (close → solve dispatch),
  ``solve`` (dispatch → fetch), ``bind`` (fetch → bound), and ``e2e``
  (enqueue → bound).
- :class:`BurnSentinel` — multi-window burn-rate alerting per band: the
  fraction of ``e2e`` samples (and intake sheds) breaching the band's
  latency objective, over a fast (1m) and a slow (30m) window, divided
  by the error budget. When both windows burn past their thresholds the
  sentinel trips the flight recorder (``slo-burn``), flags the band for
  readyz, and keeps gauges updated.

Window identity rides the same carryable-context pattern as
``obs.trace``: :func:`use_marks` reinstates a window's
:class:`WindowMarks` (close timestamp + per-pod band/intake metadata) on
whichever thread fetches the batch.

This module registers no metrics itself — the ``karpenter_slo_*`` series
live in ``karpenter_tpu.metrics.slo`` (imported lazily on publish) so
the metrics lint's registration-site scan stays closed.
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from karpenter_tpu.obs import trace

STAGES = ("intake", "schedule", "solve", "bind", "e2e")

_N_STRIPES = 8


# ---------------------------------------------------------------------------
# Digest
# ---------------------------------------------------------------------------


class Digest:
    """DDSketch-style relative-error quantile sketch.

    A positive value ``v`` lands in bucket ``ceil(log(v)/log(gamma))``;
    the bucket's representative value ``2*gamma^i/(gamma+1)`` (the
    geometric midpoint) is within ``alpha`` relative error of every
    sample in the bucket. Values at or below ``MIN_VALUE`` share a zero
    bucket. Memory is bounded: past ``max_bins`` buckets the two lowest
    collapse into one, trading low-quantile accuracy for tail fidelity
    (the tail is what SLOs read)."""

    __slots__ = ("alpha", "gamma", "_inv_lg", "max_bins", "counts",
                 "n", "total", "vmin", "vmax", "zero")

    MIN_VALUE = 1e-6

    def __init__(self, alpha: float = 0.008, max_bins: int = 1024) -> None:
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        self.alpha = alpha
        self.gamma = (1.0 + alpha) / (1.0 - alpha)
        self._inv_lg = 1.0 / math.log(self.gamma)
        self.max_bins = max_bins
        self.counts: Dict[int, int] = {}
        self.n = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self.zero = 0

    # -- record -------------------------------------------------------------
    def record(self, v: float) -> None:
        self.n += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v
        if v <= self.MIN_VALUE:
            self.zero += 1
            return
        idx = math.ceil(math.log(v) * self._inv_lg)
        counts = self.counts
        counts[idx] = counts.get(idx, 0) + 1
        if len(counts) > self.max_bins:
            self._collapse()

    def record_n(self, v: float, count: int) -> None:
        """Record ``count`` identical samples in O(1) — a chunk of pods
        sharing one schedule/solve/bind duration is one bucket add."""
        if count <= 0:
            return
        self.n += count
        self.total += v * count
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v
        if v <= self.MIN_VALUE:
            self.zero += count
            return
        idx = math.ceil(math.log(v) * self._inv_lg)
        counts = self.counts
        counts[idx] = counts.get(idx, 0) + count
        if len(counts) > self.max_bins:
            self._collapse()

    def _collapse(self) -> None:
        """Fold the lowest bucket upward until within budget — tail
        buckets (what p99 reads) are never touched."""
        while len(self.counts) > self.max_bins:
            keys = sorted(self.counts)
            lo, nxt = keys[0], keys[1]
            self.counts[nxt] += self.counts.pop(lo)

    # -- merge --------------------------------------------------------------
    def merge(self, other: "Digest") -> "Digest":
        """Fold ``other`` into self (bucket-count addition). Requires the
        same alpha so bucket indices line up."""
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError("cannot merge digests with different alpha")
        for idx, c in other.counts.items():
            self.counts[idx] = self.counts.get(idx, 0) + c
        if len(self.counts) > self.max_bins:
            self._collapse()
        self.n += other.n
        self.total += other.total
        self.zero += other.zero
        if other.vmin < self.vmin:
            self.vmin = other.vmin
        if other.vmax > self.vmax:
            self.vmax = other.vmax
        return self

    def copy(self) -> "Digest":
        d = Digest(self.alpha, self.max_bins)
        d.counts = dict(self.counts)
        d.n, d.total, d.zero = self.n, self.total, self.zero
        d.vmin, d.vmax = self.vmin, self.vmax
        return d

    # -- read ---------------------------------------------------------------
    def quantile(self, q: float) -> float:
        """Estimate the q-quantile using the same rank convention as the
        replay's exact-list report (``vs[min(n-1, int(n*q))]``), clamped
        to the exact observed [min, max]."""
        if self.n == 0:
            return 0.0
        rank = min(self.n - 1, int(self.n * q))
        if rank < self.zero:
            return max(0.0, min(self.vmin, self.MIN_VALUE))
        cum = self.zero
        for idx in sorted(self.counts):
            cum += self.counts[idx]
            if cum > rank:
                est = 2.0 * self.gamma ** idx / (self.gamma + 1.0)
                return min(self.vmax, max(self.vmin, est))
        return self.vmax

    def bins(self) -> int:
        return len(self.counts)

    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0

    def report(self) -> Dict[str, Any]:
        """The shape the replay report (and its verdict gate) reads."""
        if self.n == 0:
            return {"p50": 0.0, "p99": 0.0, "max": 0.0, "n": 0}
        return {"p50": round(self.quantile(0.50), 4),
                "p99": round(self.quantile(0.99), 4),
                "max": round(self.vmax, 4), "n": self.n}

    # -- serialization ------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {"alpha": self.alpha, "max_bins": self.max_bins,
                "counts": {str(k): v for k, v in self.counts.items()},
                "n": self.n, "total": self.total, "zero": self.zero,
                "min": (None if self.n == 0 else self.vmin),
                "max": (None if self.n == 0 else self.vmax)}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Digest":
        dg = cls(d.get("alpha", 0.008), d.get("max_bins", 1024))
        dg.counts = {int(k): int(v) for k, v in d.get("counts", {}).items()}
        dg.n = int(d.get("n", 0))
        dg.total = float(d.get("total", 0.0))
        dg.zero = int(d.get("zero", 0))
        dg.vmin = math.inf if d.get("min") is None else float(d["min"])
        dg.vmax = -math.inf if d.get("max") is None else float(d["max"])
        return dg

    @classmethod
    def merged(cls, digests: Iterable["Digest"]) -> "Digest":
        out: Optional[Digest] = None
        for d in digests:
            if out is None:
                out = d.copy()
            else:
                out.merge(d)
        return out if out is not None else cls()


# ---------------------------------------------------------------------------
# Engine: lock-striped (band × stage) cells
# ---------------------------------------------------------------------------


class SloEngine:
    """Fixed-memory per-cell latency accounting. ``record`` hashes the
    (band, stage) key onto one of ``stripes`` locks so shard workers
    stamping different cells never contend."""

    def __init__(self, alpha: float = 0.008, max_bins: int = 1024,
                 stripes: int = _N_STRIPES) -> None:
        self.alpha = alpha
        self.max_bins = max_bins
        self._stripes = [threading.Lock() for _ in range(stripes)]
        self._make_lock = threading.Lock()
        # key -> (stripe lock, digest): one dict hit on the hot path;
        # the same key always maps to the same stripe lock
        self._cells: Dict[Tuple[str, str], Tuple[Any, Digest]] = {}

    def _cell(self, key: Tuple[str, str]) -> Tuple[Any, Digest]:
        with self._make_lock:
            ent = self._cells.get(key)
            if ent is None:
                lock = self._stripes[hash(key) % len(self._stripes)]
                ent = self._cells[key] = (lock, Digest(self.alpha,
                                                       self.max_bins))
            return ent

    def record(self, band: str, stage: str, seconds: float,
               count: int = 1) -> None:
        key = (band, stage)
        ent = self._cells.get(key)
        if ent is None:
            ent = self._cell(key)
        lock, cell = ent
        with lock:
            if count == 1:
                cell.record(seconds)
            else:
                cell.record_n(seconds, count)

    def digest(self, band: str, stage: str) -> Optional[Digest]:
        """Copy of one cell's digest (safe to merge/read lock-free)."""
        ent = self._cells.get((band, stage))
        if ent is None:
            return None
        lock, cell = ent
        with lock:
            return cell.copy()

    def merge_from(self, other: "SloEngine") -> None:
        """Fold another engine's cells in — shard aggregation."""
        for (band, stage) in list(other._cells):
            d = other.digest(band, stage)
            if d is None or d.n == 0:
                continue
            lock, cell = self._cell((band, stage))
            with lock:
                cell.merge(d)

    def stage_digest(self, stage: str) -> Digest:
        """All bands merged for one stage — what traceview renders."""
        return Digest.merged(
            d for d in (self.digest(b, s) for (b, s) in list(self._cells)
                        if s == stage) if d is not None)

    # -- introspection ------------------------------------------------------
    def records_total(self) -> int:
        return sum(d.n for d in (self.digest(b, s)
                                 for (b, s) in list(self._cells))
                   if d is not None)

    def cell_count(self) -> int:
        return len(self._cells)

    def total_bins(self) -> int:
        return sum(d.bins() for d in (self.digest(b, s)
                                      for (b, s) in list(self._cells))
                   if d is not None)

    def snapshot(self) -> Dict[str, Any]:
        """Quantile summary per cell plus per-stage all-band merges."""
        cells: Dict[str, Dict[str, Any]] = {}
        stages_present = set()
        for (band, stage) in sorted(self._cells):
            d = self.digest(band, stage)
            if d is None:
                continue
            cells.setdefault(band, {})[stage] = d.report()
            stages_present.add(stage)
        stages = {s: self.stage_digest(s).report()
                  for s in STAGES if s in stages_present}
        return {"alpha": self.alpha, "max_bins": self.max_bins,
                "cells": cells, "stages": stages,
                "records": self.records_total(),
                "total_bins": self.total_bins()}

    def reset(self) -> None:
        with self._make_lock:
            for lk in self._stripes:
                lk.acquire()
            try:
                self._cells.clear()
            finally:
                for lk in self._stripes:
                    lk.release()


# ---------------------------------------------------------------------------
# Objectives + burn-rate sentinel
# ---------------------------------------------------------------------------


class Objective:
    """Latency objective for one band: ``target`` fraction of pods bound
    within ``threshold_s`` (measured on the ``e2e`` stage; intake sheds
    count as breaches — a shed pod is burning budget by definition)."""

    __slots__ = ("threshold_s", "target", "stage")

    def __init__(self, threshold_s: float, target: float = 0.99,
                 stage: str = "e2e") -> None:
        self.threshold_s = float(threshold_s)
        self.target = float(target)
        self.stage = stage

    def to_dict(self) -> Dict[str, Any]:
        return {"threshold_s": self.threshold_s, "target": self.target,
                "stage": self.stage}


def default_objectives() -> Dict[str, Objective]:
    """Generous production defaults for the cohort bands (the bands the
    replay gate reads). Low/besteffort carry no objective: the pressure
    ladder sheds them by design and that must not read as an SLO burn."""
    return {"system-critical": Objective(30.0),
            "high": Objective(45.0),
            "default": Objective(60.0)}


class BurnSentinel:
    """Fast/slow-window burn-rate evaluation per band.

    Samples land in coarse time buckets (``BUCKET_S``); the ring is
    bounded by the slow window, so memory is O(bands × buckets). Burn
    rate = (breach fraction over the window) / (1 − target). A band is
    *burning* when the fast window exceeds ``fast_burn`` AND the slow
    window exceeds ``slow_burn`` (the classic multi-window rule: fast
    catches the spike, slow filters the blip)."""

    BUCKET_S = 5.0

    def __init__(self, objectives: Optional[Dict[str, Objective]] = None,
                 fast_window_s: float = 60.0, slow_window_s: float = 1800.0,
                 fast_burn: float = 6.0, slow_burn: float = 1.0,
                 trip_interval_s: float = 30.0,
                 timefunc=time.monotonic) -> None:
        self.objectives = (objectives if objectives is not None
                           else default_objectives())
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self.fast_burn = fast_burn
        self.slow_burn = slow_burn
        self.trip_interval_s = trip_interval_s
        self._time = timefunc
        self._lock = threading.Lock()
        max_buckets = int(slow_window_s / self.BUCKET_S) + 2
        # band -> deque of [bucket_key, total, breaches]
        self._rings: Dict[str, deque] = {}
        self._max_buckets = max_buckets
        self._sample_trace: Dict[str, Optional[str]] = {}
        self._burning: Dict[str, Dict[str, Any]] = {}
        self._last_trip: Dict[str, float] = {}
        self._last_trip_tags: Optional[Dict[str, Any]] = None
        self._trips_total = 0
        self._breaches_total = 0

    # -- feed ---------------------------------------------------------------
    def observe(self, band: str, seconds: Optional[float] = None,
                shed: bool = False) -> None:
        obj = self.objectives.get(band)
        if obj is None:
            return
        breach = shed or (seconds is not None and seconds > obj.threshold_s)
        now = self._time()
        bucket = int(now // self.BUCKET_S)
        with self._lock:
            ring = self._rings.get(band)
            if ring is None:
                ring = self._rings[band] = deque(maxlen=self._max_buckets)
            if not ring or ring[-1][0] != bucket:
                ring.append([bucket, 0, 0])
            ring[-1][1] += 1
            if breach:
                ring[-1][2] += 1
                self._breaches_total += 1
                self._sample_trace[band] = trace.current_trace_id()
        if breach:
            self._note_breach(band, obj, seconds, shed)

    def _note_breach(self, band: str, obj: Objective,
                     seconds: Optional[float], shed: bool) -> None:
        try:
            from karpenter_tpu.metrics import slo as mslo
            mslo.SLO_BREACHES.inc(band=band, stage=obj.stage)
            if seconds is not None:
                mslo.SLO_BREACH_LATENCY.observe(
                    seconds, exemplar=self._sample_trace.get(band),
                    band=band)
        except Exception:
            pass

    # -- evaluate -----------------------------------------------------------
    def _window_burn(self, ring: deque, window_s: float, now: float,
                     budget: float) -> Tuple[float, int, int]:
        cutoff = int((now - window_s) // self.BUCKET_S)
        total = breaches = 0
        for bucket, t, b in ring:
            if bucket >= cutoff:
                total += t
                breaches += b
        if total == 0:
            return 0.0, 0, 0
        return (breaches / total) / budget, total, breaches

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Burn rates per band; updates the burning set, trips the
        flight recorder on sustained burn, publishes gauges."""
        now = self._time() if now is None else now
        out: Dict[str, Any] = {}
        to_trip: List[Tuple[str, Dict[str, Any]]] = []
        with self._lock:
            for band, obj in self.objectives.items():
                ring = self._rings.get(band)
                if not ring:
                    continue
                budget = max(1e-9, 1.0 - obj.target)
                fast, fn, fb = self._window_burn(
                    ring, self.fast_window_s, now, budget)
                slow, sn, sb = self._window_burn(
                    ring, self.slow_window_s, now, budget)
                burning = fast >= self.fast_burn and slow >= self.slow_burn
                out[band] = {"fast_burn": round(fast, 3),
                             "slow_burn": round(slow, 3),
                             "burning": burning,
                             "fast_samples": fn, "fast_breaches": fb,
                             "slow_samples": sn, "slow_breaches": sb}
                if burning:
                    rec = self._burning.setdefault(band, {"since": now})
                    rec["last"] = now
                    last = self._last_trip.get(band, -math.inf)
                    if now - last >= self.trip_interval_s:
                        self._last_trip[band] = now
                        self._trips_total += 1
                        tags = {"band": band, "stage": obj.stage,
                                "burn_rate": round(fast, 2),
                                "slow_burn": round(slow, 2),
                                "objective_s": obj.threshold_s,
                                "target": obj.target,
                                "sample_trace_id":
                                    self._sample_trace.get(band)}
                        self._last_trip_tags = tags
                        to_trip.append((band, tags))
                else:
                    self._burning.pop(band, None)
        for _band, tags in to_trip:
            try:
                from karpenter_tpu.obs import flight
                flight.trip("slo-burn", **tags)
            except Exception:
                pass
        self._publish(out)
        return out

    def _publish(self, burn: Dict[str, Any]) -> None:
        try:
            from karpenter_tpu.metrics import slo as mslo
        except Exception:
            return
        for band, rec in burn.items():
            mslo.SLO_BURN_RATE.set(rec["fast_burn"], band=band,
                                   window="fast")
            mslo.SLO_BURN_RATE.set(rec["slow_burn"], band=band,
                                   window="slow")
        mslo.SLO_BURNING_BANDS.set(
            sum(1 for r in burn.values() if r["burning"]))
        mslo.SLO_BURN_TRIPS.set(self._trips_total)

    # -- introspection ------------------------------------------------------
    def burning(self) -> List[str]:
        with self._lock:
            return sorted(self._burning)

    def trips_total(self) -> int:
        with self._lock:
            return self._trips_total

    def breaches_total(self) -> int:
        with self._lock:
            return self._breaches_total

    def last_trip_tags(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._last_trip_tags) if self._last_trip_tags else None

    def state(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "objectives": {b: o.to_dict()
                               for b, o in sorted(self.objectives.items())},
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s,
                "fast_burn_threshold": self.fast_burn,
                "slow_burn_threshold": self.slow_burn,
                "burning": sorted(self._burning),
                "trips": self._trips_total,
                "breaches": self._breaches_total,
                "last_trip": (dict(self._last_trip_tags)
                              if self._last_trip_tags else None),
            }

    def reset(self) -> None:
        with self._lock:
            self._rings.clear()
            self._sample_trace.clear()
            self._burning.clear()
            self._last_trip.clear()
            self._last_trip_tags = None
            self._trips_total = 0
            self._breaches_total = 0


# ---------------------------------------------------------------------------
# Window marks: carryable per-window stamp context
# ---------------------------------------------------------------------------


class WindowMarks:
    """One window's SLO stamp context: the close timestamp
    (``time.perf_counter``) plus per-pod ``id(pod) -> (band, intake_s)``
    metadata captured at window close. Carried across the
    ``BatchHandle`` dispatch/fetch split exactly like span context."""

    __slots__ = ("t_close", "meta")

    def __init__(self, t_close: float,
                 meta: Dict[int, Tuple[str, float]]) -> None:
        self.t_close = t_close
        self.meta = meta


_TLS = threading.local()


def current_marks() -> Optional[WindowMarks]:
    return getattr(_TLS, "marks", None)


class use_marks:
    """Reinstate captured window marks on the current thread (no-op when
    ``marks`` is None)."""

    __slots__ = ("_marks", "_prev")

    def __init__(self, marks: Optional[WindowMarks]) -> None:
        self._marks = marks
        self._prev: Any = None

    def __enter__(self) -> Optional[WindowMarks]:
        self._prev = getattr(_TLS, "marks", None)
        if self._marks is not None:
            _TLS.marks = self._marks
        return self._marks

    def __exit__(self, *exc: Any) -> bool:
        _TLS.marks = self._prev
        return False


# ---------------------------------------------------------------------------
# Module-level singleton API (what production code calls)
# ---------------------------------------------------------------------------


def _env_enabled() -> bool:
    return os.environ.get("KARPENTER_SLO", "1").lower() not in (
        "0", "false", "no", "off")


_ENABLED = _env_enabled()
_ENGINE = SloEngine()
_SENTINEL = BurnSentinel()
# record() INVOCATIONS (weighted record_n is one call) — the honest unit
# for the bench's overhead bound: calls × measured ns/call, not samples
_RECORD_CALLS = 0
_EVAL_INTERVAL_S = 1.0
_LAST_EVAL = 0.0
_QUANTILE_PUBLISH_INTERVAL_S = 5.0
_LAST_QUANTILE_PUBLISH = 0.0


def enabled() -> bool:
    return _ENABLED


def enable() -> None:
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def engine() -> SloEngine:
    return _ENGINE


def sentinel() -> BurnSentinel:
    return _SENTINEL


def configure(enabled: Optional[bool] = None,
              objectives: Optional[Dict[str, Objective]] = None,
              fast_window_s: Optional[float] = None,
              slow_window_s: Optional[float] = None,
              fast_burn: Optional[float] = None,
              slow_burn: Optional[float] = None,
              trip_interval_s: Optional[float] = None) -> None:
    """Adjust the singleton sentinel. ``objectives`` replaces the full
    map (pass :func:`default_objectives` to restore defaults); other
    arguments override individual knobs, None leaves them alone."""
    global _ENABLED, _SENTINEL
    if enabled is not None:
        _ENABLED = bool(enabled)
    s = _SENTINEL
    _SENTINEL = BurnSentinel(
        objectives=(objectives if objectives is not None else s.objectives),
        fast_window_s=(fast_window_s if fast_window_s is not None
                       else s.fast_window_s),
        slow_window_s=(slow_window_s if slow_window_s is not None
                       else s.slow_window_s),
        fast_burn=(fast_burn if fast_burn is not None else s.fast_burn),
        slow_burn=(slow_burn if slow_burn is not None else s.slow_burn),
        trip_interval_s=(trip_interval_s if trip_interval_s is not None
                         else s.trip_interval_s),
        timefunc=s._time)
    _publish_objectives()


def _publish_objectives() -> None:
    try:
        from karpenter_tpu.metrics import slo as mslo
        for band, obj in _SENTINEL.objectives.items():
            mslo.SLO_OBJECTIVE.set(obj.threshold_s, band=band)
    except Exception:
        pass


def record(band: str, stage: str, seconds: float, count: int = 1) -> None:
    """Stamp one lifecycle stage for ``count`` pods. O(1) regardless of
    count; a strict near-no-op when disabled."""
    global _RECORD_CALLS
    if not _ENABLED:
        return
    _RECORD_CALLS += 1
    _ENGINE.record(band, stage, seconds, count)
    if stage == "e2e":
        _SENTINEL.observe(band, seconds)
        _maybe_evaluate()


def note_shed(band: str) -> None:
    """An intake shed burns the band's error budget without ever
    producing a latency sample — count it as a breach."""
    if not _ENABLED:
        return
    _SENTINEL.observe(band, shed=True)
    _maybe_evaluate()


def _maybe_evaluate() -> None:
    global _LAST_EVAL, _LAST_QUANTILE_PUBLISH
    now = time.monotonic()
    if now - _LAST_EVAL < _EVAL_INTERVAL_S:
        return
    _LAST_EVAL = now
    _SENTINEL.evaluate()
    if now - _LAST_QUANTILE_PUBLISH >= _QUANTILE_PUBLISH_INTERVAL_S:
        _LAST_QUANTILE_PUBLISH = now
        _publish_quantiles()


def _publish_quantiles() -> None:
    try:
        from karpenter_tpu.metrics import slo as mslo
    except Exception:
        return
    snap = _ENGINE.snapshot()
    for band, stages in snap["cells"].items():
        for stage, rep in stages.items():
            mslo.SLO_STAGE_P50.set(rep["p50"], band=band, stage=stage)
            mslo.SLO_STAGE_P99.set(rep["p99"], band=band, stage=stage)
            mslo.SLO_SAMPLES.set(rep["n"], band=band, stage=stage)


def burning() -> List[str]:
    return _SENTINEL.burning()


def trips_total() -> int:
    return _SENTINEL.trips_total()


def evaluate() -> Dict[str, Any]:
    """Force a sentinel evaluation (readyz, /debug/vars, tests)."""
    return _SENTINEL.evaluate()


def snapshot() -> Dict[str, Any]:
    """Engine quantile summary — also exported into the chrome trace
    dump's otherData for traceview's per-stage p50/p99 columns."""
    return _ENGINE.snapshot()


def state() -> Dict[str, Any]:
    """Status block for /debug/vars and the replay report."""
    return {"enabled": _ENABLED,
            "engine": _ENGINE.snapshot(),
            "burn": _SENTINEL.state()}


def record_calls() -> int:
    """record() invocations since the last reset (bench tax bound)."""
    return _RECORD_CALLS


def reset() -> None:
    """Tests / between bench legs: drop all samples and burn state (the
    objective map and window knobs survive; use configure() to change)."""
    global _LAST_EVAL, _LAST_QUANTILE_PUBLISH, _RECORD_CALLS
    _ENGINE.reset()
    _SENTINEL.reset()
    _LAST_EVAL = 0.0
    _LAST_QUANTILE_PUBLISH = 0.0
    _RECORD_CALLS = 0


# ---------------------------------------------------------------------------
# Overhead measurement (bench config_7 slo-tax bound, mirrors trace's)
# ---------------------------------------------------------------------------


def measure_overhead(n: int = 20_000) -> Dict[str, float]:
    """ns/record for the enabled and disabled stamping paths, measured
    against scratch engine/sentinel instances so live digests stay
    clean. The enabled probe uses the ``e2e`` stage — the most expensive
    one (digest + sentinel ring)."""
    global _ENABLED, _ENGINE, _SENTINEL
    was_enabled, eng, sen = _ENABLED, _ENGINE, _SENTINEL
    try:
        _ENGINE = SloEngine()
        _SENTINEL = BurnSentinel()
        _ENABLED = False
        t0 = time.perf_counter()
        for _ in range(n):
            record("default", "e2e", 0.25)
        disabled_ns = (time.perf_counter() - t0) / n * 1e9
        _ENABLED = True
        t0 = time.perf_counter()
        for _ in range(n):
            record("default", "e2e", 0.25)
        enabled_ns = (time.perf_counter() - t0) / n * 1e9
    finally:
        _ENABLED, _ENGINE, _SENTINEL = was_enabled, eng, sen
    return {"disabled_ns_per_record": disabled_ns,
            "enabled_ns_per_record": enabled_ns, "n": float(n)}


# Surface digest quantiles inside every chrome trace dump so traceview
# can render per-stage p50/p99 columns next to the critical-path table.
trace.add_dump_extra("slo", snapshot)
