"""Process-wide span tracer for the provisioning path.

Design constraints (ISSUE 9):

- ~µs overhead when enabled, a strict no-op when disabled: the disabled
  ``span()`` call returns a preallocated singleton and touches nothing
  else, so steady-state allocation count stays flat (pinned by
  ``tests/test_obs.py``).
- Lock-striped finished-span rings: writers hash their span id onto one
  of ``_N_STRIPES`` bounded deques so shard workers never contend on a
  single lock.
- Span context is an explicit, carryable value: ``current_context()``
  captures the active span and ``use_context()`` reinstates it on
  another thread — this is how a window's identity survives the
  ``BatchHandle``/``WhatIfHandle`` dispatch/fetch split and the shard
  worker handoff.
- ``new_window_id()`` works even when tracing is disabled so
  ``window_id=`` log keys exist unconditionally and logs/traces join on
  the same id.

Export is Chrome-trace-event JSON (``dump_chrome``): complete events
(``ph="X"``, ts/dur in µs) for spans, instant events (``ph="i"``) for
point events such as DeviceRing alloc/refill.  ``tools/traceview.py``
reads this dump.  When ``enable(jax_annotations=True)`` and jax is
importable, every entered span also enters a
``jax.profiler.TraceAnnotation`` so a flag-gated profiler capture lines
up with the device-solve spans.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

_N_STRIPES = 8
_RING_PER_STRIPE = 4096

# Module-level state. `_ENABLED` is read as a plain attribute on every
# span() call — no lock, no function call — which keeps the disabled
# path at tens of nanoseconds.
_ENABLED = False
_JAX_ANNOTATIONS = False
_EPOCH = time.perf_counter()  # ts base for the chrome dump (µs since import)


class _Stripe:
    __slots__ = ("lock", "ring", "dropped")

    def __init__(self, cap: int) -> None:
        self.lock = threading.Lock()
        self.ring: deque = deque(maxlen=cap)
        self.dropped = 0


_STRIPES = [_Stripe(_RING_PER_STRIPE) for _ in range(_N_STRIPES)]
_TLS = threading.local()
_IDS = itertools.count(1)  # CPython next() is atomic under the GIL
_PID_PREFIX = f"{os.getpid() & 0xFFFF:04x}"

# Sinks let obs.flight (and tests) observe finished spans without trace
# importing flight (keeps this module a leaf).
_SINKS: List[Any] = []

# Dump extras let higher layers (obs.slo) ride their state into every
# chrome dump's otherData without trace importing them (still a leaf).
_DUMP_EXTRAS: Dict[str, Any] = {}


def add_dump_extra(name: str, fn: Any) -> None:
    """Register a callable whose result is embedded as
    ``otherData[name]`` in every :func:`dump_chrome` payload."""
    _DUMP_EXTRAS[name] = fn


def new_window_id() -> str:
    """Cheap process-unique window id — available with tracing DISABLED
    too, so structured ``window_id=`` log keys never go missing."""
    return f"w-{_PID_PREFIX}-{next(_IDS):07d}"


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------


class _NoopSpan:
    """Singleton stand-in when tracing is disabled: every method is a
    no-op and allocates nothing."""

    __slots__ = ()
    trace_id: Optional[str] = None
    span_id = 0

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def tag(self, **tags: Any) -> "_NoopSpan":
        return self


_NOOP = _NoopSpan()


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t0", "t1",
                 "tags", "tid", "_prev", "_jax_ctx")

    def __init__(self, name: str, trace_id: Optional[str],
                 parent_id: int, tags: Optional[Dict[str, Any]]) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = next(_IDS)
        self.parent_id = parent_id
        self.tags = tags
        self.t0 = 0.0
        self.t1 = 0.0
        self.tid = 0
        self._prev: Any = None
        self._jax_ctx: Any = None

    def tag(self, **tags: Any) -> "Span":
        if self.tags is None:
            self.tags = tags
        else:
            self.tags.update(tags)
        return self

    def __enter__(self) -> "Span":
        self._prev = getattr(_TLS, "span", None)
        _TLS.span = self
        self.t0 = time.perf_counter()
        if _JAX_ANNOTATIONS:
            try:
                from jax.profiler import TraceAnnotation

                self._jax_ctx = TraceAnnotation(self.name)
                self._jax_ctx.__enter__()
            except Exception:
                self._jax_ctx = None
        return self

    def __exit__(self, *exc: Any) -> bool:
        self.t1 = time.perf_counter()
        if self._jax_ctx is not None:
            try:
                self._jax_ctx.__exit__(*exc)
            except Exception:
                pass
            self._jax_ctx = None
        _TLS.span = self._prev
        self._prev = None
        _record(self)
        return False


def _record(sp: Span) -> None:
    sp.tid = threading.get_ident() & 0xFFFFFF
    stripe = _STRIPES[sp.span_id & (_N_STRIPES - 1)]
    with stripe.lock:
        if len(stripe.ring) == stripe.ring.maxlen:
            stripe.dropped += 1
        stripe.ring.append(sp)
    for sink in _SINKS:
        try:
            sink(sp)
        except Exception:
            pass


def span(name: str, **tags: Any):
    """Child span under the thread's current context (or a parentless
    span when none is active). Returns the no-op singleton when tracing
    is disabled."""
    if not _ENABLED:
        return _NOOP
    cur = getattr(_TLS, "span", None)
    return Span(name, cur.trace_id if cur is not None else None,
                cur.span_id if cur is not None else 0, tags or None)


def window_span(kind: str, window_id: Optional[str] = None, **tags: Any):
    """Root span for one provisioning/consolidation/replay window. The
    window id IS the trace id, so logs carrying ``window_id=`` join the
    trace directly."""
    if not _ENABLED:
        return _NOOP
    return Span(kind, window_id or new_window_id(), 0, tags or None)


def add_span(name: str, t0: float, t1: float,
             trace_id: Optional[str] = None, parent_id: int = 0,
             **tags: Any) -> None:
    """Record a retroactively-timed span (e.g. the intake wait measured
    before its window span exists, or the device-solve in-flight period
    only known at fetch). t0/t1 are time.perf_counter() values."""
    if not _ENABLED:
        return
    if trace_id is None:
        cur = getattr(_TLS, "span", None)
        if cur is not None:
            trace_id = cur.trace_id
            if parent_id == 0:
                parent_id = cur.span_id
    sp = Span(name, trace_id, parent_id, tags or None)
    sp.t0, sp.t1 = t0, t1
    _record(sp)


def event(name: str, **tags: Any) -> None:
    """Instant event (Chrome ``ph="i"``) — DeviceRing alloc/refill etc."""
    if not _ENABLED:
        return
    now = time.perf_counter()
    add_span(name, now, now, **tags)


# ---------------------------------------------------------------------------
# Context carry (dispatch/fetch split, shard handoff)
# ---------------------------------------------------------------------------


def current_context() -> Optional[Span]:
    """The active span, as a value that can be carried across threads."""
    if not _ENABLED:
        return None
    return getattr(_TLS, "span", None)


def current_trace_id() -> Optional[str]:
    cur = getattr(_TLS, "span", None)
    return cur.trace_id if cur is not None else None


class use_context:
    """Reinstate a captured span context on the current thread — the
    fetch half of a handle runs its children under the window that
    dispatched it, wherever fetch happens."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[Span]) -> None:
        self._ctx = ctx
        self._prev: Any = None

    def __enter__(self) -> Optional[Span]:
        self._prev = getattr(_TLS, "span", None)
        if self._ctx is not None:
            _TLS.span = self._ctx
        return self._ctx

    def __exit__(self, *exc: Any) -> bool:
        _TLS.span = self._prev
        return False


# ---------------------------------------------------------------------------
# Enable / disable / introspection
# ---------------------------------------------------------------------------


def enable(jax_annotations: bool = False) -> None:
    global _ENABLED, _JAX_ANNOTATIONS
    _JAX_ANNOTATIONS = bool(jax_annotations)
    _ENABLED = True


def disable() -> None:
    global _ENABLED, _JAX_ANNOTATIONS
    _ENABLED = False
    _JAX_ANNOTATIONS = False


def enabled() -> bool:
    return _ENABLED


def add_sink(fn: Any) -> None:
    if fn not in _SINKS:
        _SINKS.append(fn)


def remove_sink(fn: Any) -> None:
    if fn in _SINKS:
        _SINKS.remove(fn)


def reset() -> None:
    """Drop all recorded spans (tests / between bench legs)."""
    for stripe in _STRIPES:
        with stripe.lock:
            stripe.ring.clear()
            stripe.dropped = 0


def snapshot(limit: int = 0) -> List[Dict[str, Any]]:
    """All finished spans as dicts, t0-ordered. limit=0 means all."""
    spans: List[Span] = []
    for stripe in _STRIPES:
        with stripe.lock:
            spans.extend(stripe.ring)
    spans.sort(key=lambda s: s.t0)
    if limit:
        spans = spans[-limit:]
    return [_span_dict(s) for s in spans]


def _span_dict(s: Span) -> Dict[str, Any]:
    d: Dict[str, Any] = {
        "name": s.name, "trace_id": s.trace_id, "span_id": s.span_id,
        "parent_id": s.parent_id, "t0": s.t0, "t1": s.t1, "tid": s.tid,
    }
    if s.tags:
        d["tags"] = s.tags
    return d


def state() -> Dict[str, Any]:
    """Cheap status block for /debug/vars."""
    recorded = sum(len(st.ring) for st in _STRIPES)
    dropped = sum(st.dropped for st in _STRIPES)
    return {"enabled": _ENABLED, "jax_annotations": _JAX_ANNOTATIONS,
            "spans_buffered": recorded, "spans_dropped": dropped,
            "stripes": _N_STRIPES}


# ---------------------------------------------------------------------------
# Export
# ---------------------------------------------------------------------------


def chrome_events(spans: Optional[List[Dict[str, Any]]] = None
                  ) -> List[Dict[str, Any]]:
    """Chrome-trace-event list: ``X`` complete events for spans,
    ``i`` instant events for zero-duration ones."""
    out: List[Dict[str, Any]] = []
    for d in (spans if spans is not None else snapshot()):
        args = dict(d.get("tags") or {})
        if d.get("trace_id"):
            args["trace_id"] = d["trace_id"]
        args["span_id"] = d["span_id"]
        if d.get("parent_id"):
            args["parent_id"] = d["parent_id"]
        ts = (d["t0"] - _EPOCH) * 1e6
        ev: Dict[str, Any] = {"name": d["name"], "pid": 1,
                              "tid": d.get("tid", 0), "ts": ts, "args": args}
        if d["t1"] <= d["t0"]:
            ev["ph"] = "i"
            ev["s"] = "t"
        else:
            ev["ph"] = "X"
            ev["dur"] = (d["t1"] - d["t0"]) * 1e6
        out.append(ev)
    return out


def dump_chrome(path: str) -> str:
    """Write the buffered spans as a Chrome/Perfetto-loadable trace.
    Returns the path written."""
    payload = {"traceEvents": chrome_events(),
               "displayTimeUnit": "ms",
               "otherData": {"tracer": "karpenter_tpu.obs.trace",
                             "spans": state()}}
    for name, fn in list(_DUMP_EXTRAS.items()):
        try:
            payload["otherData"][name] = fn()
        except Exception:
            pass
    dirname = os.path.dirname(os.path.abspath(path))
    if dirname:
        os.makedirs(dirname, exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


# ---------------------------------------------------------------------------
# Overhead measurement (bench config_7 tracing-tax bound)
# ---------------------------------------------------------------------------


def measure_overhead(n: int = 20_000) -> Dict[str, float]:
    """ns/span for the enabled and disabled paths. Restores the prior
    enabled state and drops the measurement spans afterwards."""
    was_enabled, was_jax = _ENABLED, _JAX_ANNOTATIONS
    try:
        disable()
        t0 = time.perf_counter()
        for _ in range(n):
            with span("overhead-probe"):
                pass
        disabled_ns = (time.perf_counter() - t0) / n * 1e9
        enable(jax_annotations=False)
        t0 = time.perf_counter()
        for _ in range(n):
            with span("overhead-probe"):
                pass
        enabled_ns = (time.perf_counter() - t0) / n * 1e9
    finally:
        disable()
        if was_enabled:
            enable(jax_annotations=was_jax)
    # the probe spans are noise — drop them (cheap: rings are bounded)
    reset()
    return {"disabled_ns_per_span": disabled_ns,
            "enabled_ns_per_span": enabled_ns, "n": float(n)}
