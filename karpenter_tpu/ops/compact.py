"""Active-shape compaction: re-bucket the alive shapes between chunks.

FFD consumes shapes in descending order, so after the first committed
nodes the overwhelming majority of a high-cardinality problem's shape rows
have ``counts == 0`` — and a ``count == 0`` shape is a provable no-op in
the kernel's ``one_shape`` step (``active`` is False, so ``k == 0`` and
the ``reserved``/``stopped``/``npacked`` carry is untouched). Gathering
the alive shapes into a dense prefix therefore cannot change any packing
decision; it only lets the next chunk run the kernel compiled for a
smaller static SHAPE_BUCKET. The gather is a stable ascending-index take
(``np.flatnonzero``), which preserves the descending FFD visit order
bit-for-bit — docs/solver.md ("shape compaction & re-bucketing") carries
the full argument, including why the fast-forward bound survives:
``maxfit`` depends only on (shapes, totals, reserved0, valid), so the
compacted problem's bound is exactly ``maxfit_full[perm]``.

The permutation ``perm`` maps compacted row → ORIGINAL (padded) shape
index; the chunk loop uses it to decode ``packed`` record rows and
``dropped`` deltas back to the original index space before
models/ffd._decode materializes pod ids.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

from karpenter_tpu.ops.encode import SHAPE_BUCKETS, bucket


class Compaction(NamedTuple):
    perm: np.ndarray      # (n_alive,) int64: compacted row → original index
    shapes: np.ndarray    # (S_new, R) int32, alive prefix + zero padding
    counts: np.ndarray    # (S_new,) int32
    maxfit: np.ndarray    # (S_new,) int32 (padding rows irrelevant: k==0)
    num_shapes: int       # S_new (the new, smaller bucket)


def compact_alive(
    counts_now: np.ndarray,        # (S_cur,) current chunk-boundary counts
    perm: Optional[np.ndarray],    # current compaction, None = identity
    shapes_full: np.ndarray,       # (S_orig, R) the ORIGINAL padded shapes
    maxfit_full: np.ndarray,       # (S_orig,) the once-per-solve bound
) -> Optional[Compaction]:
    """Decide whether re-bucketing the alive shapes pays off; None when the
    alive set still needs the current bucket (or no shapes remain alive —
    the chunk loop is about to exit anyway)."""
    S_cur = counts_now.shape[0]
    alive = np.flatnonzero(counts_now > 0)  # ascending: stable, order-safe
    if alive.size == 0:
        return None
    S_new = bucket(int(alive.size), SHAPE_BUCKETS)
    if S_new is None or S_new >= S_cur:
        return None
    new_perm = alive if perm is None else perm[alive]
    R = shapes_full.shape[1]
    shapes_c = np.zeros((S_new, R), np.int32)
    shapes_c[:alive.size] = shapes_full[new_perm]
    counts_c = np.zeros((S_new,), np.int32)
    counts_c[:alive.size] = counts_now[alive]
    maxfit_c = np.zeros((S_new,), np.int32)
    maxfit_c[:alive.size] = maxfit_full[new_perm]
    return Compaction(new_perm, shapes_c, counts_c, maxfit_c, S_new)


def sparse_record(packed_row: np.ndarray, perm: np.ndarray):
    """A compacted ``packed`` record row → the sparse [(original_shape,
    count), ...] form models/ffd._decode already accepts (the native
    per-pod kernel's ABI). Padding rows past len(perm) are structurally
    zero (count == 0 shapes pack nothing), so the slice is exact."""
    row = np.asarray(packed_row[:perm.size])
    return [(int(perm[s]), int(row[s])) for s in np.flatnonzero(row)]


def compact_rows(counts_rows: np.ndarray, perms: list,
                 shapes_full_rows: np.ndarray, S_new: int):
    """Batched variant for solver/batch_solve.py: every problem row is
    compacted to the SAME target bucket ``S_new`` (the batch tensors must
    stay uniform; the caller picks the bucket of the LARGEST alive set).
    ``perms`` holds one per-problem permutation (None = identity) and is
    returned updated; rows past ``len(perms)`` are mesh padding (all-zero
    counts) and compact to zero rows. ``shapes_full_rows`` is the ORIGINAL
    (B, S_orig, R) host copy."""
    Bpad, R = counts_rows.shape[0], shapes_full_rows.shape[2]
    shapes_c = np.zeros((Bpad, S_new, R), np.int32)
    counts_c = np.zeros((Bpad, S_new), np.int32)
    new_perms = list(perms)
    for b in range(len(perms)):
        alive = np.flatnonzero(counts_rows[b] > 0)
        perm_b = alive if perms[b] is None else perms[b][alive]
        new_perms[b] = perm_b
        shapes_c[b, :alive.size] = shapes_full_rows[b][perm_b]
        counts_c[b, :alive.size] = counts_rows[b][alive]
    return new_perms, shapes_c, counts_c


def scatter_dropped(dropped_full: np.ndarray, dropped_delta: np.ndarray,
                    perm: Optional[np.ndarray]) -> None:
    """Accumulate a chunk's ``dropped`` delta (in the chunk's compacted
    index space) into the original-index accumulator, in place."""
    if perm is None:
        dropped_full[:dropped_delta.shape[0]] += dropped_delta
    else:
        np.add.at(dropped_full, perm, dropped_delta[:perm.size])
