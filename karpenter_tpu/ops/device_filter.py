"""Device-resident fused feasibility: the pods×types bitset mask on device.

The PR 3 columnar filter (ops/feasibility.py) answers "which instance
types can this schedule use?" with numpy AND-reduces computed on host,
once per (catalog, allowed, required) key; the batched solver then ships
the resulting ``valid`` rows to the device every window. This module moves
the whole question onto the device and fuses the answer straight into the
FFD pack kernel:

- **Catalog bit-planes** (:class:`Planes`): the per-key value vocab of one
  instance-type list interned as persistent uint32 bit-planes — one-hot
  name/arch words ``(T, W)``, multi-bit OS words, and a per-capacity-type
  zone bitmask ``(T, C, W_z)`` for the non-separable (capacity type, zone)
  offering product. Planes are cached by the catalog feasibility token
  (the PR 3 identity) and ride a token-aware ``DeviceRing`` slot
  (solver/pipeline.py): a steady-state window re-fills by token match —
  zero transfer, zero fresh device allocation, counted on
  ``filter_plane_ring_reuses_total``.
- **Schedule rows**: each schedule's ``(allowed, required)`` key encodes to
  a handful of uint32 allowed-bitmask words (``allowed=None`` encodes to
  an all-zero row — Go's ``sets.Has(nil)`` rejection, exactly like the
  scalar oracle). Rows are tiny, cached per (planes, key), and flow to the
  device through the same ring slot the planes live in.
- **One pjit per window** (:func:`_window_jit`): computes the whole
  pods×types mask as an AND-reduce of ``pod_allowed_word &
  type_value_bit`` across requirement keys, batched over every schedule in
  the window, plus ``last_valid`` and small probe outputs. The ``(B, T)``
  mask is emitted with the batch sharding the pack kernel expects and is
  handed to ``pack_batch_sharded_*`` as its ``valid`` input directly — it
  is never materialized on host and never crosses PCIe.

The device verdict stays a FILTER in the repo's idiom: every window's mask
is spot-checked against the scalar oracle (``adapter._validate``) on a
sampled set of type columns (the full row for small catalogs), every
kernel-chosen type is re-validated at decode, and any divergence sends
that problem back to the host columnar path — scalar wins, counted on
``filter_fallback_total{reason="device-mask-mismatch"}`` and
``filter_device_fallback_total``. ``KARPENTER_DEVICE_FILTER=0`` is the
kill switch; the legacy ``KARPENTER_FEASIBILITY_BACKEND=jax`` toggle
(whose host-side leg this module replaces) aliases to ON. The host
columnar path is preserved unchanged as the differential reference and
the CPU/fallback leg.

Type-axis contract (docs/solver.md §16): fused problems encode against
the **universe packables** (adapter.build_universe_packables) — the whole
catalog with overhead/daemons reserved, sorted by the stable
``(cpu, memory)`` key. On every fused-eligible feasible subset (at least
one GPU class uniformly zero — guaranteed unless all three classes are
required, which is excluded below) this order restricted to the feasible
types equals the host comparator's order, so masking the universe axis IS
the host path's sorted feasible axis and decode indices agree by
construction.
"""

from __future__ import annotations

import functools
import itertools
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.metrics.filter import (
    FILTER_DEVICE_FALLBACK_TOTAL, FILTER_DEVICE_SECONDS,
    FILTER_FALLBACK_TOTAL, FILTER_PLANE_RING_REUSES_TOTAL,
)
from karpenter_tpu.utils import resources as res

_ENV = "KARPENTER_DEVICE_FILTER"
_LEGACY_ENV = "KARPENTER_FEASIBILITY_BACKEND"

# special-resource bit layout (planes.special / row.req words) — bit order
# is adapter._SPECIAL_RESOURCES: ENI, then the GPU classes, which are
# exclusive both ways (packable.go:205-219)
_ENI_BIT = np.uint32(1)
_GPU_MASK = np.uint32(0b1110)
_GPU_CLASSES = (res.NVIDIA_GPU, res.AMD_GPU, res.AWS_NEURON)

_MAX_CT_VOCAB = 32       # ct bits live in ONE uint32 row word
_PROBE_K = 32            # sampled columns per window (full row when T <= K)

_LOCK = threading.Lock()
_PLANES_CACHE: dict = {}           # catalog token tuple -> Planes|_FAILED
_PLANES_CACHE_CAP = 8
_FAILED = object()
_ROW_CACHE: dict = {}              # (planes key, allowed, required) -> row
_ROW_CACHE_CAP = 1024
_window_counter = itertools.count(1)


def enabled() -> bool:
    """Kill switch / opt-in resolution. ``KARPENTER_DEVICE_FILTER`` wins
    (0/false/off disables, 1/true/on enables); the legacy
    ``KARPENTER_FEASIBILITY_BACKEND=jax`` toggle aliases to ON; default is
    ON (the verdict is a filter — every divergence self-heals to scalar)."""
    v = os.environ.get(_ENV, "").strip().lower()
    if v in ("0", "false", "off"):
        return False
    if v in ("1", "true", "on"):
        return True
    if os.environ.get(_LEGACY_ENV, "").strip().lower() == "jax":
        return True
    return True


def _words(nbits: int) -> int:
    return max(1, -(-nbits // 32))


class Planes:
    """Persistent uint32 bit-planes of one instance-type list (type axis
    padded to the encoder's TYPE_BUCKETS so the mask aligns with the padded
    encoding's type axis). Padding rows are all-zero, which the mask algebra
    rejects — a padded type column is never valid."""

    __slots__ = ("key", "n", "TB", "name_vocab", "arch_vocab", "os_vocab",
                 "ct_vocab", "zone_vocab", "name_plane", "arch_plane",
                 "os_plane", "offer_plane", "special")

    def host_arrays(self) -> Dict[str, np.ndarray]:
        return {"name_plane": self.name_plane, "arch_plane": self.arch_plane,
                "os_plane": self.os_plane, "offer_plane": self.offer_plane,
                "special": self.special}


def _build_planes(instance_types, key: tuple) -> Optional[Planes]:
    from karpenter_tpu.ops.encode import TYPE_BUCKETS, bucket

    n = len(instance_types)
    TB = bucket(max(n, 1), TYPE_BUCKETS)
    if TB is None:
        return None  # beyond the largest device type bucket
    p = Planes()
    p.key, p.n, p.TB = key, n, TB
    p.name_vocab = {}
    p.arch_vocab = {}
    p.os_vocab = {}
    p.ct_vocab = {}
    p.zone_vocab = {}
    # first pass: vocabs (so word counts are known before the planes)
    for it in instance_types:
        p.name_vocab.setdefault(it.name, len(p.name_vocab))
        p.arch_vocab.setdefault(it.architecture, len(p.arch_vocab))
        for os_name in it.operating_systems:
            p.os_vocab.setdefault(os_name, len(p.os_vocab))
        for o in it.offerings:
            p.ct_vocab.setdefault(o.capacity_type, len(p.ct_vocab))
            p.zone_vocab.setdefault(o.zone, len(p.zone_vocab))
    if len(p.ct_vocab) > _MAX_CT_VOCAB:
        return None  # ct bits must fit one row word
    wn, wa = _words(len(p.name_vocab)), _words(len(p.arch_vocab))
    wo, wz = _words(len(p.os_vocab)), _words(len(p.zone_vocab))
    C = max(1, len(p.ct_vocab))
    p.name_plane = np.zeros((TB, wn), np.uint32)
    p.arch_plane = np.zeros((TB, wa), np.uint32)
    p.os_plane = np.zeros((TB, wo), np.uint32)
    p.offer_plane = np.zeros((TB, C, wz), np.uint32)
    p.special = np.zeros((TB,), np.uint32)
    for t, it in enumerate(instance_types):
        b = p.name_vocab[it.name]
        p.name_plane[t, b // 32] |= np.uint32(1 << (b % 32))
        b = p.arch_vocab[it.architecture]
        p.arch_plane[t, b // 32] |= np.uint32(1 << (b % 32))
        for os_name in it.operating_systems:
            b = p.os_vocab[os_name]
            p.os_plane[t, b // 32] |= np.uint32(1 << (b % 32))
        for o in it.offerings:
            c = p.ct_vocab[o.capacity_type]
            b = p.zone_vocab[o.zone]
            p.offer_plane[t, c, b // 32] |= np.uint32(1 << (b % 32))
        sp = 0
        if not it.aws_pod_eni.is_zero():
            sp |= 1
        for i, (name, qty) in enumerate(
                ((res.NVIDIA_GPU, it.nvidia_gpus), (res.AMD_GPU, it.amd_gpus),
                 (res.AWS_NEURON, it.aws_neurons))):
            if not qty.is_zero():
                sp |= 1 << (1 + i)
        p.special[t] = sp
    for arr in p.host_arrays().values():
        arr.flags.writeable = False
    return p


def planes_for(instance_types) -> Optional[Planes]:
    """Planes for this catalog identity (the PR 3 feasibility token),
    cached. None = not device-indexable (counted); the caller falls back to
    the host columnar path."""
    from karpenter_tpu.ops.feasibility import _catalog_token

    key = tuple(_catalog_token(it) for it in instance_types)
    with _LOCK:
        hit = _PLANES_CACHE.get(key)
    if hit is _FAILED:
        return None
    if hit is not None:
        return hit
    planes = _build_planes(instance_types, key)
    if planes is None:
        FILTER_DEVICE_FALLBACK_TOTAL.inc(reason="ct-vocab-overflow")
    with _LOCK:
        if len(_PLANES_CACHE) >= _PLANES_CACHE_CAP:
            _PLANES_CACHE.pop(next(iter(_PLANES_CACHE)))
        _PLANES_CACHE[key] = planes if planes is not None else _FAILED
    return planes


def _bits_row(vocab: Dict[str, int], allowed, nwords: int) -> np.ndarray:
    """Allowed-set bitmask words over a plane vocab. ``None`` → all-zero
    (rejects everything — the scalar oracle's Go sets.Has(nil) contract);
    out-of-vocab values contribute nothing (they can't match any type)."""
    row = np.zeros((nwords,), np.uint32)
    if allowed:
        for v in allowed:
            b = vocab.get(v)
            if b is not None:
                row[b // 32] |= np.uint32(1 << (b % 32))
    return row


def schedule_row(planes: Planes, allowed: tuple, required: frozenset) -> tuple:
    """One schedule's device row: per-axis allowed bitmask words + the
    required special-resource bits. Cached per (planes, allowed, required)
    — the pod-side analog of the delta-marshal arena: constraint churn
    re-encodes a few words, never the planes."""
    key = (planes.key, allowed, required)
    with _LOCK:
        hit = _ROW_CACHE.get(key)
        if hit is not None:
            return hit
    cts, zones, its, archs, oss = allowed
    req = 0
    if res.AWS_POD_ENI in required:
        req |= 1
    for i, name in enumerate(_GPU_CLASSES):
        if name in required:
            req |= 1 << (1 + i)
    ct_bits = np.uint32(0)
    if cts:
        for v in cts:
            b = planes.ct_vocab.get(v)
            if b is not None:
                ct_bits |= np.uint32(1 << b)
    row = (
        _bits_row(planes.name_vocab, its, planes.name_plane.shape[1]),
        _bits_row(planes.arch_vocab, archs, planes.arch_plane.shape[1]),
        _bits_row(planes.os_vocab, oss, planes.os_plane.shape[1]),
        _bits_row(planes.zone_vocab, zones, planes.offer_plane.shape[2]),
        ct_bits,
        np.uint32(req),
    )
    with _LOCK:
        if len(_ROW_CACHE) >= _ROW_CACHE_CAP:
            _ROW_CACHE.pop(next(iter(_ROW_CACHE)))
        _ROW_CACHE[key] = row
    return row


def _stack_rows(planes: Planes, rows: Sequence[tuple], Bpad: int):
    """Stack per-schedule rows into (Bpad, W) arrays; padding rows are
    all-zero (reject everything — a padded batch row packs nothing)."""
    wn = planes.name_plane.shape[1]
    wa = planes.arch_plane.shape[1]
    wo = planes.os_plane.shape[1]
    wz = planes.offer_plane.shape[2]
    name_r = np.zeros((Bpad, wn), np.uint32)
    arch_r = np.zeros((Bpad, wa), np.uint32)
    os_r = np.zeros((Bpad, wo), np.uint32)
    zone_r = np.zeros((Bpad, wz), np.uint32)
    ct_r = np.zeros((Bpad,), np.uint32)
    req_r = np.zeros((Bpad,), np.uint32)
    for b, (nr, ar, osr, zr, ct, rq) in enumerate(rows):
        name_r[b], arch_r[b], os_r[b], zone_r[b] = nr, ar, osr, zr
        ct_r[b], req_r[b] = ct, rq
    return name_r, arch_r, os_r, zone_r, ct_r, req_r


def _mask_expr(jnp, name_p, arch_p, os_p, offer_p, special_p,
               name_r, arch_r, os_r, zone_r, ct_r, req_r):
    """The shared (B, T) mask algebra — one AND-reduce of
    ``pod_allowed_word & type_value_bit`` per requirement key, plus the
    offering product and the exclusive special-resource rule. Exactly the
    scalar oracle (adapter._validate), fuzz-pinned in
    tests/test_device_filter.py."""
    def axis_ok(plane, row):  # (T, W) x (B, W) -> (B, T)
        return ((plane[None, :, :] & row[:, None, :]) != 0).any(-1)

    name_ok = axis_ok(name_p, name_r)
    arch_ok = axis_ok(arch_p, arch_r)
    os_ok = axis_ok(os_p, os_r)
    # offerings: feasible iff SOME offering has (ct allowed AND zone
    # allowed) — a per-(type, ct) zone bitmask keeps the product exact
    # (any-ct AND any-zone would be wrong: the pair is not separable)
    zc = ((offer_p[None, :, :, :] & zone_r[:, None, None, :]) != 0).any(-1)
    C = offer_p.shape[1]
    ct_bits = ((ct_r[:, None] >> jnp.arange(C, dtype=jnp.uint32)) &
               jnp.uint32(1)).astype(bool)              # (B, C)
    offer_ok = (zc & ct_bits[:, None, :]).any(-1)
    req = req_r[:, None]                                 # (B, 1)
    tb = special_p[None, :]                              # (1, T)
    eni_ok = (req & jnp.uint32(1) & ~tb) == 0
    gpu_ok = (req & jnp.uint32(14)) == (tb & jnp.uint32(14))
    return name_ok & arch_ok & os_ok & offer_ok & eni_ok & gpu_ok


@functools.lru_cache(maxsize=4)
def _window_jit(mesh):
    """The per-window fused-filter program: (B, T) mask + last_valid with
    the pack kernel's batch sharding (consumed on device — the mask never
    lands on host), plus the small probe outputs the fetch-side
    verification reads (any-feasible per schedule, sampled mask columns)."""
    import jax
    import jax.numpy as jnp

    from karpenter_tpu.parallel.mesh import batch_sharding, replicated

    bs, rep = batch_sharding(mesh), replicated(mesh)

    def body(name_p, arch_p, os_p, offer_p, special_p,
             name_r, arch_r, os_r, zone_r, ct_r, req_r, probe_idx):
        mask = _mask_expr(jnp, name_p, arch_p, os_p, offer_p, special_p,
                          name_r, arch_r, os_r, zone_r, ct_r, req_r)
        iota = jnp.arange(mask.shape[1], dtype=jnp.int32)
        lv = jnp.max(jnp.where(mask, iota[None, :], -1), axis=1)
        any_feas = lv >= 0
        last_valid = jnp.maximum(lv, 0).astype(jnp.int32)
        probe = jnp.take(mask, probe_idx, axis=1)
        return mask, last_valid, any_feas, probe

    return jax.jit(body,
                   in_shardings=(rep,) * 5 + (bs,) * 6 + (rep,),
                   out_shardings=(bs, bs, bs, bs))


@functools.lru_cache(maxsize=4)
def _rows_jit(mesh):
    """Replicated small-batch variant (gang columns, tests, bench stage
    timing): same algebra, no batch padding/sharding requirements."""
    import jax
    import jax.numpy as jnp

    from karpenter_tpu.parallel.mesh import replicated

    rep = replicated(mesh)

    def body(name_p, arch_p, os_p, offer_p, special_p,
             name_r, arch_r, os_r, zone_r, ct_r, req_r):
        return _mask_expr(jnp, name_p, arch_p, os_p, offer_p, special_p,
                          name_r, arch_r, os_r, zone_r, ct_r, req_r)

    return jax.jit(body, in_shardings=(rep,) * 11, out_shardings=rep)


class _PlanesResidency:
    """Device residency of one Planes set (plus, for the fused path, the
    window's row stack) on a token-aware DeviceRing slot. The slot is held
    until :meth:`release` so an in-flight program can never see its buffers
    donated away by a later refill; a steady-state window re-acquires the
    same slot and every plane fill short-circuits on its content token
    (``filter_plane_ring_reuses_total``)."""

    def __init__(self, planes: Planes, mesh, rows_host=None):
        from karpenter_tpu.parallel.mesh import batch_sharding, replicated
        from karpenter_tpu.solver.pipeline import DeviceRing, get_ring

        self._ring = get_ring()
        host = dict(planes.host_arrays())
        row_names = ("name_r", "arch_r", "os_r", "zone_r", "ct_r", "req_r")
        if rows_host is not None:
            host.update(zip(row_names, rows_host))
        self._slot = self._ring.acquire(DeviceRing.signature(host))
        try:
            rep = replicated(mesh)
            before = self._ring.reuses
            self.planes_d = tuple(
                self._ring.fill(self._slot, name, arr, rep,
                                token=("planes", planes.key, name))
                for name, arr in planes.host_arrays().items())
            reused = self._ring.reuses - before
            if reused:
                FILTER_PLANE_RING_REUSES_TOTAL.inc(amount=float(reused))
            self.rows_d = None
            if rows_host is not None:
                # every row array leads with the padded batch axis
                bsh = batch_sharding(mesh)
                self.rows_d = tuple(
                    self._ring.fill(self._slot, name, arr, bsh)
                    for name, arr in zip(row_names, rows_host))
        except BaseException:
            self.release()
            raise

    def release(self) -> None:
        slot, self._slot = self._slot, None
        if slot is not None:
            self._ring.release(slot)


def compute_mask(instance_types, pairs) -> Optional[np.ndarray]:
    """Host-visible (S, T) device mask for ``pairs`` of (allowed, required)
    keys — the differential surface tests and the gang column use (the
    fused solve path never materializes its mask; this wrapper exists for
    everything that wants the same verdicts ON host). None when the
    catalog is not device-indexable or the device backend is unavailable."""
    planes = planes_for(instance_types)
    if planes is None:
        return None
    try:
        from karpenter_tpu.parallel.mesh import solver_mesh

        mesh = solver_mesh()
        rows = [schedule_row(planes, allowed, required)
                for allowed, required in pairs]
        stacked = _stack_rows(planes, rows, max(1, len(rows)))
        # ride the token-aware ring for the planes (a planes-only slot —
        # distinct signature from the fused window slots): repeat calls on
        # the same catalog skip the plane transfer entirely. The small row
        # stack transfers per call (it varies per call anyway).
        residency = _PlanesResidency(planes, mesh)
        try:
            out = _rows_jit(mesh)(*residency.planes_d, *stacked)
            mask = np.asarray(out)[:len(rows), :planes.n]
        finally:
            # np.asarray above blocks until the program retires, so the
            # plane buffers are safe to hand back for donation
            residency.release()
    except Exception:
        FILTER_DEVICE_FALLBACK_TOTAL.inc(reason="jax-backend-unavailable")
        FILTER_FALLBACK_TOTAL.inc(reason="jax-backend-unavailable")
        return None
    return mask


def gang_member_column(instance_types, member_keys) -> Optional[np.ndarray]:
    """The gang member-AND column ((T,) bool — every member's validators
    accept the type) computed from the persistent catalog bit-planes in one
    device call, instead of one host columnar mask per distinct member key.
    None → the caller runs the host/scalar leg unchanged."""
    if not enabled() or not member_keys:
        return None
    t0 = time.perf_counter()
    mask = compute_mask(instance_types, member_keys)
    if mask is None:
        return None
    FILTER_DEVICE_SECONDS.observe(time.perf_counter() - t0, stage="gang")
    col = mask.all(axis=0)
    col.flags.writeable = False
    return col


# --------------------------------------------------------------------------
# The fused batched-solve path (solver/batch_solve.py)
# --------------------------------------------------------------------------

class FusedMismatch(Exception):
    """Raised at decode when the kernel's chosen type fails the scalar
    oracle — the device mask lied; the problem self-heals to the host path."""


class FusedBatch:
    """Everything the batched run needs to consume the device mask:
    the mask/last_valid device arrays (batch-sharded, fed to the pack
    kernel as ``valid``), the shared universe packables/types axis, and
    the per-problem verification state (probe columns + scalar memo)."""

    def __init__(self, batch_idx, encs, packables, uni_types, verify,
                 mask_d, last_valid_d, any_d, probe_d, probe_idx,
                 residency: _PlanesResidency, soft=None):
        self.batch_idx = list(batch_idx)
        self.encs = list(encs)
        self.packables = packables
        self.uni_types = uni_types
        self.verify = list(verify)         # [(allowed, required)] per member
        # per-member preferred-affinity vote map ({(key, value): signed
        # weight} or None) — consumed by the scoring kernel (ops/policy.py)
        self.soft = list(soft) if soft is not None \
            else [None] * len(self.batch_idx)
        self.mask_d = mask_d
        self.last_valid_d = last_valid_d
        self.any_d = any_d
        self.probe_d = probe_d
        self.probe_idx = probe_idx         # host np (K,) int32, deduped view
        self._residency = residency
        self._ok_memos: List[Optional[dict]] = [None] * len(self.batch_idx)

    def release(self) -> None:
        residency, self._residency = self._residency, None
        if residency is not None:
            residency.release()

    def _ok(self, b: int, t: int) -> bool:
        """Memoized scalar oracle for (member b, universe type t)."""
        from karpenter_tpu.solver.adapter import _validate

        memo = self._ok_memos[b]
        if memo is None:
            memo = self._ok_memos[b] = {}
        if t not in memo:
            allowed, required = self.verify[b]
            memo[t] = _validate(self.uni_types[t], allowed,
                                required) is None
        return memo[t]

    def _options_fn(self, b: int):
        """instance_options over the FEASIBLE subsequence of the universe
        axis: the window is the next ``maxn`` feasible types from ``chosen``
        (host_ffd.instance_options over the host's feasible list, by the
        §16 order equivalence), with every scanned type re-validated by the
        scalar oracle — the chosen type's check IS the primary fused
        verification."""
        from karpenter_tpu.solver.host_ffd import R_MEMORY, R_PODS

        def options_fn(packables, chosen, maxn):
            if not self._ok(b, chosen):
                raise FusedMismatch(chosen)
            base = packables[chosen]
            out: List[int] = []
            taken = 0
            j = chosen
            while j < len(packables) and taken < maxn:
                if self._ok(b, j):
                    taken += 1
                    if base.total[R_MEMORY] <= packables[j].total[R_MEMORY] \
                            and base.total[R_PODS] <= packables[j].total[R_PODS]:
                        out.append(packables[j].index)
                j += 1
            return out

        return options_fn

    def decode_all(self, decode, records, dropped_full, max_instance_types):
        """Per-problem decode with the self-heal contract: probe columns
        re-checked against the scalar oracle, all-False rows re-derived,
        every chosen type re-validated inside the options walk. A problem
        that diverges returns None in its slot (the handle solves it on
        the host path — scalar wins) and counts on BOTH fallback series."""
        t0 = time.perf_counter()
        probe = np.asarray(self.probe_d)
        any_feas = np.asarray(self.any_d)
        out: List[Optional[object]] = []
        for b, enc in enumerate(self.encs):
            bad = None
            for k, t in enumerate(self.probe_idx):
                if bool(probe[b, k]) != self._ok(b, int(t)):
                    bad = f"probe type {int(t)}"
                    break
            if bad is None and not any_feas[b] and any(
                    self._ok(b, t) for t in range(len(self.uni_types))):
                bad = "all-false row"
            if bad is None:
                try:
                    out.append(decode(enc, records[b], dropped_full[b],
                                      self.packables, max_instance_types,
                                      options_fn=self._options_fn(b)))
                    continue
                except FusedMismatch as e:
                    bad = f"chosen type {e.args[0]}"
            FILTER_FALLBACK_TOTAL.inc(reason="device-mask-mismatch")
            FILTER_DEVICE_FALLBACK_TOTAL.inc(reason="device-mask-mismatch")
            out.append(None)
        FILTER_DEVICE_SECONDS.observe(time.perf_counter() - t0,
                                      stage="verify")
        return out


def _probe_indices(n: int) -> np.ndarray:
    """The window's verification columns: every real type for small
    catalogs (tests verify the full row), else a deterministic per-window
    sample. Always shape (_PROBE_K,) so the jit never retraces."""
    if n <= _PROBE_K:
        idx = np.arange(n, dtype=np.int32)
    else:
        rng = np.random.default_rng(next(_window_counter))
        idx = rng.choice(n, size=_PROBE_K, replace=False).astype(np.int32)
    if len(idx) < _PROBE_K:
        idx = np.concatenate(
            [idx, np.full(_PROBE_K - len(idx), idx[-1] if len(idx) else 0,
                          np.int32)])
    return idx


def prepare_fused(problems, marshaled, config, max_shapes: int):
    """Dispatch-side fused preparation for one window: universe packables,
    planes residency, row encode, universe encodes, and the async mask
    dispatch. Returns a :class:`FusedBatch` (≥2 members) or None — the
    caller then runs the classic host-columnar batch path unchanged."""
    if not enabled():
        return None
    t0 = time.perf_counter()
    try:
        from karpenter_tpu.ops.encode import encode, pad_encoding
        from karpenter_tpu.parallel.mesh import solver_mesh
        from karpenter_tpu.solver import adapter

        # one universe per fused batch: every member must share the catalog
        # identity and daemon overhead (the shared type axis + planes)
        key0 = None
        for prob in problems:
            key = (tuple(adapter._instance_token(it)
                         for it in prob.instance_types),
                   tuple(adapter.pod_vector(d) for d in prob.daemons))
            if key0 is None:
                key0 = key
            elif key != key0:
                FILTER_DEVICE_FALLBACK_TOTAL.inc(reason="mixed-universe")
                return None
        if key0 is None or not key0[0]:
            return None
        packables, uni_types, uni_version = adapter.build_universe_packables(
            problems[0].instance_types, daemon_vecs=key0[1])
        if not packables:
            return None
        planes = planes_for(uni_types)
        if planes is None:
            return None

        batch_idx: List[int] = []
        encs = []
        verify = []
        soft = []
        for i, prob in enumerate(problems):
            vecs, required, sids = marshaled[i]
            if len(required & set(_GPU_CLASSES)) >= 3:
                # all three GPU classes required: the host comparator's
                # order on the feasible subset is no longer the stable
                # (cpu, mem) key (§16) — keep such exotica on the host path
                FILTER_DEVICE_FALLBACK_TOTAL.inc(reason="gpu-trio")
                continue
            allowed = adapter.allowed_sets_cached(prob.constraints)
            if any(a is not None and len(a) == 0 or a is None
                   for a in allowed):
                # a None/empty allowed set rejects every type (Go
                # sets.Has(nil)) — the solo path answers "all
                # unschedulable" immediately; an all-False device row
                # would grind through the kernel's drop path instead
                continue
            enc = encode(vecs, list(range(len(prob.pods))), packables,
                         pad=False, sids=sids, catalog_version=uni_version)
            if enc is None or enc.num_shapes > max_shapes:
                continue
            penc = pad_encoding(enc)
            if penc is None:
                continue
            batch_idx.append(i)
            encs.append(penc)
            verify.append((allowed, required))
            soft.append(getattr(prob, "soft_affinity", None))
        if len(batch_idx) < 2:
            return None

        TB = encs[0].totals.shape[0]
        if TB != planes.TB or any(e.totals.shape[0] != TB for e in encs):
            FILTER_DEVICE_FALLBACK_TOTAL.inc(reason="bucket-mismatch")
            return None
        mesh = solver_mesh()
        B = len(encs)
        Bpad = -(-B // mesh.devices.size) * mesh.devices.size
        rows = [schedule_row(planes, allowed, required)
                for allowed, required in verify]
        stacked = _stack_rows(planes, rows, Bpad)
        probe_idx = _probe_indices(planes.n)
        residency = _PlanesResidency(planes, mesh, rows_host=stacked)
        try:
            import jax

            from karpenter_tpu.parallel.mesh import replicated

            probe_d = jax.device_put(probe_idx, replicated(mesh))
            mask_d, lv_d, any_d, probe_out = _window_jit(mesh)(
                *residency.planes_d, *residency.rows_d, probe_d)
        except BaseException:
            residency.release()
            raise
        fused = FusedBatch(
            batch_idx, encs, packables, uni_types, verify, mask_d, lv_d,
            any_d, probe_out, probe_idx, residency, soft=soft)
        FILTER_DEVICE_SECONDS.observe(time.perf_counter() - t0,
                                      stage="dispatch")
        return fused
    except Exception:
        FILTER_DEVICE_FALLBACK_TOTAL.inc(reason="jax-backend-unavailable")
        FILTER_FALLBACK_TOTAL.inc(reason="jax-backend-unavailable")
        return None


# --------------------------------------------------------------------------
# Pod-pod affinity: the selectors × peers match matrix from pair bit-planes
# --------------------------------------------------------------------------
#
# Peers (distinct pod-label signatures) intern their (key, value) pairs
# into dense bit positions; each peer becomes one row of uint32 words with
# its pair bits set. Every supported selector clause then reduces to ANY /
# NONE over a clause bitmask against that plane — match_labels and In are
# ANY over the named pair bits, NotIn is NONE over them, Exists/DoesNotExist
# are ANY/NONE over all pair bits of the key — and the whole (S, P) matrix
# is one device call: per-clause hits, then a segment-sum of violations per
# selector. The matrix is a FILTER like every device verdict here: the
# caller (ops/feasibility.affinity_match_matrix) probe-checks cells against
# the scalar matches() oracle and self-heals to scalar on divergence.

_AFFINITY_MATRIX_CACHE: dict = {}
_AFFINITY_MATRIX_CACHE_CAP = 64


@functools.lru_cache(maxsize=8)
def _affinity_jit(S: int):
    import jax
    import jax.numpy as jnp

    def body(peer_plane, cmask, ckind, csel):
        # (C, P): does any clause-mask bit intersect the peer's pair bits?
        hit = ((peer_plane[None, :, :] & cmask[:, None, :]) != 0).any(-1)
        ok = jnp.where(ckind[:, None] == 0, hit, ~hit)
        viol = jax.ops.segment_sum((~ok).astype(jnp.int32), csel,
                                   num_segments=S)
        return viol == 0

    return jax.jit(body)


def affinity_matrix(sel_sigs: tuple, peer_sigs: tuple) -> Optional[np.ndarray]:
    """(S, P) match matrix for pre-validated selector signatures (the
    feasibility layer's selector_signature tuples — only In/NotIn/Exists/
    DoesNotExist reach here) against peer label signatures. None → the
    caller's host columnar leg runs unchanged."""
    if not enabled() or not sel_sigs or not peer_sigs:
        return None
    ckey = (sel_sigs, peer_sigs)
    with _LOCK:
        hit = _AFFINITY_MATRIX_CACHE.get(ckey)
    if hit is not None:
        return hit
    t0 = time.perf_counter()
    try:
        pair_vocab: Dict[tuple, int] = {}
        key_bits: Dict[str, list] = {}
        for sig in peer_sigs:
            for kv in sig:
                if kv not in pair_vocab:
                    pair_vocab[kv] = len(pair_vocab)
                    key_bits.setdefault(kv[0], []).append(pair_vocab[kv])
        W = _words(len(pair_vocab))
        P = len(peer_sigs)
        Ppad = max(8, 1 << (P - 1).bit_length())
        peer_plane = np.zeros((Ppad, W), np.uint32)
        for p, sig in enumerate(peer_sigs):
            for kv in sig:
                b = pair_vocab[kv]
                peer_plane[p, b // 32] |= np.uint32(1 << (b % 32))

        def clause_mask(bits) -> np.ndarray:
            row = np.zeros((W,), np.uint32)
            for b in bits:
                row[b // 32] |= np.uint32(1 << (b % 32))
            return row

        masks: List[np.ndarray] = []
        kinds: List[int] = []   # 0 = ANY-of, 1 = NONE-of
        sel_of: List[int] = []
        for s, (match_labels, exprs) in enumerate(sel_sigs):
            for kv in match_labels:
                b = pair_vocab.get(kv)
                # an unseen pair can match no peer: the empty ANY mask
                # makes the clause (and the row's cells) False, exactly
                # like the scalar oracle
                masks.append(clause_mask([] if b is None else [b]))
                kinds.append(0)
                sel_of.append(s)
            for key, op, values in exprs:
                if op in ("In", "NotIn"):
                    bits = [pair_vocab[(key, v)] for v in values
                            if (key, v) in pair_vocab]
                    masks.append(clause_mask(bits))
                    kinds.append(0 if op == "In" else 1)
                else:  # Exists / DoesNotExist: ANY/NONE over the key's pairs
                    masks.append(clause_mask(key_bits.get(key, [])))
                    kinds.append(0 if op == "Exists" else 1)
                sel_of.append(s)
        S = len(sel_sigs)
        C = len(masks)
        if C == 0:
            # every selector is empty: matches() returns True everywhere
            mat = np.ones((S, P), bool)
        else:
            Cpad = -(-C // 8) * 8
            while len(masks) < Cpad:
                # padding clauses: NONE over the empty mask — always ok,
                # charged to selector 0, never a violation
                masks.append(np.zeros((W,), np.uint32))
                kinds.append(1)
                sel_of.append(0)
            out = _affinity_jit(S)(
                peer_plane, np.stack(masks),
                np.asarray(kinds, np.int32), np.asarray(sel_of, np.int32))
            mat = np.asarray(out)[:, :P]
    except Exception:
        FILTER_DEVICE_FALLBACK_TOTAL.inc(reason="jax-backend-unavailable")
        return None
    mat = np.asarray(mat, bool)
    mat.flags.writeable = False
    FILTER_DEVICE_SECONDS.observe(time.perf_counter() - t0, stage="affinity")
    with _LOCK:
        if len(_AFFINITY_MATRIX_CACHE) >= _AFFINITY_MATRIX_CACHE_CAP:
            _AFFINITY_MATRIX_CACHE.pop(next(iter(_AFFINITY_MATRIX_CACHE)))
        _AFFINITY_MATRIX_CACHE[ckey] = mat
    return mat


def clear_caches() -> None:
    """Tests only."""
    with _LOCK:
        _PLANES_CACHE.clear()
        _ROW_CACHE.clear()
        _AFFINITY_MATRIX_CACHE.clear()
    try:
        from karpenter_tpu.solver import adapter

        with adapter._packables_lock:
            adapter._UNIVERSE_CACHE.clear()
    except Exception:
        pass
