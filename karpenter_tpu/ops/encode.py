"""Encode a packing problem into dense int32 device tensors.

Pods collapse to unique resource *shapes* with counts — the key TPU-first
transformation: the greedy pack then scans over shapes (dozens) instead of
pods (tens of thousands), vectorized over all instance types at once.

Quantities are nano-unit integers on the host; each resource dimension is
divided by the GCD of all its values so realistic problems (milli CPUs,
Mi-aligned memory) fit int32 exactly. If any dimension cannot be encoded
exactly below 2**31, encoding fails and the caller falls back to the host
oracle — exactness is never traded for speed (the ±1 node-count target).
"""

from __future__ import annotations

import math
import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.metrics.marshal import (
    CATALOG_ENCODING_REBUILDS_TOTAL, MARSHAL_DELTA_FRACTION,
    MARSHAL_ROW_CACHE_EVICTIONS_TOTAL, MARSHAL_ROW_CACHE_HITS_TOTAL,
    MARSHAL_ROW_CACHE_MISSES_TOTAL,
)
from karpenter_tpu.solver.host_ffd import NUM_RESOURCES, Packable, R_PODS, Vec

INT32_LIMIT = 2**31 - 1

# Pad shapes/types to these static sizes so XLA compiles one executable per
# bucket pair instead of one per batch (SURVEY.md §7 "ragged shapes").
# The 8192+ buckets serve heterogeneous clusters (50k pods with thousands
# of distinct request vectors); the kernel's shape walk is block-tiled and
# early-terminating (ops/pack.py), and the chunk loop compacts the alive
# shapes down to smaller buckets as FFD consumes them (ops/compact.py), so
# the big buckets only price the FIRST chunks of a solve, not all of them.
SHAPE_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192,
                 16384, 32768)
# 2048/4096: the "catalog is large" regime the type-axis SPMD kernel
# exists for (parallel/type_sharded.py) — a real cloud catalog with every
# size × family × generation easily exceeds 1024 distinct types
TYPE_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


def bucket(n: int, buckets: Sequence[int]) -> Optional[int]:
    for b in buckets:
        if n <= b:
            return b
    return None


@dataclass
class EncodedProblem:
    shapes: np.ndarray        # (S, R) int32, reserve semantics (pods includes +1)
    counts: np.ndarray        # (S,) int32
    totals: np.ndarray        # (T, R) int32
    reserved0: np.ndarray     # (T, R) int32
    valid: np.ndarray         # (T,) bool
    last_valid: int           # index of the largest viable type
    num_shapes: int           # unpadded S
    num_types: int            # unpadded T
    shape_pods: List[List[int]]   # pod ids per shape, pack order
    scales: Tuple[int, ...]   # per-resource divisor (nano → device units)
    pods_unit: int = 1        # one pod in device units (10**9 / scales[R_PODS])
    # content identity of the catalog-side tensors (totals/reserved0/valid):
    # set when the encoding came through the versioned catalog cache, so the
    # device ring can skip re-uploading bytes it already holds. None =
    # unversioned (every fill ships).
    catalog_token: Optional[tuple] = None


def _gcd_scale(columns: List[List[int]]) -> Optional[Tuple[int, ...]]:
    scales = []
    for vals in columns:
        g = 0
        for v in vals:
            g = math.gcd(g, v)
        g = g or 1
        if max((v // g for v in vals), default=0) > INT32_LIMIT:
            return None
        scales.append(g)
    return tuple(scales)


def _dedupe_interned(sids: np.ndarray, gen: int, pod_ids: Sequence[int]):
    """Vectorized pod→shape dedupe over interned shape ids. Returns
    (vecs descending, counts, pod-id groups) with the exact semantics of
    the dict path — shapes ordered descending by full resource vector, pod
    ids within a shape in original batch order — or None when the intern
    table rolled over under the caller (generation mismatch: fall back)."""
    from karpenter_tpu.solver.adapter import interned_vecs_snapshot

    sids = np.asarray(sids, dtype=np.int64)
    uniq, inverse, cnts = np.unique(
        sids, return_inverse=True, return_counts=True)
    uniq_vecs = interned_vecs_snapshot(uniq, gen)
    if uniq_vecs is None:
        return None
    order = sorted(range(len(uniq)),
                   key=lambda i: tuple(-v for v in uniq_vecs[i]))
    pos = np.empty(len(uniq), np.int64)
    pos[np.asarray(order, np.int64)] = np.arange(len(uniq), dtype=np.int64)
    shape_of_pod = pos[inverse]
    sort_order = np.argsort(shape_of_pod, kind="stable")
    pid_sorted = np.asarray(pod_ids, dtype=np.int64)[sort_order]
    counts_ord = cnts[np.asarray(order, np.int64)]
    bounds = np.cumsum(counts_ord)[:-1]
    groups = [seg.tolist() for seg in np.split(pid_sorted, bounds)]
    return ([uniq_vecs[i] for i in order], counts_ord.tolist(), groups)


# -- delta-marshal row arena -------------------------------------------------
#
# The window marshal's steady state: consecutive replay windows share almost
# all of their pods, so re-deriving (interned shape id, special mask) per pod
# per window is pure rework. The arena pins each distinct marshal row —
# (sid, special) — in numpy columns; a pod caches its row index (plus the
# arena generation it was minted in) on its __dict__, and a window's sid
# tensor is ONE numpy gather over the cached rows. Only new or churned
# signatures pay the Python encode.
#
# Invalidation is generational, never in place: the arena generation bumps
# whenever (a) the adapter's shape intern table rebinds (cached sids would
# dangle), (b) the feasibility vocab rebinds (the columnar topology/schedule
# columns derived alongside the marshal must not outlive their vocab), or
# (c) the row capacity overflows. A generation bump voids every cached
# per-pod row atomically (the mismatch makes them misses), so a stale row
# can never be gathered — the chaos suite in tests/test_marshal_delta.py
# forces mid-window bumps and pins bit-for-bit equality with the cold path.


def _arena_max_from_env() -> int:
    raw = os.environ.get("KARPENTER_MARSHAL_ARENA_MAX", "")
    if not raw.strip():
        return 1 << 20
    try:
        return max(1, int(raw.strip()))
    except ValueError:
        import logging

        logging.getLogger("karpenter.ops.encode").warning(
            "KARPENTER_MARSHAL_ARENA_MAX=%r is not an integer; using "
            "default %d", raw, 1 << 20)
        return 1 << 20


class MarshalArena:
    """Pinned, signature-keyed marshal rows (see module block comment)."""

    def __init__(self, cap: Optional[int] = None):
        self.cap = cap if cap is not None else _arena_max_from_env()
        self.generation = 0
        self._lock = threading.Lock()
        size = min(4096, self.cap)
        self._sids = np.empty(max(size, 1), np.int64)
        self._special = np.empty(max(size, 1), np.int64)
        self._rows: dict = {}          # (sid, special) -> row index
        self._n = 0
        self._adapter_gen: Optional[int] = None
        self._vocab_gen: Optional[int] = None

    def _reset_locked(self, adapter_gen, vocab_gen) -> None:
        if self._n:
            MARSHAL_ROW_CACHE_EVICTIONS_TOTAL.inc(amount=float(self._n))
        self._rows.clear()
        self._n = 0
        self.generation += 1
        self._adapter_gen = adapter_gen
        self._vocab_gen = vocab_gen

    def begin_window(self, adapter_gen: int) -> int:
        """Validate against the live intern generations (adapter shape table
        + feasibility vocab); a mismatch resets the arena. Returns the arena
        generation cached pod rows must carry to count as hits."""
        from karpenter_tpu.ops import feasibility

        vocab_gen = feasibility.intern_table_stats()[1]
        with self._lock:
            if (self._adapter_gen != adapter_gen
                    or self._vocab_gen != vocab_gen):
                self._reset_locked(adapter_gen, vocab_gen)
            return self.generation

    def assign(self, sid: int, special: int,
               adapter_gen: int) -> Tuple[int, int]:
        """Row index for (sid, special), minting one on first sight.
        Returns (row, generation) — the generation may have advanced past
        the caller's ``begin_window`` (capacity rollover, or the adapter
        table rebound mid-window); the caller must then restart its gather,
        because every previously collected row index is void."""
        with self._lock:
            if adapter_gen != self._adapter_gen:
                self._reset_locked(adapter_gen, self._vocab_gen)
            row = self._rows.get((sid, special))
            if row is None:
                if self._n >= self.cap:
                    self._reset_locked(self._adapter_gen, self._vocab_gen)
                n = self._n
                if n >= self._sids.shape[0]:
                    grown = min(max(self._sids.shape[0] * 2, 1024), self.cap)
                    self._sids = np.resize(self._sids, grown)
                    self._special = np.resize(self._special, grown)
                self._sids[n] = sid
                self._special[n] = special
                self._rows[(sid, special)] = n
                self._n = n + 1
                row = n
            return row, self.generation

    def gather(self, rows: np.ndarray,
               generation: int) -> Optional[Tuple[np.ndarray, int, int]]:
        """(sid array, OR of special masks, adapter generation) for a
        window's row indices — the single-gather assembly of the window's
        pod tensor inputs. None when the arena generation moved past the
        caller's (concurrent reset): every collected row index is void and
        the caller must restart its window."""
        with self._lock:
            if generation != self.generation:
                return None
            sids = self._sids[rows]
            if rows.size:
                special = int(np.bitwise_or.reduce(self._special[rows]))
            else:
                special = 0
            return sids, special, self._adapter_gen

    def note_window(self, hits: int, misses: int) -> None:
        if hits:
            MARSHAL_ROW_CACHE_HITS_TOTAL.inc(amount=float(hits))
        if misses:
            MARSHAL_ROW_CACHE_MISSES_TOTAL.inc(amount=float(misses))
        total = hits + misses
        if total:
            MARSHAL_DELTA_FRACTION.set(misses / total)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"rows": self._n, "generation": self.generation}


_ARENA: Optional[MarshalArena] = None
_ARENA_LOCK = threading.Lock()


def marshal_arena() -> MarshalArena:
    """The process-wide arena (marshal rows are process-wide state, exactly
    like the shape intern table they index into)."""
    global _ARENA
    with _ARENA_LOCK:
        if _ARENA is None:
            _ARENA = MarshalArena()
        return _ARENA


def reset_marshal_arena() -> None:
    """Drop the process arena (tests; a fresh arena re-counts from zero)."""
    global _ARENA
    with _ARENA_LOCK:
        _ARENA = None


# -- versioned catalog encoding cache ----------------------------------------
#
# The catalog-side device tensors (totals/reserved0/valid) are a pure
# function of (packables-cache version, GCD scales, padded T): the version
# identifies the exact packable list — catalog tokens + constraints-derived
# allowed sets + daemon vectors + required resources, i.e. catalog token +
# constraints fingerprint (adapter.build_packables_versioned) — and the
# scales couple the catalog columns to the pod columns of the SAME window.
# Steady-state windows repeat the key, so they reuse the shared read-only
# arrays AND inherit a content token the device ring uses to skip the
# host→device upload entirely (pipeline.DeviceRing.fill token match).

_CATALOG_ENC_LOCK = threading.Lock()
_CATALOG_ENC_CACHE: dict = {}
_CATALOG_ENC_CAP = 32


def _catalog_encoding(catalog_version: int, scales: Tuple[int, ...],
                      packables: Sequence[Packable], TB: int):
    """(totals, reserved0, valid, token) at padded size ``TB`` — shared
    read-only arrays, rebuilt (and counted) only on a fresh key."""
    T = len(packables)
    key = (catalog_version, scales, TB)
    with _CATALOG_ENC_LOCK:
        hit = _CATALOG_ENC_CACHE.get(key)
    if hit is not None:
        return hit
    totals = np.zeros((TB, NUM_RESOURCES), np.int32)
    reserved0 = np.zeros((TB, NUM_RESOURCES), np.int32)
    valid = np.zeros((TB,), bool)
    for t, p in enumerate(packables):
        totals[t] = [v // g for v, g in zip(p.total, scales)]
        reserved0[t] = [v // g for v, g in zip(p.reserved, scales)]
        valid[t] = True
    for arr in (totals, reserved0, valid):
        arr.setflags(write=False)
    entry = (totals, reserved0, valid, ("cat", catalog_version, scales, TB))
    CATALOG_ENCODING_REBUILDS_TOTAL.inc()
    with _CATALOG_ENC_LOCK:
        if len(_CATALOG_ENC_CACHE) >= _CATALOG_ENC_CAP:
            _CATALOG_ENC_CACHE.pop(next(iter(_CATALOG_ENC_CACHE)))
        _CATALOG_ENC_CACHE[key] = entry
    return entry


def clear_catalog_encoding_cache() -> None:
    """Tests: force the next window to rebuild (and count) fresh."""
    with _CATALOG_ENC_LOCK:
        _CATALOG_ENC_CACHE.clear()


def encode(
    pod_vecs: Sequence[Vec],
    pod_ids: Sequence[int],
    packables: Sequence[Packable],
    pad: bool = True,
    sids: Optional[Tuple[np.ndarray, int]] = None,
    catalog_version: Optional[int] = None,
) -> Optional[EncodedProblem]:
    """Returns None when the problem can't be encoded exactly (host fallback).

    ``pod_vecs`` may be in any order: pods dedupe to shapes via hashing
    (O(pods)) and only the small shape set is sorted — the device solve
    never sorts the pod axis. Pods within a shape are interchangeable.
    ``packables`` must be ascending (adapter.build_packables output).

    All nano-unit arithmetic stays in Python ints until after GCD scaling
    (nano memory values overflow int64 beyond ~9Gi).

    ``pad=True`` (the device path) pads to the static SHAPE/TYPE buckets and
    fails beyond the largest bucket — XLA needs static shapes. ``pad=False``
    (the native C++ executors) emits exact-size arrays with NO cardinality
    limit: host kernels don't recompile per shape, so a 50k-distinct-shape
    problem still gets an exact integer encoding.
    """
    if not packables:
        return None

    # -- dedupe pods into shapes ------------------------------------------
    deduped = None
    if sids is not None and len(sids[0]) == len(pod_vecs):
        # vectorized: interned shape ids (adapter._intern_vec, assigned at
        # marshal/ingest time) dedupe via np.unique — no Python loop over
        # the pod axis. Ordering/grouping semantics are identical to the
        # dict path below (differentially tested in tests/test_encode_limits);
        # an intern-table rollover mid-flight returns None → dict fallback
        deduped = _dedupe_interned(sids[0], sids[1], pod_ids)
    if deduped is not None:
        ordered, counts_list, groups = deduped
    else:
        by_vec: Dict[Vec, List[int]] = {}
        for vec, pid in zip(pod_vecs, pod_ids):
            by_vec.setdefault(vec, []).append(pid)
        # descending by full resource vector: the same total order the host
        # oracle sorts pods with (host_ffd.pack), so tie-breaking agrees
        items = sorted(by_vec.items(), key=lambda kv: tuple(-v for v in kv[0]))
        ordered = [vec for vec, _ in items]
        counts_list = [len(pids) for _, pids in items]
        groups = [pids for _, pids in items]
    shape_vecs: List[List[int]] = []
    counts: List[int] = []
    shape_pods: List[List[int]] = []
    for vec, n, pids in zip(ordered, counts_list, groups):
        reserve_vec = list(vec)
        reserve_vec[R_PODS] += 10**9  # implicit pods:1 in nano units
        shape_vecs.append(reserve_vec)
        counts.append(n)
        shape_pods.append(pids)

    S, T = len(shape_vecs), len(packables)
    SB, TB = S, T
    if pad:
        SB, TB = bucket(S, SHAPE_BUCKETS), bucket(T, TYPE_BUCKETS)
        if SB is None or TB is None:
            return None

    # -- per-resource exact scaling -----------------------------------------
    columns = []
    for r in range(NUM_RESOURCES):
        col = [sv[r] for sv in shape_vecs]
        col += [p.total[r] for p in packables]
        col += [p.reserved[r] for p in packables]
        if r == R_PODS:
            # the kernel subtracts the implicit pods:1 for the early-exit
            # vector, so the scale must divide one pod exactly
            col.append(10**9)
        columns.append(col)
    scales = _gcd_scale(columns)
    if scales is None:
        return None

    shapes = np.zeros((SB, NUM_RESOURCES), np.int32)
    counts_a = np.zeros((SB,), np.int32)
    for s in range(S):
        shapes[s] = [v // g for v, g in zip(shape_vecs[s], scales)]
        counts_a[s] = counts[s]
    token: Optional[tuple] = None
    if catalog_version is not None:
        totals, reserved0, valid, token = _catalog_encoding(
            catalog_version, scales, packables, TB)
    else:
        totals = np.zeros((TB, NUM_RESOURCES), np.int32)
        reserved0 = np.zeros((TB, NUM_RESOURCES), np.int32)
        valid = np.zeros((TB,), bool)
        for t, p in enumerate(packables):
            totals[t] = [v // g for v, g in zip(p.total, scales)]
            reserved0[t] = [v // g for v, g in zip(p.reserved, scales)]
            valid[t] = True

    return EncodedProblem(
        shapes=shapes, counts=counts_a, totals=totals, reserved0=reserved0,
        valid=valid, last_valid=T - 1, num_shapes=S, num_types=T,
        shape_pods=shape_pods, scales=scales,
        pods_unit=10**9 // scales[R_PODS],
        catalog_token=token,
    )


def pad_encoding(enc: EncodedProblem) -> Optional[EncodedProblem]:
    """Pad an exact-size encoding (``encode(pad=False)``) to the static
    device buckets; None above the largest bucket. Lets the solve path
    encode ONCE and serve both the device ring (padded) and the native C++
    ring (exact-size) without re-running the O(pods) dedupe + GCD scaling."""
    S, T = enc.num_shapes, enc.num_types
    if enc.shapes.shape[0] != S or enc.totals.shape[0] != T:
        return enc  # already padded
    SB, TB = bucket(S, SHAPE_BUCKETS), bucket(T, TYPE_BUCKETS)
    if SB is None or TB is None:
        return None
    shapes = np.zeros((SB, NUM_RESOURCES), np.int32)
    counts = np.zeros((SB,), np.int32)
    totals = np.zeros((TB, NUM_RESOURCES), np.int32)
    reserved0 = np.zeros((TB, NUM_RESOURCES), np.int32)
    valid = np.zeros((TB,), bool)
    shapes[:S] = enc.shapes
    counts[:S] = enc.counts
    totals[:T] = enc.totals
    reserved0[:T] = enc.reserved0
    valid[:T] = enc.valid
    return EncodedProblem(
        shapes=shapes, counts=counts, totals=totals, reserved0=reserved0,
        valid=valid, last_valid=enc.last_valid, num_shapes=S, num_types=T,
        shape_pods=enc.shape_pods, scales=enc.scales,
        pods_unit=enc.pods_unit,
        # the padded catalog content is a pure function of the exact content
        # plus the bucket, so the identity extends rather than resets
        catalog_token=(enc.catalog_token + ("pad", TB)
                       if enc.catalog_token is not None else None),
    )
