"""Columnar constraint filter: the interned-bitset twin of the scalar
requirement algebra (api/requirements.py, api/constraints.py).

The control plane's hot loop is "constraint filter + bin-packing". The
packing half runs on device; this module makes the filter half columnar.
Label values are interned into dense bit positions per key, each key's
``(∩ In) ∖ (∪ NotIn)`` set becomes a packed bitmask (a Python int of
arbitrary width — one bit per interned value), and the three per-pod hot
loops evaluate as mask algebra:

- pod × provisioner validation  (``validate_pod_fast`` /
  ``CompiledConstraints.validate``) — Scheduler._get_schedules and
  SelectionController._select_provisioner
- constraint tightening signatures (``CompiledConstraints.schedule_entry``)
  — ``tighten()`` runs once per signature instead of once per pod, and the
  schedule group key is exactly ``scheduler._constraints_key`` of the
  tightened result (scheduler.go:100-110 SlicesAsSets semantics)
- pod-set × instance-type feasibility (``catalog_feasibility_mask``) — the
  whole catalog validated as numpy (optionally JAX) boolean columns,
  memoized by catalog generation + allowed sets

Exactness contract (same as ops/encode.py): exactness is never traded for
speed. Quirks of the scalar algebra are preserved bit-for-bit:

- NotIn-without-In collapses to the empty set, not "unconstrained"
  (requirements.go:189-194 — Go's nil.Difference returns non-nil empty);
  modeled by ``has_notin`` forcing ``(r or 0) & ~notin`` even when no In
  row exists, including a NotIn with an empty values list.
- Alias keys (wellknown.NORMALIZED_LABELS) are normalized on the POD side
  (mirroring pod_requirements' add()) but looked up literally on the
  constraint side (mirroring requirement(key)'s literal match) — a raw
  un-normalized constraint row keeps failing exactly as it does today.
- Operators other than In/NotIn on constraint rows are skipped (they never
  reach ``requirement()``'s loops); on pod rows, Exists/DoesNotExist
  contribute key presence only, and anything else (Gt/Lt/unknown) sends
  the pod to the scalar path — counted in karpenter_filter_fallback_total.
- Go's sets.Has(nil) == false: an unconstrained allowed-set REJECTS every
  catalog entry (the provisioning controller always injects the universe
  first, adapter._validate's note).
- Taint toleration replays core/v1 ToleratesTaint exactly, including the
  "Exists tolerations must not carry a value" rule.

Any verdict the engine cannot produce (unsupported operator, compile
failure, >64 operating systems in one catalog) falls back to the scalar
path. When the engine says "fail" it re-runs the scalar validator for the
exact error string — if the scalar path disagrees and passes, the scalar
answer wins (self-healing; counted as reason="verdict-mismatch"), so a
divergence can never reject a schedulable pod in production. The fuzz
suite (tests/test_feasibility.py) compares the RAW engine verdict against
the scalar oracle to keep that guarantee honest.

Interning is global, generation-bounded (KARPENTER_FEASIBILITY_INTERN_MAX,
default 65536 values) like the adapter's shape intern table: crossing the
cap REBINDS the vocab (never mutates the per-key dicts), so compiled
objects holding the old dicts stay internally consistent forever and new
compiles start a fresh generation.
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.constraints import Constraints
from karpenter_tpu.api.core import Pod
from karpenter_tpu.api.requirements import IN, NOT_IN
from karpenter_tpu.metrics.filter import (
    FILTER_BATCH_SECONDS, FILTER_FALLBACK_TOTAL, FILTER_INTERN_TABLE_SIZE,
)
from karpenter_tpu.utils import resources as res

log = logging.getLogger("karpenter.feasibility")

_PRESENCE_OPS = ("Exists", "DoesNotExist")

# -- global value intern table ----------------------------------------------
#
# {key: {value: single-bit mask}}. Bit positions are dense per key and
# append-only within a dict's lifetime. On overflow the TOP-LEVEL dict is
# rebound (never cleared): compiled constraints keep references to the
# per-key dicts they interned against, so their masks stay valid across
# generations; only sharing with future compiles is lost.


def _intern_max_from_env() -> int:
    raw = os.environ.get("KARPENTER_FEASIBILITY_INTERN_MAX", "")
    if not raw.strip():
        return 1 << 16
    try:
        return max(1, int(raw.strip()))
    except ValueError:
        log.warning("KARPENTER_FEASIBILITY_INTERN_MAX=%r is not an integer; "
                    "using default %d", raw, 1 << 16)
        return 1 << 16


_INTERN_MAX = _intern_max_from_env()
_INTERN_LOCK = threading.Lock()
_VOCAB: Dict[str, Dict[str, int]] = {}
_VOCAB_SIZE = 0
_VOCAB_GEN = 0


def _intern_value(vocab: Dict[str, int], value: str) -> int:
    """Single-bit mask for ``value`` in this key's vocab; caller holds
    _INTERN_LOCK. A dict handed out before a generation reset keeps
    growing privately — correct, just unshared."""
    global _VOCAB, _VOCAB_SIZE, _VOCAB_GEN
    m = vocab.get(value)
    if m is None:
        if _VOCAB_SIZE >= _INTERN_MAX:
            _VOCAB = {}
            _VOCAB_SIZE = 0
            _VOCAB_GEN += 1
            FILTER_FALLBACK_TOTAL.inc(reason="intern-reset")
        m = 1 << len(vocab)
        vocab[value] = m
        _VOCAB_SIZE += 1
    return m


def intern_table_stats() -> Tuple[int, int]:
    """(live size, generation) — tests and diagnostics."""
    with _INTERN_LOCK:
        return _VOCAB_SIZE, _VOCAB_GEN


def reset_intern_table() -> None:
    """Force a generation reset (tests)."""
    global _VOCAB, _VOCAB_SIZE, _VOCAB_GEN
    with _INTERN_LOCK:
        _VOCAB = {}
        _VOCAB_SIZE = 0
        _VOCAB_GEN += 1
    FILTER_INTERN_TABLE_SIZE.set(0)


# -- compiled constraints ----------------------------------------------------


class _KeyFilter:
    """One key's constraint-side state: vocab ref + In/NotIn masks + the
    precomputed own-requirement result (None=unconstrained, int=mask)."""

    __slots__ = ("vocab", "in_mask", "notin_mask", "has_notin", "own")

    def __init__(self, vocab: Dict[str, int]):
        self.vocab = vocab
        self.in_mask: Optional[int] = None
        self.notin_mask = 0
        self.has_notin = False
        self.own: Optional[int] = None


_MISSING = object()
_CACHE_CAP = 16384


class CompiledConstraints:
    """Bitset form of one Constraints object. Attached to the object's
    ``__dict__`` (the pod ``_marshal`` precedent) and shared, never copied:
    ``__deepcopy__`` returns self, and the identity fingerprint mismatches
    on the copy, forcing a fresh compile there."""

    __slots__ = ("fingerprint", "cref", "filters", "taints",
                 "_val_cache", "_sched_cache")

    def __init__(self, fingerprint, cref: Constraints,
                 filters: Dict[str, _KeyFilter], taints: tuple):
        self.fingerprint = fingerprint
        self.cref = cref
        self.filters = filters
        self.taints = taints
        self._val_cache: dict = {}
        self._sched_cache: dict = {}

    def __deepcopy__(self, memo):
        return self

    def __copy__(self):
        return self

    # -- raw bitset verdict (the fuzz-tested core) --------------------------
    def _raw_ok(self, sig) -> bool:
        """True iff the pod signature passes — the mask-algebra mirror of
        Constraints.validate_pod's three stages. Boolean only; error
        strings always come from the scalar path."""
        rows, tols, _gpus = sig
        for taint in self.taints:
            tolerated = False
            for tk, top, tv, te in tols:
                if te and te != taint.effect:
                    continue
                if tk and tk != taint.key:
                    continue
                if top == "Exists":
                    if tv == "":
                        tolerated = True
                        break
                elif top == "" or top == "Equal":
                    if tv == taint.value:
                        tolerated = True
                        break
            if not tolerated:
                return False
        if not rows:
            return True
        filters = self.filters
        order: List[str] = []
        grouped: Dict[str, list] = {}
        for key, op, vals in rows:
            g = grouped.get(key)
            if g is None:
                g = grouped[key] = []
                order.append(key)
            g.append((op, vals))
        for key in order:
            kf = filters.get(key)
            if kf is None or not kf.own:
                # own requirement None (unconstrained) or empty: loop 1 of
                # validate_pod rejects either way
                return False
            r = kf.in_mask
            notin = kf.notin_mask
            has_notin = kf.has_notin
            vocab = kf.vocab
            for op, vals in grouped[key]:
                if op == IN:
                    m = 0
                    for v in vals:
                        b = vocab.get(v)
                        if b is not None:
                            # a value the constraint never interned cannot
                            # be in any constraint set: dropping it from the
                            # In mask is exact, and bounds vocab growth
                            m |= b
                    r = m if r is None else (r & m)
                elif op == NOT_IN:
                    for v in vals:
                        b = vocab.get(v)
                        if b is not None:  # subtracting unknown is a no-op
                            notin |= b
                    has_notin = True
                # Exists/DoesNotExist assert key presence only:
                # requirement() never reads them (requirements.go:176-195)
            if has_notin:
                r = (r if r is not None else 0) & ~notin
            if not r:
                return False
        return True

    # -- validation with exact scalar error strings -------------------------
    def validate(self, pod: Pod) -> Optional[str]:
        """Drop-in for ``constraints.validate_pod(pod)``: same verdict, same
        error strings, memoized per pod signature."""
        sig = pod_signature(pod)
        if sig is None:
            return self.cref.validate_pod(pod)
        hit = self._val_cache.get(sig, _MISSING)
        if hit is not _MISSING:
            return hit
        if self._raw_ok(sig):
            out = None
        else:
            out = self.cref.validate_pod(pod)
            if out is None:
                FILTER_FALLBACK_TOTAL.inc(reason="verdict-mismatch")
        if len(self._val_cache) >= _CACHE_CAP:
            self._val_cache.clear()
        self._val_cache[sig] = out
        return out

    # -- scheduler entry: validate + memoized tighten + group key -----------
    def schedule_entry(self, pod: Pod):
        """(err, tightened, group_key) for one pod. ``tighten()`` runs once
        per signature; the key equals
        ``_constraints_key(cref.tighten(pod), res.gpu_limits_for(pod))``
        because the GPU-request axis is part of the signature and the rest
        is a pure function of it."""
        sig = pod_signature(pod)
        if sig is None:
            c = self.cref
            err = c.validate_pod(pod)
            if err is not None:
                return err, None, None
            tightened = c.tighten(pod)
            gpus = tuple(sorted(
                (k, q.nano) for k, q in res.gpu_limits_for(pod).items()))
            return None, tightened, constraints_key_parts(tightened) + (gpus,)
        hit = self._sched_cache.get(sig)
        if hit is None:
            if self._raw_ok(sig):
                err = None
            else:
                err = self.cref.validate_pod(pod)
                if err is None:
                    FILTER_FALLBACK_TOTAL.inc(reason="verdict-mismatch")
            if err is not None:
                hit = (err, None, None)
            else:
                tightened = self.cref.tighten(pod)
                hit = (None, tightened, constraints_key_parts(tightened))
            if len(self._sched_cache) >= _CACHE_CAP:
                self._sched_cache.clear()
            self._sched_cache[sig] = hit
        err, tightened, parts = hit
        if err is not None:
            return err, None, None
        return None, tightened, parts + (sig[2],)


class _CompileFailed:
    """Negative-cache marker so a constraints object that failed to compile
    is not re-attempted per pod."""

    __slots__ = ("fingerprint",)

    def __deepcopy__(self, memo):
        return self


def _fingerprint(c: Constraints) -> tuple:
    # Identity + length: every in-repo mutation of a live constraints object
    # (topology.inject appending hostname rows) changes a length; wholesale
    # replacement changes an id. Copies (fastcopy/deepcopy) always get fresh
    # ids, so a shared CompiledConstraints can never serve a copy stale.
    return (id(c.requirements), len(c.requirements.items),
            id(c.taints), len(c.taints))


def compile_constraints(c: Constraints) -> Optional[CompiledConstraints]:
    """Compile (or fetch the cached compile of) a Constraints object.
    None means the scalar path must be used for every decision."""
    fp = _fingerprint(c)
    cached = c.__dict__.get("_feas_compiled")
    if cached is not None and cached.fingerprint == fp:
        return cached if type(cached) is CompiledConstraints else None
    try:
        cc = _compile(c, fp)
    except Exception:
        log.warning("feasibility compile failed; using scalar path",
                    exc_info=True)
        FILTER_FALLBACK_TOTAL.inc(reason="compile-error")
        failed = _CompileFailed()
        failed.fingerprint = fp
        c.__dict__["_feas_compiled"] = failed
        return None
    c.__dict__["_feas_compiled"] = cc
    return cc


def _compile(c: Constraints, fp: tuple) -> CompiledConstraints:
    filters: Dict[str, _KeyFilter] = {}
    with _INTERN_LOCK:
        for r in c.requirements.items:
            op = r.operator
            if op != IN and op != NOT_IN:
                # requirement() ignores these rows entirely; their keys only
                # matter via keys(), which validation never consults on the
                # constraint side
                continue
            kf = filters.get(r.key)
            if kf is None:
                vocab = _VOCAB.get(r.key)
                if vocab is None:
                    vocab = _VOCAB[r.key] = {}
                kf = filters[r.key] = _KeyFilter(vocab)
            m = 0
            for v in r.values:
                m |= _intern_value(kf.vocab, v)
            if op == IN:
                kf.in_mask = m if kf.in_mask is None else (kf.in_mask & m)
            else:
                kf.notin_mask |= m
                kf.has_notin = True
        size = _VOCAB_SIZE
    FILTER_INTERN_TABLE_SIZE.set(size)
    for kf in filters.values():
        own = kf.in_mask
        if kf.has_notin:
            own = (own if own is not None else 0) & ~kf.notin_mask
        kf.own = own
    return CompiledConstraints(fp, c, filters, tuple(c.taints))


# -- pod signatures ----------------------------------------------------------


def pod_signature(pod: Pod):
    """(filter rows, tolerations, gpu requests) — the pod's entire input to
    validation + grouping, as a hashable value. Rows mirror
    pod_requirements' extraction exactly: nodeSelector (normalized, In),
    then the heaviest preferred term, then required[0]. None means an
    operator outside {In, NotIn, Exists, DoesNotExist} appeared — scalar
    fallback. Never cached on the Pod: topology injection and preference
    relaxation mutate pod specs between calls."""
    normalized = wellknown.NORMALIZED_LABELS
    rows = []
    for key, value in pod.spec.node_selector.items():
        rows.append((normalized.get(key, key), IN, (value,)))
    affinity = pod.spec.affinity
    if affinity is not None and affinity.node_affinity is not None:
        na = affinity.node_affinity
        exprs = []
        if na.preferred:
            heaviest = max(na.preferred, key=lambda t: t.weight)
            exprs.extend(heaviest.preference.match_expressions)
        if na.required:
            exprs.extend(na.required[0].match_expressions)
        for r in exprs:
            op = r.operator
            if op != IN and op != NOT_IN and op not in _PRESENCE_OPS:
                FILTER_FALLBACK_TOTAL.inc(reason="unsupported-operator")
                return None
            rows.append((normalized.get(r.key, r.key), op, tuple(r.values)))
    tols = tuple((t.key, t.operator, t.value, t.effect)
                 for t in pod.spec.tolerations)
    gpus = tuple(sorted(
        (k, q.nano) for k, q in res.gpu_limits_for(pod).items()))
    return (tuple(rows), tols, gpus)


def constraints_key_parts(c: Constraints) -> tuple:
    """The (requirements, taints, labels) parts of the schedule group key —
    scheduler.go:100-110 SlicesAsSets semantics (order-insensitive).
    scheduler._constraints_key is these parts + the GPU-request axis."""
    reqs = tuple(sorted(
        (r.key, r.operator, tuple(sorted(r.values)))
        for r in c.requirements.items))
    taints = tuple(sorted((t.key, t.value, t.effect) for t in c.taints))
    labels = tuple(sorted(c.labels.items()))
    return (reqs, taints, labels)


def topology_allowed(cc: CompiledConstraints, sig, key: str):
    """Columnar twin of the topology-spread allowed-domain query
    (scheduling/topology.py):

        constraints.requirements.add(*pod_requirements(pod).items)
                   .requirement(key)

    for any pod whose ``pod_signature`` is ``sig``. Returns the same
    ``Optional[frozenset]``: None = unconstrained, a set = allowed domains.

    The combined requirement list is the constraint rows (compiled into
    ``cc.filters``) plus the pod rows (already normalized in the
    signature); ``requirement()`` evaluates all In rows first, then all
    NotIn rows, so list order beyond that split is irrelevant and the two
    sides compose as set algebra:

    - Constraint side has an In row (``kf.in_mask is not None``): the
      result is a subset of the constraint's In set, which is fully
      interned — exact mask algebra, pod values outside the vocab can
      only shrink the intersection and drop out anyway. Surviving bits
      decode back to strings through the key's vocab (under the intern
      lock: the dict may be growing concurrently).
    - Constraint side has only NotIn rows, or no rows at all: pod In
      values the constraint never interned are legitimate members of the
      result, so the pod side runs in string space and the constraint
      NotIn mask is decoded to strings before subtraction. The Go quirk
      carries over: any NotIn row with no In row anywhere collapses to
      the empty set, never to "unconstrained" (requirements.go:189-194).
    """
    rows, _tols, _gpus = sig
    pod_in: List[tuple] = []
    pod_notin: List[tuple] = []
    for k, op, vals in rows:
        if k != key:
            continue
        if op == IN:
            pod_in.append(vals)
        elif op == NOT_IN:
            pod_notin.append(vals)
        # presence ops assert key existence only; requirement() skips them
    kf = cc.filters.get(key)
    if kf is not None and kf.in_mask is not None:
        r = kf.in_mask
        notin = kf.notin_mask
        vocab = kf.vocab
        for vals in pod_in:
            m = 0
            for v in vals:
                b = vocab.get(v)
                if b is not None:
                    m |= b
            r &= m
        for vals in pod_notin:
            for v in vals:
                b = vocab.get(v)
                if b is not None:
                    notin |= b
        r &= ~notin
        out = set()
        with _INTERN_LOCK:
            for v, b in vocab.items():
                if r & b:
                    out.add(v)
        return frozenset(out)
    # string space: constraint contributes at most a NotIn mask
    result: Optional[set] = None
    for vals in pod_in:
        s = set(vals)
        result = s if result is None else (result & s)
    if kf is not None and kf.has_notin:
        notin_vals = set()
        with _INTERN_LOCK:
            for v, b in kf.vocab.items():
                if kf.notin_mask & b:
                    notin_vals.add(v)
        result = (result or set()) - notin_vals
    for vals in pod_notin:
        result = (result or set()) - set(vals)
    return frozenset(result) if result is not None else None


def validate_pod_fast(constraints: Constraints, pod: Pod) -> Optional[str]:
    """Engine-accelerated ``constraints.validate_pod(pod)`` — identical
    verdicts and error strings, scalar on any fallback condition."""
    cc = compile_constraints(constraints)
    if cc is None:
        return constraints.validate_pod(pod)
    return cc.validate(pod)


# -- whole-catalog feasibility mask ------------------------------------------
#
# The type axis is the real batch here: columns over instance types, one
# boolean lookup per allowed-set, combined with elementwise AND (numpy, or
# JAX behind KARPENTER_FEASIBILITY_BACKEND=jax). Memoized by catalog
# generation (a monotonic token per InstanceType object, the adapter's
# _instance_token pattern) + allowed sets + required resources.

_token_counter = itertools.count(1)
_CATALOG_LOCK = threading.Lock()
_INDEX_CACHE: dict = {}
_INDEX_CACHE_CAP = 8
_INDEX_FAILED = object()
_MASK_CACHE: dict = {}
_MASK_CACHE_CAP = 128

_GPU_CLASSES = (res.NVIDIA_GPU, res.AMD_GPU, res.AWS_NEURON)


def _catalog_token(it) -> int:
    tok = it.__dict__.get("_feas_token")
    if tok is None:
        tok = it.__dict__["_feas_token"] = next(_token_counter)
    return tok


class CatalogIndex:
    """Columnar view of one instance-type catalog."""

    __slots__ = ("n", "name_vocab", "name_col", "arch_vocab", "arch_col",
                 "os_vocab", "os_mask", "ct_vocab", "zone_vocab",
                 "offer_type", "offer_ct", "offer_zone", "eni_zero",
                 "gpu_zero")


def _build_catalog_index(instance_types) -> Optional[CatalogIndex]:
    n = len(instance_types)
    idx = CatalogIndex()
    idx.n = n
    idx.name_vocab = {}
    idx.arch_vocab = {}
    idx.os_vocab = {}
    idx.ct_vocab = {}
    idx.zone_vocab = {}
    idx.name_col = np.zeros(n, np.int32)
    idx.arch_col = np.zeros(n, np.int32)
    idx.os_mask = np.zeros(n, np.uint64)
    idx.eni_zero = np.zeros(n, bool)
    idx.gpu_zero = {name: np.zeros(n, bool) for name in _GPU_CLASSES}
    ot: List[int] = []
    oc: List[int] = []
    oz: List[int] = []
    for t, it in enumerate(instance_types):
        idx.name_col[t] = idx.name_vocab.setdefault(it.name, len(idx.name_vocab))
        idx.arch_col[t] = idx.arch_vocab.setdefault(
            it.architecture, len(idx.arch_vocab))
        m = 0
        for os_name in it.operating_systems:
            b = idx.os_vocab.setdefault(os_name, len(idx.os_vocab))
            if b >= 64:
                # a single uint64 word per type keeps the column dense;
                # catalogs with >64 distinct OS values use the scalar path
                return None
            m |= 1 << b
        idx.os_mask[t] = m
        for o in it.offerings:
            ot.append(t)
            oc.append(idx.ct_vocab.setdefault(o.capacity_type, len(idx.ct_vocab)))
            oz.append(idx.zone_vocab.setdefault(o.zone, len(idx.zone_vocab)))
        idx.eni_zero[t] = it.aws_pod_eni.is_zero()
        idx.gpu_zero[res.NVIDIA_GPU][t] = it.nvidia_gpus.is_zero()
        idx.gpu_zero[res.AMD_GPU][t] = it.amd_gpus.is_zero()
        idx.gpu_zero[res.AWS_NEURON][t] = it.aws_neurons.is_zero()
    idx.offer_type = np.array(ot, np.int64)
    idx.offer_ct = np.array(oc, np.int64)
    idx.offer_zone = np.array(oz, np.int64)
    return idx


def _vocab_ok(vocab: Dict[str, int], allowed) -> np.ndarray:
    """Boolean lookup table over a local vocab. ``allowed`` None rejects
    everything — Go's sets.Has(nil) is false (adapter._validate's note)."""
    ok = np.zeros(len(vocab), bool)
    if allowed:
        for v, i in vocab.items():
            if v in allowed:
                ok[i] = True
    return ok


def _combine_columns(cols, n: int) -> np.ndarray:
    # Always numpy. The old KARPENTER_FEASIBILITY_BACKEND=jax leg — which
    # re-transferred every column host→device per call and was strictly
    # slower than this AND-reduce — folded into the device-resident fused
    # filter (ops/device_filter.py): the env value now aliases to
    # device_filter.enabled(), where the whole mask is computed FROM
    # device-resident bit-planes instead of re-shipped columns, and the
    # "jax-backend-unavailable" fallback counter lives on.
    acc = np.ones(n, bool)
    for c in cols:
        acc &= c
    return acc


def _compute_mask(idx: CatalogIndex, allowed: tuple,
                  required: frozenset) -> np.ndarray:
    cts, zones, its, archs, oss = allowed
    n = idx.n
    ct_ok = _vocab_ok(idx.ct_vocab, cts)
    zone_ok = _vocab_ok(idx.zone_vocab, zones)
    row_ok = ct_ok[idx.offer_ct] & zone_ok[idx.offer_zone]
    offer_ok = np.bincount(
        idx.offer_type[row_ok], minlength=n).astype(bool)[:n]
    name_ok = _vocab_ok(idx.name_vocab, its)[idx.name_col]
    arch_ok = _vocab_ok(idx.arch_vocab, archs)[idx.arch_col]
    os_bits = 0
    if oss:
        for v, b in idx.os_vocab.items():
            if v in oss:
                os_bits |= 1 << b
    os_ok = (idx.os_mask & np.uint64(os_bits)) != 0
    cols = [offer_ok, name_ok, arch_ok, os_ok]
    if res.AWS_POD_ENI in required:
        cols.append(~idx.eni_zero)
    for name in _GPU_CLASSES:
        zero = idx.gpu_zero[name]
        # GPU classes are exclusive both ways (packable.go:205-219)
        cols.append(~zero if name in required else zero)
    mask = _combine_columns(cols, n)
    mask.flags.writeable = False
    return mask


def catalog_feasibility_mask(instance_types, allowed: tuple,
                             required: frozenset) -> Optional[np.ndarray]:
    """Per-type viability (True = adapter._validate would return None) for
    the whole catalog, or None when the catalog cannot be indexed. The
    result array is shared and read-only."""
    tokens = tuple(_catalog_token(it) for it in instance_types)
    mkey = (tokens, allowed, required)
    with _CATALOG_LOCK:
        hit = _MASK_CACHE.get(mkey)
        if hit is not None:
            return hit
        idx = _INDEX_CACHE.get(tokens)
    if idx is _INDEX_FAILED:
        return None
    t0 = time.perf_counter()
    if idx is None:
        idx = _build_catalog_index(instance_types)
        if idx is None:
            FILTER_FALLBACK_TOTAL.inc(reason="os-vocab-overflow")
            with _CATALOG_LOCK:
                if len(_INDEX_CACHE) >= _INDEX_CACHE_CAP:
                    _INDEX_CACHE.pop(next(iter(_INDEX_CACHE)))
                _INDEX_CACHE[tokens] = _INDEX_FAILED
            return None
        with _CATALOG_LOCK:
            if len(_INDEX_CACHE) >= _INDEX_CACHE_CAP:
                _INDEX_CACHE.pop(next(iter(_INDEX_CACHE)))
            _INDEX_CACHE[tokens] = idx
    mask = _compute_mask(idx, allowed, required)
    FILTER_BATCH_SECONDS.observe(time.perf_counter() - t0, stage="catalog")
    with _CATALOG_LOCK:
        if len(_MASK_CACHE) >= _MASK_CACHE_CAP:
            _MASK_CACHE.pop(next(iter(_MASK_CACHE)))
        _MASK_CACHE[mkey] = mask
    return mask


# -- group-level (gang) columns ----------------------------------------------
#
# A gang's allowed-offering mask is the AND of its members' per-type
# feasibility columns intersected with a slice-compatibility column
# (offering topology ⊇ requested slice shape) — the same mask-space algebra
# `_compute_mask` runs over, with the same scalar self-heal contract
# `topology_allowed` carries: any suspicious mask-space answer is re-derived
# from the scalar per-member oracle, the scalar verdict wins, and the
# divergence is counted on FILTER_FALLBACK_TOTAL. Built once per gang
# signature (catalog tokens + distinct member keys + slice shape) — a
# 256-gang window whose gangs share constraints pays for one column.

_GANG_MASK_CACHE: dict = {}
_GANG_MASK_CACHE_CAP = 128
_SLICE_COL_CACHE: dict = {}
_SLICE_COL_CACHE_CAP = 64


def _slice_column(instance_types, tokens: tuple, shape) -> np.ndarray:
    """Per-type slice compatibility column, cached per (catalog, shape)."""
    skey = (tokens, str(shape))
    with _CATALOG_LOCK:
        col = _SLICE_COL_CACHE.get(skey)
        if col is not None:
            return col
    from karpenter_tpu.api.gang import instance_slice_shape, slice_fits

    col = np.fromiter(
        (slice_fits(instance_slice_shape(it), shape) for it in instance_types),
        dtype=bool, count=len(instance_types))
    col.flags.writeable = False
    with _CATALOG_LOCK:
        if len(_SLICE_COL_CACHE) >= _SLICE_COL_CACHE_CAP:
            _SLICE_COL_CACHE.pop(next(iter(_SLICE_COL_CACHE)))
        _SLICE_COL_CACHE[skey] = col
    return col


def gang_scalar_mask(instance_types, member_keys, slice_shape) -> np.ndarray:
    """The scalar per-member oracle: type t is gang-viable iff
    adapter._validate accepts it for EVERY member (allowed, required) key
    and its advertised topology contains the requested slice. This is the
    reference semantics the columnar path must reproduce exactly
    (tests/test_gang.py fuzzes the two against each other)."""
    from karpenter_tpu.api.gang import instance_slice_shape, slice_fits
    from karpenter_tpu.solver.adapter import _validate

    out = np.zeros(len(instance_types), bool)
    for t, it in enumerate(instance_types):
        if any(_validate(it, allowed, required) is not None
               for allowed, required in member_keys):
            continue
        if slice_shape is not None and not slice_fits(
                instance_slice_shape(it), slice_shape):
            continue
        out[t] = True
    return out


def gang_feasibility_mask(instance_types, member_keys,
                          slice_shape=None) -> np.ndarray:
    """Group-level feasibility column for one gang: True = every member's
    scalar validators accept the type AND the type can carve the requested
    slice (when one is declared). ``member_keys`` is a sequence of
    (allowed, required) pairs as :func:`catalog_feasibility_mask` takes —
    one per member (duplicates collapse; a gang whose members share
    tightened constraints costs one column). Never returns None: when the
    catalog cannot be indexed the scalar oracle fills in. The result is
    shared and read-only."""
    tokens = tuple(_catalog_token(it) for it in instance_types)
    distinct = tuple(sorted(set(member_keys)))
    gkey = (tokens, distinct, str(slice_shape) if slice_shape else "")
    with _CATALOG_LOCK:
        hit = _GANG_MASK_CACHE.get(gkey)
    if hit is not None:
        return hit
    t0 = time.perf_counter()
    mask: Optional[np.ndarray] = None
    if distinct:
        # device leg first (when on): the member-AND column computed from
        # the persistent catalog bit-planes in ONE device call
        # (ops/device_filter.py), instead of one host columnar mask per
        # distinct member key. None → host/scalar legs below, unchanged;
        # the all-False self-heal applies to either leg's verdict.
        try:
            from karpenter_tpu.ops import device_filter

            mask = device_filter.gang_member_column(instance_types, distinct)
        except Exception:
            mask = None
    if mask is None:
        mask = np.ones(len(instance_types), bool)
        for allowed, required in distinct:
            col = catalog_feasibility_mask(instance_types, allowed, required)
            if col is None:
                mask = None  # catalog not indexable: scalar path
                break
            mask = mask & col
    if mask is not None and slice_shape is not None:
        mask = mask & _slice_column(instance_types, tokens, slice_shape)
    if mask is None:
        mask = gang_scalar_mask(instance_types, distinct, slice_shape)
        FILTER_FALLBACK_TOTAL.inc(reason="gang-unindexable")
    elif distinct and not mask.any():
        # scalar self-heal (the topology_allowed contract): an all-False
        # group column is re-derived from the scalar oracle; scalar wins.
        scalar = gang_scalar_mask(instance_types, distinct, slice_shape)
        if scalar.any():
            FILTER_FALLBACK_TOTAL.inc(reason="gang-mismatch")
            mask = scalar
    mask = np.asarray(mask, bool)
    mask.flags.writeable = False
    FILTER_BATCH_SECONDS.observe(time.perf_counter() - t0, stage="gang")
    with _CATALOG_LOCK:
        if len(_GANG_MASK_CACHE) >= _GANG_MASK_CACHE_CAP:
            _GANG_MASK_CACHE.pop(next(iter(_GANG_MASK_CACHE)))
        _GANG_MASK_CACHE[gkey] = mask
    return mask


# -- pod-pod affinity: per-signature peer columns ----------------------------
#
# Required pod-(anti-)affinity on the hostname topology key compiles to a
# selectors × peers boolean match matrix: S distinct LabelSelector
# signatures evaluated against P distinct pod-label signatures as numpy
# column algebra (one interned value-id column per key), instead of S×P
# scalar LabelSelector.matches calls. The device twin
# (ops/device_filter.affinity_matrix) computes the same matrix from packed
# (key, value) pair bit-planes in one call. Either leg's verdict stays a
# FILTER: sampled cells are re-checked against the scalar matches() oracle
# and any divergence recomputes the whole matrix scalar — counted as
# filter_fallback_total{reason="affinity-mismatch"}.
# KARPENTER_POLICY_COLUMNAR=0 is the kill switch (scalar matrix outright).

_AFFINITY_ENV = "KARPENTER_POLICY_COLUMNAR"
_AFFINITY_OPS = frozenset({"In", "NotIn", "Exists", "DoesNotExist"})
_AFFINITY_PROBE_K = 32


def affinity_columnar_enabled() -> bool:
    return os.environ.get(_AFFINITY_ENV, "").strip() != "0"


def labels_signature(labels: Dict[str, str]) -> tuple:
    """Hashable identity of one pod's label set — the peer axis is deduped
    by this, so a 10k-replica deployment is ONE peer column."""
    return tuple(sorted(labels.items()))


def selector_signature(sel) -> Optional[tuple]:
    """Hashable identity of a LabelSelector, or None when it carries an
    operator outside {In, NotIn, Exists, DoesNotExist} — such selectors
    send the whole matrix to the scalar path (matches() silently skips
    unknown operators; the columnar mirror refuses to guess instead)."""
    for e in sel.match_expressions:
        if e.operator not in _AFFINITY_OPS:
            return None
    return (tuple(sorted(sel.match_labels.items())),
            tuple((e.key, e.operator, tuple(e.values))
                  for e in sel.match_expressions))


def _affinity_scalar(selectors, peer_sigs) -> np.ndarray:
    """The scalar oracle: LabelSelector.matches per cell — the reference
    semantics both columnar legs must reproduce exactly."""
    out = np.zeros((len(selectors), len(peer_sigs)), bool)
    dicts = [dict(sig) for sig in peer_sigs]
    for s, sel in enumerate(selectors):
        for p, labels in enumerate(dicts):
            out[s, p] = sel.matches(labels)
    return out


def _affinity_columnar(selectors, peer_sigs) -> np.ndarray:
    """Host columnar leg: per-key (presence, value-id) peer columns, one
    vector op per selector clause. Mirrors matches() clause by clause:
    an absent key fails match_labels and In, passes NotIn."""
    P = len(peer_sigs)
    key_cols: Dict[str, tuple] = {}

    def cols_for(key: str):
        ent = key_cols.get(key)
        if ent is None:
            has = np.zeros(P, bool)
            vid = np.full(P, -1, np.int64)
            vocab: Dict[str, int] = {}
            for p, sig in enumerate(peer_sigs):
                for k, v in sig:
                    if k == key:
                        has[p] = True
                        vid[p] = vocab.setdefault(v, len(vocab))
                        break
            ent = key_cols[key] = (has, vid, vocab)
        return ent

    out = np.zeros((len(selectors), P), bool)
    for s, sel in enumerate(selectors):
        acc = np.ones(P, bool)
        for k, v in sel.match_labels.items():
            _has, vid, vocab = cols_for(k)
            i = vocab.get(v)
            acc &= (vid == i) if i is not None else np.zeros(P, bool)
        for e in sel.match_expressions:
            has, vid, vocab = cols_for(e.key)
            if e.operator == "In":
                ids = [vocab[v] for v in e.values if v in vocab]
                acc &= np.isin(vid, ids) if ids else np.zeros(P, bool)
            elif e.operator == "NotIn":
                ids = [vocab[v] for v in e.values if v in vocab]
                if ids:
                    acc &= ~np.isin(vid, ids)
            elif e.operator == "Exists":
                acc &= has
            else:  # DoesNotExist (signature gate excludes everything else)
                acc &= ~has
        out[s] = acc
    return out


def affinity_match_matrix(selectors, peer_sigs) -> np.ndarray:
    """(S, P) bool: ``selectors[s].matches(dict(peer_sigs[p]))`` for every
    cell, computed columnar (device bit-planes when available, numpy
    columns otherwise) with the probe-verified scalar self-heal described
    above. ``peer_sigs`` are :func:`labels_signature` tuples."""
    if not selectors or not peer_sigs:
        return np.zeros((len(selectors), len(peer_sigs)), bool)
    if not affinity_columnar_enabled():
        return _affinity_scalar(selectors, peer_sigs)
    sigs = tuple(selector_signature(s) for s in selectors)
    if any(sig is None for sig in sigs):
        FILTER_FALLBACK_TOTAL.inc(reason="unsupported-operator")
        return _affinity_scalar(selectors, peer_sigs)
    t0 = time.perf_counter()
    mat: Optional[np.ndarray] = None
    try:
        from karpenter_tpu.ops import device_filter

        mat = device_filter.affinity_matrix(sigs, tuple(peer_sigs))
    except Exception:
        mat = None
    if mat is None:
        mat = _affinity_columnar(selectors, peer_sigs)
    # probe self-heal: sampled cells against the scalar oracle; one
    # divergence condemns the whole matrix (scalar wins)
    S, P = mat.shape
    rng = np.random.default_rng(S * 73856093 + P * 19349663 + 1)
    k = min(_AFFINITY_PROBE_K, S * P)
    cells = rng.choice(S * P, size=k, replace=False)
    for c in cells:
        s, p = int(c) // P, int(c) % P
        if bool(mat[s, p]) != selectors[s].matches(dict(peer_sigs[p])):
            FILTER_FALLBACK_TOTAL.inc(reason="affinity-mismatch")
            mat = _affinity_scalar(selectors, peer_sigs)
            break
    FILTER_BATCH_SECONDS.observe(time.perf_counter() - t0, stage="affinity")
    return mat


def clear_catalog_caches() -> None:
    """Tests only."""
    with _CATALOG_LOCK:
        _INDEX_CACHE.clear()
        _MASK_CACHE.clear()
        _GANG_MASK_CACHE.clear()
        _SLICE_COL_CACHE.clear()
