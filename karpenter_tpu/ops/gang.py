"""Gang co-pack window encoding: G gangs × B candidate bins as one tensor.

The batched what-if pattern (ops/whatif.py, docs/solver.md §13) applied to
provisioning-side gangs: a window holds G all-or-nothing pod groups; each
gang is one independent sub-solve — first-fit its members into a shared
pool of *prospective* nodes (bins) — and all G sub-solves fold into one
vmap'd device kernel (solver/gang.py). Where what-if's sub-solves exclude
their own bin, a gang's sub-solve has no own bin (the nodes do not exist
yet); the same masked-write reserve discipline applies, and rollback is
structural — vmap hands every gang a private copy of the pool, so an
unplaceable gang perturbs nothing.

Bins are prospective nodes. For each gang the encoder materializes enough
empty nodes of its *cheapest* feasible instance type (by catalog price) to
host the whole gang alone; the pool is shared, so a gang may also land in
the leftover space of another gang's compatible bins — the co-pack win
Tesserae measures. ``compat[g, b]`` is the gang's group-level feasibility
column (ops/feasibility.gang_feasibility_mask) indexed by bin type.

The device result is a FILTER. Every gang the device calls feasible is
re-verified member-by-member on exact host nano ints against the window's
running pool state (:func:`verify_and_commit_gang`) before any bind —
zero unverified placements by construction, exactly the what-if contract.

All integers are nano units GCD-scaled to int32 (whatif._gcd_scale_signed);
scaling divides by a common factor, so device comparisons are exact.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.api.core import Pod
from karpenter_tpu.ops.whatif import (
    MAX_WINDOW_CELLS, _gcd_scale_signed, _pow2, _reserve_vec,
)
from karpenter_tpu.solver.host_ffd import NUM_RESOURCES

Vec = Tuple[int, ...]


@dataclass
class GangBin:
    """One prospective node: an empty instance of ``type_index`` whose free
    vector is the type's allocatable after overhead + daemon reserve."""

    name: str
    type_index: int
    free: List[int]


@dataclass
class EncodedGang:
    """One gang's host-side view inside a window."""

    index: int
    key: Any                      # gang identity (namespace, name)
    pods: List[Pod]
    vecs: List[Vec]               # reserve vectors, sorted desc (cpu, mem)
    type_mask: np.ndarray         # (T,) group feasibility over instance types
    context: Any = None           # caller payload (Schedule), carried through


@dataclass
class GangEncoding:
    """Host + device tensors of one gang co-pack window."""

    gangs: List[EncodedGang]
    bins: List[GangBin]
    compat: np.ndarray            # (G, B) bool: gang may use bin
    g: int
    k: int                        # max members over gangs
    b: int
    # device side (None when the window did not encode: too big / empty)
    d_pods: Optional[np.ndarray] = None     # (GB, KB, R) int32, scaled
    d_valid: Optional[np.ndarray] = None    # (GB, KB) bool
    d_compat: Optional[np.ndarray] = None   # (GB, BB) bool
    d_free0: Optional[np.ndarray] = None    # (BB, R) int32, scaled
    scales: Optional[Tuple[int, ...]] = None
    skipped: List[Tuple[Any, str]] = field(default_factory=list)

    @property
    def device_ready(self) -> bool:
        return self.d_pods is not None

    @property
    def cells(self) -> int:
        if self.d_pods is None:
            return 0
        gb, kb, _ = self.d_pods.shape
        return gb * kb * self.d_compat.shape[1]


def _nodes_needed(vecs: Sequence[Vec], free: Sequence[int]) -> Optional[int]:
    """First-fit node count for one gang alone on unlimited empty bins with
    this free vector; None when some member overflows even an empty bin."""
    opened: List[List[int]] = []
    for vec in vecs:
        if any(vec[r] > free[r] for r in range(NUM_RESOURCES)):
            return None
        for node in opened:
            if all(node[r] >= vec[r] for r in range(NUM_RESOURCES)):
                for r in range(NUM_RESOURCES):
                    node[r] -= vec[r]
                break
        else:
            node = list(free)
            for r in range(NUM_RESOURCES):
                node[r] -= vec[r]
            opened.append(node)
    return len(opened)


def encode_gang_window(
    gangs: Sequence[Tuple[Any, Sequence[Pod], np.ndarray, Any]],
    type_frees: Sequence[Optional[Sequence[int]]],
    type_prices: Sequence[float],
    type_names: Sequence[str],
    max_cells: int = MAX_WINDOW_CELLS,
    max_bins: int = 4096,
) -> GangEncoding:
    """Encode one window.

    ``gangs``: (key, pods, type_mask, context) per gang, window priority
    order. ``type_frees[t]`` is type t's empty-node free vector (nano,
    after overhead + daemons) or None when the type cannot even boot
    (daemons overflow it). A gang with no viable type — empty mask, no
    type that fits its largest member — is recorded in ``skipped`` with a
    reason and excluded from the tensors; a partial answer beats no window.
    """
    encoded: List[EncodedGang] = []
    bins: List[GangBin] = []
    skipped: List[Tuple[Any, str]] = []
    bins_per_type: dict = {}  # type_index → bin count already materialized

    for key, pods, type_mask, context in gangs:
        # sort members desc (cpu, mem) keeping the pod association: slots[i]
        # names the bin for pods[i] all the way through bind
        pairs = sorted(((_reserve_vec(p), p) for p in pods),
                       key=lambda t: (-t[0][0], -t[0][1]))
        vecs = [v for v, _ in pairs]
        pods = [p for _, p in pairs]
        viable = [t for t in np.flatnonzero(np.asarray(type_mask))
                  if type_frees[t] is not None]
        if not viable:
            skipped.append((key, "no feasible instance type"))
            continue
        # cheapest-first: the gang's bins come from its cheapest type that
        # can host it alone; cost tiebreak by name keeps runs deterministic
        viable.sort(key=lambda t: (type_prices[t], type_names[t]))
        need, chosen = None, None
        for t in viable:
            need = _nodes_needed(vecs, type_frees[t])
            if need is not None:
                chosen = t
                break
        if chosen is None:
            skipped.append((key, "members exceed every feasible type"))
            continue
        # grow the shared pool so this gang could place alone on its chosen
        # type even after earlier gangs consumed their own replicas
        have = bins_per_type.get(chosen, 0)
        grow = need  # one gang's worth; sharing leftovers is a bonus
        for i in range(grow):
            bins.append(GangBin(
                name=f"{type_names[chosen]}~{have + i}",
                type_index=chosen,
                free=list(type_frees[chosen])))
        bins_per_type[chosen] = have + grow
        encoded.append(EncodedGang(
            index=len(encoded), key=key, pods=list(pods), vecs=vecs,
            type_mask=np.asarray(type_mask, bool), context=context))
        if len(bins) > max_bins:
            break

    g, b = len(encoded), len(bins)
    k = max((len(e.vecs) for e in encoded), default=0)
    enc = GangEncoding(gangs=encoded, bins=bins,
                       compat=np.zeros((g, b), bool), g=g, k=k, b=b,
                       skipped=skipped)
    if g == 0 or b == 0 or k == 0:
        return enc
    bin_types = np.array([bn.type_index for bn in bins], np.int64)
    for e in encoded:
        enc.compat[e.index] = e.type_mask[bin_types]

    # GCD-scale every column that meets the comparator (whatif contract)
    cols = [[bn.free[r] for bn in bins] for r in range(NUM_RESOURCES)]
    for r in range(NUM_RESOURCES):
        cols[r].extend(v[r] for e in encoded for v in e.vecs)
    scales = _gcd_scale_signed(cols)
    if scales is None:
        return enc  # values overflow int32 even scaled: host path only
    gb, kb, bb = _pow2(g), _pow2(k), _pow2(b)
    if gb * kb * bb > max_cells:
        return enc
    d_pods = np.zeros((gb, kb, NUM_RESOURCES), np.int32)
    d_valid = np.zeros((gb, kb), bool)
    d_compat = np.zeros((gb, bb), bool)
    d_free0 = np.zeros((bb, NUM_RESOURCES), np.int32)
    for bi, bn in enumerate(bins):
        for r in range(NUM_RESOURCES):
            d_free0[bi, r] = bn.free[r] // scales[r]
    for e in encoded:
        for ki, vec in enumerate(e.vecs):
            for r in range(NUM_RESOURCES):
                d_pods[e.index, ki, r] = vec[r] // scales[r]
            d_valid[e.index, ki] = True
        d_compat[e.index, :b] = enc.compat[e.index]
    enc.d_pods, enc.d_valid, enc.d_compat, enc.d_free0 = (
        d_pods, d_valid, d_compat, d_free0)
    enc.scales = scales
    return enc


def host_gang(enc: GangEncoding) -> Tuple[np.ndarray, np.ndarray]:
    """Exact host mirror of the device kernel: per gang, first-fit its
    members into a PRIVATE copy of the full pool (each gang judged
    independently, as vmap does). Returns (feasible (G,), slots (G, K))
    with -1 for unplaced/padded members. Nano ints, no scaling."""
    feasible = np.zeros(enc.g, bool)
    slots = np.full((enc.g, enc.k), -1, np.int64)
    for e in enc.gangs:
        free = [list(bn.free) for bn in enc.bins]
        ok = True
        for ki, vec in enumerate(e.vecs):
            placed = False
            for bi in range(enc.b):
                if not enc.compat[e.index, bi]:
                    continue
                if all(free[bi][r] >= vec[r] for r in range(NUM_RESOURCES)):
                    for r in range(NUM_RESOURCES):
                        free[bi][r] -= vec[r]
                    slots[e.index, ki] = bi
                    placed = True
                    break
            if not placed:
                ok = False
                break
        feasible[e.index] = ok
        if not ok:
            slots[e.index, :] = -1
    return feasible, slots


def verify_and_commit_gang(
    enc: GangEncoding,
    gang_index: int,
    free_state: List[List[int]],
) -> Optional[List[int]]:
    """Exact host re-verification of one gang against the window's RUNNING
    pool state: first-fit every member on nano ints into a trial copy;
    commit the trial (mutating ``free_state``) only when every member
    lands. Returns the member→bin assignment or None (state untouched).
    This is the only path to a gang bind — the device verdict never
    commits anything by itself."""
    e = enc.gangs[gang_index]
    trial: dict = {}  # copy-on-write: only touched bins are copied
    slots: List[int] = []
    for vec in e.vecs:
        placed = False
        for bi in range(enc.b):
            if not enc.compat[gang_index, bi]:
                continue
            free = trial.get(bi)
            if free is None:
                free = free_state[bi]
            if all(free[r] >= vec[r] for r in range(NUM_RESOURCES)):
                work = trial.get(bi)
                if work is None:
                    work = trial[bi] = list(free_state[bi])
                for r in range(NUM_RESOURCES):
                    work[r] -= vec[r]
                slots.append(bi)
                placed = True
                break
        if not placed:
            return None
    for bi, work in trial.items():
        free_state[bi] = work
    return slots
