"""Gang co-pack window encoding: G gangs × B candidate bins as one tensor.

The batched what-if pattern (ops/whatif.py, docs/solver.md §13) applied to
provisioning-side gangs: a window holds G all-or-nothing pod groups; each
gang is one independent sub-solve — first-fit its members into a shared
pool of *prospective* nodes (bins) — and all G sub-solves fold into one
vmap'd device kernel (solver/gang.py). Where what-if's sub-solves exclude
their own bin, a gang's sub-solve has no own bin (the nodes do not exist
yet); the same masked-write reserve discipline applies, and rollback is
structural — vmap hands every gang a private copy of the pool, so an
unplaceable gang perturbs nothing.

Bins are prospective nodes. For each gang the encoder materializes enough
empty nodes of its *cheapest* feasible instance type (by catalog price) to
host the whole gang alone; the pool is shared, so a gang may also land in
the leftover space of another gang's compatible bins — the co-pack win
Tesserae measures. ``compat[g, b]`` is the gang's group-level feasibility
column (ops/feasibility.gang_feasibility_mask) indexed by bin type.

The device result is a FILTER. Every gang the device calls feasible is
re-verified member-by-member on exact host nano ints against the window's
running pool state (:func:`verify_and_commit_gang`) before any bind —
zero unverified placements by construction, exactly the what-if contract.

All integers are nano units GCD-scaled to int32 (whatif._gcd_scale_signed);
scaling divides by a common factor, so device comparisons are exact.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.api.core import Pod
from karpenter_tpu.ops.whatif import (
    MAX_WINDOW_CELLS, _gcd_scale_signed, _pow2, _reserve_vec,
)
from karpenter_tpu.solver.host_ffd import NUM_RESOURCES

Vec = Tuple[int, ...]


@dataclass
class GangBin:
    """One candidate node of the window pool. Prospective bins (the
    default) are empty instances of ``type_index`` whose free vector is
    the type's allocatable after overhead + daemon reserve; SEED bins
    (``node_name`` set) are real partially-occupied nodes re-offered by
    the occupancy ledger — placing there binds to the existing node, no
    create. ``grid``/``occ`` carry the type's torus dimensions and the
    bin's occupancy bit-plane when carving is on (ops/topology.py)."""

    name: str
    type_index: int
    free: List[int]
    grid: Optional[Tuple[int, ...]] = None
    occ: Optional[np.ndarray] = None        # (cells,) bool
    node_name: Optional[str] = None         # existing node; None = fresh


@dataclass
class EncodedGang:
    """One gang's host-side view inside a window."""

    index: int
    key: Any                      # gang identity (namespace, name)
    pods: List[Pod]
    vecs: List[Vec]               # reserve vectors, sorted desc (cpu, mem)
    type_mask: np.ndarray         # (T,) group feasibility over instance types
    context: Any = None           # caller payload (Schedule), carried through
    slice_dims: Optional[Tuple[int, ...]] = None  # declared slice grid
    band: str = "default"         # pressure band (preemption ordering)
    # $/h of the fresh node(s) the cheapest feasible type would cost this
    # gang alone — the preemption pricing comparator; None = no fresh
    # capacity possible (displacement is then the only path)
    fresh_cost: Optional[float] = None


@dataclass
class GangEncoding:
    """Host + device tensors of one gang co-pack window."""

    gangs: List[EncodedGang]
    bins: List[GangBin]
    compat: np.ndarray            # (G, B) bool: gang may use bin
    g: int
    k: int                        # max members over gangs
    b: int
    # device side (None when the window did not encode: too big / empty)
    d_pods: Optional[np.ndarray] = None     # (GB, KB, R) int32, scaled
    d_valid: Optional[np.ndarray] = None    # (GB, KB) bool
    d_compat: Optional[np.ndarray] = None   # (GB, BB) bool
    d_free0: Optional[np.ndarray] = None    # (BB, R) int32, scaled
    scales: Optional[Tuple[int, ...]] = None
    skipped: List[Tuple[Any, str]] = field(default_factory=list)
    # carve tensors when any gang declares a slice (ops/topology.py);
    # None = carve-neutral window, bit-for-bit the shape-only behavior
    carve: Optional[Any] = None

    @property
    def device_ready(self) -> bool:
        return self.d_pods is not None

    @property
    def cells(self) -> int:
        if self.d_pods is None:
            return 0
        gb, kb, _ = self.d_pods.shape
        return gb * kb * self.d_compat.shape[1]


def _nodes_needed(vecs: Sequence[Vec], free: Sequence[int]) -> Optional[int]:
    """First-fit node count for one gang alone on unlimited empty bins with
    this free vector; None when some member overflows even an empty bin."""
    opened: List[List[int]] = []
    for vec in vecs:
        if any(vec[r] > free[r] for r in range(NUM_RESOURCES)):
            return None
        for node in opened:
            if all(node[r] >= vec[r] for r in range(NUM_RESOURCES)):
                for r in range(NUM_RESOURCES):
                    node[r] -= vec[r]
                break
        else:
            node = list(free)
            for r in range(NUM_RESOURCES):
                node[r] -= vec[r]
            opened.append(node)
    return len(opened)


def encode_gang_window(
    gangs: Sequence[Tuple[Any, Sequence[Pod], np.ndarray, Any]],
    type_frees: Sequence[Optional[Sequence[int]]],
    type_prices: Sequence[float],
    type_names: Sequence[str],
    max_cells: int = MAX_WINDOW_CELLS,
    max_bins: int = 4096,
    slices: Optional[Sequence[Optional[Tuple[int, ...]]]] = None,
    bands: Optional[Sequence[str]] = None,
    type_grids: Optional[Sequence[Optional[Tuple[int, ...]]]] = None,
    seed_bins: Optional[Sequence[GangBin]] = None,
    grow: bool = True,
) -> GangEncoding:
    """Encode one window.

    ``gangs``: (key, pods, type_mask, context) per gang, window priority
    order. ``type_frees[t]`` is type t's empty-node free vector (nano,
    after overhead + daemons) or None when the type cannot even boot
    (daemons overflow it). A gang with no viable type — empty mask, no
    type that fits its largest member — is recorded in ``skipped`` with a
    reason and excluded from the tensors; a partial answer beats no window.

    Carving (all optional — omitted, the window is bit-for-bit the
    shape-only encoding): ``slices[i]``/``bands[i]`` annotate gang i with
    its declared slice grid and pressure band; ``type_grids[t]`` is type
    t's torus dimensions; ``seed_bins`` are real partially-occupied nodes
    from the occupancy ledger, entering the pool FIRST so first-fit reuses
    live fragmented capacity before opening fresh nodes. ``grow=False``
    suppresses fresh-bin growth entirely (saturated-pool benches)."""
    encoded: List[EncodedGang] = []
    bins: List[GangBin] = list(seed_bins or [])
    skipped: List[Tuple[Any, str]] = []
    bins_per_type: dict = {}  # type_index → bin count already materialized

    for gi, (key, pods, type_mask, context) in enumerate(gangs):
        # sort members desc (cpu, mem) keeping the pod association: slots[i]
        # names the bin for pods[i] all the way through bind
        pairs = sorted(((_reserve_vec(p), p) for p in pods),
                       key=lambda t: (-t[0][0], -t[0][1]))
        vecs = [v for v, _ in pairs]
        pods = [p for _, p in pairs]
        viable = [t for t in np.flatnonzero(np.asarray(type_mask))
                  if type_frees[t] is not None]
        if not viable:
            skipped.append((key, "no feasible instance type"))
            continue
        # cheapest-first: the gang's bins come from its cheapest type that
        # can host it alone; cost tiebreak by name keeps runs deterministic
        viable.sort(key=lambda t: (type_prices[t], type_names[t]))
        need, chosen = None, None
        for t in viable:
            need = _nodes_needed(vecs, type_frees[t])
            if need is not None:
                chosen = t
                break
        if chosen is None and grow:
            skipped.append((key, "members exceed every feasible type"))
            continue
        if chosen is not None and grow:
            # grow the shared pool so this gang could place alone on its
            # chosen type even after earlier gangs consumed their replicas
            have = bins_per_type.get(chosen, 0)
            for i in range(need):
                bins.append(GangBin(
                    name=f"{type_names[chosen]}~{have + i}",
                    type_index=chosen,
                    free=list(type_frees[chosen]),
                    grid=(type_grids[chosen] if type_grids is not None
                          else None)))
            bins_per_type[chosen] = have + need
        encoded.append(EncodedGang(
            index=len(encoded), key=key, pods=list(pods), vecs=vecs,
            type_mask=np.asarray(type_mask, bool), context=context,
            slice_dims=(tuple(slices[gi]) if slices is not None
                        and slices[gi] is not None else None),
            band=(bands[gi] if bands is not None else "default"),
            fresh_cost=(type_prices[chosen] * need
                        if chosen is not None else None)))
        if len(bins) > max_bins:
            break

    g, b = len(encoded), len(bins)
    k = max((len(e.vecs) for e in encoded), default=0)
    enc = GangEncoding(gangs=encoded, bins=bins,
                       compat=np.zeros((g, b), bool), g=g, k=k, b=b,
                       skipped=skipped)
    if g == 0 or b == 0 or k == 0:
        return enc
    bin_types = np.array([bn.type_index for bn in bins], np.int64)
    for e in encoded:
        enc.compat[e.index] = e.type_mask[bin_types]

    # GCD-scale every column that meets the comparator (whatif contract)
    cols = [[bn.free[r] for bn in bins] for r in range(NUM_RESOURCES)]
    for r in range(NUM_RESOURCES):
        cols[r].extend(v[r] for e in encoded for v in e.vecs)
    scales = _gcd_scale_signed(cols)
    if scales is None:
        return _attach_carve(enc)  # int32 overflow: host path only
    gb, kb, bb = _pow2(g), _pow2(k), _pow2(b)
    if gb * kb * bb > max_cells:
        return _attach_carve(enc)
    d_pods = np.zeros((gb, kb, NUM_RESOURCES), np.int32)
    d_valid = np.zeros((gb, kb), bool)
    d_compat = np.zeros((gb, bb), bool)
    d_free0 = np.zeros((bb, NUM_RESOURCES), np.int32)
    for bi, bn in enumerate(bins):
        for r in range(NUM_RESOURCES):
            d_free0[bi, r] = bn.free[r] // scales[r]
    for e in encoded:
        for ki, vec in enumerate(e.vecs):
            for r in range(NUM_RESOURCES):
                d_pods[e.index, ki, r] = vec[r] // scales[r]
            d_valid[e.index, ki] = True
        d_compat[e.index, :b] = enc.compat[e.index]
    enc.d_pods, enc.d_valid, enc.d_compat, enc.d_free0 = (
        d_pods, d_valid, d_compat, d_free0)
    enc.scales = scales
    return _attach_carve(enc)


def _attach_carve(enc: GangEncoding) -> GangEncoding:
    """Build the carve tensors when any gang declares a slice; padded to
    the gang window's own device axes so the (G, B) carve verdict ANDs
    straight into ``d_compat`` on device."""
    from karpenter_tpu.ops.topology import encode_carve

    gb = enc.d_compat.shape[0] if enc.d_compat is not None else None
    bb = enc.d_compat.shape[1] if enc.d_compat is not None else None
    enc.carve = encode_carve(enc, gb=gb, bb=bb)
    return enc


def host_gang(enc: GangEncoding,
              carve_ok: Optional[np.ndarray] = None
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Exact host mirror of the device kernel: per gang, first-fit its
    members into a PRIVATE copy of the full pool (each gang judged
    independently, as vmap does). Returns (feasible (G,), slots (G, K))
    with -1 for unplaced/padded members. Nano ints, no scaling.
    ``carve_ok`` ((G, B) bool) mirrors the device composition: the carve
    verdict ANDs into compat before the first-fit scan."""
    feasible = np.zeros(enc.g, bool)
    slots = np.full((enc.g, enc.k), -1, np.int64)
    compat = enc.compat if carve_ok is None else (enc.compat & carve_ok)
    for e in enc.gangs:
        free = [list(bn.free) for bn in enc.bins]
        ok = True
        for ki, vec in enumerate(e.vecs):
            placed = False
            for bi in range(enc.b):
                if not compat[e.index, bi]:
                    continue
                if all(free[bi][r] >= vec[r] for r in range(NUM_RESOURCES)):
                    for r in range(NUM_RESOURCES):
                        free[bi][r] -= vec[r]
                    slots[e.index, ki] = bi
                    placed = True
                    break
            if not placed:
                ok = False
                break
        feasible[e.index] = ok
        if not ok:
            slots[e.index, :] = -1
    return feasible, slots


def verify_and_commit_gang(
    enc: GangEncoding,
    gang_index: int,
    free_state: List[List[int]],
    occ_state: Optional[List[Optional[np.ndarray]]] = None,
    carves_out: Optional[dict] = None,
    bin_limit: Optional[int] = None,
) -> Optional[List[int]]:
    """Exact host re-verification of one gang against the window's RUNNING
    pool state: first-fit every member on nano ints into a trial copy;
    commit the trial (mutating ``free_state``) only when every member
    lands. Returns the member→bin assignment or None (state untouched).
    This is the only path to a gang bind — the device verdict never
    commits anything by itself.

    Carving (``occ_state`` set, per-bin running occupancy planes or None
    for gridless bins): a slice-shaped gang must additionally carve ONE
    contiguous torus sub-grid of its declared shape on every bin it
    touches, verified CELL BY CELL by the scalar oracle
    (ops/topology.first_carve) against the running plane. A bin whose
    resources fit but whose free chips form no contiguous sub-grid is
    REJECTED — that is the phantom capacity the shape-only gate admitted.
    Committed carve cells land in ``carves_out[bin] = cells`` and the
    occupancy planes advance with the pool state.

    ``bin_limit`` restricts the walk to ``bins[:bin_limit]`` — the seed
    (real node) prefix — so the planner can price live-capacity placement
    and preemption against opening fresh nodes."""
    from karpenter_tpu.ops.topology import first_carve, grid_cells

    e = enc.gangs[gang_index]
    carve_mode = occ_state is not None and e.slice_dims is not None
    trial: dict = {}  # copy-on-write: only touched bins are copied
    trial_occ: dict = {}
    trial_carve: dict = {}
    # a bin's occupancy only changes within this walk via the gang's own
    # carve, so a failed first_carve stays failed: memoize the reject so
    # later members skip the scan and the counter counts bins, not
    # (members x bins)
    carve_rejected: set = set()
    slots: List[int] = []
    b_max = enc.b if bin_limit is None else min(bin_limit, enc.b)
    for vec in e.vecs:
        placed = False
        for bi in range(b_max):
            if not enc.compat[gang_index, bi]:
                continue
            free = trial.get(bi)
            if free is None:
                free = free_state[bi]
            if not all(free[r] >= vec[r] for r in range(NUM_RESOURCES)):
                continue
            if carve_mode and bi not in trial_carve:
                if bi in carve_rejected:
                    continue
                # first member landing on this bin: the whole gang shares
                # one carve of the declared shape here
                grid = enc.bins[bi].grid
                if grid is None:
                    continue  # cannot model contiguity: unsafe for slices
                occ = trial_occ.get(bi)
                if occ is None:
                    occ = occ_state[bi]
                    if occ is None:
                        occ = np.zeros(grid_cells(grid), bool)
                cells = first_carve(occ, grid, e.slice_dims)
                if cells is None:
                    carve_rejected.add(bi)
                    from karpenter_tpu.metrics.topology import (
                        TOPOLOGY_CARVE_REJECTS_TOTAL)
                    TOPOLOGY_CARVE_REJECTS_TOTAL.inc()
                    continue  # resources fit, chips do not: phantom
                work_occ = trial_occ.get(bi)
                if work_occ is None:
                    base = occ_state[bi]
                    work_occ = trial_occ[bi] = (
                        base.copy() if base is not None
                        else np.zeros(grid_cells(grid), bool))
                work_occ[list(cells)] = True
                trial_carve[bi] = cells
            work = trial.get(bi)
            if work is None:
                work = trial[bi] = list(free_state[bi])
            for r in range(NUM_RESOURCES):
                work[r] -= vec[r]
            slots.append(bi)
            placed = True
            break
        if not placed:
            return None
    for bi, work in trial.items():
        free_state[bi] = work
    if carve_mode:
        for bi, occ in trial_occ.items():
            occ_state[bi] = occ
        if carves_out is not None:
            carves_out.update(trial_carve)
    return slots
