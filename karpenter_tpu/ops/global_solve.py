"""Whole-window global-solve encoding: one batched relaxation program.

The provisioning hot loop packs each schedule greedily (FFD per schedule,
batched on device); with a priced heterogeneous catalog the cheapest fleet
is provably not the per-schedule-greedy one. This module encodes ALL
schedules of a provisioning window — per-schedule pod-shape segments ×
priced instance-type columns — into ONE batched tensor program for the
proximal/ADMM kernel in solver/global_solve.py, and supplies the exact
integer arithmetic that decides what leaves the solve:

- ``price_micro`` is EXACTLY models/ffd.encode_prices' per-entry
  truncation (``min(int(p * 1e6), INT32_MAX)``, saturating; the same seam
  ops/policy._encode_micro rides), so "strictly cheaper" is decided in
  exact nano-int micro-$ arithmetic, never float.
- ``plan_cost_micro`` charges a host plan its cheapest viable option per
  node in python ints — overflow-free, bit-stable across platforms.
- ``verify_plan`` independently replays every node of a candidate plan
  through fresh host Packable reservations (exact nano ints) and checks
  pod conservation — the verdict-is-a-filter half of the contract: no
  placement reaches a bind without passing it.

The per-schedule type columns already ride the feasibility engine:
``build_packables_cached`` only yields types the §16 bit-plane /columnar
filter admits for the schedule, so the relaxation never sees an
infeasible (schedule × type) cell. Shapes/capacities are float32-
normalized per schedule (the relax.py discipline) purely for the
gradient kernel; nothing float ever decides acceptance.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from karpenter_tpu.solver.host_ffd import (
    NUM_RESOURCES, HostSolveResult, Packable)

log = logging.getLogger("karpenter.ops.global_solve")

# int32 saturation ceiling of the micro-$ price domain (models/ffd.py)
SAT_MICRO = 2 ** 31 - 1


def price_micro(p: float) -> int:
    """models/ffd.encode_prices' exact per-entry truncation as a scalar:
    finite prices truncate to int micro-$ saturating at INT32_MAX; inf
    (no viable offering) saturates outright."""
    if p != float("inf"):
        return min(int(p * 1e6), SAT_MICRO)
    return SAT_MICRO


def plan_cost_micro(result: HostSolveResult,
                    prices_micro: Sequence[int]) -> int:
    """Exact integer cost of a host plan in micro-$/h, charging each node
    its cheapest viable option — the int twin of models/cost.plan_cost's
    convention. Python ints: no overflow, no rounding."""
    total = 0
    for p in result.packings:
        total += min(prices_micro[j] for j in p.instance_type_indices) \
            * p.node_quantity
    return total


def verify_plan(pod_vecs: Dict[int, Sequence[int]],
                packables_by_index: Dict[int, Packable],
                result: HostSolveResult) -> bool:
    """Independent host re-verification of a candidate plan on exact nano
    ints: every node's pods must reserve onto a FRESH copy of the node's
    chosen type (first option — the type the rounding actually packed),
    and every input pod must appear exactly once across packings and
    unschedulable. Any failure rejects the whole plan."""
    seen: set = set()
    for packing in result.packings:
        if not packing.instance_type_indices:
            return False
        if len(packing.pod_ids) != packing.node_quantity:
            return False
        chosen = packables_by_index.get(packing.instance_type_indices[0])
        if chosen is None:
            return False
        for node in packing.pod_ids:
            fresh = chosen.copy()
            for pid in node:
                if pid in seen:
                    return False
                seen.add(pid)
                vec = pod_vecs.get(pid)
                if vec is None or not fresh.reserve_pod(vec):
                    return False
    for pid in result.unschedulable:
        if pid in seen:
            return False
        seen.add(pid)
    return seen == set(pod_vecs)


@dataclass
class GlobalScheduleEnc:
    """One schedule's slice of the window: the exact host-side problem
    (pods ordered descending, viable packables, int micro-$ prices) plus —
    when encodable — its row in the batched kernel tensors."""

    pos: int                       # position in the window's problem list
    reason: Optional[str] = None   # early decline (empty|unpriced|unencodable)
    constraints: Optional[object] = None   # the problem's Constraints
    pod_vecs: list = field(default_factory=list)   # descending pack order
    pod_ids: list = field(default_factory=list)    # original pod positions
    pods: list = field(default_factory=list)       # Pod objects, input order
    packables: list = field(default_factory=list)
    sorted_types: list = field(default_factory=list)
    prices: list = field(default_factory=list)        # $/h per sorted type
    prices_micro: list = field(default_factory=list)  # int µ$ per sorted type
    num_shapes: int = 0
    num_types: int = 0
    row: int = -1                  # row in the batched tensors (-1 = none)


@dataclass
class GlobalWindowEncoding:
    """The window: per-schedule host problems + the batched padded float32
    tensors the kernel consumes. ``b/sb/tb`` are the padded bucket dims."""

    scheds: List[GlobalScheduleEnc]
    b: int = 0
    sb: int = 0
    tb: int = 0
    d_shapes: Optional[np.ndarray] = None   # (B, SB, R) f32 normalized
    d_counts: Optional[np.ndarray] = None   # (B, SB)    f32
    d_caps: Optional[np.ndarray] = None     # (B, TB, R) f32 normalized
    d_prices: Optional[np.ndarray] = None   # (B, TB)    f32 in [0, 1]
    d_tmask: Optional[np.ndarray] = None    # (B, TB)    f32 validity
    d_x0: Optional[np.ndarray] = None       # (B, SB, TB) f32 warm start
    d_n0: Optional[np.ndarray] = None       # (B, TB)    f32 warm start

    @property
    def live(self) -> List[GlobalScheduleEnc]:
        return [s for s in self.scheds if s.row >= 0]

    @property
    def cells(self) -> int:
        return self.b * self.sb * self.tb

    @property
    def device_ready(self) -> bool:
        return self.d_shapes is not None and self.b > 0


def _pow2(n: int, lo: int = 4) -> int:
    p = lo
    while p < n:
        p <<= 1
    return p


def _schedule_tensors(enc_problem, obj_prices: Sequence[float]):
    """relax.py's per-schedule float32 normalization: shapes/caps divided
    per-resource, prices scaled into [0, 1], plus the even-spread warm
    start. Returns (shapes, counts, caps, prices, x0, n0)."""
    S, T = enc_problem.num_shapes, enc_problem.num_types
    shapes = np.asarray(enc_problem.shapes[:S], dtype=np.float32)
    caps = np.asarray(enc_problem.totals[:T], dtype=np.float32)
    counts = np.asarray(enc_problem.counts[:S], dtype=np.float32)
    norm = np.maximum(np.maximum(shapes.max(axis=0, initial=1.0),
                                 caps.max(axis=0, initial=1.0)), 1.0)
    shapes, caps = shapes / norm, caps / norm
    prices = np.asarray(obj_prices, dtype=np.float32)
    pmax = float(prices.max()) or 1.0
    prices = prices / pmax
    x0 = np.tile((counts / max(T, 1))[:, None], (1, T)).astype(np.float32)
    need = np.einsum("s,sr->r", counts, shapes)
    denom = np.maximum(caps, 1e-6)
    n0 = (np.max(need[None, :] / denom, axis=1) / max(T, 1)).astype(np.float32)
    return shapes, counts, caps, prices, x0, n0


def encode_window(problems: Sequence, cost_config,
                  max_schedules: int = 256) -> GlobalWindowEncoding:
    """Marshal a provisioning window's Problem list into the batched
    relaxation program. Per schedule: viable packables + sorted catalog
    (feasibility-filtered, cached), descending pod order, exact int
    micro-$ prices; schedules that cannot join the relaxation (no pods,
    no priced type, unencodable ints) carry an early-decline reason and
    no tensor row — the caller's FFD result stands for them untouched."""
    from karpenter_tpu.models.cost import effective_price
    from karpenter_tpu.ops.encode import encode
    from karpenter_tpu.solver.adapter import (
        build_packables_cached, marshal_pods_interned)

    scheds: List[GlobalScheduleEnc] = []
    rows: List[tuple] = []
    for pos, problem in enumerate(problems):
        s = GlobalScheduleEnc(pos=pos, pods=list(problem.pods),
                              constraints=problem.constraints)
        scheds.append(s)
        if not problem.pods or pos >= max_schedules:
            s.reason = "empty" if not problem.pods else "window-cap"
            continue
        pod_vecs, required, _ = marshal_pods_interned(problem.pods)
        packables, sorted_types = build_packables_cached(
            problem.instance_types, problem.constraints, problem.pods,
            problem.daemons, required=required)
        if not packables:
            s.reason = "empty"
            continue
        order = sorted(range(len(problem.pods)),
                       key=lambda i: (-pod_vecs[i][0], -pod_vecs[i][1]))
        prices = [effective_price(it, problem.constraints.requirements,
                                  cost_config)[0] for it in sorted_types]
        prices = [0.0 if p == float("inf") else p for p in prices]
        s.pod_vecs = [pod_vecs[i] for i in order]
        s.pod_ids = order
        s.packables = packables
        s.sorted_types = sorted_types
        s.prices = prices
        s.prices_micro = [price_micro(p) for p in prices]
        by_pos = [s.prices_micro[p.index] for p in packables]
        if not any(0 < m < SAT_MICRO for m in by_pos):
            s.reason = "unpriced"
            continue
        enc = encode(s.pod_vecs, s.pod_ids, packables, pad=False)
        if enc is None:
            s.reason = "unencodable"
            continue
        # unpriced/saturated types keep the saturated stand-in so the
        # objective pushes their node count to zero, exactly like the
        # repack relaxation's discipline
        obj = [float(m) if 0 < m < SAT_MICRO else float(SAT_MICRO)
               for m in by_pos]
        s.num_shapes, s.num_types = enc.num_shapes, enc.num_types
        s.row = len(rows)
        rows.append(_schedule_tensors(enc, obj))

    win = GlobalWindowEncoding(scheds=scheds)
    if not rows:
        return win
    R = NUM_RESOURCES
    win.b = _pow2(len(rows), lo=1)
    win.sb = _pow2(max(sh.shape[0] for sh, *_ in rows))
    win.tb = _pow2(max(cp.shape[0] for _, _, cp, *_ in rows))
    B, SB, TB = win.b, win.sb, win.tb
    win.d_shapes = np.zeros((B, SB, R), np.float32)
    win.d_counts = np.zeros((B, SB), np.float32)
    win.d_caps = np.zeros((B, TB, R), np.float32)
    win.d_prices = np.ones((B, TB), np.float32)
    win.d_tmask = np.zeros((B, TB), np.float32)
    win.d_x0 = np.zeros((B, SB, TB), np.float32)
    win.d_n0 = np.zeros((B, TB), np.float32)
    for i, (shapes, counts, caps, prices, x0, n0) in enumerate(rows):
        S, T = shapes.shape[0], caps.shape[0]
        win.d_shapes[i, :S] = shapes
        win.d_counts[i, :S] = counts
        win.d_caps[i, :T] = caps
        win.d_prices[i, :T] = prices
        win.d_tmask[i, :T] = 1.0
        win.d_x0[i, :S, :T] = x0
        win.d_n0[i, :T] = n0
    return win


_RHO, _MU, _LR = 8.0, 8.0, 0.05


def host_global_support(win: GlobalWindowEncoding,
                        iters: int) -> np.ndarray:
    """Numpy mirror of the device kernel: the SAME projected-gradient
    recurrence (manual gradients of the penalty objective), batched over
    the window rows. The device answer is only a filter, so the mirror
    needs mathematical — not bit — equivalence."""
    B, SB, TB = win.b, win.sb, win.tb
    out = np.zeros((B, TB), np.float32)
    for i in range(B):
        shapes = win.d_shapes[i]          # (SB, R)
        counts = win.d_counts[i]          # (SB,)
        caps = win.d_caps[i]              # (TB, R)
        pr = win.d_prices[i]              # (TB,)
        tmask = win.d_tmask[i]            # (TB,)
        x = win.d_x0[i].copy()            # (SB, TB)
        n = win.d_n0[i].copy()            # (TB,)
        for _ in range(iters):
            load = np.einsum("st,sr->tr", x, shapes)
            over = np.maximum(load - n[:, None] * caps, 0.0)
            short = x.sum(axis=1) - counts
            gx = _RHO * np.einsum("tr,sr->st", over, shapes) \
                + _MU * short[:, None]
            gn = pr - _RHO * (over * caps).sum(axis=1)
            x = np.maximum(x - _LR * gx, 0.0) * tmask[None, :]
            n = np.maximum(n - _LR * gn, 0.0) * tmask
        out[i] = n
    return out


#: the hand-tuned strict/widened keep-rule corners the adaptive
#: controller interpolates between (absolute floor, fraction of max)
STRICT_SUPPORT = (0.4, 0.02)
WIDE_SUPPORT = (0.05, 0.005)


def support_positions(n_row: np.ndarray, num_types: int,
                      abs_thr: float = STRICT_SUPPORT[0],
                      frac_thr: float = STRICT_SUPPORT[1]) -> List[int]:
    """relax.py's keep rule over one fetched node-count row: a type
    carries the support when the optimum provisions a meaningful fraction
    of a node there (the absolute floor absorbs rounding noise; n is in
    nodes). Defaults are the hand-tuned strict corner; the adaptive
    :class:`SupportController` feeds EWMA-interpolated thresholds."""
    n = np.asarray(n_row[:num_types], dtype=np.float64)
    if n.size == 0 or not np.all(np.isfinite(n)):
        return []
    return [t for t in range(num_types)
            if n[t] >= max(abs_thr, frac_thr * float(n.max()))]


class SupportController:
    """Acceptance-rate-driven support threshold, replacing the fixed
    ``max(0.4, 0.02 x max n)`` keep rule with an EWMA interpolation
    between the strict and widened corners.

    The strict rule is right when the relaxation's optima are crisp (most
    attempts round to an accepted plan) and too aggressive for fleets of
    small schedules whose node counts all optimize fractional — there it
    declines with no-support, pays the widened retry every window, and
    the hand-tuned corner never learns. The controller tracks the
    STRICT-pass acceptance rate as an EWMA (seeded at 1.0 — trust the
    tuned rule until evidence): as acceptance falls, thresholds slide
    toward the widened corner, so the first rounding attempt starts
    where the retry would have ended up; as acceptance recovers, they
    tighten back. The widened retry itself stays untouched BELOW the
    adaptive pass as the unconditional floor, so the accept set is never
    smaller than the two-pass scheme's — every accept still clears the
    exact infeasible/costlier/unverified gates, which is what makes a
    widened accept as sound as a strict one.
    """

    def __init__(self, alpha: float = 0.2) -> None:
        self.alpha = float(alpha)
        self.rate = 1.0

    def thresholds(self) -> tuple:
        """(abs, frac) in force: linear in the EWMA acceptance rate —
        rate 1.0 is the strict corner, rate 0.0 the widened one."""
        f = 1.0 - min(max(self.rate, 0.0), 1.0)
        a = STRICT_SUPPORT[0] + f * (WIDE_SUPPORT[0] - STRICT_SUPPORT[0])
        r = STRICT_SUPPORT[1] + f * (WIDE_SUPPORT[1] - STRICT_SUPPORT[1])
        return a, r

    def note(self, accepted: bool) -> None:
        self.rate += self.alpha * ((1.0 if accepted else 0.0) - self.rate)

    def reset(self) -> None:
        self.rate = 1.0


#: process-wide controller (same lifetime as the solve caches); the
#: gauge karpenter_global_support_threshold mirrors thresholds()[0]
SUPPORT = SupportController()


def widened_support_positions(n_row: np.ndarray,
                              num_types: int) -> List[int]:
    """The no-support retry's relaxed keep rule: small schedules often
    optimize to fractional node counts everywhere (every n_t < 0.4), so
    the strict rule returns empty and the window declines. Widening keeps
    any type with a non-trivial share of the mass — the exact rounding,
    strictly-cheaper and re-verify gates downstream still hold, so a
    widened accept is as sound as a strict one; it is merely attempted
    second."""
    n = np.asarray(n_row[:num_types], dtype=np.float64)
    if n.size == 0 or not np.all(np.isfinite(n)) or float(n.max()) <= 0.0:
        return []
    return [t for t in range(num_types)
            if n[t] >= max(0.05, 0.005 * float(n.max()))]
