"""The TPU bin-packing kernel: FFD with exact Go-packer parity.

Replaces the reference's sequential hot loop (packer.go:114-141 +
packable.go:111-130, O(pods × types × resources) on one CPU core) with an
XLA program whose sequential axis is *distinct packing decisions*, not pods:

- inner ``lax.scan`` over unique pod shapes (S ≈ dozens), each step a
  vectorized fit over ALL instance types at once (T×R int32 math on the VPU);
- outer ``lax.scan`` over node-packing iterations, with an exact
  *fast-forward*: while every consumed shape's remaining count stays
  strictly above its maxfit bound, the whole round provably repeats, so q
  identical nodes are committed in one step (the device analog of the
  reference's dedupe-by-hash NodeQuantity++, packer.go:130-139). The
  validity condition is derived in docs/solver.md.

Semantics preserved per quirk list in solver/host_ffd.py; differential tests
in tests/test_pack_parity.py enforce exact node-count equality.

All tensors are int32 (TPU-native); encode.py guarantees exactness.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from karpenter_tpu.solver.host_ffd import R_PODS

INT32_MAX = jnp.iinfo(jnp.int32).max


def compute_maxfit(shapes, totals, reserved0, valid):
    """Per-shape upper bound on any valid type's capacity fit from the
    initial reservation — the fast-forward validity bound (docs/solver.md).
    Shared by the XLA scan and the pallas wrapper (the pallas kernel takes
    it as an INPUT: computing it in-kernel was an O(R·S²) masked-reduction
    loop, the dominant fixed cost at the 8192-shape bucket). Computed with
    an unrolled loop over R so peak memory is (S, T), never (S, T, R) —
    at the 8192-shape bucket the dense intermediate would be ~270 MB."""
    S = shapes.shape[0]
    T = totals.shape[0]
    avail0 = totals - reserved0  # (T, R)
    kfit0 = jnp.full((S, T), INT32_MAX, jnp.int32)
    for r in range(shapes.shape[1]):
        col = shapes[:, r][:, None]  # (S, 1)
        kr_r = jnp.where(col > 0, avail0[None, :, r] // jnp.maximum(col, 1),
                         INT32_MAX)
        kfit0 = jnp.minimum(kfit0, kr_r)
    return jnp.max(jnp.where(valid[None, :], kfit0, -1), axis=1)  # (S,)


@functools.partial(jax.jit, static_argnames=("num_iters", "cost_tiebreak"))
def pack_chunk(
    shapes: jax.Array,     # (S, R) int32, descending, reserve semantics
    counts: jax.Array,     # (S,) int32 remaining pods per shape
    dropped: jax.Array,    # (S,) int32 accumulated unschedulable pods
    totals: jax.Array,     # (T, R) int32
    reserved0: jax.Array,  # (T, R) int32 overhead+daemons reservation
    valid: jax.Array,      # (T,) bool
    last_valid: jax.Array,  # () int32 index of largest viable type
    pods_unit: jax.Array,  # () int32 one pod in device units
    num_iters: int,
    prices: jax.Array = None,      # (T,) int32 effective micro-$/h, optional
    cost_tiebreak: bool = False,
    maxfit: jax.Array = None,      # (S,) int32, optional precomputed bound
):
    """Run up to ``num_iters`` node-packing iterations; host loops chunks
    until ``done``. Returns (counts, dropped, done, chosen[L], qty[L],
    packed[L,S]).

    ``cost_tiebreak``: when several types achieve max-pods, pick the one
    with the lowest effective price (capacity order breaks price ties)
    instead of Go's smallest-capacity-first. Parity mode (default) ignores
    ``prices`` entirely — Go semantics bit-for-bit.

    ``maxfit``: the fast-forward bound depends only on (shapes, totals,
    reserved0, valid), all chunk-invariant, so callers that loop chunks
    (models/ffd.solve_ffd_device) compute it once per solve and pass it in;
    when omitted it is computed here, once per chunk."""
    S, R = shapes.shape
    T = totals.shape[0]
    pods_one = jnp.zeros((R,), jnp.int32).at[R_PODS].set(pods_unit)

    # Upper bound on any type's capacity fit per shape, from the initial
    # reservation (reserved only grows during a node pack). Fast-forward
    # validity needs counts to stay STRICTLY above this on every repeated
    # round — see the derivation in docs/solver.md.
    if maxfit is None:
        maxfit = compute_maxfit(shapes, totals, reserved0, valid)  # (S,)

    # Block-tile the sequential shape axis: B shape steps unrolled per
    # block. Semantics are identical (the shapes are still consumed
    # strictly in order); the tiling only amortizes per-step loop
    # overhead, which dominates at the large shape buckets. Every
    # SHAPE_BUCKET is a multiple of 8.
    BLK = 8 if S % 8 == 0 else 1

    def node_iter(carry, _):
        counts, dropped, done = carry
        has = counts > 0
        largest_idx = jnp.argmax(has)                       # first shape remaining
        smallest_idx = S - 1 - jnp.argmax(has[::-1])        # last shape remaining
        # fits() uses raw requests (no implicit pods:1) — packable.go:118,146
        smallest_fits = jnp.maximum(shapes[smallest_idx] - pods_one, 0)

        def one_shape(c2, shape, count):
            reserved, stopped, npacked = c2
            active = (count > 0) & (~stopped)
            avail = totals - reserved  # (T, R)
            kr = jnp.where(shape[None, :] > 0,
                           avail // jnp.maximum(shape[None, :], 1), INT32_MAX)
            kfit = jnp.min(kr, axis=1)                      # (T,)
            k = jnp.where(active, jnp.clip(kfit, 0, count), 0)
            failure = active & (k < count)
            reserved = reserved + k[:, None] * shape[None, :]
            # early-exit: smallest remaining pod reaches/exceeds a nonzero total
            full = jnp.any((totals > 0) &
                           (reserved + smallest_fits[None, :] >= totals), axis=1)
            npacked = npacked + k
            stopped = stopped | (failure & (full | (npacked == 0)))
            return (reserved, stopped, npacked), k

        # Two-level early-terminating walk over shape blocks. A dense scan
        # over all S/BLK blocks pays the full shape axis on every node
        # iteration, but at high cardinality almost all of it is provable
        # no-ops: a count == 0 shape leaves one_shape's carry untouched
        # (active=False → k=0), and once every type is stopped, so does
        # every later shape. So the while_loop (a) starts at the block
        # holding the largest remaining shape, (b) exits after the block
        # holding the smallest remaining shape, and (c) exits as soon as
        # ``stopped`` is all-true across types. k rows for skipped blocks
        # stay 0, exactly what one_shape would have returned — the record
        # stream is bit-for-bit identical to the dense scan's.
        first_b = largest_idx // BLK
        last_b = smallest_idx // BLK

        def block_cond(state):
            b, _, stopped, _, _ = state
            return (b <= last_b) & ~jnp.all(stopped)

        def block_body(state):
            b, reserved, stopped, npacked, k_all = state
            base = b * BLK
            blk_shapes = jax.lax.dynamic_slice(shapes, (base, 0), (BLK, R))
            blk_counts = jax.lax.dynamic_slice(counts, (base,), (BLK,))
            c2 = (reserved, stopped, npacked)
            ks = []
            for j in range(BLK):  # unrolled: one fused kernel per block
                c2, k = one_shape(c2, blk_shapes[j], blk_counts[j])
                ks.append(k)
            k_all = jax.lax.dynamic_update_slice(k_all, jnp.stack(ks),
                                                 (base, 0))
            reserved, stopped, npacked = c2
            return (b + 1, reserved, stopped, npacked, k_all)

        # inits derive from inputs so varying-axis types line up under
        # shard_map; folding ``done`` into the stopped init makes node
        # iterations after chunk completion cost O(T), not O(S·T)
        init = (first_b, reserved0, ~valid | done,
                jnp.zeros_like(totals[:, 0]), jnp.zeros((S, T), jnp.int32))
        _, _, _, npacked, k_all = jax.lax.while_loop(
            block_cond, block_body, init)
        # k_all: (S, T) pods of each shape packed per candidate type

        max_pods = npacked[last_valid]
        tie = valid & (npacked == max_pods)
        if cost_tiebreak and prices is not None:
            # cheapest type among the max-pods ties; capacity order (first
            # index) breaks price ties — beyond-reference capability, the
            # device analog of models/cost.order_options_by_price
            best_price = jnp.min(jnp.where(tie, prices, INT32_MAX))
            chosen = jnp.argmax(tie & (prices == best_price))
        else:
            chosen = jnp.argmax(tie)                         # first (smallest) type
        packedv = k_all[:, chosen]                           # (S,)
        nothing = max_pods == 0

        # Exact fast-forward: q identical nodes in one iteration. Validity
        # (proof in docs/solver.md): a round repeats identically iff every
        # shape it consumes stays STRICTLY above maxfit on every repeated
        # round — count' > maxfit ≥ kr keeps every type's clip inactive
        # (so all T simulated fills, max_pods and the tie-break repeat) AND
        # every failure flag strict (k < count'), which is what arms the Go
        # packer's is_full_for early exit. Consuming down TO maxfit (the
        # old ≥-bound) flips a failure flag at equality: the real packer
        # then keeps filling that node with smaller shapes instead of
        # stopping. Hence count - (q-1)·pv ≥ maxfit+1 per packed shape.
        terms = jnp.where(packedv > 0,
                          (counts - maxfit - 1) // jnp.maximum(packedv, 1),
                          INT32_MAX)
        q = jnp.maximum(1, 1 + jnp.min(terms))
        q = jnp.where(nothing | done, 0, q)

        # drop path: largest remaining shape fits nowhere (packer.go:124-128);
        # every pod of that shape fails identically, so drop them all at once
        drop_here = nothing & ~done
        drop_vec = jnp.where((jnp.arange(S) == largest_idx) & drop_here, counts, 0)

        new_counts = jnp.where(done, counts, counts - q * packedv - drop_vec)
        new_dropped = dropped + drop_vec
        new_done = ~jnp.any(new_counts > 0)
        rec = (jnp.where(q > 0, chosen, -1), q, packedv)
        return (new_counts, new_dropped, new_done), rec

    (counts_f, dropped_f, done_f), (chosen_seq, q_seq, packed_seq) = jax.lax.scan(
        node_iter, (counts, dropped, ~jnp.any(counts > 0)), None, length=num_iters)
    return counts_f, dropped_f, done_f, chosen_seq, q_seq, packed_seq


@functools.partial(jax.jit, static_argnames=("num_iters", "cost_tiebreak"))
def pack_chunk_flat(
    shapes, counts, dropped, totals, reserved0, valid, last_valid, pods_unit,
    num_iters: int, prices=None, cost_tiebreak: bool = False, maxfit=None,
):
    """pack_chunk with all outputs flattened into ONE int32 buffer so a solve
    costs exactly one device→host fetch. The TPU here sits behind a tunnel
    with tens-of-ms round-trip latency; the 200 ms p99 budget is spent on
    RTTs, not FLOPs. Layout: [counts S | dropped S | done 1 | chosen L |
    q L | packed L*S]."""
    return flatten_chunk_outputs(*pack_chunk(
        shapes, counts, dropped, totals, reserved0, valid, last_valid,
        pods_unit, num_iters=num_iters, prices=prices,
        cost_tiebreak=cost_tiebreak, maxfit=maxfit))


def flatten_chunk_outputs(counts_f, dropped_f, done_f, chosen_seq, q_seq,
                          packed_seq):
    """THE flat buffer layout (single source of truth, decoded by
    unpack_flat): [counts S | dropped S | done 1 | chosen L | q L |
    packed L·S]. Shared by the XLA and Pallas flat kernels."""
    return jnp.concatenate([
        counts_f, dropped_f, done_f.astype(jnp.int32)[None],
        chosen_seq.astype(jnp.int32), q_seq, packed_seq.reshape(-1),
    ])


def unpack_flat(buf, S: int, L: int):
    """Split a pack_chunk_flat buffer (host numpy) back into components."""
    counts_f = buf[:S]
    dropped_f = buf[S:2 * S]
    done = bool(buf[2 * S])
    o = 2 * S + 1
    chosen = buf[o:o + L]
    q = buf[o + L:o + 2 * L]
    packed = buf[o + 2 * L:o + 2 * L + L * S].reshape(L, S)
    return counts_f, dropped_f, done, chosen, q, packed
