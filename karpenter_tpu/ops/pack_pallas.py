"""Pallas TPU kernel: the whole FFD chunk solve fused into ONE kernel.

The XLA formulation (ops/pack.py) lowers the outer node loop and the inner
shape scan to ~num_iters × S separate fused HLO ops; every intermediate
(reserved, stopped, npacked) round-trips through HBM between scan steps.
This kernel keeps ALL solver state — the (R,T) reservation matrix, per-type
stop flags, per-shape counts — resident in VMEM for the entire solve and
exits the node loop the moment the problem is done (a `while_loop`, not a
fixed-length scan), so converged problems don't pay for dead iterations.

Layout is TPU-native: capacity tensors are stored transposed (R, T) /
(R, S) so the resource axis (R = 8) sits on sublanes and the wide
type/shape axes on lanes; the per-shape fit `min_r floor(avail/shape)` is a
sublane reduction of an (R, T) VPU op.

Semantics are bit-identical to ops.pack.pack_chunk for every committed
node record (chosen, q, packed) and for counts/dropped/done — enforced by
tests/test_pack_pallas.py against both the XLA kernel and the host oracle.
Reference hot loop being replaced: packer.go:114-141 + packable.go:111-173.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from karpenter_tpu.solver.host_ffd import R_PODS

INT32_MAX = jnp.iinfo(jnp.int32).max


def _pack_kernel(
    # inputs
    shapes_t,     # (R, S) int32, reserve semantics, descending shapes
    counts_in,    # (1, S) int32
    dropped_in,   # (1, S) int32
    totals_t,     # (R, T) int32
    reserved0_t,  # (R, T) int32
    valid,        # (1, T) int32 (0/1)
    prices_in,    # (1, T) int32 effective micro-$/h (cost_tiebreak only)
    lastv,        # (1, 1) int32 SMEM — index of largest viable type
    pods_unit,    # (1, 1) int32 SMEM — one pod in device units
    # outputs
    counts_out,   # (1, S)
    dropped_out,  # (1, S)
    done_out,     # (1, 1) SMEM
    chosen_out,   # (1, L)
    q_out,        # (1, L)
    packed_out,   # (L, S)
    # scratch
    resv,         # (R, T) VMEM
    stopped,      # (1, T) VMEM int32
    npacked,      # (1, T) VMEM int32
    maxfit,       # (1, S) VMEM int32
    packedv_s,    # (1, S) VMEM int32
    *,
    cost_tiebreak: bool,
):
    R, S = shapes_t.shape
    T = totals_t.shape[1]
    L = q_out.shape[1]

    # Mosaic has no dynamic slices/loads on the lane (last) axis; columns
    # and scalars at runtime-computed lane indices are extracted by masked
    # reduction instead (a full-width VPU op — cheap at these sizes).
    def lane_col(mat, iota, idx):
        """mat (R, N)[:, idx] → (R, 1) without a dynamic lane slice."""
        return jnp.sum(jnp.where(iota == idx, mat, 0), axis=1, keepdims=True)

    def lane_scalar(row, iota, idx):
        """row (1, N)[0, idx] → scalar without a dynamic lane load."""
        return jnp.sum(jnp.where(iota == idx, row, 0))

    counts_out[:] = counts_in[:]
    dropped_out[:] = dropped_in[:]
    chosen_out[:] = jnp.full((1, L), -1, jnp.int32)
    q_out[:] = jnp.zeros((1, L), jnp.int32)
    packed_out[:] = jnp.zeros((L, S), jnp.int32)

    iota_s = jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
    iota_t = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
    valid_b = valid[:] != 0
    avail0 = totals_t[:] - reserved0_t[:]          # (R, T)

    # maxfit_s = max over valid types of the capacity-bound fit from the
    # initial reservation (fast-forward validity bound — docs/solver.md)
    def maxfit_body(s, _):
        shape_col = lane_col(shapes_t[:], iota_s, s)   # (R, 1)
        kr = jnp.where(shape_col > 0,
                       avail0 // jnp.maximum(shape_col, 1), INT32_MAX)
        kfit = jnp.min(kr, axis=0, keepdims=True)  # (1, T)
        best = jnp.max(jnp.where(valid_b, kfit, -1))
        # masked row store — Mosaic has no scalar VMEM stores
        maxfit[:] = jnp.where(iota_s == s, best, maxfit[:])
        return 0

    jax.lax.fori_loop(0, S, maxfit_body, 0)

    pods_one = jnp.where(
        jax.lax.broadcasted_iota(jnp.int32, (R, 1), 0) == R_PODS,
        pods_unit[0, 0], 0)                        # (R, 1)

    def node_iter(carry):
        it, _ = carry
        counts = counts_out[:]                     # (1, S)
        has = counts > 0
        largest_idx = jnp.min(jnp.where(has, iota_s, INT32_MAX))
        smallest_idx = jnp.max(jnp.where(has, iota_s, -1))
        # fits() uses raw requests (no implicit pods:1) — packable.go:118,146
        smallest_fits = jnp.maximum(
            lane_col(shapes_t[:], iota_s, smallest_idx) - pods_one, 0)  # (R, 1)

        # pass 1: greedy-fill every candidate type at once (VPU over T)
        resv[:] = reserved0_t[:]
        stopped[:] = jnp.where(valid_b, 0, 1).astype(jnp.int32)
        npacked[:] = jnp.zeros((1, T), jnp.int32)

        def shape_step(s, _):
            count = lane_scalar(counts_out[:], iota_s, s)

            @pl.when(count > 0)
            def _():
                shape_col = lane_col(shapes_t[:], iota_s, s)  # (R, 1)
                active = stopped[:] == 0                      # (1, T)
                avail = totals_t[:] - resv[:]
                kr = jnp.where(shape_col > 0,
                               avail // jnp.maximum(shape_col, 1), INT32_MAX)
                kfit = jnp.min(kr, axis=0, keepdims=True)     # (1, T)
                k = jnp.where(active, jnp.clip(kfit, 0, count), 0)
                failure = active & (k < count)
                new_resv = resv[:] + k * shape_col            # bcast (R, T)
                resv[:] = new_resv
                full = jnp.any(
                    (totals_t[:] > 0) &
                    (new_resv + smallest_fits >= totals_t[:]),
                    axis=0, keepdims=True)                    # (1, T)
                new_np = npacked[:] + k
                npacked[:] = new_np
                stopped[:] = jnp.where(
                    failure & (full | (new_np == 0)), 1, stopped[:])
            return 0

        jax.lax.fori_loop(0, S, shape_step, 0)

        max_pods = lane_scalar(npacked[:], iota_t, lastv[0, 0])
        tie = valid_b & (npacked[:] == max_pods)
        if cost_tiebreak:
            # cheapest max-pods type; capacity order (smallest index) breaks
            # price ties — same semantics as ops/pack.py's cost branch and
            # models/cost.order_options_by_price. The fast-forward stays
            # valid: prices are constant, so a repeated round re-derives
            # the identical tie set and the identical chosen type.
            best_price = jnp.min(jnp.where(tie, prices_in[:], INT32_MAX))
            tie = tie & (prices_in[:] == best_price)
        chosen = jnp.min(jnp.where(tie, iota_t, INT32_MAX))
        nothing = max_pods == 0

        # pass 2: replay the chosen type's column alone to recover its
        # per-shape pack vector (each type's fill is independent, so the
        # replay is exact) — avoids materializing the (S, T) k matrix
        totals_col = lane_col(totals_t[:], iota_t, chosen)    # (R, 1)
        resv0_col = lane_col(reserved0_t[:], iota_t, chosen)

        def replay_step(s, carry2):
            resv_col, stopped_c, npacked_c = carry2
            count = lane_scalar(counts_out[:], iota_s, s)
            shape_col = lane_col(shapes_t[:], iota_s, s)
            active = (count > 0) & (stopped_c == 0)
            avail = totals_col - resv_col
            kr = jnp.where(shape_col > 0,
                           avail // jnp.maximum(shape_col, 1), INT32_MAX)
            kfit = jnp.min(kr)
            k = jnp.where(active, jnp.clip(kfit, 0, count), 0)
            failure = active & (k < count)
            resv_col = resv_col + k * shape_col
            full = jnp.any((totals_col > 0) &
                           (resv_col + smallest_fits >= totals_col))
            npacked_c = npacked_c + k
            stopped_c = jnp.where(failure & (full | (npacked_c == 0)),
                                  1, stopped_c)
            packedv_s[:] = jnp.where(iota_s == s, k, packedv_s[:])
            return resv_col, stopped_c, npacked_c

        jax.lax.fori_loop(
            0, S, replay_step,
            (resv0_col, jnp.int32(0), jnp.int32(0)))

        packed = packedv_s[:]                                 # (1, S)
        # exact fast-forward (ops/pack.py, proof in docs/solver.md): every
        # packed shape must stay STRICTLY above maxfit through all repeats
        terms = jnp.where(packed > 0,
                          (counts - maxfit[:] - 1) // jnp.maximum(packed, 1),
                          INT32_MAX)
        q = jnp.maximum(1, 1 + jnp.min(terms))
        q = jnp.where(nothing, 0, q)

        # drop path: the largest remaining shape fits nowhere
        drop_vec = jnp.where(nothing & (iota_s == largest_idx), counts, 0)

        new_counts = counts - q * packed - drop_vec
        counts_out[:] = new_counts
        dropped_out[:] = dropped_out[:] + drop_vec

        @pl.when(q > 0)
        def _():
            iota_l = jax.lax.broadcasted_iota(jnp.int32, (1, L), 1)
            chosen_out[:] = jnp.where(iota_l == it, chosen, chosen_out[:])
            q_out[:] = jnp.where(iota_l == it, q, q_out[:])
            packed_out[pl.ds(it, 1), :] = packed

        done = jnp.logical_not(jnp.any(new_counts > 0))
        return it + 1, done

    init_done = jnp.logical_not(jnp.any(counts_in[:] > 0))
    it_f, done_f = jax.lax.while_loop(
        lambda c: jnp.logical_not(c[1]) & (c[0] < L),
        node_iter, (jnp.int32(0), init_done))
    done_out[0, 0] = done_f.astype(jnp.int32)


@functools.partial(
    jax.jit, static_argnames=("num_iters", "interpret", "cost_tiebreak"))
def pack_chunk_pallas(
    shapes,     # (S, R) int32 — same layout as ops.pack.pack_chunk
    counts,     # (S,)
    dropped,    # (S,)
    totals,     # (T, R)
    reserved0,  # (T, R)
    valid,      # (T,) bool
    last_valid,  # () int32
    pods_unit,  # () int32
    num_iters: int,
    interpret: bool = False,
    prices=None,               # (T,) int32 micro-$/h (models/ffd.encode_prices)
    cost_tiebreak: bool = False,
):
    """Same contract as ops.pack.pack_chunk (up to the junk-row caveat:
    iterations past `done` or with q == 0 report chosen=-1/q=0/packed=0
    here, while the scan version reports stale values — callers only
    consume q > 0 rows). Transposes at the boundary; the kernel runs in
    the (R, lanes) layout. ``cost_tiebreak`` matches ops.pack.pack_chunk:
    cheapest max-pods type wins, capacity order breaks price ties."""
    S, R = shapes.shape
    T = totals.shape[0]
    L = num_iters
    if prices is None:
        prices = jnp.zeros((T,), jnp.int32)

    outs = pl.pallas_call(
        functools.partial(_pack_kernel, cost_tiebreak=cost_tiebreak),
        out_shape=(
            jax.ShapeDtypeStruct((1, S), jnp.int32),   # counts
            jax.ShapeDtypeStruct((1, S), jnp.int32),   # dropped
            jax.ShapeDtypeStruct((1, 1), jnp.int32),   # done
            jax.ShapeDtypeStruct((1, L), jnp.int32),   # chosen
            jax.ShapeDtypeStruct((1, L), jnp.int32),   # q
            jax.ShapeDtypeStruct((L, S), jnp.int32),   # packed
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),     # shapes_t
            pl.BlockSpec(memory_space=pltpu.VMEM),     # counts
            pl.BlockSpec(memory_space=pltpu.VMEM),     # dropped
            pl.BlockSpec(memory_space=pltpu.VMEM),     # totals_t
            pl.BlockSpec(memory_space=pltpu.VMEM),     # reserved0_t
            pl.BlockSpec(memory_space=pltpu.VMEM),     # valid
            pl.BlockSpec(memory_space=pltpu.VMEM),     # prices
            pl.BlockSpec(memory_space=pltpu.SMEM),     # last_valid
            pl.BlockSpec(memory_space=pltpu.SMEM),     # pods_unit
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((R, T), jnp.int32),   # resv
            pltpu.VMEM((1, T), jnp.int32),   # stopped
            pltpu.VMEM((1, T), jnp.int32),   # npacked
            pltpu.VMEM((1, S), jnp.int32),   # maxfit
            pltpu.VMEM((1, S), jnp.int32),   # packedv
        ],
        interpret=interpret,
    )(
        shapes.T.astype(jnp.int32),
        counts.reshape(1, S).astype(jnp.int32),
        dropped.reshape(1, S).astype(jnp.int32),
        totals.T.astype(jnp.int32),
        reserved0.T.astype(jnp.int32),
        valid.reshape(1, T).astype(jnp.int32),
        prices.reshape(1, T).astype(jnp.int32),
        jnp.asarray(last_valid, jnp.int32).reshape(1, 1),
        jnp.asarray(pods_unit, jnp.int32).reshape(1, 1),
    )
    counts_f, dropped_f, done_f, chosen_seq, q_seq, packed_seq = outs
    return (counts_f[0], dropped_f[0], done_f[0, 0] != 0,
            chosen_seq[0], q_seq[0], packed_seq)


@functools.partial(
    jax.jit, static_argnames=("num_iters", "interpret", "cost_tiebreak"))
def pack_chunk_pallas_flat(
    shapes, counts, dropped, totals, reserved0, valid, last_valid, pods_unit,
    num_iters: int,
    interpret: bool = False,
    prices=None,
    cost_tiebreak: bool = False,
):
    """Flattened single-buffer variant in ops.pack's shared layout
    (flatten_chunk_outputs / unpack_flat) so a solve costs exactly one
    device→host fetch (see pack_chunk_flat's rationale — the tunnel RTT
    dwarfs the kernel)."""
    from karpenter_tpu.ops.pack import flatten_chunk_outputs

    return flatten_chunk_outputs(*pack_chunk_pallas(
        shapes, counts, dropped, totals, reserved0, valid,
        last_valid, pods_unit, num_iters=num_iters, interpret=interpret,
        prices=prices, cost_tiebreak=cost_tiebreak))
