"""Pallas TPU kernel: the whole FFD chunk solve fused into ONE kernel.

The XLA formulation (ops/pack.py) lowers the outer node loop and the inner
shape scan to ~num_iters × S separate fused HLO ops; every intermediate
(reserved, stopped, npacked) round-trips through HBM between scan steps.
This kernel keeps ALL solver state — the (R,T) reservation matrix, per-type
stop flags, per-shape counts — resident in VMEM for the entire solve and
exits the node loop the moment the problem is done (a `while_loop`, not a
fixed-length scan), so converged problems don't pay for dead iterations.

Layout is TPU-native and BLOCKED on the shape axis: shapes live as
(n_b, R, B) with B = 128 lanes per block, so the sequential shape walk
loads one block with a dynamic leading index (a cheap VMEM copy) and then
addresses individual shapes with STATIC lane slices — free at compile
time. Mosaic has no dynamic slices on the lane axis, and the previous
formulation worked around that with a masked O(R·S) reduction per shape
step: at the 8192-shape bucket that made every node decision an O(R·S²)
sweep (~0.5 G lane-ops) and the whole solve ~9.5 s. Three structural
changes remove it:

- blocked shape walk: per-step shape access is O(R) (static lane slice)
  plus one O(R·B) block load per 128 steps;
- the fast-forward bound (maxfit) arrives as an INPUT, computed by XLA in
  the jitted wrapper (ops.pack.compute_maxfit) — in-kernel it was an
  O(R·S²) masked loop that dominated the fixed cost;
- early exit: the per-node fill walk stops at the first block where every
  candidate type is stopped (exact — stopped types never unstop within a
  node decision), so a node that fills after a few hundred shapes does not
  walk all 8192.

Semantics are bit-identical to ops.pack.pack_chunk for every committed
node record (chosen, q, packed) and for counts/dropped/done — enforced by
tests/test_pack_pallas.py against both the XLA kernel and the host oracle.
Reference hot loop being replaced: packer.go:114-141 + packable.go:111-173.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from karpenter_tpu.solver.host_ffd import R_PODS

INT32_MAX = jnp.iinfo(jnp.int32).max

LANE_BLOCK = 128  # shape-axis block width (one full lane register)

# The VPU has no native integer divide: a plain int32 `//` lowers to a long
# software sequence that dominated this kernel (measured ~75% of the
# 8192-bucket walk). The solver's divisions only need EXACT results while
# the quotient is small — a capacity fit is consumed through
# clip(kfit, 0, count) and a fast-forward term through 1 + min(terms), and
# both count and terms are bounded by the pod count (the batcher guards at
# 100k, models/ffd.py re-checks) — so quotients are computed in float32
# with exact integer correction rounds, valid for true quotients
# < DIV_CAP-2 and monotonically clamped ABOVE count beyond that
# (behaviorally identical through the clip). Error analysis: q <= DIV_CAP
# keeps the f32 relative error (~3·2^-24) well under 0.05 absolute, BUT
# input rounding can cross an integer boundary in EITHER direction (e.g.
# a=33558527, b=4096: f32(a)=33558528 gives an exact qf of 8193.0, one
# above the true floor 8192 — caught in review r5), so the estimate may be
# off by one either way. One downward and two upward correction rounds
# restore exactness; the remainder test is wrap-safe because with
# q <= q_true+1 the true remainder lies in (-2^31, 2^31), so int32 modular
# arithmetic reproduces it exactly and its SIGN detects the overshoot.
DIV_CAP = 1 << 18


def _floordiv_small(a, b):
    """floor(a/b) for b >= 1: exact while the true quotient < DIV_CAP-2,
    clamped (monotone, >= DIV_CAP-2) above. Negative ``a`` returns exactly
    -1 (the 0-clamped estimate plus one downward correction; NOT the true
    floor, which may be more negative) — the clip consumers treat any
    value <= -1 identically to the true negative floor."""
    qf = a.astype(jnp.float32) / b.astype(jnp.float32)
    q = jnp.minimum(qf, jnp.float32(DIV_CAP)).astype(jnp.int32)
    q = jnp.maximum(q, 0)
    # exact by modular arithmetic (see note above); r < 0 means the float
    # estimate overshot the floor by one — correct DOWN first
    r = a - q * b
    dec = (r < 0).astype(jnp.int32)
    q = q - dec
    r = r + dec * b
    inc = (r >= b).astype(jnp.int32)
    q = q + inc
    r = r - inc * b
    q = q + (r >= b).astype(jnp.int32)
    return q


def _pack_kernel(
    # inputs
    shapes_b,     # (n_b, R, B) int32, reserve semantics, descending shapes
    counts_in,    # (n_b, 1, B) int32
    dropped_in,   # (n_b, 1, B) int32
    totals_t,     # (R, T) int32
    reserved0_t,  # (R, T) int32
    valid,        # (1, T) int32 (0/1)
    prices_in,    # (1, T) int32 effective micro-$/h (cost_tiebreak only)
    maxfit_in,    # (n_b, 1, B) int32 fast-forward bound (wrapper-computed)
    lastv,        # (1, 1) int32 SMEM — index of largest viable type
    pods_unit,    # (1, 1) int32 SMEM — one pod in device units
    # outputs
    counts_out,   # (n_b, 1, B)
    dropped_out,  # (n_b, 1, B)
    done_out,     # (1, 1) SMEM
    chosen_out,   # (1, L)
    q_out,        # (1, L)
    packed_out,   # (n_b, L, B)
    # scratch
    resv,         # (R, T) VMEM
    stopped,      # (1, T) VMEM int32
    npacked,      # (1, T) VMEM int32
    packedv_s,    # (n_b, 1, B) VMEM int32
    *,
    cost_tiebreak: bool,
):
    n_b, R, B = shapes_b.shape
    T = totals_t.shape[1]
    L = q_out.shape[1]

    # lane-axis columns/scalars at RUNTIME-computed indices are extracted by
    # masked reduction (no dynamic lane slices in Mosaic). In this blocked
    # formulation these run once per NODE DECISION, never per shape step.
    def lane_col(mat, iota, idx):
        """mat (R, N)[:, idx] → (R, 1) without a dynamic lane slice."""
        return jnp.sum(jnp.where(iota == idx, mat, 0), axis=1, keepdims=True)

    def lane_scalar(row, iota, idx):
        """row (1, N)[0, idx] → scalar without a dynamic lane load."""
        return jnp.sum(jnp.where(iota == idx, row, 0))

    counts_out[:] = counts_in[:]
    dropped_out[:] = dropped_in[:]
    chosen_out[:] = jnp.full((1, L), -1, jnp.int32)
    q_out[:] = jnp.zeros((1, L), jnp.int32)
    packed_out[:] = jnp.zeros((n_b, L, B), jnp.int32)

    iota_t = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
    # global shape index per blocked element: [b, 0, j] → b*B + j
    giota = (jax.lax.broadcasted_iota(jnp.int32, (n_b, 1, B), 0) * B
             + jax.lax.broadcasted_iota(jnp.int32, (n_b, 1, B), 2))
    valid_b = valid[:] != 0

    pods_one = jnp.where(
        jax.lax.broadcasted_iota(jnp.int32, (R, 1), 0) == R_PODS,
        pods_unit[0, 0], 0)                        # (R, 1)

    def node_iter(carry):
        it, _ = carry
        counts = counts_out[:]                     # (n_b, 1, B)
        has = counts > 0
        largest_idx = jnp.min(jnp.where(has, giota, INT32_MAX))
        smallest_idx = jnp.max(jnp.where(has, giota, -1))
        # fits() uses raw requests (no implicit pods:1) — packable.go:118,146
        s_blk = shapes_b[pl.ds(smallest_idx // B, 1)][0]       # (R, B)
        smallest_fits = jnp.maximum(
            lane_col(s_blk, iota_b, smallest_idx % B) - pods_one, 0)

        # pass 1: greedy-fill every candidate type at once (VPU over T).
        # Walk shapes block-by-block; stop at the first block boundary
        # where no type remains active (exact: stopped never clears).
        resv[:] = reserved0_t[:]
        stopped[:] = jnp.where(valid_b, 0, 1).astype(jnp.int32)
        npacked[:] = jnp.zeros((1, T), jnp.int32)

        def fill_block(carry2):
            b, _ = carry2
            sh_blk = shapes_b[pl.ds(b, 1)][0]      # (R, B)
            cnt_blk = counts_out[pl.ds(b, 1)][0]   # (1, B)
            for j in range(B):                     # static lane indices
                # BRANCHLESS step: the per-shape count stays a (1, 1)
                # vector (no vector→scalar transfer, no pl.when branch) —
                # per-step scalar extraction and branching dominated the
                # 8192-bucket walk. count == 0 degrades to a no-op through
                # the masks (k = 0 everywhere).
                count = cnt_blk[:, j:j + 1]                   # (1, 1)
                shape_col = sh_blk[:, j:j + 1]                # (R, 1)
                active = (stopped[:] == 0) & (count > 0)      # (1, T)
                avail = totals_t[:] - resv[:]
                kr = jnp.where(shape_col > 0,
                               _floordiv_small(
                                   avail, jnp.maximum(shape_col, 1)),
                               INT32_MAX)
                kfit = jnp.min(kr, axis=0, keepdims=True)     # (1, T)
                k = jnp.where(active, jnp.clip(kfit, 0, count), 0)
                failure = active & (k < count)
                new_resv = resv[:] + k * shape_col            # bcast (R, T)
                resv[:] = new_resv
                full = jnp.any(
                    (totals_t[:] > 0) &
                    (new_resv + smallest_fits >= totals_t[:]),
                    axis=0, keepdims=True)                    # (1, T)
                new_np = npacked[:] + k
                npacked[:] = new_np
                stopped[:] = jnp.where(
                    failure & (full | (new_np == 0)), 1, stopped[:])
            return b + 1, jnp.any(stopped[:] == 0)

        # start at the first block holding a remaining shape: shapes are
        # consumed in descending order, so late node decisions would
        # otherwise trudge through thousands of already-empty leading lanes
        # (count 0 → branchless no-ops, but real cycles). largest_idx IS
        # the first remaining shape. Exact: skipped blocks are all-zero.
        first_b = largest_idx // B
        jax.lax.while_loop(
            lambda c: (c[0] < n_b) & c[1],
            fill_block, (first_b, jnp.any(stopped[:] == 0)))

        max_pods = lane_scalar(npacked[:], iota_t, lastv[0, 0])
        tie = valid_b & (npacked[:] == max_pods)
        if cost_tiebreak:
            # cheapest max-pods type; capacity order (smallest index) breaks
            # price ties — same semantics as ops/pack.py's cost branch and
            # models/cost.order_options_by_price. The fast-forward stays
            # valid: prices are constant, so a repeated round re-derives
            # the identical tie set and the identical chosen type.
            best_price = jnp.min(jnp.where(tie, prices_in[:], INT32_MAX))
            tie = tie & (prices_in[:] == best_price)
        chosen = jnp.min(jnp.where(tie, iota_t, INT32_MAX))
        nothing = max_pods == 0

        # pass 2: replay the chosen type's column alone to recover its
        # per-shape pack vector (each type's fill is independent, so the
        # replay is exact) — avoids materializing the (S, T) k matrix.
        # All per-step math here is (R, 1)-sized; the walk early-exits the
        # moment the replayed type stops (its k is 0 ever after — exact,
        # and packedv_s is pre-zeroed).
        totals_col = lane_col(totals_t[:], iota_t, chosen)    # (R, 1)
        resv0_col = lane_col(reserved0_t[:], iota_t, chosen)
        packedv_s[:] = jnp.zeros((n_b, 1, B), jnp.int32)

        def replay_block(carry2):
            b, resv_col, stopped_c, npacked_c = carry2
            sh_blk = shapes_b[pl.ds(b, 1)][0]      # (R, B)
            cnt_blk = counts_out[pl.ds(b, 1)][0]   # (1, B)
            kblk = jnp.zeros((1, B), jnp.int32)
            for j in range(B):
                # branchless, all-(1,1)/(R,1) math — see fill_block
                count = cnt_blk[:, j:j + 1]                   # (1, 1)
                shape_col = sh_blk[:, j:j + 1]                # (R, 1)
                active = (count > 0) & (stopped_c == 0)       # (1, 1)
                avail = totals_col - resv_col
                kr = jnp.where(shape_col > 0,
                               _floordiv_small(
                                   avail, jnp.maximum(shape_col, 1)),
                               INT32_MAX)
                kfit = jnp.min(kr, axis=0, keepdims=True)     # (1, 1)
                k = jnp.where(active, jnp.clip(kfit, 0, count), 0)
                failure = active & (k < count)
                resv_col = resv_col + k * shape_col
                full = jnp.any((totals_col > 0) &
                               (resv_col + smallest_fits >= totals_col),
                               axis=0, keepdims=True)         # (1, 1)
                npacked_c = npacked_c + k
                stopped_c = jnp.where(failure & (full | (npacked_c == 0)),
                                      1, stopped_c)
                kblk = jnp.where(iota_b == j, k, kblk)  # static mask: free
            packedv_s[pl.ds(b, 1)] = kblk.reshape(1, 1, B)
            return b + 1, resv_col, stopped_c, npacked_c

        jax.lax.while_loop(
            lambda c: (c[0] < n_b) & jnp.all(c[2] == 0),
            replay_block,
            (first_b, resv0_col, jnp.zeros((1, 1), jnp.int32),
             jnp.zeros((1, 1), jnp.int32)))

        packed = packedv_s[:]                                 # (n_b, 1, B)
        # exact fast-forward (ops/pack.py, proof in docs/solver.md): every
        # packed shape must stay STRICTLY above maxfit through all repeats.
        # Negative numerators (count already at/below the bound) must yield
        # a negative term so q stays 1 — _floordiv_small returns -1 for
        # them (not the true, possibly more negative floor), which would
        # already suffice; the explicit -1 branch states the intent.
        numer = counts - maxfit_in[:] - 1
        terms = jnp.where(
            packed > 0,
            jnp.where(numer < 0, -1,
                      _floordiv_small(numer, jnp.maximum(packed, 1))),
            INT32_MAX)
        q = jnp.maximum(1, 1 + jnp.min(terms))
        q = jnp.where(nothing, 0, q)

        # drop path: the largest remaining shape fits nowhere
        drop_vec = jnp.where(nothing & (giota == largest_idx), counts, 0)

        new_counts = counts - q * packed - drop_vec
        counts_out[:] = new_counts
        dropped_out[:] = dropped_out[:] + drop_vec

        @pl.when(q > 0)
        def _():
            iota_l = jax.lax.broadcasted_iota(jnp.int32, (1, L), 1)
            chosen_out[:] = jnp.where(iota_l == it, chosen, chosen_out[:])
            q_out[:] = jnp.where(iota_l == it, q, q_out[:])

            def store(b, _):
                packed_out[pl.ds(b, 1), pl.ds(it, 1), :] = (
                    packedv_s[pl.ds(b, 1)])
                return 0

            jax.lax.fori_loop(0, n_b, store, 0)

        done = jnp.logical_not(jnp.any(new_counts > 0))
        return it + 1, done

    init_done = jnp.logical_not(jnp.any(counts_in[:] > 0))
    it_f, done_f = jax.lax.while_loop(
        lambda c: jnp.logical_not(c[1]) & (c[0] < L),
        node_iter, (jnp.int32(0), init_done))
    done_out[0, 0] = done_f.astype(jnp.int32)


@functools.partial(
    jax.jit, static_argnames=("num_iters", "interpret", "cost_tiebreak"))
def pack_chunk_pallas(
    shapes,     # (S, R) int32 — same layout as ops.pack.pack_chunk
    counts,     # (S,)
    dropped,    # (S,)
    totals,     # (T, R)
    reserved0,  # (T, R)
    valid,      # (T,) bool
    last_valid,  # () int32
    pods_unit,  # () int32
    num_iters: int,
    interpret: bool = False,
    prices=None,               # (T,) int32 micro-$/h (models/ffd.encode_prices)
    cost_tiebreak: bool = False,
    maxfit=None,               # (S,) int32 precomputed fast-forward bound
):
    """Same contract as ops.pack.pack_chunk (up to the junk-row caveat:
    iterations past `done` or with q == 0 report chosen=-1/q=0/packed=0
    here, while the scan version reports stale values — callers only
    consume q > 0 rows). Re-layouts at the boundary (XLA-side, cheap): the
    kernel runs blocked (n_b, R, B) on the shape axis and (R, lanes) for
    capacity tensors. ``cost_tiebreak`` matches ops.pack.pack_chunk:
    cheapest max-pods type wins, capacity order breaks price ties.

    PRECONDITION: every entry of ``counts`` must stay below ``DIV_CAP - 2``
    (1 << 18, minus the two correction rounds) — the kernel's divisions are
    exact float32 only while true quotients stay under that cap, and a
    fast-forward quotient can reach the largest per-shape count. Callers
    holding concrete counts should call ``check_counts_within_div_cap``;
    the auto-router (models/ffd.py, solver/batch_solve.py) demotes such
    problems to the XLA scan instead.

    ``maxfit``: chunk-invariant fast-forward bound; passed in by chunk
    loops that compute it once per solve (models/ffd.solve_ffd_device),
    computed here (once per chunk) when omitted."""
    from karpenter_tpu.ops.pack import compute_maxfit

    S, R = shapes.shape
    T = totals.shape[0]
    L = num_iters
    B = min(S, LANE_BLOCK)
    assert S % B == 0, f"shape bucket {S} not a multiple of {B}"
    n_b = S // B
    if prices is None:
        prices = jnp.zeros((T,), jnp.int32)

    shapes32 = shapes.astype(jnp.int32)
    # [b, r, j] = shapes[b*B + j, r]
    shapes_blocked = shapes32.T.reshape(R, n_b, B).transpose(1, 0, 2)
    if maxfit is None:
        maxfit = compute_maxfit(shapes32, totals.astype(jnp.int32),
                                reserved0.astype(jnp.int32), valid)

    outs = pl.pallas_call(
        functools.partial(_pack_kernel, cost_tiebreak=cost_tiebreak),
        out_shape=(
            jax.ShapeDtypeStruct((n_b, 1, B), jnp.int32),   # counts
            jax.ShapeDtypeStruct((n_b, 1, B), jnp.int32),   # dropped
            jax.ShapeDtypeStruct((1, 1), jnp.int32),        # done
            jax.ShapeDtypeStruct((1, L), jnp.int32),        # chosen
            jax.ShapeDtypeStruct((1, L), jnp.int32),        # q
            jax.ShapeDtypeStruct((n_b, L, B), jnp.int32),   # packed
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),     # shapes_b
            pl.BlockSpec(memory_space=pltpu.VMEM),     # counts
            pl.BlockSpec(memory_space=pltpu.VMEM),     # dropped
            pl.BlockSpec(memory_space=pltpu.VMEM),     # totals_t
            pl.BlockSpec(memory_space=pltpu.VMEM),     # reserved0_t
            pl.BlockSpec(memory_space=pltpu.VMEM),     # valid
            pl.BlockSpec(memory_space=pltpu.VMEM),     # prices
            pl.BlockSpec(memory_space=pltpu.VMEM),     # maxfit
            pl.BlockSpec(memory_space=pltpu.SMEM),     # last_valid
            pl.BlockSpec(memory_space=pltpu.SMEM),     # pods_unit
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((R, T), jnp.int32),        # resv
            pltpu.VMEM((1, T), jnp.int32),        # stopped
            pltpu.VMEM((1, T), jnp.int32),        # npacked
            pltpu.VMEM((n_b, 1, B), jnp.int32),   # packedv
        ],
        interpret=interpret,
    )(
        shapes_blocked,
        counts.reshape(n_b, 1, B).astype(jnp.int32),
        dropped.reshape(n_b, 1, B).astype(jnp.int32),
        totals.T.astype(jnp.int32),
        reserved0.T.astype(jnp.int32),
        valid.reshape(1, T).astype(jnp.int32),
        prices.reshape(1, T).astype(jnp.int32),
        maxfit.reshape(n_b, 1, B).astype(jnp.int32),
        jnp.asarray(last_valid, jnp.int32).reshape(1, 1),
        jnp.asarray(pods_unit, jnp.int32).reshape(1, 1),
    )
    counts_f, dropped_f, done_f, chosen_seq, q_seq, packed_seq = outs
    return (counts_f.reshape(S), dropped_f.reshape(S), done_f[0, 0] != 0,
            chosen_seq[0], q_seq[0],
            packed_seq.transpose(1, 0, 2).reshape(L, S))


@functools.partial(
    jax.jit, static_argnames=("num_iters", "interpret", "cost_tiebreak"))
def pack_chunk_pallas_flat(
    shapes, counts, dropped, totals, reserved0, valid, last_valid, pods_unit,
    num_iters: int,
    interpret: bool = False,
    prices=None,
    cost_tiebreak: bool = False,
    maxfit=None,
):
    """Flattened single-buffer variant in ops.pack's shared layout
    (flatten_chunk_outputs / unpack_flat) so a solve costs exactly one
    device→host fetch (see pack_chunk_flat's rationale — the tunnel RTT
    dwarfs the kernel). Same ``counts < DIV_CAP - 2`` precondition as
    pack_chunk_pallas."""
    from karpenter_tpu.ops.pack import flatten_chunk_outputs

    return flatten_chunk_outputs(*pack_chunk_pallas(
        shapes, counts, dropped, totals, reserved0, valid,
        last_valid, pods_unit, num_iters=num_iters, interpret=interpret,
        prices=prices, cost_tiebreak=cost_tiebreak, maxfit=maxfit))


def check_counts_within_div_cap(counts) -> None:
    """Host-side guard for the DIV_CAP precondition, for call sites where
    ``counts`` is still concrete (tests, bench, direct kernel users). The
    jitted wrappers above only ever see tracers, so they cannot enforce
    this themselves; the production routers (models/ffd.py,
    solver/batch_solve.py) demote violating problems to the XLA scan
    instead of raising."""
    import numpy as np

    m = int(np.asarray(counts).max(initial=0))
    if m >= DIV_CAP - 2:
        raise ValueError(
            f"pack_chunk_pallas precondition violated: max per-shape count "
            f"{m} >= DIV_CAP-2 ({DIV_CAP - 2}); the kernel's float32 "
            f"division is only exact below that — route to the XLA scan")
