"""Device-vectorized packing-policy scoring for fused windows.

The host cost tie-break prices one (packable, constraints) cell at a time —
``policy.score()`` per packable per problem, a Python loop over offerings
inside each call. A fused window (ops/device_filter.py) already holds the
catalog's offering structure on device as bit-planes; this module scores
EVERY feasible (schedule × type × capacity-type) cell of the window in one
jit and hands the per-problem int32 micro-$ rows straight to the pack
kernel's existing ``prices`` seam.

Table algebra (host-built, cached per (planes, policy, cost config, ctx)):

- ``price_ct (TB, C) int32``: the policy's base score of type t at capacity
  type c, in micro-$ — encoded with models/ffd.encode_prices' exact
  truncation (``min(int(p * 1e6), INT32_MAX)``). Encoding is monotone, so
  min-over-offerings commutes with it: for penalty-free policies the device
  row is bit-for-bit ``encode_prices([policy.score(...)])`` (the default
  policy's differential guarantee rides on this).
- ``rate_tz (TB, Z) float32``: spot interruption rate per (type, zone),
  +inf where the type has no spot offering in the zone. Only built for the
  interruption-priced policy.
- ``soft_bz (B, Z) int32`` (per WINDOW, not per planes): the schedule's
  preferred-affinity votes as fixed-point micro-$ adjustments,
  ``clamp(-weight x round(soft_cost x 1e6))`` per voted zone, 0 elsewhere
  (scheduling/affinity.py builds the votes from the probe-verified pair
  planes). A cell's adjustment is the best case over its viable zones
  (min), applied with an offset-uint32 exact add — operands are clamped
  to ±(2^30-1) so the sum can never wrap — floored at 0 and saturated at
  INT32_MAX. A zero vote row (or weight scale 0, or the
  KARPENTER_SOFT_AFFINITY kill switch) skips the term entirely: the jit
  is compiled without it, so the default path stays bit-for-bit the
  pre-soft-affinity program (docs/scheduling.md §8).

Device kernel per window: the offering viability product
``zc & ct_allowed`` (the same algebra as device_filter._mask_expr), plus —
for interruption-priced — the reclaim tax ``round(float32(min allowed-zone
rate) × float32(repack micro-$))`` added to the spot column with a
saturating int32 add (a saturated cell never beats a real price; a zero
penalty leaves the cell bit-identical to the base price). ``best(b, t)`` is
the min over viable capacity types, INT32_MAX where none.

The device verdict stays a FILTER: every window's score rows are
spot-checked at the fused probe columns against a numpy mirror of the same
tables; a diverging member's whole row is re-derived on host (scalar wins,
``karpenter_policy_fallback_total{reason="score-mismatch"}``), and any
backend failure falls back to the per-cell host loop for the whole window.
``KARPENTER_POLICY_DEVICE=0`` is the kill switch (the bench A/B lever).
"""

from __future__ import annotations

import functools
import os
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from karpenter_tpu.api import wellknown
from karpenter_tpu.metrics.policy import (
    POLICY_CELLS_SCORED_TOTAL, POLICY_FALLBACK_TOTAL, POLICY_SCORE_SECONDS,
)

_ENV = "KARPENTER_POLICY_DEVICE"
_INT32_MAX = np.int32(np.iinfo(np.int32).max)
# soft adjustments clamp to ±(2^30 - 1) so (adj + 2^30) fits int32 and the
# offset-uint32 add below can never wrap
_SOFT_CLAMP = (1 << 30) - 1
_SOFT_OFF = np.uint32(1 << 30)

_LOCK = threading.Lock()
_TABLE_CACHE: dict = {}
_TABLE_CACHE_CAP = 16
_TCZ_CACHE: dict = {}


def enabled() -> bool:
    """Kill switch: KARPENTER_POLICY_DEVICE=0/false/off forces the per-cell
    host loop (the bench A/B baseline); default ON."""
    return os.environ.get(_ENV, "").strip().lower() not in ("0", "false", "off")


def _encode_micro(p: float) -> np.int32:
    """EXACTLY models/ffd.encode_prices' per-entry truncation, so the
    device row and the host loop's encode_prices output agree bit-for-bit
    for penalty-free policies."""
    if p != float("inf"):
        return np.int32(min(int(p * 1e6), int(_INT32_MAX)))
    return _INT32_MAX


class _Tables:
    __slots__ = ("price_ct", "rate_tz", "spot_idx", "use_pen", "repack_micro")


def _build_tables(planes, policy, cost_config, ctx) -> Optional[_Tables]:
    """Host-side score tables over the planes' type axis. None when the
    policy's algebra doesn't factor into (type, ct) base + spot penalty —
    such policies keep the host loop."""
    from karpenter_tpu.solver.policy import (
        CheapestFeasible, InterruptionPriced, ThroughputPerDollar,
    )

    if not isinstance(policy, (CheapestFeasible, InterruptionPriced,
                               ThroughputPerDollar)):
        return None
    C = max(1, len(planes.ct_vocab))
    Z = max(1, len(planes.zone_vocab))
    t = _Tables()
    t.spot_idx = planes.ct_vocab.get(wellknown.CAPACITY_TYPE_SPOT, -1)
    t.use_pen = (isinstance(policy, InterruptionPriced) and t.spot_idx >= 0
                 and ctx.repack_cost_per_hour > 0.0)
    t.repack_micro = np.float32(ctx.repack_cost_per_hour * 1e6)
    t.price_ct = np.full((planes.TB, C), _INT32_MAX, np.int32)
    t.rate_tz = np.full((planes.TB, Z), np.inf, np.float32) if t.use_pen \
        else None
    # resolve the planes axis back to instance types via the catalog key —
    # callers pass the same uni_types list the planes were built from
    return t


def _fill_tables(t: _Tables, planes, uni_types, policy, cost_config, ctx):
    from karpenter_tpu.solver.policy import ThroughputPerDollar

    factor = cost_config.spot_price_factor
    tput = isinstance(policy, ThroughputPerDollar)
    for i, it in enumerate(uni_types):
        div = 1.0
        if tput:
            div = float(ctx.throughput.get(it.name, 1.0))
            if div <= 0.0:
                continue  # zero-throughput types never win: stay INT32_MAX
        for c, ci in planes.ct_vocab.items():
            base = it.price * factor \
                if c == wellknown.CAPACITY_TYPE_SPOT else it.price
            # same float path as the scalar scorers: multiply/divide in
            # float64, encode once at the end
            t.price_ct[i, ci] = _encode_micro(base / div)
        if t.rate_tz is not None:
            for o in it.offerings:
                if o.capacity_type != wellknown.CAPACITY_TYPE_SPOT:
                    continue
                z = planes.zone_vocab.get(o.zone)
                if z is not None:
                    t.rate_tz[i, z] = min(t.rate_tz[i, z],
                                          np.float32(o.interruption_rate))


def tables_for(planes, uni_types, policy, cost_config, ctx) -> Optional[_Tables]:
    key = (planes.key, policy.name, cost_config, ctx.token())
    with _LOCK:
        hit = _TABLE_CACHE.get(key)
    if hit is not None:
        return hit if hit is not False else None
    t = _build_tables(planes, policy, cost_config, ctx)
    if t is not None:
        _fill_tables(t, planes, uni_types, policy, cost_config, ctx)
        t.price_ct.flags.writeable = False
        if t.rate_tz is not None:
            t.rate_tz.flags.writeable = False
    with _LOCK:
        if len(_TABLE_CACHE) >= _TABLE_CACHE_CAP:
            _TABLE_CACHE.pop(next(iter(_TABLE_CACHE)))
        _TABLE_CACHE[key] = t if t is not None else False
    return t


def _offer_tcz(planes) -> np.ndarray:
    """(TB, C, Z) bool unpack of the offer plane's zone words, cached per
    planes identity — the soft-affinity term's per-zone viability view."""
    with _LOCK:
        hit = _TCZ_CACHE.get(planes.key)
    if hit is not None:
        return hit
    Z = max(1, len(planes.zone_vocab))
    z = np.arange(Z)
    tcz = ((planes.offer_plane[:, :, z // 32] >> (z % 32).astype(np.uint32))
           & np.uint32(1)).astype(bool)
    tcz.flags.writeable = False
    with _LOCK:
        if len(_TCZ_CACHE) >= _TABLE_CACHE_CAP:
            _TCZ_CACHE.pop(next(iter(_TCZ_CACHE)))
        _TCZ_CACHE[planes.key] = tcz
    return tcz


def _soft_rows(planes, soft_list, ctx) -> Optional[np.ndarray]:
    """(B, Z) int32 fixed-point soft-affinity rows, or None when no member
    carries a usable zone vote (the jit then compiles without the term).
    Votes for zones outside the planes vocabulary can never launch here
    and are dropped."""
    from karpenter_tpu.scheduling.affinity import soft_enabled
    from karpenter_tpu.solver.policy import soft_zone_votes

    if soft_list is None or not soft_enabled():
        return None
    scale = int(round(ctx.soft_affinity_cost_per_weight * 1e6))
    if scale <= 0:
        return None
    Z = max(1, len(planes.zone_vocab))
    rows = np.zeros((len(soft_list), Z), np.int32)
    any_vote = False
    for b, soft in enumerate(soft_list):
        for zone, w in soft_zone_votes(soft).items():
            z = planes.zone_vocab.get(zone)
            if z is None:
                continue
            rows[b, z] = np.int32(
                max(-_SOFT_CLAMP, min(-w * scale, _SOFT_CLAMP)))
            any_vote = any_vote or rows[b, z] != 0
    return rows if any_vote else None


def _cells_expr(xp, offer_p, price_ct, zone_words, ct_allowed,
                rate_tz, zone_allowed, repack, spot_idx, use_pen,
                soft_bz=None, offer_tcz=None, use_soft=False):
    """The shared (B, TB, C) cell algebra — numpy and jax.numpy run the
    same expression, so the host mirror IS the device program on xp=np."""
    zc = ((offer_p[None, :, :, :] & zone_words[:, None, None, :]) != 0).any(-1)
    viable = zc & ct_allowed[:, None, :]
    cells = xp.where(viable, price_ct[None, :, :], _INT32_MAX)    # int32
    if use_pen:
        rmask = zone_allowed[:, None, :] & xp.isfinite(rate_tz)[None, :, :]
        minrate = xp.min(
            xp.where(rmask, rate_tz[None, :, :], xp.float32(xp.inf)),
            axis=-1)                       # (B, TB), float32 on BOTH sides
        # (a float64 promotion here would fork the mirror from the device)
        # reclaim tax in float32, identical mirror ops both sides; the add
        # saturates in uint32 (max sum (2^31-1) + 2^31 < 2^32, no wrap) so
        # a saturated spot cell never beats a real price and a zero penalty
        # leaves the cell bit-identical to the base price
        penf = xp.where(xp.isfinite(minrate),
                        xp.round(minrate.astype(xp.float32) * repack),
                        xp.float32(0.0))
        pen_u = xp.minimum(penf, xp.float32(2147483648.0)).astype(xp.uint32)
        spot_u = cells[:, :, spot_idx].astype(xp.uint32)
        cell_u = xp.minimum(spot_u + pen_u, xp.uint32(_INT32_MAX))
        spot = cell_u.astype(xp.int32)
        if xp is np:
            cells[:, :, spot_idx] = spot
        else:
            cells = cells.at[:, :, spot_idx].set(spot)
    if use_soft:
        # preferred-affinity term: per (schedule, type, ct) the BEST case
        # over viable zones (min of the signed fixed-point votes — the
        # launch steering realizes the winning zone). Exact int add via a
        # +2^30 offset in uint32: adj ∈ [-(2^30-1), 2^30-1] and cells ∈
        # [0, 2^31-1], so the sum < 2^32 never wraps; the result floors at
        # 0 and saturates at INT32_MAX. Infeasible/saturated cells keep
        # INT32_MAX — a bonus can never revive a cell feasibility rejected.
        zmask = offer_tcz[None, :, :, :] & zone_allowed[:, None, None, :]
        adj = xp.min(xp.where(zmask, soft_bz[:, None, None, :], _INT32_MAX),
                     axis=-1)                                  # (B, TB, C)
        adj = xp.where(adj == _INT32_MAX, xp.int32(0), adj)
        cell_u = cells.astype(xp.uint32) \
            + (adj + xp.int32(1 << 30)).astype(xp.uint32)
        soft_cells = xp.minimum(
            xp.maximum(cell_u, _SOFT_OFF) - _SOFT_OFF,
            xp.uint32(_INT32_MAX)).astype(xp.int32)
        cells = xp.where(cells != _INT32_MAX, soft_cells, cells)
    best = xp.min(cells, axis=-1).astype(xp.int32)                # (B, TB)
    return best, viable


@functools.lru_cache(maxsize=8)
def _score_jit(spot_idx: int, use_pen: bool, use_soft: bool = False):
    import jax
    import jax.numpy as jnp

    def body(offer_p, price_ct, zone_words, ct_allowed, rate_tz,
             zone_allowed, repack, soft_bz, offer_tcz):
        best, viable = _cells_expr(jnp, offer_p, price_ct, zone_words,
                                   ct_allowed, rate_tz, zone_allowed,
                                   repack, spot_idx, use_pen,
                                   soft_bz=soft_bz, offer_tcz=offer_tcz,
                                   use_soft=use_soft)
        return best, jnp.sum(viable)

    return jax.jit(body)


def _rows_host(planes, verify) -> tuple:
    """Per-schedule allowed words/bits for the scoring kernel, unpacked to
    boolean ct/zone rows (host numpy; B and vocab sizes are small)."""
    from karpenter_tpu.ops.device_filter import schedule_row

    B = len(verify)
    C = max(1, len(planes.ct_vocab))
    Z = max(1, len(planes.zone_vocab))
    Wz = planes.offer_plane.shape[2]
    zone_words = np.zeros((B, Wz), np.uint32)
    ct_allowed = np.zeros((B, C), bool)
    zone_allowed = np.zeros((B, Z), bool)
    for b, (allowed, required) in enumerate(verify):
        _, _, _, zr, ct_bits, _ = schedule_row(planes, allowed, required)
        zone_words[b] = zr
        ct_allowed[b] = [(int(ct_bits) >> c) & 1 for c in range(C)]
        zone_allowed[b] = [(int(zr[z // 32]) >> (z % 32)) & 1
                           for z in range(Z)]
    return zone_words, ct_allowed, zone_allowed


def _host_best(t: _Tables, planes, zone_words, ct_allowed, zone_allowed,
               cols: Optional[np.ndarray] = None,
               soft_bz: Optional[np.ndarray] = None) -> np.ndarray:
    """Numpy mirror of the device program (optionally restricted to the
    probe type columns) — the scalar-oracle leg of the filter contract."""
    offer_p = planes.offer_plane
    price_ct = t.price_ct
    rate_tz = t.rate_tz
    offer_tcz = _offer_tcz(planes) if soft_bz is not None else None
    if cols is not None:
        offer_p = offer_p[cols]
        price_ct = price_ct[cols]
        rate_tz = rate_tz[cols] if rate_tz is not None else None
        offer_tcz = offer_tcz[cols] if offer_tcz is not None else None
    if rate_tz is None:
        rate_tz = np.zeros((price_ct.shape[0], zone_allowed.shape[1]),
                           np.float32)
    best, _ = _cells_expr(np, offer_p, price_ct, zone_words, ct_allowed,
                          rate_tz.copy(), zone_allowed, t.repack_micro,
                          t.spot_idx, t.use_pen,
                          soft_bz=soft_bz, offer_tcz=offer_tcz,
                          use_soft=soft_bz is not None)
    return best


def score_fused_window(fused, policy, cost_config, ctx) -> Optional[List[np.ndarray]]:
    """Score every member of a fused batch on device: one jit for the whole
    window, probe-verified per member. Returns one pre-encoded (TB,) int32
    micro-$ row per member (aligned with ``fused.batch_idx``, gathered to
    the member's packable order), or None → the caller runs the per-cell
    host loop unchanged."""
    from karpenter_tpu.ops.device_filter import planes_for

    if not enabled():
        return None
    planes = planes_for(fused.uni_types)
    if planes is None:
        return None
    tables = tables_for(planes, fused.uni_types, policy, cost_config, ctx)
    if tables is None:
        POLICY_FALLBACK_TOTAL.inc(reason="unfactorable-policy")
        return None
    t0 = time.perf_counter()
    zone_words, ct_allowed, zone_allowed = _rows_host(planes, fused.verify)
    rate_tz = tables.rate_tz if tables.rate_tz is not None else \
        np.zeros((planes.TB, zone_allowed.shape[1]), np.float32)
    soft_bz = _soft_rows(planes, getattr(fused, "soft", None), ctx)
    use_soft = soft_bz is not None
    if use_soft:
        offer_tcz = _offer_tcz(planes)
    else:
        # the no-preference window compiles WITHOUT the soft term (the
        # extra operands are dead inputs) — bit-for-bit the pre-soft path
        soft_bz = np.zeros((1, 1), np.int32)
        offer_tcz = np.zeros((1, 1, 1), bool)
    try:
        best_d, ncells = _score_jit(tables.spot_idx, tables.use_pen,
                                    use_soft)(
            planes.offer_plane, tables.price_ct, zone_words, ct_allowed,
            rate_tz, zone_allowed, tables.repack_micro, soft_bz, offer_tcz)
        best = np.asarray(best_d)
        POLICY_CELLS_SCORED_TOTAL.inc(amount=float(np.asarray(ncells)))
    except Exception:
        POLICY_FALLBACK_TOTAL.inc(reason="jax-backend-unavailable")
        return None
    POLICY_SCORE_SECONDS.observe(time.perf_counter() - t0, stage="device")

    # probe verification: the fused window's sampled type columns, device
    # vs the numpy mirror — exact int equality expected; a diverging
    # member's row is re-derived fully on host (scalar wins)
    t1 = time.perf_counter()
    cols = np.unique(fused.probe_idx[fused.probe_idx < planes.n])
    ref = _host_best(tables, planes, zone_words, ct_allowed, zone_allowed,
                     cols=cols,
                     soft_bz=soft_bz if use_soft else None)    # (B, K)
    got = best[:, cols]
    for b in range(len(fused.verify)):
        if not np.array_equal(got[b], ref[b]):
            soft_member = use_soft and bool(soft_bz[b].any())
            POLICY_FALLBACK_TOTAL.inc(
                reason="soft-affinity-mismatch" if soft_member
                else "score-mismatch")
            best[b] = _host_best(
                tables, planes, zone_words[b:b + 1], ct_allowed[b:b + 1],
                zone_allowed[b:b + 1],
                soft_bz=soft_bz[b:b + 1] if use_soft else None)[0]
    POLICY_SCORE_SECONDS.observe(time.perf_counter() - t1, stage="verify")

    # gather the planes axis to each member's packable order and pad to TB
    # (identical today — universe packables ride the planes' type order —
    # but the gather keeps the seam correct if packables ever filter)
    idx = np.fromiter((p.index for p in fused.packables), np.int64,
                      len(fused.packables))
    out: List[np.ndarray] = []
    for b in range(len(fused.batch_idx)):
        row = np.full((planes.TB,), _INT32_MAX, np.int32)
        row[:len(idx)] = best[b, idx]
        out.append(row)
    return out


def steer_zone(instance_types, requirements, cost_config, ctx,
               soft) -> Optional[str]:
    """Launch-time zone steering, the scalar half of the soft contract: the
    scoring kernel priced the best-case zone into the row; this picks that
    zone so the fleet launch actually lands there. Exact int micro-$ over
    every allowed offering of the packed node's type options:
    ``base_micro(offering) + clamp(-weight x scale)`` (the same fixed point
    as the device term), argmin with (higher vote, zone name) as the
    deterministic tiebreak — the saturation floor at 0 can erase the vote
    discount on cheap offerings (price 0 ties every zone at 0), and a tie
    must still land on the preferred zone, not the alphabetical one.
    Returns None — launch unchanged — when there are no usable
    votes, the kill switch is off, the zone is already pinned, or no
    offering is viable; a Some answer always keeps >=1 offering viable by
    construction (the winning offering is in that zone)."""
    from karpenter_tpu.scheduling.affinity import soft_enabled
    from karpenter_tpu.solver.policy import soft_zone_votes

    votes = soft_zone_votes(soft)
    if not votes or not soft_enabled():
        return None
    scale = int(round(ctx.soft_affinity_cost_per_weight * 1e6))
    if scale <= 0:
        return None
    zones = requirements.zones()
    if zones is not None and len(zones) <= 1:
        return None  # already pinned — nothing to steer
    cts = requirements.capacity_types()
    factor = cost_config.spot_price_factor
    best: Optional[tuple] = None
    for it in instance_types:
        for o in it.offerings:
            if zones is not None and o.zone not in zones:
                continue
            if cts is not None and o.capacity_type not in cts:
                continue
            base = it.price * factor \
                if o.capacity_type == wellknown.CAPACITY_TYPE_SPOT \
                else it.price
            adj = max(-_SOFT_CLAMP,
                      min(-votes.get(o.zone, 0) * scale, _SOFT_CLAMP))
            total = max(0, min(int(_encode_micro(base)) + adj,
                               int(_INT32_MAX)))
            cand = (total, -votes.get(o.zone, 0), o.zone)
            if best is None or cand < best:
                best = cand
    if best is None:
        return None
    # no vote touches a viable zone → every total is the plain price:
    # don't narrow (the unsteered lowest-price launch is already optimal)
    if all(votes.get(z, 0) == 0 for z in
           {o.zone for it in instance_types for o in it.offerings
            if (zones is None or o.zone in zones)
            and (cts is None or o.capacity_type in cts)}):
        return None
    return best[2]


def clear_caches() -> None:
    """Tests only."""
    with _LOCK:
        _TABLE_CACHE.clear()
        _TCZ_CACHE.clear()
