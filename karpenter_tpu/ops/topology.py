"""Torus-grid slice carving: occupancy bit-planes + carve-mask encoding.

Shape containment (api/gang.py ``slice_fits``) tells us a v5e-4x8 *could*
host a v5e-4x4 gang, but says nothing about whether the chips still free on
a partially-occupied pod form a contiguous sub-grid — without topology the
second gang lands on phantom capacity a real TPU runtime would reject
(Tesserae, arXiv 2508.04953). This module models each multi-host pod as a
2D/3D **torus** chip grid (every axis' ICI links wrap, so a carve may wrap
around any axis) and encodes, per gang window:

- per-bin occupancy bit-planes: one bool per flattened grid cell;
- per (slice shape, host grid) the full placement-mask bank — every
  distinct (origin × orientation) carve as a (P, C) bool matrix, duplicate
  cell sets deduped (symmetric orientations, full-axis wraps);
- the window tensors the ``solver/topology.py`` kernel scans in one jit:
  gang g is carve-feasible on bin b iff some placement row has no overlap
  with b's occupancy plane.

The kernel verdict is a FILTER (docs/solver.md §19): it only lets the host
walk SKIP gangs/bins; every accepted carve is re-verified **cell by cell**
by the scalar oracle :func:`first_carve` against the window's RUNNING
occupancy before anything commits — zero unverified placements, same
contract as every prior kernel. Occupancy only grows during a window walk,
so carve-infeasible at the initial planes implies carve-infeasible later —
skipping is sound (the monotonic-shrink argument of solver/gang.py).

:class:`OccupancyLedger` is the process-global registry of committed
carves on *real* nodes: it feeds partially-occupied pods back into the
next window as seed bins (the fragmentation-recovery win) and names the
resident gangs preemption may displace.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from functools import lru_cache
from itertools import permutations, product
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

Dims = Tuple[int, ...]


def grid_cells(dims: Sequence[int]) -> int:
    n = 1
    for d in dims:
        n *= d
    return n


@lru_cache(maxsize=1024)
def orientations(slice_dims: Dims, ndim: int) -> Tuple[Dims, ...]:
    """Distinct axis assignments of the slice grid on an ``ndim``-axis
    host: unit dims dropped, the rest padded with 1s to the host rank,
    every distinct permutation, sorted for determinism. Empty when the
    slice has more non-unit axes than the host has axes."""
    dims = tuple(d for d in slice_dims if d > 1)
    if len(dims) > ndim:
        return ()
    dims = dims + (1,) * (ndim - len(dims))
    return tuple(sorted(set(permutations(dims))))


def _strides(host_dims: Dims) -> List[int]:
    """Row-major flat strides of the host grid."""
    strides, s = [], 1
    for d in reversed(host_dims):
        strides.append(s)
        s *= d
    return strides[::-1]


@lru_cache(maxsize=512)
def placement_masks(host_dims: Dims, slice_dims: Dims
                    ) -> Optional[np.ndarray]:
    """(P, C) bool — every distinct torus carve of ``slice_dims`` on
    ``host_dims``: each orientation × each origin, wrap-around along every
    axis via modular arithmetic, cells flattened row-major. Duplicate cell
    sets (symmetric orientations, spans covering a whole axis) dedup to
    one row. None when no orientation fits at all."""
    cells = grid_cells(host_dims)
    strides = _strides(host_dims)
    masks: List[np.ndarray] = []
    seen: set = set()
    for orient in orientations(tuple(slice_dims), len(host_dims)):
        if any(o > h for o, h in zip(orient, host_dims)):
            continue
        for origin in product(*(range(d) for d in host_dims)):
            flat = np.zeros(1, np.int64)
            for ax, (o, d, st) in enumerate(
                    zip(orient, host_dims, strides)):
                offs = ((origin[ax] + np.arange(o)) % d) * st
                flat = (flat[:, None] + offs[None, :]).ravel()
            mask = np.zeros(cells, bool)
            mask[flat] = True
            key = mask.tobytes()
            if key not in seen:
                seen.add(key)
                masks.append(mask)
    if not masks:
        return None
    out = np.stack(masks)
    out.setflags(write=False)
    return out


def first_carve(occ, host_dims: Sequence[int],
                slice_dims: Sequence[int]) -> Optional[Tuple[int, ...]]:
    """Scalar host oracle: the first feasible carve of ``slice_dims`` on a
    host torus whose occupied cells are ``occ`` (bool sequence over flat
    cells, or any container of flat indices), walking orientations then
    origins in deterministic order and testing CELL BY CELL. Returns the
    covered flat-cell tuple or None. Deliberately independent of the
    vectorized mask bank — this is the fuzz / self-heal / commit-time
    verification oracle."""
    host_dims = tuple(host_dims)
    ndim = len(host_dims)
    if isinstance(occ, np.ndarray):
        occupied = set(int(i) for i in np.flatnonzero(occ))
    else:
        occupied = set(int(i) for i in occ)
    strides = _strides(host_dims)
    for orient in orientations(tuple(slice_dims), ndim):
        if any(o > h for o, h in zip(orient, host_dims)):
            continue
        for origin in product(*(range(d) for d in host_dims)):
            covered: List[int] = []
            ok = True
            for rel in product(*(range(o) for o in orient)):
                ci = 0
                for ax in range(ndim):
                    ci += ((origin[ax] + rel[ax]) % host_dims[ax]) \
                        * strides[ax]
                if ci in occupied:
                    ok = False
                    break
                covered.append(ci)
            if ok:
                return tuple(sorted(covered))
    return None


def constraints_sig(labels: Optional[dict], taints: Optional[Sequence]
                    ) -> tuple:
    """Structural signature of the (labels, taints) a gang node was
    created with. A ledger node is only offered back to schedules whose
    constraints produce the same signature — the seed-bin analog of the
    'prospective nodes carry one schedule's labels' rule."""
    lab = tuple(sorted((labels or {}).items()))
    tnt = tuple(sorted(
        (getattr(t, "key", ""), getattr(t, "value", "") or "",
         getattr(t, "effect", "") or "") for t in (taints or [])))
    return (lab, tnt)


def sig_from_json(obj):
    """Re-tuplify a constraints signature that round-tripped through the
    intent journal (JSON turns the nested tuples into lists). Recovery
    must restore the EXACT tuple shape or the rebuilt ledger's nodes
    would never match a window's ``constraints_sig`` and silently stop
    being seed bins."""
    if isinstance(obj, (list, tuple)):
        return tuple(sig_from_json(x) for x in obj)
    return obj


# -- the process occupancy ledger -----------------------------------------

@dataclass
class CarveRecord:
    """One committed carve: a gang's contiguous cell set on one node."""

    gang_key: Any
    cells: np.ndarray            # flat cell indices held on the node
    band: str
    pods: List[Tuple[str, str]]  # (namespace, name) of the members here
    # the write-ahead carve intent backing this record (empty when no
    # journal is attached): the id rides with the record so every release
    # seam — preemption, gang unwind, node termination, prune — can close
    # the durable half without a separate gang→intent map
    intent_id: str = ""


@dataclass
class NodeGrid:
    """One real node's torus state in the ledger."""

    node: str
    dims: Dims
    type_name: str
    labels_sig: tuple
    occ: np.ndarray              # (C,) bool occupancy plane
    carves: Dict[Any, CarveRecord] = field(default_factory=dict)


class OccupancyLedger:
    """Process-global registry of committed carves per real node.

    Written by the provisioning controller after every successful slice-
    gang bind; read at window-encode time to (a) seed partially-occupied
    pods back into the bin pool and (b) enumerate preemption victims.
    ``prune(live)`` drops nodes the cluster no longer has — the encoder
    calls it with the live node set every window, so terminated nodes
    self-clean without a dedicated hook.

    The in-memory state is the CACHE; the durable half is the set of
    open ``carve`` intents in the write-ahead journal (one per
    gang × node, ``intent_id`` on each record — docs/robustness.md §6).
    Every mutation seam that removes a record returns it so the caller
    can close its intent; startup recovery rebuilds this ledger from the
    open intents before any controller runs."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._nodes: Dict[str, NodeGrid] = {}

    def commit(self, node: str, dims: Sequence[int], type_name: str,
               labels_sig: tuple, gang_key: Any, cells: Sequence[int],
               band: str, pods: Sequence[Tuple[str, str]],
               intent_id: str = "") -> None:
        with self._lock:
            ng = self._nodes.get(node)
            if ng is None or tuple(ng.dims) != tuple(dims):
                ng = NodeGrid(node=node, dims=tuple(dims),
                              type_name=type_name, labels_sig=labels_sig,
                              occ=np.zeros(grid_cells(dims), bool))
                self._nodes[node] = ng
            idx = np.asarray(list(cells), np.int64)
            ng.occ[idx] = True
            ng.carves[gang_key] = CarveRecord(
                gang_key=gang_key, cells=idx, band=band, pods=list(pods),
                intent_id=intent_id)
        self._gauge()

    def pop_gang(self, gang_key: Any) -> List[Tuple[str, CarveRecord]]:
        """Free every cell the gang holds anywhere; empty nodes drop out.
        Returns the removed ``(node, record)`` pairs — the records carry
        the carve intent ids the caller must close in the journal."""
        removed: List[Tuple[str, CarveRecord]] = []
        with self._lock:
            for name in list(self._nodes):
                ng = self._nodes[name]
                rec = ng.carves.pop(gang_key, None)
                if rec is None:
                    continue
                ng.occ[rec.cells] = False
                removed.append((name, rec))
                if not ng.carves:
                    del self._nodes[name]
        if removed:
            self._gauge()
        return removed

    def release_gang(self, gang_key: Any) -> List[str]:
        """:meth:`pop_gang` keeping only the touched node names (the
        journal-free callers' shape)."""
        return [name for name, _rec in self.pop_gang(gang_key)]

    def pop_node(self, node: str) -> List[CarveRecord]:
        """Drop one node's grid entirely (termination finalizer / GC
        seam) and return its carve records so the caller can close their
        journal intents — a terminated node must stop being a seed bin
        AND stop being durable."""
        with self._lock:
            ng = self._nodes.pop(node, None)
        self._gauge()
        return list(ng.carves.values()) if ng is not None else []

    def forget_node(self, node: str) -> None:
        with self._lock:
            self._nodes.pop(node, None)
        self._gauge()

    def prune(self, live: Sequence[str]) -> List[CarveRecord]:
        """Drop nodes the cluster no longer has; returns the dropped
        carve records so a journal-aware caller can close their intents
        (otherwise recovery's node-gone rule closes them at the next
        restart)."""
        keep = set(live)
        dropped: List[CarveRecord] = []
        with self._lock:
            for name in [n for n in self._nodes if n not in keep]:
                dropped.extend(self._nodes[name].carves.values())
                del self._nodes[name]
        self._gauge()
        return dropped

    def snapshot(self) -> List[NodeGrid]:
        """Deep-enough copies for a window encode: occupancy planes and
        carve records are copied so the walk never races a commit."""
        with self._lock:
            return [NodeGrid(
                node=ng.node, dims=ng.dims, type_name=ng.type_name,
                labels_sig=ng.labels_sig, occ=ng.occ.copy(),
                carves={k: CarveRecord(r.gang_key, r.cells.copy(), r.band,
                                       list(r.pods), r.intent_id)
                        for k, r in ng.carves.items()})
                for ng in self._nodes.values()]

    def node_count(self) -> int:
        with self._lock:
            return len(self._nodes)

    def reset(self) -> None:
        with self._lock:
            self._nodes.clear()
        self._gauge()

    def _gauge(self) -> None:
        from karpenter_tpu.metrics.topology import TOPOLOGY_LEDGER_NODES
        TOPOLOGY_LEDGER_NODES.set(float(self.node_count()))


LEDGER = OccupancyLedger()


# -- window carve encoding -------------------------------------------------

@dataclass
class CarveEncoding:
    """Carve tensors of one gang window (host + padded device views).

    Host grids and slice shapes are interned into classes so the mask bank
    is (S, NC, P, C) instead of a per-(gang, bin) blowup: ``scls_of[g]``
    names gang g's slice class (-1 = no slice → trivially feasible),
    ``cls_of[b]`` names bin b's grid class (-1 = no grid → infeasible for
    any slice gang)."""

    classes: List[Dims]          # distinct host grids
    slice_classes: List[Dims]    # distinct slice shapes
    cls_of: np.ndarray           # (B,) int32
    scls_of: np.ndarray          # (G,) int32
    occ0: np.ndarray             # (B, C) bool, initial occupancy planes
    pmask: np.ndarray            # (S, NC, P, C) bool placement banks
    pvalid: np.ndarray           # (S, NC, P) bool real placement rows
    g: int
    b: int
    c: int
    p: int
    # padded device views (None when the gang window itself has none)
    d_occ: Optional[np.ndarray] = None      # (BB, CB) bool
    d_cls: Optional[np.ndarray] = None      # (BB,) int32
    d_scls: Optional[np.ndarray] = None     # (GB,) int32
    d_pmask: Optional[np.ndarray] = None    # (SB, NCB, PB, CB) bool
    d_pvalid: Optional[np.ndarray] = None   # (SB, NCB, PB) bool

    @property
    def device_ready(self) -> bool:
        return self.d_occ is not None


def encode_carve(enc, gb: Optional[int] = None, bb: Optional[int] = None
                 ) -> Optional[CarveEncoding]:
    """Build the carve tensors for a GangEncoding whose gangs/bins carry
    ``slice_dims`` / ``grid`` annotations (ops/gang.py). Returns None when
    no gang declares a slice — the window is carve-neutral and the gang
    kernel runs exactly as before. ``gb``/``bb`` are the gang window's
    padded gang/bin axes so the device verdict aligns with ``d_compat``."""
    from karpenter_tpu.ops.whatif import _pow2

    if not any(e.slice_dims is not None for e in enc.gangs):
        return None
    classes: List[Dims] = []
    cls_index: Dict[Dims, int] = {}
    cls_of = np.full(enc.b, -1, np.int32)
    for bi, bn in enumerate(enc.bins):
        if bn.grid is None:
            continue
        dims = tuple(bn.grid)
        if dims not in cls_index:
            cls_index[dims] = len(classes)
            classes.append(dims)
        cls_of[bi] = cls_index[dims]
    slice_classes: List[Dims] = []
    scls_index: Dict[Dims, int] = {}
    scls_of = np.full(enc.g, -1, np.int32)
    for e in enc.gangs:
        if e.slice_dims is None:
            continue
        dims = tuple(e.slice_dims)
        if dims not in scls_index:
            scls_index[dims] = len(slice_classes)
            slice_classes.append(dims)
        scls_of[e.index] = scls_index[dims]
    nc = max(len(classes), 1)
    c = max((grid_cells(d) for d in classes), default=1)
    banks: Dict[Tuple[int, int], np.ndarray] = {}
    p = 1
    for si, sd in enumerate(slice_classes):
        for ci, cd in enumerate(classes):
            bank = placement_masks(cd, sd)
            if bank is not None:
                banks[(si, ci)] = bank
                p = max(p, bank.shape[0])
    s = max(len(slice_classes), 1)
    pmask = np.zeros((s, nc, p, c), bool)
    pvalid = np.zeros((s, nc, p), bool)
    for (si, ci), bank in banks.items():
        pn, cn = bank.shape
        pmask[si, ci, :pn, :cn] = bank
        pvalid[si, ci, :pn] = True
    occ0 = np.zeros((max(enc.b, 1), c), bool)
    for bi, bn in enumerate(enc.bins):
        if bn.occ is not None:
            cn = bn.occ.shape[0]
            occ0[bi, :cn] = bn.occ
    cv = CarveEncoding(classes=classes, slice_classes=slice_classes,
                       cls_of=cls_of, scls_of=scls_of, occ0=occ0,
                       pmask=pmask, pvalid=pvalid,
                       g=enc.g, b=enc.b, c=c, p=p)
    if gb is not None and bb is not None:
        cb, pb = _pow2(c), _pow2(p)
        sb, ncb = _pow2(s), _pow2(nc)
        d_occ = np.zeros((bb, cb), bool)
        d_occ[:enc.b, :c] = occ0[:enc.b]
        d_cls = np.full(bb, -1, np.int32)
        d_cls[:enc.b] = cls_of
        d_scls = np.full(gb, -1, np.int32)
        d_scls[:enc.g] = scls_of
        d_pmask = np.zeros((sb, ncb, pb, cb), bool)
        d_pmask[:s, :nc, :p, :c] = pmask
        d_pvalid = np.zeros((sb, ncb, pb), bool)
        d_pvalid[:s, :nc, :p] = pvalid
        cv.d_occ, cv.d_cls, cv.d_scls = d_occ, d_cls, d_scls
        cv.d_pmask, cv.d_pvalid = d_pmask, d_pvalid
    return cv


def host_carve(cv: CarveEncoding) -> np.ndarray:
    """Exact numpy mirror of the device carve kernel: (G, B) bool,
    True = some placement row of gang g's bank on bin b's grid class has
    zero overlap with b's initial occupancy plane (or g has no slice)."""
    out = np.ones((cv.g, cv.b), bool)
    for gi in range(cv.g):
        si = int(cv.scls_of[gi])
        if si < 0:
            continue
        for bi in range(cv.b):
            ci = int(cv.cls_of[bi])
            if ci < 0:
                out[gi, bi] = False
                continue
            overlap = np.any(cv.pmask[si, ci] & cv.occ0[bi][None, :],
                             axis=1)
            out[gi, bi] = bool(np.any(cv.pvalid[si, ci] & ~overlap))
    return out


def scalar_carve(enc) -> np.ndarray:
    """(G, B) carve feasibility from the scalar oracle alone — the
    self-heal fallback when a device verdict fails its probes, and the
    bench's honest host-loop baseline. O(G·B) ``first_carve`` calls."""
    out = np.ones((enc.g, enc.b), bool)
    for e in enc.gangs:
        if e.slice_dims is None:
            continue
        for bi, bn in enumerate(enc.bins):
            if bn.grid is None:
                out[e.index, bi] = False
                continue
            occ = bn.occ if bn.occ is not None \
                else np.zeros(grid_cells(bn.grid), bool)
            out[e.index, bi] = first_carve(
                occ, bn.grid, e.slice_dims) is not None
    return out


def scalar_carve_cell(enc, gang_index: int, bin_index: int) -> bool:
    """One (gang, bin) cell of :func:`scalar_carve` — the probe oracle."""
    e = enc.gangs[gang_index]
    if e.slice_dims is None:
        return True
    bn = enc.bins[bin_index]
    if bn.grid is None:
        return False
    occ = bn.occ if bn.occ is not None \
        else np.zeros(grid_cells(bn.grid), bool)
    return first_carve(occ, bn.grid, e.slice_dims) is not None
