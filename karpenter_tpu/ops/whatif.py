"""What-if window encoding: N candidate drains as one batched tensor program.

Consolidation asks N independent questions per window — "do node i's
movable pods fit on the surviving cluster?" — that differ only in which
node is subtracted. The encoding exploits that: ONE shared free-capacity
matrix over all bins (every settled node), ONE compatibility tensor
(selector/affinity/taints, precomputed on host exactly like
models/consolidate._compatible), and a per-candidate bin index whose
exclusion IS the "cluster minus node i" delta. The kernel then first-fits
each candidate's pods (pre-sorted descending, the place_onto order) into
the shared bins under a vmap over the candidate axis — no per-candidate
host re-pack, no N× copies of the cluster state.

Quantities follow ops/encode.py exactly: nano-unit Python ints on the
host, divided by the per-resource GCD so realistic problems fit int32
exactly. Pod vectors use reserve semantics (R_PODS includes +1 pod slot),
which also makes zero-padded bins and candidates self-excluding — a padded
bin has free=0 and can never absorb a pod slot, so no masking tensor is
needed for padding. If any dimension cannot be scaled into int32, or the
window exceeds the cell cap, the device tensors are omitted and callers
run the exact host mirror (``host_whatif``) — exactness is never traded
for speed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import math

import numpy as np

from karpenter_tpu.api.core import Pod
from karpenter_tpu.api.requirements import pod_requirements
from karpenter_tpu.solver.adapter import pod_vector
from karpenter_tpu.solver.host_ffd import NUM_RESOURCES, R_PODS

NANO = 10**9
INT32_LIMIT = 2**31 - 1

# NB*KB*BB bool/int32 cells above this: skip the device tensors (a
# pathological window would OOM the host before helping the device)
MAX_WINDOW_CELLS = 1 << 26


def _pow2(n: int, floor: int = 4) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


@dataclass
class WhatIfEncoding:
    """One consolidation window, exact host-side plus optional device-side.

    Host side (always present — the verification authority):
    - ``bins``: the survivors' free capacity (models/consolidate._Bin
      compatible: .name/.free/.labels/.taints), exact nano ints.
    - ``cand_bin``: bin index of each candidate.
    - ``cand_pods``: per candidate, (reserve-vector, pod) pairs sorted
      descending by (cpu, mem) — the place_onto order.
    - ``compat``: (N, K, B) bool — pod k of candidate i may land on bin b.

    Device side (None when unencodable): int32 GCD-scaled mirrors padded
    to power-of-two buckets, ready for solver/whatif._whatif_jit.

    ``kept`` is the receiver-pruned bin set: a bin whose free vector fits
    NO pod in the window (component-wise, resource-only — compat can only
    restrict further) can never be chosen by first-fit, so dropping it
    from the solve axis is exact. This is shared encode work the
    per-candidate host path cannot amortize: a steady-state cluster is
    mostly full bins, and pruning collapses the solve's bin axis to the
    few real receivers. ``d_cand_bin`` holds each candidate's own-bin
    position WITHIN kept, or -1 when its bin was pruned (nothing to
    exclude — it couldn't receive anyway).
    """

    bins: Sequence
    cand_bin: List[int]
    cand_pods: List[List[Tuple[Tuple[int, ...], Pod]]]
    compat: np.ndarray
    n: int
    k: int
    b: int
    kept: Optional[np.ndarray] = None        # original indices of kept bins
    # device tensors (padded, scaled) — None ⇒ host fallback
    d_pods: Optional[np.ndarray] = None      # (NB, KB, R) int32
    d_valid: Optional[np.ndarray] = None     # (NB, KB) bool
    d_compat: Optional[np.ndarray] = None    # (NB, KB, BB) bool
    d_free0: Optional[np.ndarray] = None     # (BB, R) int32
    d_cand_bin: Optional[np.ndarray] = None  # (NB,) int32 (kept position | -1)
    scales: Tuple[int, ...] = field(default_factory=tuple)

    @property
    def device_ready(self) -> bool:
        return self.d_pods is not None

    @property
    def cells(self) -> int:
        if self.d_compat is None:
            return self.n * self.k * self.b
        return int(np.prod(self.d_compat.shape))


def _gcd_scale_signed(columns: List[List[int]]) -> Optional[Tuple[int, ...]]:
    """ops/encode._gcd_scale with signed support: free vectors may be
    negative (an overcommitted node), and gcd divides them exactly too."""
    scales = []
    for vals in columns:
        g = 0
        for v in vals:
            g = math.gcd(g, v)
        g = g or 1
        if max((abs(v) // g for v in vals), default=0) > INT32_LIMIT:
            return None
        scales.append(g)
    return tuple(scales)


def _reserve_vec(pod: Pod) -> Tuple[int, ...]:
    v = list(pod_vector(pod))
    v[R_PODS] += NANO  # reserve semantics: the pod slot rides the vector
    return tuple(v)


def _compat_matrix(bins: Sequence, cand_pods) -> np.ndarray:
    """(N, K, B) bool with the exact models/consolidate._compatible
    semantics. Fast path: unconstrained pods on untainted bins are the
    overwhelming default, so the matrix starts True and only constrained
    pods / tainted bins pay a host loop."""
    n = len(cand_pods)
    k = max((len(ps) for ps in cand_pods), default=0)
    b = len(bins)
    compat = np.ones((n, max(k, 1), max(b, 1)), dtype=bool)
    tainted = frozenset(j for j, bn in enumerate(bins) if len(bn.taints))
    for i, pods in enumerate(cand_pods):
        for kk, (_, pod) in enumerate(pods):
            reqs = pod_requirements(pod)
            keys = list(reqs.keys())
            if keys:
                for j, bn in enumerate(bins):
                    ok = True
                    for key in keys:
                        allowed = reqs.requirement(key)
                        if allowed is None:
                            continue
                        if bn.labels.get(key) not in allowed:
                            ok = False
                            break
                    if ok and j in tainted:
                        # tolerates() returns scheduling errors: empty ⇒ ok
                        ok = not bn.taints.tolerates(pod)
                    compat[i, kk, j] = ok
            elif tainted:
                for j in tainted:
                    compat[i, kk, j] = not bins[j].taints.tolerates(pod)
    return compat


def encode_window(
    bins: Sequence,
    cand_bin: Sequence[int],
    cand_movable: Sequence[Sequence[Pod]],
    max_cells: int = MAX_WINDOW_CELLS,
) -> WhatIfEncoding:
    """Build the window encoding. The exact host side always succeeds; the
    device tensors are attached only when every dimension GCD-scales into
    int32 and the padded window fits the cell cap."""
    cand_pods = [
        sorted(((_reserve_vec(p), p) for p in pods),
               key=lambda t: (-t[0][0], -t[0][1]))
        for pods in cand_movable
    ]
    n, b = len(cand_pods), len(bins)
    k = max((len(ps) for ps in cand_pods), default=0)
    compat = _compat_matrix(bins, cand_pods)
    enc = WhatIfEncoding(bins=bins, cand_bin=list(cand_bin),
                         cand_pods=cand_pods, compat=compat, n=n, k=k, b=b)
    if n == 0 or b == 0 or k == 0:
        return enc

    columns: List[List[int]] = [[] for _ in range(NUM_RESOURCES)]
    for bn in bins:
        for r in range(NUM_RESOURCES):
            columns[r].append(bn.free[r])
    for pods in cand_pods:
        for vec, _ in pods:
            for r in range(NUM_RESOURCES):
                columns[r].append(vec[r])
    scales = _gcd_scale_signed(columns)
    if scales is None:
        return enc  # host-only window

    # Receiver pruning (exact): scaled division is exact, so the int64
    # compare below is the nano compare. A bin that fits NO window pod
    # resource-wise can never be chosen by first-fit — drop it from the
    # solve axis. Compat ignored here: it only restricts further, so kept
    # is a superset of reachable bins.
    free_scaled = np.empty((b, NUM_RESOURCES), dtype=np.int64)
    for j, bn in enumerate(bins):
        for r in range(NUM_RESOURCES):
            free_scaled[j, r] = bn.free[r] // scales[r]
    vec_scaled = np.unique(np.array(
        [[vec[r] // scales[r] for r in range(NUM_RESOURCES)]
         for pods in cand_pods for vec, _ in pods], dtype=np.int64), axis=0)
    fits_any = (free_scaled[:, None, :] >= vec_scaled[None, :, :]) \
        .all(axis=2).any(axis=1)
    kept = np.nonzero(fits_any)[0]
    enc.kept = kept
    bk = len(kept)
    if bk == 0:
        return enc  # nothing can receive: host mirror answers instantly

    nb, kb, bb = _pow2(n), _pow2(k), _pow2(bk)
    if nb * kb * bb > max_cells:
        return enc

    pos = np.full((b,), -1, dtype=np.int32)
    pos[kept] = np.arange(bk, dtype=np.int32)
    d_pods = np.zeros((nb, kb, NUM_RESOURCES), dtype=np.int32)
    d_valid = np.zeros((nb, kb), dtype=bool)
    d_compat = np.zeros((nb, kb, bb), dtype=bool)
    d_free0 = np.zeros((bb, NUM_RESOURCES), dtype=np.int32)
    d_cand_bin = np.zeros((nb,), dtype=np.int32)
    d_free0[:bk] = free_scaled[kept].astype(np.int32)
    for i, pods in enumerate(cand_pods):
        d_cand_bin[i] = pos[cand_bin[i]]
        for kk, (vec, _) in enumerate(pods):
            for r in range(NUM_RESOURCES):
                d_pods[i, kk, r] = vec[r] // scales[r]
            d_valid[i, kk] = True
    d_compat[:n, :compat.shape[1], :bk] = compat[:, :, kept]

    enc.d_pods, enc.d_valid, enc.d_compat = d_pods, d_valid, d_compat
    enc.d_free0, enc.d_cand_bin, enc.scales = d_free0, d_cand_bin, scales
    return enc


def host_whatif(enc: WhatIfEncoding) -> Tuple[np.ndarray, np.ndarray]:
    """Exact host mirror of the device kernel: per candidate, first-fit its
    reserve vectors into every bin but its own, in nano ints. Returns
    (feasible (N,), slots (N, K) bin index or -1) — the differential
    contract is bit-identical to the scaled device result because GCD
    scaling is an exact division."""
    n, k = enc.n, enc.k
    feasible = np.zeros((n,), dtype=bool)
    slots = np.full((n, max(k, 1)), -1, dtype=np.int32)
    # scan receiver-pruned bins when the encoder computed them (exact —
    # pruned bins fit no window pod), the full bin set otherwise
    scan = list(enc.kept) if enc.kept is not None else range(enc.b)
    for i in range(n):
        own = enc.cand_bin[i]
        free = [list(bn.free) for bn in enc.bins]
        ok = True
        for kk, (vec, _) in enumerate(enc.cand_pods[i]):
            placed = -1
            for j in scan:
                if j == own or not enc.compat[i, kk, j]:
                    continue
                f = free[j]
                if all(f[r] >= vec[r] for r in range(NUM_RESOURCES)):
                    placed = j
                    break
            if placed < 0:
                ok = False
                break
            f = free[placed]
            for r in range(NUM_RESOURCES):
                f[r] -= vec[r]
            slots[i, kk] = placed
        feasible[i] = ok
    return feasible, slots


def verify_and_commit(
    enc: WhatIfEncoding,
    cand: int,
    free_state: List[List[int]],
    excluded: set,
    scan: Optional[Sequence[int]] = None,
) -> Optional[List[int]]:
    """The authority check before a drain executes: exact first-fit of
    candidate ``cand``'s pods into ``free_state`` (nano ints), skipping its
    own bin and every ``excluded`` bin (already-drained this window).
    ``scan`` restricts and orders the receiver bins (default: every bin in
    index order). Commits the placement on success and returns the
    receiving bin indices; None ⇒ the candidate no longer fits after
    earlier drains. Device results are a filter — this is the only path
    that authorizes evictions, so an (impossible) kernel false-positive can
    never drain a node whose pods don't fit."""
    own = enc.cand_bin[cand]
    trial = [list(f) for f in free_state]
    placed_bins: List[int] = []
    for kk, (vec, _) in enumerate(enc.cand_pods[cand]):
        placed = -1
        for j in (scan if scan is not None else range(enc.b)):
            if j == own or j in excluded or not enc.compat[cand, kk, j]:
                continue
            f = trial[j]
            if all(f[r] >= vec[r] for r in range(NUM_RESOURCES)):
                placed = j
                break
        if placed < 0:
            return None
        f = trial[placed]
        for r in range(NUM_RESOURCES):
            f[r] -= vec[r]
        placed_bins.append(placed)
    for j in range(enc.b):
        free_state[j][:] = trial[j]
    return placed_bins


def soft_affinity_loss(node, movable: Sequence[Pod], fleet: Sequence,
                       pods_by_node: Dict[str, List[Pod]],
                       cost_per_weight: float) -> float:
    """$/h a drain of ``node`` would forfeit in currently-satisfied
    preferred pod-affinity: for each movable pod, each preferred affinity
    term whose selector matches a same-namespace peer in the node's
    topology domain (same node for hostname, same node label value
    otherwise) counts its weight once. The scheduler paid ``weight x
    soft_affinity_cost_per_weight`` to co-locate that set (solver/policy
    soft_zone_adjust / ops/policy soft rows); the drain's savings must
    beat that price or consolidation is just undoing placement work.

    Preferred ANTI-affinity pays nothing: a drain reschedules the pod and
    the scheduler can re-satisfy anti terms elsewhere, whereas a scattered
    co-located set stays scattered until its peers churn. Scalar oracle —
    evaluated with api.core.LabelSelector.matches, the same authority the
    pair bit-planes are probe-verified against. Gated by the
    KARPENTER_SOFT_AFFINITY kill switch (scheduling.affinity.soft_enabled);
    off or zero-cost ⇒ 0.0, bit-for-bit the pre-soft savings."""
    if cost_per_weight <= 0.0 or not movable:
        return 0.0
    from karpenter_tpu.scheduling.affinity import (
        _preferred_terms, soft_enabled)
    if not soft_enabled():
        return 0.0

    def domain(n, key: str):
        if key == "kubernetes.io/hostname":
            return n.metadata.name
        return n.metadata.labels.get(key)

    weight = 0
    for pod in movable:
        terms = _preferred_terms(pod, False)
        if not terms:
            continue
        for w, term in terms:
            if not term.topology_key or term.label_selector is None:
                continue
            dom = domain(node, term.topology_key)
            if dom is None:
                continue
            satisfied = False
            for other in fleet:
                if domain(other, term.topology_key) != dom:
                    continue
                for peer in pods_by_node.get(other.metadata.name, ()):
                    if peer is pod:
                        continue
                    if peer.metadata.namespace != pod.metadata.namespace:
                        continue
                    if term.label_selector.matches(peer.metadata.labels):
                        satisfied = True
                        break
                if satisfied:
                    break
            if satisfied:
                weight += abs(int(w))
    return weight * cost_per_weight
