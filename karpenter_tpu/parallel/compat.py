"""jax API compatibility for the parallel layer.

``shard_map`` graduated from ``jax.experimental.shard_map`` to a top-level
``jax.shard_map`` export (and its replication-checking kwarg was renamed
``check_rep`` → ``check_vma``) across the jax 0.4 → 0.5 series. The
sharded kernels are written against the new-style API; this shim lets the
same call sites run on either series.
"""

from __future__ import annotations

try:  # jax >= 0.5: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # jax 0.4.x: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{_CHECK_KW: check_vma})
