"""Device mesh construction for the sharded solver.

Axes:
- "batch": independent packing problems (schedules). The provisioning plane
  produces many isomorphic-constraint groups per solve window; each is an
  independent FFD instance, so the batch axis shards perfectly with no
  cross-device communication (the analog of the reference's per-Provisioner
  goroutines, provisioner.go:53-60 — but data-parallel on ICI instead of
  host threads).

Multi-host: jax initializes the global device set; the same mesh spec spans
slices (DCN between hosts is handled by XLA's collectives). Nothing here is
TPU-count-specific — tests use a virtual 8-device CPU mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def solver_mesh(devices: Optional[Sequence] = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    import numpy as np

    return Mesh(np.array(devs), axis_names=("batch",))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("batch"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
