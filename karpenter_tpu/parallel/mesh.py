"""Device mesh construction for the sharded solver — the ONE mesh authority.

Axes:
- "batch": independent packing problems (schedules). The provisioning plane
  produces many isomorphic-constraint groups per solve window; each is an
  independent FFD instance, so the batch axis shards perfectly with no
  cross-device communication (the analog of the reference's per-Provisioner
  goroutines, provisioner.go:53-60 — but data-parallel on ICI instead of
  host threads).

Every sharded entry point (parallel/sharded_pack.py, parallel/type_sharded.py,
solver/batch_solve.py) derives its ``NamedSharding``s from here, so the
explicit-sharding ``pjit`` calls and the device ring (solver/pipeline.py)
agree on placement — buffer donation only aliases when the donated input and
the matching output carry the SAME sharding, which a second ad-hoc mesh
would silently break.

Multi-host: jax initializes the global device set; the same mesh spec spans
slices (DCN between hosts is handled by XLA's collectives). Nothing here is
TPU-count-specific — tests use a virtual 8-device CPU mesh and the bench
forces N virtual CPU devices via --xla_force_host_platform_device_count.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_LOCK = threading.Lock()
_CACHED: Optional[Mesh] = None


def solver_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """The process-wide solver mesh over the global device set (cached —
    ``Mesh`` equality is by device array, and the jit caches key on it, so
    handing out one object keeps every compiled entry shared). Passing an
    explicit ``devices`` sequence bypasses the cache (tests build sub-meshes)."""
    global _CACHED
    import numpy as np

    if devices is not None:
        return Mesh(np.array(list(devices)), axis_names=("batch",))
    with _LOCK:
        if _CACHED is None:
            _CACHED = Mesh(np.array(jax.devices()), axis_names=("batch",))
        return _CACHED


def device_count() -> int:
    return solver_mesh().devices.size


def batch_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Leading axis sharded over "batch" — the placement of every per-problem
    tensor in the batched solve AND of the ring slots that cycle through the
    donated kernel (they must match for the alias to hold)."""
    return NamedSharding(mesh if mesh is not None else solver_mesh(),
                         P("batch"))


def replicated(mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh if mesh is not None else solver_mesh(), P())


def device_bytes_in_use(devices: Optional[Sequence] = None) -> dict:
    """Live device memory by device id: ``memory_stats()['bytes_in_use']``
    where the backend implements it (TPU), else the sum of live buffer sizes
    from the client (CPU test meshes report None for memory_stats). Returns
    {} when neither source is available — callers must treat the gauge as
    best-effort, never gate on it."""
    devs = list(devices) if devices is not None else jax.devices()
    out: dict = {}
    for d in devs:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and "bytes_in_use" in stats:
            out[d.id] = int(stats["bytes_in_use"])
    if out:
        return out
    try:
        by_dev: dict = {}
        for buf in devs[0].client.live_buffers():
            dev = buf.device() if callable(getattr(buf, "device", None)) \
                else getattr(buf, "device", None)
            did = getattr(dev, "id", 0)
            by_dev[did] = by_dev.get(did, 0) + buf.size * buf.dtype.itemsize
        return {d.id: by_dev.get(d.id, 0) for d in devs}
    except Exception:
        return {}
